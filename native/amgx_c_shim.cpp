/* amgx_c_shim.cpp — native implementation of the AMGX C ABI.
 *
 * Exports real C symbols (AMGX_initialize, AMGX_solver_solve, ...) from a
 * shared library by embedding the CPython interpreter and delegating to
 * amgx_tpu.capi (which drives the JAX/XLA TPU runtime).  Existing C
 * drivers written against the reference (examples/amgx_capi.c style) link
 * against libamgx_tpu_c.so and run unchanged.
 *
 * Array arguments cross the boundary zero-copy via numpy views of the
 * caller's buffers (the Python side copies on upload, preserving AMGX's
 * caller-owns-memory contract).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "amgx_tpu_c.h"

namespace {

std::mutex g_mutex;
PyObject *g_capi = nullptr;        /* amgx_tpu.capi module */
PyObject *g_print_cb_obj = nullptr;
AMGX_print_callback g_print_cb = nullptr;

struct Handle {
    PyObject *obj;
};

const char *mode_name(AMGX_Mode m) {
    static const char *names[] = {"hDDI", "hDFI", "hFFI", "dDDI", "dDFI",
                                  "dFFI", "hZZI", "hZCI", "hCCI", "dZZI",
                                  "dZCI", "dCCI"};
    if (m < 0 || m > 11) return "dDDI";
    return names[m];
}

/* data dtype per mode's matrix precision */
int mode_mat_typenum(AMGX_Mode m) {
    switch (m) {
        case AMGX_mode_hDDI: case AMGX_mode_dDDI: return NPY_FLOAT64;
        case AMGX_mode_hDFI: case AMGX_mode_dDFI: return NPY_FLOAT32;
        case AMGX_mode_hFFI: case AMGX_mode_dFFI: return NPY_FLOAT32;
        case AMGX_mode_hZZI: case AMGX_mode_dZZI: return NPY_COMPLEX128;
        case AMGX_mode_hZCI: case AMGX_mode_dZCI: return NPY_COMPLEX64;
        case AMGX_mode_hCCI: case AMGX_mode_dCCI: return NPY_COMPLEX64;
        default: return NPY_FLOAT64;
    }
}

int mode_vec_typenum(AMGX_Mode m) {
    switch (m) {
        case AMGX_mode_hFFI: case AMGX_mode_dFFI: return NPY_FLOAT32;
        case AMGX_mode_hZZI: case AMGX_mode_dZZI:
        case AMGX_mode_hZCI: case AMGX_mode_dZCI: return NPY_COMPLEX128;
        case AMGX_mode_hCCI: case AMGX_mode_dCCI: return NPY_COMPLEX64;
        default: return NPY_FLOAT64;
    }
}

AMGX_RC ensure_init() {
    if (g_capi) return AMGX_RC_OK;
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
    }
    PyGILState_STATE st = PyGILState_Ensure();
    if (_import_array() < 0) {
        PyErr_Clear();
        PyGILState_Release(st);
        return AMGX_RC_INTERNAL;
    }
    PyObject *mod = PyImport_ImportModule("amgx_tpu.capi");
    if (!mod) {
        PyErr_Print();
        PyGILState_Release(st);
        return AMGX_RC_PLUGIN;
    }
    g_capi = mod;
    PyGILState_Release(st);
    return AMGX_RC_OK;
}

AMGX_RC rc_from_long(long v) { return (AMGX_RC)v; }

/* call capi.<name>(args); returns new ref or nullptr */
PyObject *call(const char *name, PyObject *args) {
    PyObject *fn = PyObject_GetAttrString(g_capi, name);
    if (!fn) { Py_XDECREF(args); return nullptr; }
    PyObject *out = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (!out) PyErr_Print();
    return out;
}

/* result is either an int RC, or a tuple (rc, out0, out1, ...) */
AMGX_RC unpack_rc(PyObject *out, std::vector<PyObject *> *outputs = nullptr) {
    if (!out) return AMGX_RC_UNKNOWN;
    AMGX_RC rc = AMGX_RC_UNKNOWN;
    if (PyTuple_Check(out)) {
        rc = rc_from_long(PyLong_AsLong(PyTuple_GetItem(out, 0)));
        if (outputs) {
            for (Py_ssize_t i = 1; i < PyTuple_Size(out); ++i) {
                PyObject *o = PyTuple_GetItem(out, i);
                Py_INCREF(o);
                outputs->push_back(o);
            }
        }
    } else if (PyLong_Check(out)) {
        rc = rc_from_long(PyLong_AsLong(out));
    }
    Py_DECREF(out);
    return rc;
}

Handle *wrap(PyObject *obj) {
    Handle *h = new Handle{obj};
    return h;
}

PyObject *obj(void *handle) {
    if (!handle) Py_RETURN_NONE;
    PyObject *o = static_cast<Handle *>(handle)->obj;
    Py_INCREF(o);
    return o;
}

void drop(void *handle) {
    if (!handle) return;
    Handle *h = static_cast<Handle *>(handle);
    PyGILState_STATE st = PyGILState_Ensure();
    Py_XDECREF(h->obj);
    PyGILState_Release(st);
    delete h;
}

PyObject *np_view(const void *data, npy_intp n, int typenum) {
    if (!data) Py_RETURN_NONE;
    return PyArray_SimpleNewFromData(1, &n, typenum,
                                     const_cast<void *>(data));
}

struct Gil {
    PyGILState_STATE st;
    Gil() { st = PyGILState_Ensure(); }
    ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

AMGX_RC AMGX_initialize(void) {
    std::lock_guard<std::mutex> lk(g_mutex);
    AMGX_RC rc = ensure_init();
    if (rc != AMGX_RC_OK) return rc;
    Gil gil;
    return unpack_rc(call("AMGX_initialize", PyTuple_New(0)));
}

AMGX_RC AMGX_initialize_plugins(void) { return AMGX_RC_OK; }
AMGX_RC AMGX_finalize_plugins(void) { return AMGX_RC_OK; }

AMGX_RC AMGX_finalize(void) {
    if (!g_capi) return AMGX_RC_OK;
    Gil gil;
    return unpack_rc(call("AMGX_finalize", PyTuple_New(0)));
}

AMGX_RC AMGX_get_error_string(AMGX_RC err, char *buf, int buf_len) {
    /* pure-C table: usable before initialization, as the reference's
       AMGX_SAFE_CALL error macro requires (amgx_c.h:160-165) */
    const char *msg;
    switch (err) {
        case AMGX_RC_OK: msg = "No error."; break;
        case AMGX_RC_BAD_PARAMETERS:
            msg = "Incorrect parameters to AMGX call."; break;
        case AMGX_RC_UNKNOWN: msg = "Unknown error."; break;
        case AMGX_RC_NOT_SUPPORTED_TARGET:
            msg = "Unsupported target."; break;
        case AMGX_RC_NOT_SUPPORTED_BLOCKSIZE:
            msg = "Unsupported block size."; break;
        case AMGX_RC_CUDA_FAILURE: msg = "Device failure."; break;
        case AMGX_RC_THRUST_FAILURE:
            msg = "Device library failure."; break;
        case AMGX_RC_NO_MEMORY: msg = "Insufficient memory."; break;
        case AMGX_RC_IO_ERROR: msg = "I/O error."; break;
        case AMGX_RC_BAD_MODE: msg = "Invalid mode."; break;
        case AMGX_RC_CORE: msg = "Error initializing amgx core."; break;
        case AMGX_RC_PLUGIN: msg = "Error initializing plugins."; break;
        case AMGX_RC_BAD_CONFIGURATION:
            msg = "Invalid configuration."; break;
        case AMGX_RC_NOT_IMPLEMENTED: msg = "Not implemented."; break;
        case AMGX_RC_LICENSE_NOT_FOUND: msg = "License not found."; break;
        case AMGX_RC_INTERNAL: msg = "Internal error."; break;
        default: msg = "Unknown error code."; break;
    }
    if (!buf || buf_len <= 0) return AMGX_RC_BAD_PARAMETERS;
    std::snprintf(buf, (size_t)buf_len, "%s", msg);
    return AMGX_RC_OK;
}

void AMGX_abort(AMGX_resources_handle, int err) {
    std::fprintf(stderr, "AMGX_abort: error %d\n", err);
    std::fflush(stderr);
    std::exit(err ? err : 1);
}

AMGX_RC AMGX_get_api_version(int *major, int *minor) {
    if (major) *major = 2;
    if (minor) *minor = 0;
    return AMGX_RC_OK;
}

AMGX_RC AMGX_pin_memory(void *, unsigned int) { return AMGX_RC_OK; }
AMGX_RC AMGX_unpin_memory(void *) { return AMGX_RC_OK; }

AMGX_RC AMGX_install_signal_handler(void) {
    if (ensure_init() != AMGX_RC_OK) return AMGX_RC_INTERNAL;
    Gil gil;
    return unpack_rc(call("AMGX_install_signal_handler", PyTuple_New(0)));
}

AMGX_RC AMGX_reset_signal_handler(void) {
    if (!g_capi) return AMGX_RC_OK;
    Gil gil;
    return unpack_rc(call("AMGX_reset_signal_handler", PyTuple_New(0)));
}

AMGX_RC AMGX_register_print_callback(AMGX_print_callback callback) {
    g_print_cb = callback;
    return AMGX_RC_OK; /* messages route through python stdout otherwise */
}

AMGX_RC AMGX_solver_register_print_callback(AMGX_print_callback callback) {
    /* amgx_c.h:396: the reference routes solver prints to the same
       global stream as AMGX_register_print_callback */
    return AMGX_register_print_callback(callback);
}

/* ------------------------------------------------------------- config */
AMGX_RC AMGX_config_create(AMGX_config_handle *cfg, const char *options) {
    if (ensure_init() != AMGX_RC_OK) return AMGX_RC_INTERNAL;
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_config_create", Py_BuildValue("(s)", options)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *cfg = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_config_create_from_file(AMGX_config_handle *cfg,
                                     const char *param_file) {
    if (ensure_init() != AMGX_RC_OK) return AMGX_RC_INTERNAL;
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(call("AMGX_config_create_from_file",
                                Py_BuildValue("(s)", param_file)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *cfg = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_config_create_from_file_and_string(AMGX_config_handle *cfg,
                                                const char *param_file,
                                                const char *options) {
    if (ensure_init() != AMGX_RC_OK) return AMGX_RC_INTERNAL;
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_config_create_from_file_and_string",
             Py_BuildValue("(ss)", param_file, options)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *cfg = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_config_add_parameters(AMGX_config_handle *cfg,
                                   const char *options) {
    Gil gil;
    PyObject *args = PyTuple_Pack(2, static_cast<Handle *>(*cfg)->obj,
                                  PyUnicode_FromString(options));
    return unpack_rc(call("AMGX_config_add_parameters", args));
}

AMGX_RC AMGX_config_get_default_number_of_rings(AMGX_config_handle cfg,
                                                int *num_rings) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(call("AMGX_config_get_default_number_of_rings",
                                PyTuple_Pack(1, obj(cfg))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *num_rings = (int)PyLong_AsLong(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_config_destroy(AMGX_config_handle cfg) {
    drop(cfg);
    return AMGX_RC_OK;
}

AMGX_RC AMGX_write_parameters_description(char *filename) {
    Gil gil;
    return unpack_rc(call("AMGX_write_parameters_description",
                          Py_BuildValue("(s)", filename)));
}

/* ---------------------------------------------------------- resources */
AMGX_RC AMGX_resources_create(AMGX_resources_handle *rsc,
                              AMGX_config_handle cfg, void *,
                              int device_num, const int *) {
    Gil gil;
    std::vector<PyObject *> outs;
    PyObject *args = PyTuple_Pack(3, static_cast<Handle *>(cfg)->obj,
                                  Py_None, PyLong_FromLong(device_num));
    Py_INCREF(static_cast<Handle *>(cfg)->obj);
    Py_INCREF(Py_None);
    AMGX_RC rc = unpack_rc(call("AMGX_resources_create", args), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *rsc = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_resources_create_simple(AMGX_resources_handle *rsc,
                                     AMGX_config_handle cfg) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(call("AMGX_resources_create_simple",
                                PyTuple_Pack(1, obj(cfg))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *rsc = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_resources_destroy(AMGX_resources_handle rsc) {
    drop(rsc);
    return AMGX_RC_OK;
}

/* ------------------------------------------------------------- matrix */
AMGX_RC AMGX_matrix_create(AMGX_matrix_handle *mtx,
                           AMGX_resources_handle rsc, AMGX_Mode mode) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_matrix_create",
             Py_BuildValue("(Os)", static_cast<Handle *>(rsc)->obj,
                           mode_name(mode))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *mtx = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_matrix_destroy(AMGX_matrix_handle mtx) {
    drop(mtx);
    return AMGX_RC_OK;
}

/* numpy typenum of a dtype attribute on the handle's mode object: the
 * byte width of caller buffers depends on it, so every memcpy across the
 * ABI must use this, not a hardcoded float64. */
static int handle_mode_typenum(Handle *h, const char *dtype_attr) {
    PyObject *mode_obj = PyObject_GetAttrString(h->obj, "mode");
    PyObject *vd =
        mode_obj ? PyObject_GetAttrString(mode_obj, dtype_attr) : nullptr;
    int tn = NPY_FLOAT64;
    if (vd) {
        PyArray_Descr *descr = nullptr;
        if (PyArray_DescrConverter(vd, &descr) && descr) {
            tn = descr->type_num;
            Py_DECREF(descr);
        }
        Py_DECREF(vd);
    }
    Py_XDECREF(mode_obj);
    PyErr_Clear();
    return tn;
}

static int mode_mat_typenum(Handle *h) {
    return handle_mode_typenum(h, "mat_dtype");
}

AMGX_RC AMGX_matrix_upload_all(AMGX_matrix_handle mtx, int n, int nnz,
                               int block_dimx, int block_dimy,
                               const int *row_ptrs, const int *col_indices,
                               const void *data, const void *diag_data) {
    Gil gil;
    Handle *h = static_cast<Handle *>(mtx);
    int tn = mode_mat_typenum(h);
    npy_intp nvals = (npy_intp)nnz * block_dimx * block_dimy;
    PyObject *rp = np_view(row_ptrs, n + 1, NPY_INT32);
    PyObject *ci = np_view(col_indices, nnz, NPY_INT32);
    PyObject *dv = np_view(data, nvals, tn);
    PyObject *dd = diag_data
                       ? np_view(diag_data,
                                 (npy_intp)n * block_dimx * block_dimy, tn)
                       : (Py_INCREF(Py_None), Py_None);
    PyObject *args = Py_BuildValue("(OiiiiOOOO)", h->obj, n, nnz,
                                   block_dimx, block_dimy, rp, ci, dv, dd);
    Py_DECREF(rp);
    Py_DECREF(ci);
    Py_DECREF(dv);
    Py_DECREF(dd);
    return unpack_rc(call("AMGX_matrix_upload_all", args));
}

AMGX_RC AMGX_matrix_replace_coefficients(AMGX_matrix_handle mtx, int n,
                                         int nnz, const void *data,
                                         const void *diag_data) {
    Gil gil;
    Handle *h = static_cast<Handle *>(mtx);
    PyObject *bd = PyObject_GetAttrString(h->obj, "matrix");
    PyObject *bdim =
        bd ? PyObject_GetAttrString(bd, "block_dim") : nullptr;
    long b = bdim ? PyLong_AsLong(bdim) : 1;
    Py_XDECREF(bdim);
    Py_XDECREF(bd);
    PyObject *dv =
        np_view(data, (npy_intp)nnz * b * b, mode_mat_typenum(h));
    PyObject *args = Py_BuildValue("(OiiO)", h->obj, n, nnz, dv);
    Py_DECREF(dv);
    return unpack_rc(call("AMGX_matrix_replace_coefficients", args));
}

AMGX_RC AMGX_matrix_get_size(AMGX_matrix_handle mtx, int *n,
                             int *block_dimx, int *block_dimy) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_matrix_get_size", PyTuple_Pack(1, obj(mtx))), &outs);
    if (rc == AMGX_RC_OK && outs.size() >= 3) {
        if (n) *n = (int)PyLong_AsLong(outs[0]);
        if (block_dimx) *block_dimx = (int)PyLong_AsLong(outs[1]);
        if (block_dimy) *block_dimy = (int)PyLong_AsLong(outs[2]);
    }
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_matrix_get_nnz(AMGX_matrix_handle mtx, int *nnz) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_matrix_get_nnz", PyTuple_Pack(1, obj(mtx))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *nnz = (int)PyLong_AsLong(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_matrix_download_all(AMGX_matrix_handle mtx, int *row_ptrs,
                                 int *col_indices, void *data, void **) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_matrix_download_all", PyTuple_Pack(1, obj(mtx))), &outs);
    if (rc == AMGX_RC_OK && outs.size() >= 3) {
        PyArrayObject *rp = (PyArrayObject *)PyArray_FROM_OTF(
            outs[0], NPY_INT32, NPY_ARRAY_C_CONTIGUOUS);
        PyArrayObject *ci = (PyArrayObject *)PyArray_FROM_OTF(
            outs[1], NPY_INT32, NPY_ARRAY_C_CONTIGUOUS);
        PyArrayObject *dv = (PyArrayObject *)PyArray_FROM_OTF(
            outs[2], mode_mat_typenum(static_cast<Handle *>(mtx)),
            NPY_ARRAY_C_CONTIGUOUS);
        if (rp && row_ptrs)
            memcpy(row_ptrs, PyArray_DATA(rp),
                   PyArray_NBYTES(rp));
        if (ci && col_indices)
            memcpy(col_indices, PyArray_DATA(ci), PyArray_NBYTES(ci));
        if (dv && data) memcpy(data, PyArray_DATA(dv), PyArray_NBYTES(dv));
        Py_XDECREF(rp);
        Py_XDECREF(ci);
        Py_XDECREF(dv);
    }
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_matrix_vector_multiply(AMGX_matrix_handle mtx,
                                    AMGX_vector_handle x,
                                    AMGX_vector_handle y) {
    Gil gil;
    return unpack_rc(call("AMGX_matrix_vector_multiply",
                          PyTuple_Pack(3, obj(mtx), obj(x), obj(y))));
}

/* ------------------------------------------------------------- vector */
AMGX_RC AMGX_matrix_comm_from_maps(AMGX_matrix_handle mtx,
                                   int allocated_halo_depth,
                                   int num_import_rings,
                                   int max_num_neighbors,
                                   const int *neighbors,
                                   const int *send_ptrs,
                                   const int *send_maps,
                                   const int *recv_ptrs,
                                   const int *recv_maps) {
    Gil gil;
    Handle *h = static_cast<Handle *>(mtx);
    int nn = max_num_neighbors;
    PyObject *nb = np_view(neighbors, nn, NPY_INT32);
    PyObject *sp = np_view(send_ptrs, nn + 1, NPY_INT32);
    PyObject *sm = np_view(send_maps, nn ? send_ptrs[nn] : 0, NPY_INT32);
    PyObject *rp = np_view(recv_ptrs, nn + 1, NPY_INT32);
    PyObject *rm = np_view(recv_maps, nn ? recv_ptrs[nn] : 0, NPY_INT32);
    PyObject *args = Py_BuildValue("(OiiiOOOOO)", h->obj,
                                   allocated_halo_depth, num_import_rings,
                                   nn, nb, sp, sm, rp, rm);
    Py_DECREF(nb); Py_DECREF(sp); Py_DECREF(sm);
    Py_DECREF(rp); Py_DECREF(rm);
    return unpack_rc(call("AMGX_matrix_comm_from_maps", args));
}

AMGX_RC AMGX_matrix_comm_from_maps_one_ring(AMGX_matrix_handle mtx,
                                            int allocated_halo_depth,
                                            int num_neighbors,
                                            const int *neighbors,
                                            const int *send_sizes,
                                            const int **send_maps,
                                            const int *recv_sizes,
                                            const int **recv_maps) {
    Gil gil;
    Handle *h = static_cast<Handle *>(mtx);
    int nn = num_neighbors;
    PyObject *nb = np_view(neighbors, nn, NPY_INT32);
    PyObject *ss = np_view(send_sizes, nn, NPY_INT32);
    PyObject *rs = np_view(recv_sizes, nn, NPY_INT32);
    PyObject *sml = PyList_New(nn);
    PyObject *rml = PyList_New(nn);
    for (int i = 0; i < nn; ++i) {
        PyList_SetItem(sml, i,
                       np_view(send_maps[i], send_sizes[i], NPY_INT32));
        PyList_SetItem(rml, i,
                       np_view(recv_maps[i], recv_sizes[i], NPY_INT32));
    }
    PyObject *args = Py_BuildValue("(OiiOOOOO)", h->obj,
                                   allocated_halo_depth, nn, nb, ss, sml,
                                   rs, rml);
    Py_DECREF(nb); Py_DECREF(ss); Py_DECREF(rs);
    Py_DECREF(sml); Py_DECREF(rml);
    return unpack_rc(call("AMGX_matrix_comm_from_maps_one_ring", args));
}

AMGX_RC AMGX_vector_create(AMGX_vector_handle *vec,
                           AMGX_resources_handle rsc, AMGX_Mode mode) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_vector_create",
             Py_BuildValue("(Os)", static_cast<Handle *>(rsc)->obj,
                           mode_name(mode))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *vec = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_vector_destroy(AMGX_vector_handle vec) {
    drop(vec);
    return AMGX_RC_OK;
}

static int handle_vec_typenum(Handle *h) {
    return handle_mode_typenum(h, "vec_dtype");
}

AMGX_RC AMGX_vector_upload(AMGX_vector_handle vec, int n, int block_dim,
                           const void *data) {
    Gil gil;
    Handle *h = static_cast<Handle *>(vec);
    int tn = handle_vec_typenum(h);
    PyObject *arr = np_view(data, (npy_intp)n * block_dim, tn);
    PyObject *args = Py_BuildValue("(OiiO)", h->obj, n, block_dim, arr);
    Py_DECREF(arr);
    return unpack_rc(call("AMGX_vector_upload", args));
}

AMGX_RC AMGX_vector_set_zero(AMGX_vector_handle vec, int n,
                             int block_dim) {
    Gil gil;
    return unpack_rc(call("AMGX_vector_set_zero",
                          Py_BuildValue("(Oii)",
                                        static_cast<Handle *>(vec)->obj, n,
                                        block_dim)));
}

AMGX_RC AMGX_vector_download(AMGX_vector_handle vec, void *data) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_vector_download", PyTuple_Pack(1, obj(vec))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty() && data) {
        PyArrayObject *arr = (PyArrayObject *)PyArray_FROM_OTF(
            outs[0], handle_vec_typenum(static_cast<Handle *>(vec)),
            NPY_ARRAY_C_CONTIGUOUS);
        if (arr) {
            memcpy(data, PyArray_DATA(arr), PyArray_NBYTES(arr));
            Py_DECREF(arr);
        }
    }
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_vector_get_size(AMGX_vector_handle vec, int *n,
                             int *block_dim) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_vector_get_size", PyTuple_Pack(1, obj(vec))), &outs);
    if (rc == AMGX_RC_OK && outs.size() >= 2) {
        if (n) *n = (int)PyLong_AsLong(outs[0]);
        if (block_dim) *block_dim = (int)PyLong_AsLong(outs[1]);
    }
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_vector_bind(AMGX_vector_handle vec, AMGX_matrix_handle mtx) {
    Gil gil;
    return unpack_rc(
        call("AMGX_vector_bind", PyTuple_Pack(2, obj(vec), obj(mtx))));
}

/* ------------------------------------------------------------- solver */
AMGX_RC AMGX_solver_create(AMGX_solver_handle *slv,
                           AMGX_resources_handle rsc, AMGX_Mode mode,
                           AMGX_config_handle cfg) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_solver_create",
             Py_BuildValue("(OsO)", static_cast<Handle *>(rsc)->obj,
                           mode_name(mode),
                           static_cast<Handle *>(cfg)->obj)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *slv = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_solver_destroy(AMGX_solver_handle slv) {
    drop(slv);
    return AMGX_RC_OK;
}

AMGX_RC AMGX_solver_setup(AMGX_solver_handle slv, AMGX_matrix_handle mtx) {
    Gil gil;
    return unpack_rc(
        call("AMGX_solver_setup", PyTuple_Pack(2, obj(slv), obj(mtx))));
}

AMGX_RC AMGX_solver_resetup(AMGX_solver_handle slv,
                            AMGX_matrix_handle mtx) {
    Gil gil;
    return unpack_rc(
        call("AMGX_solver_resetup", PyTuple_Pack(2, obj(slv), obj(mtx))));
}

AMGX_RC AMGX_solver_solve(AMGX_solver_handle slv, AMGX_vector_handle rhs,
                          AMGX_vector_handle sol) {
    Gil gil;
    return unpack_rc(call(
        "AMGX_solver_solve", PyTuple_Pack(3, obj(slv), obj(rhs), obj(sol))));
}

AMGX_RC AMGX_solver_solve_with_0_initial_guess(AMGX_solver_handle slv,
                                               AMGX_vector_handle rhs,
                                               AMGX_vector_handle sol) {
    Gil gil;
    return unpack_rc(call("AMGX_solver_solve_with_0_initial_guess",
                          PyTuple_Pack(3, obj(slv), obj(rhs), obj(sol))));
}

AMGX_RC AMGX_solver_get_iterations_number(AMGX_solver_handle slv, int *n) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(call("AMGX_solver_get_iterations_number",
                                PyTuple_Pack(1, obj(slv))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *n = (int)PyLong_AsLong(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_solver_get_iteration_residual(AMGX_solver_handle slv, int it,
                                           int idx, double *res) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_solver_get_iteration_residual",
             Py_BuildValue("(Oii)", static_cast<Handle *>(slv)->obj, it,
                           idx)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *res = PyFloat_AsDouble(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_solver_get_status(AMGX_solver_handle slv,
                               AMGX_SOLVE_STATUS *st) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_solver_get_status", PyTuple_Pack(1, obj(slv))), &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *st = (AMGX_SOLVE_STATUS)PyLong_AsLong(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_solver_get_setup_time(AMGX_solver_handle slv, double *t) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_solver_get_setup_time", PyTuple_Pack(1, obj(slv))),
        &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *t = PyFloat_AsDouble(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

AMGX_RC AMGX_solver_get_solve_time(AMGX_solver_handle slv, double *t) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_solver_get_solve_time", PyTuple_Pack(1, obj(slv))),
        &outs);
    if (rc == AMGX_RC_OK && !outs.empty())
        *t = PyFloat_AsDouble(outs[0]);
    for (auto *o : outs) Py_DECREF(o);
    return rc;
}

/* ----------------------------------------------------------------- io */
AMGX_RC AMGX_read_system(AMGX_matrix_handle mtx, AMGX_vector_handle rhs,
                         AMGX_vector_handle sol, const char *filename) {
    Gil gil;
    PyObject *args =
        Py_BuildValue("(OOOs)", static_cast<Handle *>(mtx)->obj,
                      rhs ? static_cast<Handle *>(rhs)->obj : Py_None,
                      sol ? static_cast<Handle *>(sol)->obj : Py_None,
                      filename);
    return unpack_rc(call("AMGX_read_system", args));
}

AMGX_RC AMGX_write_system(AMGX_matrix_handle mtx, AMGX_vector_handle rhs,
                          AMGX_vector_handle sol, const char *filename) {
    Gil gil;
    PyObject *args =
        Py_BuildValue("(OOOs)", static_cast<Handle *>(mtx)->obj,
                      rhs ? static_cast<Handle *>(rhs)->obj : Py_None,
                      sol ? static_cast<Handle *>(sol)->obj : Py_None,
                      filename);
    return unpack_rc(call("AMGX_write_system", args));
}

/* -------------------------------------------------------- eigensolver */
AMGX_RC AMGX_eigensolver_create(AMGX_eigensolver_handle *es,
                                AMGX_resources_handle rsc, AMGX_Mode mode,
                                AMGX_config_handle cfg) {
    Gil gil;
    std::vector<PyObject *> outs;
    AMGX_RC rc = unpack_rc(
        call("AMGX_eigensolver_create",
             Py_BuildValue("(OsO)", static_cast<Handle *>(rsc)->obj,
                           mode_name(mode),
                           static_cast<Handle *>(cfg)->obj)), &outs);
    if (rc == AMGX_RC_OK && !outs.empty()) *es = wrap(outs[0]);
    return rc;
}

AMGX_RC AMGX_eigensolver_setup(AMGX_eigensolver_handle es,
                               AMGX_matrix_handle mtx) {
    Gil gil;
    return unpack_rc(call("AMGX_eigensolver_setup",
                          PyTuple_Pack(2, obj(es), obj(mtx))));
}

AMGX_RC AMGX_eigensolver_solve(AMGX_eigensolver_handle es,
                               AMGX_vector_handle x) {
    Gil gil;
    return unpack_rc(
        call("AMGX_eigensolver_solve", PyTuple_Pack(2, obj(es), obj(x))));
}

AMGX_RC AMGX_eigensolver_destroy(AMGX_eigensolver_handle es) {
    drop(es);
    return AMGX_RC_OK;
}

}  /* extern "C" */
