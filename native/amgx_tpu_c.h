/* amgx_tpu_c.h — C ABI of the TPU-native AmgX-capable solver library.
 *
 * Freshly authored declaration of the AMGX C contract (function names and
 * signatures follow the public API documented in the reference's
 * base/include/amgx_c.h so existing drivers compile unchanged; no code is
 * copied — this is the ABI, implemented by embedding the amgx_tpu Python
 * runtime, see amgx_c_shim.cpp).
 */
#ifndef AMGX_TPU_C_H
#define AMGX_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

/* return codes (values match the reference AMGX_RC enum) */
typedef enum {
    AMGX_RC_OK = 0,
    AMGX_RC_BAD_PARAMETERS = 1,
    AMGX_RC_UNKNOWN = 2,
    AMGX_RC_NOT_SUPPORTED_TARGET = 3,
    AMGX_RC_NOT_SUPPORTED_BLOCKSIZE = 4,
    AMGX_RC_CUDA_FAILURE = 5,
    AMGX_RC_THRUST_FAILURE = 6,
    AMGX_RC_NO_MEMORY = 7,
    AMGX_RC_IO_ERROR = 8,
    AMGX_RC_BAD_MODE = 9,
    AMGX_RC_CORE = 10,
    AMGX_RC_PLUGIN = 11,
    AMGX_RC_BAD_CONFIGURATION = 12,
    AMGX_RC_NOT_IMPLEMENTED = 13,
    AMGX_RC_LICENSE_NOT_FOUND = 14,
    AMGX_RC_INTERNAL = 15
} AMGX_RC;

typedef enum {
    AMGX_SOLVE_SUCCESS = 0,
    AMGX_SOLVE_FAILED = 1,
    AMGX_SOLVE_DIVERGED = 2
} AMGX_SOLVE_STATUS;

/* modes: packed like the reference AMGX_Mode enum ordering */
typedef enum {
    AMGX_mode_hDDI = 0, AMGX_mode_hDFI = 1, AMGX_mode_hFFI = 2,
    AMGX_mode_dDDI = 3, AMGX_mode_dDFI = 4, AMGX_mode_dFFI = 5,
    AMGX_mode_hZZI = 6, AMGX_mode_hZCI = 7, AMGX_mode_hCCI = 8,
    AMGX_mode_dZZI = 9, AMGX_mode_dZCI = 10, AMGX_mode_dCCI = 11
} AMGX_Mode;

/* opaque handles */
typedef void *AMGX_config_handle;
typedef void *AMGX_resources_handle;
typedef void *AMGX_matrix_handle;
typedef void *AMGX_vector_handle;
typedef void *AMGX_solver_handle;
typedef void *AMGX_eigensolver_handle;

typedef void (*AMGX_print_callback)(const char *msg, int length);

/* lifecycle */
AMGX_RC AMGX_initialize(void);
AMGX_RC AMGX_initialize_plugins(void);
AMGX_RC AMGX_finalize(void);
AMGX_RC AMGX_finalize_plugins(void);
AMGX_RC AMGX_get_api_version(int *major, int *minor);
AMGX_RC AMGX_get_error_string(AMGX_RC err, char *buf, int buf_len);
void AMGX_abort(AMGX_resources_handle rsrc, int err);
AMGX_RC AMGX_register_print_callback(AMGX_print_callback callback);
/* amgx_c.h:396 — routes to the same global print stream */
AMGX_RC AMGX_solver_register_print_callback(AMGX_print_callback callback);
AMGX_RC AMGX_install_signal_handler(void);
AMGX_RC AMGX_reset_signal_handler(void);
AMGX_RC AMGX_pin_memory(void *ptr, unsigned int bytes);
AMGX_RC AMGX_unpin_memory(void *ptr);

/* config */
AMGX_RC AMGX_config_create(AMGX_config_handle *cfg, const char *options);
AMGX_RC AMGX_config_create_from_file(AMGX_config_handle *cfg,
                                     const char *param_file);
AMGX_RC AMGX_config_create_from_file_and_string(AMGX_config_handle *cfg,
                                                const char *param_file,
                                                const char *options);
AMGX_RC AMGX_config_add_parameters(AMGX_config_handle *cfg,
                                   const char *options);
AMGX_RC AMGX_config_get_default_number_of_rings(AMGX_config_handle cfg,
                                                int *num_rings);
AMGX_RC AMGX_config_destroy(AMGX_config_handle cfg);
AMGX_RC AMGX_write_parameters_description(char *filename);

/* resources */
AMGX_RC AMGX_resources_create(AMGX_resources_handle *rsc,
                              AMGX_config_handle cfg, void *comm,
                              int device_num, const int *devices);
AMGX_RC AMGX_resources_create_simple(AMGX_resources_handle *rsc,
                                     AMGX_config_handle cfg);
AMGX_RC AMGX_resources_destroy(AMGX_resources_handle rsc);

/* matrix */
AMGX_RC AMGX_matrix_create(AMGX_matrix_handle *mtx,
                           AMGX_resources_handle rsc, AMGX_Mode mode);
AMGX_RC AMGX_matrix_destroy(AMGX_matrix_handle mtx);
AMGX_RC AMGX_matrix_upload_all(AMGX_matrix_handle mtx, int n, int nnz,
                               int block_dimx, int block_dimy,
                               const int *row_ptrs, const int *col_indices,
                               const void *data, const void *diag_data);
AMGX_RC AMGX_matrix_replace_coefficients(AMGX_matrix_handle mtx, int n,
                                         int nnz, const void *data,
                                         const void *diag_data);
AMGX_RC AMGX_matrix_get_size(AMGX_matrix_handle mtx, int *n,
                             int *block_dimx, int *block_dimy);
AMGX_RC AMGX_matrix_get_nnz(AMGX_matrix_handle mtx, int *nnz);
AMGX_RC AMGX_matrix_download_all(AMGX_matrix_handle mtx, int *row_ptrs,
                                 int *col_indices, void *data,
                                 void **diag_data);
AMGX_RC AMGX_matrix_vector_multiply(AMGX_matrix_handle mtx,
                                    AMGX_vector_handle x,
                                    AMGX_vector_handle y);
AMGX_RC AMGX_matrix_comm_from_maps(AMGX_matrix_handle mtx,
                                   int allocated_halo_depth,
                                   int num_import_rings,
                                   int max_num_neighbors,
                                   const int *neighbors,
                                   const int *send_ptrs,
                                   const int *send_maps,
                                   const int *recv_ptrs,
                                   const int *recv_maps);
AMGX_RC AMGX_matrix_comm_from_maps_one_ring(AMGX_matrix_handle mtx,
                                            int allocated_halo_depth,
                                            int num_neighbors,
                                            const int *neighbors,
                                            const int *send_sizes,
                                            const int **send_maps,
                                            const int *recv_sizes,
                                            const int **recv_maps);

/* vector */
AMGX_RC AMGX_vector_create(AMGX_vector_handle *vec,
                           AMGX_resources_handle rsc, AMGX_Mode mode);
AMGX_RC AMGX_vector_destroy(AMGX_vector_handle vec);
AMGX_RC AMGX_vector_upload(AMGX_vector_handle vec, int n, int block_dim,
                           const void *data);
AMGX_RC AMGX_vector_set_zero(AMGX_vector_handle vec, int n, int block_dim);
AMGX_RC AMGX_vector_download(AMGX_vector_handle vec, void *data);
AMGX_RC AMGX_vector_get_size(AMGX_vector_handle vec, int *n,
                             int *block_dim);
AMGX_RC AMGX_vector_bind(AMGX_vector_handle vec, AMGX_matrix_handle mtx);

/* solver */
AMGX_RC AMGX_solver_create(AMGX_solver_handle *slv,
                           AMGX_resources_handle rsc, AMGX_Mode mode,
                           AMGX_config_handle cfg);
AMGX_RC AMGX_solver_destroy(AMGX_solver_handle slv);
AMGX_RC AMGX_solver_setup(AMGX_solver_handle slv, AMGX_matrix_handle mtx);
AMGX_RC AMGX_solver_resetup(AMGX_solver_handle slv, AMGX_matrix_handle mtx);
AMGX_RC AMGX_solver_solve(AMGX_solver_handle slv, AMGX_vector_handle rhs,
                          AMGX_vector_handle sol);
AMGX_RC AMGX_solver_solve_with_0_initial_guess(AMGX_solver_handle slv,
                                               AMGX_vector_handle rhs,
                                               AMGX_vector_handle sol);
AMGX_RC AMGX_solver_get_iterations_number(AMGX_solver_handle slv, int *n);
AMGX_RC AMGX_solver_get_iteration_residual(AMGX_solver_handle slv, int it,
                                           int idx, double *res);
AMGX_RC AMGX_solver_get_status(AMGX_solver_handle slv,
                               AMGX_SOLVE_STATUS *st);
AMGX_RC AMGX_solver_get_setup_time(AMGX_solver_handle slv, double *t);
AMGX_RC AMGX_solver_get_solve_time(AMGX_solver_handle slv, double *t);

/* io */
AMGX_RC AMGX_read_system(AMGX_matrix_handle mtx, AMGX_vector_handle rhs,
                         AMGX_vector_handle sol, const char *filename);
AMGX_RC AMGX_write_system(AMGX_matrix_handle mtx, AMGX_vector_handle rhs,
                          AMGX_vector_handle sol, const char *filename);

/* eigensolver */
AMGX_RC AMGX_eigensolver_create(AMGX_eigensolver_handle *es,
                                AMGX_resources_handle rsc, AMGX_Mode mode,
                                AMGX_config_handle cfg);
AMGX_RC AMGX_eigensolver_setup(AMGX_eigensolver_handle es,
                               AMGX_matrix_handle mtx);
AMGX_RC AMGX_eigensolver_solve(AMGX_eigensolver_handle es,
                               AMGX_vector_handle x);
AMGX_RC AMGX_eigensolver_destroy(AMGX_eigensolver_handle es);

#ifdef __cplusplus
}
#endif
#endif /* AMGX_TPU_C_H */
