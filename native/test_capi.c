/* test_capi.c — C driver against libamgx_tpu_c.so proving the native ABI
 * works end-to-end: build a 2D Poisson, PCG+Jacobi solve, check residual.
 * (The flow mirrors the reference examples/amgx_capi.c shape.)
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "amgx_tpu_c.h"

#define NX 16
#define N (NX * NX)
#define CHECK(call)                                                    \
    do {                                                               \
        AMGX_RC rc_ = (call);                                          \
        if (rc_ != AMGX_RC_OK) {                                       \
            fprintf(stderr, "FAILED %s -> %d\n", #call, (int)rc_);     \
            return 1;                                                  \
        }                                                              \
    } while (0)

int main(void) {
    /* assemble 5-point Poisson in CSR */
    int *row_ptrs = malloc((N + 1) * sizeof(int));
    int *cols = malloc(5 * N * sizeof(int));
    double *vals = malloc(5 * N * sizeof(double));
    int nnz = 0;
    for (int i = 0; i < N; ++i) {
        int x = i % NX, y = i / NX;
        row_ptrs[i] = nnz;
        if (y > 0) { cols[nnz] = i - NX; vals[nnz++] = -1.0; }
        if (x > 0) { cols[nnz] = i - 1; vals[nnz++] = -1.0; }
        cols[nnz] = i; vals[nnz++] = 4.0;
        if (x < NX - 1) { cols[nnz] = i + 1; vals[nnz++] = -1.0; }
        if (y < NX - 1) { cols[nnz] = i + NX; vals[nnz++] = -1.0; }
    }
    row_ptrs[N] = nnz;

    CHECK(AMGX_initialize());
    AMGX_config_handle cfg;
    CHECK(AMGX_config_create(&cfg,
        "config_version=2, solver(s)=PCG, "
        "s:preconditioner(p)=BLOCK_JACOBI, p:max_iters=3, s:max_iters=200, "
        "s:monitor_residual=1, s:tolerance=1e-9, "
        "s:convergence=RELATIVE_INI"));
    AMGX_resources_handle rsc;
    CHECK(AMGX_resources_create_simple(&rsc, cfg));
    AMGX_matrix_handle A;
    AMGX_vector_handle b, x;
    CHECK(AMGX_matrix_create(&A, rsc, AMGX_mode_hDDI));
    CHECK(AMGX_vector_create(&b, rsc, AMGX_mode_hDDI));
    CHECK(AMGX_vector_create(&x, rsc, AMGX_mode_hDDI));
    CHECK(AMGX_matrix_upload_all(A, N, nnz, 1, 1, row_ptrs, cols, vals,
                                 NULL));
    double *ones = malloc(N * sizeof(double));
    for (int i = 0; i < N; ++i) ones[i] = 1.0;
    CHECK(AMGX_vector_upload(b, N, 1, ones));
    CHECK(AMGX_vector_set_zero(x, N, 1));

    AMGX_solver_handle solver;
    CHECK(AMGX_solver_create(&solver, rsc, AMGX_mode_hDDI, cfg));
    CHECK(AMGX_solver_setup(solver, A));
    CHECK(AMGX_solver_solve(solver, b, x));
    AMGX_SOLVE_STATUS st;
    int iters;
    CHECK(AMGX_solver_get_status(solver, &st));
    CHECK(AMGX_solver_get_iterations_number(solver, &iters));

    double *sol = malloc(N * sizeof(double));
    CHECK(AMGX_vector_download(x, sol));
    /* residual check in C */
    double rmax = 0.0;
    for (int i = 0; i < N; ++i) {
        double ax = 0.0;
        for (int k = row_ptrs[i]; k < row_ptrs[i + 1]; ++k)
            ax += vals[k] * sol[cols[k]];
        double r = fabs(1.0 - ax);
        if (r > rmax) rmax = r;
    }
    printf("status=%d iterations=%d max_residual=%.3e\n", (int)st, iters,
           rmax);
    CHECK(AMGX_solver_destroy(solver));
    CHECK(AMGX_matrix_destroy(A));
    CHECK(AMGX_vector_destroy(b));
    CHECK(AMGX_vector_destroy(x));
    CHECK(AMGX_resources_destroy(rsc));
    CHECK(AMGX_config_destroy(cfg));
    CHECK(AMGX_finalize());
    if (st != AMGX_SOLVE_SUCCESS || rmax > 1e-6) {
        fprintf(stderr, "SOLVE CHECK FAILED\n");
        return 2;
    }
    printf("NATIVE CAPI TEST PASSED\n");
    return 0;
}
