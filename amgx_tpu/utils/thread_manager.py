"""Async setup task pool.

Reference: ``base/include/thread_manager.h:46-173`` — ``ThreadManager``
with ``spawn_threads``/``join_threads``/``wait_threads`` running
``AsyncTask``s so smoother setup overlaps across levels, and the
``serialize_threads`` debug flag (``core.cu:356``) that forces serial
execution.

Here the pool overlaps the HOST side of per-level setup (coloring,
slab packing, diagonal inversion in numpy/scipy, which release the GIL)
and the async device uploads those setups dispatch.  Tasks must be
independent — the hierarchy's per-level smoother setups are.  The
serving layer (amgx_tpu/serve/) runs its batch solves on the same pool
shape, so task failures are survivable: a raising task is counted
(``amgx_worker_task_failures_total``) and recorded, the worker and pool
stay alive, and :meth:`wait_threads` re-raises the first failure to the
caller that asked for the results.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, List, Optional


class ThreadManager:
    """Small task pool mirroring the reference API surface."""

    def __init__(self, max_workers: Optional[int] = None,
                 serialize: bool = False):
        self.serialize = bool(serialize)
        self._max_workers = max_workers
        self._futures: List[concurrent.futures.Future] = []
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._fail_lock = threading.Lock()
        self._spawn_lock = threading.Lock()
        #: first exception harvested from a PRUNED completed future —
        #: wait_threads re-raises it so pruning never swallows a failure
        self._pending_exc: Optional[BaseException] = None
        #: tasks that raised since construction (cumulative; the pool
        #: survives every one of them)
        self.failed_tasks = 0

    # ------------------------------------------------ reference API names
    def spawn_threads(self) -> None:
        # locked: concurrent first pushes auto-spawn (push_work below) —
        # an unlocked check-then-create would leak a second executor
        with self._spawn_lock:
            if not self.serialize and self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="amgx-worker")

    def _guard(self, task: Callable[[], None]):
        """Exception-safe task wrapper: count + record the failure (the
        telemetry counter makes silent worker deaths observable) and
        re-raise into the future so :meth:`wait_threads` keeps its
        fail-the-caller contract.  The executor worker itself survives
        and keeps draining the queue."""
        try:
            return task()
        except BaseException:
            with self._fail_lock:
                self.failed_tasks += 1
            try:
                from ..telemetry import metrics as _m
                _m.counter_inc("amgx_worker_task_failures_total")
            except Exception:
                pass    # telemetry must never mask the task's failure
            raise

    def push_work(self, task: Callable[[], None]) -> None:
        """Queue one AsyncTask; runs inline under ``serialize_threads``.

        ``push_work`` before :meth:`spawn_threads` auto-spawns the pool
        (the old behaviour ran the task inline, silently serialising a
        caller that forgot to spawn)."""
        if self.serialize:
            self._guard(task)
            return
        if self._pool is None:
            self.spawn_threads()
        self._futures.append(self._pool.submit(self._guard, task))
        if len(self._futures) >= 512:
            # long-running users (the serving dispatcher) push work for
            # the process lifetime and only wait at drain — prune
            # completed futures so the list stays bounded, harvesting
            # any failure for the next wait_threads
            keep = []
            for f in self._futures:
                if f.done():
                    exc = f.exception()
                    if exc is not None and self._pending_exc is None:
                        self._pending_exc = exc
                else:
                    keep.append(f)
            self._futures = keep

    def wait_threads(self) -> None:
        """Block until every queued task finished; re-raise the first
        failure (a failed smoother setup must fail the hierarchy setup)."""
        futures, self._futures = self._futures, []
        first_exc, self._pending_exc = self._pending_exc, None
        for f in futures:
            try:
                f.result()
            except BaseException as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def join_threads(self) -> None:
        self.wait_threads()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        self.spawn_threads()
        return self

    def __exit__(self, *exc):
        try:
            self.join_threads()
        except Exception:
            if exc == (None, None, None):
                raise
        return False
