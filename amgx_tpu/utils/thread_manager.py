"""Async setup task pool.

Reference: ``base/include/thread_manager.h:46-173`` — ``ThreadManager``
with ``spawn_threads``/``join_threads``/``wait_threads`` running
``AsyncTask``s so smoother setup overlaps across levels, and the
``serialize_threads`` debug flag (``core.cu:356``) that forces serial
execution.

Here the pool overlaps the HOST side of per-level setup (coloring,
slab packing, diagonal inversion in numpy/scipy, which release the GIL)
and the async device uploads those setups dispatch.  Tasks must be
independent — the hierarchy's per-level smoother setups are.  The
serving layer (amgx_tpu/serve/) runs its batch solves on the same pool
shape, so task failures are survivable: a raising task is counted
(``amgx_worker_task_failures_total``) and recorded, the worker and pool
stay alive, and :meth:`wait_threads` re-raises the first failure to the
caller that asked for the results.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, List, Optional


class ThreadManager:
    """Small task pool mirroring the reference API surface."""

    def __init__(self, max_workers: Optional[int] = None,
                 serialize: bool = False):
        self.serialize = bool(serialize)
        self._max_workers = max_workers
        self._futures: List[concurrent.futures.Future] = []
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._fail_lock = threading.Lock()
        self._spawn_lock = threading.Lock()
        #: first exception harvested from a PRUNED completed future —
        #: wait_threads re-raises it so pruning never swallows a failure
        self._pending_exc: Optional[BaseException] = None
        #: tasks that raised since construction (cumulative; the pool
        #: survives every one of them)
        self.failed_tasks = 0
        #: pools re-created after worker death / out-of-band shutdown
        #: was detected (push_work checks liveness before submitting)
        self.respawns = 0

    # ------------------------------------------------ reference API names
    def spawn_threads(self) -> None:
        # locked: concurrent first pushes auto-spawn (push_work below) —
        # an unlocked check-then-create would leak a second executor
        with self._spawn_lock:
            if not self.serialize and self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="amgx-worker")

    def ensure_alive(self) -> bool:
        """Worker-death detection: a pool that was shut down out of
        band (or whose worker threads all died) is replaced with a
        fresh one so the NEXT task runs instead of raising
        ``RuntimeError: cannot schedule new futures``.  Returns True
        when a respawn happened.  The detection AND replacement run
        under one lock so a concurrent ``push_work`` never observes a
        half-respawned (None) pool.  In-flight futures of the dead
        pool stay failed — their requests complete with a terminal
        error through the batch task's own guards, never a hang."""
        if self.serialize:
            return False
        with self._spawn_lock:
            pool = self._pool
            if pool is None:
                return False
            dead = getattr(pool, "_shutdown", False)
            if not dead:
                threads = getattr(pool, "_threads", None)
                dead = bool(threads) and all(not t.is_alive()
                                             for t in threads)
            if not dead:
                return False
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="amgx-worker")
            self.respawns += 1
        try:
            from ..telemetry import metrics as _m
            _m.counter_inc("amgx_worker_respawns_total")
        except Exception:
            pass    # telemetry must never block the respawn
        return True

    def _guard(self, task: Callable[[], None]):
        """Exception-safe task wrapper: count + record the failure (the
        telemetry counter makes silent worker deaths observable) and
        re-raise into the future so :meth:`wait_threads` keeps its
        fail-the-caller contract.  The executor worker itself survives
        and keeps draining the queue."""
        try:
            # chaos harness (utils/faultinject.py): the worker_death
            # point kills THIS task the way a crashing worker would —
            # the guard's accounting below proves the pool survives it
            from .faultinject import WorkerDeathError, maybe_raise
            maybe_raise("worker_death",
                        WorkerDeathError("injected worker death"))
            return task()
        except BaseException:
            with self._fail_lock:
                self.failed_tasks += 1
            try:
                from ..telemetry import metrics as _m
                _m.counter_inc("amgx_worker_task_failures_total")
            except Exception:
                pass    # telemetry must never mask the task's failure
            raise

    def push_work(self, task: Callable[[], None]
                  ) -> "Optional[concurrent.futures.Future]":
        """Queue one AsyncTask; runs inline under ``serialize_threads``.

        ``push_work`` before :meth:`spawn_threads` auto-spawns the pool
        (the old behaviour ran the task inline, silently serialising a
        caller that forgot to spawn).  Returns the Future (None under
        ``serialize``) so callers that must observe worker death — the
        serving lanes, whose in-flight requests would otherwise hang if
        a worker died before entering the batch body — can attach a
        done-callback."""
        if self.serialize:
            self._guard(task)
            return None
        if self._pool is None:
            self.spawn_threads()
        else:
            self.ensure_alive()
        try:
            fut = self._pool.submit(self._guard, task)
        except RuntimeError:
            # raced a shutdown between the liveness check and submit:
            # respawn once and retry — a second failure is a real bug
            self.ensure_alive()
            fut = self._pool.submit(self._guard, task)
        self._futures.append(fut)
        self._prune()
        return fut

    def _prune(self):
        if len(self._futures) >= 512:
            # long-running users (the serving dispatcher) push work for
            # the process lifetime and only wait at drain — prune
            # completed futures so the list stays bounded, harvesting
            # any failure for the next wait_threads
            keep = []
            for f in self._futures:
                if f.done():
                    exc = f.exception()
                    if exc is not None and self._pending_exc is None:
                        self._pending_exc = exc
                else:
                    keep.append(f)
            self._futures = keep

    def wait_threads(self) -> None:
        """Block until every queued task finished; re-raise the first
        failure (a failed smoother setup must fail the hierarchy setup)."""
        futures, self._futures = self._futures, []
        first_exc, self._pending_exc = self._pending_exc, None
        for f in futures:
            try:
                f.result()
            except BaseException as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def join_threads(self) -> None:
        self.wait_threads()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        self.spawn_threads()
        return self

    def __exit__(self, *exc):
        try:
            self.join_threads()
        except Exception:
            if exc == (None, None, None):
                raise
        return False
