"""Async setup task pool.

Reference: ``base/include/thread_manager.h:46-173`` — ``ThreadManager``
with ``spawn_threads``/``join_threads``/``wait_threads`` running
``AsyncTask``s so smoother setup overlaps across levels, and the
``serialize_threads`` debug flag (``core.cu:356``) that forces serial
execution.

Here the pool overlaps the HOST side of per-level setup (coloring,
slab packing, diagonal inversion in numpy/scipy, which release the GIL)
and the async device uploads those setups dispatch.  Tasks must be
independent — the hierarchy's per-level smoother setups are.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, List, Optional


class ThreadManager:
    """Small task pool mirroring the reference API surface."""

    def __init__(self, max_workers: Optional[int] = None,
                 serialize: bool = False):
        self.serialize = bool(serialize)
        self._max_workers = max_workers
        self._futures: List[concurrent.futures.Future] = []
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None

    # ------------------------------------------------ reference API names
    def spawn_threads(self) -> None:
        if not self.serialize and self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="amgx-setup")

    def push_work(self, task: Callable[[], None]) -> None:
        """Queue one AsyncTask; runs inline under ``serialize_threads``."""
        if self.serialize or self._pool is None:
            task()
            return
        self._futures.append(self._pool.submit(task))

    def wait_threads(self) -> None:
        """Block until every queued task finished; re-raise the first
        failure (a failed smoother setup must fail the hierarchy setup)."""
        futures, self._futures = self._futures, []
        for f in futures:
            f.result()

    def join_threads(self) -> None:
        self.wait_threads()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        self.spawn_threads()
        return self

    def __exit__(self, *exc):
        try:
            self.join_threads()
        except Exception:
            if exc == (None, None, None):
                raise
        return False
