"""Atomic file writes shared by the warm-start persistence layers.

Both the AOT executable store (``serve/aot.py``) and the runstate
counter file (``telemetry/runstate.py``) must never expose a torn file
to a concurrent reader — entries are written to a temp file in the
destination directory and moved into place with ``os.replace``.
"""
from __future__ import annotations

import os
import tempfile


def atomic_write(path: str, data: bytes):
    """Write ``data`` to ``path`` atomically (tmp + rename in the same
    directory).  Raises ``OSError`` on failure after removing the temp
    file — callers decide whether a failed write is fatal."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
