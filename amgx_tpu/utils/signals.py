"""Signal handlers printing a stack trace.

Reference: ``base/src/amg_signal.cu:28-120`` + ``stacktrace.h`` — hooks
SIGSEGV/SIGFPE/SIGINT/… to print a backtrace before dying
(``AMGX_install_signal_handler``, amgx_c.h:208).
"""
from __future__ import annotations

import signal
import sys
import traceback

from .logging import error_output

_HOOKED = (signal.SIGSEGV, signal.SIGFPE, signal.SIGABRT, signal.SIGINT,
           signal.SIGTERM)
_old_handlers = {}


def _handler(signum, frame):
    name = signal.Signals(signum).name
    error_output(f"Caught signal {signum} - {name}\n")
    error_output("".join(traceback.format_stack(frame)))
    # restore + re-raise so default semantics apply (amg_signal.cu behaviour)
    reset_signal_handlers()
    signal.raise_signal(signum)


def install_signal_handlers():
    for sig in _HOOKED:
        try:
            _old_handlers[sig] = signal.signal(sig, _handler)
        except (ValueError, OSError):
            pass  # non-main thread or unsupported signal


def reset_signal_handlers():
    for sig, old in list(_old_handlers.items()):
        try:
            signal.signal(sig, old)
        except (ValueError, OSError):
            pass
        _old_handlers.pop(sig, None)
