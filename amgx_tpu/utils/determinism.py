"""Determinism checker: checksum checkpoints of intermediate buffers.

Reference: ``base/include/determinism_checker.h:28-52`` —
``hash_path_determinism_checker::checkpoint/checksum`` used to debug
reproducibility; pairs with the ``determinism_flag`` config (SURVEY §5.2).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple

import numpy as np


def checksum(data) -> str:
    """Stable content hash of an array (host transfer for device arrays)."""
    arr = np.asarray(data)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class DeterminismChecker:
    """Record named checkpoints; compare across runs
    (``checkpoint(name, buf)`` in the reference)."""

    def __init__(self):
        self.path: List[Tuple[str, str]] = []

    def checkpoint(self, name: str, data) -> str:
        c = checksum(data)
        self.path.append((name, c))
        return c

    def digest(self) -> str:
        h = hashlib.sha256()
        for name, c in self.path:
            h.update(name.encode())
            h.update(c.encode())
        return h.hexdigest()[:16]

    def compare(self, other: "DeterminismChecker") -> List[str]:
        """Return the names of mismatching checkpoints."""
        bad = []
        for (n1, c1), (n2, c2) in zip(self.path, other.path):
            if n1 != n2 or c1 != c2:
                bad.append(n1)
        if len(self.path) != len(other.path):
            bad.append("<path length mismatch>")
        return bad

    def reset(self):
        self.path = []


_checker = DeterminismChecker()


def determinism_checker() -> DeterminismChecker:
    return _checker


#: Tie-break seed used when ``determinism_flag`` is OFF.  The reference's
#: flag exists because GPU determinism costs extra work; here determinism
#: is free, so even the "non-deterministic" mode uses one fixed
#: per-process seed rather than consuming global numpy RNG state — results
#: never depend on what else the process computed.
SESSION_SEED = 0x5EED
