"""CPU profiler tree + phase timers.

Reference: ``base/include/amgx_timer.h`` — ``Profiler_tree`` /
``Profiler_entry`` aggregating RAII ``AMGX_CPU_PROFILER`` markers
(``amgx_timer.h:150-274``), per-level phase timers (``levelProfile``), and
the ``TimerMap``.  Here: nested context-manager markers aggregated in a
tree, plus optional forwarding to ``jax.profiler.TraceAnnotation`` so
markers show up in XLA profiles.

Every marker also doubles as a telemetry span: when the structured
telemetry layer (:mod:`amgx_tpu.telemetry`) is enabled, ``scope()``
appends typed ``span_begin``/``span_end`` records to its ring buffer —
one instrumentation point, two consumers (the in-process aggregate tree
and the exportable trace).
"""
from __future__ import annotations

import contextlib
import time
import warnings
from typing import Dict, Optional

from ..telemetry import recorder as _telemetry

_forward_to_jax = False


def enable_jax_trace_annotations(enable: bool = True):
    global _forward_to_jax
    _forward_to_jax = enable


class ProfilerEntry:
    __slots__ = ("name", "total", "count", "children", "_start")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.children: Dict[str, "ProfilerEntry"] = {}
        self._start = 0.0

    def child(self, name):
        if name not in self.children:
            self.children[name] = ProfilerEntry(name)
        return self.children[name]


class ProfilerTree:
    """Singleton-ish profiler tree (reference Profiler_tree)."""

    def __init__(self):
        self.root = ProfilerEntry("root")
        self._stack = [self.root]

    @contextlib.contextmanager
    def scope(self, name: str, _attrs: Optional[dict] = None):
        entry = self._stack[-1].child(name)
        self._stack.append(entry)
        try:
            # annotation setup BEFORE the timer starts: an import/enter
            # failure here must neither corrupt the stack depth (the
            # outer finally pops) nor charge its cost to the entry
            ann = None
            if _forward_to_jax:
                import jax
                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            try:
                sid = _telemetry.span_begin(name, _attrs) \
                    if _telemetry.is_enabled() else None
                t0 = time.perf_counter()
                try:
                    yield entry
                finally:
                    entry.total += time.perf_counter() - t0
                    entry.count += 1
                    _telemetry.span_end(sid, name)
            finally:
                if ann is not None:
                    ann.__exit__(None, None, None)
        finally:
            self._stack.pop()

    def report(self) -> str:
        lines = []

        def rec(entry, depth):
            if depth > 0:
                lines.append(f"{'  ' * depth}{entry.name:<40s} "
                             f"{entry.total:10.6f}s  x{entry.count}")
            for c in entry.children.values():
                rec(c, depth + 1)

        rec(self.root, 0)
        return "\n".join(lines)

    def reset(self):
        self.root = ProfilerEntry("root")
        self._stack = [self.root]


import threading as _threading

_tls = _threading.local()


def profiler_tree() -> ProfilerTree:
    """Per-THREAD profiler tree: markers now live in the library's hot
    paths, and concurrent solver instances (amgx_capi_multi-style
    drivers) must not interleave push/pops on one shared stack."""
    tree = getattr(_tls, "tree", None)
    if tree is None:
        tree = _tls.tree = ProfilerTree()
    return tree


def cpu_profiler(name: str):
    """RAII marker (reference AMGX_CPU_PROFILER, amgx_timer.h:269)."""
    return profiler_tree().scope(name)


#: warn-once latch for TimerMap.toc-without-tic (module-wide: the
#: mistake is a call-site bug, not per-instance state)
_TOC_WARNED = False


class TimerMap:
    """Named wall-clock timers (reference TimerMap, amgx_timer.h:435)."""

    def __init__(self):
        self._timers: Dict[str, float] = {}
        self._starts: Dict[str, float] = {}

    def tic(self, name):
        self._starts[name] = time.perf_counter()

    def toc(self, name) -> float:
        t0 = self._starts.pop(name, None)
        if t0 is None:
            # toc without tic: report 0.0 without polluting the
            # aggregate map (the old default-now() pop silently
            # recorded a ~0 entry), and warn once per process
            global _TOC_WARNED
            if not _TOC_WARNED:
                _TOC_WARNED = True
                warnings.warn(
                    f"TimerMap.toc({name!r}) called without a matching "
                    "tic(); returning 0.0", RuntimeWarning, stacklevel=2)
            return 0.0
        dt = time.perf_counter() - t0
        self._timers[name] = self._timers.get(name, 0.0) + dt
        return dt

    def get(self, name) -> float:
        return self._timers.get(name, 0.0)

    def report(self) -> str:
        return "\n".join(f"{k:<30s} {v:10.6f}s"
                         for k, v in sorted(self._timers.items()))
