"""Fault-injection harness: named failure points with triggers.

Chaos engineering for the solve stack (the reference proves its failure
paths with signal handlers and error-code plumbing, ``amg_signal.cu``;
here every failure path is *provable on demand*): a process-global plan
of named **injection points**, armed by the ``fault_inject`` config
knob (or :func:`configure` directly), each with a **count** and/or
**probability** trigger.  Every firing is recorded — a
``fault_injected`` telemetry event plus the
``amgx_fault_injected_total{point}`` counter — so a chaos run's trace
says exactly which faults were synthetic.

Injection points (wired at the existing seams):

===============  ==========================================================
``values_nan``   traced into the solve loop: NaN-poisons the iteration
                 state at iteration ``iter`` (default 1) — the
                 ``nan_poison`` taxonomy kind
``krylov_zero``  traced into the solve loop: zeroes the Krylov scalars
                 (CG's ``rho``) at iteration ``iter`` — the
                 ``krylov_breakdown`` kind.  Bites CG-family solvers
                 (their recursion carries rho); solvers that recompute
                 it each iteration (BiCGStab) are immune, and the
                 firing is only recorded when the breakdown was
                 actually provoked
``setup_error``  raises from ``Solver.setup`` (``setup_error`` kind)
``upload_error`` raises from the device pack upload (``device_error``)
``oom``          raises ``RC.NO_MEMORY`` from the pack phase
``worker_death`` raises from a worker-pool task
                 (``utils/thread_manager.py``); the pool survives and
                 in-flight serve requests fail cleanly
``aot_corrupt``  the AOT store treats the next entry as corrupt
                 (``serve/aot.py`` fallback path)
``halo_exchange`` raises from the distributed vector shard/halo seam
                 (``distributed/matrix.py``; ``device_error``)
===============  ==========================================================

Spec grammar (the ``fault_inject`` knob)::

    point[:key:val]*  [ point2[:key:val]* ...]
    # config-string-safe form (an AMGConfig entry allows exactly one
    # '=' and splits on commas, so keys pair with values by ':'
    # alternation and points separate on whitespace):
    #   "fault_inject=values_nan:iter:3:count:1 worker_death:count:2"
    # the programmatic API additionally accepts the '='/',' form:
    #   configure("values_nan:iter=3:count=1, upload_error:prob=0.5")

Triggers: ``count:N`` fires the next N times (decrementing; the
default is fire-always), ``prob:P`` fires with probability P per
opportunity (``seed`` makes it deterministic), and point-specific
params ride alongside (``iter`` for the traced points).

**Zero overhead when off**: the plan is a single module global that is
``None`` until armed — every seam's guard is one ``is None`` check, and
the traced points add nothing to the jaxpr unless armed (the solve
body consults :func:`trace_mode` at trace time).
"""
from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple

#: the known injection-point vocabulary (a typo'd spec must fail loud,
#: not silently never fire)
POINTS = ("values_nan", "krylov_zero", "setup_error", "upload_error",
          "oom", "worker_death", "aot_corrupt", "halo_exchange")

#: points whose injection is traced INTO the solve loop (mutating the
#: iteration state at a target iteration) rather than raised at a seam
TRACED_POINTS = ("values_nan", "krylov_zero")


class InjectedFault(Exception):
    """Raised by an armed seam-style injection point."""


class WorkerDeathError(InjectedFault):
    """The ``worker_death`` point's payload: a worker-pool task dying
    mid-batch (the pool must survive; in-flight requests must fail
    cleanly, not hang)."""


class _Trigger:
    __slots__ = ("point", "count", "prob", "params", "fired", "_rng")

    def __init__(self, point: str, count: Optional[int] = None,
                 prob: Optional[float] = None,
                 seed: Optional[int] = None, **params):
        self.point = point
        self.count = count          # remaining firings; None = always
        self.prob = prob
        self.params = params        # point-specific (e.g. iter=3)
        self.fired = 0
        self._rng = random.Random(seed)

    def armed(self) -> bool:
        return self.count is None or self.count > 0


_PLAN: Optional[Dict[str, _Trigger]] = None
_lock = threading.Lock()


def parse_spec(spec: str) -> Dict[str, _Trigger]:
    """Parse the ``fault_inject`` grammar into triggers; raises
    ``ValueError`` on an unknown point or malformed entry.  Params
    accept ``key:val`` alternation (the config-string-safe form — an
    AMGConfig entry allows exactly one '=' and splits on commas) and
    ``key=val``; points separate on commas or whitespace."""
    import re
    plan: Dict[str, _Trigger] = {}
    for token in re.split(r"[,\s]+", str(spec)):
        token = token.strip()
        if not token:
            continue
        parts = token.split(":")
        point = parts[0].strip()
        if point not in POINTS:
            raise ValueError(
                f"unknown fault-injection point {point!r}; known: "
                f"{POINTS}")
        kw: dict = {}
        rest = parts[1:]
        i = 0
        while i < len(rest):
            p = rest[i].strip()
            if "=" in p:
                k, v = p.split("=", 1)
                i += 1
            elif i + 1 < len(rest):
                k, v = p, rest[i + 1].strip()
                i += 2
            else:
                raise ValueError(
                    f"malformed fault-injection param {p!r} in "
                    f"{token!r} (want key:value or key=value)")
            k = k.strip()
            if k == "prob":
                kw[k] = float(v)
            else:
                kw[k] = int(float(v))
        if point in TRACED_POINTS and "prob" in kw:
            # the traced points are compiled INTO the loop — a
            # probability coin cannot gate an already-traced injection,
            # and recording would drift from execution
            raise ValueError(
                f"prob triggers are not supported for traced point "
                f"{point!r} (the injection is compiled into the solve "
                "loop); use count")
        plan[point] = _Trigger(point, **kw)
    return plan


def configure(spec: "str | dict | None"):
    """Arm the process-global plan (replacing any previous one).  An
    empty/None spec disarms — same as :func:`reset`."""
    global _PLAN, _KNOB_SPEC
    _KNOB_SPEC = None           # a programmatic (re)arm owns the plan
    if not spec:
        _PLAN = None
        return
    _PLAN = parse_spec(spec) if isinstance(spec, str) else {
        k: (v if isinstance(v, _Trigger) else _Trigger(k, **v))
        for k, v in dict(spec).items()}


#: the spec string the ``fault_inject`` KNOB last armed — knob arming
#: is idempotent per spec (see :func:`configure_knob`)
_KNOB_SPEC: Optional[str] = None


def configure_knob(spec: str):
    """The ``fault_inject`` config knob's arming path: idempotent per
    spec string.  Solvers and services are constructed freely from the
    same config (every serve session-cache miss builds one; the
    recovery ladder's conservative rung builds a twin) — re-arming on
    each construction would reset consumed counts and turn
    'fire exactly once' into fire-once-per-solver.  A CHANGED spec
    re-arms; :func:`reset`/:func:`configure` clear the memo."""
    global _KNOB_SPEC
    if not spec or spec == _KNOB_SPEC:
        return
    configure(spec)
    _KNOB_SPEC = spec


def reset():
    """Disarm every injection point (and the knob-spec memo)."""
    global _PLAN, _KNOB_SPEC
    _PLAN = None
    _KNOB_SPEC = None


def active() -> bool:
    return _PLAN is not None


def armed(point: str) -> bool:
    """Is ``point`` in the plan with firings remaining?  (Advisory —
    :func:`should_fire` makes the atomic decision.)"""
    plan = _PLAN
    if plan is None:
        return False
    t = plan.get(point)
    return t is not None and t.armed()


def _note(point: str, ctx: dict):
    """Record one firing: the schema-validated ``fault_injected`` event
    + the per-point counter.  Telemetry-off chaos runs still fire —
    recording is observability, not the trigger."""
    try:
        from ..telemetry import metrics, recorder
        if recorder.is_enabled():
            recorder.event("fault_injected", point=point,
                           **{k: v for k, v in ctx.items()
                              if v is not None})
            metrics.counter_inc("amgx_fault_injected_total",
                                point=point)
    except Exception:
        pass    # observability must never mask the injected fault


def should_fire(point: str, consume: bool = True, **ctx) -> bool:
    """Atomically evaluate ``point``'s trigger; a firing is recorded
    and (for count triggers) consumed."""
    plan = _PLAN
    if plan is None:
        return False
    t = plan.get(point)
    if t is None:
        return False
    with _lock:
        if not t.armed():
            return False
        if t.prob is not None and t._rng.random() >= t.prob:
            return False
        if consume and t.count is not None:
            t.count -= 1
        t.fired += 1
    _note(point, ctx)
    return True


def fired(point: str, **ctx) -> bool:
    """Consume + record one firing whose *decision* was made elsewhere
    (the traced solve-loop points: the jaxpr carries the injection, the
    host records it per executed solve)."""
    return should_fire(point, consume=True, **ctx)


def maybe_raise(point: str, exc: Optional[BaseException] = None):
    """Raise ``exc`` (default :class:`InjectedFault`) when ``point``
    fires; the fast path is one global ``is None`` check."""
    if _PLAN is None:
        return
    if should_fire(point):
        raise exc if exc is not None \
            else InjectedFault(f"injected fault at point {point!r}")


def param(point: str, key: str, default=None):
    plan = _PLAN
    if plan is None or point not in plan:
        return default
    return plan[point].params.get(key, default)


def trace_mode() -> Optional[Tuple[str, int]]:
    """The armed traced-solve injection as ``(mode, iteration)``, or
    None.  Consulted by the solve driver before (re)using its jitted
    body: an armed traced point is compiled INTO the loop, and its
    disarming (count exhausted) retraces clean — so the knobs-off path
    never carries injection code."""
    plan = _PLAN
    if plan is None:
        return None
    for mode in TRACED_POINTS:
        t = plan.get(mode)
        if t is not None and t.armed():
            return mode, int(t.params.get("iter", 1))
    return None


def stats() -> dict:
    """{point: {"fired": n, "remaining": count-or-None}} of the current
    plan ({} when disarmed)."""
    plan = _PLAN
    if plan is None:
        return {}
    return {p: {"fired": t.fired, "remaining": t.count}
            for p, t in plan.items()}
