"""Generic retry with jittered exponential backoff.

One policy object + one driver for every transient-failure site that
used to hand-roll its own loop: bench.py's backend-init retry (the
BENCH_r05 flaky-worker guard), AOT-store entry reads on a possibly
networked cache filesystem, and the observability endpoint's port bind.
Centralising it means every retry is bounded, jittered (no synchronized
thundering herds from N lanes retrying in lockstep) and counted
(``amgx_retries_total{label}``).

The **retryable predicate** is the contract: only failures the caller
recognises as transient burn an attempt — anything else re-raises
immediately, exactly like an unguarded call.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional


@dataclasses.dataclass
class RetryPolicy:
    """Bounded, jittered exponential backoff.

    ``max_attempts`` counts the FIRST call too (1 = no retry); delay
    before attempt k (k >= 2) is
    ``min(base_delay_s * multiplier**(k-2), max_delay_s)`` scaled by a
    uniform ``[1-jitter, 1+jitter]`` factor."""

    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.1
    #: exception filter: True = transient, retry; False = re-raise now
    retryable: Callable[[BaseException], bool] = \
        lambda exc: isinstance(exc, OSError)

    def delay_s(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Backoff before attempt ``attempt`` (2-based; attempt 1 never
        waits)."""
        base = min(self.base_delay_s
                   * self.multiplier ** max(attempt - 2, 0),
                   self.max_delay_s)
        if self.jitter <= 0:
            return base
        r = (rng or random).uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return base * r


def retry_call(fn: Callable, *, policy: Optional[RetryPolicy] = None,
               max_attempts: Optional[int] = None,
               base_delay_s: Optional[float] = None,
               retryable: Optional[Callable[[BaseException], bool]] = None,
               on_retry: Optional[Callable[[BaseException, int], None]]
               = None,
               label: str = "",
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None):
    """Call ``fn()`` under ``policy``; returns its result.

    A non-retryable failure (or the last attempt's) re-raises the
    original exception.  ``on_retry(exc, next_attempt)`` fires before
    each backoff sleep — the caller's logging hook.  Each retry counts
    into ``amgx_retries_total{label}`` when telemetry is enabled."""
    pol = policy or RetryPolicy()
    if max_attempts is not None:
        pol = dataclasses.replace(pol, max_attempts=int(max_attempts))
    if base_delay_s is not None:
        pol = dataclasses.replace(pol, base_delay_s=float(base_delay_s))
    if retryable is not None:
        pol = dataclasses.replace(pol, retryable=retryable)
    attempts = max(1, int(pol.max_attempts))
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — predicate-filtered
            if attempt >= attempts or not pol.retryable(exc):
                raise
            try:
                from ..telemetry import metrics, recorder
                if recorder.is_enabled():
                    metrics.counter_inc("amgx_retries_total",
                                        label=label or "unlabeled")
            except Exception:
                pass    # observability must never mask the retry
            if on_retry is not None:
                on_retry(exc, attempt + 1)
            sleep(pol.delay_s(attempt + 1, rng))
    raise AssertionError("unreachable")  # pragma: no cover
