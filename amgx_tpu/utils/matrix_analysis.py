"""Matrix diagnostics.

Reference: ``core/src/matrix_analysis.cu`` (~700 LoC) — structural and
spectral analysis used for debugging solver behaviour.
"""
from __future__ import annotations

from typing import Dict

import numpy as np
import scipy.sparse as sp


def analyze_matrix(A) -> Dict:
    """Structure + conditioning diagnostics of a (scalar view of a)
    sparse matrix."""
    csr = sp.csr_matrix(A)
    n = csr.shape[0]
    deg = np.diff(csr.indptr)
    diag = csr.diagonal()
    absrow = np.asarray(abs(csr).sum(axis=1)).ravel()
    offsum = absrow - np.abs(diag)
    dd = np.abs(diag) - offsum            # diagonal dominance margin
    sym_err = 0.0
    if csr.shape[0] == csr.shape[1]:
        d = (csr - csr.T).tocsr()
        sym_err = float(np.abs(d.data).max()) if d.nnz else 0.0
    rowsum = np.asarray(csr.sum(axis=1)).ravel()
    out = {
        "n_rows": int(n),
        "n_cols": int(csr.shape[1]),
        "nnz": int(csr.nnz),
        "avg_nnz_per_row": float(deg.mean()) if n else 0.0,
        "max_nnz_per_row": int(deg.max()) if n else 0,
        "empty_rows": int((deg == 0).sum()),
        "zero_diagonal_entries": int((diag == 0).sum()),
        "diag_dominant_rows_frac": float((dd >= 0).mean()) if n else 0.0,
        "structurally_symmetric": _struct_symmetric(csr),
        "symmetry_error_max": sym_err,
        "zero_row_sum_rows": int((np.abs(rowsum) < 1e-14).sum()),
        "norm_inf": float(absrow.max()) if n else 0.0,
        "bandwidth": _bandwidth(csr),
    }
    return out


def _struct_symmetric(csr: sp.csr_matrix) -> bool:
    if csr.shape[0] != csr.shape[1]:
        return False
    pat = sp.csr_matrix((np.ones(len(csr.data), dtype=np.int8),
                         csr.indices.copy(), csr.indptr.copy()),
                        shape=csr.shape)
    return (pat != pat.T).nnz == 0


def _bandwidth(csr: sp.csr_matrix) -> int:
    if csr.nnz == 0:
        return 0
    rows = np.repeat(np.arange(csr.shape[0]), np.diff(csr.indptr))
    return int(np.abs(rows - csr.indices).max())


def estimate_spectral_bounds(A, n_iters: int = 30) -> Dict:
    """λmax estimate (power iteration) + Gershgorin bounds."""
    csr = sp.csr_matrix(A).astype(np.float64)
    n = csr.shape[0]
    x = np.random.default_rng(0).standard_normal(n)
    lam = 0.0
    for _ in range(n_iters):
        y = csr @ x
        nrm = np.linalg.norm(y)
        if nrm == 0:
            break
        lam = x @ y / (x @ x)
        x = y / nrm
    diag = csr.diagonal()
    absrow = np.asarray(abs(csr).sum(axis=1)).ravel()
    r = absrow - np.abs(diag)
    return {
        "lambda_max_estimate": float(lam),
        "gershgorin_upper": float((diag + r).max()),
        "gershgorin_lower": float((diag - r).min()),
    }
