"""Filter known-benign XLA noise off stderr, keeping a raw sidecar.

The multichip dryrun forces the CPU platform in a fresh process, and
the persistent compile cache then replays CPU-AOT executables compiled
on a machine with a different feature set.  XLA's ``cpu_aot_loader``
logs every mismatch as a multi-kilobyte host-feature dump straight to
fd 2 — the captured ``tail`` in ``MULTICHIP_r*.json`` drowned in it,
so a REAL failure (an assert, a traceback) was unreadable.

These warnings come from C++ (absl/tsl logging), so a ``sys.stderr``
wrapper never sees them: :func:`install_stderr_filter` splices a pipe
onto fd 2 and a reader thread routes each line — known-benign XLA noise
goes to a raw sidecar file (nothing is thrown away), everything else
passes through to the original stderr unchanged.  At exit, one short
summary line says how many lines were filtered and where they live.

Scope: installed explicitly by entry points that need a readable tail
(``__graft_entry__.dryrun_multichip``); never at library import.
"""
from __future__ import annotations

import atexit
import os
import sys
import tempfile
import threading
from typing import Optional

#: a line containing ANY of these is known-benign XLA CPU-AOT noise
BENIGN_PATTERNS = (
    b"cpu_aot_loader.cc",
    b"Machine type used for XLA:CPU compilation",
    b"could lead to execution errors such as SIGILL",
    b"is not  supported on the host machine",
    b"vs host machine features:",
)

_installed = False


def is_benign(line: bytes) -> bool:
    return any(p in line for p in BENIGN_PATTERNS)


def install_stderr_filter(sidecar_path: Optional[str] = None
                          ) -> Optional[str]:
    """Splice the fd-level filter onto stderr (idempotent).

    ``sidecar_path``: where filtered lines are kept raw; default
    ``$AMGX_XLA_NOISE_SIDECAR`` or ``<tmpdir>/amgx_xla_noise_<pid>.log``.
    Returns the sidecar path (None when already installed).
    """
    global _installed
    if _installed:
        return None
    sidecar_path = sidecar_path or os.environ.get(
        "AMGX_XLA_NOISE_SIDECAR") or os.path.join(
        tempfile.gettempdir(), f"amgx_xla_noise_{os.getpid()}.log")
    try:
        orig_fd = os.dup(2)
        rd, wr = os.pipe()
        os.dup2(wr, 2)
        os.close(wr)
    except OSError:
        return None             # exotic fd setup: leave stderr alone
    _installed = True
    sys.stderr.flush()
    state = {"filtered": 0}

    def pump():
        sidecar = None
        buf = b""
        while True:
            try:
                chunk = os.read(rd, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            *lines, buf = buf.split(b"\n")
            for line in lines:
                if is_benign(line):
                    state["filtered"] += 1
                    if sidecar is None:
                        sidecar = open(sidecar_path, "ab")
                    sidecar.write(line + b"\n")
                    sidecar.flush()
                else:
                    os.write(orig_fd, line + b"\n")
        if buf:
            os.write(orig_fd, buf)
        if sidecar is not None:
            sidecar.close()

    pump_thread = threading.Thread(target=pump, daemon=True,
                                   name="amgx-xla-noise-filter")
    pump_thread.start()

    def restore_and_summarize():
        # restore the real stderr FIRST (a crash traceback written
        # between here and process death must not land in a pipe nobody
        # reads), then close the pipe's write end so the pump sees EOF
        # and drains whatever is still buffered — without this, bytes
        # written just before exit (exactly the failure case this
        # module must keep readable) die with the daemon thread
        sys.stderr.flush()
        try:
            os.dup2(orig_fd, 2)     # also drops the pipe write end
        except OSError:
            pass
        pump_thread.join(timeout=2.0)
        if state["filtered"]:
            # one short, honest line in the real stream: noise was
            # filtered, not lost — the raw sidecar has every byte
            os.write(orig_fd,
                     (f"[xla-noise] {state['filtered']} benign XLA "
                      f"CPU-AOT warning lines filtered -> "
                      f"{sidecar_path}\n").encode())

    atexit.register(restore_and_summarize)
    return sidecar_path
