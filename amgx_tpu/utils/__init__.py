from .logging import (amgx_output, error_output, amgx_distributed_output,
                      register_print_callback, set_verbosity,
                      get_verbosity)
from .profiler import cpu_profiler, profiler_tree, TimerMap
from .determinism import checksum, determinism_checker, DeterminismChecker
from .memory import memory_info, MemoryInfo
from .matrix_analysis import analyze_matrix, estimate_spectral_bounds
from .retry import RetryPolicy, retry_call
from . import faultinject

__all__ = ["amgx_output", "error_output", "amgx_distributed_output",
           "register_print_callback", "set_verbosity", "get_verbosity",
           "cpu_profiler", "profiler_tree", "TimerMap",
           "checksum", "determinism_checker", "DeterminismChecker",
           "memory_info", "MemoryInfo",
           "analyze_matrix", "estimate_spectral_bounds",
           "RetryPolicy", "retry_call", "faultinject"]
