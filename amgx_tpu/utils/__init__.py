from .logging import (amgx_output, error_output, amgx_distributed_output,
                      register_print_callback, set_verbosity)

__all__ = ["amgx_output", "error_output", "amgx_distributed_output",
           "register_print_callback", "set_verbosity"]
