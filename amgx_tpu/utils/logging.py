"""Print-callback logging.

The reference routes all library output through a host-app-registered
callback (``AMGX_register_print_callback``, ``amgx_c.h:212``;
``amgx_output`` / ``error_output`` / ``amgx_distributed_output``,
``base/include/misc.h:33-36``).  Same indirection here.

Output is level-gated: each message declares a verbosity ``level``
(1 = essential solver output, 2 = informational tables such as grid
stats, 3 = chatty diagnostics) and is emitted only when the configured
``_verbosity`` is at least that level — previously any nonzero
verbosity printed everything.  ``error_output`` is never gated.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

_print_callback: Optional[Callable[[str], None]] = None
_verbosity = 3


def register_print_callback(fn: Optional[Callable[[str], None]]):
    global _print_callback
    _print_callback = fn


def set_verbosity(level: int):
    global _verbosity
    _verbosity = int(level)


def get_verbosity() -> int:
    return _verbosity


def amgx_output(msg: str, level: int = 1):
    """Emit ``msg`` through the registered callback (or stdout) when the
    configured verbosity is at least ``level``."""
    if _verbosity <= 0 or _verbosity < int(level):
        return
    if _print_callback is not None:
        _print_callback(msg)
    else:
        sys.stdout.write(msg)


def error_output(msg: str):
    if _print_callback is not None:
        _print_callback(msg)
    else:
        sys.stderr.write(msg)


def amgx_distributed_output(msg: str, rank: int = 0, level: int = 1):
    """Only rank 0 prints (reference amgx_distributed_output)."""
    if rank == 0:
        amgx_output(msg, level=level)
