"""Print-callback logging.

The reference routes all library output through a host-app-registered
callback (``AMGX_register_print_callback``, ``amgx_c.h:212``;
``amgx_output`` / ``error_output`` / ``amgx_distributed_output``,
``base/include/misc.h:33-36``).  Same indirection here.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

_print_callback: Optional[Callable[[str], None]] = None
_verbosity = 3


def register_print_callback(fn: Optional[Callable[[str], None]]):
    global _print_callback
    _print_callback = fn


def set_verbosity(level: int):
    global _verbosity
    _verbosity = int(level)


def amgx_output(msg: str):
    if _verbosity <= 0:
        return
    if _print_callback is not None:
        _print_callback(msg)
    else:
        sys.stdout.write(msg)


def error_output(msg: str):
    if _print_callback is not None:
        _print_callback(msg)
    else:
        sys.stderr.write(msg)


def amgx_distributed_output(msg: str, rank: int = 0):
    """Only rank 0 prints (reference amgx_distributed_output)."""
    if rank == 0:
        amgx_output(msg)
