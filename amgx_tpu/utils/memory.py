"""Memory observability.

Reference: ``base/include/memory_info.h`` — ``MemoryInfo`` max-usage
tracking reported in the grid-stats table (used at ``amg.cu:1138``).
Here: live device-buffer accounting via ``jax.live_arrays`` plus
backend memory stats where the platform exposes them.
"""
from __future__ import annotations

from typing import Dict


class MemoryInfo:
    def __init__(self):
        self.max_bytes = 0

    def current_device_bytes(self) -> int:
        import jax

        total = 0
        for a in jax.live_arrays():
            try:
                total += a.nbytes
            except Exception:
                pass
        return total

    def update_max_memory_usage(self) -> int:
        """Reference ``MemoryInfo::updateMaxMemoryUsage``."""
        cur = self.current_device_bytes()
        self.max_bytes = max(self.max_bytes, cur)
        return self.max_bytes

    def backend_stats(self) -> Dict:
        import jax

        try:
            return dict(jax.devices()[0].memory_stats() or {})
        except Exception:
            return {}

    def report(self) -> str:
        self.update_max_memory_usage()
        gb = self.max_bytes / (1 << 30)
        return f"Maximum Memory Usage: {gb:8.3g} GB"


_info = MemoryInfo()


def memory_info() -> MemoryInfo:
    return _info


def device_tree_bytes(tree) -> int:
    """Total device bytes of a pytree's array leaves — the per-session
    accounting unit of the serving setup cache (serve/cache.py): one
    prepared solver's bindings pytree is exactly its resident hierarchy
    + smoother data, so summing leaf ``nbytes`` prices a cache entry
    without touching backend allocator stats.

    Leaves are deduplicated by buffer identity: shallow views
    (``precision_view`` / ``placement_view`` / lane replicas) share the
    same device arrays, and a shared buffer costs its bytes once — a
    double count here makes cache budgets over-evict."""
    import jax

    total = 0
    seen = set()
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None:
            continue
        key = id(leaf)
        if key in seen:
            continue
        seen.add(key)
        try:
            total += int(nb)
        except Exception:
            pass
    return total
