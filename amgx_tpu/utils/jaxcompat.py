"""Small shims over JAX private APIs.

``trace_state_clean`` guards the lazy device-pack caches: a value
produced while a trace is active is a tracer and must never be cached
past the trace.  The symbol is private (``jax._src.core``); if a JAX
upgrade moves it, the fallback conservatively reports "tracing", which
disables caching in the lazy properties — correctness is preserved
because the Matrix handles cache their packs themselves and the binding
machinery swaps tracers into those slots.
"""
from __future__ import annotations

try:
    from jax._src.core import trace_state_clean
except ImportError:      # pragma: no cover - depends on the jax version
    def trace_state_clean() -> bool:
        return False
