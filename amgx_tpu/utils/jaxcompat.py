"""Small shims over JAX private APIs.

``trace_state_clean`` guards the lazy device-pack caches: a value
produced while a trace is active is a tracer and must never be cached
past the trace.  The symbol is private (``jax._src.core``); if a JAX
upgrade moves it, the fallback conservatively reports "tracing", which
disables caching in the lazy properties — correctness is preserved
because the Matrix handles cache their packs themselves and the binding
machinery swaps tracers into those slots.

``install_compile_counter`` hooks ``jax.monitoring`` duration events to
count jit cache misses for the telemetry registry: a retrace fires
``.../jaxpr_trace_duration`` (python-cache miss), an actual XLA backend
compile fires ``.../backend_compile_duration``.  NOTE (measured on the
pinned jax): the backend-compile duration event wraps
``compile_or_get_cached`` and therefore fires on persistent-compile-
cache HITS too — the cache-event listener below flags those per thread
so a warm-cache executable load is counted as
``amgx_compile_cache_hits_total``, NOT as an ``amgx_jit_compile_total``
recompile (which is the operational meaning callers assert on, e.g. the
cross-process zero-recompile test).  The listeners are process-wide and
permanent — JAX has no unregister — and cost one dict update when
telemetry is disabled.

``enable_compilation_cache`` / ``serialize_compiled`` /
``deserialize_compiled`` are the warm-start primitives: the first wires
JAX's persistent compilation cache to a directory (the
``compile_cache_dir`` config knob), the other two wrap
``jax.experimental.serialize_executable`` for the explicit AOT
executable store (:mod:`amgx_tpu.serve.aot`).
"""
from __future__ import annotations

import pickle
import threading

try:
    from jax._src.core import trace_state_clean
except ImportError:      # pragma: no cover - depends on the jax version
    def trace_state_clean() -> bool:
        return False


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax releases: the public symbol (with
    its ``check_vma`` knob) when present, else the older
    ``jax.experimental.shard_map.shard_map`` (whose equivalent knob is
    ``check_rep``; disabled — the callers that need the escape hatch
    wrap pallas_calls whose out_shapes carry no rep/vma annotation).
    The distributed tier (sharded SpMV, halo exchange, slab smoothers)
    routes every shard_map through here so one jax upgrade or downgrade
    never strands the whole tier."""
    import jax
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs,
                                 check_vma=check_vma)
        except TypeError:      # releases where the knob is check_rep
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def axis_type_auto():
    """``jax.sharding.AxisType.Auto`` on modern jax; None on releases
    without sharding-in-types (their meshes are implicitly GSPMD/auto,
    which is exactly the mode the distributed layer wants)."""
    import jax
    at = getattr(jax.sharding, "AxisType", None)
    return None if at is None else at.Auto


_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_compile_listener_installed = False

#: ungated process totals of persistent-compile-cache traffic — the
#: cross-process warm-start probes (bench cold/warm child, tier-1 test)
#: read these without having to enable the telemetry recorder first
_cc_stats = {"hits": 0, "misses": 0}
_cc_lock = threading.Lock()
_cc_tls = threading.local()


def compile_cache_stats() -> dict:
    """Process totals of persistent-compile-cache hits/misses (counted
    since :func:`install_compile_counter`; independent of telemetry)."""
    with _cc_lock:
        return dict(_cc_stats)


def thread_cache_hits() -> int:
    """Persistent-cache hits observed on THIS thread (monotonic) —
    compile events fire on the compiling thread, so a delta across a
    ``lower().compile()`` call answers "was MY compile served from the
    cache" immune to concurrent compiles on other threads."""
    return getattr(_cc_tls, "hits_seen", 0)


def install_compile_counter() -> bool:
    """Register the jit cache-miss + persistent-cache listeners
    (idempotent); returns True when listeners are in place.  Counts land
    in ``amgx_jit_trace_total`` / ``amgx_jit_compile_total`` /
    ``amgx_compile_cache_{hits,misses}_total`` and compile durations in
    the ``amgx_jit_compile_seconds`` histogram."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True

    def _on_event(event, **kwargs):
        try:
            if event == _CACHE_HIT_EVENT:
                with _cc_lock:
                    _cc_stats["hits"] += 1
                # flag the thread: the backend-compile duration event
                # that follows this hit is an executable LOAD, not a
                # compile (see module docstring)
                _cc_tls.hit = True
                # never-consumed per-thread total: lets a caller detect
                # post hoc that a compile IT ran was served from the
                # cache (thread_cache_hits; the flag above is consumed
                # by the duration listener)
                _cc_tls.hits_seen = getattr(_cc_tls, "hits_seen", 0) + 1
            elif event == _CACHE_MISS_EVENT:
                with _cc_lock:
                    _cc_stats["misses"] += 1
            else:
                return
            from ..telemetry import metrics, recorder
            if not recorder.is_enabled():
                return
            name = ("amgx_compile_cache_hits_total"
                    if event == _CACHE_HIT_EVENT
                    else "amgx_compile_cache_misses_total")
            metrics.counter_inc(name, layer="xla")
        except Exception:   # a metrics bug must never break compilation
            pass

    def _on_duration(event, duration, **kwargs):
        try:
            cache_hit = False
            if event == _COMPILE_EVENT:
                cache_hit = getattr(_cc_tls, "hit", False)
                _cc_tls.hit = False
            from ..telemetry import metrics, recorder
            if not recorder.is_enabled():
                return
            if event == _TRACE_EVENT:
                metrics.counter_inc("amgx_jit_trace_total")
            elif event == _COMPILE_EVENT:
                if not cache_hit:
                    metrics.counter_inc("amgx_jit_compile_total")
                    metrics.hist_observe("amgx_jit_compile_seconds",
                                         float(duration))
            else:
                return
            # setup attribution (telemetry/setup_profile.py): the
            # duration lands on the innermost open setup phase of the
            # firing thread — compiles run synchronously on the thread
            # that triggered them, so this answers "which setup phase
            # paid that compile" exactly.  A cache-hit load still
            # forwards (it is wall time the phase spent in the compile
            # pipeline), it just isn't a recompile.
            from ..telemetry import setup_profile
            setup_profile.note_duration(event == _COMPILE_EVENT,
                                        float(duration))
        except Exception:   # a metrics bug must never break compilation
            pass

    try:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        jax.monitoring.register_event_listener(_on_event)
    except Exception:    # pragma: no cover - depends on the jax version
        return False
    _compile_listener_installed = True
    return True


# ------------------------------------------------------ warm-start layer
def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` (the
    ``compile_cache_dir`` config knob; an explicit knob overrides the
    import-time env default).  Every jit in the stack becomes
    disk-backed: a fresh process re-loads executables instead of
    recompiling them.  Returns True when the cache is (now) active.

    Size/time floors are zeroed — AMG setup compiles many small-but-
    numerous executables whose aggregate, not individual, cost is the
    cold-start problem.  Safe to call after compiles already ran: the
    initialized-once cache singleton is reset so the new directory takes
    effect."""
    if not cache_dir:
        return False
    import jax
    changed = jax.config.jax_compilation_cache_dir != cache_dir
    if changed:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    # guarded: this runs in every (nested) solver construction and
    # jax.config.update is not free
    if jax.config.jax_persistent_cache_min_compile_time_secs != 0.0:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    if jax.config.jax_persistent_cache_min_entry_size_bytes != 0:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if changed:
        try:    # private, version-dependent: the dir still applies to
                # future cache initialization if this shim ever breaks
            from jax._src import compilation_cache as _cc
            if _cc.is_initialized():
                _cc.reset_cache()
        except Exception:   # pragma: no cover
            pass
    install_compile_counter()
    return True


def backend_fingerprint() -> str:
    """Identity of the executable-compatibility domain: platform +
    device kind + device count.  Part of every AOT-store key — an
    executable serialized for one domain never deserializes into
    another (jax/jaxlib VERSIONS are deliberately meta-checked instead
    of key-mixed, so an upgrade surfaces as a loud
    ``compile_cache_fallback`` rather than a silent miss)."""
    import jax
    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "?"
        return f"{jax.default_backend()}:{kind}:{len(devs)}"
    except Exception:       # pragma: no cover - backend init failure
        return "unknown"


def runtime_versions() -> dict:
    """The version tuple an AOT entry was built under (checked at load;
    a mismatch falls back to a normal compile)."""
    import jax
    try:
        import jaxlib
        jl = getattr(jaxlib, "__version__", "?")
    except Exception:       # pragma: no cover
        jl = "?"
    return {"jax": jax.__version__, "jaxlib": jl}


def aval_signature(args) -> str:
    """Stable digest input of an argument pytree's shapes/dtypes +
    structure — what decides whether one compiled executable can serve a
    call (all values ride as arguments in this codebase, so the aval
    signature IS the executable's shape identity)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = [str(treedef)]
    for l in leaves:
        dt = getattr(l, "dtype", None)
        sh = getattr(l, "shape", None)
        if dt is None or sh is None:
            import numpy as np
            a = np.asarray(l)
            dt, sh = a.dtype, a.shape
        parts.append(f"{dt}{tuple(sh)}")
    return ";".join(parts)


def compile_uncached(jit_fn, args):
    """``jit_fn.lower(*args).compile()`` with the XLA persistent cache
    scoped OFF — producing an executable that is safe to serialize.

    Why: on XLA CPU (measured on the pinned jax), serializing an
    executable that was itself LOADED from the persistent compilation
    cache yields a blob whose JIT-registered kernel symbols are missing
    — every later ``deserialize_executable`` fails with "Symbols not
    found", in any process.  An AOT-store entry must therefore come
    from a genuine compile; the one-time extra compile (only when the
    XLA cache is warm but the AOT store is cold) buys a permanently
    loadable entry.  The config scope is thread-local, so concurrent
    compiles on other threads keep their caching."""
    try:
        from jax._src import compilation_cache as _cc
        from jax._src.config import enable_compilation_cache
    except ImportError:      # pragma: no cover - jax version dependent
        return jit_fn.lower(*args).compile()
    # one uncached compile at a time: the reset/compile/reset dance
    # manipulates jax's process-global check-once singleton, so two
    # concurrent AOT compiles would race each other's resets.  A jit
    # on an UNRELATED thread can still flip the global verdict back to
    # cached mid-compile — callers detect that case with a
    # thread_cache_hits() delta and skip persisting (serve/aot.py).
    with _UNCACHED_LOCK:
        try:
            with enable_compilation_cache(False):
                # the used-or-not verdict is a check-once singleton:
                # once any compile ran with the cache on, the scoped
                # disable above is ignored — reset forces a re-check,
                # which sees the disabled scope and compiles for real
                _cc.reset_cache()
                return jit_fn.lower(*args).compile()
        finally:
            # ...and a second reset lets the NEXT normal compile
            # re-enable caching (the verdict would otherwise stick at
            # False)
            _cc.reset_cache()


_UNCACHED_LOCK = threading.Lock()


def serialize_compiled(compiled) -> bytes:
    """One self-contained blob for a ``jax.stages.Compiled`` —
    (payload, in_tree, out_tree) pickled together (PyTreeDefs pickle on
    the pinned jax; the payload is XLA's own serialized executable)."""
    from jax.experimental.serialize_executable import serialize
    payload, in_tree, out_tree = serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes):
    """Rehydrate a :func:`serialize_compiled` blob into a callable
    executable bound to the CURRENT backend.  Raises on any
    incompatibility — callers treat that as a cache fallback."""
    from jax.experimental.serialize_executable import \
        deserialize_and_load
    payload, in_tree, out_tree = pickle.loads(blob)
    return deserialize_and_load(payload, in_tree, out_tree)
