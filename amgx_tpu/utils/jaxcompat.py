"""Small shims over JAX private APIs.

``trace_state_clean`` guards the lazy device-pack caches: a value
produced while a trace is active is a tracer and must never be cached
past the trace.  The symbol is private (``jax._src.core``); if a JAX
upgrade moves it, the fallback conservatively reports "tracing", which
disables caching in the lazy properties — correctness is preserved
because the Matrix handles cache their packs themselves and the binding
machinery swaps tracers into those slots.

``install_compile_counter`` hooks ``jax.monitoring`` duration events to
count jit cache misses for the telemetry registry: a retrace fires
``.../jaxpr_trace_duration`` (python-cache miss), an actual XLA backend
compile fires ``.../backend_compile_duration`` (persistent-compile-
cache hits do NOT fire it, matching what "recompile" means
operationally).  The listener is process-wide and permanent — JAX has
no unregister — so it is a no-op unless telemetry is enabled.
"""
from __future__ import annotations

try:
    from jax._src.core import trace_state_clean
except ImportError:      # pragma: no cover - depends on the jax version
    def trace_state_clean() -> bool:
        return False


_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_compile_listener_installed = False


def install_compile_counter() -> bool:
    """Register the jit cache-miss listener (idempotent); returns True
    when a listener is in place.  Counts land in
    ``amgx_jit_trace_total`` / ``amgx_jit_compile_total`` and compile
    durations in the ``amgx_jit_compile_seconds`` histogram."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True

    def _on_duration(event, duration, **kwargs):
        try:
            from ..telemetry import metrics, recorder
            if not recorder.is_enabled():
                return
            if event == _TRACE_EVENT:
                metrics.counter_inc("amgx_jit_trace_total")
            elif event == _COMPILE_EVENT:
                metrics.counter_inc("amgx_jit_compile_total")
                metrics.hist_observe("amgx_jit_compile_seconds",
                                     float(duration))
            else:
                return
            # setup attribution (telemetry/setup_profile.py): the
            # duration lands on the innermost open setup phase of the
            # firing thread — compiles run synchronously on the thread
            # that triggered them, so this answers "which setup phase
            # paid that compile" exactly
            from ..telemetry import setup_profile
            setup_profile.note_duration(event == _COMPILE_EVENT,
                                        float(duration))
        except Exception:   # a metrics bug must never break compilation
            pass

    try:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:    # pragma: no cover - depends on the jax version
        return False
    _compile_listener_installed = True
    return True
