from .matrix_market import read_matrix_market, write_matrix_market, SystemData
from .binary import read_binary, write_binary, read_system_auto
from .poisson import (poisson5pt, poisson7pt, poisson7pt_dia, poisson9pt,
                      poisson27pt, generate_distributed_poisson_7pt)
from .device_gen import poisson7pt_device
from .gauntlet import gauntlet_cases

__all__ = ["read_matrix_market", "write_matrix_market", "SystemData",
           "read_binary", "write_binary", "read_system_auto",
           "poisson5pt", "poisson7pt", "poisson7pt_dia", "poisson9pt",
           "poisson27pt", "generate_distributed_poisson_7pt",
           "poisson7pt_device", "gauntlet_cases"]
