"""The real-matrix gauntlet: block-structured benchmark systems.

The headline benchmarks are scalar Poisson — exactly the matrices the
structured DIA path eats.  AmgX's performance claims come from block
CSR on the workloads the paper targets (PAPER.md L4/L7): elasticity and
CFD systems with b = 3–5 coupled unknowns per mesh point, nonsymmetric
convection, anisotropy, and jumping coefficients.  This module builds
SuiteSparse-STYLE synthetic systems of each class — deterministic,
size-parameterised, and small enough to regenerate per run — and
``bench.py`` / ``scripts/prim_bench.py block`` route every one through
the MatrixMarket writer + the ``block_dim`` re-blocking reader
(io/matrix_market.py), so the measured operator took the full upload
path a user's matrix takes.

Every case records a solver config matched to its structure (SPD cases
ride PCG + aggregation AMG, nonsymmetric ones BiCGStab + multicolor
DILU — the BASELINE config-4 class), so bench's gauntlet block reports
a CONVERGENCE number (iterations) next to the throughput numbers
(achieved GB/s, GFLOP/s) for each block case, not just scalar Poisson.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import numpy as np
import scipy.sparse as sp

from .poisson import poisson5pt, poisson7pt


def _conv_diff_2d(n: int, cx: float = 0.6, cy: float = 0.3
                  ) -> sp.csr_matrix:
    """2D convection–diffusion, first-order upwind convection: the
    standard nonsymmetric test operator (diagonally dominant, so the
    block couplings below cannot break solvability)."""
    L = poisson5pt(n, n)
    e = np.ones(n)
    d1 = sp.diags([e, -e], [0, -1], shape=(n, n))
    conv = (cx * sp.kron(sp.identity(n), d1)
            + cy * sp.kron(d1, sp.identity(n)))
    return sp.csr_matrix(L + conv)


def _aniso_2d(n: int, eps: float = 1e-2) -> sp.csr_matrix:
    """Anisotropic 2D Laplacian: strong x-coupling, eps-weak y."""
    Ix, Iy = sp.identity(n), sp.identity(n)
    d = sp.diags([2 * np.ones(n), -np.ones(n - 1), -np.ones(n - 1)],
                 [0, -1, 1])
    return sp.csr_matrix(sp.kron(Iy, d) + eps * sp.kron(d, Ix))


def _jump_2d(n: int, jump: float = 1e3) -> sp.csr_matrix:
    """2D diffusion with a coefficient jump: k = 1 except ``jump`` in
    the lower-left quadrant, assembled as Gᵀ·diag(k_edge)·G with
    harmonic-mean edge coefficients (SPD by construction) plus a small
    mass shift so quadrant-boundary rows stay nonsingular."""
    k = np.ones((n, n))
    k[: n // 2, : n // 2] = jump
    kf = k.ravel()

    def grad_1d(m):
        return sp.diags([-np.ones(m - 1), np.ones(m - 1)], [0, 1],
                        shape=(m - 1, m))

    Gx = sp.kron(sp.identity(n), grad_1d(n))   # x-edges
    Gy = sp.kron(grad_1d(n), sp.identity(n))   # y-edges
    idx = np.arange(n * n).reshape(n, n)
    ex = 2.0 / (1.0 / kf[idx[:, :-1].ravel()]
                + 1.0 / kf[idx[:, 1:].ravel()])
    ey = 2.0 / (1.0 / kf[idx[:-1, :].ravel()]
                + 1.0 / kf[idx[1:, :].ravel()])
    A = (Gx.T @ sp.diags(ex) @ Gx) + (Gy.T @ sp.diags(ey) @ Gy)
    return sp.csr_matrix(A + 1e-3 * sp.identity(n * n))


def scattered_block_operator(nb: int = 12288, b: int = 4,
                             density: float = 0.0008,
                             seed: int = 15) -> sp.bsr_matrix:
    """THE block SpMV A/B operator: a diagonally-shifted scattered
    b×b block matrix past every structured gate, shared by
    ``bench.py``'s ``block_kernels`` block and ``scripts/prim_bench.py
    block`` so the perf_gate-pinned ``block_spmv_speedup`` contract is
    measured on exactly the operator developers tune against."""
    rng = np.random.default_rng(seed)
    base = (sp.random(nb, nb, density=density, random_state=seed,
                      format="csr")
            + sp.diags(rng.uniform(3.0, 4.0, nb))).tocsr()
    data = rng.standard_normal((base.nnz, b, b))
    return sp.bsr_matrix((data, base.indices, base.indptr),
                         shape=(nb * b, nb * b))


def _spd_block(b: int, coupling: float = 0.3) -> np.ndarray:
    """A fixed SPD b×b stiffness block: I + coupling·(rank-one)."""
    v = np.linspace(1.0, 2.0, b)
    return np.eye(b) * (1.0 + np.arange(b) * 0.25) \
        + coupling * np.outer(v, v) / b


def _nonsym_block(b: int, g: float = 0.15) -> np.ndarray:
    """A fixed nonsymmetric b×b coupling block (velocity–pressure-ish
    off-diagonal skew), small enough to keep diagonal dominance."""
    B = np.zeros((b, b))
    B[:-1, -1] = g
    B[-1, :-1] = -g
    B[-1, -1] = 2 * g
    return B


def elasticity3(n_side: int = 12) -> Tuple[sp.bsr_matrix, int]:
    """b=3 elasticity-like system: 3D 7-pt Laplacian ⊗ SPD 3×3
    stiffness (the vector-Laplacian skeleton of linear elasticity on a
    structured mesh).  SPD."""
    L = poisson7pt(n_side, n_side, n_side)
    A = sp.kron(L, _spd_block(3), format="bsr")
    return sp.bsr_matrix(A, blocksize=(3, 3)), 3


def cfd4(n_side: int = 24) -> Tuple[sp.bsr_matrix, int]:
    """b=4 CFD-like system: nonsymmetric convection–diffusion ⊗ I₄
    plus a per-point nonsymmetric 4×4 coupling (3 velocity components
    + pressure)."""
    D = _conv_diff_2d(n_side)
    n = D.shape[0]
    A = sp.kron(D, sp.identity(4)) \
        + sp.kron(sp.identity(n), _nonsym_block(4))
    return sp.bsr_matrix(A, blocksize=(4, 4)), 4


def species5(n_side: int = 20) -> Tuple[sp.bsr_matrix, int]:
    """b=5 reaction–diffusion system: 2D Laplacian ⊗ diag diffusivities
    plus a nonsymmetric reaction coupling block per point."""
    L = poisson5pt(n_side, n_side)
    n = L.shape[0]
    diff = np.diag(np.linspace(1.0, 3.0, 5))
    R = _nonsym_block(5, g=0.2) + 0.1 * np.eye(5)
    A = sp.kron(L, diff) + sp.kron(sp.identity(n), R + R.T * 0.25)
    return sp.bsr_matrix(A, blocksize=(5, 5)), 5


def aniso3(n_side: int = 24, eps: float = 1e-2
           ) -> Tuple[sp.bsr_matrix, int]:
    """b=3 anisotropic vector system: eps-anisotropic 2D operator ⊗
    SPD 3×3 block.  SPD, and the anisotropy is exactly what smoother /
    coarsening quality regressions show up on."""
    A = sp.kron(_aniso_2d(n_side, eps), _spd_block(3, 0.2))
    return sp.bsr_matrix(A, blocksize=(3, 3)), 3


def jump2(n_side: int = 32, jump: float = 1e3
          ) -> Tuple[sp.bsr_matrix, int]:
    """b=2 coefficient-jump system: quadrant-jump diffusion ⊗ SPD 2×2
    block — the 6-orders-of-magnitude-coefficient class AmgX's strength
    thresholds exist for."""
    A = sp.kron(_jump_2d(n_side, jump), _spd_block(2, 0.25))
    return sp.bsr_matrix(A, blocksize=(2, 2)), 2


#: solver configs per structure class
_CFG_SPD = (
    "config_version=2, solver(out)=PCG, out:max_iters=400, "
    "out:monitor_residual=1, out:tolerance=1e-8, "
    "out:convergence=RELATIVE_INI, out:preconditioner(amg)=AMG, "
    "amg:algorithm=AGGREGATION, amg:selector=SIZE_2, amg:max_iters=1, "
    "amg:max_levels=10, amg:smoother(sm)=BLOCK_JACOBI, sm:max_iters=1, "
    "amg:presweeps=2, amg:postsweeps=2, amg:min_coarse_rows=24, "
    "amg:coarse_solver=DENSE_LU_SOLVER")
_CFG_NONSYM = (
    "config_version=2, solver(out)=PBICGSTAB, out:max_iters=400, "
    "out:monitor_residual=1, out:tolerance=1e-8, "
    "out:convergence=RELATIVE_INI, "
    "out:preconditioner(pre)=MULTICOLOR_DILU, pre:max_iters=1")


@dataclasses.dataclass(frozen=True)
class GauntletCase:
    """One gauntlet entry: a builder returning (BSR matrix, b) plus the
    solver config its structure class calls for."""

    name: str
    build: Callable[[], Tuple[sp.bsr_matrix, int]]
    block_dim: int
    cfg: str
    symmetric: bool


def gauntlet_cases(scale: float = 1.0):
    """The gauntlet roster at a size scale (1.0 = bench defaults; tests
    use ~0.5 to stay fast).  Every case is a true b×b block system with
    b ∈ {2, 3, 4, 5}."""
    s = max(scale, 0.25)

    def sz(n):
        return max(int(n * s), 4)

    return [
        GauntletCase("elast3", lambda: elasticity3(sz(12)), 3,
                     _CFG_SPD, True),
        GauntletCase("cfd4", lambda: cfd4(sz(24)), 4, _CFG_NONSYM,
                     False),
        GauntletCase("species5", lambda: species5(sz(20)), 5,
                     _CFG_NONSYM, False),
        GauntletCase("aniso3", lambda: aniso3(sz(24)), 3, _CFG_SPD,
                     True),
        GauntletCase("jump2", lambda: jump2(sz(32)), 2, _CFG_SPD,
                     True),
    ]


def load_via_matrix_market(case: GauntletCase, tmpdir: str):
    """Round-trip one case through the extended MatrixMarket IO: write
    the assembled system SCALAR-wise, read it back with the explicit
    ``block_dim`` re-blocking — the exact upload path a user's .mtx
    takes (and the satellite's divisibility validation, exercised on
    every bench run)."""
    import os

    from .matrix_market import read_matrix_market, write_matrix_market
    A, b = case.build()
    path = os.path.join(tmpdir, f"gauntlet_{case.name}.mtx")
    write_matrix_market(path, sp.csr_matrix(A))
    sysd = read_matrix_market(path, block_dim=b)
    return sysd, path
