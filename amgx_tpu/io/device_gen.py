"""On-device stencil operator generation.

The reference generates its benchmark operator INSIDE the library —
``AMGX_generate_distributed_poisson_7pt`` (``base/include/amgx_c.h:515-526``)
assembles the 7-point Poisson directly in device memory
(``examples/generate_poisson7_dist_renum.cu``), so its benchmarks never
pay a host→device operator transfer.  Through this rig's remote-TPU
tunnel an uploaded 256³ operator costs ~9 s of pure transfer; matching
the reference therefore means generating the DIA values ON THE CHIP:
boundary masks + constants, one tiny jitted executable, milliseconds of
device time, zero bytes across the link.

The returned :class:`~amgx_tpu.core.matrix.Matrix` carries BOTH views:

* the device DIA pack, generated on device (bit-identical to what
  uploading the host arrays would produce — the values are ±1/6,
  exact in every dtype);
* the analytic host diagonal arrays (``io.poisson.poisson7pt_dia``),
  which setup planning, IO, and the mixed-precision refinement residual
  consume without ever downloading from the device.
"""
from __future__ import annotations

import functools

import numpy as np

from ..core.matrix import Matrix, _dia_device_diag, _dia_device_matrix
from .poisson import poisson7pt_dia, poisson7pt_offsets


@functools.lru_cache(maxsize=None)
def _gen7pt_fn(nx: int, ny: int, nz: int, dtype_str: str):
    """Jitted on-device generator of the kept 7-pt diagonal rows."""
    import jax
    import jax.numpy as jnp

    n = nx * ny * nz
    dt = jnp.dtype(dtype_str)

    def gen():
        i = jnp.arange(n, dtype=jnp.int32)
        x = i % nx
        r = i // nx
        y = r % ny
        z = r // ny
        neg = jnp.asarray(-1.0, dt)
        zero = jnp.asarray(0.0, dt)
        # rows in poisson7pt_offsets order (the shared source of truth
        # with the host generator); ONE stacked output — the tunnel
        # charges ~0.1 s per executable output at load time
        rows = [
            jnp.where(z > 0, neg, zero),
            jnp.where(y > 0, neg, zero),
            jnp.where(x > 0, neg, zero),
            jnp.full((n,), 6.0, dt),
            jnp.where(x < nx - 1, neg, zero),
            jnp.where(y < ny - 1, neg, zero),
            jnp.where(z < nz - 1, neg, zero),
        ]
        spec = poisson7pt_offsets(nx, ny, nz)
        assert len(rows) == len(spec)
        return jnp.stack([row for row, (_, kept) in zip(rows, spec)
                          if kept])

    return jax.jit(gen)


def precompile_poisson7pt(nx: int, ny: int, nz: int,
                          device_dtype=np.float32) -> None:
    """Compile-and-warm the generator executable: benchmark acquisition
    windows should time the GENERATION, not a cold remote compile — the
    reference's built-in generator likewise ships precompiled.  (One
    throwaway generation runs; it costs milliseconds of device time and
    populates jit's executable cache, which ``.lower().compile()`` would
    not.)"""
    import jax
    jax.block_until_ready(
        _gen7pt_fn(nx, ny, nz, np.dtype(device_dtype).str)())


def poisson7pt_device(nx: int, ny: int, nz: int,
                      device_dtype=np.float32) -> Matrix:
    """7-point Poisson generated on the device (see module docstring).

    Equivalent to ``amgx.Matrix(poisson7pt(nx, ny, nz))`` with
    ``device_dtype`` set — same host analytic diagonals, same device
    pack values — except the device values never cross the link.
    """
    n = nx * ny * nz
    offsets = [o for o, kept in poisson7pt_offsets(nx, ny, nz) if kept]
    m = Matrix()
    m.block_dim = 1
    m.dtype = np.dtype(np.float64)   # host analytic arrays are f64
    m._n_dia = (n, n)
    # host arrays stay LAZY (oracle residuals / IO are the only
    # consumers); planning runs off the analytic hints below
    m._dia_thunk = lambda: poisson7pt_dia(nx, ny, nz)
    m._dia_offsets_hint = offsets
    m._stencil_consistent = True     # boundary-masked, no wrap couplings
    m._vals_f32_exact = True         # values are ±1/6: exact in f32
    m.grid_dims = (nz, ny, nx)
    dt = np.dtype(device_dtype)
    m.device_dtype = dt
    dvals = _gen7pt_fn(nx, ny, nz, dt.str)()
    assert dvals.shape[0] == len(offsets), (dvals.shape, offsets)
    ddiag = _dia_device_diag(offsets, dvals)
    m._device = _dia_device_matrix(offsets, dvals, ddiag, n)
    m._device_dtype = dt
    return m
