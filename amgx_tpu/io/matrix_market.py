"""MatrixMarket reader/writer with the reference's AMGX extensions.

Reference: ``base/src/matrix_io.cu`` (reader/writer registry) and
``core/src/readers.cu:666-1500`` (``ReadMatrixMarket``).  Supported beyond
standard MatrixMarket:

* a second header line ``%%AMGX <tokens>`` (also accepts ``%%NVAMG``) with
  tokens: ``rhs`` / ``solution`` (vectors appended after the entries),
  ``diagonal`` (external diagonal block stored after the entries),
  ``sorted``, ``base0``, and one or two integers giving the block size
  (``readers.cu:795-835``).
* ``symmetric`` / ``skew-symmetric`` / ``hermitian`` qualifiers (mirrored on
  read).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import scipy.sparse as sp

from ..errors import IOError_


@dataclasses.dataclass
class SystemData:
    """A linear system read from disk: A, and optionally b and x0."""

    A: sp.spmatrix
    rhs: Optional[np.ndarray]
    solution: Optional[np.ndarray]
    block_dimx: int = 1
    block_dimy: int = 1

    @property
    def block_dim(self):
        return self.block_dimx


def _tokens(line: str):
    return line.strip().lower().split()


def read_matrix_market(path: str,
                       block_dim: Optional[int] = None) -> SystemData:
    """Read a MatrixMarket system; ``block_dim`` re-blocks a SCALAR
    file into a b×b BSR system on the way in (the gauntlet loader for
    elasticity/CFD matrices stored entry-wise, ISSUE 15 satellite):
    dimensions must be divisible by ``block_dim`` — the error names the
    failing dimension — and a file that itself declares a conflicting
    block size is rejected rather than silently re-interpreted."""
    with open(path) as f:
        header = f.readline()
        if not header.startswith("%%MatrixMarket"):
            raise IOError_(f"{path}: missing %%MatrixMarket header")
        htok = _tokens(header[2:])
        is_complex = "complex" in htok
        symmetric = "symmetric" in htok or "skew-symmetric" in htok
        skew = "skew-symmetric" in htok
        hermitian = "hermitian" in htok
        if "pattern" in htok:
            raise IOError_("'pattern' matrices are not supported")

        block_dimx = block_dimy = 1
        index_base = 1
        has_rhs = has_soln = has_diag = False
        # comment lines; %%AMGX/%%NVAMG extension line
        pos = f.tell()
        line = f.readline()
        while line.startswith("%"):
            if line.startswith("%%AMGX") or line.startswith("%%NVAMG"):
                sizes = []
                for t in _tokens(line[2:])[1:]:
                    if t == "rhs":
                        has_rhs = True
                    elif t == "solution":
                        has_soln = True
                    elif t == "diagonal":
                        has_diag = True
                    elif t == "base0":
                        index_base = 0
                    elif t and t[0].isdigit():
                        sizes.append(int(t))
                if len(sizes) == 1:
                    block_dimx = block_dimy = sizes[0]
                elif len(sizes) >= 2:
                    block_dimx, block_dimy = sizes[0], sizes[1]
            pos = f.tell()
            line = f.readline()
        first_data_line = line

        parts = first_data_line.split()
        if len(parts) != 3:
            raise IOError_(f"{path}: expected 'rows cols nnz' line")
        rows, cols, entries = (int(p) for p in parts)

        rest = f.read().split()

    vals_per_entry = 4 if is_complex else 3
    need = entries * vals_per_entry
    if len(rest) < need:
        raise IOError_(f"{path}: truncated entry data "
                       f"({len(rest)} tokens < {need})")
    entry_tok = np.asarray(rest[:need])
    rest = rest[need:]
    ijv = entry_tok.reshape(entries, vals_per_entry)
    i = ijv[:, 0].astype(np.int64) - index_base
    j = ijv[:, 1].astype(np.int64) - index_base
    if is_complex:
        v = ijv[:, 2].astype(np.float64) + 1j * ijv[:, 3].astype(np.float64)
    else:
        v = ijv[:, 2].astype(np.float64)

    if symmetric or hermitian:
        off = i != j
        i2, j2 = j[off], i[off]
        v2 = v[off]
        if skew:
            v2 = -v2
        elif hermitian:
            v2 = np.conj(v2)
        i = np.concatenate([i, i2])
        j = np.concatenate([j, j2])
        v = np.concatenate([v, v2])

    A = sp.csr_matrix((v, (i, j)), shape=(rows, cols))
    A.sum_duplicates()
    A.sort_indices()

    if has_diag:
        # external diagonal: one value per row appended (readers.cu diag
        # path) — 're im' pairs in complex files, like every other block
        per = 2 if is_complex else 1
        ntok = rows * per
        tok = np.asarray(rest[:ntok])
        rest = rest[ntok:]
        if is_complex:
            t = tok.reshape(rows, 2)
            dvals = t[:, 0].astype(np.float64) \
                + 1j * t[:, 1].astype(np.float64)
        else:
            dvals = tok.astype(np.float64)
        A = A + sp.diags(dvals, shape=(rows, cols))
        A = sp.csr_matrix(A)

    def read_vec(rest):
        """rhs/solution block: complex systems carry 're im' pairs per
        entry (same convention as the coordinate entries)."""
        per = 2 if is_complex else 1
        if len(rest) < rows * per:
            raise IOError_(f"{path}: truncated vector block")
        tok = np.asarray(rest[:rows * per])
        rest = rest[rows * per:]
        if is_complex:
            t = tok.reshape(rows, 2)
            return (t[:, 0].astype(np.float64)
                    + 1j * t[:, 1].astype(np.float64)), rest
        return tok.astype(np.float64), rest

    rhs = soln = None
    if has_rhs:
        rhs, rest = read_vec(rest)
    if has_soln:
        soln, rest = read_vec(rest)

    if block_dim is not None:
        b = int(block_dim)
        if b < 1:
            raise IOError_(f"{path}: block_dim must be >= 1, got {b}")
        if (block_dimx, block_dimy) not in ((1, 1), (b, b)):
            raise IOError_(
                f"{path}: file declares {block_dimx}x{block_dimy} "
                f"blocks; explicit block_dim={b} conflicts")
        if b > 1:
            bad = []
            if rows % b:
                bad.append(f"rows {rows} % {b} = {rows % b}")
            if cols % b:
                bad.append(f"cols {cols} % {b} = {cols % b}")
            if bad:
                raise IOError_(
                    f"{path}: cannot re-block a {rows}x{cols} scalar "
                    f"matrix into {b}x{b} blocks ({'; '.join(bad)})")
            A = sp.bsr_matrix(A, blocksize=(b, b))
        block_dimx = block_dimy = b

    return SystemData(A=A, rhs=rhs, solution=soln,
                      block_dimx=block_dimx, block_dimy=block_dimy)


def write_matrix_market(path: str, A: sp.spmatrix,
                        rhs: Optional[np.ndarray] = None,
                        solution: Optional[np.ndarray] = None,
                        block_dim: int = 1):
    """Write a system in the reference's extended MatrixMarket format
    (``MatrixIO::writeSystemMatrixMarket``, base/src/matrix_io.cu)."""
    A = sp.coo_matrix(A)
    is_complex = (np.iscomplexobj(A.data)
                  or (rhs is not None and np.iscomplexobj(rhs))
                  or (solution is not None
                      and np.iscomplexobj(solution)))
    field = "complex" if is_complex else "real"
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        ext = []
        if block_dim != 1:
            ext.append(str(block_dim))
        if rhs is not None:
            ext.append("rhs")
        if solution is not None:
            ext.append("solution")
        if ext:
            f.write("%%AMGX " + " ".join(ext) + "\n")
        f.write(f"{A.shape[0]} {A.shape[1]} {A.nnz}\n")
        if is_complex:
            data_c = A.data.astype(np.complex128)
            for i, j, v in zip(A.row, A.col, data_c):
                f.write(f"{i+1} {j+1} {v.real:.17g} {v.imag:.17g}\n")
        else:
            for i, j, v in zip(A.row, A.col, A.data):
                f.write(f"{i+1} {j+1} {v:.17g}\n")
        for vec in (rhs, solution):
            if vec is not None:
                vv = np.asarray(vec).ravel()
                if is_complex:
                    for v in vv.astype(np.complex128):
                        f.write(f"{v.real:.17g} {v.imag:.17g}\n")
                else:
                    for v in vv:
                        f.write(f"{v:.17g}\n")
