"""Poisson problem generators.

Equivalent of the bundled CUSP gallery generators used throughout the
reference tests (``cusp::gallery::poisson5pt/7pt/9pt/27pt``,
``base/include/cusp/gallery/poisson.h``) and the distributed generator
``AMGX_generate_distributed_poisson_7pt`` (``amgx_c.h:515-526``,
``examples/generate_poisson7_dist_renum.cu``).
"""
from __future__ import annotations

import numpy as np
import scipy.sparse as sp


def _laplace_1d(n: int) -> sp.csr_matrix:
    return sp.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n), format="csr")


def _eye(n):
    return sp.identity(n, format="csr")


def poisson5pt(nx: int, ny: int) -> sp.csr_matrix:
    """2D 5-point Laplacian on an nx×ny grid."""
    return (sp.kron(_eye(ny), _laplace_1d(nx)) +
            sp.kron(_laplace_1d(ny), _eye(nx))).tocsr()


def poisson7pt_offsets(nx: int, ny: int, nz: int):
    """THE canonical 7-pt diagonal order: ``[(flat offset, kept)]``
    where ``kept`` marks diagonals of non-degenerate axes (a size-1
    axis has an all-zero coupling row, which the generators drop).
    Single source of truth for the host CSR generator, the host DIA
    arrays, and the on-device generator (``io/device_gen.py``) — their
    row orders MUST agree entry for entry."""
    return [(-nx * ny, nz > 1), (-nx, ny > 1), (-1, nx > 1), (0, True),
            (1, nx > 1), (nx, ny > 1), (nx * ny, nz > 1)]


def poisson7pt_dia(nx: int, ny: int, nz: int):
    """Analytic row-aligned DIA arrays of the 3D 7-point Laplacian:
    ``(offsets, vals)`` with all-zero diagonals of degenerate axes
    dropped.  Shared by the CSR generator below and the on-device
    generator (``io/device_gen.py``), which must produce bit-identical
    values."""
    n = nx * ny * nz
    X = np.tile(np.arange(nx), ny * nz)
    Y = np.tile(np.repeat(np.arange(ny), nx), nz)
    Z = np.repeat(np.arange(nz), nx * ny)
    vals = np.empty((7, n), dtype=np.float64)
    vals[0] = np.where(Z > 0, -1.0, 0.0)
    vals[1] = np.where(Y > 0, -1.0, 0.0)
    vals[2] = np.where(X > 0, -1.0, 0.0)
    vals[3] = 6.0
    vals[4] = np.where(X < nx - 1, -1.0, 0.0)
    vals[5] = np.where(Y < ny - 1, -1.0, 0.0)
    vals[6] = np.where(Z < nz - 1, -1.0, 0.0)
    spec = poisson7pt_offsets(nx, ny, nz)
    keep = [k for k, (o, kept) in enumerate(spec) if kept]
    return [spec[k][0] for k in keep], vals[keep]


def poisson7pt(nx: int, ny: int, nz: int) -> sp.csr_matrix:
    """3D 7-point Laplacian on an nx×ny×nz grid — the reference's headline
    benchmark operator (BASELINE.md configs 2-3).

    The returned CSR carries its analytic row-aligned diagonal
    decomposition as ``A._amgx_dia`` (+ ``A._amgx_grid_dims``), the same
    shortcut the reference's built-in generator enjoys
    (``AMGX_generate_distributed_poisson_7pt`` assembles directly in its
    partitioned layout): setup consumes the diagonals without ever
    re-extracting them from CSR."""
    n = nx * ny * nz
    offsets, vals = poisson7pt_dia(nx, ny, nz)
    from ..amg.pairwise import dia_to_scipy
    A = dia_to_scipy(offsets, vals, n)
    A._amgx_dia = (offsets, vals)
    A._amgx_grid_dims = (nz, ny, nx)
    return A


def poisson9pt(nx: int, ny: int) -> sp.csr_matrix:
    """2D 9-point stencil (8 neighbours + center)."""
    n = nx * ny
    ii, jj, vv = [], [], []
    idx = lambda x, y: y * nx + x
    for y in range(ny):
        for x in range(nx):
            r = idx(x, y)
            for dy in (-1, 0, 1):
                for dx in (-1, 0, 1):
                    X, Y = x + dx, y + dy
                    if 0 <= X < nx and 0 <= Y < ny:
                        ii.append(r)
                        jj.append(idx(X, Y))
                        vv.append(8.0 if (dx == 0 and dy == 0) else -1.0)
    return sp.csr_matrix((vv, (ii, jj)), shape=(n, n))


def poisson27pt(nx: int, ny: int, nz: int) -> sp.csr_matrix:
    """3D 27-point stencil."""
    n = nx * ny * nz
    idx3 = lambda x, y, z: (z * ny + y) * nx + x
    ii, jj, vv = [], [], []
    X, Y, Z = np.meshgrid(np.arange(nx), np.arange(ny), np.arange(nz),
                          indexing="ij")
    X, Y, Z = X.ravel(), Y.ravel(), Z.ravel()
    rows = idx3(X, Y, Z)
    for dz in (-1, 0, 1):
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                Xn, Yn, Zn = X + dx, Y + dy, Z + dz
                ok = ((0 <= Xn) & (Xn < nx) & (0 <= Yn) & (Yn < ny) &
                      (0 <= Zn) & (Zn < nz))
                ii.append(rows[ok])
                jj.append(idx3(Xn[ok], Yn[ok], Zn[ok]))
                center = (dx == 0 and dy == 0 and dz == 0)
                vv.append(np.full(ok.sum(), 26.0 if center else -1.0))
    return sp.csr_matrix(
        (np.concatenate(vv), (np.concatenate(ii), np.concatenate(jj))),
        shape=(n, n))


def generate_distributed_poisson_7pt(nx: int, ny: int, nz: int,
                                     px: int = 1, py: int = 1, pz: int = 1):
    """Generate the global 7-pt Poisson and a partition vector for a
    px×py×pz processor grid over the (nx·px, ny·py, nz·pz) global grid.

    Mirrors ``AMGX_generate_distributed_poisson_7pt``: each rank owns an
    nx×ny×nz brick; rows are numbered rank-contiguously (the "renumbered"
    layout of ``generate_poisson7_dist_renum.cu``).  Returns
    (A_global_csr, partition_vector) with rows ordered rank-major.
    """
    gx, gy, gz = nx * px, ny * py, nz * pz
    n = gx * gy * gz
    # global lexicographic index → rank-contiguous permutation
    X, Y, Z = np.meshgrid(np.arange(gx), np.arange(gy), np.arange(gz),
                          indexing="ij")
    X, Y, Z = X.ravel(), Y.ravel(), Z.ravel()
    lex = (Z * gy + Y) * gx + X
    rank = (Z // nz) * (px * py) + (Y // ny) * px + (X // nx)
    # local index within the brick
    lx, ly, lz = X % nx, Y % ny, Z % nz
    local = (lz * ny + ly) * nx + lx
    per_rank = nx * ny * nz
    newids = rank * per_rank + local
    perm = np.empty(n, dtype=np.int64)
    perm[lex] = newids
    A = poisson7pt(gx, gy, gz)
    P = sp.csr_matrix((np.ones(n), (perm, np.arange(n))), shape=(n, n))
    A_renum = (P @ A @ P.T).tocsr()
    partition = np.repeat(np.arange(px * py * pz), per_rank)
    return A_renum, partition
