"""NVAMG binary system format.

Reference: the ``%%NVAMGBinary`` reader/writer
(``core/src/readers.cu:1700-1960``).  Layout (studied from the reader's
field order; implementation is fresh):

    "%%NVAMGBinary\\n"                     14 bytes
    uint32[9]: is_mtx, is_rhs, is_soln, matrix_format(0=CSR), diag,
               block_dimx, block_dimy, num_rows, num_nz
    int32 row_offsets[num_rows+1]
    int32 col_indices[num_nz]
    float64 values[num_nz·bx·by]
    [float64 diag[num_rows·bx·by]]   when diag flag set (external diagonal)
    [float64 rhs[num_rows·bx]]        when is_rhs
    [float64 soln[num_rows·bx]]       when is_soln
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp

from ..errors import IOError_
from .matrix_market import SystemData

_MAGIC = b"%%NVAMGBinary\n"


def read_binary(path: str) -> SystemData:
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise IOError_(f"{path}: not an NVAMGBinary file")
        flags = np.fromfile(f, dtype=np.uint32, count=9)
        (_is_mtx, is_rhs, is_soln, _fmt, diag_flag, bx, by, n_rows,
         n_nz) = (int(v) for v in flags)
        indptr = np.fromfile(f, dtype=np.int32, count=n_rows + 1)
        indices = np.fromfile(f, dtype=np.int32, count=n_nz)
        vals = np.fromfile(f, dtype=np.float64, count=n_nz * bx * by)
        if len(vals) != n_nz * bx * by:
            raise IOError_(f"{path}: truncated values")
        if bx == 1:
            A = sp.csr_matrix((vals, indices, indptr),
                              shape=(n_rows, n_rows))
        else:
            A = sp.bsr_matrix((vals.reshape(-1, bx, by), indices, indptr),
                              shape=(n_rows * bx, n_rows * by))
        if diag_flag:
            dvals = np.fromfile(f, dtype=np.float64,
                                count=n_rows * bx * by)
            if bx == 1:
                A = sp.csr_matrix(A + sp.diags(dvals))
            else:
                D = sp.block_diag(list(dvals.reshape(-1, bx, by)),
                                  format="bsr")
                A = sp.bsr_matrix(A + D, blocksize=(bx, by))
        rhs = soln = None
        if is_rhs:
            rhs = np.fromfile(f, dtype=np.float64, count=n_rows * bx)
        if is_soln:
            soln = np.fromfile(f, dtype=np.float64, count=n_rows * bx)
    return SystemData(A=A, rhs=rhs, solution=soln, block_dimx=bx,
                      block_dimy=by)


def write_binary(path: str, A, rhs: Optional[np.ndarray] = None,
                 solution: Optional[np.ndarray] = None, block_dim: int = 1):
    b = int(block_dim)
    if b == 1:
        csr = sp.csr_matrix(A)
        csr.sort_indices()
        indptr, indices = csr.indptr, csr.indices
        vals = csr.data.astype(np.float64)
        n_rows = csr.shape[0]
        n_nz = csr.nnz
    else:
        bsr = A if isinstance(A, sp.bsr_matrix) else sp.bsr_matrix(
            A, blocksize=(b, b))
        bsr.sort_indices()
        indptr, indices = bsr.indptr, bsr.indices
        vals = bsr.data.astype(np.float64).ravel()
        n_rows = bsr.shape[0] // b
        n_nz = len(bsr.indices)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        flags = np.array([1, rhs is not None, solution is not None, 0, 0,
                          b, b, n_rows, n_nz], dtype=np.uint32)
        flags.tofile(f)
        indptr.astype(np.int32).tofile(f)
        indices.astype(np.int32).tofile(f)
        vals.tofile(f)
        if rhs is not None:
            np.asarray(rhs, dtype=np.float64).tofile(f)
        if solution is not None:
            np.asarray(solution, dtype=np.float64).tofile(f)


def read_system_auto(path: str) -> SystemData:
    """Dispatch MatrixMarket vs binary by magic (MatrixIO reader registry,
    matrix_io.h:51-107)."""
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
    if head == _MAGIC:
        return read_binary(path)
    from .matrix_market import read_matrix_market
    return read_matrix_market(path)
