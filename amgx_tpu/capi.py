"""AMGX-compatible C API surface.

Python realisation of the full ``base/include/amgx_c.h`` +
``amgx_eig_c.h`` contract (SURVEY §2.9): opaque handles + ``AMGX_*``
functions returning :class:`~amgx_tpu.errors.RC` codes, with exceptions
caught at the boundary exactly like the reference's ``AMGX_CATCHES``
(``amgx_c.cu:89-91``).  The native shared library (``native/``) exports
the same symbols as real C functions by embedding this module, so
``amgx_capi.c``-shaped drivers link and run unchanged.

Conventions: functions that have C out-params return ``(rc, value…)``
tuples; all others return the RC alone.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Optional

import numpy as np

from . import io as _io
from .config import AMGConfig
from .core.matrix import Matrix
from .eigen import EigenSolverFactory
from .errors import AMGXError, BadParametersError, RC, SolveStatus
from .modes import parse_mode
from .solvers import SolverFactory
from .utils import register_print_callback as _register_cb

__all__ = [n for n in dir() if n.startswith("AMGX_")]  # populated below


def _catches(n_outputs: int = 0):
    """Translate exceptions into RC codes (AMGX_CATCHES analog)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                out = fn(*args, **kwargs)
            except AMGXError as e:
                return (e.rc,) + (None,) * n_outputs if n_outputs else e.rc
            except Exception:
                if os.environ.get("AMGX_TPU_DEBUG"):
                    import traceback
                    traceback.print_exc()
                return ((RC.UNKNOWN,) + (None,) * n_outputs
                        if n_outputs else RC.UNKNOWN)
            if n_outputs == 0:
                return RC.OK if out is None else out
            if not isinstance(out, tuple):
                out = (out,)
            return (RC.OK,) + out
        return wrapper
    return deco


# ------------------------------------------------------------------ handles
class ConfigHandle:
    def __init__(self, cfg: AMGConfig):
        self.cfg = cfg


class ResourcesHandle:
    """Reference ``Resources`` (resources.h:44-82): devices + config."""

    def __init__(self, cfg: ConfigHandle, comm=None, device_num=0,
                 devices=None):
        self.cfg = cfg
        self.comm = comm
        self.devices = devices or [device_num]
        # zero cold-start knobs ride Resources creation — the first
        # object every C driver builds — so the persistent compile
        # cache and the AOT executable store are wired before any
        # solver or service compiles (solver construction re-applies
        # the same knobs idempotently for pure-python drivers)
        try:
            cache_dir = str(cfg.cfg.get("compile_cache_dir"))
            aot_dir = str(cfg.cfg.get("aot_store_dir"))
            if cache_dir:
                from .utils.jaxcompat import enable_compilation_cache
                enable_compilation_cache(cache_dir)
            if aot_dir:
                from .serve import aot as _aot
                _aot.configure(aot_dir)
            if cache_dir or aot_dir:
                from .telemetry import runstate
                runstate.configure_default(aot_dir or cache_dir)
        except Exception as e:
            # warm-start wiring must never fail create, but silently
            # losing it would leave the operator cold-starting with no
            # signal (the pure-python Solver path raises the same error)
            from .utils.logging import error_output
            error_output("AMGX warning: warm-start wiring failed "
                         f"(compile cache / AOT store disabled): {e!r}\n")


class MatrixHandle:
    def __init__(self, rsrc: ResourcesHandle, mode):
        self.rsrc = rsrc
        self.mode = parse_mode(mode)
        self.matrix: Optional[Matrix] = None
        self.bound_vectors = []


class VectorHandle:
    def __init__(self, rsrc: ResourcesHandle, mode):
        self.rsrc = rsrc
        self.mode = parse_mode(mode)
        self.data: Optional[np.ndarray] = None
        self.block_dim = 1
        self.bound_matrix: Optional[MatrixHandle] = None


class SolverHandle:
    def __init__(self, rsrc: ResourcesHandle, mode, cfg: ConfigHandle):
        self.rsrc = rsrc
        self.mode = parse_mode(mode)
        self.cfg = cfg.cfg
        self.solver = SolverFactory.allocate(self.cfg, "default", "solver")
        self.solver._toplevel = True    # owns solve-boundary transforms
        self.last_result = None


class EigenSolverHandle:
    def __init__(self, rsrc: ResourcesHandle, mode, cfg: ConfigHandle):
        self.rsrc = rsrc
        self.mode = parse_mode(mode)
        self.cfg = cfg.cfg
        self.solver = EigenSolverFactory.allocate(self.cfg)
        self.last_result = None


# ---------------------------------------------------------------- lifecycle
@_catches()
def AMGX_initialize():
    from . import initialize
    initialize()


@_catches()
def AMGX_initialize_plugins():
    pass  # eigensolvers are built in


@_catches()
def AMGX_finalize():
    from . import finalize
    finalize()


@_catches()
def AMGX_finalize_plugins():
    pass


@_catches(2)
def AMGX_get_api_version():
    return 2, 0


@_catches(3)
def AMGX_get_build_info_strings():
    from . import __reference_version__, __version__
    return (f"amgx_tpu {__version__}", f"API {__reference_version__}",
            "tpu/jax backend")


@_catches()
def AMGX_register_print_callback(fn):
    _register_cb(fn)


@_catches()
def AMGX_install_signal_handler():
    from .utils.signals import install_signal_handlers
    install_signal_handlers()


@_catches()
def AMGX_reset_signal_handler():
    from .utils.signals import reset_signal_handlers
    reset_signal_handlers()


@_catches()
def AMGX_pin_memory(arr):
    pass  # host memory is always accessible to XLA transfers


@_catches()
def AMGX_unpin_memory(arr):
    pass


# ------------------------------------------------------------------- config
@_catches(1)
def AMGX_config_create(options: str):
    return ConfigHandle(AMGConfig(options if options else
                                  "config_version=2"))


@_catches(1)
def AMGX_config_create_from_file(path: str):
    return ConfigHandle(AMGConfig.from_file(path))


@_catches(1)
def AMGX_config_create_from_file_and_string(path: str, options: str):
    cfg = AMGConfig.from_file(path)
    if options:
        cfg.parse(options)
    return ConfigHandle(cfg)


@_catches()
def AMGX_config_add_parameters(cfg: ConfigHandle, options: str):
    cfg.cfg.parse(options)


@_catches(1)
def AMGX_config_get_default_number_of_rings(cfg: ConfigHandle):
    # reference: 1 ring unless aggregation-style solvers require 2
    # (amgx_c.cu default ring logic)
    solver = str(cfg.cfg.get("solver"))
    algo = str(cfg.cfg.get("algorithm"))
    return 2 if (solver == "AMG" and algo == "AGGREGATION") else 1


@_catches()
def AMGX_config_destroy(cfg: ConfigHandle):
    pass


@_catches(1)
def AMGX_write_parameters_description(path_or_none=None):
    text = AMGConfig().write_parameters_description()
    if path_or_none:
        with open(path_or_none, "w") as f:
            f.write(text)
    return text


# ---------------------------------------------------------------- resources
@_catches(1)
def AMGX_resources_create(cfg: ConfigHandle, comm=None, device_num=0,
                          devices=None):
    return ResourcesHandle(cfg, comm, device_num, devices)


@_catches(1)
def AMGX_resources_create_simple(cfg: ConfigHandle):
    return ResourcesHandle(cfg)


@_catches()
def AMGX_resources_destroy(rsrc: ResourcesHandle):
    pass


# ------------------------------------------------------------------- matrix
def _apply_mode_policy(mtx: MatrixHandle):
    """Pin the pack to the mode's device and apply the precision policy
    (host modes → CPU fp64; device modes → accelerator, fp64→fp32 on
    TPU — see Mode.effective_mat_dtype)."""
    m = mtx.matrix
    if m is None:
        return
    m.placement = mtx.mode.placement_device()
    eff = mtx.mode.effective_mat_dtype()
    if np.dtype(m.dtype) != eff:
        if np.dtype(eff).itemsize < np.dtype(m.dtype).itemsize:
            # narrower device than the uploaded data (dDDI on TPU):
            # KEEP the wide host matrix and narrow only the device pack —
            # mixed-precision refinement then recovers full-precision
            # residuals (the dDFI path), instead of silently degrading
            # every solve to fp32 accuracy
            m.device_dtype = np.dtype(eff)
            m._device = None
        elif m.host is None and m.blocks is not None:
            m.blocks = [b.astype(eff) for b in m.blocks]
            m.dtype = np.dtype(eff)
            m._device = None
        else:
            m.set(m.host.astype(eff), block_dim=m.block_dim)


@_catches(1)
def AMGX_matrix_create(rsrc: ResourcesHandle, mode):
    return MatrixHandle(rsrc, mode)


@_catches()
def AMGX_matrix_destroy(mtx: MatrixHandle):
    mtx.matrix = None


@_catches()
def AMGX_matrix_upload_all(mtx: MatrixHandle, n, nnz, block_dimx,
                           block_dimy, row_ptrs, col_indices, data,
                           diag_data=None):
    """``amgx_c.h:288-296``: block-CSR upload with optional external
    diagonal."""
    if block_dimx != block_dimy:
        raise AMGXError("non-square blocks are not supported",
                        RC.NOT_SUPPORTED_BLOCKSIZE)
    data = np.asarray(data, dtype=mtx.mode.mat_dtype)
    m = Matrix.from_csr(np.asarray(row_ptrs), np.asarray(col_indices),
                        data, block_dim=int(block_dimx))
    if diag_data is not None:
        # external diagonal (DIAG property): add to the assembled matrix
        import scipy.sparse as sp
        b = int(block_dimx)
        dd = np.asarray(diag_data, dtype=mtx.mode.mat_dtype)
        if b == 1:
            D = sp.diags(dd.ravel())
        else:
            D = sp.block_diag([blk for blk in dd.reshape(-1, b, b)],
                              format="csr")
        m.set(sp.csr_matrix(m.host + D), block_dim=b)
    mtx.matrix = m
    _apply_mode_policy(mtx)


@_catches()
def AMGX_matrix_replace_coefficients(mtx: MatrixHandle, n, nnz, data,
                                     diag_data=None):
    mtx.matrix.replace_coefficients(
        np.asarray(data, dtype=mtx.mode.mat_dtype))


@_catches(3)
def AMGX_matrix_get_size(mtx: MatrixHandle):
    m = mtx.matrix
    return m.n_block_rows, m.block_dim, m.block_dim


@_catches(1)
def AMGX_matrix_get_nnz(mtx: MatrixHandle):
    return mtx.matrix.nnz // (mtx.matrix.block_dim ** 2)


@_catches(3)
def AMGX_matrix_download_all(mtx: MatrixHandle):
    import scipy.sparse as sp
    b = mtx.matrix.block_dim
    if b == 1:
        csr = mtx.matrix.scalar_csr()
        return csr.indptr.copy(), csr.indices.copy(), csr.data.copy()
    bsr = mtx.matrix.host if isinstance(mtx.matrix.host, sp.bsr_matrix) \
        else sp.bsr_matrix(mtx.matrix.host, blocksize=(b, b))
    return bsr.indptr.copy(), bsr.indices.copy(), bsr.data.copy()


@_catches()
def AMGX_matrix_vector_multiply(mtx: MatrixHandle, x: "VectorHandle",
                                y: "VectorHandle"):
    from .ops.spmv import spmv
    d = mtx.matrix.device(dtype=mtx.mode.mat_dtype)
    y.data = np.asarray(spmv(d, np.asarray(x.data, dtype=d.dtype)))


@_catches()
def AMGX_matrix_set_boundary_separation(mtx: MatrixHandle, flag: int):
    mtx.boundary_separation = int(flag)


@_catches()
def AMGX_matrix_attach_coloring(mtx: MatrixHandle, row_coloring,
                                num_rows, num_colors):
    from .coloring import MatrixColoring
    mtx.matrix.coloring = MatrixColoring(
        colors=np.asarray(row_coloring, dtype=np.int32),
        num_colors=int(num_colors))


@_catches()
def AMGX_matrix_attach_geometry(mtx: MatrixHandle, geox, geoy, geoz=None):
    """``amgx_c.h:541-546`` — per-row coordinates.  When they form a
    regular lexicographic grid, grid dims are derived and feed the GEO
    selector's structured fast path (amg/structured.py)."""
    coords = tuple(np.asarray(g, dtype=np.float64) for g in
                   (geox, geoy, geoz) if g is not None)
    mtx.matrix.geometry = coords
    dims = _regular_grid_dims(coords)
    if dims is not None:
        mtx.matrix.grid_dims = dims


def _regular_grid_dims(coords):
    """(nz, ny, nx) when the coordinate arrays describe a full regular
    grid in lexicographic (x fastest, then y, then z) order; None
    otherwise.  The FULL layout is verified — every axis must equal the
    exact tile/repeat pattern of its sorted unique values, so serpentine
    orderings or swapped axis nesting are rejected rather than producing
    misordered dims."""
    if not coords:
        return None
    n = len(coords[0])
    uniques, sizes = [], []
    for axis in coords:
        u = np.unique(axis)                 # sorted
        if len(u) == 0 or n % len(u) != 0:
            return None
        uniques.append(u)
        sizes.append(len(u))
    if int(np.prod(sizes)) != n:
        return None
    inner = 1
    for axis, u, s in zip(coords, uniques, sizes):
        expect = np.tile(np.repeat(u, inner), n // (inner * s))
        if not np.array_equal(axis, expect):
            return None
        inner *= s
    dims3 = ([1] * (3 - len(sizes)) + list(reversed(sizes)))
    return tuple(int(d) for d in dims3)


# ------------------------------------------------------------------- vector
@_catches(1)
def AMGX_vector_create(rsrc: ResourcesHandle, mode):
    return VectorHandle(rsrc, mode)


@_catches()
def AMGX_vector_destroy(vec: VectorHandle):
    vec.data = None


@_catches()
def AMGX_vector_upload(vec: VectorHandle, n, block_dim, data):
    vec.block_dim = int(block_dim)
    vec.data = np.asarray(data, dtype=vec.mode.vec_dtype).reshape(-1).copy()


@_catches()
def AMGX_vector_set_zero(vec: VectorHandle, n, block_dim):
    vec.block_dim = int(block_dim)
    vec.data = np.zeros(int(n) * int(block_dim), dtype=vec.mode.vec_dtype)


@_catches()
def AMGX_vector_set_random(vec: VectorHandle, n):
    vec.data = np.random.default_rng().standard_normal(int(n)).astype(
        vec.mode.vec_dtype)


@_catches(1)
def AMGX_vector_download(vec: VectorHandle):
    return vec.data.copy()


@_catches(2)
def AMGX_vector_get_size(vec: VectorHandle):
    if vec.data is None:
        return 0, vec.block_dim
    return len(vec.data) // vec.block_dim, vec.block_dim


@_catches()
def AMGX_vector_bind(vec: VectorHandle, mtx: MatrixHandle):
    """Attach the vector to the matrix's distribution
    (``amgx_c.h:391-393``) so uploads are reordered/haloed identically."""
    vec.bound_matrix = mtx
    mtx.bound_vectors.append(vec)


# ------------------------------------------------------------------- solver
@_catches(1)
def AMGX_solver_create(rsrc: ResourcesHandle, mode, cfg: ConfigHandle):
    return SolverHandle(rsrc, mode, cfg)


@_catches()
def AMGX_solver_destroy(slv: SolverHandle):
    slv.solver = None


@_catches()
def AMGX_solver_setup(slv: SolverHandle, mtx: MatrixHandle):
    slv.solver.setup(mtx.matrix)
    slv.matrix = mtx


@_catches()
def AMGX_solver_resetup(slv: SolverHandle, mtx: MatrixHandle):
    if hasattr(slv.solver, "resetup"):
        slv.solver.resetup(mtx.matrix)
    else:
        slv.solver.setup(mtx.matrix)
    slv.matrix = mtx


@_catches()
def AMGX_solver_solve(slv: SolverHandle, rhs: VectorHandle,
                      sol: VectorHandle):
    res = slv.solver.solve(rhs.data, x0=sol.data)
    slv.last_result = res
    sol.data = np.asarray(res.x)


@_catches()
def AMGX_solver_solve_with_0_initial_guess(slv: SolverHandle,
                                           rhs: VectorHandle,
                                           sol: VectorHandle):
    res = slv.solver.solve(rhs.data, zero_initial_guess=True)
    slv.last_result = res
    sol.data = np.asarray(res.x)


@_catches(1)
def AMGX_solver_get_iterations_number(slv: SolverHandle):
    return 0 if slv.last_result is None else slv.last_result.iterations


@_catches(1)
def AMGX_solver_get_iteration_residual(slv: SolverHandle, iteration,
                                       idx=0):
    h = slv.last_result.residual_history
    if h is None:
        raise AMGXError("residual history not stored "
                        "(set store_res_history=1)", RC.BAD_PARAMETERS)
    # reference Solver::get_residual(it) indexes m_res_history[it] directly
    # (index 0 = initial residual, i+1 = after iteration i)
    return float(np.atleast_2d(h)[iteration].ravel()[idx])


@_catches(1)
def AMGX_solver_get_status(slv: SolverHandle):
    return (SolveStatus.SUCCESS if slv.last_result is None
            else slv.last_result.status)


@_catches(1)
def AMGX_solver_calculate_residual_norm(slv: SolverHandle,
                                        mtx: MatrixHandle,
                                        rhs: VectorHandle,
                                        sol: VectorHandle):
    from .ops.spmv import spmv
    d = mtx.matrix.device()
    r = rhs.data - np.asarray(spmv(d, np.asarray(sol.data,
                                                 dtype=d.dtype)))
    return float(np.linalg.norm(r))


@_catches(1)
def AMGX_solver_get_setup_time(slv: SolverHandle):
    """Wall seconds of the last ``AMGX_solver_setup``/``_resetup`` —
    the same value the telemetry registry records as
    ``amgx_last_setup_seconds``."""
    return float(getattr(slv.solver, "setup_time", 0.0))


@_catches(1)
def AMGX_solver_get_solve_time(slv: SolverHandle):
    """Wall seconds of the last ``AMGX_solver_solve`` (telemetry gauge
    ``amgx_last_solve_seconds``)."""
    return (0.0 if slv.last_result is None
            else float(slv.last_result.solve_time))


@_catches(1)
def AMGX_solver_get_telemetry_snapshot(slv: SolverHandle):
    """Prometheus text-format snapshot of the telemetry registry (empty
    until a config with ``telemetry=1`` enabled recording)."""
    from . import telemetry
    return telemetry.prometheus_text()


# ----------------------------------------------------------------------- io
def _resolve_rhs(sysdata, mtx: MatrixHandle):
    if sysdata.rhs is not None:
        return sysdata.rhs
    cfg = mtx.rsrc.cfg.cfg
    if int(cfg.get("rhs_from_a")):
        e = np.ones(sysdata.A.shape[0])
        return np.asarray(sysdata.A @ e).ravel()
    return np.ones(sysdata.A.shape[0])


@_catches()
def AMGX_read_system(mtx: MatrixHandle, rhs: VectorHandle,
                     sol: VectorHandle, path: str):
    """``amgx_c.h:441-449``: read A (+rhs/solution when present)."""
    sysdata = _io.read_system_auto(path)
    mtx.matrix = Matrix(sysdata.A.astype(mtx.mode.mat_dtype),
                        block_dim=sysdata.block_dimx)
    _apply_mode_policy(mtx)
    if rhs is not None:
        rhs.data = np.asarray(_resolve_rhs(sysdata, mtx),
                              dtype=rhs.mode.vec_dtype)
        rhs.block_dim = sysdata.block_dimx
    if sol is not None:
        n = sysdata.A.shape[0]
        sol.data = (np.asarray(sysdata.solution, dtype=sol.mode.vec_dtype)
                    if sysdata.solution is not None
                    else np.zeros(n, dtype=sol.mode.vec_dtype))
        sol.block_dim = sysdata.block_dimx


@_catches()
def AMGX_write_system(mtx: MatrixHandle, rhs: VectorHandle,
                      sol: VectorHandle, path: str):
    writer = str(mtx.rsrc.cfg.cfg.get("matrix_writer"))
    write = (_io.write_binary if writer == "binary"
             else _io.write_matrix_market)
    write(path, mtx.matrix.host,
          rhs=None if rhs is None else rhs.data,
          solution=None if sol is None else sol.data,
          block_dim=mtx.matrix.block_dim)


@_catches()
def AMGX_read_system_global(mtx: MatrixHandle, rhs: VectorHandle,
                            sol: VectorHandle, path: str,
                            n_parts: int = None, part_offsets=None):
    """Distributed read (``read_system_global``): every rank gets the
    global system; here we read once and attach a distribution."""
    AMGX_read_system.__wrapped__(mtx, rhs, sol, path)
    if n_parts:
        _maybe_distribute(mtx.matrix, n_parts, part_offsets)


@_catches()
def AMGX_read_system_distributed(mtx: MatrixHandle, rhs: VectorHandle,
                                 sol: VectorHandle, path: str,
                                 allocated_halo_depth=1, num_partitions=1,
                                 partition_sizes=None,
                                 partition_vector=None):
    """``amgx_c.h:464`` / ``distributed_io.cu:182-278``:
    partition-vector-driven read.

    The partition vector assigns each GLOBAL row to a rank (rows need
    not be contiguous); like the reference's
    ``DistributedRead``/renumbering, rows are permuted rank-major
    (stable, preserving in-rank order), each rank receives ITS row block
    (``set_distributed_blocks`` — the global matrix is never the setup
    representation), and the permutation is recorded on the handle so
    ``AMGX_write_system_distributed`` round-trips to the ORIGINAL
    numbering."""
    sysdata = _io.read_system_auto(path)
    A = sysdata.A.astype(mtx.mode.mat_dtype)
    b = _resolve_rhs(sysdata, mtx)
    x = sysdata.solution
    mtx._dist_perm = None
    if partition_vector is None and partition_sizes is not None \
            and num_partitions > 1:
        # contiguous-size partitioning (the reference's
        # partition_sizes-without-vector form): synthesize the
        # rank-major partition vector — rows are already contiguous, so
        # the stable renumbering below is the identity
        sizes = np.asarray(partition_sizes, dtype=np.int64)
        if len(sizes) != num_partitions or int(sizes.sum()) != \
                A.shape[0]:
            raise BadParametersError(
                "partition_sizes must list num_partitions row counts "
                "summing to the global row count")
        partition_vector = np.repeat(
            np.arange(num_partitions, dtype=np.int64), sizes)
    if num_partitions > 1 and partition_vector is not None:
        import scipy.sparse as _sp
        pv = np.asarray(partition_vector)
        order = np.argsort(pv, kind="stable")   # rank-major renumbering
        A = _sp.csr_matrix(A)[order][:, order].tocsr()
        b = np.asarray(b)[order]
        if x is not None:
            x = np.asarray(x)[order]
        counts = np.bincount(pv, minlength=num_partitions)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        mtx._dist_perm = order
        import jax as _jax
        if len(_jax.devices()) >= num_partitions:
            from .distributed import make_mesh
            from .distributed.partition import split_row_blocks
            blocks = split_row_blocks(_sp.csr_matrix(A), offsets)
            m = Matrix()
            m.set_distributed_blocks(blocks, offsets,
                                     make_mesh(num_partitions))
            mtx.matrix = m
        else:
            mtx.matrix = Matrix(A)   # 1-chip session: renumbered global
    else:
        mtx.matrix = Matrix(A)
        if num_partitions > 1:
            _maybe_distribute(mtx.matrix, num_partitions, None)
    _apply_mode_policy(mtx)
    if rhs is not None:
        rhs.data = np.asarray(b, dtype=rhs.mode.vec_dtype)
    if sol is not None:
        n = A.shape[0]
        sol.data = (np.asarray(x, dtype=sol.mode.vec_dtype)
                    if x is not None
                    else np.zeros(n, dtype=sol.mode.vec_dtype))


@_catches()
def AMGX_write_system_distributed(mtx: MatrixHandle, rhs: VectorHandle,
                                  sol: VectorHandle, path: str,
                                  allocated_halo_depth=1,
                                  num_partitions=1, partition_sizes=None,
                                  partition_vector_size=0,
                                  partition_vector=None):
    """``amgx_c.h:447`` / ``distributed_io.cu``: gather the per-rank row
    blocks (consolidation — the write-side halo exchange) and write ONE
    system file in the ORIGINAL global numbering: the renumbering
    recorded by the distributed read (or given here as a partition
    vector) is inverted so read→write round-trips byte-for-value."""
    m = mtx.matrix
    A = m.assemble_global() if (m.host is None and m.blocks is not None) \
        else m.scalar_csr()
    b = None if rhs is None else np.asarray(rhs.data)
    x = None if sol is None else np.asarray(sol.data)
    perm = getattr(mtx, "_dist_perm", None)
    if perm is None and partition_vector is not None:
        perm = np.argsort(np.asarray(partition_vector), kind="stable")
    if perm is not None:
        import scipy.sparse as _sp
        inv = np.argsort(perm)
        A = _sp.csr_matrix(A)[inv][:, inv].tocsr()
        if b is not None and len(b) == A.shape[0]:
            b = b[inv]
        if x is not None and len(x) == A.shape[0]:
            x = x[inv]
    writer = str(mtx.rsrc.cfg.cfg.get("matrix_writer"))
    write = (_io.write_binary if writer == "binary"
             else _io.write_matrix_market)
    write(path, A, rhs=b, solution=x, block_dim=m.block_dim)


# -------------------------------------------------------------- distributed

def _maybe_distribute(matrix, n_parts, offsets=None):
    """Attach a mesh distribution when enough devices exist; otherwise run
    replicated on the available device(s) (the 1-rank MPI case)."""
    import jax
    if n_parts <= 1:
        return
    if len(jax.devices()) < n_parts:
        return  # single-chip session: solve globally (mpirun -n 1 analog)
    from .distributed import make_mesh
    matrix.set_distribution(make_mesh(n_parts), offsets=offsets)

@_catches()
def AMGX_matrix_upload_all_global(mtx: MatrixHandle, n_global, n, nnz,
                                  block_dimx, block_dimy, row_ptrs,
                                  col_indices_global, data, diag_data=None,
                                  allocated_halo_depth=1, num_import_rings=1,
                                  partition_vector=None):
    """``amgx_c.h:568-590``: global-index upload + partition vector.

    The reference renumbers and builds B2L maps here
    (``loadDistributedMatrix``); our shard pack does the same at
    ``Matrix.device()`` time.
    """
    AMGX_matrix_upload_all.__wrapped__(
        mtx, n, nnz, block_dimx, block_dimy, row_ptrs, col_indices_global,
        data, diag_data)
    if partition_vector is not None:
        from .distributed import partition_offsets_from_vector
        pv = np.asarray(partition_vector)
        n_parts = int(pv.max()) + 1
        offsets = partition_offsets_from_vector(pv, n_parts)
        _maybe_distribute(mtx.matrix, n_parts, offsets)


@_catches()
def AMGX_matrix_upload_distributed(mtx: MatrixHandle, n_global, n, nnz,
                                   block_dimx, block_dimy, row_ptrs,
                                   col_indices_global, data, diag_data,
                                   distribution):
    """``amgx_c.h:592-609`` with an AMGX_distribution handle.

    The reference contract is per-rank: each MPI rank passes its LOCAL
    rows (``n < n_global``) with global column indices.  This embedding
    is single-process, so successive calls with local blocks accumulate
    on the handle until all partitions are uploaded (scalable path: the
    global CSR is never assembled); a call with ``n == n_global`` is the
    whole matrix at once (legacy path).
    """
    import scipy.sparse as _sp
    n_global = int(n_global)
    n = int(n)
    offsets = None
    if distribution is not None:
        offsets = distribution.get("partition_offsets")
    if n == n_global or offsets is None:
        mtx._pending_blocks = None    # abandon any partial block sequence
        AMGX_matrix_upload_all.__wrapped__(
            mtx, n, nnz, block_dimx, block_dimy, row_ptrs,
            col_indices_global, data, diag_data)
        if distribution is not None:
            n_parts = (len(offsets) - 1 if offsets is not None
                       else distribution.get("num_partitions", 1))
            _maybe_distribute(mtx.matrix, n_parts, offsets)
        return
    # per-rank block accumulation (AMGX per-rank upload semantics):
    # blocks arrive in rank order, validated against the offsets
    if int(block_dimx) != 1 or int(block_dimy) != 1:
        raise BadParametersError(
            "distributed upload currently requires 1x1 blocks")
    offsets = np.asarray(offsets)
    pending = getattr(mtx, "_pending_blocks", None) or []
    rank = len(pending)
    expect = int(offsets[rank + 1] - offsets[rank]) \
        if rank + 1 < len(offsets) else -1
    if n != expect:
        mtx._pending_blocks = None
        raise BadParametersError(
            f"distributed upload out of order: rank {rank} owns {expect} "
            f"rows per the partition offsets, got {n}")
    dtype = mtx.mode.mat_dtype
    block = _sp.csr_matrix(
        (np.asarray(data, dtype=dtype).ravel(),
         np.asarray(col_indices_global).copy(),
         np.asarray(row_ptrs).copy()), shape=(n, n_global))
    if diag_data is not None:
        # external-diagonal property: fold the separate diagonal in
        # (upload_all does the same for the global path)
        rows = np.arange(n)
        block = _sp.csr_matrix(block + _sp.csr_matrix(
            (np.asarray(diag_data, dtype=dtype).ravel(),
             (rows, rows + int(offsets[rank]))), shape=(n, n_global)))
    pending.append(block)
    mtx._pending_blocks = pending
    if len(pending) < len(offsets) - 1:
        return                     # more ranks to come
    n_parts = len(offsets) - 1
    import jax as _jax
    mtx.matrix = Matrix()
    if len(_jax.devices()) >= n_parts > 1:
        from .distributed import make_mesh
        mtx.matrix.set_distributed_blocks(pending, offsets,
                                          make_mesh(n_parts))
    else:   # single-chip session: assemble and solve globally
        mtx.matrix.set(_sp.vstack(pending).tocsr())
    mtx._pending_blocks = None
    _apply_mode_policy(mtx)
    _try_validate_comm_maps(mtx)   # maps may have arrived before upload


@_catches()
def AMGX_matrix_upload_all_global_32(mtx: MatrixHandle, n_global, n, nnz,
                                     block_dimx, block_dimy, row_ptrs,
                                     col_indices_global, data,
                                     diag_data=None,
                                     allocated_halo_depth=1,
                                     num_import_rings=1,
                                     partition_vector=None):
    """``amgx_c.h:568-590`` (32-bit variant): identical contract with
    int32 global column indices — the native width of every device pack
    here, so this simply delegates (the 64-bit entry point accepts any
    integer dtype)."""
    return AMGX_matrix_upload_all_global.__wrapped__(
        mtx, n_global, n, nnz, block_dimx, block_dimy, row_ptrs,
        np.asarray(col_indices_global, dtype=np.int32), data, diag_data,
        allocated_halo_depth, num_import_rings, partition_vector)


@_catches(1)
def AMGX_distribution_create(cfg: ConfigHandle = None):
    return {"partition_offsets": None, "num_partitions": 1,
            "colindices_32bit": False}


@_catches()
def AMGX_distribution_set_partition_data(dist, kind, data):
    dist["partition_offsets"] = np.asarray(data)
    dist["num_partitions"] = len(data) - 1


@_catches()
def AMGX_distribution_set_32bit_colindices(dist, on):
    """``amgx_c.h:438``: declare 32-bit column indices for the coming
    upload.  Informational here — device packs always use int32 columns
    (``DeviceMatrix`` layout), and the upload path accepts either
    width."""
    dist["colindices_32bit"] = bool(on)


@_catches()
def AMGX_distribution_destroy(dist):
    pass


@_catches()
def AMGX_solver_register_print_callback(fn):
    """``amgx_c.h:396``: solver print-callback registration — the
    reference routes it to the same global print stream as
    ``AMGX_register_print_callback``; so do we."""
    _register_cb(fn)


@_catches(2)
def AMGX_generate_distributed_poisson_7pt(mtx: MatrixHandle,
                                          rhs: VectorHandle,
                                          sol: VectorHandle,
                                          nx, ny, nz, px=1, py=1, pz=1):
    """``amgx_c.h:515-526`` — built-in distributed Poisson assembly."""
    A, pv = _io.generate_distributed_poisson_7pt(nx, ny, nz, px, py, pz)
    mtx.matrix = Matrix(A.astype(mtx.mode.mat_dtype))
    _apply_mode_policy(mtx)
    n_parts = px * py * pz
    if n_parts > 1:
        from .distributed import partition_offsets_from_vector
        offsets = partition_offsets_from_vector(pv, n_parts)
        _maybe_distribute(mtx.matrix, n_parts, offsets)
    n = A.shape[0]
    if rhs is not None:
        rhs.data = np.ones(n, dtype=rhs.mode.vec_dtype)
    if sol is not None:
        sol.data = np.zeros(n, dtype=sol.mode.vec_dtype)
    return A, pv


# ------------------------------------------------------------------ serving
class ServiceHandle:
    """Opaque handle over a :class:`amgx_tpu.serve.SolveService`
    (TPU-build extension — the reference has no request-level serving
    layer; its building blocks, ``thread_manager.h`` AsyncTasks and the
    replace-coefficients resetup path, are what the service composes)."""

    def __init__(self, rsrc: ResourcesHandle, mode, cfg: ConfigHandle):
        import threading
        from .serve import SolveService
        self.rsrc = rsrc
        self.mode = parse_mode(mode)
        self.service = SolveService(cfg.cfg)
        self._tickets = {}
        self._next_ticket = 1
        #: concurrent driver threads submit/wait through one handle —
        #: ticket allocation must not race
        self._lock = threading.Lock()


@_catches(1)
def AMGX_serve_create(rsrc: ResourcesHandle, mode, cfg: ConfigHandle):
    """Start a solve service configured by ``cfg`` (``serve_*`` knobs:
    workers, queue depth, batch window, cache budget, deadlines)."""
    return ServiceHandle(rsrc, mode, cfg)


@_catches(1)
def AMGX_serve_submit(srv: ServiceHandle, mtx: MatrixHandle,
                      rhs: VectorHandle):
    """Queue one solve of ``mtx``'s matrix against ``rhs``; returns an
    integer ticket for :func:`AMGX_serve_wait`.  Over capacity the call
    returns ``RC.REJECTED`` and no ticket — the documented backpressure
    signal (queue bounded by ``serve_queue_depth``)."""
    pending = srv.service.submit(mtx.matrix, np.asarray(rhs.data))
    if pending.rc != RC.OK:
        raise AMGXError(pending.error or "admission rejected", pending.rc)
    with srv._lock:
        ticket = srv._next_ticket
        srv._next_ticket += 1
        srv._tickets[ticket] = pending
    return ticket


@_catches(2)
def AMGX_serve_wait(srv: ServiceHandle, ticket: int,
                    sol: VectorHandle = None, timeout: float = None):
    """Block for a submitted ticket; fills ``sol`` and returns
    ``(rc, status, iterations)``.  A timed-out wait KEEPS the ticket —
    the request is still running and a later wait can still collect
    it (popping here would make a slow solve unrecoverable)."""
    with srv._lock:
        pending = srv._tickets.get(int(ticket))
    if pending is None:
        raise BadParametersError(f"unknown serve ticket {ticket}")
    if not pending.wait_done(timeout):
        raise AMGXError("serve wait timed out; ticket still pending",
                        RC.UNKNOWN)
    with srv._lock:
        srv._tickets.pop(int(ticket), None)
    res = pending.result
    if pending.rc != RC.OK or res is None:
        raise AMGXError(pending.error or "request failed",
                        pending.rc if pending.rc != RC.OK else RC.UNKNOWN)
    if sol is not None:
        sol.data = np.asarray(res.x)
    return res.status, res.iterations


@_catches(1)
def AMGX_serve_warmup(srv: ServiceHandle, mtxs):
    """Prefetch executables for the given uploaded matrices' patterns
    off the request path (:meth:`SolveService.warmup`): session setup +
    the power-of-two batch-bucket ladder, persisted through the
    compile-cache/AOT knobs so the NEXT process starts warm.  ``mtxs``
    is one :class:`MatrixHandle` or a sequence; returns the warmup
    summary dict."""
    handles = mtxs if isinstance(mtxs, (list, tuple)) else [mtxs]
    mats = []
    for h in handles:
        if h.matrix is None:
            raise BadParametersError("warmup matrix not uploaded")
        mats.append(h.matrix)
    return srv.service.warmup(mats)


@_catches(1)
def AMGX_serve_stats(srv: ServiceHandle):
    """Operational snapshot: queue depth, completion/rejection counts,
    latency percentiles, SLO attainment/burn rate, per-phase split,
    cache hit/miss/eviction and per-session setup-reuse counts."""
    return srv.service.stats()


@_catches(1)
def AMGX_serve_endpoint(srv: ServiceHandle, port: int = None):
    """Base URL of the service's observability endpoint
    (``/metrics`` ``/healthz`` ``/statusz`` ``/debug/trace``
    ``/debug/profile`` — telemetry/httpd.py).  Already running when the
    config set ``metrics_port``; passing ``port`` here starts it on
    demand (0 binds an ephemeral port).  Returns None when it is not
    running and no port was given."""
    if port is not None:
        return srv.service.start_endpoint(int(port))
    return srv.service.endpoint


@_catches(1)
def AMGX_serve_health(srv: ServiceHandle):
    """The lane-aware liveness snapshot ``/healthz`` serves: aggregate
    queue/SLO state, ``overloaded`` (true only when EVERY executor
    lane is saturated — the LB eviction trip wire), and per-lane
    health entries naming the saturated subset."""
    return srv.service.health()


@_catches()
def AMGX_serve_drain(srv: ServiceHandle, timeout: float = None):
    """Stop admission and flush every queued request on every lane
    CONCURRENTLY (new submissions reject with ``RC.REJECTED`` until
    re-created).  On timeout the error message names the wedged
    lane(s); the per-lane report stays readable via
    ``AMGX_serve_stats()['last_drain']``."""
    if not srv.service.drain(timeout):
        stuck = [str(r["lane"]) for r
                 in (srv.service.last_drain or {}).get("lanes", [])
                 if not r.get("ok")]
        raise AMGXError("serve drain timed out on lane(s) "
                        + (",".join(stuck) or "?"), RC.UNKNOWN)


@_catches(1)
def AMGX_serve_drain_lane(srv: ServiceHandle, lane: int,
                          timeout: float = None):
    """Drain ONE executor lane while the service keeps serving (the
    chip-eviction path: the router re-routes the lane's patterns).
    Returns the lane's drain report; ``AMGX_serve_resume_lane``
    reopens it."""
    return srv.service.drain_lane(int(lane), timeout)


@_catches()
def AMGX_serve_resume_lane(srv: ServiceHandle, lane: int):
    """Reopen a drained executor lane for admission."""
    srv.service.resume_lane(int(lane))


@_catches()
def AMGX_serve_destroy(srv: ServiceHandle):
    srv.service.shutdown()
    srv._tickets.clear()


# -------------------------------------------------------------- eigensolver
@_catches(1)
def AMGX_eigensolver_create(rsrc: ResourcesHandle, mode,
                            cfg: ConfigHandle):
    return EigenSolverHandle(rsrc, mode, cfg)


@_catches()
def AMGX_eigensolver_setup(es: EigenSolverHandle, mtx: MatrixHandle):
    es.solver.setup(mtx.matrix)


@_catches()
def AMGX_eigensolver_pagerank_setup(es: EigenSolverHandle,
                                    vec: VectorHandle = None):
    es.solver.pagerank_setup(None if vec is None else vec.data)


@_catches()
def AMGX_eigensolver_solve(es: EigenSolverHandle, x: VectorHandle):
    res = es.solver.solve(x.data if x is not None and x.data is not None
                          else None)
    es.last_result = res
    if x is not None and res.eigenvectors is not None:
        x.data = np.asarray(res.eigenvectors[:, 0])


@_catches()
def AMGX_eigensolver_destroy(es: EigenSolverHandle):
    es.solver = None


# ------------------------------------------------------- error/abort tail
_RC_STRINGS = {
    RC.OK: "No error.",
    RC.BAD_PARAMETERS: "Incorrect parameters to AMGX call.",
    RC.UNKNOWN: "Unknown error.",
    RC.NOT_SUPPORTED_TARGET: "Unsupported target.",
    RC.NOT_SUPPORTED_BLOCKSIZE: "Unsupported block size.",
    RC.CUDA_FAILURE: "Device failure.",
    RC.THRUST_FAILURE: "Device library failure.",
    RC.NO_MEMORY: "Insufficient memory.",
    RC.IO_ERROR: "I/O error.",
    RC.BAD_MODE: "Invalid mode.",
    RC.CORE: "Error initializing amgx core.",
    RC.PLUGIN: "Error initializing plugins.",
    RC.BAD_CONFIGURATION: "Invalid configuration.",
    RC.NOT_IMPLEMENTED: "Not implemented.",
    RC.LICENSE_NOT_FOUND: "License not found.",
    RC.INTERNAL: "Internal error.",
    RC.REJECTED: "Request rejected by serving admission control.",
}


@_catches(1)
def AMGX_get_error_string(err):
    """``amgx_c.h:182-186`` — human-readable RC description."""
    try:
        rc = RC(int(err))
    except ValueError:
        return f"Unknown error code {int(err)}."
    return _RC_STRINGS.get(rc, rc.name.replace("_", " ").capitalize())


def AMGX_abort(rsrc, err):
    """``amgx_c.h:196`` — report and terminate the process (the reference
    aborts the communicator; never returns)."""
    from .utils.logging import amgx_output
    try:
        rc_txt = AMGX_get_error_string(err)
        msg = rc_txt[1] if isinstance(rc_txt, tuple) else str(err)
        amgx_output(f"AMGX_abort: error {int(err)} ({msg})\n")
    finally:
        os._exit(int(err) if err else 1)


# ------------------------------------------- user-supplied halo comm maps
def _record_comm_maps(mtx: MatrixHandle, entry: dict):
    """Accumulate per-rank comm maps (one call per rank, like the per-rank
    upload path) and validate against the matrix's own partition analysis
    once all ranks have reported.

    In this single-process SPMD embedding the halo maps are derivable
    from the uploaded blocks, so user maps serve as a cross-check (and
    let reference drivers that supply their own maps run unchanged):
    inconsistent neighbor lists are rejected with BAD_PARAMETERS.
    """
    pend = getattr(mtx, "_pending_comm", None) or []
    pend.append(entry)
    mtx._pending_comm = pend
    _try_validate_comm_maps(mtx)


def _try_validate_comm_maps(mtx: MatrixHandle):
    """Validate accumulated comm maps once both the matrix and a full set
    of per-rank maps exist — re-invoked from the upload completion path
    so maps-before-upload call orders (the reference driver order) also
    validate.  Entries are taken in rank order, matching the per-rank
    upload's enforced ordering."""
    pend = getattr(mtx, "_pending_comm", None)
    m = mtx.matrix
    if not pend or m is None or m.dist is None or m.dist[2] is None:
        return
    n_parts = len(np.asarray(m.dist[2])) - 1
    if len(pend) < n_parts:
        return
    from .distributed.partition import build_partition_from_blocks
    if m.blocks is not None:
        part = build_partition_from_blocks(m.blocks, m.block_offsets)
    else:
        from .distributed.partition import build_partition
        part = build_partition(m.scalar_csr(), n_parts,
                               np.asarray(m.dist[2]))
    for p, e in enumerate(pend[-n_parts:]):
        want = set(int(q) for q in part.neighbors[p])
        got = set(int(q) for q in e["neighbors"])
        if not want <= got:
            mtx._pending_comm = None
            raise BadParametersError(
                f"comm maps for rank {p} miss neighbors "
                f"{sorted(want - got)} required by the matrix structure")
    mtx.comm_maps = pend[-n_parts:]
    mtx._pending_comm = None


@_catches()
def AMGX_matrix_comm_from_maps(mtx: MatrixHandle, allocated_halo_depth,
                               num_import_rings, max_num_neighbors,
                               neighbors, send_ptrs, send_maps,
                               recv_ptrs, recv_maps):
    """``amgx_c.h:337-346`` — supply multi-ring halo maps (CSR-style
    per-neighbor pointer arrays)."""
    rings = int(num_import_rings)
    if rings not in (1, 2):
        raise BadParametersError("num_import_rings must be 1 or 2")
    nb = np.asarray(neighbors)[:int(max_num_neighbors)].astype(np.int64)
    sp_ = np.asarray(send_ptrs)
    rp_ = np.asarray(recv_ptrs)
    entry = {
        "rings": rings,
        "neighbors": nb.copy(),
        "send": [np.asarray(send_maps)[sp_[i]:sp_[i + 1]].copy()
                 for i in range(len(nb))],
        "recv": [np.asarray(recv_maps)[rp_[i]:rp_[i + 1]].copy()
                 for i in range(len(nb))],
    }
    _record_comm_maps(mtx, entry)


@_catches()
def AMGX_matrix_comm_from_maps_one_ring(mtx: MatrixHandle,
                                        allocated_halo_depth,
                                        num_neighbors, neighbors,
                                        send_sizes, send_maps,
                                        recv_sizes, recv_maps):
    """``amgx_c.h:348-356`` — one-ring maps with per-neighbor arrays."""
    nn = int(num_neighbors)
    nb = np.asarray(neighbors)[:nn].astype(np.int64)
    entry = {
        "rings": 1,
        "neighbors": nb.copy(),
        "send": [np.asarray(send_maps[i])[:int(send_sizes[i])].copy()
                 for i in range(nn)],
        "recv": [np.asarray(recv_maps[i])[:int(recv_sizes[i])].copy()
                 for i in range(nn)],
    }
    _record_comm_maps(mtx, entry)


import dataclasses as _dataclasses


@_dataclasses.dataclass
class OneRingSystem:
    """One rank's local system + one-ring maps (a plain object, NOT a
    tuple: ``_catches(1)`` splices tuples into the rc return)."""

    n: int
    nnz: int
    block_dimx: int
    block_dimy: int
    row_ptrs: np.ndarray
    col_indices: np.ndarray
    data: np.ndarray
    diag_data: Optional[np.ndarray]
    rhs: np.ndarray
    sol: np.ndarray
    num_neighbors: int
    neighbors: np.ndarray
    send_sizes: np.ndarray
    send_maps: list
    recv_sizes: np.ndarray
    recv_maps: list


@_catches(1)
def AMGX_read_system_maps_one_ring(rsrc: ResourcesHandle, mode, filename,
                                   allocated_halo_depth=1,
                                   num_partitions=1, partition_sizes=None,
                                   partition_vector=None, rank=0):
    """``amgx_c.h:475-499`` — read a system, partition it, and return one
    rank's LOCAL matrix (columns renumbered to [local | halo]) plus its
    one-ring communication maps.

    The reference infers ``rank`` from the resources' communicator; this
    single-process embedding takes it as an argument (default 0) so a
    driver can loop over ranks.
    """
    mode = parse_mode(mode)
    sysdata = _io.read_system_auto(filename)
    A = sysdata.A.tocsr()
    n_glob = A.shape[0]
    num_partitions = int(num_partitions)
    if partition_vector is not None:
        from .distributed import partition_offsets_from_vector
        offsets = partition_offsets_from_vector(
            np.asarray(partition_vector), num_partitions)
    elif partition_sizes is not None:
        offsets = np.concatenate(
            [[0], np.cumsum(np.asarray(partition_sizes))])
    else:
        nl = -(-n_glob // num_partitions)
        offsets = np.minimum(np.arange(num_partitions + 1) * nl, n_glob)
    from .distributed.partition import build_partition
    part = build_partition(A, num_partitions, offsets)
    r = int(rank)
    lo, hi = int(part.offsets[r]), int(part.offsets[r + 1])
    nl = hi - lo
    import scipy.sparse as _sp
    sub = _sp.csr_matrix(A[lo:hi])
    sub.sort_indices()
    ext = part.halo_global[r]          # sorted global ids of halo rows
    gcols = sub.indices.astype(np.int64)
    local = (gcols >= lo) & (gcols < hi)
    lcols = np.where(local, gcols - lo, 0)
    if len(ext):
        slot = np.minimum(np.searchsorted(ext, gcols), len(ext) - 1)
        lcols = np.where(local, lcols, nl + slot)
    owner = np.zeros(n_glob, dtype=np.int64)
    for p in range(num_partitions):
        owner[part.offsets[p]:part.offsets[p + 1]] = p
    nb = part.neighbors[r]
    send_maps, recv_maps = [], []
    for q in nb:
        # rows of r that q needs (→ q's halo), as r-local ids
        ext_q = part.halo_global[q]
        send = ext_q[owner[ext_q] == r] - lo
        send_maps.append(send.astype(np.int32))
        # r's halo slots owned by q, in r-local [nl..nl+H) numbering
        recv = nl + np.flatnonzero(owner[ext] == q)
        recv_maps.append(recv.astype(np.int32))
    dt = mode.mat_dtype
    rhs_g = (np.asarray(sysdata.rhs) if sysdata.rhs is not None
             else np.ones(n_glob))
    sol_g = (np.asarray(sysdata.solution)
             if sysdata.solution is not None else np.zeros(n_glob))
    return OneRingSystem(
        n=nl, nnz=sub.nnz, block_dimx=1, block_dimy=1,
        row_ptrs=sub.indptr.copy(),
        col_indices=lcols.astype(np.int32), data=sub.data.astype(dt),
        diag_data=None, rhs=rhs_g[lo:hi].astype(mode.vec_dtype),
        sol=sol_g[lo:hi].astype(mode.vec_dtype),
        num_neighbors=len(nb), neighbors=nb.astype(np.int32),
        send_sizes=np.asarray([len(s) for s in send_maps], np.int32),
        send_maps=send_maps,
        recv_sizes=np.asarray([len(s) for s in recv_maps], np.int32),
        recv_maps=recv_maps)


@_catches()
def AMGX_free_system_maps_one_ring(*args, **kwargs):
    """``amgx_c.h:501-513`` — buffers are GC-managed here; no-op."""


__all__ = [n for n in dict(globals()) if n.startswith("AMGX_")]
