"""Eigensolvers (reference plugin ``eigensolvers/``, SURVEY §2.7)."""
from .base import (EigenSolver, EigenSolverFactory, EigenResult,
                   register_eigensolver)
from . import algorithms  # registers all algorithms

__all__ = ["EigenSolver", "EigenSolverFactory", "EigenResult",
           "register_eigensolver"]
