"""Eigensolver framework.

Reference: ``base/include/eigensolvers/eigensolver.h:48-179`` (EigenSolver
base: setup/solve contract, shift, which=largest/smallest, eigenvector
extraction) + factory registry (``eigensolvers/src/eigensolvers.cu:60-70``);
params ``eig_*`` (``:44-54``).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AMGConfig
from ..core.matrix import Matrix
from ..errors import BadConfigurationError, SolveStatus
from ..ops.spmv import spmv

_eigensolver_registry: Dict[str, Type["EigenSolver"]] = {}


def register_eigensolver(name: str):
    def deco(cls):
        _eigensolver_registry[name] = cls
        cls.config_name = name
        return cls
    return deco


class EigenSolverFactory:
    @staticmethod
    def allocate(cfg: AMGConfig, scope: str = "default") -> "EigenSolver":
        value, new_scope = cfg.get_scoped("eig_solver", scope)
        name = str(value)
        if name not in _eigensolver_registry:
            raise BadConfigurationError(
                f"unknown eigensolver {name!r}; known: "
                f"{sorted(_eigensolver_registry)}")
        return _eigensolver_registry[name](cfg, new_scope)

    @staticmethod
    def registered():
        return dict(_eigensolver_registry)


@dataclasses.dataclass
class EigenResult:
    eigenvalues: np.ndarray
    eigenvectors: Optional[np.ndarray]   # (n, k) or None
    iterations: int
    status: SolveStatus
    residuals: Optional[np.ndarray] = None
    solve_time: float = 0.0


class EigenSolver:
    """Base: setup/solve contract (``eigensolver.h:102-133``)."""

    config_name = "?"

    def __init__(self, cfg: AMGConfig, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda name: cfg.get(name, scope)
        self.max_iters = int(g("eig_max_iters"))
        self.tolerance = float(g("eig_tolerance"))
        self.shift = float(g("eig_shift"))
        self.which = str(g("eig_which"))
        self.wanted_count = int(g("eig_wanted_count"))
        self.damping = float(g("eig_damping_factor"))
        self.A: Optional[Matrix] = None
        self.Ad = None

    def setup(self, A: Matrix):
        self.A = A if isinstance(A, Matrix) else None
        self.Ad = A.device() if isinstance(A, Matrix) else A
        self.solver_setup()
        return self

    def solver_setup(self):
        pass

    def pagerank_setup(self, ranks=None):
        """Reference AMGX_eigensolver_pagerank_setup."""
        return self

    def _op(self, x):
        """Shifted operator application (A − σI)x."""
        y = spmv(self.Ad, x)
        if self.shift != 0.0:
            y = y - self.shift * x
        return y

    def solve(self, x0=None) -> EigenResult:
        t0 = time.perf_counter()
        n = self.Ad.n
        if x0 is None:
            x0 = np.random.default_rng(0).standard_normal(n)
        x0 = jnp.asarray(np.asarray(x0), dtype=self.Ad.dtype)
        res = self._solve_impl(x0)
        res.solve_time = time.perf_counter() - t0
        return res

    def _solve_impl(self, x0) -> EigenResult:
        raise NotImplementedError
