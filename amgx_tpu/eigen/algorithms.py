"""Eigensolver algorithms.

Reference: ``core/src/eigensolvers/`` (2389 LoC) — POWER_ITERATION /
INVERSE_ITERATION / PAGERANK (all via ``single_iteration_eigensolver.cu``),
SUBSPACE_ITERATION, LANCZOS, ARNOLDI, LOBPCG, JACOBI_DAVIDSON; QR and
multivector helpers (``qr.cu``).

TPU notes: LOBPCG and subspace iteration are dominated by tall-skinny dense
algebra (blocked SpMV + small Gram matrices + QR) — exactly the shape the
MXU likes, as anticipated in SURVEY §7 M7.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..errors import SolveStatus
from ..ops.spmv import spmm, spmv
from ..solvers.base import SolverFactory
from .base import EigenResult, EigenSolver, register_eigensolver


def _nrm(x):
    return jnp.sqrt(jnp.real(jnp.vdot(x, x)))


@register_eigensolver("POWER_ITERATION")
class PowerIterationSolver(EigenSolver):
    """Largest-|λ| eigenpair by power iteration
    (``single_iteration_eigensolver.cu`` with the plain multiply op)."""

    def _iterate_op(self, x):
        return self._op(x)

    def _solve_impl(self, x0):
        tol = self.tolerance
        max_iters = self.max_iters

        def cond(carry):
            x, lam, it, done = carry
            return (~done) & (it < max_iters)

        def body(carry):
            x, lam, it, _ = carry
            y = self._iterate_op(x)
            nrm = _nrm(y)
            lam_new = jnp.vdot(x, y)
            y = y / jnp.maximum(nrm, 1e-300)
            done = jnp.abs(lam_new - lam) <= tol * jnp.abs(lam_new)
            return y, lam_new, it + 1, done

        x = x0 / jnp.maximum(_nrm(x0), 1e-300)
        lam0 = jnp.asarray(0.0, x.dtype)
        x, lam, it, done = jax.lax.while_loop(
            cond, body, (x, lam0, jnp.asarray(0), jnp.asarray(False)))
        lam_np = np.asarray(lam) + self.shift
        status = SolveStatus.SUCCESS if bool(done) else \
            SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=np.atleast_1d(lam_np),
                           eigenvectors=np.asarray(x)[:, None],
                           iterations=int(it), status=status)


@register_eigensolver("INVERSE_ITERATION")
class InverseIterationSolver(PowerIterationSolver):
    """Smallest-|λ−σ| eigenpair: power iteration on (A−σI)⁻¹ with a nested
    linear solver from config (reference inverse path of the
    single-iteration driver)."""

    def solver_setup(self):
        self.inner = SolverFactory.allocate(self.cfg, self.scope, "solver")
        a = self.A if self.A is not None else self.Ad
        self.inner.setup(a)

    def _iterate_op(self, x):
        return self.inner.apply(x)

    def _solve_impl(self, x0):
        res = super()._solve_impl(x0)
        # λ(A) = 1/λ((A−σI)⁻¹) + σ
        lam_inv = res.eigenvalues - self.shift
        lam = np.where(lam_inv != 0, 1.0 / lam_inv, np.inf) + self.shift
        res.eigenvalues = lam
        return res


@register_eigensolver("PAGERANK")
class PageRankSolver(EigenSolver):
    """PageRank via damped power iteration on the Google matrix
    (reference ``PagerankOperator`` + pagerank_setup,
    ``amgx_eig_c.h:42``): x ← d·Pᵀx + (1−d)/n, with P the row-stochastic
    link matrix of A's pattern."""

    def pagerank_setup(self, ranks=None):
        # build column-normalised Pᵀ on host
        csr = self.A.scalar_csr().astype(np.float64)
        out_deg = np.asarray(np.abs(csr).sum(axis=1)).ravel()
        out_deg[out_deg == 0] = 1.0
        P = sp.csr_matrix(sp.diags(1.0 / out_deg) @ abs(csr))
        from ..core.matrix import Matrix as _M
        self.PT = _M(sp.csr_matrix(P.T).astype(
            np.asarray(self.Ad.diag).dtype)).device()
        # pack dtype, not f64: a wider dangling vector would promote the
        # while-loop carry and break the traced loop on f32 devices
        self.dangling = jnp.asarray(
            (np.asarray(np.abs(csr).sum(axis=1)).ravel() == 0
             ).astype(np.asarray(self.Ad.diag).dtype))
        return self

    def solver_setup(self):
        self.pagerank_setup()

    def _solve_impl(self, x0):
        n = self.Ad.n
        d = self.damping
        x = jnp.abs(x0)
        x = x / jnp.sum(x)
        tol = self.tolerance

        def cond(carry):
            x, it, delta = carry
            return (delta > tol) & (it < self.max_iters)

        def body(carry):
            x, it, _ = carry
            y = d * spmv(self.PT, x) + (1.0 - d) / n
            # dangling mass redistribution
            y = y + d * jnp.sum(x * self.dangling) / n
            y = y / jnp.sum(y)
            delta = jnp.sum(jnp.abs(y - x))
            return y, it + 1, delta

        x, it, delta = jax.lax.while_loop(
            cond, body, (x, jnp.asarray(0), jnp.asarray(jnp.inf, x.dtype)))
        status = SolveStatus.SUCCESS if float(delta) <= self.tolerance \
            else SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=np.array([1.0]),
                           eigenvectors=np.asarray(x)[:, None],
                           iterations=int(it), status=status)


@functools.lru_cache(maxsize=None)
def _subspace_fn(n: int, m: int, k: int, dtype_str: str, tol: float,
                 max_iters: int, shift: float):
    """Compiled-once subspace-iteration loop (same whole-loop fusion as
    LOBPCG: a per-iteration host sync costs ~0.3 s through the tunnel)."""
    dt = jnp.dtype(dtype_str)

    def body(Ad, carry):
        X, lam_old, it, _done = carry
        Y = spmm(Ad, X)
        if shift:
            Y = Y - jnp.asarray(shift, dt) * X
        Q, _ = jnp.linalg.qr(Y)
        AQ = spmm(Ad, Q)
        if shift:
            AQ = AQ - jnp.asarray(shift, dt) * Q
        H = Q.T @ AQ
        w, V = jnp.linalg.eigh((H + H.T) / 2)
        order = jnp.argsort(-jnp.abs(w))
        X = Q @ V[:, order]
        lam = w[order]
        done = jnp.max(jnp.abs(lam[:k] - lam_old[:k])) <= \
            tol * jnp.maximum(jnp.max(jnp.abs(lam[:k])), 1e-30)
        return X, lam, it + 1, done

    def cond(carry):
        _X, _lam, it, done = carry
        return (~done) & (it < max_iters)

    @jax.jit
    def run(Ad, X0):
        return jax.lax.while_loop(
            cond, lambda c: body(Ad, c),
            (X0, jnp.zeros((m,), dt), jnp.asarray(0),
             jnp.asarray(False)))

    return run


@register_eigensolver("SUBSPACE_ITERATION")
class SubspaceIterationSolver(EigenSolver):
    """Block power iteration + Rayleigh-Ritz (``subspace_iteration.cu``),
    fused into one cached ``lax.while_loop`` executable."""

    def _solve_impl(self, x0):
        k = max(self.wanted_count, 1)
        m = min(2 * k + 2, self.Ad.n)
        n = self.Ad.n
        rng = np.random.default_rng(1)
        X = jnp.asarray(rng.standard_normal((n, m)), dtype=x0.dtype)
        X, _ = jnp.linalg.qr(X)
        run = _subspace_fn(n, m, k, np.dtype(self.Ad.dtype).str,
                           float(self.tolerance), int(self.max_iters),
                           float(self.shift))
        X, lam, it, done = run(self.Ad, X)
        lam_np = np.asarray(lam)[:k] + self.shift
        status = SolveStatus.SUCCESS if bool(done) else \
            SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=lam_np,
                           eigenvectors=np.asarray(X)[:, :k],
                           iterations=int(it), status=status)


@register_eigensolver("LANCZOS")
class LanczosSolver(EigenSolver):
    """Symmetric Lanczos tridiagonalisation (``lanczos.cu``): m Krylov
    steps with full reorthogonalisation, then eigh of the tridiagonal."""

    def _solve_impl(self, x0):
        n = self.Ad.n
        m = min(self.max_iters, n)
        V = np.zeros((m + 1, n))
        alpha = np.zeros(m)
        beta = np.zeros(m + 1)
        v = np.array(x0, dtype=np.float64)
        v /= np.linalg.norm(v)
        V[0] = v
        mv = jax.jit(lambda x: self._op(x))
        k_done = m
        for k in range(m):
            w = np.asarray(mv(jnp.asarray(V[k], dtype=self.Ad.dtype)),
                           dtype=np.float64)
            alpha[k] = V[k] @ w
            w = w - alpha[k] * V[k] - (beta[k] * V[k - 1] if k > 0 else 0)
            # full reorthogonalisation (the reference reorthogonalises too)
            w = w - V[:k + 1].T @ (V[:k + 1] @ w)
            beta[k + 1] = np.linalg.norm(w)
            if beta[k + 1] < 1e-12:
                k_done = k + 1
                break
            V[k + 1] = w / beta[k + 1]
        T = np.diag(alpha[:k_done]) + np.diag(beta[1:k_done], 1) + \
            np.diag(beta[1:k_done], -1)
        w_all, S = np.linalg.eigh(T)
        if self.which == "smallest":
            order = np.argsort(w_all)
        else:
            order = np.argsort(-np.abs(w_all))
        k = max(self.wanted_count, 1)
        lam = w_all[order[:k]] + self.shift
        vecs = V[:k_done].T @ S[:, order[:k]]
        return EigenResult(eigenvalues=lam, eigenvectors=vecs,
                           iterations=k_done, status=SolveStatus.SUCCESS)


@register_eigensolver("ARNOLDI")
class ArnoldiSolver(EigenSolver):
    """Arnoldi Hessenberg factorisation for nonsymmetric spectra
    (``arnoldi.cu``)."""

    def _solve_impl(self, x0):
        n = self.Ad.n
        m = min(self.max_iters, n)
        V = np.zeros((m + 1, n))
        H = np.zeros((m + 1, m))
        v = np.array(x0, dtype=np.float64)
        v /= np.linalg.norm(v)
        V[0] = v
        mv = jax.jit(lambda x: self._op(x))
        k_done = m
        for k in range(m):
            w = np.asarray(mv(jnp.asarray(V[k], dtype=self.Ad.dtype)),
                           dtype=np.float64)
            h = V[:k + 1] @ w
            w = w - V[:k + 1].T @ h
            # CGS2
            h2 = V[:k + 1] @ w
            w = w - V[:k + 1].T @ h2
            H[:k + 1, k] = h + h2
            H[k + 1, k] = np.linalg.norm(w)
            if H[k + 1, k] < 1e-12:
                k_done = k + 1
                break
            V[k + 1] = w / H[k + 1, k]
        w_all, S = np.linalg.eig(H[:k_done, :k_done])
        if self.which == "smallest":
            order = np.argsort(np.abs(w_all))
        else:
            order = np.argsort(-np.abs(w_all))
        k = max(self.wanted_count, 1)
        lam = w_all[order[:k]] + self.shift
        vecs = V[:k_done].T @ np.real(S[:, order[:k]])
        return EigenResult(eigenvalues=lam, eigenvectors=vecs,
                           iterations=k_done, status=SolveStatus.SUCCESS)


@functools.lru_cache(maxsize=None)
def _lobpcg_fn(n: int, k: int, dtype_str: str, smallest: bool,
               tol: float, max_iters: int, shift: float):
    """Compiled-once LOBPCG loop (the pack rides as a jit ARGUMENT, so
    value-only resetups reuse the executable).  The operator is the
    SHIFTED A − σI, matching the other eigensolvers' ``_op``."""
    dt = jnp.dtype(dtype_str)

    def op(Ad, X):
        AX = spmm(Ad, X)
        if shift:
            AX = AX - jnp.asarray(shift, dt) * X
        return AX

    def body(Ad, carry):
        X, Pdir, _lam, it, _done = carry
        AX = op(Ad, X)
        G = X.T @ AX
        lam, U = jnp.linalg.eigh((G + G.T) / 2)
        X = X @ U
        AX = AX @ U
        R = AX - X * lam[None, :]
        rnorm = jnp.linalg.norm(R, axis=0)
        conv = jnp.max(rnorm) <= tol * jnp.maximum(
            jnp.max(jnp.abs(lam)), 1e-30)
        S = jnp.concatenate([X, R, Pdir], axis=1)
        Q, _ = jnp.linalg.qr(S)
        AQ = op(Ad, Q)
        G2 = Q.T @ AQ
        w_all, V = jnp.linalg.eigh((G2 + G2.T) / 2)
        idx = (jnp.argsort(w_all) if smallest
               else jnp.argsort(-w_all))[:k]
        X_new = Q @ V[:, idx]
        Pdir = X_new - X @ (X.T @ X_new)
        return X_new, Pdir, w_all[idx], it + 1, conv

    def cond(carry):
        _X, _P, _lam, it, done = carry
        return (~done) & (it < max_iters)

    @jax.jit
    def run(Ad, X0):
        carry0 = (X0, jnp.zeros((n, k), dt), jnp.zeros((k,), dt),
                  jnp.asarray(0), jnp.asarray(False))
        X, _P, lam, it, done = jax.lax.while_loop(
            cond, lambda c: body(Ad, c), carry0)
        return X, lam, it, done

    return run


@register_eigensolver("LOBPCG")
class LOBPCGSolver(EigenSolver):
    """Locally optimal block preconditioned CG (``lobpcg_eigensolver.cu``):
    blocked SpMV + nested preconditioner from config + Rayleigh-Ritz on
    [X R P] — tall-skinny dense algebra, MXU-friendly."""

    def solver_setup(self):
        self.precond = None
        if self.cfg.has("preconditioner", self.scope) or \
                self.cfg.has("solver", self.scope):
            try:
                self.precond = SolverFactory.allocate(self.cfg, self.scope,
                                                      "preconditioner")
                a = self.A if self.A is not None else self.Ad
                self.precond.setup(a)
            except Exception:
                self.precond = None

    def _solve_impl(self, x0):
        if self.precond is None:
            return self._solve_impl_fused(x0)
        return self._solve_impl_host(x0)

    def _solve_impl_fused(self, x0):
        """Whole-iteration ``lax.while_loop``: one executable, ONE host
        sync per solve.  The host-loop variant below syncs the
        convergence test every iteration — ~0.1-0.3 s each through a
        remote-TPU tunnel, which dominated the eigensolver benchmark
        (measured 18.7 s for 60 iterations at 32³; the fused loop pays
        the device time only — 0.65 s).  P rides the carry as a zero
        block on the first iteration (a rank-deficient column in the
        trial QR only adds an arbitrary orthonormal direction — harmless
        to Rayleigh-Ritz)."""
        n = self.Ad.n
        k = max(self.wanted_count, 1)
        smallest = self.which != "largest"
        rng = np.random.default_rng(3)
        X0 = np.linalg.qr(np.asarray(
            rng.standard_normal((n, k))))[0]
        dt = self.Ad.dtype
        X0 = jnp.asarray(X0, dtype=dt)
        run = _lobpcg_fn(n, k, np.dtype(dt).str, smallest,
                         float(self.tolerance), int(self.max_iters),
                         float(self.shift))
        X, lam, it, done = run(self.Ad, X0)
        lam_np = np.asarray(lam)
        order = np.argsort(lam_np) if smallest else np.argsort(-lam_np)
        lam_np = lam_np[order] + self.shift
        vecs = np.asarray(X)[:, order]
        status = SolveStatus.SUCCESS if bool(done) else \
            SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=lam_np, eigenvectors=vecs,
                           iterations=int(it), status=status)

    def _solve_impl_host(self, x0):
        n = self.Ad.n
        k = max(self.wanted_count, 1)
        smallest = self.which != "largest"
        rng = np.random.default_rng(3)
        X = np.asarray(rng.standard_normal((n, k)))
        X, _ = np.linalg.qr(X)
        X = jnp.asarray(X, dtype=self.Ad.dtype)
        P = None
        lam = None
        it_done = 0
        converged = False
        sh = jnp.asarray(self.shift, self.Ad.dtype)
        for it in range(self.max_iters):
            AX = spmm(self.Ad, X)
            if self.shift:
                AX = AX - sh * X        # the shifted _op, like the
                                        # other eigensolvers
            G = X.T @ AX
            lam_mat, U = jnp.linalg.eigh((G + G.T) / 2)
            X = X @ U
            AX = AX @ U
            lam = lam_mat
            R = AX - X * lam[None, :]
            rnorm = jnp.linalg.norm(R, axis=0)
            it_done = it + 1
            if bool(jnp.max(rnorm) <= self.tolerance *
                    jnp.maximum(jnp.max(jnp.abs(lam)), 1e-300)):
                converged = True
                break
            W = R
            if self.precond is not None:
                # column loop, not vmap: the preconditioner may trace
                # Pallas kernels, which reject batching
                W = jnp.stack([self.precond.apply(R[:, j])
                               for j in range(R.shape[1])], axis=1)
            basis = [X, W] + ([P] if P is not None else [])
            S = jnp.concatenate(basis, axis=1)
            # orthonormalise the trial space
            Q, _ = jnp.linalg.qr(S)
            AQ = spmm(self.Ad, Q)
            G = Q.T @ AQ
            w_all, V = jnp.linalg.eigh((G + G.T) / 2)
            if smallest:
                idx = jnp.argsort(w_all)[:k]
            else:
                idx = jnp.argsort(-w_all)[:k]
            X_new = Q @ V[:, idx]
            P = X_new - X @ (X.T @ X_new)
            X = X_new
        order = np.argsort(np.asarray(lam)) if smallest else \
            np.argsort(-np.asarray(lam))
        lam_np = np.asarray(lam)[order] + self.shift
        vecs = np.asarray(X)[:, order]
        status = SolveStatus.SUCCESS if converged else \
            SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=lam_np, eigenvectors=vecs,
                           iterations=it_done, status=status)


@register_eigensolver("JACOBI_DAVIDSON")
class JacobiDavidsonSolver(EigenSolver):
    """Davidson method with diagonal (Jacobi) correction preconditioner
    (``jacobi_davidson.cu`` behavioural parity)."""

    def _solve_impl(self, x0):
        n = self.Ad.n
        m_max = min(max(20, 2 * self.wanted_count + 10), n)
        diag = np.asarray(self.Ad.diag, dtype=np.float64).reshape(-1)
        if diag.ndim > 1:
            diag = np.ones(n)
        mv = jax.jit(lambda x: self._op(x))
        V = np.zeros((m_max, n))
        v = np.array(x0, dtype=np.float64)
        v /= np.linalg.norm(v)
        V[0] = v
        m = 1
        theta = 0.0
        u = v
        it_done = 0
        for it in range(self.max_iters):
            W = np.stack([np.asarray(mv(jnp.asarray(V[i],
                                                    dtype=self.Ad.dtype)),
                                     dtype=np.float64)
                          for i in range(m)])
            H = V[:m] @ W.T
            w_all, S = np.linalg.eigh((H + H.T) / 2)
            pick = -1 if self.which == "largest" else 0
            theta = w_all[pick]
            u = V[:m].T @ S[:, pick]
            r = np.asarray(mv(jnp.asarray(u, dtype=self.Ad.dtype)),
                           dtype=np.float64) - theta * u
            it_done = it + 1
            if np.linalg.norm(r) <= self.tolerance * max(abs(theta), 1e-300):
                break
            # Davidson correction with diagonal preconditioner
            denom = diag - theta
            denom[np.abs(denom) < 1e-12] = 1e-12
            t = -r / denom
            # orthogonalise against V
            t = t - V[:m].T @ (V[:m] @ t)
            nt = np.linalg.norm(t)
            if nt < 1e-14 or m >= m_max:
                # restart with current best
                V[0] = u / np.linalg.norm(u)
                m = 1
                continue
            V[m] = t / nt
            m += 1
        status = SolveStatus.SUCCESS if it_done < self.max_iters else \
            SolveStatus.NOT_CONVERGED
        return EigenResult(eigenvalues=np.array([theta + self.shift]),
                           eigenvectors=u[:, None],
                           iterations=it_done, status=status)
