"""amgx_tpu — a TPU-native algebraic multigrid + Krylov sparse solver
framework with the capabilities of NVIDIA AmgX (reference:
``/root/reference``), built on JAX/XLA/Pallas.

Architecture (see SURVEY.md for the reference layer map this mirrors):

* irregular *setup* (coarsening, coloring, SpGEMM structure) runs on host
  over scipy CSR, producing frozen, statically-shaped device packs;
* the regular *solve* phase is a single jitted XLA computation —
  ``lax.while_loop`` over a state pytree, with preconditioner/smoother
  stacks composed at trace time;
* distribution is row-wise domain decomposition over a
  ``jax.sharding.Mesh`` with ``ppermute``/``psum`` collectives replacing the
  reference's MPI halo exchange.
"""
from __future__ import annotations

import os as _os

import jax as _jax

# fp64 host modes (hDDI) and convergence-parity testing need x64 enabled.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compile cache: the reference ships precompiled kernels,
# so its setup pays zero JIT cost at run time; caching compiled
# executables across processes is the XLA equivalent (first-ever run
# still compiles).  NOTE: this mutates global JAX config AT IMPORT TIME
# (documented in README; JAX creates the directory lazily at the first
# persisted compile); the guard below never clobbers a cache dir the
# host application configured before importing amgx_tpu.  Opt out with
# AMGX_TPU_COMPILE_CACHE=0.  The `compile_cache_dir` config knob (and
# `aot_store_dir` — the explicit AOT executable store, serve/aot.py)
# overrides this default per solver/service/Resources; see the README
# "Zero cold-start" section.
_cache_dir = _os.environ.get("AMGX_TPU_COMPILE_CACHE",
                             _os.path.expanduser("~/.cache/amgx_tpu_xla"))
if _cache_dir not in ("0", "") and \
        _jax.config.jax_compilation_cache_dir is None:
    # never clobber a cache the host application already configured
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # hit/miss accounting (utils/jaxcompat.py) rides along whenever the
    # cache is active — compile_cache_stats() and the runstate file
    # must count env-configured processes too, not just telemetry runs
    from .utils.jaxcompat import install_compile_counter as _icc
    _icc()

__version__ = "0.1.0"
#: reference parity target (ReleaseVersion.txt:1)
__reference_version__ = "2.1.0.131-opensource"

from . import errors
from .errors import RC, SolveStatus, AMGXError
from .modes import Mode, parse_mode, PUBLIC_MODES
from .config import AMGConfig
from .core import Matrix, DeviceMatrix
from .ops import blas, spmv, spmm
from .solvers import Solver, SolverFactory, SolveResult
from . import io
from . import telemetry
from .utils import register_print_callback, amgx_output

_initialized = False


def initialize():
    """Library init (reference ``AMGX_initialize``, core.cu:739)."""
    global _initialized
    _initialized = True
    return RC.OK


def finalize():
    global _initialized
    _initialized = False
    return RC.OK


def get_api_version():
    return (2, 0)


def create_solver(config, mode: str = "dDDI") -> Solver:
    """Convenience: build the outer solver described by a config
    (JSON dict/string/path or AMGConfig)."""
    cfg = config if isinstance(config, AMGConfig) else AMGConfig(config)
    slv = SolverFactory.allocate(cfg, "default", "solver")
    #: the OUTERMOST solver owns solve-boundary transforms (RCM reorder)
    slv._toplevel = True
    return slv


__all__ = [
    "initialize", "finalize", "get_api_version", "create_solver",
    "AMGConfig", "Matrix", "DeviceMatrix", "Solver", "SolverFactory",
    "SolveResult", "Mode", "parse_mode", "PUBLIC_MODES", "RC", "SolveStatus",
    "AMGXError", "blas", "spmv", "spmm", "io", "telemetry",
    "register_print_callback", "amgx_output",
]
