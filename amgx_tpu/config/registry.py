"""Typed parameter registry.

Mirrors the reference's static registry ``AMG_Config::param_desc``
(``base/include/amg_config.h:49-190``) populated by ``registerParameters()``
(``core/src/core.cu:331-560``).  Every parameter has a name, python type,
default value, description and optional allowed values/range.  Lookup is
*scoped*: nested solvers read their own sub-config scope, falling back to the
"default" scope (``amg_config.h:197-198``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

from ..errors import BadConfigurationError

_BOOL = (0, 1)
_NORMS = ("L1", "L2", "LMAX", "L1_SCALED")
_VIEWS = ("INTERIOR", "OWNED", "FULL", "ALL")
_ALGOS = ("CLASSICAL", "AGGREGATION", "ENERGYMIN")
_COLORING = ("FIRST", "SYNC_COLORS", "LAST")
_BLOCK_FORMATS = ("ROW_MAJOR", "COL_MAJOR")


@dataclasses.dataclass
class ParameterDescription:
    name: str
    type: type
    default: Any
    description: str = ""
    allowed: Optional[Sequence[Any]] = None     # enumerated values
    range: Optional[Tuple[Any, Any]] = None     # inclusive numeric range


_registry: Dict[str, ParameterDescription] = {}


def register_parameter(name, type_, default, description="", allowed=None,
                       range_=None, overwrite=False):
    if name in _registry and not overwrite:
        return
    _registry[name] = ParameterDescription(name, type_, default, description,
                                           allowed, range_)


def get_description(name: str) -> Optional[ParameterDescription]:
    return _registry.get(name)


def all_parameters() -> Dict[str, ParameterDescription]:
    return dict(_registry)


def coerce(name: str, value: Any) -> Any:
    """Coerce a parsed value to the registered type, validating allowed values.

    Mirrors ``AMG_Config::setNamedParameter`` overloads
    (``amg_config.cu:439-517``): int<->double cross-assignment is allowed,
    strings parse to numbers for numeric params.
    """
    desc = _registry.get(name)
    if desc is None:
        # Unknown parameter: keep as-is (reference raises; we store and let the
        # consuming factory complain — but validate obvious typos at get()).
        return value
    t = desc.type
    try:
        if t is int:
            if isinstance(value, str):
                value = int(float(value))
            elif isinstance(value, float):
                value = int(value)
            else:
                value = int(value)
        elif t is float:
            value = float(value)
        elif t is str:
            value = str(value)
    except (TypeError, ValueError):
        raise BadConfigurationError(
            f"parameter {name!r}: cannot convert {value!r} to {t.__name__}")
    if desc.allowed is not None and value not in desc.allowed:
        raise BadConfigurationError(
            f"parameter {name!r}: value {value!r} not in allowed set "
            f"{tuple(desc.allowed)}")
    if desc.range is not None:
        lo, hi = desc.range
        if not (lo <= value <= hi):
            raise BadConfigurationError(
                f"parameter {name!r}: value {value!r} outside [{lo}, {hi}]")
    return value


_SOLVER_VALUES = (
    "AMG", "CG", "PCG", "PCGF", "BICGSTAB", "PBICGSTAB", "GMRES", "FGMRES",
    "IDR", "IDRMSYNC", "JACOBI_L1", "BLOCK_JACOBI", "CF_JACOBI", "GS",
    "MULTICOLOR_GS", "FIXCOLOR_GS", "MULTICOLOR_ILU", "MULTICOLOR_DILU",
    "KACZMARZ", "CHEBYSHEV", "CHEBYSHEV_POLY", "POLYNOMIAL", "KPZ_POLYNOMIAL",
    "DENSE_LU_SOLVER", "NOSOLVER", "PCG_CA", "PCG_PIPE",
)

_KRYLOV_COMM = ("CLASSIC", "CA", "PIPELINED")


def register_default_parameters():
    """Register the reference's parameter set (``core/src/core.cu:331-560``)."""
    R = register_parameter
    # --- global/debug flags (core.cu:337-381)
    R("determinism_flag", int, 0, "force deterministic aggregation/coloring", _BOOL)
    R("exception_handling", int, 0, "internal exception processing", _BOOL)
    R("fine_level_consolidation", int, 0, "consolidate fine level", _BOOL)
    R("use_cuda_ipc_consolidation", int, 0, "(GPU legacy) IPC consolidation", _BOOL)
    R("amg_consolidation_flag", int, 0, "use amg level consolidation")
    R("matrix_consolidation_lower_threshold", int, 0,
      "avg rows at which partitions must be merged")
    R("matrix_consolidation_upper_threshold", int, 1000,
      "avg rows merged partitions should have")
    R("device_mem_pool_size", int, 256 * 1024 * 1024, "device pool bytes")
    R("device_consolidation_pool_size", int, 256 * 1024 * 1024)
    R("device_mem_pool_max_alloc_size", int, 20 * 1024 * 1024)
    R("device_alloc_scaling_factor", int, 10)
    R("device_alloc_scaling_threshold", int, 16 * 1024)
    R("device_mem_pool_size_limit", int, 0)
    R("num_streams", int, 0, "extra async streams")
    R("serialize_threads", int, 0, "serialize setup threads", _BOOL)
    R("high_priority_stream", int, 0, "", _BOOL)
    R("communicator", str, "MPI", "<MPI|MPI_DIRECT> (TPU: ICI collectives)")
    R("separation_interior", str, "INTERIOR", "latency-hiding split", _VIEWS)
    R("separation_exterior", str, "OWNED", "smoothing extent", _VIEWS)
    R("min_rows_latency_hiding", int, -1, "rows to disable latency hiding")
    R("matrix_halo_exchange", int, 0, "0 none, 1 diag, 2 full")
    R("boundary_coloring", str, "SYNC_COLORS", "", _COLORING)
    R("halo_coloring", str, "LAST", "", _COLORING)
    R("use_sum_stopping_criteria", int, 0)
    R("rhs_from_a", int, 0, "generate missing RHS from A")
    R("complex_conversion", int, 0)
    R("matrix_writer", str, "matrixmarket", "", ("matrixmarket", "binary"))
    R("block_format", str, "ROW_MAJOR", "", _BLOCK_FORMATS)
    R("block_convert", int, 0)
    # --- solver selection (core.cu:404-411)
    R("solver", str, "AMG", "solving algorithm", _SOLVER_VALUES)
    R("preconditioner", str, "AMG", "preconditioner algorithm", _SOLVER_VALUES)
    R("coarse_solver", str, "DENSE_LU_SOLVER", "", _SOLVER_VALUES)
    R("smoother", str, "BLOCK_JACOBI", "", _SOLVER_VALUES)
    R("fine_smoother", str, "BLOCK_JACOBI", "", _SOLVER_VALUES)
    R("coarse_smoother", str, "BLOCK_JACOBI", "", _SOLVER_VALUES)
    # --- Krylov params (core.cu:413-416)
    R("gmres_n_restart", int, 20, "Krylov vectors in (F)GMRES")
    R("gmres_krylov_dim", int, 0, "max Krylov dim (0: = restart)")
    R("subspace_dim_s", int, 8, "IDR subspace dim")
    R("krylov_comm", str, "CLASSIC",
      "Krylov communication mode: CLASSIC (two blocking reductions per CG "
      "iter), CA (Chronopoulos-Gear single-reduction), PIPELINED "
      "(Ghysels-Vanroose, reduction overlapped with SpMV+precond)",
      _KRYLOV_COMM)
    R("ca_residual_replace", int, 10,
      "iterations between true-residual replacement in CA/pipelined CG "
      "(0 disables; drift must never fake convergence — pipelined "
      "recurrence drift on jumpy-coefficient operators exceeds 1e-4 "
      "within ~15 iters, so the default must fire inside a typical "
      "AMG-preconditioned solve)", None, (0, 1 << 30))
    # --- direct/smoother params (core.cu:418-439)
    R("dense_lu_num_rows", int, 128)
    R("dense_lu_max_rows", int, 0)
    R("relaxation_factor", float, 0.9, "", None, (0.0, 2.0))
    R("ilu_sparsity_level", int, 0, "0:ILU0, 1:ILU1, ...")
    R("symmetric_GS", int, 0, "", _BOOL)
    R("jacobi_iters", int, 5)
    R("GS_L1_variant", int, 0, "", _BOOL)
    R("kpz_mu", int, 4)
    R("kpz_order", int, 3)
    R("chebyshev_polynomial_order", int, 5)
    R("chebyshev_lambda_estimate_mode", int, 0, "", None, (0, 3))
    R("cheby_max_lambda", float, 1.0, "", None, (0.0, 1.0e20))
    R("cheby_min_lambda", float, 0.125, "", None, (0.0, 1.0e20))
    R("kaczmarz_coloring_needed", int, 1)
    R("cf_smoothing_mode", int, 0)
    # --- AMG hierarchy (core.cu:445-467)
    R("algorithm", str, "CLASSICAL", "AMG algorithm", _ALGOS)
    R("amg_host_levels_rows", int, -1)
    R("cycle", str, "V", "", ("V", "W", "F", "CG", "CGF"))
    R("max_levels", int, 100)
    R("min_fine_rows", int, 1)
    R("min_coarse_rows", int, 2)
    R("max_coarse_iters", int, 100)
    R("coarsen_threshold", float, 1.0)
    R("presweeps", int, 1)
    R("postsweeps", int, 1)
    R("finest_sweeps", int, -1)
    R("coarsest_sweeps", int, 2)
    R("cycle_iters", int, 2, "CG/CGF cycle inner iters")
    R("structure_reuse_levels", int, 0)
    # allowed values as the reference registers them (core.cu:461-464);
    # the Vanek modes 4/5 are not registered there either
    R("error_scaling", int, 0, "", (0, 2, 3))
    R("reuse_scale", int, 0)
    R("scaling_smoother_steps", int, 2)
    R("intensive_smoothing", int, 0)
    # --- aggregation (core.cu:471-502)
    R("coarseAgenerator", str, "LOW_DEG", "", ("LOW_DEG", "THRUST", "HYBRID"))
    R("coarseAgenerator_coarse", str, "LOW_DEG", "",
      ("LOW_DEG", "THRUST", "HYBRID"))
    R("interpolator", str, "D1", "", ("D1", "D2", "MULTIPASS", "EM"))
    R("energymin_interpolator", str, "EM")
    R("energymin_selector", str, "CR")
    R("selector", str, "PMIS")
    R("aggressive_levels", int, 0)
    R("aggressive_selector", str, "DEFAULT")
    R("aggressive_interpolator", str, "MULTIPASS")
    R("handshaking_phases", int, 1, "", (1, 2))
    R("aggregation_edge_weight_component", int, 0)
    R("max_matching_iterations", int, 15)
    R("max_unassigned_percentage", float, 0.05)
    R("weight_formula", int, 0)
    R("aggregation_passes", int, 3)
    R("filter_weights", int, 0)
    R("filter_weights_alpha", float, 0.5, "", None, (0.0, 1.0))
    R("full_ghost_level", int, 0)
    R("notay_weights", int, 0)
    R("ghost_offdiag_limit", int, 0)
    R("merge_singletons", int, 1)
    R("serial_matching", int, 0)
    R("modified_handshake", int, 0)
    R("aggregate_size", int, 2)
    # --- classical strength/interp (core.cu:504-510)
    R("strength", str, "AHAT", "", ("AHAT", "ALL", "AFFINITY"))
    R("strength_threshold", float, 0.25)
    R("max_row_sum", float, 1.1)
    R("interp_truncation_factor", float, 1.1)
    R("interp_max_elements", int, -1)
    R("affinity_iterations", int, 4)
    R("affinity_vectors", int, 4)
    # --- coloring (core.cu:512-527)
    R("coloring_level", int, 1)
    R("reorder_cols_by_color", int, 0)
    R("insert_diag_while_reordering", int, 0)
    R("matrix_coloring_scheme", str, "MIN_MAX")
    R("max_num_hash", int, 7)
    R("num_colors", int, 10)
    R("max_uncolored_percentage", float, 0.15, "", None, (0.0, 1.0))
    R("initial_color", int, 0)
    R("use_bsrxmv", int, 0)
    R("fine_levels", int, -1)
    R("coloring_try_remove_last_colors", int, 0)
    R("coloring_custom_arg", str, "")
    R("print_coloring_info", int, 0)
    R("weakness_bound", int, 2**31 - 1)
    R("late_rejection", int, 0)
    R("geometric_dim", int, 2)
    # --- deprecated spmm knobs kept for config compat (core.cu:529-532)
    R("spmm_gmem_size", int, 1024)
    R("spmm_no_sort", int, 1)
    R("spmm_verbose", int, 0)
    R("spmm_max_attempts", int, 6)
    # --- outer solve control (core.cu:534-555)
    R("max_iters", int, 100)
    R("monitor_residual", int, 0, "", _BOOL)
    R("convergence", str, "ABSOLUTE",
      "<ABSOLUTE|RELATIVE_MAX|RELATIVE_INI|RELATIVE_INI_CORE|RELATIVE_MAX_CORE"
      "|COMBINED_REL_INI_ABS>")
    R("norm", str, "L2", "", _NORMS)
    R("use_scalar_norm", int, 0, "", _BOOL)
    R("tolerance", float, 1e-12)
    R("alt_rel_tolerance", float, 1e-12)
    R("verbosity_level", int, 3)
    R("solver_verbose", int, 0)
    R("print_config", int, 0)
    R("print_solve_stats", int, 0)
    R("print_grid_stats", int, 0)
    R("print_vis_data", int, 0)
    R("print_aggregation_info", int, 0)
    R("obtain_timings", int, 0)
    R("store_res_history", int, 0)
    R("convergence_analysis", int, 0)
    R("scaling", str, "NONE", "",
      ("NONE", "BINORMALIZATION", "NBINORMALIZATION", "DIAGONAL_SYMMETRIC"))
    # setup-time bandwidth-reduction reordering (reference analog: the
    # setup renumbering of matrix.cu:760-813): AUTO rescues matrices
    # that would otherwise fall off the windowed-kernel budget onto the
    # TPU gather cliff; RCM forces it; NONE disables
    R("matrix_reorder", str, "AUTO", "", ("NONE", "RCM", "AUTO"))
    # --- eigensolver params (eigensolvers/src/eigensolvers.cu:44-54)
    R("eig_solver", str, "POWER_ITERATION")
    R("eig_max_iters", int, 100)
    R("eig_tolerance", float, 1e-6)
    R("eig_shift", float, 0.0)
    R("eig_damping_factor", float, 0.85, "PageRank damping")
    R("eig_which", str, "largest", "", ("largest", "smallest", "pagerank"))
    R("eig_eigenvector", int, 0, "number of eigenvectors to extract")
    R("eig_wanted_count", int, 1)
    R("eig_eigenvector_solver", str, "default")
    # --- TPU-build extensions (no reference equivalent)
    R("tpu_matrix_dtype", str, "default",
      "override device matrix dtype <default|float64|float32|bfloat16>",
      ("default", "float64", "float32", "bfloat16"))
    # mixed precision (core/precision.py — the dDFI mixed-mode analog,
    # amgx_config.h:114-123): the AMG hierarchy's level operators,
    # smoother data and transfer packs are STORED in hierarchy_dtype
    # (arithmetic accumulates in f32); Krylov vectors, dot products and
    # residual monitoring run in krylov_dtype; tolerances below the
    # active precision's floor promote through the defect-correction
    # ladder (bf16 preconditioner -> f32 Krylov -> f64 refinement)
    R("hierarchy_dtype", str, "default",
      "storage dtype of AMG hierarchy levels from "
      "mixed_precision_from_level down (bf16 halves per-cycle HBM "
      "bytes; RAP/setup still compute in f32+)",
      ("default", "float64", "float32", "bfloat16"))
    R("krylov_dtype", str, "default",
      "device dtype of the outer Krylov loop (vectors, dots, residual "
      "monitoring); applied by the top-level solver only",
      ("default", "float64", "float32", "bfloat16"))
    R("mixed_precision_from_level", int, 0,
      "first hierarchy level stored in hierarchy_dtype (0 = the whole "
      "hierarchy incl. the fine-level smoothing pack)")
    R("tpu_ell_max_width", int, 2048,
      "max padded row width before SpMV falls back to CSR segment-sum")
    # structured telemetry (amgx_tpu/telemetry/): process-global
    # recording enabled from any solver whose config sets telemetry=1;
    # enabling also keeps the residual history so per-iteration
    # residual records can be emitted
    R("telemetry", int, 0,
      "enable structured telemetry (spans/events/metrics)", _BOOL)
    R("telemetry_path", str, "",
      "JSONL trace file; appended incrementally after setup/solve")
    R("telemetry_ring_size", int, 65536,
      "max telemetry records held in the in-memory ring buffer")
    # convergence forensics (telemetry/forensics.py): per-level cycle
    # anatomy (residual norms at the four cut points of every cycle),
    # hierarchy quality probes at setup, and the asymptotic
    # convergence-factor gauge.  Off by default: the traced cycle is
    # bit-identical to the uninstrumented one when 0 (no extra jit
    # traces); 1 adds three residual-norm SpMVs per level per cycle
    R("forensics", int, 0,
      "enable convergence forensics (cycle anatomy + hierarchy probes)",
      _BOOL)
    # setup profiler (telemetry/setup_profile.py): per-level ×
    # per-component setup phase tree with compile/transfer/memory
    # attribution.  Off by default: the setup hot path then pays one
    # attribute check per marker and is otherwise byte-identical
    R("setup_profile", int, 0,
      "enable setup attribution (phase tree, compile/transfer split, "
      "HBM watermarks)", _BOOL)
    # HBM ledger (telemetry/memledger.py): device-memory ownership
    # attribution (registry + live-array census + backend memory_stats)
    # with hbm_snapshot sampling and oom_postmortem bundles.  Off by
    # default: registration sites then pay one attribute check and
    # solve traces are byte-identical (zero-overhead contract)
    R("memledger", int, 0,
      "enable the HBM ledger (device-memory ownership attribution, "
      "hbm_snapshot sampling, OOM post-mortems)", _BOOL)
    R("memledger_sample_s", float, 0.5,
      "min seconds between hbm_snapshot samples at phase boundaries "
      "(0 = sample at every boundary)")
    # device-side setup engine (amg/device_setup/ + ops/spgemm.py):
    # pattern-keyed Galerkin RAP executables — host-symbolic once,
    # device-numeric under jit with zero recompiles on resetup.  Host
    # scipy remains the fallback for every gated case (the engine emits
    # device_setup_fallback events with the reason)
    R("device_setup", int, 1,
      "route classical/aggregation Galerkin RAP through the device "
      "SpGEMM engine (0 = host scipy only)", _BOOL)
    R("device_setup_min_rows", int, 4096,
      "fine rows below which the host Galerkin is kept (tiny levels "
      "finish faster on host than a device dispatch)")
    R("device_setup_cache_mb", int, 256,
      "schedule-byte budget of the pattern-keyed setup-plan cache "
      "(LRU evicts past it; an over-budget single plan falls back)")
    # pod-scale distributed AMG (distributed/agglomerate.py): coarse
    # levels below the per-rank row threshold agglomerate onto a
    # shrinking sub-mesh (P -> P/factor -> ... -> 1) instead of paying
    # P-way collectives on a few hundred rows per chip — AmgX's
    # shrinking-communicator consolidation (amg.cu:328-390, glue.h)
    R("dist_agglomerate_min_rows", int, 0,
      "rows per ACTIVE rank below which a distributed coarse level "
      "agglomerates onto a smaller sub-mesh (0 disables; redistribution "
      "packs are cached and replayed across resetups)")
    R("dist_agglomerate_factor", int, 2,
      "sub-mesh shrink factor per agglomeration step "
      "(P -> P/factor -> ... -> 1)", None, (2, 1 << 16))
    # serving subsystem (amgx_tpu/serve/): request-level concurrency —
    # sessions with a pattern-keyed setup cache, micro-batched multi-RHS
    # solves, bounded-queue admission control
    R("serve_workers", int, 2,
      "solve worker threads of the serving pool")
    R("serve_queue_depth", int, 64,
      "admission queue capacity; a full queue rejects with RC.REJECTED")
    R("serve_batch_window_ms", float, 2.0,
      "micro-batch aggregation window (milliseconds)")
    R("serve_max_batch", int, 16,
      "max RHS stacked into one multi-RHS solve executable")
    R("serve_cache_bytes", int, 1 << 30,
      "setup-cache byte budget bounding resident hierarchies")
    R("serve_deadline_ms", float, 0.0,
      "default per-request deadline in ms; 0 disables deadlines")
    # multi-device scale-out (serve/router.py): per-device executor
    # lanes with pattern-affinity routing, hot-pattern replication and
    # cold-pattern work stealing.  serve_lanes=1 keeps the single-lane
    # service; queue_depth/workers knobs above apply PER LANE, the
    # cache byte budget is sliced evenly across lanes
    R("serve_lanes", int, 1,
      "executor lanes (one bounded queue + dispatcher + worker pool + "
      "setup-cache slice per lane, lane i pinned to visible device i); "
      "0 = one lane per visible device")
    R("serve_replicate_frac", float, 0.75,
      "home-lane queue fraction at which a hot pattern replicates onto "
      "an idle lane (its session is rebuilt there; the shared AOT/"
      "compile caches keep the replica's compile cost at zero)")
    R("serve_steal_frac", float, 0.5,
      "queue fraction under which a lane counts as idle (replication "
      "target) and over which a cold pattern's hash-home is skipped "
      "for the least-loaded lane (the work steal)")
    # zero cold-start (utils/jaxcompat.py + serve/aot.py): persistent
    # XLA compile cache + AOT executable store, so a fresh process
    # serves its first request without paying compilation.  Both knobs
    # are directories; empty keeps the import-time env defaults
    # (AMGX_TPU_COMPILE_CACHE / AMGX_TPU_AOT_STORE)
    R("compile_cache_dir", str, "",
      "persistent XLA compilation cache directory (disk-backs every "
      "jit; an explicit value overrides the env default)")
    R("aot_store_dir", str, "",
      "AOT executable store directory: solve bodies, multi-RHS batch "
      "buckets and spgemm setup plans are serialized/loaded here")
    R("serve_warmup_max_batch", int, 0,
      "warmup() prefetches batch buckets 1,2,4,.. up to this width "
      "(0: up to serve_max_batch)")
    # live serving observability (telemetry/httpd.py + telemetry/slo.py
    # + request-lifecycle tracing in serve/): everything off by default
    # and one attribute check when disabled
    R("metrics_port", int, 0,
      "serve /metrics /healthz /statusz /debug/* on 127.0.0.1:port "
      "while the service runs (0 disables; port 0 is rejected — use "
      "SolveService.start_endpoint(0) for an ephemeral port)")
    R("slo_window_s", float, 300.0,
      "sliding window (seconds) of the SLO request-outcome reservoir")
    R("slo_latency_ms", float, 0.0,
      "per-request latency objective in ms; 0 means attainment counts "
      "OK completion + deadline only")
    R("slo_target", float, 0.99,
      "SLO attainment objective; error budget = 1 - target, burn rate "
      "= (1 - attainment) / (1 - target)")
    R("serve_profile_every", int, 0,
      "fence + profile every Nth served batch, feeding measured device "
      "seconds into the cost model (achieved-vs-roofline per pattern; "
      "0 disables)")
    # breakdown-aware solving (errors.FailureKind + solvers/recovery.py
    # + utils/faultinject.py): early in-loop breakdown detection is
    # always on; the RECOVERY ladder and fault injection are opt-in
    R("recovery_policy", str, "NONE",
      "automatic recovery ladder for failed solves: AUTO walks "
      "restart -> promote precision -> conservative smoother -> full "
      "re-setup, each attempt telemetry-audited; NONE returns the "
      "failure to the caller", ("NONE", "AUTO"))
    R("recovery_max_attempts", int, 4,
      "ladder attempt budget per failed solve (executed rungs only; "
      "inapplicable rungs are audited as skipped and burn nothing)",
      None, (0, 16))
    R("fault_inject", str, "",
      "fault-injection plan (utils/faultinject.py): "
      "'point[:key:val]*' entries separated by spaces (e.g. "
      "'values_nan:iter:3:count:1 worker_death:count:2') over the "
      "named injection points (values_nan, krylov_zero, setup_error, "
      "upload_error, oom, worker_death, aot_corrupt, halo_exchange) "
      "with count/prob/seed/iter triggers; empty (default) disarms — "
      "zero overhead and a byte-identical solve trace")
    # serve hardening (ISSUE 13): per-request execution retries, the
    # poison-pill pattern quarantine, and the per-lane circuit breaker
    R("serve_retry_max", int, 0,
      "per-request execution retry budget: a batch whose prepare/solve "
      "RAISED re-queues its requests up to this many times each, "
      "deadline permitting (0 disables; convergence failures are "
      "deterministic and never retried)")
    R("serve_quarantine_threshold", int, 3,
      "consecutive error-outcome requests of one pattern after which "
      "the pattern is quarantined — rejected at admission with "
      "RC.REJECTED instead of re-running its failing setup forever "
      "(0 disables; SolveService.unquarantine() lifts it)")
    R("serve_breaker_threshold", int, 0,
      "consecutive failed batches after which one executor lane's "
      "circuit breaker opens and the router routes around it "
      "(0 disables)")
    R("serve_breaker_cooldown_s", float, 5.0,
      "seconds a tripped lane breaker stays open before traffic is "
      "routed back (half-open probe)")


register_default_parameters()
