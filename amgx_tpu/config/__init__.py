"""Configuration system.

TPU-native re-implementation of ``AMG_Config`` (``base/include/amg_config.h``,
``base/src/amg_config.cu``): a typed, scoped parameter store populated from
``key=value`` strings (config_version 1/2), legacy ``.cfg`` files, or JSON
documents (``config_version: 2`` with nested solver objects and ``scope``
keys, e.g. ``core/configs/FGMRES_AGGREGATION.json``).

Scope semantics (mirroring ``amg_config.cu:563-631`` ``import_json_object``):
a nested JSON object under key K defines a child solver; the parent scope
records parameter K = the object's ``"solver"`` value, annotated with the
object's ``"scope"`` name; all other entries in the object are stored under
the child scope.  Lookup `get(name, scope)` checks (scope, name) then
("default", name) then the registry default.
"""
from __future__ import annotations

import json
import re
from typing import Any, Dict, Optional, Tuple

from ..errors import BadConfigurationError
from . import registry
from .registry import (ParameterDescription, all_parameters, coerce,
                       get_description, register_parameter)

__all__ = [
    "AMGConfig", "register_parameter", "get_description", "all_parameters",
    "ParameterDescription",
]

_IDENT_RE = re.compile(r"^[A-Za-z0-9_.\- ]+$")
_unnamed_scope_counter = [0]


class AMGConfig:
    """A scoped parameter store (reference: ``AMG_Config``)."""

    #: parameters only allowed in the default scope (amg_config.cu:544-548)
    _DEFAULT_SCOPE_ONLY = frozenset({
        "determinism_flag", "block_format", "separation_interior",
        "separation_exterior", "min_rows_latency_hiding",
        "fine_level_consolidation", "use_cuda_ipc_consolidation",
    })

    #: parameters that may carry a new_scope annotation (solver-valued)
    _SOLVER_PARAMS = frozenset({
        "solver", "preconditioner", "smoother", "coarse_solver",
        "fine_smoother", "coarse_smoother", "eig_solver",
        "eig_eigenvector_solver",
    })

    def __init__(self, source: "str | dict | None" = None):
        # (scope, name) -> (value, new_scope)
        self._params: Dict[Tuple[str, str], Tuple[Any, str]] = {}
        self._scopes = {"default"}
        self.config_version = 2
        self.allow_modifications = True
        if source is not None:
            self.parse(source)

    # ------------------------------------------------------------------ parse
    def parse(self, source: "str | dict") -> "AMGConfig":
        """Parse a JSON dict, JSON text, key=value string, or file path."""
        if isinstance(source, dict):
            self._import_json_object(source, outer=True)
            return self
        text = source.strip()
        if text.startswith("{"):
            return self.parse_json_string(text)
        return self.parse_string(text)

    @classmethod
    def from_file(cls, path: str) -> "AMGConfig":
        cfg = cls()
        cfg.parse_file(path)
        return cfg

    def parse_file(self, path: str) -> "AMGConfig":
        with open(path) as f:
            text = f.read()
        try:
            doc = json.loads(text)
        except ValueError:
            return self.parse_string(text)
        self._import_json_object(doc, outer=True)
        return self

    def parse_json_string(self, text: str) -> "AMGConfig":
        try:
            doc = json.loads(text)
        except ValueError as e:
            raise BadConfigurationError(f"cannot parse JSON config: {e}")
        self._import_json_object(doc, outer=True)
        return self

    def parse_string(self, params: str) -> "AMGConfig":
        """Parse ``key=value`` entries separated by ``,``, ``;`` or newlines.

        Grammar per entry (``amg_config.cu:1247-1330`` extractParamInfo):
        ``[current_scope:]name[(new_scope)]=value``.
        """
        entries = re.split(r"[,;\n]+", params)
        parsed = []
        for entry in entries:
            entry = entry.strip()
            if not entry:
                continue
            parsed.append(self._extract_param_info(entry))
        # config_version handling (amg_config.cu:172-208)
        version = 1
        if parsed and parsed[0][0] == "config_version":
            version = int(float(parsed[0][1]))
            if version not in (1, 2):
                raise BadConfigurationError(
                    f"config_version must be 1 or 2, got {version}")
            parsed = parsed[1:]
        self.config_version = version
        for name, value, cur_scope, new_scope in parsed:
            if version == 1:
                if cur_scope != "default" or new_scope != "default":
                    raise BadConfigurationError(
                        "scopes require config_version=2: "
                        f"{cur_scope}:{name}({new_scope})")
                # v1 -> v2 conversion (amg_config.cu:210-266)
                if name == "smoother_weight":
                    name = "relaxation_factor"
                elif name == "min_block_rows":
                    name = "min_coarse_rows"
                if value in ("JACOBI", "JACOBI_NO_CUSP"):
                    value = "BLOCK_JACOBI"
            self._set_entry(name, value, cur_scope, new_scope)
        return self

    @staticmethod
    def _extract_param_info(entry: str) -> Tuple[str, str, str, str]:
        if entry.count("=") != 1:
            raise BadConfigurationError(
                f"config entry must contain exactly one '=': {entry!r}")
        name, value = entry.split("=")
        value = value.strip()
        name = name.strip()
        new_scope = "default"
        m = re.match(r"^([^()]*)\(([^()]*)\)$", name)
        if m:
            name, new_scope = m.group(1).strip(), m.group(2).strip()
            if new_scope == "default" or not new_scope:
                raise BadConfigurationError(
                    f"new scope cannot be empty/default: {entry!r}")
        elif "(" in name or ")" in name:
            raise BadConfigurationError(f"unbalanced parentheses: {entry!r}")
        cur_scope = "default"
        if ":" in name:
            if name.count(":") > 1:
                raise BadConfigurationError(f"too many ':' in {entry!r}")
            cur_scope, name = (s.strip() for s in name.split(":"))
        for s in (name, cur_scope, new_scope):
            if not s or not _IDENT_RE.match(s):
                raise BadConfigurationError(f"bad identifier in {entry!r}")
        return name, value, cur_scope, new_scope

    def _import_json_object(self, obj: dict, outer: bool,
                            current_scope: str = "default"):
        current_scope = obj.get("scope", current_scope if not outer
                                else "default")
        for key, val in obj.items():
            if key in ("config_version", "scope"):
                if key == "config_version":
                    self.config_version = int(val)
                continue
            if key in ("solver", "eig_solver") and not outer:
                continue  # handled by the parent (importNamedParameter)
            if isinstance(val, dict):
                child = dict(val)
                if "scope" not in child:
                    child["scope"] = (
                        f"unnamed_solver_{_unnamed_scope_counter[0]}")
                    _unnamed_scope_counter[0] += 1
                solver_key = "eig_solver" if "eig_solver" in child else "solver"
                if solver_key not in child:
                    raise BadConfigurationError(
                        f"nested solver object {key!r} has no 'solver' entry")
                self._set_entry(key, child[solver_key], current_scope,
                                child["scope"])
                self._import_json_object(child, outer=False,
                                         current_scope=child["scope"])
            elif isinstance(val, (int, float, str)):
                self._set_entry(key, val, current_scope, "default")
            elif isinstance(val, bool):
                self._set_entry(key, int(val), current_scope, "default")
            elif isinstance(val, list):
                self._set_entry(key, val, current_scope, "default")
            else:
                raise BadConfigurationError(
                    f"cannot import parameter {key!r} of type "
                    f"{type(val).__name__}")

    # -------------------------------------------------------------- get / set
    def _set_entry(self, name: str, value: Any, current_scope: str,
                   new_scope: str):
        if new_scope != "default":
            if new_scope in self._scopes and not self.allow_modifications:
                raise BadConfigurationError(
                    f"new scope already defined: {new_scope}")
            if name not in self._SOLVER_PARAMS:
                raise BadConfigurationError(
                    "a new scope can only be associated with a solver: "
                    f"{name}({new_scope})")
            self._scopes.add(new_scope)
        if name in self._DEFAULT_SCOPE_ONLY and current_scope != "default":
            raise BadConfigurationError(
                f"parameter {name!r} can only be set in the default scope")
        value = coerce(name, value)
        self._params[(current_scope, name)] = (value, new_scope)

    def set(self, name: str, value: Any, scope: str = "default",
            new_scope: str = "default"):
        self._set_entry(name, value, scope, new_scope)

    def get(self, name: str, scope: str = "default", default: Any = None):
        """Scoped lookup: (scope, name) → ("default", name) → registry default."""
        for key in ((scope, name), ("default", name)):
            if key in self._params:
                return self._params[key][0]
        desc = get_description(name)
        if desc is not None:
            return desc.default
        if default is not None:
            return default
        raise BadConfigurationError(
            f"unknown parameter {name!r} (scope {scope!r})")

    def get_scoped(self, name: str, scope: str = "default") -> Tuple[Any, str]:
        """Return (value, new_scope) — used to allocate nested solvers.

        Reference: ``getParameter(name, &new_scope, current_scope)``.
        """
        for key in ((scope, name), ("default", name)):
            if key in self._params:
                return self._params[key]
        desc = get_description(name)
        if desc is not None:
            return desc.default, "default"
        raise BadConfigurationError(
            f"unknown parameter {name!r} (scope {scope!r})")

    def has(self, name: str, scope: str = "default") -> bool:
        return (scope, name) in self._params or ("default", name) in self._params

    def items(self):
        for (scope, name), (value, new_scope) in sorted(self._params.items()):
            yield scope, name, value, new_scope

    def stable_hash(self) -> str:
        """Stable digest of every (scope, name) → value entry — two
        configs that resolve identically hash equal regardless of the
        source text's entry order.  Keys serving sessions
        (serve/session.py) and the AOT executable store
        (serve/aot.py)."""
        import hashlib
        items = sorted((scope, name, str(v), str(ns))
                       for (scope, name), (v, ns) in self._params.items())
        return hashlib.blake2b(repr(items).encode(),
                               digest_size=12).hexdigest()

    def clone(self) -> "AMGConfig":
        cfg = AMGConfig()
        cfg._params = dict(self._params)
        cfg._scopes = set(self._scopes)
        cfg.config_version = self.config_version
        return cfg

    # ----------------------------------------------------- self-documentation
    def write_parameters_description(self) -> str:
        """Dump the registry (reference: AMGX_write_parameters_description)."""
        out = {}
        for name, desc in sorted(all_parameters().items()):
            entry = {"default": desc.default, "description": desc.description,
                     "type": desc.type.__name__}
            if desc.allowed:
                entry["allowed"] = list(desc.allowed)
            if desc.range:
                entry["range"] = list(desc.range)
            out[name] = entry
        return json.dumps(out, indent=2)

    def __repr__(self):
        n = len(self._params)
        return f"AMGConfig({n} params, scopes={sorted(self._scopes)})"
