"""Coarse-level agglomeration onto shrinking sub-meshes.

Reference: AmgX never lets a coarse grid strand the full communicator —
below a per-rank row threshold it consolidates shrinking levels onto
fewer ranks (``amg.cu:328-390`` + ``distributed/glue.h:73-263``, the
``coarsest_sweeps``-style consolidation of PAPER.md §2.11) so a level
with a few hundred rows per chip stops paying P-way collectives.

This module is the planner half of that story for the TPU mesh:

* :func:`plan_submesh` — pick the active sub-mesh size
  (P → P/factor → … → 1) for a coarse level's row count under the
  ``dist_agglomerate_min_rows`` threshold;
* :func:`plan_for` — build (or reuse) an :class:`AgglomPlan`: the
  agglomerated row offsets plus explicit **redistribution packs** — per
  destination rank, the ordered ``(src rank, lo, hi)`` local row ranges
  it receives.  Plans are cached by ``(src offsets, threshold, factor)``
  so a values-only resetup replays the SAME packs (zero re-planning,
  the ``structure_reuse`` analog for the mesh layout);
* :func:`redistribute_blocks` — apply a plan's packs to per-rank row
  blocks (host CSR; in-process the "send" is an array slice, multi-host
  each pack entry IS one point-to-point message).

The hierarchy records the resulting sub-mesh in ``AMGLevel
.submesh_parts``; grid-transfer packs (classical P/R, aggregation maps)
are built against the agglomerated offsets, so cycles route correction
transfers through the same redistribution automatically — no extra
collective is ever issued for the migration itself.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Tuple

import numpy as np
import scipy.sparse as sp


@dataclasses.dataclass(frozen=True)
class RedistPack:
    """One destination rank's receive schedule: ordered local row
    ranges of the source ranks that land on ``dst`` (rank-major, so the
    concatenation is exactly the destination's new row block)."""
    dst: int
    srcs: Tuple[Tuple[int, int, int], ...]   # (src rank, local lo, hi)


@dataclasses.dataclass(frozen=True)
class AgglomPlan:
    """A frozen agglomeration decision for one coarse level layout."""
    n_parts: int
    p_active: int                  # active ranks after agglomeration
    src_offsets: Tuple[int, ...]   # balanced per-rank row offsets (P+1)
    dst_offsets: Tuple[int, ...]   # agglomerated offsets (P+1; tail flat)
    packs: Tuple[RedistPack, ...]  # one per destination rank

    @property
    def n_rows(self) -> int:
        return int(self.src_offsets[-1])

    @property
    def replicated(self) -> bool:
        """Fully agglomerated: the level lives on one rank, so the
        coarse solve stops being a P-way broadcast."""
        return self.p_active == 1


def active_parts(offsets) -> int:
    """Ranks that actually own rows under ``offsets`` (agglomerated
    levels keep the P+1 offset vector but flatten its tail)."""
    return int(np.sum(np.diff(np.asarray(offsets)) > 0))


def plan_submesh(n_rows: int, n_parts: int, min_rows: int,
                 factor: int = 2) -> int:
    """Active sub-mesh size for ``n_rows`` total rows: shrink the P
    active ranks by ``factor`` until every active rank holds at least
    ``min_rows`` rows (or one rank remains)."""
    p = max(int(n_parts), 1)
    factor = max(int(factor), 2)
    min_rows = max(int(min_rows), 1)
    while p > 1 and n_rows // p < min_rows:
        p = max(1, p // factor)
    return p


def _build_packs(src_offsets: np.ndarray,
                 dst_offsets: np.ndarray) -> Tuple[RedistPack, ...]:
    """Per-destination receive schedules mapping the global row range
    [dst[q], dst[q+1]) onto (src rank, local lo, hi) slices."""
    n_parts = len(src_offsets) - 1
    packs = []
    for q in range(n_parts):
        lo, hi = int(dst_offsets[q]), int(dst_offsets[q + 1])
        srcs: List[Tuple[int, int, int]] = []
        if hi > lo:
            for s in range(n_parts):
                slo, shi = int(src_offsets[s]), int(src_offsets[s + 1])
                a, b = max(lo, slo), min(hi, shi)
                if b > a:
                    srcs.append((s, a - slo, b - slo))
        packs.append(RedistPack(dst=q, srcs=tuple(srcs)))
    return tuple(packs)


def build_agglomeration(src_offsets, min_rows: int, factor: int = 2
                        ) -> Optional[AgglomPlan]:
    """Plan the agglomeration of a level laid out by ``src_offsets``;
    None when the level already satisfies the threshold (or cannot
    shrink further)."""
    src = np.asarray(src_offsets, dtype=np.int64)
    n_parts = len(src) - 1
    n_rows = int(src[-1])
    act = active_parts(src)
    if n_rows <= 0 or act <= 1 or min_rows <= 0:
        return None
    p_active = plan_submesh(n_rows, act, min_rows, factor)
    if p_active >= act:
        return None
    per = -(-n_rows // p_active)
    dst = np.concatenate([
        np.minimum(np.arange(p_active + 1, dtype=np.int64) * per, n_rows),
        np.full(n_parts - p_active, n_rows, dtype=np.int64)])
    return AgglomPlan(
        n_parts=n_parts, p_active=p_active,
        src_offsets=tuple(int(o) for o in src),
        dst_offsets=tuple(int(o) for o in dst),
        packs=_build_packs(src, dst))


def redistribute_blocks(blocks, plan: AgglomPlan) -> list:
    """Apply the plan's redistribution packs to per-rank row blocks
    (CSR, any column space).  Each destination rank's new block is the
    rank-major concatenation of its pack's source slices — the
    in-process form of the neighbour-wise migration messages."""
    n_cols = None
    for b in blocks:
        if b is not None:
            n_cols = b.shape[1]
            break
    out = []
    for pack in plan.packs:
        pieces = [sp.csr_matrix(blocks[s][lo:hi])
                  for (s, lo, hi) in pack.srcs]
        if pieces:
            out.append(sp.csr_matrix(sp.vstack(pieces)))
        else:
            out.append(sp.csr_matrix((0, n_cols or 0)))
    return out


# ----------------------------------------------------------- plan cache
#: (src_offsets, min_rows, factor) → AgglomPlan | None; a values-only
#: resetup re-plans the SAME level layouts, so the cache turns the
#: replay into pure lookups (packs reused, zero re-planning)
_PLANS: dict = {}
_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0}


def plan_for(src_offsets, min_rows: int, factor: int = 2,
             level=None) -> Optional[AgglomPlan]:
    """Cached :func:`build_agglomeration` + telemetry: the single entry
    point the hierarchy paths use.  Emits one ``dist_agglomerate``
    event (and bumps ``amgx_dist_agglomerate_total``) per planned
    agglomeration, with ``reused`` distinguishing a cache replay."""
    key = (tuple(int(o) for o in src_offsets), int(min_rows),
           int(factor))
    with _LOCK:
        if key in _PLANS:
            _STATS["hits"] += 1
            plan, reused = _PLANS[key], True
        else:
            plan, reused = None, False
    if not reused:
        plan = build_agglomeration(src_offsets, min_rows, factor)
        with _LOCK:
            _STATS["misses"] += 1
            _PLANS[key] = plan
            while len(_PLANS) > 512:
                _PLANS.pop(next(iter(_PLANS)))
    if plan is not None:
        from .. import telemetry
        if telemetry.is_enabled():
            telemetry.counter_inc("amgx_dist_agglomerate_total",
                                  reused=int(reused))
            telemetry.event(
                "dist_agglomerate", level=level,
                from_parts=active_parts(plan.src_offsets),
                to_parts=int(plan.p_active), rows=int(plan.n_rows),
                rows_per_part=int(plan.n_rows // plan.p_active),
                replicated=bool(plan.replicated), reused=bool(reused))
    return plan


def agglomeration_stats() -> dict:
    with _LOCK:
        return {"plans": len(_PLANS), "hits": int(_STATS["hits"]),
                "misses": int(_STATS["misses"])}


def reset_plans() -> None:
    """Drop the plan cache (test isolation)."""
    with _LOCK:
        _PLANS.clear()
        _STATS["hits"] = _STATS["misses"] = 0
