"""Sharded matrix pack + distributed SpMV.

TPU-native equivalent of the reference's distributed SpMV with latency
hiding (``base/src/multiply.cu:75-196``, SURVEY §3.4):

    exchange_halo_async → SpMV on INTERIOR rows → wait → SpMV on BOUNDARY

Here the halo exchange is a mesh collective inside ``jax.shard_map``:
``all_gather`` of the fixed-size B2L send buffers (general partitions) or a
``ppermute`` neighbour schedule (1D stencil partitions).  XLA overlaps the
collective with the interior gather/multiply the way the reference overlaps
MPI with the interior kernel — without hand-rolled streams.

Vectors are flat (P·n_loc,) arrays sharded over mesh axis ``p`` with a
``NamedSharding``; everything outside SpMV (dots, axpys, Krylov updates) is
plain jnp code that GSPMD partitions automatically, inserting ``psum`` for
reductions — the TPU analog of the reference's MPI all-reduce dots
(SURVEY §3.3 "Every dot product in Krylov is an MPI all-reduce").

Padding invariant: shards are equal-sized; padding rows are identity rows
whose rhs/solution entries are exactly zero through every cycle operation,
so padded entries never pollute dots or norms.
"""
from __future__ import annotations

import dataclasses
import functools
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..errors import BadParametersError
from ..utils.jaxcompat import shard_map as _shard_map
from .partition import Partition, build_partition


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["cols", "vals", "diag", "send_idx", "halo_src",
                 "bnd_rows", "send_idx2", "halo_src2", "win_blocks",
                 "win_codes", "win_vals"],
    meta_fields=["n_global", "n_parts", "n_loc", "ell_width", "block_dim",
                 "axis", "dists", "dists2", "offsets", "win_tile",
                 "mesh", "n_loc_cols", "col_offsets", "send_counts",
                 "halo_counts", "halo_counts2", "bnd_counts"],
)
@dataclasses.dataclass(frozen=True)
class ShardedMatrix:
    """Frozen sharded ELL pack (leading axis = mesh axis ``p``).

    ``cols`` index into the per-shard extended vector
    ``[x_local (n_loc) | halo (H)]``.  ``bnd_rows`` lists each shard's
    boundary rows (padded with the trash slot ``n_loc``) so SpMV can
    overlap the halo exchange with the interior compute; ``send_idx2`` /
    ``halo_src2`` are the ring-2 B2L maps (``distributed_manager.h:
    284-305`` per-ring maps).
    """

    cols: jax.Array       # (P, n_loc, K) int32
    vals: jax.Array       # (P, n_loc, K)
    diag: jax.Array       # (P·n_loc,) flat, sharded like vectors
    send_idx: jax.Array   # (P, B) int32 — ring-1 B2L gather map
    halo_src: jax.Array   # (P, H) int32 — d_slot·B + pos into recv bufs
    bnd_rows: jax.Array   # (P, Bd) int32 — boundary rows, pad → n_loc
    send_idx2: jax.Array  # (P, B2) int32 — ring-2 B2L gather map
    halo_src2: jax.Array  # (P, H2) int32
    n_global: int
    n_parts: int
    n_loc: int
    ell_width: int
    block_dim: int
    axis: str             # mesh axis name
    dists: tuple          # ring-1 rank distances (owner − p) mod P
    dists2: tuple         # ring-2 rank distances
    offsets: tuple        # (P+1,) real row offsets per rank
    #: per-shard windowed-ELL pack (ops/pallas_ell.py) for the interior
    #: SpMV on TPU backends; None when some shard exceeds the window
    #: budget (local compute then falls back to the XLA gather)
    win_blocks: Optional[jax.Array] = None   # (P, n_tiles·B) int32
    #: int16 (codes < 5120 by construction — halves transfer bytes);
    #: _ell_window_call widens to int32 at trace time for the kernel
    win_codes: Optional[jax.Array] = None    # (P, n_pad·K) int16
    win_vals: Optional[jax.Array] = None     # (P, n_pad·K)
    win_tile: int = 0
    #: static (meta) so traced packs keep it — tracers have no .sharding
    mesh: Mesh = None
    #: rectangular operators (classical P/R): the COLUMN space has its
    #: own partition — halo exchange runs in that space; None ⇒ square
    n_loc_cols: Optional[int] = None
    col_offsets: Optional[tuple] = None
    #: per-rank UNPADDED map sizes (static, from the Partition) — the
    #: telemetry cost model reads these so halo byte counters report
    #: both wire bytes (padded) and useful entries (analytic boundary
    #: sizes); None on packs built before instrumentation cared
    send_counts: Optional[tuple] = None
    halo_counts: Optional[tuple] = None
    halo_counts2: Optional[tuple] = None
    bnd_counts: Optional[tuple] = None

    @property
    def n(self) -> int:
        """Padded global scalar size (P · n_loc · b) — vector length."""
        return self.n_parts * self.n_loc * self.block_dim

    @property
    def n_rows(self) -> int:
        """Padded global (block-)row count."""
        return self.n_parts * self.n_loc

    @property
    def n_cols(self) -> int:
        """Padded global COLUMN size."""
        return self.n_parts * (self.n_loc_cols
                               if self.n_loc_cols is not None
                               else self.n_loc)

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def fmt(self):
        return "sharded-ell"


def pad_map(offsets: np.ndarray, n_loc: int) -> np.ndarray:
    """real global row id → padded id (rank p, local l → p·n_loc + l)."""
    n_parts = len(offsets) - 1
    out = np.empty(offsets[-1], dtype=np.int64)
    for p in range(n_parts):
        lo, hi = offsets[p], offsets[p + 1]
        out[lo:hi] = p * n_loc + np.arange(hi - lo)
    return out


def embed_padded(M: sp.csr_matrix, row_offsets, row_nloc,
                 col_offsets, col_nloc) -> sp.csr_matrix:
    """Re-index a real-sized sparse matrix into padded coordinates (pad
    rows/cols stay empty).  Used to embed classical P/R into the padded
    vector spaces."""
    M = sp.coo_matrix(M)
    rmap = pad_map(np.asarray(row_offsets), row_nloc)
    cmap = pad_map(np.asarray(col_offsets), col_nloc)
    n_parts = len(row_offsets) - 1
    shape = (n_parts * row_nloc, (len(col_offsets) - 1) * col_nloc)
    return sp.csr_matrix((M.data, (rmap[M.row], cmap[M.col])), shape=shape)


def make_mesh(n_devices: Optional[int] = None, axis: str = "p") -> Mesh:
    """Build a 1D device mesh in Auto (GSPMD) mode — collectives for the
    Krylov-level algebra are inserted by the partitioner; only the SpMV
    halo exchange is hand-scheduled via shard_map."""
    from ..utils.jaxcompat import axis_type_auto
    devs = jax.devices()
    n = n_devices or len(devs)
    auto = axis_type_auto()
    if auto is None:           # pre-sharding-in-types jax: always GSPMD
        return Mesh(np.array(devs[:n]), (axis,))
    return Mesh(np.array(devs[:n]), (axis,), axis_types=(auto,))


def _auto_mesh(mesh: Mesh) -> Mesh:
    """Coerce a mesh to Auto axis types (GSPMD) — explicit sharding-in-types
    meshes would demand out_sharding annotations on every contraction."""
    from ..utils.jaxcompat import axis_type_auto
    auto = axis_type_auto()
    if auto is None or getattr(mesh, "axis_types", None) is None:
        return mesh            # pre-sharding-in-types jax: already auto
    if all(t == auto for t in mesh.axis_types):
        return mesh
    return Mesh(mesh.devices, mesh.axis_names,
                axis_types=(auto,) * len(mesh.axis_names))


def _ml_register_pack(pack: "ShardedMatrix", kind: str) -> "ShardedMatrix":
    """HBM-ledger registration of a freshly built sharded pack (owner
    ``amgx/dist/<kind>`` — device values plus halo/B2L exchange maps).
    A weakref finalizer releases the ledger entry when the pack dies,
    so the builders need no explicit teardown hook; never raises."""
    from .. import telemetry
    ml = telemetry.memledger
    if not ml.is_enabled():
        return pack
    tok = None
    try:
        tok = ml.register(ml.owner_name("dist", kind), pack)
        if tok is not None:
            weakref.finalize(pack, ml.release, tok)
    except Exception:
        ml.release(tok)
    return pack


def shard_matrix(A: sp.csr_matrix, mesh: Mesh, axis: str = "p",
                 dtype=None, offsets=None, n_loc: Optional[int] = None,
                 partition: Optional[Partition] = None) -> ShardedMatrix:
    """Pack a global CSR matrix into a ShardedMatrix laid out over ``mesh``
    (convenience wrapper: splits into per-rank row blocks first)."""
    A = sp.csr_matrix(A)
    mesh = _auto_mesh(mesh)
    n_parts = mesh.shape[axis]
    if partition is not None:
        offsets = np.asarray(partition.offsets)
    elif offsets is None:
        n = A.shape[0]
        nl = -(-n // n_parts)
        offsets = np.minimum(np.arange(n_parts + 1) * nl, n)
    else:
        offsets = np.asarray(offsets)
    from .partition import split_row_blocks
    return shard_matrix_from_blocks(split_row_blocks(A, offsets), offsets,
                                    mesh, axis=axis, dtype=dtype,
                                    n_loc=n_loc, partition=partition)


def shard_matrix_from_blocks(blocks, offsets, mesh: Mesh, axis: str = "p",
                             dtype=None, n_loc: Optional[int] = None,
                             partition: Optional[Partition] = None,
                             col_offsets=None,
                             n_loc_cols: Optional[int] = None
                             ) -> ShardedMatrix:
    """Pack per-rank row blocks (global column ids) into a ShardedMatrix.

    The scalable-setup entry point — no step materialises a global matrix
    (``AMGX_matrix_upload_distributed`` semantics).  Mirrors
    ``DistributedManager::loadDistributedMatrix``
    (``distributed_manager.h:1815``): build B2L maps from per-rank data
    (``distributed_arranger.h:85-140``), renumber columns to
    [local | halo] slots, pad shards to equal size with identity rows.

    ``col_offsets``/``n_loc_cols``: rectangular operators (classical P/R)
    whose column space is partitioned differently — halo maps then live
    in the column space, padding rows are zero rows, and the diagonal is
    meaningless (zeros).
    """
    from .partition import build_partition_from_blocks
    blocks = [sp.csr_matrix(b) for b in blocks]
    offsets = np.asarray(offsets)
    rect = col_offsets is not None
    dtype = np.dtype(dtype or blocks[0].dtype)
    mesh = _auto_mesh(mesh)
    n_parts = mesh.shape[axis]
    if len(blocks) != n_parts:
        raise BadParametersError(
            f"{len(blocks)} row blocks for a {n_parts}-way mesh axis")
    part = partition or build_partition_from_blocks(
        blocks, offsets, n_rings=1 if rect else 2,
        col_offsets=col_offsets)
    if n_loc is not None and n_loc > part.n_loc:
        part = dataclasses.replace(part, n_loc=n_loc)
    n_loc = part.n_loc
    if rect:
        col_offsets = np.asarray(col_offsets)
        nlc = n_loc_cols or int(np.max(np.diff(col_offsets)))
    else:
        col_offsets = part.offsets
        nlc = n_loc
    K = max((int(np.diff(b.indptr).max()) if b.nnz else 1
             for b in blocks), default=1)

    cols = np.zeros((n_parts, n_loc, K), dtype=np.int32)
    vals = np.zeros((n_parts, n_loc, K), dtype=dtype)
    diag = np.zeros((n_parts, n_loc), dtype=dtype)
    for p in range(n_parts):
        lo, hi = part.offsets[p], part.offsets[p + 1]
        clo, chi = col_offsets[p], col_offsets[p + 1]
        nl = hi - lo
        sub = blocks[p]
        sub.sort_indices()
        ext = part.halo_global[p]
        gcols = sub.indices.astype(np.int64)
        local = (gcols >= clo) & (gcols < chi)
        lcols = np.where(local, gcols - clo, 0)
        if len(ext):
            halo_slot = np.searchsorted(ext, gcols)
            halo_slot = np.minimum(halo_slot, len(ext) - 1)
            lcols = np.where(local, lcols, nlc + halo_slot)
        deg = np.diff(sub.indptr)
        rr = np.repeat(np.arange(nl), deg)
        pos = np.arange(len(gcols)) - np.repeat(sub.indptr[:-1], deg)
        cols[p, rr, pos] = lcols
        vals[p, rr, pos] = sub.data
        if not rect:
            on_diag = gcols == rr + lo
            # add (not assign): duplicate diagonal entries are legal CSR
            # input and the ELL pack sums them too
            np.add.at(diag[p], rr[on_diag], sub.data[on_diag])
            # identity padding rows (zero rows in rectangular packs: a
            # padded output entry must stay exactly zero)
            r = np.arange(nl, n_loc)
            cols[p, r, 0] = r
            vals[p, r, 0] = 1.0
            diag[p, r] = 1.0

    # per-shard windowed-ELL pack for the TPU interior SpMV (columns
    # index the [local | halo] extended space — rectangular is fine);
    # all shards must fit the window budget or none carry it
    win_blocks = win_codes = win_vals = None
    win_tile = 0
    from ..ops.pallas_ell import _INTERPRET
    mesh_is_tpu = mesh.devices.flat[0].platform == "tpu"
    if np.dtype(dtype) == np.float32 and K <= 160 and \
            (mesh_is_tpu or _INTERPRET):
        from ..ops.pallas_ell import ell_window_pack, win_vals_pack
        packs = [ell_window_pack(cols[p]) for p in range(n_parts)]
        if all(pk is not None for pk in packs):
            win_tile = packs[0][2]
            Bmax = max(pk[0].shape[1] for pk in packs)
            nt = packs[0][0].shape[0]
            wb = np.zeros((n_parts, nt * Bmax), dtype=np.int32)
            for p, (bids, _, _) in enumerate(packs):
                padded = np.zeros((nt, Bmax), dtype=np.int32)
                padded[:, : bids.shape[1]] = bids
                wb[p] = padded.reshape(-1)
            win_blocks = wb
            win_codes = np.stack([pk[1][0] for pk in packs])
            win_vals = np.stack(
                [win_vals_pack(vals[p], win_tile)[0]
                 for p in range(n_parts)])

    spec3 = NamedSharding(mesh, P(axis, None, None))
    spec2 = NamedSharding(mesh, P(axis, None))
    spec1 = NamedSharding(mesh, P(axis))
    if len(part.rings) > 1:
        r2 = part.rings[1]
    else:                     # rectangular packs carry no ring 2
        from .partition import Ring
        r2 = Ring(dists=(1,),
                  send_idx=np.zeros((n_parts, 1), np.int32),
                  send_count=np.zeros(n_parts, np.int32),
                  halo_src=np.zeros((n_parts, 1), np.int32),
                  halo_count=np.zeros(n_parts, np.int32),
                  halo_global=[np.zeros(0, np.int64)] * n_parts)
    return _ml_register_pack(ShardedMatrix(
        cols=jax.device_put(cols, spec3),
        vals=jax.device_put(vals, spec3),
        diag=jax.device_put(diag.reshape(-1), spec1),
        send_idx=jax.device_put(part.send_idx, spec2),
        halo_src=jax.device_put(part.halo_src, spec2),
        bnd_rows=jax.device_put(part.bnd_rows, spec2),
        send_idx2=jax.device_put(r2.send_idx, spec2),
        halo_src2=jax.device_put(r2.halo_src, spec2),
        win_blocks=None if win_blocks is None else
        jax.device_put(win_blocks, spec2),
        win_codes=None if win_codes is None else
        jax.device_put(win_codes, spec2),
        win_vals=None if win_vals is None else
        jax.device_put(win_vals, spec2),
        win_tile=win_tile,
        n_global=part.n_global, n_parts=n_parts, n_loc=n_loc,
        ell_width=K, block_dim=1, axis=axis,
        dists=part.dists, dists2=r2.dists,
        offsets=tuple(int(o) for o in part.offsets), mesh=mesh,
        n_loc_cols=nlc if rect else None,
        col_offsets=tuple(int(o) for o in col_offsets) if rect else None,
        send_counts=tuple(int(c) for c in part.send_count),
        halo_counts=tuple(int(c) for c in part.halo_count),
        halo_counts2=tuple(int(c) for c in r2.halo_count),
        bnd_counts=tuple(int(c) for c in part.bnd_count)),
        "rect_pack" if rect else "shard_pack")


def shard_block_matrix(host, block_dim: int, mesh: Mesh, axis: str = "p",
                       dtype=None, offsets=None,
                       n_loc: Optional[int] = None) -> ShardedMatrix:
    """Pack a BLOCK (b×b) matrix into a ShardedMatrix: vals
    (P, n_loc, K, b, b), cols over the [local | halo] BLOCK space, flat
    (P·n_loc·b) vectors — the reference's uniform block-CSR distribution
    (``matrix.h:87-220``).  The halo machinery runs unchanged on the
    BLOCK graph (an index CSR whose data point into the bsr blocks)."""
    from .partition import build_partition_from_blocks, split_row_blocks
    b = int(block_dim)
    bsr = host if isinstance(host, sp.bsr_matrix) else sp.bsr_matrix(
        host, blocksize=(b, b))
    bsr.sort_indices()
    dtype = np.dtype(dtype or bsr.dtype)
    mesh = _auto_mesh(mesh)
    n_parts = mesh.shape[axis]
    nb = bsr.shape[0] // b
    # block-graph index CSR: entry (I, J) stores its block id
    ind = sp.csr_matrix(
        (np.arange(len(bsr.indices), dtype=np.int64) + 1, bsr.indices,
         bsr.indptr), shape=(nb, bsr.shape[1] // b))
    if offsets is None:
        nl = -(-nb // n_parts)
        offsets = np.minimum(np.arange(n_parts + 1) * nl, nb)
    else:
        offsets = np.asarray(offsets)
    ind_blocks = split_row_blocks(ind, offsets)
    part = build_partition_from_blocks(ind_blocks, offsets, n_rings=2)
    if n_loc is not None and n_loc > part.n_loc:
        part = dataclasses.replace(part, n_loc=n_loc)
    n_loc = part.n_loc
    K = max((int(np.diff(blk.indptr).max()) if blk.nnz else 1
             for blk in ind_blocks), default=1)

    cols = np.zeros((n_parts, n_loc, K), dtype=np.int32)
    vals = np.zeros((n_parts, n_loc, K, b, b), dtype=dtype)
    diag = np.zeros((n_parts, n_loc, b, b), dtype=dtype)
    eye = np.eye(b, dtype=dtype)
    for p in range(n_parts):
        lo, hi = part.offsets[p], part.offsets[p + 1]
        nl = hi - lo
        sub = ind_blocks[p]
        sub.sort_indices()
        ext = part.halo_global[p]
        gcols = sub.indices.astype(np.int64)
        local = (gcols >= lo) & (gcols < hi)
        lcols = np.where(local, gcols - lo, 0)
        if len(ext):
            halo_slot = np.searchsorted(ext, gcols)
            halo_slot = np.minimum(halo_slot, len(ext) - 1)
            lcols = np.where(local, lcols, n_loc + halo_slot)
        deg = np.diff(sub.indptr)
        rr = np.repeat(np.arange(nl), deg)
        pos = np.arange(len(gcols)) - np.repeat(sub.indptr[:-1], deg)
        cols[p, rr, pos] = lcols
        vals[p, rr, pos] = bsr.data[sub.data - 1]
        on_diag = gcols == rr + lo
        diag[p, rr[on_diag]] += bsr.data[sub.data[on_diag] - 1]
        # identity padding rows
        r = np.arange(nl, n_loc)
        cols[p, r, 0] = r
        vals[p, r, 0] = eye
        diag[p, r] = eye

    spec5 = NamedSharding(mesh, P(axis, None, None, None, None))
    spec3 = NamedSharding(mesh, P(axis, None, None))
    spec2 = NamedSharding(mesh, P(axis, None))
    spec1 = NamedSharding(mesh, P(axis))
    r2 = part.rings[1]
    return _ml_register_pack(ShardedMatrix(
        cols=jax.device_put(cols, spec3),
        vals=jax.device_put(vals, spec5),
        diag=jax.device_put(diag.reshape(-1, b, b), spec1),
        send_idx=jax.device_put(part.send_idx, spec2),
        halo_src=jax.device_put(part.halo_src, spec2),
        bnd_rows=jax.device_put(part.bnd_rows, spec2),
        send_idx2=jax.device_put(r2.send_idx, spec2),
        halo_src2=jax.device_put(r2.halo_src, spec2),
        n_global=part.n_global, n_parts=n_parts, n_loc=n_loc,
        ell_width=K, block_dim=b, axis=axis,
        dists=part.dists, dists2=r2.dists,
        offsets=tuple(int(o) for o in part.offsets), mesh=mesh,
        send_counts=tuple(int(c) for c in part.send_count),
        halo_counts=tuple(int(c) for c in part.halo_count),
        halo_counts2=tuple(int(c) for c in r2.halo_count),
        bnd_counts=tuple(int(c) for c in part.bnd_count)),
        "block_pack")


# --------------------------------------------------------------------------
# distributed SpMV
# --------------------------------------------------------------------------
def uses_all_gather(dists: tuple, n_parts: int) -> bool:
    """THE exchange-path predicate: dense link sets fall back from the
    per-distance ppermute schedule to one all_gather.  Single authority
    shared by the real exchange (:func:`_exchange`), the telemetry path
    label (:func:`_tel_exchange`) and the cost model
    (``telemetry.costmodel.halo_wire_bytes``) — three copies would
    silently drift."""
    return n_parts > 1 and len(dists) >= n_parts - 1


def _tel_exchange(A: "ShardedMatrix", ring: int, op: str):
    """Halo-exchange telemetry (one attribute check when off).

    Like the SpMV dispatch counters (ops/spmv.py), this fires HOST-side
    at dispatch/trace time — the compiled program is unchanged; under
    ``jax.jit`` one traced exchange counts once per compilation, which
    is exactly the static cost the comms PRs are judged by.  Wire bytes
    count the PADDED send buffers every shard actually puts on the ICI
    (one per ppermute hop, or P−1 under the all_gather fallback);
    entries count the useful (analytic-boundary-size) halo values.
    """
    from ..telemetry import recorder as _trecorder
    if not _trecorder.is_enabled():
        return
    from ..telemetry import costmodel as _tcost
    from ..telemetry import metrics as _tmetrics
    dists = A.dists if ring == 1 else A.dists2
    path = "all_gather" if uses_all_gather(dists, A.n_parts) \
        else "ppermute"
    wire = _tcost.halo_wire_bytes(A, ring)
    entries = _tcost.halo_entries(A, ring)
    send_idx = A.send_idx if ring == 1 else A.send_idx2
    # the ACTUAL collective count XLA executes: the all_gather fallback
    # collapses the whole distance schedule into ONE collective —
    # reporting len(dists) there overstated what is on the wire program
    # (the wire BYTES still count every (P-1)-buffer the gather moves)
    n_coll = 1 if path == "all_gather" else len(dists)
    _tmetrics.counter_inc("amgx_halo_exchange_total", ring=ring, op=op,
                          path=path)
    _tmetrics.counter_inc("amgx_halo_bytes_total", wire, ring=ring,
                          op=op)
    _tmetrics.counter_inc("amgx_halo_entries_total", entries, ring=ring,
                          op=op)
    _tmetrics.gauge_set("amgx_dist_ring_hops", n_coll, ring=ring)
    counts = A.halo_counts if ring == 1 else A.halo_counts2
    _trecorder.event(
        "halo_exchange", op=op, ring=ring, path=path,
        n_parts=A.n_parts, hops=n_coll,
        send_buf=int(send_idx.shape[1]),
        wire_bytes=int(wire), entries=int(entries),
        per_rank_entries=None if counts is None else list(counts))


def _tel_dist_spmv(A: "ShardedMatrix"):
    """dist_spmv dispatch telemetry: the halo-exchange counters plus
    per-device boundary/halo gauges (label ``device`` = shard index —
    the SPMD program is identical per device; the per-rank numbers come
    from the partition's static counts).  The interior-path choice is
    carried by the dist_spmv span attrs."""
    from ..telemetry import recorder as _trecorder
    if not _trecorder.is_enabled():
        return
    from ..telemetry import metrics as _tmetrics
    # NOTE: the dispatch counter (pack="sharded") is ops/spmv.py's job —
    # incrementing it again here would double-count every distributed
    # SpMV; the interior-path choice rides the span attrs instead
    _tel_exchange(A, 1, "dist_spmv")
    if A.bnd_counts is None:
        return
    offs = A.offsets
    for p in range(A.n_parts):
        rows = max((offs[p + 1] - offs[p]) if offs is not None
                   else A.n_loc, 1)
        _tmetrics.gauge_set("amgx_dist_boundary_fraction",
                            A.bnd_counts[p] / rows, device=p)
        if A.halo_counts is not None:
            _tmetrics.gauge_set("amgx_dist_halo_entries",
                                A.halo_counts[p], device=p)


def _exchange(buf: jax.Array, dists: tuple, axis: str,
              n_parts: int) -> jax.Array:
    """Distance-wise neighbour exchange: rank p receives, for each d in
    ``dists``, rank (p+d) mod P's send buffer — one ``ppermute`` per
    distance (neighbour-wise like ``comms_mpi_hostbuffer_stream.cu:
    354-523``, O(D·B) instead of the all-gather's O(P·B)).  Falls back to
    one all_gather when the link set is dense."""
    if n_parts == 1:
        return buf
    if uses_all_gather(dists, n_parts):
        all_bufs = jax.lax.all_gather(buf, axis)        # (P, B[, b])
        i = jax.lax.axis_index(axis)
        order = (i + jnp.asarray(dists, jnp.int32)) % n_parts
        # keep trailing block components (b×b packs send (B, b) bufs)
        return all_bufs[order].reshape((-1,) + buf.shape[1:])
    parts = []
    for d in dists:
        # source s delivers to (s − d) mod P ⇒ rank p receives from p+d
        perm = [(s, (s - d) % n_parts) for s in range(n_parts)]
        parts.append(jax.lax.ppermute(buf, axis, perm))
    return jnp.concatenate(parts)


def exchange_halo(A: ShardedMatrix, x: jax.Array, ring: int = 1
                  ) -> jax.Array:
    """Gather the ring-``ring`` halo values of sharded ``x``: returns a
    (P, H_ring) array whose row p holds the values of
    ``partition.rings[ring-1].halo_global[p]`` (reference
    ``exchange_halo``, rings machinery of ``vector.h:38-51``)."""
    if ring not in (1, 2):
        raise BadParametersError(f"halo ring must be 1 or 2, got {ring}")
    from ..telemetry import recorder as _trecorder
    _tel_exchange(A, ring, "exchange_halo")
    # span over the host-level call: real wall time when eager, the
    # dispatch/trace cost under jit (the executed collective shows up in
    # the device profile, not the host ring)
    sid = _trecorder.span_begin("exchange_halo",
                                {"ring": ring, "n_parts": A.n_parts})
    try:
        axis = A.axis
        send_idx = A.send_idx if ring == 1 else A.send_idx2
        halo_src = A.halo_src if ring == 1 else A.halo_src2
        dists = A.dists if ring == 1 else A.dists2

        def local(si, hs, xl):
            buf = xl[si[0]]
            got = _exchange(buf, dists, axis, A.n_parts)
            return got[hs[0]][None]

        from ..telemetry import scopes as _tscopes
        with _tscopes.scope("dist", "halo_exchange"):
            return _shard_map(
                local, mesh=A.mesh,
                in_specs=(P(axis, None), P(axis, None), P(axis)),
                out_specs=P(axis, None),
            )(send_idx, halo_src, x)
    finally:
        _trecorder.span_end(sid, "exchange_halo")


def dist_spmv(A: ShardedMatrix, x: jax.Array) -> jax.Array:
    """y = A·x for a flat sharded x of length P·n_loc (call under jit).

    Interior/boundary latency hiding (``multiply.cu:113-196``): the
    interior term reads only local x (halo slots as zeros) and carries no
    data dependency on the exchange, so XLA's async collectives overlap
    the ppermutes with the bulk gather/multiply; boundary rows then get a
    small gathered correction scattered back through a trash slot.
    """
    axis = A.axis
    n_parts = A.n_parts
    if A.block_dim > 1:
        return _dist_spmv_block(A, x)
    from ..ops.pallas_ell import _INTERPRET
    # gate on the MESH's platform, not the process default backend — a
    # CPU debug mesh on a TPU host must take the gather path
    use_win = (A.win_blocks is not None
               and (A.mesh.devices.flat[0].platform == "tpu"
                    or _INTERPRET))
    from ..telemetry import recorder as _trecorder
    _tel_dist_spmv(A)
    sid = _trecorder.span_begin(
        "dist_spmv", {"n_parts": n_parts, "n_loc": A.n_loc,
                      "interior": "win" if use_win else "gather"})

    def interior_gather(cols, vals, xfull0, _wb, _wc, _wv):
        return jnp.sum(vals * xfull0[cols], axis=1)

    def interior_win(cols, vals, xfull0, wb, wc, wv):
        # windowed one-hot Pallas kernel — a per-chip gather would
        # otherwise throttle every shard (ops/pallas_ell.py)
        from ..ops.pallas_ell import _ell_window_call
        n_loc = cols.shape[0]
        T, K = A.win_tile, A.ell_width
        n_pad = wc.shape[0] // K
        n_tiles = n_pad // T
        B = wb.shape[0] // n_tiles
        m_pad = -(-xfull0.shape[0] // 128) * 128
        x2 = jnp.pad(xfull0, (0, m_pad - xfull0.shape[0])) \
            .reshape(-1, 128)
        return _ell_window_call(wb, wc[None, :], wv[None, :], x2, T,
                                (n_tiles, B, K)).reshape(-1)[:n_loc]

    interior = interior_win if use_win else interior_gather

    def local(cols, vals, send_idx, halo_src, bnd_rows, wb, wc, wv, xl):
        cols, vals = cols[0], vals[0]
        send_idx, halo_src, bnd = send_idx[0], halo_src[0], bnd_rows[0]
        n_loc_r = cols.shape[0]       # output (row) shard size
        n_loc_c = xl.shape[0]         # input (column) shard size
        H = halo_src.shape[0]
        buf = xl[send_idx]                                  # B2L gather
        got = _exchange(buf, A.dists, axis, n_parts)
        hvals = got[halo_src]                               # (H,)
        # interior: halo slots read zero — independent of the exchange
        xfull0 = jnp.concatenate([xl, jnp.zeros((H,), xl.dtype)])
        y0 = interior(cols, vals, xfull0, wb[0], wc[0], wv[0])
        # boundary rows get a small gathered correction scattered back
        # through a trash slot
        rows = jnp.minimum(bnd, n_loc_r - 1)
        cb = cols[rows]                                     # (Bd, K)
        vb = vals[rows]
        hb = jnp.where(cb >= n_loc_c,
                       vb * hvals[jnp.clip(cb - n_loc_c, 0, H - 1)], 0.0)
        corr = jnp.sum(hb, axis=1)                          # (Bd,)
        yext = jnp.zeros((n_loc_r + 1,), xl.dtype).at[bnd].add(corr)
        return y0 + yext[:n_loc_r]

    # the win arrays always ride the shard_map signature (dummy scalars
    # when absent) so both paths share one body
    zeros = jnp.zeros((n_parts, 1), jnp.int32)
    wb = A.win_blocks if A.win_blocks is not None else zeros
    wc = A.win_codes if A.win_codes is not None else zeros
    wv = A.win_vals if A.win_vals is not None else \
        jnp.zeros((n_parts, 1), A.vals.dtype)
    try:
        return _shard_map(
            local, mesh=A.mesh,
            in_specs=(P(axis, None, None), P(axis, None, None),
                      P(axis, None), P(axis, None), P(axis, None),
                      P(axis, None), P(axis, None), P(axis, None),
                      P(axis)),
            out_specs=P(axis),
            # the pallas_call's out_shape carries no varying-mesh-axes
            # annotation — skip the vma check
            check_vma=False,
        )(A.cols, A.vals, A.send_idx, A.halo_src, A.bnd_rows, wb, wc, wv,
          x)
    finally:
        _trecorder.span_end(sid, "dist_spmv")


def _dist_spmv_block(A: ShardedMatrix, x: jax.Array) -> jax.Array:
    """Block (b×b) distributed SpMV: same interior/boundary split, halo
    exchange carries (B, b) block values, contractions are batched
    einsums (the b×b MXU path)."""
    axis, n_parts, b = A.axis, A.n_parts, A.block_dim
    from ..telemetry import recorder as _trecorder
    _tel_dist_spmv(A)
    sid = _trecorder.span_begin(
        "dist_spmv", {"n_parts": n_parts, "n_loc": A.n_loc,
                      "interior": "block", "block_dim": b})

    def local(cols, vals, send_idx, halo_src, bnd_rows, xl):
        cols, vals = cols[0], vals[0]
        send_idx, halo_src, bnd = send_idx[0], halo_src[0], bnd_rows[0]
        n_loc = cols.shape[0]
        H = halo_src.shape[0]
        xb = xl.reshape(n_loc, b)
        buf = xb[send_idx]                                  # (B, b)
        got = _exchange(buf, A.dists, axis, n_parts)        # (D·B, b)
        hvals = got[halo_src]                               # (H, b)
        xfull0 = jnp.concatenate([xb, jnp.zeros((H, b), xl.dtype)])
        xg = xfull0[cols]                                   # (n,K,b)
        y0 = jnp.einsum("nkab,nkb->na", vals, xg,
                        preferred_element_type=vals.dtype)
        rows = jnp.minimum(bnd, n_loc - 1)
        cb = cols[rows]                                     # (Bd, K)
        vb = vals[rows]                                     # (Bd,K,b,b)
        hg = hvals[jnp.clip(cb - n_loc, 0, H - 1)]          # (Bd,K,b)
        hb = jnp.einsum("nkab,nkb->na", vb,
                        jnp.where((cb >= n_loc)[..., None], hg, 0.0),
                        preferred_element_type=vals.dtype)
        yext = jnp.zeros((n_loc + 1, b), xl.dtype).at[bnd].add(hb)
        return (y0 + yext[:n_loc]).reshape(-1)

    try:
        return _shard_map(
            local, mesh=A.mesh,
            in_specs=(P(axis, None, None),
                      P(axis, None, None, None, None),
                      P(axis, None), P(axis, None), P(axis, None),
                      P(axis)),
            out_specs=P(axis),
        )(A.cols, A.vals, A.send_idx, A.halo_src, A.bnd_rows, x)
    finally:
        _trecorder.span_end(sid, "dist_spmv")


def vector_sharding(A: ShardedMatrix) -> NamedSharding:
    return NamedSharding(A.mesh, P(A.axis))


def shard_vector(A: ShardedMatrix, v) -> jax.Array:
    """Pad a real-sized global vector to P·n_loc·b and place it sharded.

    The padded layout is rank-major: rank p's real (block) rows land at
    [p·n_loc, p·n_loc + count_p), ×b scalar entries each.
    """
    # chaos harness (utils/faultinject.py): the halo_exchange point
    # fails the distributed solve at its host seam — the sharded
    # placement every halo'd SpMV depends on — with the device-error RC
    # the reference's communicator failures map to
    from ..utils import faultinject
    if faultinject.active():
        from ..errors import RC, AMGXError
        faultinject.maybe_raise(
            "halo_exchange",
            AMGXError("injected halo-exchange failure", RC.CUDA_FAILURE))
    v = np.asarray(v)
    n = A.n_parts * A.n_loc * A.block_dim
    if v.shape[0] == n:
        return jax.device_put(v.astype(A.dtype), vector_sharding(A))
    out = np.zeros(n, dtype=A.dtype)
    out[_pad_map_cached(A)] = v
    return jax.device_put(out, vector_sharding(A))


def unshard_vector(A: ShardedMatrix, v: jax.Array) -> np.ndarray:
    """Gather a padded sharded vector back to real global ordering."""
    return np.asarray(v)[_pad_map_cached(A)]


_padmap_cache = {}


def _pad_map_cached(A: ShardedMatrix) -> np.ndarray:
    key = (A.offsets, A.n_loc, A.block_dim)
    if key not in _padmap_cache:
        pm = pad_map(np.asarray(A.offsets), A.n_loc)
        b = A.block_dim
        if b > 1:
            # block pad map → scalar entries
            pm = (pm[:, None] * b + np.arange(b)[None, :]).reshape(-1)
        _padmap_cache[key] = pm
    return _padmap_cache[key]
