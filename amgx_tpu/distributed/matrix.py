"""Sharded matrix pack + distributed SpMV.

TPU-native equivalent of the reference's distributed SpMV with latency
hiding (``base/src/multiply.cu:75-196``, SURVEY §3.4):

    exchange_halo_async → SpMV on INTERIOR rows → wait → SpMV on BOUNDARY

Here the halo exchange is a mesh collective inside ``jax.shard_map``:
``all_gather`` of the fixed-size B2L send buffers (general partitions) or a
``ppermute`` neighbour schedule (1D stencil partitions).  XLA overlaps the
collective with the interior gather/multiply the way the reference overlaps
MPI with the interior kernel — without hand-rolled streams.

Vectors are flat (P·n_loc,) arrays sharded over mesh axis ``p`` with a
``NamedSharding``; everything outside SpMV (dots, axpys, Krylov updates) is
plain jnp code that GSPMD partitions automatically, inserting ``psum`` for
reductions — the TPU analog of the reference's MPI all-reduce dots
(SURVEY §3.3 "Every dot product in Krylov is an MPI all-reduce").

Padding invariant: shards are equal-sized; padding rows are identity rows
whose rhs/solution entries are exactly zero through every cycle operation,
so padded entries never pollute dots or norms.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .partition import Partition, build_partition


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["cols", "vals", "diag", "send_idx", "halo_src"],
    meta_fields=["n_global", "n_parts", "n_loc", "ell_width", "block_dim",
                 "axis", "use_ring", "offsets", "mesh"],
)
@dataclasses.dataclass(frozen=True)
class ShardedMatrix:
    """Frozen sharded ELL pack (leading axis = mesh axis ``p``).

    ``cols`` index into the per-shard extended vector
    ``[x_local (n_loc) | halo (H)]``.
    """

    cols: jax.Array       # (P, n_loc, K) int32
    vals: jax.Array       # (P, n_loc, K)
    diag: jax.Array       # (P·n_loc,) flat, sharded like vectors
    send_idx: jax.Array   # (P, B) int32 — B2L gather map
    halo_src: jax.Array   # (P, H) int32 — into flattened (P·B) gathered buf
    n_global: int
    n_parts: int
    n_loc: int
    ell_width: int
    block_dim: int
    axis: str             # mesh axis name
    use_ring: bool
    offsets: tuple        # (P+1,) real row offsets per rank
    #: static (meta) so traced packs keep it — tracers have no .sharding
    mesh: Mesh = None

    @property
    def n(self) -> int:
        """Padded global size (P · n_loc)."""
        return self.n_parts * self.n_loc

    n_rows = n
    n_cols = n

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def fmt(self):
        return "sharded-ell"


def pad_map(offsets: np.ndarray, n_loc: int) -> np.ndarray:
    """real global row id → padded id (rank p, local l → p·n_loc + l)."""
    n_parts = len(offsets) - 1
    out = np.empty(offsets[-1], dtype=np.int64)
    for p in range(n_parts):
        lo, hi = offsets[p], offsets[p + 1]
        out[lo:hi] = p * n_loc + np.arange(hi - lo)
    return out


def embed_padded(M: sp.csr_matrix, row_offsets, row_nloc,
                 col_offsets, col_nloc) -> sp.csr_matrix:
    """Re-index a real-sized sparse matrix into padded coordinates (pad
    rows/cols stay empty).  Used to embed classical P/R into the padded
    vector spaces."""
    M = sp.coo_matrix(M)
    rmap = pad_map(np.asarray(row_offsets), row_nloc)
    cmap = pad_map(np.asarray(col_offsets), col_nloc)
    n_parts = len(row_offsets) - 1
    shape = (n_parts * row_nloc, (len(col_offsets) - 1) * col_nloc)
    return sp.csr_matrix((M.data, (rmap[M.row], cmap[M.col])), shape=shape)


def make_mesh(n_devices: Optional[int] = None, axis: str = "p") -> Mesh:
    """Build a 1D device mesh in Auto (GSPMD) mode — collectives for the
    Krylov-level algebra are inserted by the partitioner; only the SpMV
    halo exchange is hand-scheduled via shard_map."""
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,),
                axis_types=(jax.sharding.AxisType.Auto,))


def _auto_mesh(mesh: Mesh) -> Mesh:
    """Coerce a mesh to Auto axis types (GSPMD) — explicit sharding-in-types
    meshes would demand out_sharding annotations on every contraction."""
    if all(t == jax.sharding.AxisType.Auto for t in mesh.axis_types):
        return mesh
    return Mesh(mesh.devices, mesh.axis_names,
                axis_types=(jax.sharding.AxisType.Auto,) * len(
                    mesh.axis_names))


def shard_matrix(A: sp.csr_matrix, mesh: Mesh, axis: str = "p",
                 dtype=None, offsets=None, n_loc: Optional[int] = None,
                 partition: Optional[Partition] = None) -> ShardedMatrix:
    """Pack a global CSR matrix into a ShardedMatrix laid out over ``mesh``.

    Mirrors ``DistributedManager::loadDistributedMatrix``
    (``distributed_manager.h:1815``): build B2L maps, renumber columns to
    [local | halo] slots, pad shards to equal size with identity rows.
    """
    A = sp.csr_matrix(A)
    dtype = np.dtype(dtype or A.dtype)
    mesh = _auto_mesh(mesh)
    n_parts = mesh.shape[axis]
    part = partition or build_partition(A, n_parts, offsets)
    if n_loc is not None and n_loc > part.n_loc:
        part = dataclasses.replace(part, n_loc=n_loc)
    n_loc = part.n_loc
    K = 1
    for p in range(n_parts):
        lo, hi = part.offsets[p], part.offsets[p + 1]
        deg = np.diff(A.indptr[lo:hi + 1])
        if len(deg):
            K = max(K, int(deg.max()))

    cols = np.zeros((n_parts, n_loc, K), dtype=np.int32)
    vals = np.zeros((n_parts, n_loc, K), dtype=dtype)
    diag = np.zeros((n_parts, n_loc), dtype=dtype)
    for p in range(n_parts):
        lo, hi = part.offsets[p], part.offsets[p + 1]
        nl = hi - lo
        sub = sp.csr_matrix(A[lo:hi])
        sub.sort_indices()
        ext = part.halo_global[p]
        gcols = sub.indices.astype(np.int64)
        local = (gcols >= lo) & (gcols < hi)
        lcols = np.where(local, gcols - lo, 0)
        if len(ext):
            halo_slot = np.searchsorted(ext, gcols)
            halo_slot = np.minimum(halo_slot, len(ext) - 1)
            lcols = np.where(local, lcols, n_loc + halo_slot)
        deg = np.diff(sub.indptr)
        rr = np.repeat(np.arange(nl), deg)
        pos = np.arange(len(gcols)) - np.repeat(sub.indptr[:-1], deg)
        cols[p, rr, pos] = lcols
        vals[p, rr, pos] = sub.data
        d = A.diagonal()[lo:hi]
        diag[p, :nl] = d
        # identity padding rows
        r = np.arange(nl, n_loc)
        cols[p, r, 0] = r
        vals[p, r, 0] = 1.0
        diag[p, r] = 1.0

    spec3 = NamedSharding(mesh, P(axis, None, None))
    spec2 = NamedSharding(mesh, P(axis, None))
    spec1 = NamedSharding(mesh, P(axis))
    return ShardedMatrix(
        cols=jax.device_put(cols, spec3),
        vals=jax.device_put(vals, spec3),
        diag=jax.device_put(diag.reshape(-1), spec1),
        send_idx=jax.device_put(part.send_idx, spec2),
        halo_src=jax.device_put(part.halo_src, spec2),
        n_global=part.n_global, n_parts=n_parts, n_loc=n_loc,
        ell_width=K, block_dim=1, axis=axis,
        use_ring=part.ring_neighbors_only,
        offsets=tuple(int(o) for o in part.offsets), mesh=mesh)


# --------------------------------------------------------------------------
# distributed SpMV
# --------------------------------------------------------------------------
def dist_spmv(A: ShardedMatrix, x: jax.Array) -> jax.Array:
    """y = A·x for a flat sharded x of length P·n_loc (call under jit)."""
    axis = A.axis
    n_parts = A.n_parts

    def local(cols, vals, send_idx, halo_src, xl):
        cols, vals = cols[0], vals[0]
        send_idx, halo_src = send_idx[0], halo_src[0]
        buf = xl[send_idx]                                  # B2L gather
        if A.use_ring and n_parts > 2:
            # neighbour-only ppermute schedule (ICI ring, SURVEY §5.7)
            B = buf.shape[0]
            right = [(i, (i + 1) % n_parts) for i in range(n_parts)]
            left = [(i, (i - 1) % n_parts) for i in range(n_parts)]
            from_left = jax.lax.ppermute(buf, axis, right)
            from_right = jax.lax.ppermute(buf, axis, left)
            idx = jax.lax.axis_index(axis)
            q = halo_src // B
            pos = halo_src % B
            halo = jnp.where(q == idx - 1, from_left[pos], from_right[pos])
        else:
            all_bufs = jax.lax.all_gather(buf, axis)        # (P, B)
            halo = all_bufs.reshape(-1)[halo_src]           # (H,)
        xfull = jnp.concatenate([xl, halo])
        return jnp.sum(vals * xfull[cols], axis=1)

    return jax.shard_map(
        local, mesh=A.mesh,
        in_specs=(P(axis, None, None), P(axis, None, None),
                  P(axis, None), P(axis, None), P(axis)),
        out_specs=P(axis),
    )(A.cols, A.vals, A.send_idx, A.halo_src, x)


def vector_sharding(A: ShardedMatrix) -> NamedSharding:
    return NamedSharding(A.mesh, P(A.axis))


def shard_vector(A: ShardedMatrix, v) -> jax.Array:
    """Pad a real-sized global vector to P·n_loc and place it sharded.

    The padded layout is rank-major: rank p's real rows land at
    [p·n_loc, p·n_loc + count_p).
    """
    v = np.asarray(v)
    n = A.n_parts * A.n_loc
    if v.shape[0] == n:
        return jax.device_put(v.astype(A.dtype), vector_sharding(A))
    out = np.zeros(n, dtype=A.dtype)
    out[_pad_map_cached(A)] = v
    return jax.device_put(out, vector_sharding(A))


def unshard_vector(A: ShardedMatrix, v: jax.Array) -> np.ndarray:
    """Gather a padded sharded vector back to real global ordering."""
    return np.asarray(v)[_pad_map_cached(A)]


_padmap_cache = {}


def _pad_map_cached(A: ShardedMatrix) -> np.ndarray:
    key = (A.offsets, A.n_loc)
    if key not in _padmap_cache:
        _padmap_cache[key] = pad_map(np.asarray(A.offsets), A.n_loc)
    return _padmap_cache[key]
