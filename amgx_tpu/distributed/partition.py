"""Row partitioning and halo-map construction.

TPU-native re-design of the reference distributed layer (SURVEY §2.8):

* ``DistributedManager`` (``base/src/distributed/distributed_manager.cu``)
  keeps per-matrix partition state: neighbour lists, B2L (boundary→local)
  send maps, L2H maps, halo offsets/ranges, interior-first renumbering.
* ``DistributedArranger`` (``distributed_arranger.cu:85-140`` create_B2L)
  builds that state from global column indices + a partition vector.
* Multi-ring halos: the reference keeps per-ring B2L maps
  (``distributed_manager.h:284-305``, rings default 2, ``vector.h:38-51``
  INTERIOR/BOUNDARY/HALO1/HALO2 views).

Here the equivalent state is built on host by :func:`build_partition`:
rows are partitioned into P equal contiguous shards (padded with identity
rows), each shard's matrix is packed in ELL form with column indices into
``[0, n_loc + H)`` where slots ``n_loc..n_loc+H`` hold received ring-1 halo
values; ``send_idx`` (the B2L map) gathers boundary values into a
fixed-size send buffer.

Exchange layout is **distance-wise** (the neighbour-wise schedule of
``comms_mpi_hostbuffer_stream.cu:354-523``, re-expressed as ICI
collectives): the union of neighbour links is a small set of rank
distances d = (owner − p) mod P; the solve-time exchange issues one
``ppermute`` per distance, and ``halo_src`` addresses the received
buffers as d_slot·B + position.  ``bnd_rows`` lists each rank's boundary
rows (rows with any halo column) so the SpMV can overlap the exchange
with the interior compute and apply only a small boundary correction.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np
import scipy.sparse as sp

from ..errors import BadParametersError


@dataclasses.dataclass
class Ring:
    """One halo ring's maps (B2L send side + receive addressing)."""

    dists: Tuple[int, ...]      # rank distances (owner − p) mod P, sorted
    send_idx: np.ndarray        # (P, B) local row ids to send (B2L map)
    send_count: np.ndarray      # (P,)
    halo_src: np.ndarray        # (P, H) d_slot·B + pos into recv buffers
    halo_count: np.ndarray      # (P,)
    halo_global: List[np.ndarray]   # per-rank global col/row ids of slots

    @property
    def B(self):
        return self.send_idx.shape[1]

    @property
    def H(self):
        return self.halo_src.shape[1]


@dataclasses.dataclass
class Partition:
    """Host-side partition descriptor (the DistributedManager analog)."""

    n_global: int               # unpadded global rows
    n_parts: int
    n_loc: int                  # padded rows per shard
    offsets: np.ndarray         # (P+1,) original row offsets per rank
    rings: List[Ring]           # ring 1 (+ ring 2 when requested)
    neighbors: List[np.ndarray]     # per-rank neighbour rank lists
    bnd_rows: np.ndarray        # (P, Bd) boundary row ids (pad → n_loc)
    bnd_count: np.ndarray       # (P,)

    # ring-1 shorthands (the SpMV pack consumes these)
    @property
    def send_idx(self):
        return self.rings[0].send_idx

    @property
    def halo_src(self):
        return self.rings[0].halo_src

    @property
    def halo_global(self):
        return self.rings[0].halo_global

    @property
    def dists(self):
        return self.rings[0].dists

    @property
    def B(self):
        return self.rings[0].B

    @property
    def H(self):
        return self.rings[0].H

    @property
    def halo_count(self):
        return self.rings[0].halo_count

    @property
    def send_count(self):
        return self.rings[0].send_count

    @property
    def ring_neighbors_only(self) -> bool:
        """Every neighbour link is rank±1 (a 1D stencil partition)."""
        return set(self.dists) <= {1, self.n_parts - 1}


def split_row_blocks(A: sp.spmatrix, offsets: np.ndarray
                     ) -> List[sp.csr_matrix]:
    """Split a global matrix into per-rank row blocks (global col ids)."""
    A = sp.csr_matrix(A)
    offsets = np.asarray(offsets)
    return [sp.csr_matrix(A[offsets[p]:offsets[p + 1]])
            for p in range(len(offsets) - 1)]


def partition_offsets_from_vector(partition_vector: np.ndarray,
                                  n_parts: int) -> np.ndarray:
    """Partition vector (rank id per row, rank-contiguous) → offsets.

    Reference: partition vectors in ``AMGX_matrix_upload_distributed``;
    rows must already be rank-contiguous (the renumbered layout)."""
    pv = np.asarray(partition_vector)
    counts = np.bincount(pv, minlength=n_parts)
    # verify contiguity
    expect = np.repeat(np.arange(n_parts), counts)
    if not np.array_equal(np.sort(pv), pv) or not np.array_equal(pv, expect):
        raise BadParametersError(
            "partition vector must be rank-contiguous (renumber rows "
            "first, as AMGX_matrix_upload_distributed requires)")
    return np.concatenate([[0], np.cumsum(counts)])


def _build_ring(targets: List[np.ndarray], owner: np.ndarray,
                offsets: np.ndarray, n_parts: int) -> Ring:
    """Build one ring's maps from each rank's needed-global-ids lists."""
    # send lists: union of what every rank needs from q, sorted —
    # deterministic layout both sides can compute
    need = [[None] * n_parts for _ in range(n_parts)]
    for p, ext in enumerate(targets):
        own = owner[ext] if len(ext) else np.zeros(0, dtype=np.int32)
        for q in np.unique(own):
            need[q][p] = ext[own == q]
    send_lists: List[np.ndarray] = []
    for q in range(n_parts):
        allneed = [need[q][p] for p in range(n_parts)
                   if need[q][p] is not None]
        s = (np.unique(np.concatenate(allneed)) if allneed
             else np.zeros(0, dtype=np.int64))
        send_lists.append(s)

    B = max(max((len(s) for s in send_lists), default=0), 1)
    H = max(max((len(h) for h in targets), default=0), 1)

    send_idx = np.zeros((n_parts, B), dtype=np.int32)
    send_count = np.zeros(n_parts, dtype=np.int32)
    for q, s in enumerate(send_lists):
        send_idx[q, :len(s)] = s - offsets[q]  # local row ids
        send_count[q] = len(s)

    dset = set()
    for p, ext in enumerate(targets):
        if len(ext):
            dset.update(int(d) for d in
                        np.unique((owner[ext] - p) % n_parts))
    dists = tuple(sorted(dset)) or (1,)
    dslot = {d: i for i, d in enumerate(dists)}

    halo_src = np.zeros((n_parts, H), dtype=np.int32)
    halo_count = np.zeros(n_parts, dtype=np.int32)
    for p, ext in enumerate(targets):
        if not len(ext):
            continue
        own = owner[ext]
        pos = np.empty(len(ext), dtype=np.int64)
        slot = np.empty(len(ext), dtype=np.int64)
        for q in np.unique(own):
            mask = own == q
            pos[mask] = np.searchsorted(send_lists[q], ext[mask])
            slot[mask] = dslot[int((q - p) % n_parts)]
        halo_src[p, :len(ext)] = slot * B + pos
        halo_count[p] = len(ext)

    return Ring(dists=dists, send_idx=send_idx, send_count=send_count,
                halo_src=halo_src, halo_count=halo_count,
                halo_global=targets)


def build_partition(A: sp.csr_matrix, n_parts: int,
                    offsets: Optional[np.ndarray] = None,
                    n_rings: int = 2) -> Partition:
    """Analyse a *global* matrix and build all halo maps (convenience
    wrapper over :func:`build_partition_from_blocks`)."""
    A = sp.csr_matrix(A)
    n = A.shape[0]
    if offsets is None:
        n_loc = -(-n // n_parts)
        offsets = np.minimum(np.arange(n_parts + 1) * n_loc, n)
    else:
        offsets = np.asarray(offsets)
    return build_partition_from_blocks(split_row_blocks(A, offsets),
                                       offsets, n_rings=n_rings)


def build_partition_from_blocks(blocks: List[sp.csr_matrix],
                                offsets: np.ndarray,
                                n_rings: int = 2,
                                col_offsets: Optional[np.ndarray] = None
                                ) -> Partition:
    """Build all halo maps from per-rank row blocks (global column ids) —
    the scalable setup contract: no step touches more than one rank's
    block plus its halo rows.

    Equivalent of ``DistributedArranger::create_B2L``
    (``distributed_arranger.h:85-140`` builds B2L from per-rank data) with
    the ring-2 extension; rows keep their order — padding replaces
    interior-first renumbering because SPMD shards must be equal-sized,
    and the boundary set is carried as an explicit row list instead.

    ``col_offsets``: the COLUMN-space partition when it differs from the
    row partition — rectangular operators (classical AMG P/R transfers)
    exchange halos in their column space (reference: the distributed
    P/restriction views, ``classical_amg_level.cu:240-340``).  Ring 2 is
    row-space machinery and requires a square partition.
    """
    offsets = np.asarray(offsets)
    n_parts = len(blocks)
    rect = col_offsets is not None
    col_offsets = offsets if col_offsets is None else \
        np.asarray(col_offsets)
    n = int(col_offsets[-1])          # column-space extent (halo space)
    n_loc = int(np.max(np.diff(offsets)))
    if rect and n_rings >= 2:
        raise BadParametersError(
            "ring-2 maps are defined for square partitions only")

    # which rank owns each global COLUMN
    owner = np.zeros(n, dtype=np.int32)
    for p in range(n_parts):
        owner[col_offsets[p]:col_offsets[p + 1]] = p

    halo1: List[np.ndarray] = []
    neighbors: List[np.ndarray] = []
    bnd_lists: List[np.ndarray] = []
    for p in range(n_parts):
        lo, hi = col_offsets[p], col_offsets[p + 1]
        nrows = offsets[p + 1] - offsets[p]
        sub = blocks[p]
        cols = sub.indices
        ext_mask = (cols < lo) | (cols >= hi)
        ext = np.unique(cols[ext_mask])
        halo1.append(ext)
        neighbors.append(np.unique(owner[ext]))
        rows = np.repeat(np.arange(nrows), np.diff(sub.indptr))
        bnd_lists.append(np.unique(rows[ext_mask]))

    Bd = max(max((len(b) for b in bnd_lists), default=0), 1)
    bnd_rows = np.full((n_parts, Bd), n_loc, dtype=np.int32)  # pad→trash
    bnd_count = np.zeros(n_parts, dtype=np.int32)
    for p, bl in enumerate(bnd_lists):
        bnd_rows[p, :len(bl)] = bl
        bnd_count[p] = len(bl)

    rings = [_build_ring(halo1, owner, col_offsets, n_parts)]
    if n_rings >= 2:
        halo2: List[np.ndarray] = []
        for p in range(n_parts):
            lo, hi = offsets[p], offsets[p + 1]
            ring1 = halo1[p]
            if len(ring1):
                # ring-1 halo rows live in the owners' blocks (the
                # multi-host analog exchanges those rows neighbour-wise)
                cols2 = np.unique(np.concatenate([
                    blocks[q].indices[
                        blocks[q].indptr[r0]:blocks[q].indptr[r1]]
                    for q, r0, r1 in _owner_row_runs(ring1, owner, offsets)
                ]))
                known = np.concatenate(
                    [ring1, np.arange(lo, hi, dtype=np.int64)])
                ext2 = np.setdiff1d(cols2, known)
            else:
                ext2 = np.zeros(0, dtype=np.int64)
            halo2.append(ext2)
        rings.append(_build_ring(halo2, owner, offsets, n_parts))

    return Partition(
        n_global=int(offsets[-1]), n_parts=n_parts, n_loc=n_loc,
        offsets=offsets, rings=rings, neighbors=neighbors,
        bnd_rows=bnd_rows, bnd_count=bnd_count)


def _owner_row_runs(rows: np.ndarray, owner: np.ndarray,
                    offsets: np.ndarray):
    """Split a sorted global-row list into (owner, local_lo, local_hi+1)
    runs of CONSECUTIVE local rows so indptr slicing stays vectorised."""
    out = []
    for q in np.unique(owner[rows]):
        rq = rows[owner[rows] == q] - offsets[q]
        # split into consecutive runs
        breaks = np.where(np.diff(rq) != 1)[0] + 1
        for run in np.split(rq, breaks):
            out.append((int(q), int(run[0]), int(run[-1]) + 1))
    return out
