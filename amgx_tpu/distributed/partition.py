"""Row partitioning and halo-map construction.

TPU-native re-design of the reference distributed layer (SURVEY §2.8):

* ``DistributedManager`` (``base/src/distributed/distributed_manager.cu``)
  keeps per-matrix partition state: neighbour lists, B2L (boundary→local)
  send maps, L2H maps, halo offsets/ranges, interior-first renumbering.
* ``DistributedArranger`` (``distributed_arranger.cu:85-140`` create_B2L)
  builds that state from global column indices + a partition vector.

Here the equivalent state is built on host by :func:`build_partition`:
rows are partitioned into P equal contiguous shards (padded with identity
rows), each shard's matrix is packed in ELL form with column indices into
``[0, n_loc + H)`` where slots ``n_loc..n_loc+H`` hold received halo values;
``send_idx`` (the B2L map) gathers boundary values into a fixed-size send
buffer, and ``halo_src`` addresses the all-gathered send buffers.  At solve
time the exchange is ``all_gather`` over the mesh axis (general graphs) —
the ``lax.ppermute`` neighbour schedule lives in
:mod:`amgx_tpu.distributed.spmv` for ring partitions.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np
import scipy.sparse as sp

from ..errors import BadParametersError


@dataclasses.dataclass
class Partition:
    """Host-side partition descriptor (the DistributedManager analog)."""

    n_global: int               # unpadded global rows
    n_parts: int
    n_loc: int                  # padded rows per shard
    offsets: np.ndarray         # (P+1,) original row offsets per rank
    # per-rank halo structure (lists of arrays, rank-major)
    send_idx: np.ndarray        # (P, B) local row ids to send (B2L map)
    send_count: np.ndarray      # (P,)
    halo_src: np.ndarray        # (P, H) index into flattened (P*B) gathered buf
    halo_count: np.ndarray      # (P,)
    halo_global: List[np.ndarray]   # per-rank global col ids of halo slots
    neighbors: List[np.ndarray]     # per-rank neighbour rank lists
    ring_neighbors_only: bool = False  # every neighbour is rank±1

    @property
    def B(self):
        return self.send_idx.shape[1]

    @property
    def H(self):
        return self.halo_src.shape[1]


def partition_offsets_from_vector(partition_vector: np.ndarray,
                                  n_parts: int) -> np.ndarray:
    """Partition vector (rank id per row, rank-contiguous) → offsets.

    Reference: partition vectors in ``AMGX_matrix_upload_distributed``;
    rows must already be rank-contiguous (the renumbered layout)."""
    pv = np.asarray(partition_vector)
    counts = np.bincount(pv, minlength=n_parts)
    # verify contiguity
    expect = np.repeat(np.arange(n_parts), counts)
    if not np.array_equal(np.sort(pv), pv) or not np.array_equal(pv, expect):
        raise BadParametersError(
            "partition vector must be rank-contiguous (renumber rows "
            "first, as AMGX_matrix_upload_distributed requires)")
    return np.concatenate([[0], np.cumsum(counts)])


def build_partition(A: sp.csr_matrix, n_parts: int,
                    offsets: Optional[np.ndarray] = None) -> Partition:
    """Analyse the global matrix and build all halo maps.

    Equivalent of ``DistributedArranger::create_B2L`` + interior-first
    renumbering (here rows keep their order; padding replaces renumbering
    because SPMD shards must be equal-sized).
    """
    A = sp.csr_matrix(A)
    n = A.shape[0]
    if offsets is None:
        n_loc = -(-n // n_parts)
        offsets = np.minimum(np.arange(n_parts + 1) * n_loc, n)
    else:
        offsets = np.asarray(offsets)
    n_loc = int(np.max(np.diff(offsets)))

    # which rank owns each global row
    owner = np.zeros(n, dtype=np.int32)
    for p in range(n_parts):
        owner[offsets[p]:offsets[p + 1]] = p

    halo_global: List[np.ndarray] = []
    neighbors: List[np.ndarray] = []
    # send_sets[q][p] = global rows of q needed by p
    need = [[None] * n_parts for _ in range(n_parts)]
    for p in range(n_parts):
        lo, hi = offsets[p], offsets[p + 1]
        sub = A[lo:hi]
        cols = np.unique(sub.indices)
        ext = cols[(cols < lo) | (cols >= hi)]
        halo_global.append(ext)
        nb = np.unique(owner[ext])
        neighbors.append(nb)
        for q in nb:
            need[q][p] = ext[owner[ext] == q]

    # per-rank send lists (B2L): union of what every neighbour needs,
    # sorted — deterministic layout both sides can compute
    send_lists: List[np.ndarray] = []
    for q in range(n_parts):
        allneed = [need[q][p] for p in range(n_parts)
                   if need[q][p] is not None]
        s = (np.unique(np.concatenate(allneed)) if allneed
             else np.zeros(0, dtype=np.int64))
        send_lists.append(s)

    B = max((len(s) for s in send_lists), default=0)
    B = max(B, 1)
    H = max((len(h) for h in halo_global), default=0)
    H = max(H, 1)

    send_idx = np.zeros((n_parts, B), dtype=np.int32)
    send_count = np.zeros(n_parts, dtype=np.int32)
    for q, s in enumerate(send_lists):
        send_idx[q, :len(s)] = s - offsets[q]  # local row ids
        send_count[q] = len(s)

    halo_src = np.zeros((n_parts, H), dtype=np.int32)
    halo_count = np.zeros(n_parts, dtype=np.int32)
    for p, ext in enumerate(halo_global):
        own = owner[ext]
        pos = np.empty(len(ext), dtype=np.int64)
        for q in np.unique(own):
            mask = own == q
            pos[mask] = np.searchsorted(send_lists[q], ext[mask])
        halo_src[p, :len(ext)] = own.astype(np.int64) * B + pos
        halo_count[p] = len(ext)

    ring = all((len(nb) == 0 or
                np.all((nb == p - 1) | (nb == p + 1)))
               for p, nb in enumerate(neighbors))
    return Partition(
        n_global=n, n_parts=n_parts, n_loc=n_loc,
        offsets=offsets, send_idx=send_idx, send_count=send_count,
        halo_src=halo_src, halo_count=halo_count,
        halo_global=halo_global, neighbors=neighbors,
        ring_neighbors_only=bool(ring))
