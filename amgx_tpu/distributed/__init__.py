from .agglomerate import (AgglomPlan, build_agglomeration, plan_for,
                          plan_submesh, redistribute_blocks)
from .matrix import (ShardedMatrix, shard_matrix, dist_spmv, shard_vector,
                     unshard_vector, make_mesh, embed_padded, pad_map)
from .partition import (Partition, build_partition,
                        partition_offsets_from_vector)

__all__ = ["ShardedMatrix", "shard_matrix", "dist_spmv", "shard_vector",
           "unshard_vector", "make_mesh", "embed_padded", "pad_map",
           "Partition", "build_partition", "partition_offsets_from_vector",
           "AgglomPlan", "build_agglomeration", "plan_for",
           "plan_submesh", "redistribute_blocks"]
