"""SLO monitor: the time-windowed request-outcome reservoir.

The serving layer's original latency record was a count-bounded list of
*successful* completions — deadline-expired and rejected requests
vanished from every percentile, so an overloaded service looked
*faster* the harder it shed load.  This module replaces it with the
standard SRE accounting:

* :class:`SLOWindow` keeps ``(t, latency, outcome, deadline_met)`` for
  **every** request outcome inside a sliding time window
  (``slo_window_s``), evicting by age rather than count;
* configurable objectives (``slo_latency_ms``, ``slo_target``) turn the
  window into **attainment** (good requests / all requests — a request
  is *good* when it completed OK, met its deadline and beat the latency
  objective) and **error-budget burn rate** (``(1 - attainment) /
  (1 - target)`` — 1.0 burns the budget exactly at the objective, >1
  exhausts it early);
* an **overload detector**: the windowed rejection rate plus the live
  queue depth form a trip wire (:meth:`SLOWindow.overloaded`) that
  ``/healthz`` and the doctor read.

Percentiles are computed over the outcomes that actually *waited*
(completed, failed, expired, errored) — admission rejections return in
microseconds and would drag every percentile toward zero, which is the
inverse lie of the one this module exists to fix; they count against
attainment instead.

``snapshot()`` additionally publishes the ``amgx_slo_*`` gauges and a
schema-validated ``slo_window`` event when telemetry is enabled, so a
trace carries the SLO picture the moment anyone asked for it.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Sequence

from . import metrics, recorder

#: every terminal request outcome the window labels
OUTCOMES = ("ok", "failed", "rejected", "expired", "error")
#: outcomes with a meaningful wait — the percentile population
#: (admission rejections return immediately and count against
#: attainment, not latency)
WAITED_OUTCOMES = ("ok", "failed", "expired", "error")
#: windowed rejection+expiry rate past which the service reads
#: overloaded (the rejection leg of the trip wire)
OVERLOAD_REJECT_RATE = 0.05
#: fraction of admission capacity past which the OUTSTANDING work
#: (queued + in-flight — the dispatcher drains the queue itself every
#: batch window, so the backlog lives in-flight) alone reads
#: overloaded: the queue-depth leg catches the ramp BEFORE the first
#: rejection
OVERLOAD_QUEUE_FRAC = 0.9
#: hard count cap on the reservoir — age is the eviction policy, this
#: is the memory bound (at 300 s windows a high-rps service would
#: otherwise hold O(rps×window) tuples forever)
MAX_SAMPLES = 65536


class SLOWindow:
    """Sliding-window reservoir of request outcomes + the SLO math."""

    def __init__(self, window_s: float = 300.0,
                 latency_ms: float = 0.0, target: float = 0.99):
        self.window_s = float(window_s)
        #: latency objective in seconds; 0 disables the latency
        #: criterion (attainment then counts completion + deadline only)
        self.latency_objective_s = float(latency_ms) / 1e3
        #: target >= 1.0 means a ZERO error budget — burn rate is then
        #: undefined (reported None) instead of the absurd ~1e9× a
        #: clamped denominator would print for a single failure
        self._zero_budget = float(target) >= 1.0
        self.target = min(max(float(target), 0.0), 1.0 - 1e-9)
        self._lock = threading.Lock()
        #: (t, latency_s, outcome, deadline_met) — newest at the right
        self._dq: "collections.deque[tuple]" = collections.deque(
            maxlen=MAX_SAMPLES)

    # -------------------------------------------------------------- record
    def record(self, latency_s: float, outcome: str,
               deadline_met: bool = True,
               now: Optional[float] = None):
        """Append one terminal request outcome.  ``now`` is injectable
        (``time.monotonic`` scale) so eviction math is testable."""
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown SLO outcome {outcome!r} "
                             f"(one of {OUTCOMES})")
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._dq.append((t, float(latency_s), outcome,
                             bool(deadline_met)))
            self._evict_locked(t)

    def _evict_locked(self, now: float):
        cut = now - self.window_s
        dq = self._dq
        while dq and dq[0][0] < cut:
            dq.popleft()

    def _samples(self, now: Optional[float] = None):
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._evict_locked(t)
            return list(self._dq)

    def reset(self):
        with self._lock:
            self._dq.clear()

    def __len__(self) -> int:
        return len(self._samples())

    # --------------------------------------------------------------- query
    @staticmethod
    def _counts_of(samples) -> Dict[str, int]:
        out = {k: 0 for k in OUTCOMES}
        for _, _, oc, _ in samples:
            out[oc] += 1
        return out

    def counts(self, now: Optional[float] = None) -> Dict[str, int]:
        return self._counts_of(self._samples(now))

    @staticmethod
    def _percentiles_of(samples,
                        outcomes: Sequence[str] = WAITED_OUTCOMES
                        ) -> dict:
        lat = sorted(l for _, l, oc, _ in samples if oc in outcomes)
        if not lat:
            return {"p50": None, "p95": None, "p99": None}

        def pct(p):
            return lat[min(len(lat) - 1,
                           max(0, int(round(p * (len(lat) - 1)))))]

        return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}

    def percentiles(self, outcomes: Sequence[str] = WAITED_OUTCOMES,
                    now: Optional[float] = None) -> dict:
        """p50/p95/p99 latency (seconds) over the waited outcomes —
        the old ``latency_percentiles`` shape, minus its blind spot."""
        return self._percentiles_of(self._samples(now), outcomes)

    def _good(self, sample) -> bool:
        _, latency, outcome, deadline_met = sample
        if outcome != "ok" or not deadline_met:
            return False
        if self.latency_objective_s > 0 and \
                latency > self.latency_objective_s:
            return False
        return True

    def attainment(self, now: Optional[float] = None) -> Optional[float]:
        """good / total over the window; None on an empty window."""
        samples = self._samples(now)
        if not samples:
            return None
        return sum(1 for s in samples if self._good(s)) / len(samples)

    def burn_rate(self, now: Optional[float] = None) -> Optional[float]:
        """Error-budget burn rate: (1 - attainment) / (1 - target).
        1.0 spends the budget exactly at the objective; 2.0 exhausts it
        in half the period.  None on an empty window, and None when the
        configured target leaves no budget (slo_target >= 1.0)."""
        att = self.attainment(now)
        if att is None or self._zero_budget:
            return None
        return (1.0 - att) / (1.0 - self.target)

    def rejection_rate(self, now: Optional[float] = None
                       ) -> Optional[float]:
        """(rejected + expired) / total over the window — the shed
        fraction an open-loop client observes."""
        c = self.counts(now)
        total = sum(c.values())
        if not total:
            return None
        return (c["rejected"] + c["expired"]) / total

    @staticmethod
    def _tripped(rejection_rate: Optional[float],
                 queue_depth: Optional[int],
                 queue_capacity: Optional[int]) -> bool:
        if rejection_rate is not None and \
                rejection_rate > OVERLOAD_REJECT_RATE:
            return True
        if queue_depth is not None and queue_capacity:
            if queue_depth >= OVERLOAD_QUEUE_FRAC * queue_capacity:
                return True
        return False

    def overloaded(self, queue_depth: Optional[int] = None,
                   queue_capacity: Optional[int] = None,
                   now: Optional[float] = None) -> bool:
        """The trip wire: windowed shed rate past
        :data:`OVERLOAD_REJECT_RATE`, or the caller's OUTSTANDING work
        (queued + in-flight) past :data:`OVERLOAD_QUEUE_FRAC` of
        admission capacity."""
        return self._tripped(self.rejection_rate(now), queue_depth,
                             queue_capacity)

    # ------------------------------------------------------------ snapshot
    def snapshot(self, queue_depth: Optional[int] = None,
                 queue_capacity: Optional[int] = None,
                 now: Optional[float] = None,
                 emit_event: bool = True,
                 include_percentiles: bool = True,
                 publish_gauges: bool = True) -> dict:
        """The full SLO picture as one dict — computed from ONE pass
        over the window (pollers call this once per scrape; the
        per-metric helpers each copy the reservoir).  Also refreshes
        the ``amgx_slo_*`` gauges and — with ``emit_event`` — a
        schema-validated ``slo_window`` event when telemetry is
        enabled.  Poll paths (``/healthz``, ``/metrics``) pass
        ``emit_event=False``: a load balancer probing at 1 Hz would
        otherwise fill the bounded event ring with SLO noise, evicting
        the solve spans and request traces ``/debug/trace`` exists to
        expose.  The gauge refresh on those paths updates the registry
        ONLY (no raw ring samples) for the same reason."""
        samples = self._samples(now)
        c = self._counts_of(samples)
        total = sum(c.values())
        att = (sum(1 for s in samples if self._good(s)) / total
               if total else None)
        burn = ((1.0 - att) / (1.0 - self.target)
                if att is not None and not self._zero_budget else None)
        rej = ((c["rejected"] + c["expired"]) / total
               if total else None)
        # the sort over the waited latencies is the expensive part of a
        # snapshot; poll paths (health/scrape at LB rates) never read
        # the percentiles, so they skip it
        pct = (self._percentiles_of(samples) if include_percentiles
               else {"p50": None, "p95": None, "p99": None})
        over = self._tripped(rej, queue_depth, queue_capacity)
        out = {
            "window_s": self.window_s,
            "objective": {"latency_ms": self.latency_objective_s * 1e3,
                          "target": self.target},
            "requests": int(total),
            "by_outcome": c,
            "attainment": att,
            "burn_rate": burn,
            "rejection_rate": rej,
            "latency_s": pct,
            "overloaded": bool(over),
        }
        # publish_gauges=False: secondary windows (the per-lane SLO
        # windows of the multi-lane serving layer) must not overwrite
        # the service-level amgx_slo_* gauges — lanes publish their own
        # amgx_serve_lane_attainment{lane} series instead
        if recorder.is_enabled() and publish_gauges:
            gset = (metrics.gauge_set if emit_event
                    else metrics.registry().gauge_set)
            gset("amgx_slo_window_requests", float(total))
            if att is not None:
                gset("amgx_slo_attainment", float(att))
            else:
                # an evicted-to-empty (or reset) window must DROP the
                # gauges: a degraded wave hours ago would otherwise
                # scrape as a live outage forever
                metrics.registry().gauge_clear("amgx_slo_attainment")
            if burn is not None:
                gset("amgx_slo_burn_rate", float(burn))
            else:
                metrics.registry().gauge_clear("amgx_slo_burn_rate")
            gset("amgx_serve_overload", 1.0 if over else 0.0)
            if emit_event:
                recorder.event(
                    "slo_window", window_s=self.window_s,
                    requests=int(total),
                    attainment=att, burn_rate=burn,
                    by_outcome=c, overloaded=bool(over),
                    latency_ms_objective=self.latency_objective_s * 1e3,
                    target=self.target)
        return out


def from_config(cfg) -> SLOWindow:
    """Build the window from the ``slo_*`` knobs of a resolved config
    (config/registry.py)."""
    return SLOWindow(window_s=float(cfg.get("slo_window_s")),
                     latency_ms=float(cfg.get("slo_latency_ms")),
                     target=float(cfg.get("slo_target")))
