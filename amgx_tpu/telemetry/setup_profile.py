"""Setup profiler: attribute AMG setup wall time to hardware terms.

The bench trajectory's dominant wall is setup (``pcg_classical128``:
103.5 s setup for a 3.9 s solve, BENCH_r04) and the existing
``cpu_profiler`` markers only say which coarse phase the wall clock sat
in — not whether that time was XLA **compile**, device **execute**,
host↔device **transfer**, or host-side SciPy work.  This module is the
setup twin of the PR 3 cost model: a gated attribution layer that turns
the same markers into a per-level × per-component phase tree with an
execute/compile/transfer/host split per phase.

How attribution works:

* **phases** — :func:`phase` context managers at the existing setup
  marker sites (strength / selector / interpolation / rap / upload /
  smoother_setup / coarse_solver / resetup_plan, plus the device-setup
  phases).  Nesting is tracked per thread; each finished phase emits a
  ``setup_phase`` ring event carrying wall and **self** (exclusive)
  seconds;
* **compile** — the ``jax.monitoring`` duration-event hook
  (utils/jaxcompat.py) forwards every jaxpr-trace and backend-compile
  duration here; it lands on the innermost open phase of the firing
  thread (compiles run synchronously on the thread that triggered
  them), so "which phase paid that 12 s compile" is answered exactly;
* **transfer** — :func:`transfer` wraps the blocking put/download sites
  (``core.matrix.arena_upload``/``pack_device``, the device-pipeline
  tail download) with byte and call counts per phase;
* **memory** — live-array device bytes are sampled at phase boundaries
  (:mod:`amgx_tpu.utils.memory`), so every profiled setup reports its
  HBM high-water mark as ``amgx_setup_mem_watermark_bytes``.

The remainder of a phase's self time after compile/trace/transfer is
**execute** for device-sync phases (``kind="device"``) and **host** for
host-algorithm phases — the four-way split the doctor's "setup
attribution" section ranks phases by.

Gating contract (same as the rest of :mod:`amgx_tpu.telemetry`): off by
default; every instrument's first action is one attribute check
(:func:`phase` returns a shared no-op context manager when disabled),
and with the ``setup_profile`` config knob off the setup path is
byte-identical to the uninstrumented one.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

from . import metrics, recorder

#: the canonical per-level setup components (host classical/aggregation
#: algorithms); call sites may add others (device_fine, dia_derive, ...)
COMPONENTS = ("strength", "selector", "interpolation", "rap", "upload",
              "smoother_setup", "coarse_solver", "resetup_plan")

#: phases of the device setup engine (amg/device_setup/): ``spgemm`` is
#: the host symbolic plan build (cache-miss only), ``device_rap`` the
#: jitted numeric Galerkin pass — both nest inside the level's ``rap``
#: phase, so a dominant host-side rap reads "fell back", not "missing"
DEVICE_SETUP_COMPONENTS = ("spgemm", "device_rap")

#: compile share of setup past which the doctor recommends the
#: persistent compilation cache / AOT lowering
COMPILE_HINT = 0.4
#: transfer share of setup past which the doctor calls setup wire-bound
TRANSFER_HINT = 0.3
#: self-share of one phase past which it is called dominant
DOMINANT_HINT = 0.25
#: blocking uploads per setup past which batching earns a hint
UPLOAD_DRAIN_HINT = 8


class _State:
    __slots__ = ("enabled", "lock", "profile")

    def __init__(self):
        self.enabled = False
        self.lock = threading.Lock()
        #: the active profiled setup (one at a time; nested/concurrent
        #: profile_setup calls no-op and their phases fold into it)
        self.profile: Optional[dict] = None


_STATE = _State()
_tls = threading.local()


def _stack() -> list:
    stk = getattr(_tls, "stack", None)
    if stk is None:
        stk = _tls.stack = []
    return stk


def is_enabled() -> bool:
    return _STATE.enabled


def enable():
    """Turn the setup profiler on (idempotent).  Also enables the
    telemetry recorder — phase records live in the same ring the JSONL
    exporters flush — and installs the jax.monitoring hook that feeds
    compile attribution."""
    if _STATE.enabled:
        # idempotent fast path: nested solver allocations re-call this
        # inside profiled phases — the warm-up below must not re-run
        return
    recorder.enable()
    # warm the live-array walk: its FIRST call pays ~0.1 s of lazy jax
    # backend init, which must not land inside a profiled setup's wall
    _device_bytes()
    _STATE.enabled = True


def disable():
    _STATE.enabled = False


def reset():
    """Drop the active profile and this thread's phase stack (test
    isolation)."""
    with _STATE.lock:
        _STATE.profile = None
    _tls.stack = []


def _device_bytes() -> Optional[int]:
    """Live device-array bytes right now; None when unsampleable.  Used
    only while profiling (opt-in), so the live_arrays walk is an
    accepted cost."""
    try:
        from ..utils.memory import memory_info
        return int(memory_info().current_device_bytes())
    except Exception:
        return None


class _NullPhase:
    """Shared no-op context manager: the entire disabled-path cost of a
    :func:`phase`/:func:`transfer` call site."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullPhase()


def null() -> _NullPhase:
    """The shared no-op context manager (for call sites that gate on
    their own condition, e.g. a non-toplevel setup)."""
    return _NULL


class _Phase:
    __slots__ = ("component", "level", "kind", "depth", "parent",
                 "t0", "child_wall", "compile_s", "trace_s",
                 "n_compiles", "transfer_s", "transfer_bytes",
                 "transfers")

    def __init__(self, component: str, level, kind: str):
        self.component = str(component)
        self.level = None if level is None else int(level)
        self.kind = kind
        self.child_wall = 0.0
        self.compile_s = 0.0
        self.trace_s = 0.0
        self.n_compiles = 0
        self.transfer_s = 0.0
        self.transfer_bytes = 0
        self.transfers = 0

    def __enter__(self):
        stk = _stack()
        self.depth = len(stk)
        self.parent = stk[-1].name() if stk else None
        stk.append(self)
        self.t0 = time.perf_counter()
        return self

    def name(self) -> str:
        return self.component if self.level is None \
            else f"{self.component}@L{self.level}"

    def __exit__(self, *exc):
        wall = time.perf_counter() - self.t0
        stk = _stack()
        # pop to self — robust against an instrument raising mid-phase
        while stk:
            if stk.pop() is self:
                break
        if stk:
            stk[-1].child_wall += wall
        self_s = max(wall - self.child_wall, 0.0)
        # compile/trace/transfer land on the INNERMOST phase, so the
        # per-phase overheads are disjoint; the rest of the exclusive
        # time is device execute or host work by the phase's kind
        own = self.compile_s + self.trace_s + self.transfer_s
        rest = max(self_s - own, 0.0)
        rec = {
            "component": self.component, "level": self.level,
            "kind": self.kind, "depth": self.depth,
            "parent": self.parent, "wall_s": round(wall, 6),
            "self_s": round(self_s, 6),
            "compile_s": round(self.compile_s, 6),
            "trace_s": round(self.trace_s, 6),
            "n_compiles": self.n_compiles,
            "transfer_s": round(self.transfer_s, 6),
            "transfer_bytes": int(self.transfer_bytes),
            "transfers": int(self.transfers),
            ("execute_s" if self.kind == "device" else "host_s"):
                round(rest, 6),
        }
        prof = _STATE.profile
        if prof is not None:
            mem = _device_bytes()
            if mem is not None:
                rec["mem_bytes"] = mem
                with _STATE.lock:
                    if _STATE.profile is prof:
                        prof["mem_max"] = max(prof["mem_max"], mem)
            with _STATE.lock:
                if _STATE.profile is prof:
                    prof["frames"].append(
                        dict(rec, tid=threading.get_ident()))
        recorder.event("setup_phase", **rec)
        # HBM-ledger phase boundary (rate-limited by memledger_sample_s;
        # one attribute check when the ledger is off)
        from . import memledger
        memledger.maybe_sample(phase=self.component)
        return False


def phase(component: str, level=None, kind: str = "host"):
    """Setup phase marker.  ``kind="device"`` declares the phase a
    device-sync point (its unattributed remainder is execute time, not
    host time).  One attribute check when the profiler is off."""
    if not _STATE.enabled:
        return _NULL
    return _Phase(component, level, kind)


class _Transfer:
    __slots__ = ("nbytes", "count", "tkind", "t0")

    def __init__(self, nbytes: int, count: int, tkind: str):
        self.nbytes = int(nbytes)
        self.count = int(count)
        self.tkind = tkind

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None:
            return False
        note_transfer(self.nbytes, time.perf_counter() - self.t0,
                      count=self.count, kind=self.tkind)
        return False


def transfer(nbytes: int, count: int = 1, kind: str = "upload"):
    """Wrap one blocking host↔device transfer (``device_put`` batch,
    tail download): bytes, call count and elapsed seconds accrue to the
    innermost open phase and the setup totals."""
    if not _STATE.enabled:
        return _NULL
    return _Transfer(nbytes, count, kind)


def note_transfer(nbytes: int, seconds: float, count: int = 1,
                  kind: str = "upload"):
    if not _STATE.enabled:
        return
    stk = _stack()
    if stk:
        f = stk[-1]
        f.transfer_s += seconds
        f.transfer_bytes += int(nbytes)
        f.transfers += int(count)
    prof = _STATE.profile
    if prof is not None:
        with _STATE.lock:
            if _STATE.profile is prof:
                prof["transfer_s"] += seconds
                prof["transfer_bytes"] += int(nbytes)
                prof[kind + "s"] = prof.get(kind + "s", 0) + int(count)
    metrics.counter_inc("amgx_setup_transfer_bytes_total", int(nbytes),
                        kind=kind)
    metrics.counter_inc("amgx_setup_transfers_total", int(count),
                        kind=kind)


def note_duration(is_compile: bool, seconds: float):
    """jax.monitoring forwarding (utils/jaxcompat.py): one jaxpr-trace
    or backend-compile duration, attributed to the innermost open phase
    of the firing thread — compiles run synchronously on the thread
    that triggered them, so the attribution is exact."""
    if not _STATE.enabled:
        return
    stk = _stack()
    if stk:
        f = stk[-1]
        if is_compile:
            f.compile_s += seconds
            f.n_compiles += 1
        else:
            f.trace_s += seconds
        return
    prof = _STATE.profile
    if prof is not None:
        with _STATE.lock:
            if _STATE.profile is prof:
                key = "unattributed_compile_s" if is_compile \
                    else "unattributed_trace_s"
                prof[key] = prof.get(key, 0.0) + seconds


# --------------------------------------------------------- setup scope
class _ProfileScope:
    __slots__ = ("solver", "prof")

    def __init__(self, solver: str):
        self.solver = solver

    def __enter__(self):
        # sample memory BEFORE starting the clock: the walk is cheap
        # but not free, and it belongs to the profiler, not the setup
        mem0 = _device_bytes() or 0
        prof = {"solver": self.solver, "t0": time.perf_counter(),
                "owner_tid": threading.get_ident(), "frames": [],
                "transfer_s": 0.0, "transfer_bytes": 0,
                "mem_max": mem0}
        with _STATE.lock:
            if _STATE.profile is None:
                _STATE.profile = self.prof = prof
            else:
                self.prof = None     # a profile is already running
        return self

    def __exit__(self, *exc):
        prof = self.prof
        if prof is None:
            return False
        wall = time.perf_counter() - prof["t0"]
        with _STATE.lock:
            if _STATE.profile is prof:
                _STATE.profile = None
        mem = _device_bytes()
        if mem is not None:
            prof["mem_max"] = max(prof["mem_max"], mem)
        if exc and exc[0] is not None:
            return False      # a failed setup emits no summary
        self._emit(prof, wall)
        return False

    def _emit(self, prof: dict, wall: float):
        frames = prof["frames"]
        owner = prof["owner_tid"]
        # coverage: owner-thread depth-0 phases tile the setup wall;
        # worker-thread phases (streamed uploads, smoother tasks)
        # OVERLAP it and must not count, or coverage could exceed 1
        covered = sum(f["wall_s"] for f in frames
                      if f["depth"] == 0 and f["tid"] == owner)
        own = [f for f in frames if f["tid"] == owner]
        # the wall-clock split counts the OWNER thread only, so
        # compile + transfer + execute + host ≤ wall; worker-thread
        # time (streamed uploads, smoother-setup tasks) overlaps the
        # owner's wait phases and is reported separately
        compile_s = sum(f["compile_s"] for f in own) \
            + prof.get("unattributed_compile_s", 0.0)
        trace_s = sum(f["trace_s"] for f in own) \
            + prof.get("unattributed_trace_s", 0.0)
        worker_compile_s = sum(f["compile_s"] for f in frames
                               if f["tid"] != owner)
        # same owner-only rule for transfer: a streamed worker upload
        # overlaps the owner's drain wait (already execute time there)
        # — the global prof counter would double-count it in the split
        transfer_s = sum(f["transfer_s"] for f in own)
        worker_transfer_s = max(prof["transfer_s"] - transfer_s, 0.0)
        execute_s = sum(f.get("execute_s", 0.0) for f in own)
        host_s = sum(f.get("host_s", 0.0) for f in own)
        summary = {
            "solver": prof["solver"], "wall_s": round(wall, 6),
            "coverage": round(min(covered / wall, 1.0), 4)
            if wall > 0 else 0.0,
            "compile_s": round(compile_s, 6),
            "trace_s": round(trace_s, 6),
            "transfer_s": round(transfer_s, 6),
            "transfer_bytes": int(prof["transfer_bytes"]),
            "uploads": int(prof.get("uploads", 0)),
            "downloads": int(prof.get("downloads", 0)),
            "execute_s": round(execute_s, 6),
            "host_s": round(host_s, 6),
            "worker_compile_s": round(worker_compile_s, 6),
            "worker_transfer_s": round(worker_transfer_s, 6),
            "unattributed_compile_s": round(
                prof.get("unattributed_compile_s", 0.0), 6),
            "mem_watermark_bytes": int(prof["mem_max"]),
            "n_phases": len(frames), "owner_tid": owner,
        }
        recorder.event("setup_profile", **summary)
        metrics.gauge_set("amgx_setup_compile_seconds", compile_s)
        metrics.gauge_set("amgx_setup_trace_seconds", trace_s)
        metrics.gauge_set("amgx_setup_transfer_seconds", transfer_s)
        metrics.gauge_set("amgx_setup_mem_watermark_bytes",
                          prof["mem_max"])
        # per-component exclusive-seconds gauges: cleared first so a
        # shallower re-setup can't leave stale components behind
        metrics.registry().gauge_clear("amgx_setup_phase_seconds")
        by_comp: Dict[str, float] = {}
        for f in frames:
            by_comp[f["component"]] = by_comp.get(f["component"], 0.0) \
                + f["self_s"]
        for comp, s in by_comp.items():
            metrics.gauge_set("amgx_setup_phase_seconds", s,
                              component=comp)


def profile_setup(solver: str = "?"):
    """Scope one top-level solver setup: frames collected inside become
    the ``setup_profile`` summary event + the ``amgx_setup_*`` gauges.
    No-op (shared null context) when the profiler is off; re-entrant
    calls fold into the outer profile."""
    if not _STATE.enabled:
        return _NULL
    return _ProfileScope(solver)


# ------------------------------------------------------------ analysis
def analyze(records: Iterable[dict]) -> Optional[dict]:
    """Reduce ``setup_phase``/``setup_profile`` ring records (or JSONL
    lines read back) to the doctor/bench view: the summary of the LAST
    profiled setup plus its ranked phase list.  None when the trace
    carries no setup-profile data."""
    pending: List[dict] = []
    phases: List[dict] = []
    summary = None
    for r in records:
        if r.get("kind") != "event":
            continue
        if r["name"] == "setup_phase":
            pending.append(dict(r["attrs"], tid=r.get("tid")))
        elif r["name"] == "setup_profile":
            # a summary closes the setup whose phases PRECEDE it — keep
            # the newest completed setup; phases after the last summary
            # belong to an unfinished one and are dropped
            summary = dict(r["attrs"])
            phases, pending = pending, []
    if summary is None:
        phases = pending
    if summary is None and not phases:
        return None
    owner = (summary or {}).get("owner_tid")
    for p in phases:
        p["name"] = p["component"] if p.get("level") is None \
            else f"{p['component']}@L{p['level']}"
        # ANY frame off the owner thread overlaps the owner's wall —
        # including nested ones (a worker smoother-setup's inner pack)
        p["overlapped"] = owner is not None and p.get("tid") is not None \
            and p["tid"] != owner
    total = (summary or {}).get("wall_s") or \
        sum(p["wall_s"] for p in phases if p.get("depth") == 0) or 0.0
    ranked = sorted(phases, key=lambda p: -p["self_s"])
    for p in ranked:
        p["share"] = round(p["self_s"] / total, 4) if total else 0.0
    by_comp: Dict[str, dict] = {}
    for p in phases:
        d = by_comp.setdefault(p["component"],
                               {"self_s": 0.0, "compile_s": 0.0,
                                "transfer_bytes": 0, "count": 0})
        d["self_s"] = round(d["self_s"] + p["self_s"], 6)
        d["compile_s"] = round(d["compile_s"] + p["compile_s"], 6)
        d["transfer_bytes"] += p.get("transfer_bytes", 0)
        d["count"] += 1
    return {"summary": summary, "phases": ranked,
            "components": by_comp, "total_s": total}


def summarize(analysis: Optional[dict], top: int = 4) -> Optional[dict]:
    """Compact embedding for bench JSON / trend tables: totals, the
    compile share, and the top-``top`` phases by exclusive time."""
    if not analysis:
        return None
    s = analysis.get("summary") or {}
    total = analysis["total_s"]
    out = {
        "total_s": round(total, 4),
        "compile_s": round(s.get("compile_s", 0.0), 4),
        "transfer_s": round(s.get("transfer_s", 0.0), 4),
        "transfer_bytes": int(s.get("transfer_bytes", 0)),
        "execute_s": round(s.get("execute_s", 0.0), 4),
        "host_s": round(s.get("host_s", 0.0), 4),
        "coverage": s.get("coverage"),
        "mem_watermark_bytes": s.get("mem_watermark_bytes"),
        # compile work a persistent cache would remove: owner-thread
        # compile plus the worker-thread compiles it waits on, capped
        "compile_share": round(min(
            (s.get("compile_s", 0.0) + s.get("worker_compile_s", 0.0))
            / total, 1.0), 4) if total else None,
        # filter overlapped BEFORE slicing: worker frames can out-rank
        # every owner phase and would otherwise empty the list
        "top": [{"name": p["name"], "self_s": round(p["self_s"], 4),
                 "share": p["share"]}
                for p in [q for q in analysis["phases"]
                          if not q.get("overlapped")][:top]],
    }
    return out
