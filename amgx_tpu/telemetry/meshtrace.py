"""Mesh flight recorder: clock-aligned cross-rank rendezvous analysis.

Every other telemetry layer is per-rank: :func:`~.export.aggregate_sessions`
concatenates per-process JSONL sessions without ever reconstructing the
mesh-wide picture, so a slow collective is indistinguishable from a slow
rank everyone waited on.  This module answers the cross-rank question —
*who arrived last, who made the mesh wait, and by how much*:

* **rank join**: each distinct ``(pid, session)`` identity in a trace is
  one rank (a process that appended several sessions to the same path —
  bench's per-case flushes — stays ONE rank, because its
  ``perf_counter`` epoch is shared);
* **clock alignment**: per-rank offset+slope fit over the paired
  (``t_perf``, ``t_unix``) samples — the session meta header plus the
  rate-limited ``clock_sample`` re-samples :func:`~.export.flush_jsonl`
  emits — so drift over long runs is bounded instead of baked into a
  single session-start offset;
* **collective rendezvous**: per-rank intervals of each halo-exchange
  hop (``exchange_halo`` / ``dist_spmv`` spans — the latter is the
  solve path's fused exchange+SpMV hop), fused Krylov reduction
  (``krylov_comm`` events) and agglomeration redistribution
  (``dist_agglomerate`` events) are matched across ranks by
  (op, group, sequence); arrival
  spread and per-rank wait (``wait = last_arrival − my_arrival``) fall
  out of the aligned timelines;
* **honesty invariant**: per rank, ``compute + wait + unattributed ≡
  wall`` — schema-enforced on every ``mesh_health`` event
  (:func:`~.export.validate_record`), with a ``measured`` provenance
  bool like deviceprof/memledger: a single-rank trace, or one without
  the needed spans, degrades to an honest ``measured=False`` stub;
* on top of the join: a per-rank **straggler score** (share of
  mesh-wide induced wait caused by arriving last), a per-group
  compute-vs-wait **skew decomposition**, and a **silent-rank/desync
  detector** (a rank whose events stop mid-solve while peers continue,
  or that missed collectives its peers ran).

Surfacing: ``amgx_mesh_*`` metrics via :func:`emit`, the doctor's
"Mesh health" section, rendezvous flow arrows in the Chrome trace
(:mod:`.tracefile`), ``/debug/mesh`` on the httpd, and the bench
distributed child's ``mesh`` block.  Everything is host-side file
parsing — no device work.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple, Union

from . import metrics, recorder
from .export import read_sessions

#: version of the mesh-analysis contract carried by every mesh_health
#: event (bump on semantic changes to wait attribution)
MESH_VERSION = 1

#: a rank whose trace ends this fraction of the mesh span before the
#: last rank's final record — while peers kept emitting — reads silent
SILENT_FRACTION = 0.25
#: absolute floor under which an early trace end is never flagged
#: (sub-millisecond tails are flush jitter, not a dead rank)
SILENT_MIN_S = 1e-3

#: ops a rendezvous can belong to (the event-schema vocabulary)
OPS = ("halo", "krylov", "agglomerate")


# ------------------------------------------------------ clock alignment
def _clock_points(sessions: List[dict]) -> List[Tuple[float, float]]:
    """(t_perf, t_unix) pairs of one rank: every session meta header
    plus every rate-limited ``clock_sample`` re-sample event."""
    pts: List[Tuple[float, float]] = []
    for s in sessions:
        m = s.get("meta") or {}
        if isinstance(m.get("t_perf"), (int, float)) and \
                isinstance(m.get("t_unix"), (int, float)):
            pts.append((float(m["t_perf"]), float(m["t_unix"])))
        for r in s["records"]:
            if r["kind"] == "event" and r["name"] == "clock_sample":
                a = r.get("attrs") or {}
                if isinstance(a.get("t_perf"), (int, float)) and \
                        isinstance(a.get("t_unix"), (int, float)):
                    pts.append((float(a["t_perf"]), float(a["t_unix"])))
    return sorted(set(pts))


def fit_clock(points: List[Tuple[float, float]]
              ) -> Tuple[float, float, int]:
    """Least-squares (offset_s, drift, n_samples) fit of one rank's
    wall clock against its perf_counter: ``wall = t·(1 + drift) +
    offset``.  One sample (the meta-only case) pins ``drift = 0`` —
    exactly the old single-offset alignment; more samples bound drift
    over long runs."""
    if not points:
        return 0.0, 0.0, 0
    if len(points) == 1:
        tp, tu = points[0]
        return tu - tp, 0.0, 1
    n = len(points)
    mx = sum(p for p, _ in points) / n
    # fit the RESIDUAL y = t_unix − t_perf, so drift is the slope on
    # top of the ideal 1:1 rate and precision survives large epochs
    my = sum(u - p for p, u in points) / n
    sxx = sum((p - mx) ** 2 for p, _ in points)
    if sxx <= 0.0:
        return my, 0.0, n
    sxy = sum((p - mx) * ((u - p) - my) for p, u in points)
    drift = sxy / sxx
    return my - drift * mx, drift, n


# -------------------------------------------------- rendezvous matching
def _rank_collectives(records: List[dict]) -> List[dict]:
    """One rank's collective arrivals, in record order::

        {"op", "group", "seq", "t_arrive", "t_done", "tid"}

    ``t_*`` are raw per-rank perf_counter seconds (callers align).
    ``seq`` counts occurrences per (op, group) — the cross-rank match
    key: an SPMD program runs the same collective sequence on every
    rank, so the k-th ring-1 exchange on rank 0 IS the k-th ring-1
    exchange on rank 3.  Arrival is the span BEGIN (the rank reaching
    the collective); events arrive at their instant."""
    out: List[dict] = []
    begins: Dict[int, dict] = {}
    counts: Dict[Tuple[str, str], int] = {}

    def nxt(op, group):
        key = (op, group)
        counts[key] = counts.get(key, 0) + 1
        return counts[key] - 1

    # dist_spmv IS the solve path's halo hop (exchange fused with the
    # interior/boundary SpMV for overlap); exchange_halo is the bare
    # hop setup/tests call directly — both rendezvous
    halo_spans = ("exchange_halo", "dist_spmv")
    for r in records:
        kind = r["kind"]
        if kind == "span_begin" and r["name"] in halo_spans:
            begins[r["sid"]] = r
        elif kind == "span_end" and r["name"] in halo_spans:
            b = begins.pop(r["sid"], None)
            if r["name"] == "dist_spmv":
                group = "spmv"
            else:
                ring = (b.get("attrs") or {}).get("ring") if b else None
                group = f"ring-{ring}" if isinstance(ring, int) \
                    else "ring-?"
            dur = float(r.get("dur") or 0.0)
            out.append({"op": "halo", "group": group,
                        "seq": nxt("halo", group),
                        "t_arrive": float(r["t"]) - dur,
                        "t_done": float(r["t"]), "tid": r["tid"]})
        elif kind == "event" and r["name"] == "krylov_comm":
            a = r.get("attrs") or {}
            group = str(a.get("solver") or "?")
            out.append({"op": "krylov", "group": group,
                        "seq": nxt("krylov", group),
                        "t_arrive": float(r["t"]),
                        "t_done": float(r["t"]), "tid": r["tid"],
                        "fused": bool(a.get("fused"))})
        elif kind == "event" and r["name"] == "dist_agglomerate":
            a = r.get("attrs") or {}
            group = f"level-{a.get('level')}"
            out.append({"op": "agglomerate", "group": group,
                        "seq": nxt("agglomerate", group),
                        "t_arrive": float(r["t"]),
                        "t_done": float(r["t"]), "tid": r["tid"]})
    return out


def rendezvous_from_sessions(sessions: List[dict]) -> List[dict]:
    """Raw rendezvous join over pre-read sessions (the Chrome-trace
    exporter's entry point — it applies its own per-session offsets)::

        {"op", "group", "seq",
         "arrivals": [{"session", "rank", "tid", "t", "t_done"}, ...]}

    ``session`` indexes ``sessions``; ``rank`` is the joined rank id
    (sessions from one ``(pid, session)`` identity share it).  Only
    keys at least two DISTINCT ranks reached are rendezvous; ``t`` is
    each rank's raw (unaligned) perf_counter arrival."""
    ranks = _join_ranks(sessions)
    by_key: Dict[Tuple[str, str, int], List[dict]] = {}
    for rank_id, rk in enumerate(ranks):
        for c in _rank_collectives(rk["records"]):
            by_key.setdefault((c["op"], c["group"], c["seq"]), []).append(
                {"session": rk["session_indices"][0], "rank": rank_id,
                 "tid": c["tid"], "t": c["t_arrive"],
                 "t_done": c["t_done"],
                 "fused": c.get("fused", False)})
    out = []
    for (op, group, seq), arr in sorted(by_key.items()):
        if len({a["rank"] for a in arr}) < 2:
            continue
        out.append({"op": op, "group": group, "seq": seq,
                    "arrivals": arr})
    return out


def _join_ranks(sessions: List[dict]) -> List[dict]:
    """Group sessions into ranks by ``(pid, session)`` process identity
    (first-appearance order).  Each rank keeps its merged record list,
    its clock points, and the indices of its sessions."""
    ranks: List[dict] = []
    index: Dict[Tuple, int] = {}
    for i, s in enumerate(sessions):
        m = s.get("meta") or {}
        key = (m.get("pid"), m.get("session")) if m.get("session") \
            else ("anon", i)
        if key not in index:
            index[key] = len(ranks)
            ranks.append({"key": key, "meta": m, "records": [],
                          "session_indices": []})
        rk = ranks[index[key]]
        rk["records"].extend(s["records"])
        rk["session_indices"].append(i)
    for rk in ranks:
        rk["clock"] = _clock_points(
            [sessions[i] for i in rk["session_indices"]])
    return ranks


# ------------------------------------------------------------- analysis
def analyze_sessions(sessions: List[dict]) -> dict:
    """Mesh diagnosis of pre-read sessions (see :func:`analyze`)."""
    ranks = _join_ranks(sessions)
    n_ranks = len(ranks)
    notes: List[str] = []
    truncated = sum(
        1 for s in sessions for r in s["records"]
        if r["kind"] == "event" and r["name"] == "mesh_truncated_tail")

    # per-rank clock fit + aligned record times
    fits = []
    for rk in ranks:
        offset, drift, n = fit_clock(rk["clock"])
        fits.append((offset, drift, n))
    base_off = fits[0][0] if fits else 0.0

    def wall(rank_id: int, t: float) -> float:
        off, drift, _ = fits[rank_id]
        return t * (1.0 + drift) + off

    # collective join (reuse the raw join, then align)
    rvs = rendezvous_from_sessions(sessions)
    rendezvous: List[dict] = []
    wait_by_rank: Dict[int, float] = {r: 0.0 for r in range(n_ranks)}
    wait_by_op: Dict[str, float] = {}
    induced: Dict[int, float] = {r: 0.0 for r in range(n_ranks)}
    last_counts: Dict[int, int] = {r: 0 for r in range(n_ranks)}
    part_counts: Dict[int, int] = {r: 0 for r in range(n_ranks)}
    groups: Dict[str, dict] = {}
    for rv in rvs:
        arr = sorted(((wall(a["rank"], a["t"]), a) for a in rv["arrivals"]),
                     key=lambda p: p[0])
        t_first, t_last = arr[0][0], arr[-1][0]
        last_rank = arr[-1][1]["rank"]
        spread = max(t_last - t_first, 0.0)
        waits: Dict[int, float] = {}
        total_wait = 0.0
        for t_a, a in arr:
            w = max(t_last - t_a, 0.0)
            # a rank cannot have waited longer than it was inside the
            # collective — clock skew past the span length is clamped
            dur = max(wall(a["rank"], a["t_done"]) - t_a, 0.0)
            if dur > 0.0:
                w = min(w, dur)
            waits[a["rank"]] = w
            total_wait += w
            wait_by_rank[a["rank"]] += w
            part_counts[a["rank"]] += 1
        induced[last_rank] += total_wait
        last_counts[last_rank] += 1
        wait_by_op[rv["op"]] = wait_by_op.get(rv["op"], 0.0) + total_wait
        gkey = f"{rv['op']} {rv['group']}"
        g = groups.setdefault(gkey, {
            "op": rv["op"], "group": rv["group"], "collectives": 0,
            "wait_s": 0.0, "spread_s": 0.0, "last_by_rank": {}})
        g["collectives"] += 1
        g["wait_s"] += total_wait
        g["spread_s"] += spread
        g["last_by_rank"][last_rank] = \
            g["last_by_rank"].get(last_rank, 0) + 1
        rendezvous.append({
            "op": rv["op"], "group": rv["group"], "seq": rv["seq"],
            "n_ranks": len(arr), "t_first_s": round(t_first, 9),
            "spread_s": round(spread, 9), "last_rank": last_rank,
            "wait_total_s": round(total_wait, 9),
            "waits": {r: round(w, 9) for r, w in sorted(waits.items())},
            "fused": any(a.get("fused") for _, a in arr),
        })

    # per-group skew decomposition: between two consecutive rendezvous
    # of one group every rank ran the same program, so the arrival
    # SPREAD is the compute skew accumulated since the last sync
    for g in groups.values():
        n = g["collectives"]
        g["mean_spread_s"] = round(g["spread_s"] / n, 9) if n else 0.0
        g["wait_s"] = round(g["wait_s"], 9)
        g.pop("spread_s", None)
        if g["last_by_rank"]:
            lr, cnt = max(g["last_by_rank"].items(),
                          key=lambda kv: (kv[1], -kv[0]))
            g["last_rank_mode"] = lr
            g["last_share"] = round(cnt / n, 4)

    total_induced = sum(induced.values())
    measured = n_ranks >= 2 and bool(rendezvous)
    if n_ranks < 2:
        notes.append("single-rank trace: no cross-rank rendezvous to "
                     "reconstruct")
    elif not rendezvous:
        notes.append("no matchable collective spans/events "
                     "(exchange_halo / krylov_comm / dist_agglomerate) "
                     "appear on 2+ ranks")
    if truncated:
        notes.append(f"{truncated} truncated trailing line(s) skipped "
                     "(rank killed mid-write)")

    # per-rank health under the honesty invariant
    rank_out: Dict[int, dict] = {}
    ends = []
    for rank_id, rk in enumerate(ranks):
        ts = [wall(rank_id, r["t"]) for r in rk["records"]]
        t_first = min(ts) if ts else 0.0
        t_last = max(ts) if ts else 0.0
        ends.append(t_last)
        w = round(max(t_last - t_first, 0.0), 9)
        wait = round(min(wait_by_rank.get(rank_id, 0.0), w), 9)
        # compute = top-level span time net of the waits those spans
        # contain; clamped so the invariant closes exactly
        begins = {r["sid"]: r for r in rk["records"]
                  if r["kind"] == "span_begin"}
        comp_raw = 0.0
        for r in rk["records"]:
            if r["kind"] != "span_end":
                continue
            b = begins.get(r["sid"])
            if b is None or b.get("parent") is None:
                comp_raw += float(r.get("dur") or 0.0)
        compute = round(min(max(comp_raw - wait, 0.0),
                            max(w - wait, 0.0)), 9)
        unatt = round(w - wait - compute, 9)
        if unatt < 0.0:
            unatt = 0.0
            compute = round(max(w - wait, 0.0), 9)
        halo_bytes = sum(
            int(r["value"]) for r in rk["records"]
            if r["kind"] == "counter"
            and r["name"] == "amgx_halo_bytes_total"
            and isinstance(r["value"], (int, float)))
        off, drift, n_clk = fits[rank_id]
        rank_out[rank_id] = {
            "pid": rk["meta"].get("pid"),
            "session": rk["meta"].get("session"),
            "host": rk["meta"].get("host"),
            "wall_s": w, "compute_s": compute, "wait_s": wait,
            "unattributed_s": unatt,
            "straggler_score": round(
                induced[rank_id] / total_induced, 4)
            if total_induced > 0 else 0.0,
            "arrived_last": last_counts[rank_id],
            "collectives": part_counts[rank_id],
            "induced_wait_s": round(induced[rank_id], 9),
            "halo_bytes": halo_bytes,
            "clock_skew_s": round(off - base_off, 9),
            "clock_drift_ppm": round(drift * 1e6, 3),
            "clock_samples": n_clk,
            "first_t_s": round(t_first, 9),
            "last_t_s": round(t_last, 9),
        }

    # silent-rank / desync detection
    desync: List[dict] = []
    if n_ranks >= 2 and ends:
        mesh_end = max(ends)
        starts = [rank_out[r]["first_t_s"] for r in rank_out]
        span = max(mesh_end - min(starts), 0.0)
        for rank_id in rank_out:
            gap = mesh_end - ends[rank_id]
            if span > 0 and gap > max(SILENT_FRACTION * span,
                                      SILENT_MIN_S):
                desync.append({
                    "kind": "silent", "rank": rank_id,
                    "gap_s": round(gap, 9),
                    "gap_fraction": round(gap / span, 4),
                    "last_t_s": rank_out[rank_id]["last_t_s"]})
        # a rank that ran FEWER collectives of a key than its peers
        # desynced mid-program (crash, divergent control flow)
        key_counts: Dict[Tuple[str, str], Dict[int, int]] = {}
        for rank_id, rk in enumerate(ranks):
            for c in _rank_collectives(rk["records"]):
                d = key_counts.setdefault((c["op"], c["group"]), {})
                d[rank_id] = d.get(rank_id, 0) + 1
        for (op, group), d in sorted(key_counts.items()):
            mx = max(d.values())
            for rank_id in rank_out:
                n = d.get(rank_id, 0)
                if n < mx:
                    desync.append({
                        "kind": "missing_collectives", "rank": rank_id,
                        "op": op, "group": group,
                        "ran": n, "peers_ran": mx})

    return {
        "measured": measured,
        "mesh_version": MESH_VERSION,
        "n_ranks": n_ranks,
        "n_sessions": len(sessions),
        "ranks": rank_out,
        "rendezvous": rendezvous,
        "groups": {k: groups[k] for k in sorted(groups)},
        "collectives": {
            op: sum(1 for rv in rendezvous if rv["op"] == op)
            for op in OPS if any(rv["op"] == op for rv in rendezvous)},
        "wait_by_op": {k: round(v, 9)
                       for k, v in sorted(wait_by_op.items())},
        "total_wait_s": round(sum(wait_by_rank.values()), 9),
        "desync": desync,
        "truncated_tails": truncated,
        "notes": notes,
    }


def analyze(source: Union[str, List[str], Iterable[str]]) -> dict:
    """Mesh diagnosis of one or more JSONL traces.

    ``source``: a path, a list of paths (one per rank — or one file
    every rank appended to), or an iterable of JSONL lines.  Returns
    the mesh dict (see :func:`analyze_sessions`); a single-rank trace
    degrades to an honest ``measured=False`` stub."""
    if isinstance(source, str):
        sessions = read_sessions(source)
    else:
        src = list(source)
        if src and isinstance(src[0], str) and "\n" not in src[0] \
                and not src[0].lstrip().startswith("{"):
            sessions = []
            for p in src:
                sessions.extend(read_sessions(p))
        else:
            sessions = read_sessions(src)
    return analyze_sessions(sessions)


# ------------------------------------------------------------- emission
def emit(mesh: dict):
    """Record the mesh analysis into the ring + registry: one
    ``mesh_health`` event per rank (schema-enforced honesty invariant),
    one ``mesh_rendezvous`` event per reconstructed collective, and the
    ``amgx_mesh_*`` metric family.  No-op when telemetry is off."""
    if not recorder.is_enabled():
        return
    measured = bool(mesh.get("measured"))
    for rank_id, r in sorted((mesh.get("ranks") or {}).items()):
        recorder.event(
            "mesh_health", rank=int(rank_id), measured=measured,
            mesh_version=int(mesh.get("mesh_version", MESH_VERSION)),
            wall_s=r["wall_s"], compute_s=r["compute_s"],
            wait_s=r["wait_s"], unattributed_s=r["unattributed_s"],
            straggler_score=r["straggler_score"],
            arrived_last=int(r["arrived_last"]),
            collectives=int(r["collectives"]),
            halo_bytes=int(r["halo_bytes"]),
            clock_skew_s=r["clock_skew_s"])
        if r["wait_s"] > 0:
            metrics.counter_inc("amgx_mesh_wait_seconds_total",
                                r["wait_s"], rank=int(rank_id))
        metrics.gauge_set("amgx_mesh_straggler_score",
                          r["straggler_score"], rank=int(rank_id))
        metrics.gauge_set("amgx_mesh_clock_skew_seconds",
                          r["clock_skew_s"], rank=int(rank_id))
    for rv in mesh.get("rendezvous") or []:
        recorder.event(
            "mesh_rendezvous", op=rv["op"], group=str(rv["group"]),
            seq=int(rv["seq"]), n_ranks=int(rv["n_ranks"]),
            spread_s=rv["spread_s"], last_rank=int(rv["last_rank"]),
            wait_total_s=rv["wait_total_s"], measured=measured)
