"""Convergence forensics: where does the *error reduction* go?

The spans/cost-model layer (PRs 2-3) answers "where does the time go";
this module answers the numerical twin — which level or cycle component
stopped pulling its weight.  The reference ships the same visibility as
its grid/solve statistics machinery (``obtain_norm`` + the ``print_*``
knobs); here it is structured telemetry, gated by the ``forensics``
config knob (off by default; the traced cycle is unchanged when off).

Three pieces:

* **cycle anatomy** — :mod:`amgx_tpu.amg.cycles` records, per level and
  per cycle, the residual norm at the four cut points (cycle entry,
  after pre-smooth, after the coarse-grid correction, after
  post-smooth) as ``cycle_level`` events (plus ``cycle_coarse`` for the
  coarsest-grid solve).  :func:`cycle_anatomy` turns those into
  per-level/per-component reduction factors (geometric means) and
  :func:`weakest_component` names the bottleneck.
* **hierarchy quality probes** — :func:`probe_hierarchy` runs cheap
  algebraic health metrics per level at setup time: near-nullspace
  preservation ``‖A·1‖∞/‖A‖∞``, a sampled Galerkin consistency check
  (``R·A·P`` vs the stored coarse operator), CF-splitting/coarsening
  ratios and a strength-graph sample — exported as the
  ``amgx_forensics_*`` gauges and ``forensics_probe`` events.
* **per-solve estimate** — :func:`asymptotic_rate` estimates the
  asymptotic convergence factor from the trailing residual history
  (the early iterations of a Krylov-accelerated solve are not
  representative; the tail is what predicts iteration growth).

Everything here is host-side (numpy/scipy) — the only traced code is
the instrumentation in ``amg/cycles.py``, which hands norms to
:func:`emit_cycle_level` through ``jax.debug.callback``.
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional

import numpy as np

from . import recorder
from .metrics import gauge_set, registry

#: cut-point component names, in cycle order
COMPONENTS = ("pre_smooth", "coarse_corr", "post_smooth")

#: per-level probes never assemble a host CSR beyond this many rows —
#: forensics is opt-in, but a 128³ fine level is still ~2M rows and the
#: fine operator's health is visible from the sampled rows alone
PROBE_MAX_ROWS = 1 << 21

#: rows sampled for the strength-graph statistic
_STRENGTH_SAMPLE = 256
#: coarse rows sampled for the Galerkin consistency spot-check
_GALERKIN_SAMPLE = 64
#: the AHAT-style strength threshold used by the probe (a fixed probe
#: constant, not the configured one — the probe is a health indicator,
#: not a re-run of the setup)
_STRENGTH_THETA = 0.25

#: every gauge family this module owns (cleared before re-emission so a
#: shallower rebuild leaves no stale deep-level series)
FORENSICS_GAUGES = (
    "amgx_forensics_nullspace",
    "amgx_forensics_galerkin_err",
    "amgx_forensics_cf_ratio",
    "amgx_forensics_strong_frac",
)


# ------------------------------------------------------------- emission
def _scalar(v) -> float:
    """Callback payload → float.  Under ``vmap`` (multi-RHS solves) the
    norms arrive batched; the max lane matches the solver's max-norm
    convention for telemetry."""
    a = np.asarray(v, dtype=np.float64).reshape(-1)
    return float(np.max(a)) if a.size else float("nan")


def emit_cycle_level(level: int, flavor: str, entry, pre, coarse, post):
    """Host-side sink of the traced cut-point norms of one level of one
    cycle (``jax.debug.callback`` target — see ``amg/cycles.py``)."""
    if not recorder.is_enabled():
        return
    recorder.event("cycle_level", level=int(level), flavor=str(flavor),
                   entry=_scalar(entry), pre=_scalar(pre),
                   coarse=_scalar(coarse), post=_scalar(post))


def emit_cycle_coarse(level: int, entry, exit_):
    """Coarsest-grid solve norms (two cut points: entry/exit)."""
    if not recorder.is_enabled():
        return
    recorder.event("cycle_coarse", level=int(level),
                   entry=_scalar(entry), exit=_scalar(exit_))


# ------------------------------------------------------------- analysis
def _gmean(factors: List[float]) -> Optional[float]:
    logs = [math.log(f) for f in factors
            if isinstance(f, (int, float)) and math.isfinite(f) and f > 0]
    if not logs:
        return None
    return float(math.exp(sum(logs) / len(logs)))


def _factor(num, den) -> Optional[float]:
    if not (isinstance(num, (int, float)) and isinstance(den, (int, float))):
        return None
    if not (math.isfinite(num) and math.isfinite(den)) or den <= 0:
        return None
    return num / den


def cycle_anatomy(records: Iterable[dict]) -> Dict:
    """Per-level/per-component reduction factors from ``cycle_level`` /
    ``cycle_coarse`` telemetry events.

    Returns ``{"levels": {lvl: {"cycles": n, "pre_smooth": f,
    "coarse_corr": f, "post_smooth": f, "total": f}}, "coarse":
    {"level": L, "cycles": n, "factor": f} | None}`` where each ``f`` is
    the geometric-mean per-cycle reduction factor of that component
    (None when no finite sample survived)."""
    per: Dict[int, Dict[str, List[float]]] = {}
    coarse: Dict[int, List[float]] = {}
    for r in records:
        if r.get("kind") != "event":
            continue
        a = r.get("attrs", {})
        if r.get("name") == "cycle_level":
            lvl = int(a.get("level", -1))
            d = per.setdefault(lvl, {c: [] for c in
                                     COMPONENTS + ("total",)})
            for comp, num, den in (("pre_smooth", a.get("pre"),
                                    a.get("entry")),
                                   ("coarse_corr", a.get("coarse"),
                                    a.get("pre")),
                                   ("post_smooth", a.get("post"),
                                    a.get("coarse")),
                                   ("total", a.get("post"),
                                    a.get("entry"))):
                f = _factor(num, den)
                if f is not None:
                    d[comp].append(f)
        elif r.get("name") == "cycle_coarse":
            f = _factor(a.get("exit"), a.get("entry"))
            if f is not None:
                coarse.setdefault(int(a.get("level", -1)), []).append(f)
    levels = {}
    for lvl, d in sorted(per.items()):
        levels[lvl] = {"cycles": max(len(v) for v in d.values())}
        for comp in COMPONENTS + ("total",):
            levels[lvl][comp] = _gmean(d[comp])
    coarse_out = None
    if coarse:
        lvl = max(coarse)
        coarse_out = {"level": lvl, "cycles": len(coarse[lvl]),
                      "factor": _gmean(coarse[lvl])}
    return {"levels": levels, "coarse": coarse_out}


#: per-component factor at which the component counts as outright
#: failing — the normalization that lets components compete on one
#: axis.  Coarse correction's bar is higher on purpose: its RESIDUAL
#: factor routinely exceeds 1 transiently on healthy cycles (the
#: prolongated correction injects high-frequency residual the
#: post-smoother removes), so ranking it raw against smoothing
#: factors would misattribute a dead smoother's bottleneck to a
#: healthy coarse correction.
_COMPONENT_BASELINE = {"pre_smooth": 1.0, "post_smooth": 1.0,
                       "coarse_corr": 1.5, "coarse_solve": 1.0}


def component_score(component: str, factor: float) -> float:
    """Cross-component severity: the factor normalised by the
    component's own failure baseline (1.0 ≈ 'does nothing at all' for
    a smoother, 'pathologically amplifying' for coarse correction)."""
    return factor / _COMPONENT_BASELINE.get(component, 1.0)


def weakest_component(anatomy: Dict) -> Optional[Dict]:
    """The level/component with the worst baseline-normalised
    reduction factor — the convergence bottleneck the doctor names.
    The coarsest-grid solve competes as component ``coarse_solve``.
    ``factor`` is the raw geometric-mean factor; ``score`` the
    normalised severity the ranking used."""
    worst = None
    candidates = [(int(lvl), comp, d.get(comp))
                  for lvl, d in anatomy.get("levels", {}).items()
                  for comp in COMPONENTS]
    c = anatomy.get("coarse")
    if c and c.get("factor") is not None:
        candidates.append((int(c["level"]), "coarse_solve",
                           c["factor"]))
    for lvl, comp, f in candidates:
        if f is None:
            continue
        score = component_score(comp, f)
        if worst is None or score > worst["score"]:
            worst = {"level": lvl, "component": comp, "factor": f,
                     "score": score}
    return worst


def asymptotic_rate(norms: List[float]) -> Optional[float]:
    """Asymptotic convergence-factor estimate from a residual history:
    the geometric-mean per-iteration reduction over the trailing half
    of the trajectory (min 2 steps).  The early iterations of a
    Krylov-accelerated solve over-perform; the tail is what predicts
    how iteration counts scale with problem size."""
    ns = [float(n) for n in norms
          if isinstance(n, (int, float)) and math.isfinite(n) and n > 0]
    if len(ns) < 3:
        return None
    m = max(2, (len(ns) - 1) // 2)
    a, b = ns[-1 - m], ns[-1]
    if a <= 0 or b <= 0:
        return None
    return float((b / a) ** (1.0 / m))


def analyze(records: Iterable[dict]) -> Optional[Dict]:
    """One-stop analysis of a record stream (a :class:`Capture`'s
    records, the ring, or a parsed trace): cycle anatomy + probes +
    the weakest component.  None when the stream carries no forensics
    events at all (forensics was off)."""
    records = list(records)
    anatomy = cycle_anatomy(records)
    probes: Dict[int, dict] = {}
    rate = None
    for r in records:
        if r.get("kind") != "event":
            continue
        if r.get("name") == "forensics_probe":
            a = dict(r.get("attrs", {}))
            probes[int(a.pop("level", -1))] = a
        elif r.get("name") == "solve_forensics":
            rate = r.get("attrs", {}).get("asymptotic_rate", rate)
    if not anatomy["levels"] and not probes and rate is None:
        return None
    return {"levels": anatomy["levels"], "coarse": anatomy["coarse"],
            "probes": probes, "weakest": weakest_component(anatomy),
            "asymptotic_rate": rate}


# -------------------------------------------------------------- probes
def _csr(m):
    """Best-effort scalar CSR of a Matrix handle; None when the level
    is device-only or too large to assemble for a probe."""
    try:
        if m is None or m.n_block_rows > PROBE_MAX_ROWS:
            return None
        return m.scalar_csr()
    except Exception:
        return None


def _nullspace_metric(A) -> Optional[float]:
    """Near-nullspace preservation ``‖A·1‖∞ / ‖A‖∞``: a Poisson-class
    operator annihilates the constant vector away from boundaries, and
    a Galerkin coarse operator must inherit that — a level where this
    jumps toward 1 lost the near-nullspace (bad interpolation)."""
    try:
        rowsum = np.abs(np.asarray(A @ np.ones(A.shape[1]))).ravel()
        absrow = np.asarray(abs(A).sum(axis=1)).ravel()
        den = float(absrow.max()) if absrow.size else 0.0
        if den <= 0:
            return None
        return float(rowsum.max() / den)
    except Exception:
        return None


def _strength_metric(A, rng) -> Optional[float]:
    """Strength-graph sample: the fraction of off-diagonal couplings
    that are strong under the AHAT-style criterion
    ``|a_ij| ≥ θ·max_k|a_ik|`` over up to 256 sampled rows."""
    try:
        n = A.shape[0]
        rows = rng.choice(n, size=min(_STRENGTH_SAMPLE, n),
                          replace=False)
        strong = total = 0
        indptr, indices, data = A.indptr, A.indices, A.data
        for i in rows:
            lo, hi = indptr[i], indptr[i + 1]
            off = np.abs(data[lo:hi][indices[lo:hi] != i])
            if off.size == 0:
                continue
            total += off.size
            strong += int((off >= _STRENGTH_THETA * off.max()).sum())
        if total == 0:
            return None
        return float(strong / total)
    except Exception:
        return None


def _galerkin_metric(A, handles, Ac, rng) -> Optional[float]:
    """Sampled Galerkin consistency: ``(R·A·P)`` on up to 64 coarse
    rows vs the STORED coarse operator (relative Frobenius error).
    Catches value drift between the recorded hierarchy and what the
    transfers actually compose to (e.g. a resetup refresh gone
    stale)."""
    try:
        P = _csr(handles.get("P"))
        R = _csr(handles.get("R"))
        if P is None or R is None or Ac is None:
            return None
        nc = Ac.shape[0]
        rows = rng.choice(nc, size=min(_GALERKIN_SAMPLE, nc),
                          replace=False)
        lhs = (R[rows] @ A) @ P
        rhs = Ac[rows]
        dden = float(np.sqrt((rhs.power(2)).sum()))
        derr = float(np.sqrt(((lhs - rhs).power(2)).sum()))
        return derr / max(dden, 1e-300)
    except Exception:
        return None


def probe_hierarchy(h) -> List[dict]:
    """Run the per-level quality probes over a built ``AMGHierarchy``,
    emit the ``amgx_forensics_*`` gauges + one ``forensics_probe``
    event per level, and return the per-level probe dicts (fine to
    coarsest-but-one; the coarsest grid has no transfers to probe).

    Cheap by construction: inf-norms and one matvec per level, sampled
    strength rows, a ≤64-row Galerkin product — and never a host CSR
    past :data:`PROBE_MAX_ROWS` rows."""
    reg = registry()
    for name in FORENSICS_GAUGES:
        reg.gauge_clear(name)
    sizes = h.level_sizes()
    rng = np.random.default_rng(12345)
    out: List[dict] = []
    for i, lvl in enumerate(h.levels):
        handles = lvl.probe_handles()
        A = _csr(handles.get("A"))
        nxt = h.levels[i + 1].A if i + 1 < len(h.levels) else h.coarsest
        probe = {"level": i, "kind": getattr(lvl, "kind", "?"),
                 "rows": int(sizes[i][0]),
                 "cf_ratio": (sizes[i + 1][0] / sizes[i][0]
                              if sizes[i][0] else None)}
        if A is not None:
            probe["nullspace"] = _nullspace_metric(A)
            probe["strong_frac"] = _strength_metric(A, rng)
            probe["galerkin_err"] = _galerkin_metric(A, handles,
                                                     _csr(nxt), rng)
        cf_map = handles.get("cf_map")
        if cf_map is not None:
            # the realised C/F split of a classical level (coarse
            # fraction of the FINE rows — the PMIS outcome itself)
            probe["cf_coarse_frac"] = float(np.mean(
                np.asarray(cf_map, dtype=np.float64)))
        for key, gname in (("nullspace", "amgx_forensics_nullspace"),
                           ("galerkin_err", "amgx_forensics_galerkin_err"),
                           ("cf_ratio", "amgx_forensics_cf_ratio"),
                           ("strong_frac", "amgx_forensics_strong_frac")):
            v = probe.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                gauge_set(gname, v, level=i)
        recorder.event("forensics_probe",
                       **{k: v for k, v in probe.items()})
        out.append(probe)
    return out
