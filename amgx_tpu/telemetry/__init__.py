"""Structured solver telemetry.

One structured layer over what the reference scattered across the CPU
profiler tree (``amgx_timer.h``), per-level ``levelProfile`` timers and
the grid-stats table:

* **spans/events** (:mod:`.recorder`): ``span(name, **attrs)`` phase
  markers (which also aggregate into the legacy profiler tree and the
  ``jax.profiler.TraceAnnotation`` forwarding) and ``event(name,
  **attrs)`` point records, appended to a bounded ring buffer;
* **metrics** (:mod:`.metrics`): counters/gauges/histograms under
  stable, versioned names (``METRICS``) — SpMV pack-selection counts,
  jit recompiles, phase durations, hierarchy complexities, per-solve
  iteration/residual gauges;
* **exporters** (:mod:`.export`): JSONL traces (``dump_jsonl`` /
  incremental ``flush_jsonl``), a Prometheus text snapshot
  (``prometheus_text``), the schema validator used by
  ``scripts/telemetry_check.py``, and the multi-process session merger
  (``aggregate_sessions`` — one mesh-wide view of per-rank traces);
* **analysis** (PR 3): :mod:`.costmodel` (static bytes/FLOPs/padding
  descriptors per SpMV pack, roofline fractions), :mod:`.tracefile`
  (Chrome-trace export — view a solve in Perfetto), :mod:`.doctor`
  (``python -m amgx_tpu.telemetry.doctor trace.jsonl`` diagnosis,
  ``--diff`` for two-trace A/B comparison);
* **convergence forensics** (:mod:`.forensics`): per-level cycle
  anatomy (residual norms at the four cut points of every cycle),
  hierarchy quality probes at setup, asymptotic convergence-factor
  estimates — gated by the ``forensics`` config knob;
* **device-time attribution** (PR 17): :mod:`.scopes` (the versioned
  ``amgx/<area>/<name>`` ``jax.named_scope`` contract every
  instrumented kernel carries), :mod:`.proftrace` (shared chrome-trace
  parsing/discovery plumbing), :mod:`.deviceprof` (the profiler-trace
  correlator: per-level / per-pack / per-stage **measured device
  seconds** + measured SpMV bandwidth vs the modelled roofline,
  emitted as the ``device_anatomy`` event and
  ``amgx_device_time_seconds_total{scope}``), and :mod:`.overlap`
  (measured interior/halo overlap riding the same plumbing);
* **HBM ledger** (PR 18): :mod:`.memledger` — device-memory ownership
  attribution under the versioned ``amgx/<owner>/<name>`` taxonomy
  (registry + ``jax.live_arrays`` census + backend ``memory_stats``
  truth, honesty invariant ``accounted + unaccounted ≡ bytes_in_use``),
  ``hbm_snapshot`` sampling and ``oom_postmortem`` bundles — gated by
  the ``memledger`` knob;
* **mesh flight recorder** (PR 20): :mod:`.meshtrace` — clock-aligned
  cross-rank timelines (per-session offset+drift fit over the paired
  ``t_perf``/``t_unix`` samples), collective-rendezvous reconstruction
  (halo hops, fused Krylov reductions, agglomerations matched by
  (op, group, sequence)), per-rank wait/straggler attribution under
  the honesty invariant ``compute + wait + unattributed ≡ wall``
  (schema-enforced ``mesh_health`` events), and silent-rank/desync
  detection — surfaced as ``amgx_mesh_*`` metrics, the doctor's
  "Mesh health" section, Chrome-trace rendezvous flow arrows and
  ``/debug/mesh``;
* **live serving observability**: :mod:`.slo` (time-windowed
  request-outcome reservoir → attainment / error-budget burn rate /
  overload detection) and :mod:`.httpd` (in-process
  ``/metrics`` ``/healthz`` ``/statusz`` ``/debug/trace``
  ``/debug/profile`` endpoint behind the ``metrics_port`` knob).

Everything is **off by default** and compiled down to one attribute
check per instrument; enable globally with :func:`enable`, per config
with the ``telemetry=1`` knob (plus ``telemetry_path`` /
``telemetry_ring_size``), or scoped with :func:`capture` in tests.
"""
from __future__ import annotations

from . import (costmodel, deviceprof, export, forensics, memledger,
               meshtrace, metrics, overlap, proftrace, recorder,
               runstate, scopes, setup_profile, slo, tracefile)
from .export import (aggregate_sessions, dump_jsonl, flush_jsonl,
                     prometheus_text, read_sessions, validate_jsonl,
                     validate_record)
from .metrics import (METRICS, counter_inc, gauge_set, hist_observe,
                      registry)
from .recorder import (SCHEMA_VERSION, Capture, capture, clear, disable,
                       dropped_count, enable, event, is_enabled, records,
                       span)
from .tracefile import (chrome_trace, validate_chrome_trace,
                        write_chrome_trace)

__all__ = [
    "SCHEMA_VERSION", "METRICS", "Capture",
    "enable", "disable", "is_enabled", "capture", "clear", "records",
    "span", "event", "dropped_count",
    "counter_inc", "gauge_set", "hist_observe", "registry",
    "dump_jsonl", "flush_jsonl", "prometheus_text",
    "validate_record", "validate_jsonl",
    "read_sessions", "aggregate_sessions",
    "chrome_trace", "write_chrome_trace", "validate_chrome_trace",
    "costmodel", "forensics", "setup_profile", "runstate",
    "slo", "httpd",
    "proftrace", "scopes", "deviceprof", "overlap", "memledger",
    "meshtrace",
    "reset",
]


def __getattr__(name):
    # httpd is the ONLY lazily-bound submodule: it pulls the stdlib
    # http.server → http.client → email import chain, which every
    # non-serving `import amgx_tpu` would otherwise pay for an endpoint
    # that is off by default (serve/service.py lazy-imports it too)
    if name == "httpd":
        # importlib, not `from . import`: the fromlist resolution calls
        # getattr on this package and would re-enter this hook forever
        import importlib
        mod = importlib.import_module(".httpd", __name__)
        globals()["httpd"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def reset():
    """Drop buffered records, zero the metrics registry and the
    ring-overflow counter (test/bench isolation helper; recording stays
    in whatever on/off state it was)."""
    recorder.clear()
    recorder.reset_dropped()
    metrics.registry().reset()
    setup_profile.reset()
    memledger.reset()
