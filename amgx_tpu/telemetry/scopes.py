"""The versioned ``jax.named_scope`` naming contract.

Host spans (:mod:`amgx_tpu.telemetry.recorder`) measure *dispatch*
time under JAX's async dispatch — to attribute measured **device**
time to amgx concepts, the kernels themselves carry
``jax.named_scope`` annotations that XLA threads through to the
profiler trace's op metadata.  This module is the single authority for
those names:

``amgx/<area>/<name>``

where ``<area>`` is one of :data:`AREAS` and every ``/``-separated
segment matches ``[a-z0-9_]+``.  Dots and hyphens are deliberately
EXCLUDED from the segment alphabet: XLA appends its own op names to
the scope ("…/fusion.3", "…/all-reduce.1"), and the restricted
alphabet lets :func:`extract_scopes` cut the known-contract prefix
back out of a polluted trace string.

The vocabulary per area:

* ``cycle``  — ``level<N>/{pre_smooth,post_smooth,restrict,prolong}``,
  ``coarse_solve``, ``kcycle<N>`` (amg/cycles.py)
* ``spmv``   — the sanitised dispatch pack names of
  :data:`SPMV_PACKS` (ops/spmv.py; ``-`` → ``_``)
* ``smoother`` — the registered smoother's config name, sanitised
  (solvers/base.py wraps every smoother application)
* ``krylov`` — the fixed stage vocabulary :data:`KRYLOV_STAGES`
  (solvers/krylov.py)
* ``dist``   — ``halo_exchange`` (distributed/matrix.py)

Bump :data:`SCOPE_VERSION` when names change meaning — the
``device_anatomy`` event carries it so old traces stay interpretable.
"""
from __future__ import annotations

import re
from typing import Iterable, List, Optional

#: version of the naming contract carried by every device_anatomy event
SCOPE_VERSION = 1

#: the taxonomy's top-level areas
AREAS = ("cycle", "spmv", "smoother", "krylov", "dist")

#: every SpMV dispatch pack name ops/spmv.py can label a dispatch with
#: (the un-sanitised telemetry spelling — scripts/telemetry_check.py
#: cross-checks this list against the dispatch sites so it cannot rot)
SPMV_PACKS = (
    "sharded", "dia3", "op",
    "dia/kernel", "dia/slices", "dia/block-kernel", "dia/block-slices",
    "dense",
    "ell/shift", "ell/window", "ell/binned", "ell/gather",
    "ell/binned-block", "ell/block-gather",
    "csr/binned", "csr/binned-block",
    "csr/segsum", "csr/segsum-lean", "csr/block-segsum",
)

#: the Krylov per-stage vocabulary (solvers/krylov.py)
KRYLOV_STAGES = ("precond", "reduce", "arnoldi", "givens", "update")

#: the per-level cycle components (amg/cycles.py)
CYCLE_COMPONENTS = ("pre_smooth", "post_smooth", "restrict", "prolong")

_SEG = r"[a-z0-9_]+"
#: full-match check of a finished scope name
SCOPE_RE = re.compile(rf"amgx(?:/{_SEG})+\Z")
#: extraction hint over raw trace strings (op names / metadata) — no
#: trailing anchor; dots, hyphens, uppercase terminate the match
TRACE_RE = re.compile(rf"amgx(?:/{_SEG})+")

_CYCLE_LEVEL_RE = re.compile(r"level\d+\Z")
_KCYCLE_RE = re.compile(r"kcycle\d+\Z")


def sanitize(name: str) -> str:
    """Map any label into the scope segment alphabet: lowercase, and
    every character outside ``[a-z0-9_/]`` becomes ``_`` (so the pack
    name ``ell/binned-block`` scopes as ``ell/binned_block``)."""
    return re.sub(r"[^a-z0-9_/]", "_", str(name).lower())


def scope_name(area: str, name: str) -> str:
    """The contract name ``amgx/<area>/<sanitised name>``.

    Raises ``ValueError`` on an unknown area or a name that cannot be
    sanitised into the contract (empty segments).
    """
    if area not in AREAS:
        raise ValueError(f"unknown scope area {area!r} "
                         f"(contract v{SCOPE_VERSION} areas: {AREAS})")
    s = f"amgx/{area}/{sanitize(name)}"
    if not SCOPE_RE.match(s):
        raise ValueError(f"scope name {s!r} violates the "
                         f"amgx/<area>/<name> contract")
    return s


def scope(area: str, name: str):
    """A ``jax.named_scope`` context manager carrying the contract name
    (the one primitive every instrumented kernel calls)."""
    import jax
    return jax.named_scope(scope_name(area, name))


def validate(name: str) -> bool:
    """True iff ``name`` is a well-formed contract scope name with a
    known area."""
    if not isinstance(name, str) or not SCOPE_RE.match(name):
        return False
    parts = name.split("/")
    return len(parts) >= 3 and parts[1] in AREAS


#: sanitised pack names, longest first so two-segment packs win the
#: prefix match over their one-segment heads
_SPMV_LEAVES = sorted({sanitize(p) for p in SPMV_PACKS},
                      key=lambda p: -p.count("/"))


def canonicalize(raw: str) -> Optional[str]:
    """Trim a trace-extracted ``amgx/…`` string back to its contract
    scope name, dropping the XLA op-name segments the profiler appended
    ("amgx/cycle/level0/pre_smooth/fusion" →
    "amgx/cycle/level0/pre_smooth").  None when the string is not a
    recognisable scope."""
    if not isinstance(raw, str) or not raw.startswith("amgx/"):
        return None
    segs = raw.split("/")[1:]
    if len(segs) < 2:
        return None
    area, rest = segs[0], segs[1:]
    leaf: Optional[List[str]] = None
    if area == "cycle":
        if _CYCLE_LEVEL_RE.match(rest[0]) and len(rest) >= 2 \
                and rest[1] in CYCLE_COMPONENTS:
            leaf = rest[:2]
        elif rest[0] == "coarse_solve" or _KCYCLE_RE.match(rest[0]):
            leaf = rest[:1]
    elif area == "spmv":
        joined = "/".join(rest)
        for pack in _SPMV_LEAVES:
            if joined == pack or joined.startswith(pack + "/"):
                leaf = pack.split("/")
                break
    elif area == "smoother":
        leaf = rest[:1]
    elif area == "krylov":
        if rest[0] in KRYLOV_STAGES:
            leaf = rest[:1]
    elif area == "dist":
        if rest[0] == "halo_exchange":
            leaf = rest[:1]
    if leaf is None:
        return None
    name = "/".join(["amgx", area] + leaf)
    return name if validate(name) else None


def extract_scopes(text: str) -> List[str]:
    """Every canonical scope name embedded in a raw trace string,
    outermost first.  Nested ``jax.named_scope``s concatenate in the
    profiler metadata ("amgx/cycle/level0/pre_smooth/amgx/spmv/dia3/
    fusion.3"), so each interior ``amgx/`` segment boundary starts a
    new candidate."""
    out: List[str] = []
    for m in TRACE_RE.finditer(text):
        raw = m.group(0)
        starts = [i for i in range(len(raw))
                  if raw.startswith("amgx/", i)
                  and (i == 0 or raw[i - 1] == "/")]
        for j, st in enumerate(starts):
            end = starts[j + 1] if j + 1 < len(starts) else len(raw)
            c = canonicalize(raw[st:end].rstrip("/"))
            if c and c not in out:
                out.append(c)
    return out


def scopes_in_event(ev: dict) -> List[str]:
    """The canonical scopes referenced by one chrome-trace event: its
    name plus any string ``args`` values (XLA places the annotation
    stack in op metadata — ``args["name"]`` / ``args["long_name"]`` /
    ``args["tf_op"]`` depending on version)."""
    found = extract_scopes(str(ev.get("name", "")))
    args = ev.get("args")
    if isinstance(args, dict):
        for v in args.values():
            if isinstance(v, str):
                for s in extract_scopes(v):
                    if s not in found:
                        found.append(s)
    return found


def smoother_scopes(names: Iterable[str]) -> List[str]:
    """Contract scope names for a set of smoother config names (what
    the coverage lint expects solvers/base.py to emit)."""
    return [scope_name("smoother", n) for n in names]
