"""Solve doctor: turn a JSONL telemetry trace into a diagnosis.

``python -m amgx_tpu.telemetry.doctor trace.jsonl [more.jsonl ...]``
reads one or more trace files (multi-process sessions merge into one
mesh-wide view via :func:`amgx_tpu.telemetry.export.aggregate_sessions`)
and prints what a performance engineer would ask the trace first:

* where the wall time went (phase histograms + the span table),
* what SpMV packs were chosen and what fell back, with the cost-model
  view (bytes/flops per level, padding waste),
* the distributed picture: halo wire bytes vs local work, boundary
  fractions, ring hops,
* the convergence trajectory: iterations, final residual, and
  plateau/stall detection over the per-iteration residual events,
* convergence forensics, when the trace carries ``forensics=1`` events
  (:mod:`amgx_tpu.telemetry.forensics`): the per-level per-component
  reduction-factor table (pre-smooth / coarse correction /
  post-smooth), hierarchy quality probes, and the weakest
  level/component named explicitly,
* concrete hints ("level 3 fell back to segment-sum: over padding
  budget by 2.1×", "level 2 post-smooth reduction 0.97 → raise
  postsweeps or switch smoother", ...).

``--diff other.jsonl`` compares two traces level by level — the
pipeline-on/off or 64³-vs-128³ A/B view: iteration counts, asymptotic
rates, per-level component factors side by side with the drifts
called out.  ``--json`` prints the machine-readable diagnosis
instead.  Everything is host-side file parsing — no device work, no
compiles.
"""
from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

from .export import aggregate_sessions
from .forensics import COMPONENTS

#: trailing per-iteration reduction factor above which the residual is
#: considered plateaued (a healthy AMG-preconditioned solve reduces
#: ~10× per iteration; 0.97 ≈ 3%/iter is going nowhere)
PLATEAU_FACTOR = 0.97
PLATEAU_MIN_ITERS = 5
#: padding-waste ratio past which a level pack earns a hint
WASTE_HINT = 2.0
#: levels smaller than this never earn a padding hint (tiny coarse
#: grids pad by construction and cost microseconds)
WASTE_MIN_ROWS = 4096
#: halo-vs-local byte ratio past which the solve reads comms-bound
HALO_HINT = 0.5
#: fraction of a multi-lane service's resident sessions on ONE lane
#: past which the doctor flags lane imbalance (affinity concentrates
#: sessions by design; hoarding means replication never triggered)
LANE_IMBALANCE_SHARE = 0.6
#: per-component geometric-mean reduction factor past which a cycle
#: component earns a "weakest link" hint (a healthy V-cycle smoothing
#: component reduces the residual well below this; 0.85+ means the
#: component barely helps and ~1.0 means it does nothing)
WEAK_COMPONENT = 0.85
#: coarse-correction factor past which the correction is flagged as
#: amplifying.  NOT 1.0: a healthy coarse correction routinely grows
#: the RESIDUAL norm transiently (the prolongated correction injects
#: high-frequency residual the post-smoother then removes) — only
#: sustained growth past this is pathological
AMPLIFY_HINT = 1.5
#: sampled Galerkin relative error past which the stored coarse
#: operator no longer matches R·A·P (value drift, stale resetup)
GALERKIN_ERR_HINT = 1e-6
#: near-nullspace metric past which a level lost the constant vector
NULLSPACE_HINT = 0.9
#: absolute component-factor drift that earns a diff-mode callout
DIFF_DRIFT = 0.1
#: share of a rendezvous group's collectives in which ONE rank arrives
#: last past which the doctor names it a straggler (a balanced mesh
#: rotates the last arrival; a fixed last rank is a partition problem)
MESH_STRAGGLER_SHARE = 0.6
#: rendezvous count below which a group earns no straggler hint (a
#: handful of collectives can all land on one rank by chance)
MESH_MIN_COLLECTIVES = 4
#: fraction of total mesh wait inside fused Krylov reductions past
#: which the doctor points at compute skew instead of the collective
MESH_KRYLOV_WAIT_SHARE = 0.5


def _label_get(labels: Tuple, key: str):
    for k, v in labels:
        if k == key:
            return v
    return None


def _fmt_bytes(b) -> str:
    if b is None:
        return "?"
    for unit in ("B", "KB", "MB", "GB"):
        if abs(b) < 1024.0:
            return f"{b:.1f} {unit}" if unit != "B" else f"{int(b)} B"
        b /= 1024.0
    return f"{b:.1f} TB"


def _residual_trails(agg) -> List[List[Tuple[int, float]]]:
    """Per-solve residual trajectories: the residual events of each
    session, split into trails wherever iteration restarts at 0."""
    trails: List[List[Tuple[int, float]]] = []
    for s in agg["sessions"]:
        cur: List[Tuple[int, float]] = []
        for r in s["records"]:
            if r["kind"] != "event" or r["name"] != "residual":
                continue
            it = r["attrs"].get("iteration")
            nrm = r["attrs"].get("norm")
            if not isinstance(it, int):
                continue
            if isinstance(nrm, str):      # "Infinity"/"NaN" tokens
                nrm = float(nrm.replace("Infinity", "inf")
                            .replace("NaN", "nan"))
            if it == 0 and cur:
                trails.append(cur)
                cur = []
            cur.append((it, float(nrm)))
        if cur:
            trails.append(cur)
    return trails


def _plateau(trail: List[Tuple[int, float]]) -> Optional[dict]:
    """Longest trailing run of per-iteration reduction factors above
    PLATEAU_FACTOR (stall = factor ≥ 1).  None when converging fine."""
    if len(trail) < PLATEAU_MIN_ITERS + 1:
        return None
    norms = [n for _, n in trail]
    run = 0
    stalled = 0
    for a, b in zip(norms[-2::-1], norms[:0:-1]):   # backwards pairs
        if a <= 0:
            break
        f = b / a
        if f > PLATEAU_FACTOR:
            run += 1
            if f >= 1.0:
                stalled += 1
        else:
            break
    if run >= PLATEAU_MIN_ITERS:
        return {"iterations": run, "from_iteration": trail[-1 - run][0],
                "stalled": stalled, "norm": norms[-1]}
    return None


def diagnose(paths: List[str]) -> dict:
    """Machine-readable diagnosis of one or more JSONL traces."""
    agg = aggregate_sessions(paths)
    counters, gauges = agg["counters"], agg["gauges"]

    def csum(name, **match):
        tot = 0.0
        by = {}
        for (n, lk), v in counters.items():
            if n != name:
                continue
            if any(_label_get(lk, k) != str(mv)
                   for k, mv in match.items()):
                continue
            tot += v
            by[",".join(f"{k}={v2}" for k, v2 in lk) or "_"] = v
        return tot, by

    def glast(name):
        out = {}
        for (n, lk), v in gauges.items():
            if n == name:
                out[lk] = v
        return out

    # ---- phases (top-level only: the hist samples) ------------------
    phases = {}
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] != "hist" or not r["name"].startswith("amgx_") \
                    or not r["name"].endswith("_seconds"):
                continue
            if r["name"].startswith("amgx_serve_"):
                # request latency is not a wall phase (overlapping
                # requests sum past wall time); the serving section
                # reports it as percentiles instead
                continue
            key = r["name"][len("amgx_"):-len("_seconds")]
            d = phases.setdefault(key, {"count": 0, "total_s": 0.0})
            d["count"] += 1
            d["total_s"] = round(d["total_s"] + float(r["value"]), 6)

    # ---- packs + fallbacks ------------------------------------------
    _, packs = csum("amgx_spmv_dispatch_total")
    _, fallbacks = csum("amgx_spmv_fallback_total")

    # ---- hierarchy + cost model -------------------------------------
    levels = {}
    for lk, v in glast("amgx_level_rows").items():
        levels.setdefault(str(_label_get(lk, "level")), {})["rows"] = v
    for lk, v in glast("amgx_level_nnz").items():
        levels.setdefault(str(_label_get(lk, "level")), {})["nnz"] = v
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] == "event" and r["name"] == "level_cost":
                lvl = str(r["attrs"].get("level"))
                levels.setdefault(lvl, {}).update(
                    {k: v for k, v in r["attrs"].items()
                     if k != "level"})
    op_cost = None
    op_costs = {}              # pack -> last dispatched cost descriptor
    rejected = []
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] != "event":
                continue
            if r["name"] == "operator_cost":
                op_cost = r["attrs"]
            elif r["name"] == "op_cost":
                # keep the LARGEST operator per pack — a hierarchy
                # dispatches many dia levels and the fine one is the
                # number worth showing next to the dispatch count
                pk = str(r["attrs"].get("pack", "?"))
                prev = op_costs.get(pk)
                if prev is None or (r["attrs"].get("bytes_per_apply")
                                    or 0) > (prev.get("bytes_per_apply")
                                             or 0):
                    op_costs[pk] = r["attrs"]
            elif r["name"] == "binned_plan_rejected":
                rejected.append(r["attrs"])

    # ---- distributed ------------------------------------------------
    halo_bytes, halo_by = csum("amgx_halo_bytes_total")
    halo_entries, _ = csum("amgx_halo_entries_total")
    exchanges, _ = csum("amgx_halo_exchange_total")
    bnd = {str(_label_get(lk, "device")): v
           for lk, v in glast("amgx_dist_boundary_fraction").items()}
    # per-level overlap audit + agglomeration lifecycle (PR 12:
    # costmodel.dist_overlap events + distributed/agglomerate.py)
    dist_levels: Dict[str, dict] = {}
    agglomerations: List[dict] = []
    krylov_events: List[dict] = []
    device_anatomy: Optional[dict] = None
    hbm_snapshot: Optional[dict] = None
    oom_postmortems: List[dict] = []
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] != "event":
                continue
            if r["name"] == "dist_overlap":
                dist_levels[str(r["attrs"].get("level"))] = \
                    dict(r["attrs"])
            elif r["name"] == "dist_agglomerate":
                agglomerations.append(dict(r["attrs"]))
            elif r["name"] == "krylov_comm":
                krylov_events.append(dict(r["attrs"]))
            elif r["name"] == "device_anatomy":
                # last anatomy wins — one capture per profiled solve
                device_anatomy = dict(r["attrs"])
            elif r["name"] == "hbm_snapshot":
                # last ledger snapshot wins — what is resident NOW
                hbm_snapshot = dict(r["attrs"])
            elif r["name"] == "oom_postmortem":
                oom_postmortems.append(dict(r["attrs"]))
    local_bytes = sum(float(d.get("bytes_per_apply") or 0)
                      for d in levels.values())
    if not local_bytes and op_cost:
        local_bytes = float(op_cost.get("bytes_per_apply") or 0)
    halo_per_apply = None
    if op_cost and op_cost.get("halo_bytes_per_apply"):
        halo_per_apply = float(op_cost["halo_bytes_per_apply"])
    halo_local_ratio = None
    if halo_per_apply and local_bytes:
        halo_local_ratio = round(halo_per_apply / local_bytes, 4)

    # ---- communication-avoiding Krylov (PR 16: krylov_comm events) --
    # keys on SHARDED solves only — single-device reductions are
    # register traffic and a collectives table there is noise
    krylov = None
    sharded_kc = [e for e in krylov_events
                  if int(e.get("n_parts") or 1) > 1]
    if sharded_kc:
        by_mode: Dict[str, dict] = {}
        for e in sharded_kc:       # last event per (solver, mode) wins
            by_mode[f"{e.get('solver')}/{e.get('mode')}"] = e
        krylov = {
            "solves": by_mode,
            # profiler-measured overlap fractions (telemetry/overlap.py)
            # vs the modelled ones still in the distributed table
            "measured_overlap": {
                lvl: d.get("overlap_fraction")
                for lvl, d in dist_levels.items() if d.get("measured")},
        }

    # ---- serving (amgx_tpu/serve/) ----------------------------------
    req_total, req_by = csum("amgx_serve_requests_total")
    rej_total, rej_by = csum("amgx_serve_rejected_total")
    setups_total, setups_by = csum("amgx_serve_setup_total")
    cache_hits, _ = csum("amgx_serve_cache_hits_total")
    cache_misses, _ = csum("amgx_serve_cache_misses_total")
    cache_evict, _ = csum("amgx_serve_cache_evictions_total")
    batch_sizes, req_lat = [], []
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] != "hist":
                continue
            if r["name"] == "amgx_serve_batch_size":
                batch_sizes.append(float(r["value"]))
            elif r["name"] == "amgx_serve_request_seconds":
                req_lat.append(float(r["value"]))
    serving = None
    if req_total or batch_sizes or cache_hits or cache_misses:
        req_lat.sort()

        def _pct(p):
            if not req_lat:
                return None
            return req_lat[min(len(req_lat) - 1,
                               int(round(p * (len(req_lat) - 1))))]

        serving = {
            "requests": {k: int(v) for k, v in sorted(req_by.items())},
            "rejections": {k: int(v) for k, v in sorted(rej_by.items())},
            "setup_kinds": {k: int(v)
                            for k, v in sorted(setups_by.items())},
            "cache": {"hits": int(cache_hits),
                      "misses": int(cache_misses),
                      "evictions": int(cache_evict)},
            "batches": {
                "count": len(batch_sizes),
                "mean_size": (round(sum(batch_sizes) / len(batch_sizes),
                                    2) if batch_sizes else None),
                "max_size": (int(max(batch_sizes))
                             if batch_sizes else None),
            },
            "latency_s": {"p50": _pct(0.50), "p95": _pct(0.95),
                          "p99": _pct(0.99)},
        }

    # ---- serving lanes (serve/router.py: multi-device scale-out) ----
    # per-lane executor state from the lane-labeled gauges + the
    # router's steal/replication counters; request_trace events carry
    # the per-request lane + routing decision
    lane_map: Dict[str, dict] = {}
    for gname, key in (("amgx_serve_lane_sessions", "sessions"),
                       ("amgx_serve_lane_queue_depth", "queue_depth"),
                       ("amgx_serve_lane_inflight", "inflight"),
                       ("amgx_serve_lane_attainment", "attainment")):
        for lk, v in glast(gname).items():
            ln = str(_label_get(lk, "lane"))
            lane_map.setdefault(ln, {})[key] = v
    steals_total, steals_by = csum("amgx_serve_steals_total")
    reps_total, reps_by = csum("amgx_serve_replications_total")
    route_counts: Dict[str, int] = {}
    lane_req_counts: Dict[str, int] = {}
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] == "event" and r["name"] == "request_trace":
                rt = r["attrs"].get("route")
                if rt:
                    route_counts[str(rt)] = \
                        route_counts.get(str(rt), 0) + 1
                ln = r["attrs"].get("lane")
                if ln is not None:
                    lane_req_counts[str(ln)] = \
                        lane_req_counts.get(str(ln), 0) + 1
    for ln, n in lane_req_counts.items():
        lane_map.setdefault(ln, {})["requests"] = n
    lanes_diag = None
    if len(lane_map) > 1 or steals_total or reps_total:
        total_sessions = sum(int(d.get("sessions") or 0)
                             for d in lane_map.values())
        lanes_diag = {
            "lanes": {k: lane_map[k]
                      for k in sorted(lane_map, key=str)},
            "total_sessions": int(total_sessions),
            "steals": int(steals_total),
            "steals_by_lane": {k: int(v)
                               for k, v in sorted(steals_by.items())},
            "replications": int(reps_total),
            "replications_by_lane": {
                k: int(v) for k, v in sorted(reps_by.items())},
            "routes": dict(sorted(route_counts.items())),
        }

    # ---- SLO (telemetry/slo.py + request-lifecycle tracing) ---------
    slo_snap = None
    outcome_counts: Dict[str, int] = {}
    phase_tot: Dict[str, list] = {}
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] == "event" and r["name"] == "slo_window":
                slo_snap = dict(r["attrs"])     # last snapshot wins
            elif r["kind"] == "event" and r["name"] == "request_trace":
                oc = str(r["attrs"].get("outcome", "?"))
                outcome_counts[oc] = outcome_counts.get(oc, 0) + 1
            elif r["kind"] == "hist" and \
                    r["name"] == "amgx_serve_phase_seconds":
                ph = str(r["labels"].get("phase", "?"))
                d = phase_tot.setdefault(ph, [0, 0.0])
                d[0] += 1
                d[1] += float(r["value"])
    slo = None
    if slo_snap or outcome_counts:
        slo = {
            "window": slo_snap,
            "outcomes": dict(sorted(outcome_counts.items())),
            "phase_split": {ph: {"count": int(n),
                                 "mean_s": round(t / n, 6) if n else None}
                            for ph, (n, t)
                            in sorted(phase_tot.items())},
        }

    # ---- convergence ------------------------------------------------
    conv = {}
    for name, key in (("amgx_solve_iterations", "iterations"),
                      ("amgx_solve_final_relres", "final_relres"),
                      ("amgx_solve_convergence_rate", "rate")):
        g = glast(name)
        if g:
            conv[key] = list(g.values())[-1]
    trails = _residual_trails(agg)
    plateau = _plateau(trails[-1]) if trails else None
    divergences = agg["events"].get("divergence", 0)
    g = glast("amgx_forensics_asymptotic_rate")
    if g:
        conv["asymptotic_rate"] = list(g.values())[-1]

    # ---- convergence forensics (telemetry/forensics.py) -------------
    from . import forensics as _forensics
    fr = _forensics.analyze(r for s in agg["sessions"]
                            for r in s["records"])

    # ---- setup attribution (telemetry/setup_profile.py) -------------
    from . import setup_profile as _setup_profile
    setup = _setup_profile.analyze(r for s in agg["sessions"]
                                   for r in s["records"])

    # ---- device setup engine fallbacks (amg/device_setup/) ----------
    setup_fallbacks = [dict(r["attrs"]) for s in agg["sessions"]
                       for r in s["records"]
                       if r["kind"] == "event"
                       and r["name"] == "device_setup_fallback"]

    # ---- warm-start layer (compile cache + AOT store) ---------------
    cc_hits, cc_hits_by = csum("amgx_compile_cache_hits_total")
    cc_miss, cc_miss_by = csum("amgx_compile_cache_misses_total")
    cc_fb, cc_fb_by = csum("amgx_compile_cache_fallbacks_total")
    compile_cache = None
    if cc_hits or cc_miss or cc_fb:
        lookups = cc_hits + cc_miss
        # the compile-share hint reasons about XLA compiles, so its
        # rate must be the XLA layer's own — a warm AOT store next to
        # a cold XLA cache would otherwise read as "loads dominate"
        xla_hits, _ = csum("amgx_compile_cache_hits_total", layer="xla")
        xla_miss, _ = csum("amgx_compile_cache_misses_total",
                           layer="xla")
        xla_lk = xla_hits + xla_miss
        compile_cache = {
            "hits": int(cc_hits), "misses": int(cc_miss),
            "fallbacks": int(cc_fb),
            "hit_rate": round(cc_hits / lookups, 4) if lookups else None,
            "xla_hit_rate": (round(xla_hits / xla_lk, 4)
                             if xla_lk else None),
            "hits_by_layer": {k: int(v)
                              for k, v in sorted(cc_hits_by.items())},
            "misses_by_layer": {k: int(v)
                                for k, v in sorted(cc_miss_by.items())},
            "fallbacks_by_reason": {k: int(v)
                                    for k, v in sorted(cc_fb_by.items())},
        }

    # ---- failures & recovery (errors.FailureKind +
    # ---- solvers/recovery.py + utils/faultinject.py) ----------------
    recov_total, recov_by = csum("amgx_recovery_total")
    fi_total, fi_by = csum("amgx_fault_injected_total")
    trunc_total, _ = csum("amgx_history_truncated_total")
    fail_total, fail_by = csum("amgx_solve_failures_total")
    q_total, _ = csum("amgx_serve_quarantined_total")
    serve_retries, _ = csum("amgx_serve_retries_total")
    breaker_trips, _ = csum("amgx_serve_breaker_trips_total")
    recovery_events: List[dict] = []
    breakdown_events: List[dict] = []
    quarantine_events: List[dict] = []
    for s in agg["sessions"]:
        for r in s["records"]:
            if r["kind"] != "event":
                continue
            if r["name"] == "recovery_attempt":
                recovery_events.append(dict(r["attrs"]))
            elif r["name"] == "breakdown":
                breakdown_events.append(dict(r["attrs"]))
            elif r["name"] == "pattern_quarantined":
                quarantine_events.append(dict(r["attrs"]))
    failures = None
    if recov_total or recovery_events or fi_total or trunc_total \
            or fail_total or q_total or serve_retries or breaker_trips:
        recovered = sum(
            v for k, v in recov_by.items() if "outcome=recovered" in k)
        exhausted = sum(
            v for k, v in recov_by.items() if "outcome=exhausted" in k)
        # EXECUTED attempts only: the terminal action=ladder sample and
        # skipped (inapplicable, zero-budget) rungs are audit records,
        # not attempts — counting them would inflate the attempt total
        # and mis-fire the repeated-engagement hint
        att_counter = sum(
            v for k, v in recov_by.items()
            if "action=ladder" not in k and "outcome=skipped" not in k)
        att_events = sum(
            1 for e in recovery_events
            if e.get("action") != "ladder"
            and e.get("outcome") != "skipped")
        failures = {
            "solve_failures_by_kind": {
                k: int(v) for k, v in sorted(fail_by.items())},
            "breakdowns": breakdown_events[-8:],
            "recovery_attempts": int(max(att_counter, att_events)),
            "recovery_by": {k: int(v)
                            for k, v in sorted(recov_by.items())},
            "recovered": int(recovered),
            "exhausted": int(exhausted),
            "recovery_events": recovery_events[-16:],
            "fault_injected": {k: int(v)
                               for k, v in sorted(fi_by.items())},
            "history_truncated": int(trunc_total),
            "quarantined": int(q_total),
            "quarantine_events": quarantine_events[-8:],
            "serve_retries": int(serve_retries),
            "breaker_trips": int(breaker_trips),
        }

    # ---- hints ------------------------------------------------------
    hints: List[str] = []
    if agg["dropped_records"]:
        hints.append(
            f"trace truncated: {int(agg['dropped_records'])} records "
            "dropped by ring overflow — raise telemetry_ring_size (or "
            "flush more often via telemetry_path)")
    for lbl, cnt in sorted(fallbacks.items()):
        hints.append(f"SpMV fallback {lbl}: {int(cnt)}× — a packed "
                     "kernel layout took a generic path")
    for rej in rejected:
        over = rej.get("over_budget")
        lvl = rej.get("level")
        where = f"level {lvl}" if lvl is not None else \
            f"a {rej.get('rows', '?')}-row operator"
        if isinstance(over, (int, float)):
            hints.append(f"{where} fell back to segment-sum: over "
                         f"padding budget by {over:.1f}×")
        elif rej.get("reason") == "index_space":
            hints.append(f"{where} fell back to segment-sum: the "
                         "binned plan exceeds the int32 index space")
        else:
            hints.append(f"{where} fell back to segment-sum (binned "
                         "plan rejected)")
    for lvl, d in sorted(levels.items(), key=lambda kv: str(kv[0])):
        w = d.get("padding_waste")
        rows = d.get("rows") or 0
        # tiny coarse levels waste bandwidth by construction and cost
        # nothing — only flag levels big enough to matter
        if isinstance(w, (int, float)) and w > WASTE_HINT \
                and rows >= WASTE_MIN_ROWS:
            hints.append(
                f"level {lvl} pack {d.get('pack', '?')} wastes "
                f"{w:.2f}× bandwidth on padding slots")
    # mixed precision (core/precision.py): a multi-level f32 hierarchy
    # whose SpMV is bandwidth-class (dia/shift/window/binned — the
    # memory-bound packs) leaves the single biggest single-chip lever
    # unpulled: bf16 storage halves every level's value bytes while
    # arithmetic stays f32
    lvl_dts = {str(d.get("dtype")) for d in levels.values()
               if d.get("dtype")}
    bw_packs = ("dia", "dia3", "ell/shift", "ell/window", "ell/binned",
                "csr/binned")
    if len(levels) >= 2 and lvl_dts and lvl_dts <= {"float32"} and \
            any(str(d.get("pack", "")).startswith(bw_packs)
                for d in levels.values()):
        hints.append(
            "bandwidth-bound f32 hierarchy: every level stores float32"
            " — try mixed precision (hierarchy_dtype=bfloat16) to "
            "halve per-cycle HBM bytes; arithmetic accumulates in f32 "
            "and tolerances below the f32 floor still converge via "
            "the promotion ladder (krylov_dtype stays float32)")
    if halo_local_ratio is not None and halo_local_ratio > HALO_HINT:
        hints.append(
            f"halo exchange moves {halo_local_ratio:.2f}× the local "
            "SpMV bytes — the solve is communication-bound; consider "
            "fewer, fatter shards or overlapping more work")
    halo_bound = [d for d in dist_levels.values()
                  if d.get("halo_bound")]
    if halo_bound:
        worst = max(int(d.get("rows_per_part") or 0)
                    for d in halo_bound)
        if agglomerations:
            hints.append(
                f"{len(halo_bound)} distributed level(s) remain "
                "halo-bound after agglomeration — raise "
                f"dist_agglomerate_min_rows above {worst} rows/device "
                "so they land on a smaller sub-mesh")
        else:
            hints.append(
                f"{len(halo_bound)} distributed level(s) are "
                "halo-bound (halo time exceeds the interior "
                "SpMV even with perfect overlap) — set "
                f"dist_agglomerate_min_rows above {worst} rows/device "
                "to agglomerate those levels onto a shrinking "
                "sub-mesh")
    if krylov:
        for _key, e in sorted(krylov["solves"].items()):
            if e.get("mode") == "CLASSIC" and e.get("reduction_bound"):
                hints.append(
                    f"dot-product reductions dominate the sharded "
                    f"{e.get('solver')} solve (modelled "
                    f"{float(e.get('est_reduction_s') or 0)*1e6:.1f} us"
                    f"/iter across {int(e.get('collectives_per_iter') or 0)}"
                    " collectives vs "
                    f"{float(e.get('est_spmv_s') or 0)*1e6:.1f} us "
                    "interior SpMV) — try krylov_comm=PIPELINED to fuse "
                    "them into one collective overlapped with the SpMV")
                break
    if plateau:
        hints.append(
            f"residual plateaued for {plateau['iterations']} iterations "
            f"(from iteration {plateau['from_iteration']}, "
            f"norm ~{plateau['norm']:.3e})"
            + (" and STALLED outright" if plateau["stalled"] else "")
            + " — consider a stronger smoother/preconditioner or check "
              "the operator's conditioning")
    if divergences:
        hints.append(f"{int(divergences)} divergence event(s): a "
                     "residual went non-finite")
    if failures:
        # the recovery ladder saving a solve ONCE is working as
        # designed; repeated engagement means the underlying breakdown
        # keeps happening — a masked root cause burning 2-5× solve cost
        n_rec = failures["recovery_attempts"]
        if n_rec >= 2:
            kinds = sorted({str(e.get("kind")) for e
                            in failures["recovery_events"]}
                           or set(failures["solve_failures_by_kind"]))
            hints.append(
                f"recovery ladder engaged {n_rec}× "
                f"({failures['recovered']} recovered, "
                f"{failures['exhausted']} exhausted"
                + (f"; kinds: {', '.join(k for k in kinds if k)}"
                   if kinds else "")
                + ") — recovered solves pay 2-5× wall cost; find the "
                  "root cause in the breakdown kinds instead of "
                  "relying on the ladder")
        if failures["exhausted"]:
            hints.append(
                f"{failures['exhausted']} solve(s) exhausted the "
                "recovery ladder unrecovered — the failure survives "
                "restart, promotion, a conservative smoother AND a "
                "full re-setup: suspect the operator/rhs themselves")
        if failures["fault_injected"]:
            pts = ", ".join(f"{k}: {v}" for k, v
                            in failures["fault_injected"].items())
            hints.append(
                f"fault injection was ACTIVE in this trace ({pts}) — "
                "failures here include synthetic chaos faults, not "
                "production signal")
        if failures["history_truncated"]:
            hints.append(
                f"{failures['history_truncated']} residual history "
                "slab(s) carried non-finite rows (history_truncated "
                "events name the first bad iteration) — the iteration "
                "record around a breakdown is partial")
        if failures["quarantined"]:
            hints.append(
                f"{failures['quarantined']} pattern(s) quarantined "
                "after repeated setup/solve errors — clients of those "
                "patterns are being rejected at admission; fix the "
                "operator and lift via SolveService.unquarantine()")
    hints.extend(_forensics_hints(fr))
    hints.extend(_setup_hints(setup, setup_fallbacks, compile_cache))
    if compile_cache and compile_cache["fallbacks"]:
        reasons = ", ".join(
            f"{k}: {v}" for k, v
            in compile_cache["fallbacks_by_reason"].items())
        hints.append(
            f"{compile_cache['fallbacks']} AOT-store fallback(s) "
            f"({reasons}) — version-mismatched entries recompile "
            "cleanly; re-warm the store after jaxlib upgrades, delete "
            "it if corruption repeats")
    jit, _ = csum("amgx_jit_compile_total")
    if jit:
        hints.append(f"{int(jit)} XLA recompiles in-trace — if these "
                     "landed inside a timed region, warm up first")
    if serving:
        if rej_total:
            hints.append(
                f"serving shed {int(rej_total)} request(s) "
                f"({', '.join(f'{k}: {int(v)}' for k, v in sorted(rej_by.items()))})"
                " — raise serve_queue_depth, add serve_workers, or relax "
                "deadlines")
        full = sum(v for k, v in setups_by.items() if "kind=full" in k)
        completed = sum(v for k, v in req_by.items()
                        if "status=SUCCESS" in k or "status=FAILED" in k)
        if completed and full >= completed:
            hints.append(
                "no setup reuse: every served request paid a full setup "
                "— requests never shared a (config, pattern) session")
        fails, _ = csum("amgx_worker_task_failures_total")
        if fails:
            hints.append(f"{int(fails)} worker task(s) raised — the pool "
                         "survived, but check the service error log")
    if lanes_diag and len(lanes_diag["lanes"]) > 1:
        # lane imbalance: affinity routing concentrates sessions by
        # design, but one lane hoarding most of them means replication
        # never triggered — the hot patterns' home lane saturates while
        # the rest of the mesh idles.  Balanced fleets stay silent.
        tot = lanes_diag["total_sessions"]
        if tot >= 4:
            top_ln, top_d = max(
                lanes_diag["lanes"].items(),
                key=lambda kv: int(kv[1].get("sessions") or 0))
            share = int(top_d.get("sessions") or 0) / tot
            if share >= LANE_IMBALANCE_SHARE:
                hints.append(
                    f"lane imbalance: lane {top_ln} holds "
                    f"{share:.0%} of {tot} resident sessions — the "
                    "replication threshold is too high for this "
                    "traffic: lower serve_replicate_frac (replicate "
                    "hot patterns earlier) or serve_steal_frac (steal "
                    "cold patterns off busy lanes sooner), or warm "
                    "the expected pattern set so homes pre-distribute")
    if slo:
        w = slo.get("window") or {}
        burn = w.get("burn_rate")
        if isinstance(burn, (int, float)) and burn > 1.0:
            att = w.get("attainment")
            hints.append(
                f"SLO error budget burning at {burn:.1f}× "
                + (f"(attainment {att:.1%} vs target "
                   f"{w.get('target', 0):.1%})"
                   if isinstance(att, (int, float)) else "")
                + " — shed load earlier, add capacity, or relax the "
                  "objective")
        if w.get("overloaded"):
            hints.append(
                "overload trip wire is ON (windowed shed rate or queue "
                "depth past threshold) — the service is past its "
                "capacity; scale out or lower the offered rate")
        ps = slo.get("phase_split", {})
        qw = (ps.get("queue_wait") or {}).get("mean_s")
        sv = (ps.get("solve") or {}).get("mean_s")
        if isinstance(qw, (int, float)) and isinstance(sv, (int, float)) \
                and sv > 0 and qw > sv:
            hints.append(
                f"queue_wait ({qw * 1e3:.1f} ms mean) exceeds solve "
                f"({sv * 1e3:.1f} ms mean) per request — latency is "
                "congestion, not compute: add serve_workers, shorten "
                "serve_batch_window_ms, or shed earlier")

    # ---- device anatomy (PR 17: telemetry/deviceprof.py) ------------
    # host-vs-device skew: the solve span measures host DISPATCH under
    # JAX's async execution, the anatomy measures the device — a large
    # ratio either way is a diagnosis in itself
    if device_anatomy and device_anatomy.get("measured"):
        host_solve = (agg["spans"].get("solve") or {}).get("total_s")
        dev_total = device_anatomy.get("total_device_s")
        if isinstance(host_solve, (int, float)) \
                and isinstance(dev_total, (int, float)) \
                and host_solve > 0 and dev_total > 0:
            skew = host_solve / dev_total
            if skew > 3.0:
                hints.append(
                    f"host-vs-device skew: the solve span measured "
                    f"{host_solve:.3f}s on the host but the profiler "
                    f"saw only {dev_total:.3f}s of device time "
                    f"({skew:.1f}×) — the solve is host/dispatch-bound "
                    "(python overhead, retraces, blocking transfers), "
                    "not device-bound; check amgx_jit_trace_total "
                    "before tuning kernels")
            elif skew < 1.0 / 3.0:
                hints.append(
                    f"host-vs-device skew: {dev_total:.3f}s of device "
                    f"time behind a {host_solve:.3f}s host solve span "
                    f"({1 / skew:.1f}×) — async dispatch returned "
                    "before the device finished; host spans understate "
                    "the real cost, trust the device anatomy")
        un = device_anatomy.get("unattributed_s")
        tot = device_anatomy.get("total_device_s")
        if isinstance(un, (int, float)) and isinstance(tot, (int, float)) \
                and tot > 0 and un / tot > 0.5:
            hints.append(
                f"device anatomy: {un / tot:.0%} of device time is "
                "outside every amgx/* scope — work is running that the "
                "taxonomy does not name (transfers, setup leftovers, "
                "or an uninstrumented kernel; scripts/telemetry_check "
                "lints registered kernels)")

    # ---- device memory (PR 18: telemetry/memledger.py) --------------
    memory = None
    if hbm_snapshot is not None or oom_postmortems:
        memory = {"snapshot": hbm_snapshot,
                  "oom_postmortems": oom_postmortems}
        if hbm_snapshot and hbm_snapshot.get("measured"):
            for dev, d in (hbm_snapshot.get("devices") or {}).items():
                limit = d.get("bytes_limit") or 0
                head = d.get("headroom_bytes") or 0
                if limit > 0 and head / limit < 0.10:
                    top = sorted(
                        ((d.get("owners") or {})).items(),
                        key=lambda kv: -kv[1])[:1]
                    who = f" (largest owner {top[0][0]}, " \
                          f"{_fmt_bytes(top[0][1])})" if top else ""
                    hints.append(
                        f"device memory: {dev} is near its ceiling — "
                        f"{_fmt_bytes(head)} headroom of "
                        f"{_fmt_bytes(limit)}{who}; shrink "
                        "serve_cache_bytes, store hierarchies in "
                        "bfloat16 (hierarchy_dtype), or evict sessions "
                        "before the next setup OOMs")
        for pm in oom_postmortems:
            top = pm.get("top_owners") or []
            who = f"; top owner {top[0][0]} " \
                  f"({_fmt_bytes(top[0][1])})" if top else ""
            hints.append(
                f"device OOM in {pm.get('where')}"
                f"{' (injected)' if pm.get('injected') else ''}{who} — "
                "see the oom_postmortem event for the full ledger "
                "snapshot and eviction suggestions")

    # ---- mesh flight recorder (PR 20: telemetry/meshtrace.py) -------
    # cross-rank rendezvous join; single-rank traces stay silent (the
    # per-rank sections above already cover them)
    mesh = None
    if agg["n_sessions"] >= 2:
        from . import meshtrace
        m = meshtrace.analyze_sessions(agg["sessions"])
        if m["n_ranks"] >= 2:
            mesh = m
    if mesh and mesh.get("measured"):
        _mesh_noun = {"halo": "halo exchanges",
                      "krylov": "Krylov reductions",
                      "agglomerate": "agglomerations"}
        for g in (mesh.get("groups") or {}).values():
            share = g.get("last_share")
            lr = g.get("last_rank_mode")
            if g["collectives"] >= MESH_MIN_COLLECTIVES \
                    and isinstance(share, (int, float)) \
                    and share >= MESH_STRAGGLER_SHARE \
                    and g["wait_s"] > 0:
                ind = (mesh["ranks"].get(lr) or {}).get(
                    "induced_wait_s") or 0.0
                hints.append(
                    f"mesh straggler: rank {lr} arrives last in "
                    f"{share:.0%} of {g['group']} "
                    f"{_mesh_noun.get(g['op'], g['op'])} (induced "
                    f"{ind:.3f}s of peer wait) → partition imbalance "
                    "— check amgx_dist_boundary_fraction and the "
                    "per-part row split before tuning the collective")
        total_wait = mesh.get("total_wait_s") or 0.0
        kry_wait = (mesh.get("wait_by_op") or {}).get("krylov", 0.0)
        if total_wait > 0 and kry_wait / total_wait \
                > MESH_KRYLOV_WAIT_SHARE \
                and any(rv.get("fused")
                        for rv in mesh.get("rendezvous") or []):
            hints.append(
                f"mesh wait is {kry_wait / total_wait:.0%} fused "
                "Krylov reductions — the solver is already at one "
                "collective per iteration, so the reduction itself is "
                "not the lever: the ranks reach it at different "
                "times; look at compute skew (arrival spread) and "
                "rebalance the partition")
        _miss: Dict = {}
        for e in mesh.get("desync") or []:
            if e["kind"] == "silent":
                hints.append(
                    f"mesh desync: rank {e['rank']}'s trace goes "
                    f"silent {e['gap_s']:.3f}s "
                    f"({e['gap_fraction']:.0%} of the mesh span) "
                    "before its peers stop — a crashed rank or a "
                    "stalled flush; check its tail for "
                    "mesh_truncated_tail / oom_postmortem events")
            elif e["kind"] == "missing_collectives":
                _miss.setdefault(e["rank"], []).append(e)
        for rnk, es in sorted(_miss.items()):
            e = es[0]
            more = f" (+{len(es) - 1} more group(s))" if len(es) > 1 \
                else ""
            hints.append(
                f"mesh desync: rank {rnk} ran {e['ran']} "
                f"{e['group']} {e['op']} collective(s) vs peers' "
                f"{e['peers_ran']}{more} — divergent control flow or "
                "an early exit; on real hardware the mesh deadlocks "
                "at the first collective this rank skips")

    return {
        "files": list(paths),
        "sessions": agg["n_sessions"], "records": agg["n_records"],
        "dropped_records": agg["dropped_records"],
        "phases": phases,
        "spans": {k: dict(v, total_s=round(v["total_s"], 6))
                  for k, v in agg["spans"].items()},
        "packs": {k: int(v) for k, v in sorted(packs.items())},
        "fallbacks": {k: int(v) for k, v in sorted(fallbacks.items())},
        "levels": levels,
        "operator_cost": op_cost,
        "op_costs": op_costs,
        "distributed": {
            "halo_exchanges": int(exchanges),
            "halo_wire_bytes": int(halo_bytes),
            "halo_entries": int(halo_entries),
            "halo_bytes_by_label": {k: int(v)
                                    for k, v in sorted(halo_by.items())},
            "boundary_fraction": bnd,
            "halo_local_ratio": halo_local_ratio,
            "levels": dist_levels,
            "agglomerations": agglomerations,
        },
        "krylov": krylov,
        "device": device_anatomy,
        "memory": memory,
        "mesh": mesh,
        "serving": serving,
        "serving_lanes": lanes_diag,
        "slo": slo,
        "failures": failures,
        "convergence": dict(conv, trails=len(trails),
                            plateau=plateau, divergences=int(divergences)),
        "forensics": fr,
        "setup": setup,
        "setup_fallbacks": setup_fallbacks,
        "compile_cache": compile_cache,
        "hints": hints,
    }


#: component → actionable knob, the concrete advice a weak component
#: earns ("which config line do I change")
_COMPONENT_ADVICE = {
    "pre_smooth": "raise presweeps or switch smoother",
    "post_smooth": "raise postsweeps or switch smoother",
    "coarse_corr": "inspect interpolation/strength (check the "
                   "amgx_forensics_galerkin_err and nullspace probes)",
    "coarse_solve": "raise coarsest_sweeps or use a direct coarse "
                    "solver (DENSE_LU_SOLVER)",
}

_COMPONENT_LABEL = {
    "pre_smooth": "pre-smooth", "post_smooth": "post-smooth",
    "coarse_corr": "coarse correction", "coarse_solve": "coarse solve",
}

#: the cycle components, in cut-point order — one authority
#: (forensics.COMPONENTS) so a new component shows up everywhere
COMP_ORDER = COMPONENTS


def _forensics_hints(fr: Optional[dict]) -> List[str]:
    """Actionable convergence hints from the forensics analysis: name
    dead smoothing components, stagnating levels, amplifying coarse
    corrections, weak coarse solves and failed quality probes.  Tuned
    to stay silent on a healthy trace: smoothing factors ~0.6 and a
    mildly-over-1 coarse-correction residual factor are normal."""
    if not fr:
        return []
    hints: List[str] = []
    levels = fr.get("levels", {})
    for lvl, d in sorted(levels.items()):
        for comp in ("pre_smooth", "post_smooth"):
            f = d.get(comp)
            if f is not None and f >= WEAK_COMPONENT:
                knob = "presweeps" if comp == "pre_smooth" \
                    else "postsweeps"
                verb = "does nothing" if f >= 0.98 else "barely reduces"
                hints.append(
                    f"level {lvl} {_COMPONENT_LABEL[comp]} {verb} "
                    f"(reduction {f:.2f}) → raise {knob} or switch "
                    "smoother")
        f = d.get("coarse_corr")
        if f is not None and f >= AMPLIFY_HINT:
            hints.append(
                f"coarse correction amplifying at level {lvl} "
                f"(factor {f:.2f}) → inspect interpolation")
        t = d.get("total")
        if t is not None and t >= WEAK_COMPONENT:
            # dominant component by baseline-NORMALISED severity
            # (forensics.component_score): a raw max would let a
            # healthy transiently-amplifying coarse correction
            # out-rank a dead smoother and misdirect the advice
            from .forensics import component_score
            worst = max(
                ((component_score(c, d[c]), d[c], c)
                 for c in COMP_ORDER if d.get(c) is not None),
                default=(None, None, None))
            if worst[0] is not None:
                hints.append(
                    f"level {lvl} cycle barely reduces the residual "
                    f"(total {t:.2f}); dominant component: "
                    f"{_COMPONENT_LABEL[worst[2]]} ({worst[1]:.2f}) → "
                    f"{_COMPONENT_ADVICE[worst[2]]}")
    c = fr.get("coarse")
    if c and c.get("factor") is not None and \
            c["factor"] >= WEAK_COMPONENT:
        hints.append(
            f"coarsest-grid solve at level {c['level']} barely reduces "
            f"(factor {c['factor']:.2f}) → "
            f"{_COMPONENT_ADVICE['coarse_solve']}")
    for lvl, p in sorted(fr.get("probes", {}).items()):
        ge = p.get("galerkin_err")
        if isinstance(ge, (int, float)) and ge > GALERKIN_ERR_HINT:
            hints.append(
                f"level {lvl}: stored coarse operator drifts from "
                f"R·A·P by {ge:.1e} (sampled) — a stale value refresh "
                "or a broken transfer")
        ns = p.get("nullspace")
        if isinstance(ns, (int, float)) and ns > NULLSPACE_HINT:
            hints.append(
                f"level {lvl}: operator no longer annihilates the "
                f"constant vector (|A·1|/|A| = {ns:.2f}) — the "
                "near-nullspace was lost in coarsening")
    return hints


#: setup components whose dominance reads "the algorithm runs host-side"
_HOST_SETUP_COMPONENTS = ("strength", "selector", "interpolation", "rap")

#: phases the device setup engine emits (amg/device_setup/, single
#: source: setup_profile.DEVICE_SETUP_COMPONENTS): their presence means
#: the Galerkin RAP already runs on device, so a dominant "rap" reads
#: "a level FELL BACK", not "build the engine"
from .setup_profile import \
    DEVICE_SETUP_COMPONENTS as _DEVICE_SETUP_COMPONENTS

#: fallback reasons that are by-design (tiny levels are host-faster) —
#: reported in the table but not hinted as problems
_BENIGN_FALLBACKS = ("small", "disabled")


def _setup_hints(setup: Optional[dict],
                 setup_fallbacks: Optional[List[dict]] = None,
                 compile_cache: Optional[dict] = None) -> List[str]:
    """Actionable setup-attribution hints (telemetry/setup_profile.py):
    compile-bound setups earn warm-start advice REFINED by the
    compile-cache hit rate when the trace carries it (``warmup`` is
    only suggested when misses dominate — a hitting cache with a high
    compile share is a different problem), host-dominated classical
    components point at the device-side setup engine
    (amg/device_setup/) — or, when its ``device_rap``/``spgemm`` phases
    are present, at the specific levels that FELL BACK to the host path
    (with the recorded reason); chatty transfers point at batching."""
    if not setup:
        return []
    from .setup_profile import (COMPILE_HINT, DOMINANT_HINT,
                                TRANSFER_HINT, UPLOAD_DRAIN_HINT)
    hints: List[str] = []
    s = setup.get("summary") or {}
    total = setup.get("total_s") or 0.0
    if total:
        # worker-thread compile (smoother-setup tasks) overlaps the
        # owner's wait phases but is still compile work a persistent
        # cache would remove — count it toward the hint, capped at 1
        cshare = min(((s.get("compile_s") or 0.0)
                      + (s.get("worker_compile_s") or 0.0)) / total, 1.0)
        if cshare >= COMPILE_HINT:
            cc = compile_cache or {}
            # per-layer: the XLA rate answers "did the compiles this
            # share measures hit the cache"; the combined rate only
            # serves when no XLA-layer traffic was recorded
            rate = cc.get("xla_hit_rate")
            if rate is None:
                rate = cc.get("hit_rate")
            if rate is None:
                hints.append(
                    f"compile is {cshare:.0%} of setup → set "
                    "compile_cache_dir (persistent compilation cache) "
                    "+ aot_store_dir, then warm up (scripts/warmup.py "
                    "/ SolveService.warmup) so reruns skip it")
            elif rate < 0.5:
                hints.append(
                    f"compile is {cshare:.0%} of setup and the compile "
                    f"cache hit only {rate:.0%} of lookups → this "
                    "process ran COLD: warm up at start "
                    "(scripts/warmup.py / SolveService.warmup / "
                    "AMGX_serve_warmup) so the next one loads instead "
                    "of compiling")
            else:
                hints.append(
                    f"compile is {cshare:.0%} of setup despite a "
                    f"{rate:.0%} compile-cache hit rate — executable "
                    "LOADS dominate; route the remaining hot bodies "
                    "through the AOT store (aot_store_dir) to skip "
                    "tracing too")
        tshare = (s.get("transfer_s") or 0.0) / total
        if tshare >= TRANSFER_HINT:
            hints.append(
                f"host↔device transfers are {tshare:.0%} of setup "
                f"({_fmt_bytes(s.get('transfer_bytes'))}) — keep the "
                "hierarchy on device / batch the uploads")
    device_setup_active = any(
        p.get("component") in _DEVICE_SETUP_COMPONENTS
        for p in setup.get("phases", []))
    # group fallbacks by (component, level, reason): a resetup-heavy or
    # multi-session trace repeats the same event hundreds of times and
    # must not flood the hints list
    fb_groups: Dict[tuple, int] = {}
    for fb in setup_fallbacks or []:
        k = (fb.get("component", "rap"), fb.get("level"),
             fb.get("reason", "?"))
        fb_groups[k] = fb_groups.get(k, 0) + 1
    n_fb_hints = 0
    for (comp, lvl, reason), cnt in sorted(fb_groups.items(),
                                           key=lambda kv: -kv[1]):
        if reason.split(":")[0] in _BENIGN_FALLBACKS:
            continue
        if n_fb_hints >= 6:
            hints.append(f"… and {len(fb_groups) - 6} more distinct "
                         "device-setup fallback groups (see the "
                         "fallback section)")
            break
        where = f" at level {lvl}" if lvl is not None else ""
        times = f" ({cnt}×)" if cnt > 1 else ""
        hints.append(
            f"{comp}{where} fell back to the host path (reason: "
            f"{reason}){times} → "
            + ("raise device_setup_cache_mb or split the level"
               if reason == "budget" else
               "check the device_setup gates (amg/device_setup/)"))
        n_fb_hints += 1
    for p in setup.get("phases", [])[:3]:
        if p.get("overlapped"):
            continue
        if p.get("share", 0.0) >= DOMINANT_HINT and \
                p["component"] in _HOST_SETUP_COMPONENTS and \
                p.get("host_s", 0.0) > p.get("compile_s", 0.0):
            where = f" at level {p['level']}" \
                if p.get("level") is not None else ""
            if p["component"] == "rap" and device_setup_active:
                # the engine IS running — a dominant host rap means
                # specific levels declined it; only the non-benign
                # groups were hinted above, so say so when the
                # recorded fallbacks don't explain the dominance
                if n_fb_hints:
                    break
                if fb_groups:       # all-benign ('small') fallbacks
                    hints.append(
                        f"rap{where} runs host-side and is "
                        f"{p['share']:.0%} of setup — every recorded "
                        "fallback is benign ('small'): lower "
                        "device_setup_min_rows if these levels matter")
                else:
                    hints.append(
                        f"rap{where} runs host-side and is "
                        f"{p['share']:.0%} of setup despite the device "
                        "setup engine — enable telemetry during setup "
                        "to record the fallback reasons")
                break
            hints.append(
                f"{p['component']}{where} runs host-side and is "
                f"{p['share']:.0%} of setup → device-side setup "
                "engine (device_setup=1, amg/device_setup/; "
                "ROADMAP item 1)")
            break
    uploads = int(s.get("uploads") or 0)
    if uploads > UPLOAD_DRAIN_HINT:
        hints.append(
            f"upload drained {uploads} times during setup — arena-batch "
            "the hierarchy transfer (one device_put round trip)")
    cov = s.get("coverage")
    if isinstance(cov, (int, float)) and cov < 0.9:
        hints.append(
            f"setup attribution covers only {cov:.0%} of the wall — "
            "un-instrumented phases; extend the setup_profile markers")
    return hints


def render(d: dict) -> str:
    """Human-readable report of a :func:`diagnose` result."""
    L: List[str] = []
    L.append("amgx solve doctor")
    L.append("=" * 60)
    L.append(f"trace: {', '.join(d['files'])}")
    L.append(f"sessions: {d['sessions']}   records: {d['records']}"
             + (f"   DROPPED: {d['dropped_records']}"
                if d["dropped_records"] else ""))

    if d["phases"]:
        L.append("")
        L.append("phase breakdown (top-level)")
        L.append("-" * 40)
        for k, v in sorted(d["phases"].items(),
                           key=lambda kv: -kv[1]["total_s"]):
            L.append(f"  {k:<10} {v['total_s']:>10.4f} s"
                     f"  ({v['count']}×)")
    if d["spans"]:
        L.append("")
        L.append("span totals (nested; top 12 by time)")
        L.append("-" * 40)
        top = sorted(d["spans"].items(),
                     key=lambda kv: -kv[1]["total_s"])[:12]
        for k, v in top:
            L.append(f"  {k:<28} {v['total_s']:>10.4f} s"
                     f"  ({v['count']}×)")

    if d["packs"]:
        L.append("")
        L.append("SpMV pack choices")
        L.append("-" * 40)
        for k, v in d["packs"].items():
            # per-pack cost from the dispatch-time op_cost events —
            # covers operators no hierarchy level describes (raw spmv,
            # non-AMG solvers)
            # dispatch labels refine the pack_kind names ("dia/slices"
            # vs the descriptor's "dia") — fall back to the base kind
            pack_name = k.split("pack=", 1)[-1]
            c = d.get("op_costs", {}).get(pack_name) or \
                d.get("op_costs", {}).get(pack_name.split("/")[0])
            extra = ""
            if c and c.get("bytes_per_apply"):
                extra = (f"   {_fmt_bytes(c['bytes_per_apply'])}/apply"
                         f", waste {c.get('padding_waste', '?')}")
            L.append(f"  {k:<28} {v}{extra}")
        for k, v in d["fallbacks"].items():
            L.append(f"  FALLBACK {k:<19} {v}")

    if d["levels"]:
        L.append("")
        L.append("hierarchy cost model (per level)")
        L.append("-" * 40)
        L.append(f"  {'lvl':<4}{'rows':>10}{'nnz':>12}{'pack':>14}"
                 f"{'dtype':>10}{'bytes/apply':>14}{'waste':>8}")
        for lvl, x in sorted(d["levels"].items(),
                             key=lambda kv: int(kv[0])
                             if str(kv[0]).isdigit() else 99):
            L.append(
                f"  {lvl:<4}"
                f"{int(x.get('rows', 0)):>10}"
                f"{int(x.get('nnz', 0)):>12}"
                f"{str(x.get('pack', '?')):>14}"
                f"{str(x.get('dtype', '?')):>10}"
                f"{_fmt_bytes(x.get('bytes_per_apply')):>14}"
                + (f"{x['padding_waste']:>8.2f}"
                   if isinstance(x.get("padding_waste"), (int, float))
                   else f"{'?':>8}"))

    dist = d["distributed"]
    if dist["halo_exchanges"]:
        L.append("")
        L.append("distributed / halo exchange")
        L.append("-" * 40)
        L.append(f"  exchanges traced:   {dist['halo_exchanges']}")
        L.append(f"  wire bytes (padded): "
                 f"{_fmt_bytes(dist['halo_wire_bytes'])}")
        L.append(f"  useful halo entries: {dist['halo_entries']}")
        if dist["halo_local_ratio"] is not None:
            L.append(f"  halo/local bytes:    "
                     f"{dist['halo_local_ratio']:.3f}")
        for dev, f in sorted(dist["boundary_fraction"].items()):
            L.append(f"  boundary fraction [device {dev}]: {f:.3f}")

    if dist.get("levels"):
        L.append("")
        L.append("distributed levels (sub-mesh + overlap audit)")
        L.append("-" * 40)
        L.append(f"  {'lvl':<4}{'parts':>6}{'rows/part':>11}"
                 f"{'halo:local':>11}{'overlap':>9}  flag")
        for lvl, x in sorted(dist["levels"].items(),
                             key=lambda kv: int(kv[0])
                             if str(kv[0]).isdigit() else 99):
            ratio = x.get("halo_local_ratio")
            L.append(
                f"  {lvl:<4}"
                f"{int(x.get('submesh_parts') or 0):>6}"
                f"{int(x.get('rows_per_part') or 0):>11}"
                + (f"{ratio:>11.3f}" if isinstance(ratio, (int, float))
                   else f"{'?':>11}")
                + f"{x.get('overlap_fraction', 0):>9.2f}"
                + ("  HALO-BOUND" if x.get("halo_bound") else ""))
        for a in dist.get("agglomerations", []):
            L.append(
                f"  agglomerated level {a.get('level')}: "
                f"{a.get('from_parts')} -> {a.get('to_parts')} rank(s)"
                f" ({a.get('rows')} rows"
                + (", replicated" if a.get("replicated") else "")
                + (", pack reused" if a.get("reused") else "") + ")")

    kry = d.get("krylov")
    if kry:
        L.append("")
        L.append("Krylov communication (sharded solves)")
        L.append("-" * 40)
        L.append(f"  {'solver':<10}{'mode':<11}{'coll/iter':>10}"
                 f"{'fused':>7}{'iters':>7}  per-iter profile")
        for _key, e in sorted(kry["solves"].items()):
            prof = ", ".join(f"{k}: {v}" for k, v
                             in sorted((e.get("per_iter") or {}).items()))
            L.append(
                f"  {str(e.get('solver', '?')):<10}"
                f"{str(e.get('mode', '?')):<11}"
                f"{int(e.get('collectives_per_iter') or 0):>10}"
                + f"{'yes' if e.get('fused') else 'no':>7}"
                + f"{int(e.get('iterations') or 0):>7}"
                + f"  {prof}")
        if kry.get("measured_overlap"):
            for lvl, f in sorted(kry["measured_overlap"].items(),
                                 key=lambda kv: str(kv[0])):
                L.append(f"  measured overlap [level {lvl}]: "
                         f"{float(f or 0):.2f} (profiler trace)")
        else:
            L.append("  overlap fractions above are MODELLED — supply "
                     "a jax.profiler trace (telemetry/overlap.py) for "
                     "measured ones")

    dev = d.get("device")
    if dev:
        L.append("")
        L.append("Device anatomy (profiler-measured device time)")
        L.append("-" * 40)
        if not dev.get("measured"):
            L.append("  measured: NO — the trace carried no amgx/* "
                     "scoped device ops (CPU backend or no profiler "
                     "capture); numbers below are a stub")
        tot = float(dev.get("total_device_s") or 0)
        att = float(dev.get("attributed_s") or 0)
        pct = f"{att / tot:.0%}" if tot > 0 else "-"
        L.append(f"  device total {tot * 1e3:.3f} ms   attributed "
                 f"{att * 1e3:.3f} ms ({pct})   unattributed "
                 f"{float(dev.get('unattributed_s') or 0) * 1e3:.3f} ms"
                 f"   [{int(dev.get('n_devices') or 0)} device(s), "
                 f"scope contract v{dev.get('scope_version', '?')}]")
        lv = dev.get("levels") or {}
        if lv:
            L.append(f"  {'level':<7}{'pre':>9}{'restrict':>10}"
                     f"{'prolong':>9}{'post':>9}{'total':>9}  (ms)")

            def _ms(row, key):
                v = row.get(key)
                return f"{float(v) * 1e3:>{10 if key == 'restrict' else 9}.3f}" \
                    if isinstance(v, (int, float)) else \
                    f"{'-':>{10 if key == 'restrict' else 9}}"

            for lvl in sorted(lv, key=lambda k: int(k)):
                row = lv[lvl]
                L.append(f"  {lvl:<7}" + _ms(row, "pre_smooth")
                         + _ms(row, "restrict") + _ms(row, "prolong")
                         + _ms(row, "post_smooth") + _ms(row, "total_s"))
        if dev.get("coarse_s"):
            L.append(f"  coarse solve: "
                     f"{float(dev['coarse_s']) * 1e3:.3f} ms")
        sp = dev.get("spmv") or {}
        if sp:
            L.append(f"  {'spmv pack':<22}{'device ms':>11}"
                     f"{'GB/s':>9}{'roofline':>10}")
            for pack in sorted(sp):
                e = sp[pack]
                gbs = e.get("measured_gbs")
                rf = e.get("roofline_fraction")
                L.append(
                    f"  {pack:<22}"
                    f"{float(e.get('device_s') or 0) * 1e3:>11.3f}"
                    + (f"{gbs:>9.1f}" if isinstance(gbs, (int, float))
                       else f"{'-':>9}")
                    + (f"{rf:>10.1%}" if isinstance(rf, (int, float))
                       else f"{'-':>10}"))
        for section, label in (("smoothers", "smoother"),
                               ("krylov", "krylov stage"),
                               ("dist", "dist")):
            rows = dev.get(section) or {}
            for name in sorted(rows):
                L.append(f"  {label} {name}: "
                         f"{float(rows[name]) * 1e3:.3f} ms")

    mem = d.get("memory")
    if mem:
        L.append("")
        L.append("Device memory (HBM ledger)")
        L.append("-" * 40)
        snap = mem.get("snapshot")
        if snap:
            if not snap.get("measured"):
                L.append("  measured: NO — no device exposed "
                         "memory_stats() (CPU backend); bytes_in_use "
                         "below is the live-array census total")
            for dname in sorted(snap.get("devices") or {}):
                dd = snap["devices"][dname]
                line = (f"  {dname}: in use "
                        f"{_fmt_bytes(dd.get('bytes_in_use'))}   "
                        f"accounted "
                        f"{_fmt_bytes(dd.get('accounted_bytes'))}   "
                        f"unaccounted "
                        f"{_fmt_bytes(dd.get('unaccounted_bytes'))}")
                if snap.get("measured"):
                    line += (f"   headroom "
                             f"{_fmt_bytes(dd.get('headroom_bytes'))}"
                             f"   peak "
                             f"{_fmt_bytes(dd.get('peak_bytes'))}")
                L.append(line)
            owners = snap.get("owners") or {}
            for name, nb in sorted(owners.items(),
                                   key=lambda kv: -kv[1])[:8]:
                L.append(f"    {name:<34} {_fmt_bytes(nb):>10}")
            for name, nb in sorted((snap.get("host_owners")
                                    or {}).items()):
                L.append(f"    {name:<34} {_fmt_bytes(nb):>10} (host)")
            L.append(f"  live arrays {snap.get('n_live_arrays', 0)} "
                     f"(owned {snap.get('n_owned_arrays', 0)}), "
                     f"registered entries "
                     f"{snap.get('registered_entries', 0)} "
                     f"[ledger contract "
                     f"v{snap.get('ledger_version', '?')}]")
        for pm in mem.get("oom_postmortems") or []:
            inj = " (injected)" if pm.get("injected") else ""
            L.append(f"  OOM in {pm.get('where')}{inj}: "
                     f"{str(pm.get('error'))[:80]}")
            for name, nb in (pm.get("top_owners") or [])[:3]:
                L.append(f"    held by {name:<28} {_fmt_bytes(nb):>10}")
            for s in pm.get("suggestions") or []:
                L.append(f"    try: {s.get('knob')} — {s.get('hint')}")

    mesh = d.get("mesh")
    if mesh:
        L.append("")
        L.append("Mesh health (cross-rank flight recorder)")
        L.append("-" * 40)
        if not mesh.get("measured"):
            L.append("  measured: NO — "
                     + ("; ".join(mesh.get("notes") or [])
                        or "no cross-rank rendezvous reconstructed"))
        colls = ", ".join(f"{k}: {v}" for k, v
                          in sorted((mesh.get("collectives")
                                     or {}).items()))
        L.append(f"  ranks: {mesh['n_ranks']}   rendezvous: "
                 f"{len(mesh.get('rendezvous') or [])}"
                 + (f" ({colls})" if colls else "")
                 + f"   total wait: "
                 f"{float(mesh.get('total_wait_s') or 0):.4f} s")
        ranks = mesh.get("ranks") or {}
        if ranks:
            L.append(f"  {'rank':<6}{'compute_s':>11}{'wait_s':>9}"
                     f"{'straggler':>11}{'last':>6}{'halo':>10}"
                     f"{'skew_ms':>9}")
            for rank_id in sorted(ranks, key=lambda k: int(k)):
                r = ranks[rank_id]
                L.append(
                    f"  {str(rank_id):<6}"
                    f"{float(r['compute_s']):>11.4f}"
                    f"{float(r['wait_s']):>9.4f}"
                    f"{float(r['straggler_score']):>11.2f}"
                    f"{int(r['arrived_last']):>6}"
                    f"{_fmt_bytes(r['halo_bytes']):>10}"
                    f"{float(r['clock_skew_s']) * 1e3:>9.3f}")
        for gkey, g in sorted((mesh.get("groups") or {}).items()):
            share = g.get("last_share")
            L.append(
                f"  {gkey}: {int(g['collectives'])} rendezvous, "
                f"wait {float(g['wait_s']):.4f} s, mean spread "
                f"{float(g.get('mean_spread_s') or 0) * 1e3:.3f} ms"
                + (f", rank {g['last_rank_mode']} last {share:.0%}"
                   if isinstance(share, (int, float)) else ""))
        for e in mesh.get("desync") or []:
            if e["kind"] == "silent":
                L.append(f"  DESYNC rank {e['rank']}: silent for "
                         f"{float(e['gap_s']):.3f} s "
                         f"({float(e['gap_fraction']):.0%} of span)")
            else:
                L.append(f"  DESYNC rank {e['rank']}: {e['ran']} vs "
                         f"{e['peers_ran']} {e['group']} {e['op']} "
                         "collective(s)")
        if mesh.get("truncated_tails"):
            L.append(f"  truncated trailing line(s) skipped: "
                     f"{mesh['truncated_tails']}")

    srv = d.get("serving")
    if srv:
        L.append("")
        L.append("serving")
        L.append("-" * 40)
        for k, v in srv["requests"].items():
            L.append(f"  requests {k:<20} {v}")
        for k, v in srv["rejections"].items():
            L.append(f"  REJECTED {k:<20} {v}")
        for k, v in srv["setup_kinds"].items():
            L.append(f"  setup {k:<23} {v}")
        c = srv["cache"]
        L.append(f"  cache hits/misses/evictions: {c['hits']}/"
                 f"{c['misses']}/{c['evictions']}")
        b = srv["batches"]
        if b["count"]:
            L.append(f"  batches: {b['count']} (mean {b['mean_size']}, "
                     f"max {b['max_size']} RHS)")
        lat = srv["latency_s"]
        if lat["p50"] is not None:
            L.append(f"  latency p50/p95/p99: {lat['p50']*1e3:.1f}/"
                     f"{lat['p95']*1e3:.1f}/{lat['p99']*1e3:.1f} ms")

    lanes = d.get("serving_lanes")
    if lanes:
        L.append("")
        L.append("serving lanes (multi-device scale-out)")
        L.append("-" * 40)
        L.append(f"  {'lane':<6}{'sessions':>9}{'queue':>7}"
                 f"{'inflight':>9}{'requests':>9}{'attain':>8}")
        for ln, v in lanes["lanes"].items():
            att = v.get("attainment")
            L.append(
                f"  {ln:<6}{int(v.get('sessions') or 0):>9}"
                f"{int(v.get('queue_depth') or 0):>7}"
                f"{int(v.get('inflight') or 0):>9}"
                f"{int(v.get('requests') or 0):>9}"
                + (f"{att:>8.1%}" if isinstance(att, (int, float))
                   else f"{'-':>8}"))
        L.append(f"  steals: {lanes['steals']}   replications: "
                 f"{lanes['replications']}   sessions total: "
                 f"{lanes['total_sessions']}")
        if lanes.get("routes"):
            L.append("  routes: " + "  ".join(
                f"{k}={v}" for k, v in lanes["routes"].items()))

    slo = d.get("slo")
    if slo:
        L.append("")
        L.append("SLO (windowed attainment + request lifecycle)")
        L.append("-" * 40)
        w = slo.get("window") or {}
        att, burn = w.get("attainment"), w.get("burn_rate")
        if isinstance(att, (int, float)):
            L.append(
                f"  attainment: {att:.2%} of {int(w.get('requests', 0))}"
                f" windowed requests (target "
                f"{w.get('target', 0):.1%}"
                + (f", latency obj {w.get('latency_ms_objective'):.0f}"
                   " ms" if w.get("latency_ms_objective") else "")
                + ")")
        if isinstance(burn, (int, float)):
            L.append(f"  error-budget burn rate: {burn:.2f}×"
                     + ("  OVERLOADED" if w.get("overloaded") else ""))
        for oc, n in (slo.get("outcomes") or {}).items():
            L.append(f"  outcome {oc:<22} {n}")
        ps = slo.get("phase_split") or {}
        if ps:
            L.append(f"  {'phase':<12}{'count':>8}{'mean_ms':>10}")
            for ph, v in ps.items():
                m = v.get("mean_s")
                L.append(f"  {ph:<12}{v['count']:>8}"
                         + (f"{m * 1e3:>10.2f}"
                            if isinstance(m, (int, float))
                            else f"{'?':>10}"))

    fl = d.get("failures")
    if fl:
        L.append("")
        L.append("failures & recovery")
        L.append("-" * 40)
        for k, v in fl.get("solve_failures_by_kind", {}).items():
            L.append(f"  failed solves {k:<24} {v}")
        for e in fl.get("breakdowns", []):
            it = e.get("iteration")
            L.append(f"  breakdown {str(e.get('kind')):<20}"
                     + (f" at iteration {it}" if it is not None else ""))
        if fl.get("recovery_attempts"):
            L.append(f"  recovery attempts: {fl['recovery_attempts']}"
                     f"  (recovered {fl.get('recovered', 0)}, "
                     f"exhausted {fl.get('exhausted', 0)})")
            for e in fl.get("recovery_events", []):
                L.append(f"    {str(e.get('kind')):<20}"
                         f"{str(e.get('action')):<14}"
                         f"-> {e.get('outcome')}")
        for k, v in fl.get("fault_injected", {}).items():
            L.append(f"  INJECTED {k:<24} {v}")
        if fl.get("history_truncated"):
            L.append(f"  history truncations: "
                     f"{fl['history_truncated']}")
        if fl.get("quarantined"):
            L.append(f"  quarantined patterns: {fl['quarantined']}")
            for e in fl.get("quarantine_events", []):
                L.append(f"    {e.get('pattern')} after "
                         f"{e.get('failures')} failures")
        if fl.get("serve_retries"):
            L.append(f"  serve request retries: {fl['serve_retries']}")
        if fl.get("breaker_trips"):
            L.append(f"  lane breaker trips: {fl['breaker_trips']}")

    setup = d.get("setup")
    if setup:
        L.extend(_render_setup(setup))
    cc = d.get("compile_cache")
    if cc:
        L.append("")
        L.append("warm start (compile cache + AOT store)")
        L.append("-" * 40)
        rate = cc.get("hit_rate")
        L.append(f"  lookups: {cc['hits'] + cc['misses']}  hits: "
                 f"{cc['hits']}  misses: {cc['misses']}"
                 + (f"  (hit rate {rate:.0%})"
                    if isinstance(rate, (int, float)) else ""))
        for k, v in cc.get("hits_by_layer", {}).items():
            L.append(f"  hits {k:<28} {v}")
        for k, v in cc.get("fallbacks_by_reason", {}).items():
            L.append(f"  FALLBACK {k:<24} {v}")
    fbs = d.get("setup_fallbacks")
    if fbs:
        L.append("")
        L.append("device setup fallbacks")
        L.append("-" * 40)
        groups: dict = {}
        for fb in fbs:
            k = (fb.get("level"), fb.get("component", "rap"),
                 fb.get("reason", "?"))
            groups[k] = groups.get(k, 0) + 1
        for (lvl, comp, reason), cnt in sorted(
                groups.items(), key=lambda kv: (str(kv[0][0]),
                                                kv[0][1])):
            where = f"level {lvl}" if lvl is not None else "toplevel"
            times = f"  ({cnt}×)" if cnt > 1 else ""
            L.append(f"  {where:<10} {comp:<9} reason: "
                     f"{reason}{times}")

    conv = d["convergence"]
    if conv:
        L.append("")
        L.append("convergence")
        L.append("-" * 40)
        if "iterations" in conv:
            L.append(f"  iterations:   {int(conv['iterations'])}")
        if "final_relres" in conv:
            L.append(f"  final relres: {conv['final_relres']:.3e}")
        if "rate" in conv and isinstance(conv.get("rate"), (int, float)):
            L.append(f"  reduction/iter: {conv['rate']:.3f}")
        if isinstance(conv.get("asymptotic_rate"), (int, float)):
            L.append(f"  asymptotic rate: {conv['asymptotic_rate']:.3f}")
        if conv.get("divergences"):
            L.append(f"  DIVERGENCES:  {conv['divergences']}")

    fr = d.get("forensics")
    if fr:
        L.extend(_render_forensics(fr))

    L.append("")
    if d["hints"]:
        L.append("hints")
        L.append("-" * 40)
        for h in d["hints"]:
            L.append(f"  * {h}")
    else:
        L.append("hints: none — the trace looks healthy")
    return "\n".join(L) + "\n"


def _render_setup(setup: dict) -> List[str]:
    """The setup-attribution report block: totals with the
    execute/compile/transfer/host split, coverage + HBM watermark, and
    the ranked phase table (telemetry/setup_profile.py)."""
    L: List[str] = []
    L.append("")
    L.append("setup attribution (per phase)")
    L.append("-" * 40)
    s = setup.get("summary") or {}
    total = setup.get("total_s") or 0.0

    def pct(v):
        return f"{(v or 0.0) / total:.0%}" if total else "?"

    if s:
        L.append(f"  setup {total:.3f} s = "
                 f"compile {s.get('compile_s', 0.0):.3f} s ({pct(s.get('compile_s'))})"
                 f" + transfer {s.get('transfer_s', 0.0):.3f} s ({pct(s.get('transfer_s'))})"
                 f" + execute {s.get('execute_s', 0.0):.3f} s ({pct(s.get('execute_s'))})"
                 f" + host {s.get('host_s', 0.0):.3f} s ({pct(s.get('host_s'))})")
        wc = s.get("worker_compile_s") or 0.0
        wt = s.get("worker_transfer_s") or 0.0
        if wc or wt:
            parts = []
            if wc:
                parts.append(f"{wc:.3f} s compile")
            if wt:
                parts.append(f"{wt:.3f} s transfer")
            L.append(f"  (+{' + '.join(parts)} on worker threads, "
                     "overlapped with the owner's wait phases)")
        cov = s.get("coverage")
        wm = s.get("mem_watermark_bytes")
        L.append("  coverage: "
                 + (f"{cov:.0%} of setup wall attributed"
                    if isinstance(cov, (int, float)) else "?")
                 + (f"   HBM watermark: {_fmt_bytes(wm)}"
                    if wm else "")
                 + (f"   uploads/downloads: {int(s.get('uploads', 0))}"
                    f"/{int(s.get('downloads', 0))}"
                    if s.get("uploads") or s.get("downloads") else ""))
    L.append(f"  {'phase':<22}{'self_s':>9}{'share':>7}{'compile':>9}"
             f"{'transfer':>10}{'rest':>9}  kind")
    shown = 0
    for p in setup.get("phases", []):
        if shown >= 12:
            break
        shown += 1
        rest = p.get("execute_s", p.get("host_s", 0.0))
        L.append(
            f"  {p['name']:<22}{p['self_s']:>9.3f}"
            f"{p.get('share', 0.0):>7.1%}{p['compile_s']:>9.3f}"
            f"{p.get('transfer_s', 0.0):>10.3f}{rest:>9.3f}  "
            f"{p.get('kind', '?')}"
            + ("  (overlapped)" if p.get("overlapped") else ""))
    return L


def _fmt_factor(f) -> str:
    return f"{f:7.3f}" if isinstance(f, (int, float)) else f"{'-':>7}"


def _render_forensics(fr: dict) -> List[str]:
    """The convergence-forensics report block: per-level per-component
    reduction factors, the coarse-solve factor, the weakest link, and
    the hierarchy quality probes."""
    L: List[str] = []
    if fr.get("levels"):
        L.append("")
        L.append("convergence forensics (per-level cycle anatomy)")
        L.append("-" * 40)
        L.append(f"  {'lvl':<4}{'cycles':>7}{'pre':>8}{'coarse':>8}"
                 f"{'post':>8}{'total':>8}")
        for lvl, x in sorted(fr["levels"].items(),
                             key=lambda kv: int(kv[0])):
            L.append(f"  {lvl:<4}{int(x.get('cycles', 0)):>7}"
                     + _fmt_factor(x.get("pre_smooth")).rjust(8)
                     + _fmt_factor(x.get("coarse_corr")).rjust(8)
                     + _fmt_factor(x.get("post_smooth")).rjust(8)
                     + _fmt_factor(x.get("total")).rjust(8))
        c = fr.get("coarse")
        if c and isinstance(c.get("factor"), (int, float)):
            L.append(f"  coarse solve @{c['level']}: factor "
                     f"{c['factor']:.3f} ({c['cycles']}×)")
        w = fr.get("weakest")
        if w:
            L.append(f"  weakest component: level {w['level']} "
                     f"{_COMPONENT_LABEL[w['component']]} "
                     f"(factor {w['factor']:.3f})")
    if fr.get("probes"):
        L.append("")
        L.append("hierarchy quality probes")
        L.append("-" * 40)
        L.append(f"  {'lvl':<4}{'rows':>10}{'cf':>7}{'nullsp':>8}"
                 f"{'galerkin':>10}{'strong':>8}")
        for lvl, p in sorted(fr["probes"].items(),
                             key=lambda kv: int(kv[0])):
            ge = p.get("galerkin_err")
            L.append(f"  {lvl:<4}{int(p.get('rows', 0)):>10}"
                     + _fmt_factor(p.get("cf_ratio")).rjust(7)
                     + _fmt_factor(p.get("nullspace")).rjust(8)
                     + (f"{ge:>10.1e}" if isinstance(ge, (int, float))
                        else f"{'-':>10}")
                     + _fmt_factor(p.get("strong_frac")).rjust(8))
    return L


# ---------------------------------------------------------------- diff
def diff(da: dict, db: dict) -> dict:
    """Two-trace A/B comparison (pipeline-on/off, 64³-vs-128³): the
    level-by-level convergence picture of ``da`` vs ``db`` with drifts
    past :data:`DIFF_DRIFT` called out."""
    conv_a, conv_b = da["convergence"], db["convergence"]

    def pair(key):
        return {"a": conv_a.get(key), "b": conv_b.get(key)}

    fra = da.get("forensics") or {}
    frb = db.get("forensics") or {}
    la, lb = fra.get("levels", {}), frb.get("levels", {})
    levels = {}
    for lvl in sorted(set(la) | set(lb), key=int):
        row = {}
        for comp in COMPONENTS + ("total",):
            row[comp] = {
                "a": (la.get(lvl) or {}).get(comp),
                "b": (lb.get(lvl) or {}).get(comp)}
        levels[lvl] = row
    rows = {}
    for lvl in sorted(set(da["levels"]) | set(db["levels"]),
                      key=lambda v: int(v) if str(v).isdigit() else 99):
        rows[lvl] = {"a": (da["levels"].get(lvl) or {}).get("rows"),
                     "b": (db["levels"].get(lvl) or {}).get("rows")}
    phases = {}
    for k in sorted(set(da["phases"]) | set(db["phases"])):
        phases[k] = {
            "a": (da["phases"].get(k) or {}).get("total_s"),
            "b": (db["phases"].get(k) or {}).get("total_s")}
    drifts: List[str] = []
    for lvl, row in levels.items():
        for comp, v in row.items():
            if comp == "total":
                continue
            a, b = v["a"], v["b"]
            if isinstance(a, (int, float)) and \
                    isinstance(b, (int, float)) and \
                    abs(b - a) >= DIFF_DRIFT:
                word = "worsened" if b > a else "improved"
                drifts.append(
                    f"level {lvl} {_COMPONENT_LABEL[comp]} {word} "
                    f"{a:.2f} → {b:.2f}")
    wa, wb = fra.get("weakest"), frb.get("weakest")
    if wa and wb and (wa["level"], wa["component"]) != \
            (wb["level"], wb["component"]):
        drifts.append(
            f"weakest component moved: level {wa['level']} "
            f"{_COMPONENT_LABEL[wa['component']]} → level "
            f"{wb['level']} {_COMPONENT_LABEL[wb['component']]}")
    # device anatomy A/B: per-scope measured device seconds side by
    # side (only when BOTH traces carry a measured anatomy — comparing
    # a measurement against a stub would read as a regression)
    device = None
    deva, devb = da.get("device") or {}, db.get("device") or {}
    if deva.get("measured") and devb.get("measured"):
        sa, sb = deva.get("scopes") or {}, devb.get("scopes") or {}
        device = {
            "total_device_s": {"a": deva.get("total_device_s"),
                               "b": devb.get("total_device_s")},
            "scopes": {s: {"a": sa.get(s), "b": sb.get(s)}
                       for s in sorted(set(sa) | set(sb))},
        }
        for s, v in device["scopes"].items():
            a, b = v["a"], v["b"]
            if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                    and a > 0 and (b / a >= 1.5 or b / a <= 1 / 1.5) \
                    and max(a, b) * 1e3 >= 1.0:    # ignore sub-ms noise
                word = "worsened" if b > a else "improved"
                drifts.append(f"device time {s} {word} "
                              f"{a * 1e3:.2f} → {b * 1e3:.2f} ms")
    # HBM ledger A/B: per-owner resident bytes side by side.  Same
    # both-measured rule as the anatomy for the backend-truth fields;
    # the owner table diffs in stub mode too (census bytes are real
    # either way)
    memory = None
    mema = (da.get("memory") or {}).get("snapshot") or {}
    memb = (db.get("memory") or {}).get("snapshot") or {}
    if mema and memb:
        oa, ob = mema.get("owners") or {}, memb.get("owners") or {}
        memory = {
            "measured": {"a": mema.get("measured"),
                         "b": memb.get("measured")},
            "owners": {o: {"a": oa.get(o), "b": ob.get(o)}
                       for o in sorted(set(oa) | set(ob))},
        }
        if mema.get("measured") and memb.get("measured"):
            pa = {dev: d.get("peak_bytes")
                  for dev, d in (mema.get("devices") or {}).items()}
            pb = {dev: d.get("peak_bytes")
                  for dev, d in (memb.get("devices") or {}).items()}
            memory["peak_bytes"] = {
                dev: {"a": pa.get(dev), "b": pb.get(dev)}
                for dev in sorted(set(pa) | set(pb))}
        for o, v in memory["owners"].items():
            a, b = v["a"], v["b"]
            if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                    and a > 0 and (b / a >= 1.5 or b / a <= 1 / 1.5) \
                    and max(a, b) >= 1 << 20:     # ignore sub-MiB noise
                word = "grew" if b > a else "shrank"
                drifts.append(f"HBM owner {o} {word} "
                              f"{_fmt_bytes(a)} → {_fmt_bytes(b)}")
    # mesh A/B: per-rank wait side by side.  Same both-measured rule
    # as the anatomy — a single-rank trace has no rendezvous, and
    # comparing one against a mesh would read as a regression
    mesh = None
    ma, mb = da.get("mesh") or {}, db.get("mesh") or {}
    if ma.get("measured") and mb.get("measured"):
        ra, rb = ma.get("ranks") or {}, mb.get("ranks") or {}
        mesh = {
            "total_wait_s": {"a": ma.get("total_wait_s"),
                             "b": mb.get("total_wait_s")},
            "ranks": {r: {"a": (ra.get(r) or {}).get("wait_s"),
                          "b": (rb.get(r) or {}).get("wait_s")}
                      for r in sorted(set(ra) | set(rb),
                                      key=lambda k: int(k))},
        }
        for r, v in mesh["ranks"].items():
            a, b = v["a"], v["b"]
            if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                    and a > 0 and (b / a >= 1.5 or b / a <= 1 / 1.5) \
                    and max(a, b) >= 0.01:        # ignore sub-10ms noise
                word = "worsened" if b > a else "improved"
                drifts.append(f"mesh wait rank {r} {word} "
                              f"{a * 1e3:.1f} → {b * 1e3:.1f} ms")
    return {"a": da["files"], "b": db["files"],
            "convergence": {k: pair(k) for k in
                            ("iterations", "final_relres", "rate",
                             "asymptotic_rate")},
            "rows": rows, "phases": phases, "levels": levels,
            "device": device,
            "memory": memory,
            "mesh": mesh,
            "drifts": drifts}


def _fmt_num(v, spec=".3f") -> str:
    if isinstance(v, (int, float)):
        return format(v, spec)
    return "-"


def render_diff(dd: dict) -> str:
    """Human-readable report of a :func:`diff` result."""
    L: List[str] = []
    L.append("amgx convergence diff")
    L.append("=" * 60)
    L.append(f"A: {', '.join(dd['a'])}")
    L.append(f"B: {', '.join(dd['b'])}")
    L.append("")
    L.append("convergence (A vs B)")
    L.append("-" * 40)
    c = dd["convergence"]
    it = c["iterations"]
    if it["a"] is not None or it["b"] is not None:
        L.append(f"  iterations:      "
                 f"{_fmt_num(it['a'], '.0f')} vs "
                 f"{_fmt_num(it['b'], '.0f')}")
    rr = c["final_relres"]
    if rr["a"] is not None or rr["b"] is not None:
        L.append(f"  final relres:    "
                 f"{_fmt_num(rr['a'], '.3e')} vs "
                 f"{_fmt_num(rr['b'], '.3e')}")
    for key, label in (("rate", "reduction/iter: "),
                       ("asymptotic_rate", "asymptotic rate:")):
        v = c[key]
        if v["a"] is not None or v["b"] is not None:
            L.append(f"  {label} {_fmt_num(v['a'])} vs "
                     f"{_fmt_num(v['b'])}")
    if dd["rows"]:
        L.append("")
        L.append("hierarchy (rows, A vs B)")
        L.append("-" * 40)
        for lvl, v in dd["rows"].items():
            L.append(f"  level {lvl:<4} {_fmt_num(v['a'], '.0f'):>10}"
                     f" vs {_fmt_num(v['b'], '.0f'):>10}")
    if dd["levels"]:
        L.append("")
        L.append("cycle anatomy (A | B per component)")
        L.append("-" * 40)
        L.append(f"  {'lvl':<4}{'pre A|B':>16}{'coarse A|B':>18}"
                 f"{'post A|B':>16}")
        for lvl, row in dd["levels"].items():
            def ab(comp, row=row):
                v = row[comp]
                return (f"{_fmt_num(v['a'])}|{_fmt_num(v['b'])}")
            L.append(f"  {lvl:<4}{ab('pre_smooth'):>16}"
                     f"{ab('coarse_corr'):>18}{ab('post_smooth'):>16}")
    if dd["phases"]:
        L.append("")
        L.append("phase totals (A vs B, seconds)")
        L.append("-" * 40)
        for k, v in dd["phases"].items():
            L.append(f"  {k:<10} {_fmt_num(v['a'], '.4f'):>10} vs "
                     f"{_fmt_num(v['b'], '.4f'):>10}")
    if dd.get("device"):
        L.append("")
        L.append("device anatomy (A vs B, measured device ms)")
        L.append("-" * 40)
        t = dd["device"]["total_device_s"]
        L.append(f"  {'total':<34}"
                 f"{_fmt_num((t['a'] or 0) * 1e3):>10} vs "
                 f"{_fmt_num((t['b'] or 0) * 1e3):>10}")
        for s, v in dd["device"]["scopes"].items():
            a = (v["a"] or 0) * 1e3 if v["a"] is not None else None
            b = (v["b"] or 0) * 1e3 if v["b"] is not None else None
            L.append(f"  {s:<34}{_fmt_num(a):>10} vs "
                     f"{_fmt_num(b):>10}")
    if dd.get("memory"):
        L.append("")
        L.append("device memory (A vs B, resident bytes per owner)")
        L.append("-" * 40)
        for o, v in dd["memory"]["owners"].items():
            fa = _fmt_bytes(v["a"]) if v["a"] is not None else "-"
            fb = _fmt_bytes(v["b"]) if v["b"] is not None else "-"
            L.append(f"  {o:<34}{fa:>10} vs {fb:>10}")
        for dev, v in (dd["memory"].get("peak_bytes") or {}).items():
            fa = _fmt_bytes(v["a"]) if v["a"] is not None else "-"
            fb = _fmt_bytes(v["b"]) if v["b"] is not None else "-"
            L.append(f"  peak {dev:<29}{fa:>10} vs {fb:>10}")
    if dd.get("mesh"):
        L.append("")
        L.append("mesh wait (A vs B, seconds per rank)")
        L.append("-" * 40)
        t = dd["mesh"]["total_wait_s"]
        L.append(f"  {'total':<10}{_fmt_num(t['a'], '.4f'):>10} vs "
                 f"{_fmt_num(t['b'], '.4f'):>10}")
        for r, v in dd["mesh"]["ranks"].items():
            L.append(f"  rank {str(r):<5}{_fmt_num(v['a'], '.4f'):>10}"
                     f" vs {_fmt_num(v['b'], '.4f'):>10}")
    L.append("")
    if dd["drifts"]:
        L.append("drifts")
        L.append("-" * 40)
        for h in dd["drifts"]:
            L.append(f"  * {h}")
    else:
        L.append("drifts: none past the threshold")
    return "\n".join(L) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    diff_paths: List[str] = []
    if "--diff" in argv:
        i = argv.index("--diff")
        diff_paths = argv[i + 1:]
        argv = argv[:i]
        if not diff_paths:
            print("doctor: --diff requires a second trace",
                  file=sys.stderr)
            return 2
    paths = argv
    if not paths:
        print("usage: python -m amgx_tpu.telemetry.doctor "
              "<trace.jsonl> [more.jsonl ...] "
              "[--diff other.jsonl ...] [--json]",
              file=sys.stderr)
        return 2
    # a diverged solve restores "Infinity" gauge tokens to real floats
    # for the math above — re-sanitize so --json output stays strict
    # JSON (jq-parseable), like every other exporter here
    from .export import _sanitize
    try:
        d = diagnose(paths)
        dd = diff(d, diagnose(diff_paths)) if diff_paths else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"doctor: cannot read trace: {e}", file=sys.stderr)
        return 1
    if dd is not None:
        if as_json:
            print(json.dumps(_sanitize(dd), indent=2, default=str,
                             allow_nan=False))
        else:
            print(render_diff(dd), end="")
        return 0
    if as_json:
        print(json.dumps(_sanitize(d), indent=2, default=str,
                         allow_nan=False))
    else:
        print(render(d), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
