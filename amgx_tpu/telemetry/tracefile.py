"""Chrome-trace export: view an amgx solve in Perfetto.

Converts the span/event/metric ring (or a JSONL trace file, including
multi-process ones) into Trace Event Format JSON — the format
``chrome://tracing`` and https://ui.perfetto.dev load directly:

* every writing session becomes one *process* track (``pid`` from the
  session's meta header, falling back to a synthetic index), with its
  recording threads as thread tracks — a multi-process mesh run shows
  one lane per rank;
* ``span_begin``/``span_end`` pairs become complete (``"X"``) slices
  with the begin record's ``attrs`` as slice args;
* ``event`` records become instants (``"i"``);
* counter samples become counter (``"C"``) tracks with the RUNNING SUM
  (the trace format draws absolute values), gauges track their last
  written value;
* serving observability: threads that executed ``serve_batch`` spans
  are named ``serve-worker-N`` tracks (``"M"`` thread_name metadata),
  and every ``request_trace`` event becomes an async ``"b"``/``"e"``
  pair spanning the request's whole lifetime, keyed by its trace id —
  overlapping requests stack instead of mis-nesting, and the batch
  slice that served a request carries its trace id in ``trace_ids``.

Timestamps: record ``t`` is ``perf_counter`` seconds, whose epoch is
per-process.  Session meta headers carry a paired
(``t_perf``, ``t_unix``) clock sample, so sessions are aligned onto one
wall-clock timeline; a headerless record list falls back to t − min(t).
"""
from __future__ import annotations

import json
from typing import IO, List, Optional, Union

from . import recorder
from .export import _sanitize, read_sessions

#: trace-event phases this exporter emits (telemetry_check validates);
#: "b"/"e" are the async request-lifecycle slices, "s"/"f" the
#: rendezvous flow arrows of a multi-rank mesh trace (early arrival →
#: last arrival of one reconstructed collective)
PHASES = ("X", "i", "C", "M", "b", "e", "s", "f")


def _args(d: dict) -> dict:
    return {str(k): v for k, v in _sanitize(d or {}).items()}


def _session_events(records: List[dict], pid: int, offset_s: float,
                    label: str) -> List[dict]:
    """Trace events of one session; ``offset_s`` maps the session's
    perf_counter timeline onto the merged timeline."""
    out: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": label},
    }]

    def ts(t):
        # microseconds, clamped — a tiny negative from clock-sample
        # skew would make Perfetto drop the whole track
        return max((t + offset_s) * 1e6, 0.0)

    begins = {}             # sid -> span_begin record
    counters = {}           # (name, labels) -> running sum
    worker_tids = set()     # threads that executed serve_batch spans
    for r in records:
        kind = r["kind"]
        if kind == "span_begin":
            begins[r["sid"]] = r
        elif kind == "span_end":
            b = begins.pop(r["sid"], None)
            t1 = r["t"]
            dur = r.get("dur", 0.0) or 0.0
            if r["name"] == "serve_batch":
                worker_tids.add(r["tid"])
            out.append({
                "ph": "X", "name": r["name"], "pid": pid,
                "tid": r["tid"], "ts": ts(t1 - dur),
                "dur": max(dur * 1e6, 0.0),
                "args": _args(b["attrs"] if b else {}),
            })
        elif kind == "event" and r["name"] == "request_trace":
            # one async b/e pair per request, spanning submit →
            # terminal (the event fires at completion and carries the
            # total latency); the trace id keys the pair AND appears
            # in the serving batch slice's trace_ids args — the link
            # between a request's lifetime and the batch that ran it
            a = r.get("attrs", {})
            lat = a.get("latency_s")
            lat = float(lat) if isinstance(lat, (int, float)) else 0.0
            rid = str(a.get("trace_id", "?"))
            name = f"request:{a.get('outcome', '?')}"
            out.append({
                "ph": "b", "cat": "request", "id": rid, "name": name,
                "pid": pid, "tid": r["tid"],
                "ts": ts(r["t"] - lat), "args": _args(a),
            })
            out.append({
                "ph": "e", "cat": "request", "id": rid, "name": name,
                "pid": pid, "tid": r["tid"], "ts": ts(r["t"]),
            })
        elif kind == "event":
            out.append({
                "ph": "i", "name": r["name"], "pid": pid,
                "tid": r["tid"], "ts": ts(r["t"]), "s": "t",
                "args": _args(r.get("attrs", {})),
            })
            if r["name"] == "hbm_snapshot":
                # the HBM ledger sample additionally draws one counter
                # track per device (bytes_in_use) so memory pressure is
                # plottable next to the phase spans that caused it
                devs = (r.get("attrs") or {}).get("devices")
                if isinstance(devs, dict):
                    for dev, d in sorted(devs.items()):
                        v = d.get("bytes_in_use") \
                            if isinstance(d, dict) else None
                        if isinstance(v, (int, float)) \
                                and not isinstance(v, bool):
                            out.append({
                                "ph": "C",
                                "name": f"hbm {dev}",
                                "pid": pid, "tid": 0,
                                "ts": ts(r["t"]),
                                "args": {"value": v},
                            })
            if r["name"] == "device_anatomy":
                # the device-time anatomy additionally draws one counter
                # track per attributed scope (seconds of measured device
                # time) so the split is plottable next to the host spans
                sc = (r.get("attrs") or {}).get("scopes")
                if isinstance(sc, dict):
                    for sname, sec in sorted(sc.items()):
                        if isinstance(sec, (int, float)) \
                                and not isinstance(sec, bool):
                            out.append({
                                "ph": "C",
                                "name": f"device_s {sname}",
                                "pid": pid, "tid": 0,
                                "ts": ts(r["t"]),
                                "args": {"value": sec},
                            })
        elif kind in ("counter", "gauge", "hist"):
            v = r["value"]
            if isinstance(v, str):      # "Infinity" tokens: not plottable
                continue
            lbl = r["name"]
            if r["labels"]:
                lbl += "{" + ",".join(
                    f"{k}={v2}" for k, v2 in
                    sorted(r["labels"].items())) + "}"
            if kind == "counter":
                counters[lbl] = counters.get(lbl, 0) + v
                v = counters[lbl]
            elif kind == "hist":
                continue                # durations already shown as spans
            out.append({
                "ph": "C", "name": lbl, "pid": pid, "tid": 0,
                "ts": ts(r["t"]), "args": {"value": v},
            })
    # unmatched begins (an open span at flush time): emit as instants so
    # the work is visible rather than silently dropped
    for b in begins.values():
        out.append({"ph": "i", "name": b["name"] + " (open)", "pid": pid,
                    "tid": b["tid"], "ts": ts(b["t"]), "s": "t",
                    "args": _args(b["attrs"])})
    # name the serving worker tracks — a mesh of anonymous tids is
    # unreadable the moment two workers interleave batches
    for i, t in enumerate(sorted(worker_tids)):
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": t, "args": {"name": f"serve-worker-{i}"}})
    return out


def chrome_trace(source: Union[None, str, List[str], List[dict]] = None
                 ) -> dict:
    """Build the Trace Event Format dict.

    ``source``: None → the current ring contents (one synthetic
    session); a path or list of paths → JSONL trace file(s), one process
    track per session; a list of ring records → one synthetic session.
    """
    if source is None:
        sessions = [{"meta": None, "records": recorder.records()}]
    elif isinstance(source, str):
        sessions = read_sessions(source)
    elif source and isinstance(source[0], str):
        sessions = []
        for p in source:
            sessions.extend(read_sessions(p))
    else:
        sessions = [{"meta": None, "records": list(source or [])}]

    # wall-clock alignment: offset each session so its records land at
    # (t_unix of session start) + (t − t_perf); relative to the earliest
    # session so timestamps stay small
    t0s = []
    for s in sessions:
        m = s["meta"] or {}
        if "t_perf" in m and "t_unix" in m:
            t0s.append(m["t_unix"] - m["t_perf"])
    base = min(t0s) if t0s else None
    events: List[dict] = []
    offsets: List[float] = []
    pids: List[int] = []
    for i, s in enumerate(sessions):
        m = s["meta"] or {}
        pid = int(m.get("pid", i + 1))
        label = f"amgx pid {pid}"
        if m.get("session"):
            label += f" [{m['session']}]"
        if m.get("host"):
            label += f" @{m['host']}"
        if base is not None and "t_perf" in m:
            offset = (m["t_unix"] - m["t_perf"]) - base
        else:
            ts_all = [r["t"] for r in s["records"]]
            offset = -min(ts_all) if ts_all else 0.0
        offsets.append(offset)
        pids.append(pid)
        events.extend(_session_events(s["records"], pid, offset, label))
    events.extend(_rendezvous_flows(sessions, pids, offsets))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _rendezvous_flows(sessions: List[dict], pids: List[int],
                      offsets: List[float]) -> List[dict]:
    """Flow arrows of a multi-rank trace: for every reconstructed
    collective rendezvous (meshtrace join), one ``s`` → ``f`` arrow
    from each early rank's arrival to the last rank's — Perfetto draws
    who the mesh waited on.  Empty on single-session traces."""
    if len(sessions) < 2:
        return []
    from . import meshtrace
    out: List[dict] = []
    for n, rv in enumerate(meshtrace.rendezvous_from_sessions(sessions)):
        arr = sorted(rv["arrivals"],
                     key=lambda a: a["t"] + offsets[a["session"]])
        last = arr[-1]
        t_last = max((last["t"] + offsets[last["session"]]) * 1e6, 0.0)
        name = f"rendezvous:{rv['op']}:{rv['group']}"
        for a in arr[:-1]:
            fid = f"rv{n}-r{a['rank']}"
            out.append({
                "ph": "s", "cat": "rendezvous", "id": fid,
                "name": name, "pid": pids[a["session"]],
                "tid": a["tid"],
                "ts": max((a["t"] + offsets[a["session"]]) * 1e6, 0.0),
            })
            out.append({
                "ph": "f", "cat": "rendezvous", "id": fid, "bp": "e",
                "name": name, "pid": pids[last["session"]],
                "tid": last["tid"], "ts": t_last,
            })
    return out


def write_chrome_trace(path_or_file: Union[str, IO],
                       source: Union[None, str, List] = None) -> int:
    """Write the trace-event JSON; returns the event count.  The output
    loads in Perfetto / ``chrome://tracing`` as-is."""
    trace = chrome_trace(source)

    def write(f):
        json.dump(trace, f, allow_nan=False)

    if isinstance(path_or_file, str):
        with open(path_or_file, "w") as f:
            write(f)
    else:
        write(path_or_file)
    return len(trace["traceEvents"])


def validate_chrome_trace(trace: dict) -> int:
    """Structural validation against the trace-event schema subset this
    exporter emits (``scripts/telemetry_check.py`` calls this); returns
    the event count, raises ``ValueError`` on drift."""
    def need(cond, msg):
        if not cond:
            raise ValueError(f"chrome trace schema: {msg}")

    need(isinstance(trace, dict), "not an object")
    evs = trace.get("traceEvents")
    need(isinstance(evs, list), "missing traceEvents list")
    for e in evs:
        need(isinstance(e, dict), f"event is not an object: {e!r}")
        need(e.get("ph") in PHASES, f"unknown phase {e.get('ph')!r}")
        need(isinstance(e.get("name"), str) and e["name"],
             f"missing name: {e!r}")
        need(isinstance(e.get("pid"), int), f"missing pid: {e!r}")
        need(isinstance(e.get("tid"), int), f"missing tid: {e!r}")
        if e["ph"] != "M":
            need(isinstance(e.get("ts"), (int, float)) and e["ts"] >= 0,
                 f"bad ts: {e!r}")
        if e["ph"] == "X":
            need(isinstance(e.get("dur"), (int, float))
                 and e["dur"] >= 0, f"bad dur: {e!r}")
        if e["ph"] in ("b", "e", "s", "f"):
            # async pairs and flow arrows match on (cat, id) — either
            # missing breaks the slice/arrow silently in Perfetto
            need(isinstance(e.get("id"), str) and e["id"],
                 f"async/flow event missing id: {e!r}")
            need(isinstance(e.get("cat"), str) and e["cat"],
                 f"async/flow event missing cat: {e!r}")
        if e["ph"] == "f":
            # binding point "e" attaches the arrow head to the
            # ENCLOSING slice at ts — without it Perfetto binds to the
            # next slice and the arrow points at the wrong span
            need(e.get("bp") == "e", f"flow finish missing bp: {e!r}")
        if "args" in e:
            need(isinstance(e["args"], dict), f"bad args: {e!r}")
    # the whole thing must be strict JSON (Perfetto rejects bare NaN)
    json.dumps(trace, allow_nan=False)
    return len(evs)
