"""Shared ``jax.profiler`` chrome-trace plumbing.

Both profiler-trace consumers — :mod:`amgx_tpu.telemetry.overlap`
(measured comm-vs-compute overlap) and
:mod:`amgx_tpu.telemetry.deviceprof` (device-time cycle anatomy) —
need the same mechanics: resolve a profiler logdir to its newest
``plugins/profile/<run>/<host>.trace.json[.gz]`` capture, load the
(possibly gzipped) JSON, normalise the three accepted trace spellings
(path / loaded dict / raw event iterable) to an event list, and do
interval arithmetic over complete ("X") slices.  This module is that
single copy; host-side file parsing only, safe without any profiler
plugin installed.
"""
from __future__ import annotations

import gzip
import json
import os
import re
from typing import Iterable, Iterator, List, Optional

#: XLA op-name fragments that mean inter-chip communication.  HLO names
#: keep their kind as a prefix ("all-reduce.1", "fusion.all_gather", …)
#: across XLA versions; matching fragments is robust to the separators.
COMM_RE = re.compile(
    r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|"
    r"collective[-_]?permute|all[-_]?to[-_]?all|ppermute|psum",
    re.IGNORECASE)

#: trace-viewer metadata / host-side bookkeeping phases that are not
#: device work at all
SKIP_PH = {"M", "I", "C"}


def load_json(path: str) -> Optional[dict]:
    """Load a chrome-trace JSON file (gzip-aware); None on any error."""
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def find_trace_file(path: str) -> Optional[str]:
    """Resolve a trace argument to a concrete chrome-trace file.

    Accepts the file itself (``.trace.json`` / ``.trace.json.gz`` or any
    ``.json``) or a profiler log directory, which is searched recursively
    (``jax.profiler.trace`` writes ``plugins/profile/<run>/
    <host>.trace.json.gz``); the newest match wins.
    """
    if os.path.isfile(path):
        return path
    if not os.path.isdir(path):
        return None
    hits: List[str] = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f.endswith((".trace.json", ".trace.json.gz")):
                hits.append(os.path.join(root, f))
    if not hits:
        return None
    return max(hits, key=lambda p: os.path.getmtime(p))


def trace_events(trace: "str | dict | Iterable[dict]") -> List[dict]:
    """Normalise any accepted trace spelling to its event list.

    ``trace``: a path (file or profiler logdir), a loaded chrome-trace
    dict, or an iterable of trace events.  Returns ``[]`` when the path
    resolves to nothing or the file is unreadable/malformed — callers
    then degrade the same way they would on an empty capture.
    """
    if isinstance(trace, str):
        f = find_trace_file(trace)
        data = load_json(f) if f else None
        if data is None:
            return []
        ev = data.get("traceEvents", [])
        return ev if isinstance(ev, list) else []
    if isinstance(trace, dict):
        ev = trace.get("traceEvents", [])
        return ev if isinstance(ev, list) else []
    try:
        return list(trace)
    except TypeError:           # None, int, ... — nothing to measure
        return []


def complete_slices(events: Iterable[dict]) -> Iterator[dict]:
    """The complete ("X") duration slices of a trace: every event that
    carries real wall extent (metadata/instant/counter phases and
    zero/None-duration rows are dropped).  Malformed rows (non-dict, or
    non-numeric ts/dur) are skipped rather than raised — profiler traces
    in the wild carry junk."""
    for ev in events:
        if not isinstance(ev, dict):
            continue
        if ev.get("ph", "X") in SKIP_PH:
            continue
        dur = ev.get("dur")
        ts = ev.get("ts")
        if not isinstance(dur, (int, float)) or \
                not isinstance(ts, (int, float)) or dur <= 0:
            continue
        yield ev


def merge_intervals(iv: List[tuple]) -> List[tuple]:
    """Coalesce (start, end) intervals into a sorted disjoint cover."""
    iv = sorted(iv)
    out: List[tuple] = []
    for s, e in iv:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def overlap_len(s: float, e: float, merged: List[tuple]) -> float:
    """Length of [s, e) covered by a :func:`merge_intervals` result."""
    total = 0.0
    for ms, me in merged:
        if me <= s:
            continue
        if ms >= e:
            break
        total += min(e, me) - max(s, ms)
    return total


def union_len(iv: List[tuple]) -> float:
    """Total length of the union of (start, end) intervals."""
    return sum(e - s for s, e in merge_intervals(iv))
