"""Metrics registry: counters, gauges and wall-clock histograms.

The aggregated twin of the ring buffer: instruments update both — the
ring keeps raw samples for the JSONL trace, the registry keeps the
aggregate state the Prometheus snapshot renders.

Metric names are a **stable, versioned contract** (see :data:`METRICS`
and the README "Observability" section); renames are schema changes.
Instruments are cheap no-ops unless telemetry is enabled
(``recorder.is_enabled()``), so hot paths — SpMV dispatch runs at trace
time inside ``jax.jit`` — pay a single attribute check when it is off.
"""
from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Optional, Tuple

from . import recorder

#: v1 metric-name registry: name -> (type, help).  ``{label}`` names in
#: the help string document the label keys each metric carries.
METRICS: Dict[str, Tuple[str, str]] = {
    "amgx_spmv_dispatch_total":
        ("counter", "SpMV dispatch decisions by chosen pack {pack}"),
    "amgx_spmv_fallback_total":
        ("counter", "SpMV calls where a packed kernel layout fell back "
                    "to a generic path {pack,reason}"),
    "amgx_jit_trace_total":
        ("counter", "jax.jit python-cache misses (retraces), process-wide"),
    "amgx_device_time_seconds_total":
        ("counter", "profiler-measured device seconds attributed to a "
                    "named-scope contract scope (telemetry/deviceprof.py) "
                    "{scope}"),
    "amgx_jit_compile_total":
        ("counter", "XLA backend compiles (jit recompiles), process-wide"),
    "amgx_solves_total":
        ("counter", "completed solves by final status {status}"),
    "amgx_solve_diverged_total":
        ("counter", "solves that ended with a non-finite residual"),
    "amgx_hierarchy_levels":
        ("gauge", "levels in the last AMG hierarchy setup"),
    "amgx_level_rows":
        ("gauge", "rows of one hierarchy level {level}"),
    "amgx_level_nnz":
        ("gauge", "stored nonzeros of one hierarchy level {level}"),
    "amgx_operator_complexity":
        ("gauge", "sum(level nnz) / fine nnz of the last hierarchy"),
    "amgx_grid_complexity":
        ("gauge", "sum(level rows) / fine rows of the last hierarchy"),
    "amgx_solve_iterations":
        ("gauge", "iterations of the last solve"),
    "amgx_solve_final_relres":
        ("gauge", "final true relative residual of the last solve"),
    "amgx_solve_convergence_rate":
        ("gauge", "geometric-mean per-iteration residual reduction of "
                  "the last solve"),
    "amgx_last_setup_seconds":
        ("gauge", "wall seconds of the last solver setup"),
    "amgx_last_solve_seconds":
        ("gauge", "wall seconds of the last solve"),
    # ---- distributed / halo-exchange instrumentation (PR 3) --------
    "amgx_halo_exchange_total":
        ("counter", "halo exchanges instrumented (traced) "
                    "{ring,op,path}"),
    "amgx_halo_bytes_total":
        ("counter", "ICI wire bytes per instrumented halo exchange, "
                    "mesh-wide, padded send buffers {ring,op}"),
    "amgx_halo_entries_total":
        ("counter", "useful (unpadded) halo values gathered per "
                    "instrumented exchange, mesh-wide {ring,op}"),
    "amgx_dist_boundary_fraction":
        ("gauge", "boundary rows / local rows of one shard {device}"),
    "amgx_dist_halo_entries":
        ("gauge", "ring-1 halo width of one shard {device}"),
    "amgx_dist_ring_hops":
        ("gauge", "collectives one halo exchange executes: ppermute "
                  "hops of the ring schedule, or 1 on the all_gather "
                  "fallback {ring}"),
    # ---- pod-scale distributed AMG (distributed/agglomerate.py +
    # costmodel.dist_overlap; PR 12) ---------------------------------
    "amgx_dist_agglomerate_total":
        ("counter", "coarse-level agglomerations planned onto a "
                    "shrinking sub-mesh {reused=0|1}"),
    "amgx_dist_submesh_parts":
        ("gauge", "active ranks of the sub-mesh one distributed "
                  "hierarchy level lives on {level}"),
    "amgx_dist_overlap_fraction":
        ("gauge", "fraction of one level's halo exchange hideable "
                  "under its interior SpMV (1 = fully hidden); "
                  "modelled, or profiler-measured when a trace was "
                  "supplied (telemetry/overlap.py) {level}"),
    # ---- communication-avoiding Krylov (ops/blas.py fused
    # reductions + solvers/krylov.py CA/PIPELINED variants; PR 16) ----
    "amgx_krylov_collectives_total":
        ("counter", "reduction collectives executed by Krylov solve "
                    "loops: trace-time per-iteration profile x executed "
                    "iterations {op=dot|norm|gram|fused|replace}"),
    # ---- mesh flight recorder (telemetry/meshtrace.py; PR 20):
    # cross-rank rendezvous reconstruction over clock-aligned
    # per-rank traces ------------------------------------------------
    "amgx_mesh_wait_seconds_total":
        ("counter", "per-rank wall seconds spent waiting for the last "
                    "arrival at reconstructed collective rendezvous "
                    "(halo exchanges, fused Krylov reductions, "
                    "agglomerations) {rank}"),
    "amgx_mesh_straggler_score":
        ("gauge", "share of mesh-wide induced wait caused by one rank "
                  "arriving last at collectives (0 = never last, "
                  "1 = every second of wait) {rank}"),
    "amgx_mesh_clock_skew_seconds":
        ("gauge", "fitted wall-clock offset of one rank's trace "
                  "relative to rank 0 (per-session offset+slope fit "
                  "over meta + clock_sample pairs) {rank}"),
    # ---- convergence forensics (telemetry/forensics.py) ------------
    "amgx_forensics_nullspace":
        ("gauge", "near-nullspace preservation |A*1|inf/|A|inf of one "
                  "hierarchy level {level}"),
    "amgx_forensics_galerkin_err":
        ("gauge", "sampled relative error of R*A*P vs the stored "
                  "coarse operator below one level {level}"),
    "amgx_forensics_cf_ratio":
        ("gauge", "coarse rows / fine rows across one coarsening "
                  "{level}"),
    "amgx_forensics_strong_frac":
        ("gauge", "fraction of sampled off-diagonal couplings that are "
                  "strong (AHAT theta=0.25) on one level {level}"),
    "amgx_forensics_asymptotic_rate":
        ("gauge", "asymptotic per-iteration residual reduction of the "
                  "last solve (trailing-half estimate)"),
    # ---- static cost model (telemetry/costmodel.py); the dtype label
    # is the level's STORAGE precision (mixed precision: bf16 levels
    # stream half the value bytes of f32 ones) ---------------------
    "amgx_level_spmv_bytes":
        ("gauge", "modelled HBM bytes of one SpMV on one hierarchy "
                  "level {level,dtype}"),
    "amgx_level_spmv_flops":
        ("gauge", "useful flops (2*nnz) of one SpMV on one hierarchy "
                  "level {level,dtype}"),
    "amgx_level_padding_waste":
        ("gauge", "stored slots / nnz of one level's device pack "
                  "{level,dtype}"),
    # ---- setup profiler (telemetry/setup_profile.py) ----------------
    "amgx_setup_phase_seconds":
        ("gauge", "exclusive wall seconds of one setup phase component "
                  "of the last profiled setup {component}"),
    "amgx_setup_compile_seconds":
        ("gauge", "XLA backend-compile seconds attributed to the last "
                  "profiled setup"),
    "amgx_setup_trace_seconds":
        ("gauge", "jaxpr-trace seconds attributed to the last profiled "
                  "setup"),
    "amgx_setup_transfer_seconds":
        ("gauge", "blocking host<->device transfer seconds of the last "
                  "profiled setup"),
    "amgx_setup_mem_watermark_bytes":
        ("gauge", "device-memory high-water mark sampled at phase "
                  "boundaries of the last profiled setup"),
    "amgx_setup_transfer_bytes_total":
        ("counter", "host<->device bytes moved by instrumented setup "
                    "transfers {kind=upload|download}"),
    "amgx_setup_transfers_total":
        ("counter", "blocking transfer calls instrumented during setup "
                    "{kind=upload|download}"),
    # ---- device setup engine (amg/device_setup/ + ops/spgemm.py) ----
    "amgx_spgemm_total":
        ("counter", "device SpGEMM numeric passes by operation "
                    "{op=rap|agg|spgemm}"),
    "amgx_device_rap_total":
        ("counter", "Galerkin RAP products by executing path "
                    "{path=device|host}"),
    "amgx_device_setup_fallback_total":
        ("counter", "device setup gates that fell back to the host "
                    "path {reason}"),
    "amgx_spgemm_plan_cache":
        ("gauge", "setup plans held in the pattern-keyed plan cache"),
    "amgx_spgemm_plan_bytes":
        ("gauge", "schedule bytes held in the pattern-keyed plan "
                  "cache"),
    "amgx_setup_seconds":
        ("histogram", "solver setup wall seconds"),
    "amgx_resetup_seconds":
        ("histogram", "solver numeric-resetup wall seconds"),
    "amgx_solve_seconds":
        ("histogram", "solve wall seconds"),
    "amgx_jit_compile_seconds":
        ("histogram", "XLA backend compile wall seconds"),
    # ---- serving subsystem (amgx_tpu/serve/, PR 4) ------------------
    "amgx_serve_requests_total":
        ("counter", "serving requests completed by outcome {status}"),
    "amgx_serve_rejected_total":
        ("counter", "serving admission rejections {reason}"),
    "amgx_serve_queue_depth":
        ("gauge", "requests waiting in the serving admission queue"),
    "amgx_serve_batch_size":
        ("histogram", "RHS count of one executed micro-batch"),
    "amgx_serve_request_seconds":
        ("histogram", "request latency, submit to completed result"),
    "amgx_serve_cache_hits_total":
        ("counter", "setup-cache lookups that found a session"),
    "amgx_serve_cache_misses_total":
        ("counter", "setup-cache lookups that created a session"),
    "amgx_serve_cache_evictions_total":
        ("counter", "sessions evicted by the cache byte budget"),
    "amgx_serve_cache_bytes":
        ("gauge", "resident device bytes of cached sessions"),
    "amgx_serve_setup_total":
        ("counter", "session preparations by kind "
                    "{kind=full|resetup|reuse}"),
    "amgx_worker_task_failures_total":
        ("counter", "worker-pool tasks that raised (pool survives)"),
    # ---- zero cold-start (utils/jaxcompat.py + serve/aot.py) --------
    "amgx_compile_cache_hits_total":
        ("counter", "executable loads that skipped compilation "
                    "{layer=xla|aot}"),
    "amgx_compile_cache_misses_total":
        ("counter", "executable lookups that had to compile "
                    "{layer=xla|aot}"),
    "amgx_compile_cache_fallbacks_total":
        ("counter", "AOT-store entries unusable at load (version "
                    "mismatch, corruption, serialize failure) {reason}"),
    "amgx_aot_store_bytes":
        ("gauge", "serialized-executable bytes resident in the AOT "
                  "store directory"),
    "amgx_aot_store_entries":
        ("gauge", "executables resident in the AOT store directory"),
    "amgx_serve_warmup_seconds":
        ("histogram", "wall seconds of one SolveService.warmup "
                      "prefetch"),
    # ---- live serving observability (telemetry/slo.py + httpd.py +
    # ---- request-lifecycle tracing in serve/) -----------------------
    "amgx_serve_phase_seconds":
        ("histogram", "per-request lifecycle phase duration "
                      "{phase=admit|queue_wait|prepare|solve|finalize"
                      "|errored}"),
    "amgx_serve_inflight":
        ("gauge", "requests drained from the queue whose batch has not "
                  "finished"),
    "amgx_serve_overload":
        ("gauge", "SLO overload trip wire (1 = windowed shed rate or "
                  "queue depth past threshold)"),
    "amgx_slo_attainment":
        ("gauge", "fraction of windowed requests that completed OK "
                  "within deadline and latency objective"),
    "amgx_slo_burn_rate":
        ("gauge", "error-budget burn rate (1-attainment)/(1-target) "
                  "over the SLO window"),
    "amgx_slo_window_requests":
        ("gauge", "request outcomes currently held in the SLO window"),
    "amgx_serve_profile_total":
        ("counter", "served batches sampled by the solve-path profiler "
                    "(serve_profile_every)"),
    "amgx_serve_achieved_gbs":
        ("gauge", "measured device bandwidth of the last profiled "
                  "batch of one pattern {pattern}"),
    # ---- multi-device scale-out (serve/router.py): per-lane executor
    # ---- state + the router's replication/steal decisions -----------
    "amgx_serve_lane_queue_depth":
        ("gauge", "requests waiting in one executor lane's admission "
                  "queue {lane}"),
    "amgx_serve_lane_inflight":
        ("gauge", "requests drained from one lane's queue whose batch "
                  "has not finished {lane}"),
    "amgx_serve_lane_attainment":
        ("gauge", "SLO attainment over one lane's request window "
                  "{lane}"),
    "amgx_serve_lane_sessions":
        ("gauge", "sessions resident in one lane's setup-cache slice "
                  "{lane}"),
    "amgx_serve_steals_total":
        ("counter", "cold-pattern requests work-stolen to the "
                    "least-loaded lane instead of their hash-home "
                    "{lane=receiving lane}"),
    "amgx_serve_replications_total":
        ("counter", "hot patterns replicated onto an idle lane "
                    "{lane=replica lane}"),
    # ---- breakdown-aware solving (errors.FailureKind +
    # ---- solvers/recovery.py + utils/faultinject.py, ISSUE 13) ------
    "amgx_solve_failures_total":
        ("counter", "monitored solves that terminated in failure, by "
                    "taxonomy kind {kind}"),
    "amgx_history_truncated_total":
        ("counter", "residual-history slabs whose non-finite rows were "
                    "filtered (each emits a history_truncated event "
                    "with the first bad iteration)"),
    "amgx_recovery_total":
        ("counter", "recovery-ladder attempts {kind,action,outcome}"),
    "amgx_fault_injected_total":
        ("counter", "fault-injection firings by point {point}"),
    "amgx_retries_total":
        ("counter", "bounded transient-failure retries "
                    "(utils/retry.py) {label}"),
    "amgx_worker_respawns_total":
        ("counter", "worker pools re-created after out-of-band "
                    "death/shutdown was detected"),
    "amgx_serve_retries_total":
        ("counter", "serve requests re-queued by the per-request "
                    "execution retry budget (serve_retry_max)"),
    "amgx_serve_quarantined_total":
        ("counter", "patterns quarantined after consecutive error "
                    "outcomes (serve_quarantine_threshold)"),
    "amgx_serve_quarantined_patterns":
        ("gauge", "patterns currently rejected at admission by the "
                  "quarantine"),
    "amgx_serve_breaker_trips_total":
        ("counter", "executor-lane circuit-breaker trips {lane}"),
    # ---- HBM ledger (telemetry/memledger.py, ISSUE 18) --------------
    "amgx_hbm_bytes":
        ("gauge", "owner-attributed device bytes of the last ledger "
                  "sample {device,owner}"),
    "amgx_hbm_headroom_bytes":
        ("gauge", "bytes_limit - bytes_in_use of one device at the "
                  "last ledger sample (measured platforms only) "
                  "{device}"),
    "amgx_hbm_peak_bytes":
        ("gauge", "allocator peak_bytes_in_use of one device at the "
                  "last ledger sample (measured platforms only) "
                  "{device}"),
}

#: wall-clock histogram bucket upper bounds (seconds)
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0)
#: count-valued histogram buckets (micro-batch sizes)
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
#: histograms whose unit is a count, not seconds
_COUNT_HISTS = frozenset({"amgx_serve_batch_size"})

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(recorder._jsonable(v)))
                        for k, v in labels.items()))


class _Hist:
    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)   # +1: +Inf bucket
        self.total = 0.0
        self.count = 0

    def observe(self, v: float):
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.total += v
        self.count += 1


class MetricsRegistry:
    """Thread-safe aggregate store keyed by (name, sorted labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], float] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], float] = {}
        self._hists: Dict[Tuple[str, _LabelKey], _Hist] = {}

    # ------------------------------------------------------------- update
    def counter_inc(self, name: str, value: float = 1.0, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = value

    def gauge_clear(self, name: str):
        """Drop every labeled series of one gauge — used before
        re-emitting a label family whose cardinality may shrink (a
        shallower hierarchy must not leave stale deep-level gauges in
        the Prometheus snapshot)."""
        with self._lock:
            for key in [k for k in self._gauges if k[0] == name]:
                del self._gauges[key]

    def hist_observe(self, name: str, value: float, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                bounds = COUNT_BUCKETS if name in _COUNT_HISTS \
                    else DEFAULT_BUCKETS
                h = self._hists[key] = _Hist(bounds)
            h.observe(value)

    # -------------------------------------------------------------- query
    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def get_gauge(self, name: str, default=None, **labels):
        with self._lock:
            return self._gauges.get((name, _label_key(labels)), default)

    def snapshot(self) -> dict:
        """Plain-python copy: {"counters": {...}, "gauges": {...},
        "histograms": {...}} with ``name{k=v,...}`` string keys."""
        def fmt(name, lk):
            if not lk:
                return name
            return name + "{" + ",".join(f"{k}={v}" for k, v in lk) + "}"
        with self._lock:
            return {
                "counters": {fmt(n, lk): v for (n, lk), v
                             in sorted(self._counters.items())},
                "gauges": {fmt(n, lk): v for (n, lk), v
                           in sorted(self._gauges.items())},
                "histograms": {fmt(n, lk): {"count": h.count,
                                            "sum": h.total}
                               for (n, lk), h
                               in sorted(self._hists.items())},
            }

    def items(self):
        """Locked copy of the raw stores (used by the Prometheus
        renderer): (counters, gauges, hists)."""
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    {k: (h.bounds, tuple(h.counts), h.total, h.count)
                     for k, h in self._hists.items()})

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


# ------------------------------------------------- gated module instruments
def counter_inc(name: str, value: float = 1.0, **labels):
    if not recorder.is_enabled():
        return
    _registry.counter_inc(name, value, **labels)
    recorder.metric_sample("counter", name, value, labels)


def gauge_set(name: str, value, **labels):
    if not recorder.is_enabled():
        return
    value = float(value)
    _registry.gauge_set(name, value, **labels)
    recorder.metric_sample("gauge", name, value, labels)


def hist_observe(name: str, value: float, **labels):
    if not recorder.is_enabled():
        return
    value = float(value)
    _registry.hist_observe(name, value, **labels)
    recorder.metric_sample("hist", name, value, labels)
