"""In-process observability endpoint (stdlib, no dependencies).

A :class:`ThreadingHTTPServer` that exposes the live process the way a
production solve service must be inspectable — **without restarting
it**.  Off by default; the serving layer starts one when the
``metrics_port`` knob is set (``SolveService.start_endpoint``), and
anything else can run one via :func:`serve_httpd`.  Binds loopback
only: this is an operator surface, not a public API.

Routes:

* ``GET /metrics`` — the Prometheus text snapshot
  (:func:`amgx_tpu.telemetry.export.prometheus_text`), scrapeable by
  any textfile/HTTP collector;
* ``GET /healthz`` — liveness JSON: aggregate queue depth/capacity,
  in-flight batches, accepting flag, the SLO overload trip wire, and —
  for a multi-lane service — a ``lanes`` array with every executor
  lane's own queue/SLO/saturation state plus ``saturated_lanes``.
  Returns **503 when overloaded (which for a multi-lane service means
  EVERY lane is saturated — with a healthy lane left the router can
  still steal/replicate, so the instance keeps working capacity),
  drained (not accepting), or the health computation itself failed**
  (the load-balancer eviction contract) and 200 otherwise; a partial
  saturation stays 200 with the saturated subset named in the body so
  an LB — or ``SolveService.drain_lane`` — can drain one chip;
* ``GET /statusz`` — the solve doctor's machine-readable diagnosis of
  the current telemetry ring (``doctor.diagnose`` over a snapshot) —
  "what would the doctor say right now";
* ``GET /debug/trace?seconds=N`` — drain the event ring to JSONL
  (records of the last N seconds; everything without ``seconds``),
  exactly the file every offline tool (doctor, Perfetto exporter)
  already reads;
* ``GET /debug/profile?seconds=N`` — programmatic ``jax.profiler``
  capture of the live process for N seconds (clamped to
  [0.05, 60]); responds with the trace directory.  One capture at a
  time — concurrent requests get 409.
* ``GET /debug/memory`` — the HBM ledger's live view
  (:mod:`amgx_tpu.telemetry.memledger`): a fresh ownership snapshot,
  top owners and the recent headroom history.
* ``GET /debug/mesh`` — the mesh flight recorder's view of the
  current telemetry ring (:mod:`amgx_tpu.telemetry.meshtrace`):
  clock-aligned rendezvous join, per-rank wait/straggler table and
  desync detection; an honest ``measured=false`` stub on a
  single-rank process.

Handlers never touch solver internals beyond the read-only stats
surface, so a scrape cannot perturb a solve beyond the GIL.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import recorder
from .export import (_json_line, _meta_record, _sanitize,
                     prometheus_text)

#: /debug/profile capture bounds (seconds) — an unbounded capture
#: would let one request hold the profiler lock forever
PROFILE_MIN_S = 0.05
PROFILE_MAX_S = 60.0

#: one profiler capture at a time, process-wide (jax.profiler.trace is
#: a process singleton)
_profile_lock = threading.Lock()


class _Handler(BaseHTTPRequestHandler):
    # ``self.server.owner`` is the ObservabilityHTTPD that started the
    # ThreadingHTTPServer — the handle /healthz reads state through

    # silence the default per-request stderr line — a scraped service
    # would log every 15 s forever
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj):
        self._reply(code, json.dumps(_sanitize(obj), indent=2,
                                     default=str,
                                     allow_nan=False).encode(),
                    "application/json")

    def do_GET(self):  # noqa: N802 — stdlib contract
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            route = {
                "/metrics": self._metrics,
                "/healthz": self._healthz,
                "/statusz": self._statusz,
                "/debug/trace": self._debug_trace,
                "/debug/profile": self._debug_profile,
                "/debug/deviceprof": self._debug_deviceprof,
                "/debug/memory": self._debug_memory,
                "/debug/mesh": self._debug_mesh,
            }.get(url.path)
            if route is None:
                self._json(404, {"error": f"no route {url.path}",
                                 "routes": ["/metrics", "/healthz",
                                            "/statusz", "/debug/trace",
                                            "/debug/profile",
                                            "/debug/deviceprof",
                                            "/debug/memory",
                                            "/debug/mesh"]})
                return
            route(q)
        except BrokenPipeError:
            pass                     # client went away mid-response
        except Exception as e:       # noqa: BLE001 — endpoint must live
            try:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    # -------------------------------------------------------------- routes
    def _metrics(self, q):
        # refresh the amgx_slo_* gauges before rendering: a scrape-only
        # consumer (no stats()/healthz poller) would otherwise read
        # whatever the last poll happened to leave behind
        self.server.owner.health()
        self._reply(200, prometheus_text().encode(),
                    "text/plain; version=0.0.4")

    def _healthz(self, q):
        h = self.server.owner.health()
        # the LB eviction contract: 503 for overload, but ALSO for a
        # drained service (accepting=false rejects 100% of submissions
        # long before the shed rate trips the wire) and for a health
        # computation that itself failed
        unhealthy = (h.get("overloaded") or not h.get("ok", True)
                     or not h.get("accepting", True))
        self._reply(503 if unhealthy else 200,
                    json.dumps(_sanitize(h), allow_nan=False).encode(),
                    "application/json")

    def _statusz(self, q):
        # the doctor is a trace-file consumer — hand it a snapshot of
        # the ring through a temp file so /statusz and the offline
        # report can never drift apart
        from . import doctor
        from .export import dump_jsonl
        fd, path = tempfile.mkstemp(suffix=".jsonl",
                                    prefix="amgx_statusz_")
        os.close(fd)
        try:
            dump_jsonl(path)
            self._json(200, doctor.diagnose([path]))
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _debug_trace(self, q):
        recs = recorder.records()
        seconds = _qfloat(q, "seconds")
        if seconds is not None:
            cut = time.perf_counter() - max(seconds, 0.0)
            recs = [r for r in recs if r.get("t", 0.0) >= cut]
        lines = [_json_line(_meta_record())]
        lines.extend(_json_line(r) for r in recs)
        self._reply(200, ("\n".join(lines) + "\n").encode(),
                    "application/x-ndjson")

    def _debug_profile(self, q):
        out = self._capture_profile(q)
        self._json(out.pop("_code", 200), out)

    def _debug_deviceprof(self, q):
        # the device anatomy riding the same one-shot capture: profile,
        # correlate, return JUST the anatomy (plus the trace dir so a
        # deeper offline look stays possible)
        out = self._capture_profile(q)
        code = out.pop("_code", 200)
        if code != 200:
            self._json(code, out)
            return
        self._json(200, {"dir": out["dir"], "seconds": out["seconds"],
                         "device_anatomy": out.get("device_anatomy")})

    def _debug_memory(self, q):
        # the HBM ledger's live view: a fresh snapshot (not the last
        # sampled one) plus the recent headroom history — works with
        # the ledger knob off too, just with no registered owners
        from . import memledger
        snap = memledger.snapshot()
        self._json(200, {
            "enabled": memledger.is_enabled(),
            "snapshot": snap,
            "top_owners": memledger.top_owners(snap),
            "headroom_history": memledger.headroom_history(),
        })

    def _debug_mesh(self, q):
        # the mesh flight recorder is a trace-file consumer like the
        # doctor — hand it a ring snapshot through a temp file so the
        # live view and the offline one can never drift apart.  A
        # single-process ring is one rank: the reply is then the
        # honest measured=false stub, not an error
        from . import meshtrace
        from .export import dump_jsonl
        fd, path = tempfile.mkstemp(suffix=".jsonl",
                                    prefix="amgx_mesh_")
        os.close(fd)
        try:
            dump_jsonl(path)
            self._json(200, meshtrace.analyze(path))
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _capture_profile(self, q) -> dict:
        """One-shot profiler capture + parsed summaries.  Returns the
        reply dict (``_code`` carries a non-200 status)."""
        seconds = _qfloat(q, "seconds")
        if seconds is None:          # absent/unparsable — NOT ?seconds=0,
            seconds = 1.0            # which clamps to PROFILE_MIN_S below
        seconds = min(max(seconds, PROFILE_MIN_S), PROFILE_MAX_S)
        if not _profile_lock.acquire(blocking=False):
            return {"_code": 409,
                    "error": "a profiler capture is already "
                             "running; retry when it ends"}
        try:
            import jax
            out_dir = tempfile.mkdtemp(prefix="amgx_profile_")
            t0 = time.perf_counter()
            jax.profiler.start_trace(out_dir)
            try:
                # the capture window: device work submitted by OTHER
                # threads during this sleep lands in the trace — that
                # is the whole point of profiling the live process
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            out = {"dir": out_dir,
                   "seconds": round(seconds, 3),
                   "wall_s": round(time.perf_counter() - t0, 3)}
            # inline parsed views of the capture (best-effort: a trace
            # with no device ops yields the measured=False stub, and a
            # parse failure must never take the endpoint down)
            try:
                from . import deviceprof, overlap
                trace = overlap.find_trace_file(out_dir)
                out["device_anatomy"] = deviceprof.capture_anatomy(
                    trace if trace is not None else {"traceEvents": []})
                out["overlap"] = overlap.measure(
                    trace if trace is not None else {"traceEvents": []})
            except Exception as e:   # noqa: BLE001 — summary is extra
                out["parse_error"] = f"{type(e).__name__}: {e}"
            return out
        finally:
            _profile_lock.release()


def _qfloat(q: dict, key: str) -> Optional[float]:
    vals = q.get(key)
    if not vals:
        return None
    try:
        return float(vals[0])
    except (TypeError, ValueError):
        return None


class ObservabilityHTTPD:
    """Owns one :class:`ThreadingHTTPServer` on a daemon thread.

    ``service``: the :class:`~amgx_tpu.serve.SolveService` whose
    queue/SLO state ``/healthz`` reports; None serves process-level
    liveness only (useful for non-serving processes that still want
    ``/metrics``)."""

    def __init__(self, service=None):
        self.service = service
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.monotonic()

    # ---------------------------------------------------------- lifecycle
    def start(self, port: int, host: str = "127.0.0.1"
              ) -> "ObservabilityHTTPD":
        """Bind and serve on a daemon thread (port 0 → ephemeral; read
        the real port from :attr:`port`).  Idempotent."""
        if self._server is not None:
            return self
        # a just-released port can linger in TIME_WAIT for a beat after
        # a restart — give the bind a short bounded retry
        # (utils/retry.py) instead of failing the endpoint start
        import errno

        from ..utils.retry import retry_call
        srv = retry_call(
            lambda: ThreadingHTTPServer((host, int(port)), _Handler),
            max_attempts=3, base_delay_s=0.05,
            retryable=lambda e: isinstance(e, OSError)
            and getattr(e, "errno", None) == errno.EADDRINUSE,
            label="httpd_bind")
        srv.daemon_threads = True
        srv.owner = self
        self._server = srv
        self._t_start = time.monotonic()
        self._thread = threading.Thread(target=srv.serve_forever,
                                        name="amgx-telemetry-httpd",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> Optional[str]:
        if self._server is None:
            return None
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    # ------------------------------------------------------------- health
    def health(self) -> dict:
        """The /healthz payload: endpoint uptime plus, when a service
        is attached, its queue/in-flight/SLO-overload state."""
        out = {"ok": True,
               "uptime_s": round(time.monotonic() - self._t_start, 3),
               "overloaded": False}
        svc = self.service
        if svc is not None:
            try:
                out.update(svc.health())
            except Exception as e:  # noqa: BLE001 — health must answer
                out.update(ok=False, error=f"{type(e).__name__}: {e}")
        return out


def serve_httpd(port: int, host: str = "127.0.0.1",
                service=None) -> ObservabilityHTTPD:
    """Start a standalone endpoint (port 0 → ephemeral).  The serving
    layer calls this through ``SolveService.start_endpoint``; scripts
    and tests can call it directly."""
    return ObservabilityHTTPD(service).start(port, host)
