"""Telemetry exporters: JSONL traces and Prometheus text snapshots.

JSONL: one JSON object per line.  Every writing session starts with a
``meta`` record declaring the schema version (a file appended by two
processes holds one ``meta`` per session, and ``seq`` restarts after
each); every other line is a ring record (see
:mod:`amgx_tpu.telemetry.recorder` for the field contract).  Non-finite
numbers are encoded as the strings ``"NaN"``/``"Infinity"``/
``"-Infinity"`` so every line is strict JSON (``json.dumps`` would
otherwise emit bare ``NaN`` tokens that jq/JS/Go reject — divergence
events carry exactly such norms).  :func:`validate_record` is the
single schema authority — tests and ``scripts/telemetry_check.py``
both call it, so a drifting field shows up as a failing check, not a
silently unreadable trace.

Prometheus: the standard text exposition format (``# TYPE`` /
``# HELP`` headers from the versioned :data:`~.metrics.METRICS` list,
``_bucket``/``_sum``/``_count`` series for histograms), suitable for a
node-exporter-style textfile collector or a scrape handler.
"""
from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
import uuid
from typing import IO, Iterable, List, Optional, Union

from . import metrics, recorder

_KINDS = ("meta", "span_begin", "span_end", "event", "counter", "gauge",
          "hist")

_flush_lock = threading.Lock()
#: per-path high-water sequence number for incremental flushes
_flushed_seq = {}
#: per-path ring-overflow count at the last flush (drop detection)
_flushed_dropped = {}
#: per-path perf_counter of the last clock (re-)sample; the meta header
#: pairs the clocks once at session start, which bakes any later drift
#: into the whole trace — flush_jsonl re-pairs them at most every
#: CLOCK_RESAMPLE_S so meshtrace can fit a per-session offset+slope
_clock_sampled = {}
CLOCK_RESAMPLE_S = 60.0

#: one id per writing process — the session identity the aggregator and
#: the Chrome-trace exporter key on (a telemetry_path appended by every
#: rank of a multi-process mesh holds one meta per session)
_SESSION_ID = uuid.uuid4().hex[:12]


def _meta_record() -> dict:
    """Session header.  ``pid``/``session`` identify the writing process
    (what :func:`aggregate_sessions` merges on); ``t_perf``/``t_unix``
    sample both clocks at write time so sessions from different
    processes — whose ``perf_counter`` epochs are unrelated — can be
    aligned onto one wall-clock timeline; ``dropped`` is the cumulative
    ring-overflow count so a truncated trace is detectable."""
    rec = {"kind": "meta", "name": "amgx-telemetry",
           "schema": recorder.SCHEMA_VERSION,
           "pid": os.getpid(), "session": _SESSION_ID,
           "host": socket.gethostname(),
           "t_perf": time.perf_counter(), "t_unix": time.time(),
           "dropped": recorder.dropped_count()}
    # cumulative cache-efficacy counters (telemetry/runstate.py):
    # in-process cache stats die with the process, so the meta header
    # carries the CROSS-RESTART totals — what lets bench_trend.py (and
    # any trace reader) judge warm-start efficacy across rounds.
    # Folding here also keeps the state file fresh without a separate
    # write path.  Absent when no warm-start dir is configured.
    try:
        from . import runstate
        cum = runstate.fold()
        if cum and cum.get("counters"):
            rec["cum"] = dict(cum["counters"])
    except Exception:
        pass        # observability must never break a flush
    return rec


_NONFINITE = {"NaN": math.nan, "Infinity": math.inf,
              "-Infinity": -math.inf}


def _sanitize(v):
    """Strict-JSON encoding of non-finite floats as string tokens."""
    if isinstance(v, float) and not math.isfinite(v):
        if math.isnan(v):
            return "NaN"
        return "Infinity" if v > 0 else "-Infinity"
    if isinstance(v, dict):
        return {k: _sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_sanitize(x) for x in v]
    return v


def _json_line(rec: dict) -> str:
    return json.dumps(_sanitize(rec), allow_nan=False)


def validate_record(rec: dict):
    """Raise ``ValueError`` when ``rec`` does not conform to the
    documented schema (version ``recorder.SCHEMA_VERSION``)."""
    def need(cond, msg):
        if not cond:
            raise ValueError(f"telemetry record schema: {msg}: {rec!r}")

    need(isinstance(rec, dict), "record is not an object")
    kind = rec.get("kind")
    need(kind in _KINDS, f"unknown kind {kind!r}")
    need(isinstance(rec.get("name"), str) and rec["name"],
         "missing/empty name")
    if kind == "meta":
        need(rec.get("schema") == recorder.SCHEMA_VERSION,
             f"schema version {rec.get('schema')!r} != "
             f"{recorder.SCHEMA_VERSION}")
        return
    need(isinstance(rec.get("seq"), int) and rec["seq"] > 0,
         "missing seq")
    need(isinstance(rec.get("t"), (int, float)), "missing t")
    need(isinstance(rec.get("tid"), int), "missing tid")
    if kind in ("span_begin", "span_end"):
        need(isinstance(rec.get("sid"), int), "span missing sid")
        need(rec.get("parent") is None or isinstance(rec["parent"], int),
             "bad span parent")
        if kind == "span_begin":
            need(isinstance(rec.get("attrs"), dict), "span missing attrs")
        else:
            need(isinstance(rec.get("dur"), (int, float))
                 and rec["dur"] >= 0.0, "span_end missing dur")
    elif kind == "event":
        need(isinstance(rec.get("attrs"), dict), "event missing attrs")
        need(rec.get("sid") is None or isinstance(rec["sid"], int),
             "bad event sid")
        if rec["name"] in ("cycle_level", "cycle_coarse",
                           "forensics_probe"):
            # forensics events are an analysis input contract
            # (telemetry/forensics.py keys its anatomy on the level):
            # a level that stopped being an int mis-buckets silently
            need(isinstance(rec["attrs"].get("level"), int),
                 "forensics event missing integer level")
        if rec["name"] == "setup_phase":
            # setup-profiler phase records are the analysis input of
            # setup_profile.analyze / the doctor "setup" section
            a = rec["attrs"]
            need(isinstance(a.get("component"), str) and a["component"],
                 "setup_phase event missing component")
            need(isinstance(a.get("wall_s"), (int, float)) and
                 isinstance(a.get("self_s"), (int, float)),
                 "setup_phase event missing wall_s/self_s")
            need(a.get("level") is None or isinstance(a["level"], int),
                 "setup_phase event has non-integer level")
        if rec["name"] == "setup_profile":
            need(isinstance(rec["attrs"].get("wall_s"), (int, float)),
                 "setup_profile summary missing wall_s")
        if rec["name"] == "compile_cache_fallback":
            # warm-start fallbacks are the doctor's "why did this
            # process compile anyway" input (serve/aot.py)
            need(isinstance(rec["attrs"].get("reason"), str)
                 and rec["attrs"]["reason"],
                 "compile_cache_fallback event missing reason")
        if rec["name"] == "request_trace":
            # request-lifecycle traces are the analysis input of the
            # doctor's SLO section and the Chrome-trace request slices
            # (serve/service.py emits one per terminal request)
            a = rec["attrs"]
            need(isinstance(a.get("trace_id"), str) and a["trace_id"],
                 "request_trace event missing trace_id")
            need(a.get("outcome") in ("ok", "failed", "rejected",
                                      "expired", "error"),
                 f"request_trace event has unknown outcome "
                 f"{a.get('outcome')!r}")
            need(isinstance(a.get("latency_s"), (int, float))
                 and a["latency_s"] >= 0.0,
                 "request_trace event missing latency_s")
            # "phases": durations in the documented phase vocabulary;
            # "marks": raw monotone mark offsets from `submitted`
            for key in ("phases", "marks"):
                d = a.get(key)
                need(isinstance(d, dict) and all(
                    isinstance(v, (int, float)) and v >= 0.0
                    for v in d.values()),
                     f"request_trace event missing {key} dict")
        if rec["name"] == "slo_window":
            # SLO snapshots are what bench_trend and the doctor read
            # for attainment/burn-rate trends
            a = rec["attrs"]
            need(isinstance(a.get("window_s"), (int, float)),
                 "slo_window event missing window_s")
            need(isinstance(a.get("requests"), int),
                 "slo_window event missing requests")
            for k in ("attainment", "burn_rate"):
                need(a.get(k) is None
                     or isinstance(a[k], (int, float)),
                     f"slo_window event has non-numeric {k}")
        if rec["name"] in ("level_cost", "op_cost", "operator_cost"):
            # cost-model descriptors are the doctor's roofline input;
            # the dtype field is the mixed-precision contract — a level
            # whose precision stopped being reported would silently
            # break the bf16-vs-f32 bandwidth accounting
            a = rec["attrs"]
            need(isinstance(a.get("pack"), str) and a["pack"],
                 f"{rec['name']} event missing pack")
            need(isinstance(a.get("dtype"), str) and a["dtype"],
                 f"{rec['name']} event missing dtype")
            need(isinstance(a.get("itemsize"), int),
                 f"{rec['name']} event missing itemsize")
            if rec["name"] == "level_cost":
                need(isinstance(a.get("level"), int),
                     "level_cost event missing integer level")
        if rec["name"] == "dist_overlap":
            # the distributed-level overlap audit is the doctor's
            # "distributed levels" input (costmodel.dist_overlap)
            a = rec["attrs"]
            need(isinstance(a.get("level"), int),
                 "dist_overlap event missing integer level")
            need(isinstance(a.get("n_parts"), int) and a["n_parts"] >= 1,
                 "dist_overlap event missing n_parts")
            need(isinstance(a.get("submesh_parts"), int),
                 "dist_overlap event missing submesh_parts")
            for k in ("est_interior_s", "est_halo_s",
                      "overlap_fraction"):
                need(isinstance(a.get(k), (int, float)),
                     f"dist_overlap event missing numeric {k}")
            need(isinstance(a.get("halo_bound"), bool),
                 "dist_overlap event missing halo_bound bool")
            # modelled vs profiler-measured provenance (PR 16): every
            # overlap number must say which it is
            need(isinstance(a.get("measured"), bool),
                 "dist_overlap event missing measured bool")
        if rec["name"] == "device_anatomy":
            # the device-time cycle anatomy (telemetry/deviceprof.py):
            # every scope key must honour the naming contract, and the
            # event must say whether it is profiler truth or a stub
            a = rec["attrs"]
            need(isinstance(a.get("measured"), bool),
                 "device_anatomy event missing measured bool")
            need(isinstance(a.get("scope_version"), int)
                 and a["scope_version"] >= 1,
                 "device_anatomy event missing scope_version")
            for k in ("total_device_s", "attributed_s",
                      "unattributed_s"):
                need(isinstance(a.get(k), (int, float))
                     and not isinstance(a.get(k), bool)
                     and a[k] >= 0,
                     f"device_anatomy event missing numeric {k}")
            sc = a.get("scopes")
            need(isinstance(sc, dict),
                 "device_anatomy event missing scopes dict")
            if isinstance(sc, dict):
                from . import scopes as _scopes
                for name, sec in sc.items():
                    need(_scopes.validate(name),
                         f"device_anatomy scope {name!r} violates the "
                         f"amgx/<area>/<name> contract")
                    need(isinstance(sec, (int, float))
                         and not isinstance(sec, bool) and sec >= 0,
                         f"device_anatomy scope {name!r} has "
                         f"non-numeric seconds")
            for k in ("levels", "spmv"):
                need(isinstance(a.get(k), dict),
                     f"device_anatomy event missing {k} dict")
        if rec["name"] == "dist_agglomerate":
            # agglomeration decisions (distributed/agglomerate.py):
            # the doctor's sub-mesh lifecycle input
            a = rec["attrs"]
            for k in ("from_parts", "to_parts", "rows"):
                need(isinstance(a.get(k), int),
                     f"dist_agglomerate event missing integer {k}")
            need(a["to_parts"] >= 1
                 and a["to_parts"] <= a["from_parts"],
                 "dist_agglomerate event has non-shrinking parts")
        if rec["name"] == "recovery_attempt":
            # recovery-ladder audit records (solvers/recovery.py) are
            # the doctor's "failures & recovery" input — a drifting
            # kind/action/outcome vocabulary would silently un-count
            # recoveries
            a = rec["attrs"]
            from ..errors import FailureKind
            kinds = frozenset(k.value for k in FailureKind)
            need(a.get("kind") in kinds,
                 f"recovery_attempt event has unknown kind "
                 f"{a.get('kind')!r}")
            need(a.get("action") in ("krylov_classic", "restart",
                                     "promote", "conservative",
                                     "resetup", "ladder"),
                 f"recovery_attempt event has unknown action "
                 f"{a.get('action')!r}")
            need(isinstance(a.get("attempt"), int) and a["attempt"] >= 0,
                 "recovery_attempt event missing attempt")
            need(a.get("outcome") in ("recovered", "failed", "error",
                                      "skipped", "exhausted"),
                 f"recovery_attempt event has unknown outcome "
                 f"{a.get('outcome')!r}")
        if rec["name"] == "krylov_comm":
            # communication-avoiding Krylov accounting (PR 16): the
            # per-iteration reduction profile the perf gate's
            # collectives_per_iter ceiling and the doctor's "Krylov
            # communication" section read
            a = rec["attrs"]
            need(isinstance(a.get("solver"), str) and a["solver"],
                 "krylov_comm event missing solver")
            need(a.get("mode") in ("CLASSIC", "CA", "PIPELINED"),
                 f"krylov_comm event has unknown mode {a.get('mode')!r}")
            need(isinstance(a.get("iterations"), int)
                 and a["iterations"] >= 0,
                 "krylov_comm event missing iterations")
            per = a.get("per_iter")
            need(isinstance(per, dict) and all(
                isinstance(k, str) and isinstance(v, int)
                for k, v in per.items()),
                 "krylov_comm event missing per_iter op->count dict")
            need(isinstance(a.get("collectives_per_iter"), int)
                 and a["collectives_per_iter"] >= 0,
                 "krylov_comm event missing collectives_per_iter")
            need(isinstance(a.get("fused"), bool),
                 "krylov_comm event missing fused bool")
            need(isinstance(a.get("n_parts"), int) and a["n_parts"] >= 1,
                 "krylov_comm event missing n_parts")
        if rec["name"] == "clock_sample":
            # rate-limited clock re-pairing (flush_jsonl): the input of
            # meshtrace's per-session offset+slope fit — a sample
            # missing either clock would silently skew the whole mesh
            # timeline
            a = rec["attrs"]
            for k in ("t_perf", "t_unix"):
                need(isinstance(a.get(k), (int, float))
                     and not isinstance(a.get(k), bool),
                     f"clock_sample event missing numeric {k}")
        if rec["name"] == "mesh_truncated_tail":
            # a rank killed mid-write left a partial trailing line;
            # read_sessions skips it and says so IN the trace
            a = rec["attrs"]
            need(isinstance(a.get("line"), int) and a["line"] >= 1,
                 "mesh_truncated_tail event missing line number")
            need(isinstance(a.get("bytes"), int) and a["bytes"] >= 0,
                 "mesh_truncated_tail event missing byte count")
        if rec["name"] == "mesh_rendezvous":
            # one reconstructed cross-rank collective (meshtrace.py):
            # arrival spread + induced wait, per (op, group, sequence)
            a = rec["attrs"]
            need(a.get("op") in ("halo", "krylov", "agglomerate"),
                 f"mesh_rendezvous event has unknown op {a.get('op')!r}")
            need(isinstance(a.get("group"), str) and a["group"],
                 "mesh_rendezvous event missing group")
            need(isinstance(a.get("seq"), int) and a["seq"] >= 0,
                 "mesh_rendezvous event missing seq")
            need(isinstance(a.get("n_ranks"), int) and a["n_ranks"] >= 2,
                 "mesh_rendezvous event has fewer than 2 ranks")
            need(isinstance(a.get("last_rank"), int)
                 and a["last_rank"] >= 0,
                 "mesh_rendezvous event missing last_rank")
            for k in ("spread_s", "wait_total_s"):
                need(isinstance(a.get(k), (int, float))
                     and not isinstance(a.get(k), bool) and a[k] >= 0,
                     f"mesh_rendezvous event missing numeric {k}")
            need(isinstance(a.get("measured"), bool),
                 "mesh_rendezvous event missing measured bool")
        if rec["name"] == "mesh_health":
            # per-rank mesh accounting (meshtrace.py): the honesty
            # invariant compute + wait + unattributed ≡ wall is
            # enforced HERE, so a trace can never carry wait the rank
            # did not observably spend
            a = rec["attrs"]
            need(isinstance(a.get("measured"), bool),
                 "mesh_health event missing measured bool")
            need(isinstance(a.get("mesh_version"), int)
                 and a["mesh_version"] >= 1,
                 "mesh_health event missing mesh_version")
            need(isinstance(a.get("rank"), int) and a["rank"] >= 0,
                 "mesh_health event missing rank")
            for k in ("wall_s", "compute_s", "wait_s",
                      "unattributed_s"):
                need(isinstance(a.get(k), (int, float))
                     and not isinstance(a.get(k), bool) and a[k] >= 0,
                     f"mesh_health event missing numeric {k}")
            need(abs(a["compute_s"] + a["wait_s"] + a["unattributed_s"]
                     - a["wall_s"])
                 <= 1e-6 * max(1.0, abs(a["wall_s"])),
                 "mesh_health event violates the honesty invariant "
                 "compute + wait + unattributed == wall")
            need(isinstance(a.get("straggler_score"), (int, float))
                 and not isinstance(a.get("straggler_score"), bool)
                 and 0.0 <= a["straggler_score"] <= 1.0,
                 "mesh_health event missing straggler_score in [0,1]")
            for k in ("arrived_last", "collectives", "halo_bytes"):
                need(isinstance(a.get(k), int) and a[k] >= 0,
                     f"mesh_health event missing integer {k}")
        if rec["name"] == "fault_injected":
            # chaos-run provenance: every synthetic failure in a trace
            # must name its injection point
            need(isinstance(rec["attrs"].get("point"), str)
                 and rec["attrs"]["point"],
                 "fault_injected event missing point")
        if rec["name"] == "history_truncated":
            # forensics contract: a truncated iteration record says
            # where truncation began and how much is gone
            a = rec["attrs"]
            need(isinstance(a.get("first_bad_iteration"), int)
                 and a["first_bad_iteration"] >= 0,
                 "history_truncated event missing first_bad_iteration")
            need(isinstance(a.get("dropped"), int) and a["dropped"] >= 1,
                 "history_truncated event missing dropped count")
        if rec["name"] == "device_setup_fallback":
            # fallback events are the doctor's per-level "why did rap
            # run host-side" input (amg/device_setup/)
            a = rec["attrs"]
            need(isinstance(a.get("reason"), str) and a["reason"],
                 "device_setup_fallback event missing reason")
            need(isinstance(a.get("component"), str) and a["component"],
                 "device_setup_fallback event missing component")
            need(a.get("level") is None or isinstance(a["level"], int),
                 "device_setup_fallback event has non-integer level")

        def _check_ledger_snapshot(s, what):
            # shared shape of the HBM ledger snapshot (memledger.py):
            # the honesty invariant is validated per device, so a trace
            # can never carry an unbalanced ledger
            from . import memledger as _ml
            need(isinstance(s.get("measured"), bool),
                 f"{what} missing measured bool")
            need(isinstance(s.get("ledger_version"), int)
                 and s["ledger_version"] >= 1,
                 f"{what} missing ledger_version")
            devs = s.get("devices")
            need(isinstance(devs, dict), f"{what} missing devices dict")
            for dev, d in devs.items():
                need(isinstance(d, dict),
                     f"{what} device {dev!r} is not an object")
                for k in ("bytes_in_use", "accounted_bytes",
                          "unaccounted_bytes", "census_bytes",
                          "peak_bytes", "bytes_limit",
                          "headroom_bytes"):
                    need(isinstance(d.get(k), int) and d[k] >= 0,
                         f"{what} device {dev!r} missing integer {k}")
                need(d["accounted_bytes"] + d["unaccounted_bytes"]
                     == d["bytes_in_use"],
                     f"{what} device {dev!r} violates the honesty "
                     f"invariant accounted + unaccounted == "
                     f"bytes_in_use")
                ow = d.get("owners")
                need(isinstance(ow, dict),
                     f"{what} device {dev!r} missing owners dict")
                for name, nb in ow.items():
                    need(_ml.validate(name),
                         f"{what} owner {name!r} violates the "
                         f"amgx/<owner>/<name> contract")
                    need(isinstance(nb, int) and nb >= 0,
                         f"{what} owner {name!r} has non-integer "
                         f"bytes")
            need(isinstance(s.get("owners"), dict),
                 f"{what} missing owners dict")

        if rec["name"] == "hbm_snapshot":
            # the HBM ledger sample (telemetry/memledger.py): the
            # doctor's "Device memory" input and the chrome-trace
            # `hbm <device>` counter track
            _check_ledger_snapshot(rec["attrs"], "hbm_snapshot event")
        if rec["name"] == "oom_postmortem":
            # the OOM post-mortem bundle: a RESOURCE_EXHAUSTED without
            # one of these is an unexplained death
            a = rec["attrs"]
            need(isinstance(a.get("where"), str) and a["where"],
                 "oom_postmortem event missing where")
            need(isinstance(a.get("error"), str),
                 "oom_postmortem event missing error")
            for k in ("injected", "in_recovery", "measured"):
                need(isinstance(a.get(k), bool),
                     f"oom_postmortem event missing {k} bool")
            need(isinstance(a.get("snapshot"), dict),
                 "oom_postmortem event missing snapshot")
            _check_ledger_snapshot(a["snapshot"],
                                   "oom_postmortem snapshot")
            to = a.get("top_owners")
            need(isinstance(to, list) and all(
                isinstance(p, list) and len(p) == 2
                and isinstance(p[0], str)
                and isinstance(p[1], int) and p[1] >= 0
                for p in to),
                 "oom_postmortem event missing top_owners pairs")
            need(isinstance(a.get("headroom_history"), list),
                 "oom_postmortem event missing headroom_history")
            sg = a.get("suggestions")
            need(isinstance(sg, list) and sg and all(
                isinstance(s, dict) and isinstance(s.get("knob"), str)
                and isinstance(s.get("hint"), str) for s in sg),
                 "oom_postmortem event missing suggestions")
    else:   # counter / gauge / hist
        need(isinstance(rec.get("labels"), dict), "metric missing labels")
        v = rec.get("value")
        need((isinstance(v, (int, float)) and not isinstance(v, bool))
             or v in _NONFINITE,
             "metric missing numeric value")


def _iter_validated(lines: Iterable[str]):
    """Parse-and-validate generator over JSONL lines (each line parsed
    exactly once — ring-sized traces dominate the aggregator's cost).
    The first non-empty line must be the meta header; ``seq`` must be
    strictly increasing within a session (each appending session
    restates the meta header, after which ``seq`` may restart)."""
    last_seq = 0
    first = True
    for line in lines:
        line = line.strip()
        if not line:
            continue
        def _bare(tok):
            # the whole point of the string encoding is that strict
            # consumers (jq/JS/Go) can parse every line — a bare token
            # is exactly the drift this validator exists to catch
            raise ValueError(
                f"bare {tok} token is not strict JSON; non-finite "
                "values must be string-encoded")
        rec = json.loads(line, parse_constant=_bare)
        validate_record(rec)
        if first:
            if rec.get("kind") != "meta":
                raise ValueError("first JSONL record must be the meta "
                                 "header")
            first = False
        elif rec["kind"] == "meta":
            last_seq = 0    # a new writing session starts here
        else:
            if rec["seq"] <= last_seq:
                raise ValueError(
                    f"seq not increasing: {rec['seq']} after {last_seq}")
            last_seq = rec["seq"]
        yield rec
    if first:
        raise ValueError("empty trace: no records")


def validate_jsonl(lines: Iterable[str]) -> int:
    """Validate an iterable of JSONL lines; returns the record count."""
    return sum(1 for _ in _iter_validated(lines))


def dump_jsonl(path_or_file: Union[str, IO],
               records: Optional[List[dict]] = None) -> int:
    """Write ``records`` (default: the current ring contents) with a
    meta header; returns the number of records written."""
    recs = recorder.records() if records is None else list(records)

    def write(f):
        f.write(_json_line(_meta_record()) + "\n")
        for r in recs:
            f.write(_json_line(r) + "\n")

    if isinstance(path_or_file, str):
        with open(path_or_file, "w") as f:
            write(f)
    else:
        write(path_or_file)
    return len(recs)


def flush_jsonl(path: str) -> int:
    """Append the records produced since the last flush to ``path``;
    returns the number of records appended.  Solvers call this after
    setup/solve when ``telemetry_path`` is configured, so a
    long-running process streams its trace instead of holding it all
    in the ring.  The meta header is written on this process's FIRST
    flush to ``path`` — even when the file already has content from an
    earlier process — because ``seq`` restarts with the process and the
    header is what marks the session boundary for the validator."""
    with _flush_lock:
        first_flush = path not in _flushed_seq
        last = _flushed_seq.get(path, 0)
        # ring overflow since the last flush to this path: the evicted
        # records are gone, so say so IN the trace (the doctor reports
        # it) rather than leaving a silently truncated file
        dropped = recorder.dropped_count()
        last_dropped = _flushed_dropped.get(path, 0)
        if dropped < last_dropped:
            # recorder.reset_dropped() (telemetry.reset) zeroed the
            # counter since the last flush — restart the high-water or
            # every later overflow would hide below the stale mark
            last_dropped = 0
        if dropped > last_dropped:
            recorder.event("ring_overflow",
                           dropped=dropped - last_dropped,
                           dropped_total=dropped,
                           ring_size=recorder._STATE.ring_size)
        _flushed_dropped[path] = dropped
        # rate-limited clock re-pairing: the meta header samples
        # (t_perf, t_unix) once at session start, which bakes clock
        # drift into long traces — re-sample at most every
        # CLOCK_RESAMPLE_S so meshtrace can fit offset+slope per
        # session instead of a single offset
        now = time.perf_counter()
        if first_flush:
            _clock_sampled[path] = now      # the meta IS the first pair
        elif now - _clock_sampled.get(path, 0.0) >= CLOCK_RESAMPLE_S:
            _clock_sampled[path] = now
            recorder.event("clock_sample", t_perf=now,
                           t_unix=time.time())
        recs = [r for r in recorder.records() if r["seq"] > last]
        if first_flush or recs:
            with open(path, "a") as f:
                if first_flush:
                    f.write(_json_line(_meta_record()) + "\n")
                for r in recs:
                    f.write(_json_line(r) + "\n")
        _flushed_seq[path] = recs[-1]["seq"] if recs else last
        return len(recs)


# ------------------------------------------------------- session merging
def _restore_nonfinite(v):
    """Inverse of :func:`_sanitize` for VALUE fields read back from a
    trace: the string tokens become floats again so aggregation and the
    doctor's math see real non-finite numbers."""
    if isinstance(v, str) and v in _NONFINITE:
        return _NONFINITE[v]
    return v


def read_sessions(source: Union[str, Iterable[str]]) -> List[dict]:
    """Parse one JSONL trace into its writing sessions.

    ``source``: a path or an iterable of lines.  Returns one dict per
    session — ``{"meta": <meta record>, "records": [...]}`` — split at
    the meta headers (each appending process restates one; PR 2's
    validator contract).  The lines are validated on the way in, so a
    drifted trace fails loudly here rather than mis-merging.

    One tolerated defect: a TRAILING line that is not parseable JSON —
    a rank killed mid-write leaves exactly that, and crash postmortems
    are the mesh flight recorder's whole point — is skipped with a
    synthetic ``mesh_truncated_tail`` warning event appended to the
    last session instead of raising.  A malformed line anywhere else
    is still schema drift and still raises."""
    if isinstance(source, str):
        with open(source) as f:
            lines = f.readlines()
    else:
        lines = list(source)
    truncated = None
    for i in range(len(lines) - 1, -1, -1):
        line = lines[i].strip()
        if not line:
            continue
        try:
            json.loads(line)
        except ValueError:
            truncated = {"line": i + 1, "bytes": len(lines[i])}
            lines = lines[:i]
        break
    sessions: List[dict] = []
    for rec in _iter_validated(lines):
        if rec["kind"] == "meta":
            sessions.append({"meta": rec, "records": []})
        else:
            if "value" in rec:
                rec["value"] = _restore_nonfinite(rec["value"])
            sessions[-1]["records"].append(rec)
    if truncated is not None and sessions:
        last = sessions[-1]["records"]
        rec = {"kind": "event", "name": "mesh_truncated_tail",
               "seq": (last[-1]["seq"] + 1 if last else 1),
               "t": (last[-1]["t"] if last
                     else sessions[-1]["meta"].get("t_perf", 0.0)),
               "tid": 0, "sid": None, "attrs": truncated}
        validate_record(rec)    # the synthetic warning obeys the schema
        last.append(rec)
    return sessions


def aggregate_sessions(paths: Union[str, Iterable[str]]) -> dict:
    """Merge multi-process JSONL traces into one mesh-wide view.

    ``paths``: one path, or an iterable of paths (one per process/rank —
    or a single file every rank appended to; both layouts hold one meta
    header per session).  Returns::

        {"sessions":  [{"meta": ..., "records": [...]}, ...],
         "n_sessions": int, "n_records": int,
         "dropped_records": int,          # ring-overflow total
         "counters": {(name, labelitems): sum},   # mesh-wide sums
         "gauges":   {(name, labelitems): last},  # last write wins
         "spans":    {name: {"count": n, "total_s": s}},
         "events":   {name: count}}

    Counter samples are summed across sessions — that is what makes the
    per-rank halo byte counters a single mesh-wide total; spans keep
    per-name totals (wall-clock overlap across processes is the Chrome
    trace's job, :mod:`amgx_tpu.telemetry.tracefile`)."""
    if isinstance(paths, str):
        paths = [paths]
    sessions: List[dict] = []
    for p in paths:
        sessions.extend(read_sessions(p))
    counters: dict = {}
    gauges: dict = {}
    spans: dict = {}
    events: dict = {}
    # meta.dropped and the ring_overflow events' dropped_total are
    # CUMULATIVE per-process counters — merge with max within one
    # process identity (bench appends one session per case from the
    # same process; summing their metas would overcount), sum across
    # distinct processes
    dropped_by_proc: dict = {}
    for i, s in enumerate(sessions):
        proc = (s["meta"].get("pid"), s["meta"].get("session")) \
            if s["meta"].get("session") else ("?", i)
        s_dropped = int(s["meta"].get("dropped", 0) or 0)
        for r in s["records"]:
            kind = r["kind"]
            if kind == "counter":
                key = (r["name"], tuple(sorted(r["labels"].items())))
                counters[key] = counters.get(key, 0) + r["value"]
            elif kind == "gauge":
                key = (r["name"], tuple(sorted(r["labels"].items())))
                gauges[key] = r["value"]
            elif kind == "span_end":
                d = spans.setdefault(r["name"],
                                     {"count": 0, "total_s": 0.0})
                d["count"] += 1
                d["total_s"] += r["dur"]
            elif kind == "event":
                events[r["name"]] = events.get(r["name"], 0) + 1
                if r["name"] == "ring_overflow":
                    s_dropped = max(s_dropped, int(
                        r["attrs"].get("dropped_total", 0) or 0))
        dropped_by_proc[proc] = max(dropped_by_proc.get(proc, 0),
                                    s_dropped)
    dropped = sum(dropped_by_proc.values())
    return {"sessions": sessions, "n_sessions": len(sessions),
            "n_records": sum(len(s["records"]) for s in sessions),
            "dropped_records": dropped,
            "counters": counters, "gauges": gauges,
            "spans": spans, "events": events}


def _prom_num(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _prom_escape(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double-quote and newline must be escaped or the series line is
    unparseable (a pack name or file path label can carry any of them)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_labels(lk) -> str:
    if not lk:
        return ""
    return "{" + ",".join(f'{k}="{_prom_escape(v)}"' for k, v in lk) + "}"


def prometheus_text() -> str:
    """Registry snapshot in the Prometheus text exposition format."""
    counters, gauges, hists = metrics.registry().items()
    out: List[str] = []
    seen = set()

    def header(name, mtype):
        if name in seen:
            return
        seen.add(name)
        t, h = metrics.METRICS.get(name, (mtype, ""))
        if h:
            out.append(f"# HELP {name} {h}")
        out.append(f"# TYPE {name} {t}")

    for (name, lk), v in sorted(counters.items()):
        header(name, "counter")
        out.append(f"{name}{_prom_labels(lk)} {_prom_num(v)}")
    for (name, lk), v in sorted(gauges.items()):
        header(name, "gauge")
        out.append(f"{name}{_prom_labels(lk)} {_prom_num(v)}")
    for (name, lk), (bounds, counts, total, count) in sorted(
            hists.items()):
        header(name, "histogram")

        def bucket_labels(le):
            return _prom_labels(sorted(dict(lk, le=le).items()))

        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            out.append(f"{name}_bucket"
                       f"{bucket_labels(_prom_num(float(b)))} {cum}")
        out.append(f"{name}_bucket{bucket_labels('+Inf')} {count}")
        out.append(f"{name}_sum{_prom_labels(lk)} {_prom_num(total)}")
        out.append(f"{name}_count{_prom_labels(lk)} {count}")
    return "\n".join(out) + "\n"
