"""Event/span recorder: the telemetry ring buffer.

The reference ships operational visibility as a CPU profiler tree
(``amgx_timer.h`` ``Profiler_tree``) plus free-form prints; here the
same markers additionally produce *typed records* — spans, events and
metric samples — appended to a bounded in-memory ring buffer that the
exporters (:mod:`amgx_tpu.telemetry.export`) serialise as JSONL or a
Prometheus snapshot.

Design constraints:

* **zero overhead when off** — every instrument's first action is a
  single attribute check (``_STATE.enabled``); nothing allocates,
  locks, or formats unless telemetry was enabled;
* **thread-safe** — hierarchy setup runs smoother setups on worker
  threads (utils/thread_manager.py), so appends take a lock and span
  nesting is tracked per thread;
* **bounded** — the ring drops the oldest records past
  ``telemetry_ring_size``; the global sequence number keeps growing so
  incremental flushes (:func:`amgx_tpu.telemetry.export.flush_jsonl`)
  stay consistent across wraps.

Record schema (version :data:`SCHEMA_VERSION`, validated by
``export.validate_record`` and ``scripts/telemetry_check.py``): every
record carries ``seq`` (monotonic int), ``t`` (``time.perf_counter``
seconds), ``tid`` (thread id), ``kind`` and ``name``.  Span records add
``sid``/``parent`` nesting ids (``span_end`` adds ``dur``); events add
``attrs``; metric samples add ``labels`` and ``value``.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

#: JSONL schema version — bump when record fields change shape.
SCHEMA_VERSION = 1

DEFAULT_RING_SIZE = 65536


class _State:
    __slots__ = ("enabled", "ring", "ring_size", "lock", "seq", "dropped")

    def __init__(self):
        self.enabled = False
        self.ring_size = DEFAULT_RING_SIZE
        self.ring = collections.deque(maxlen=self.ring_size)
        self.lock = threading.Lock()
        self.seq = 0
        #: records evicted by ring overflow (cumulative) — a truncated
        #: trace is detectable instead of silently undercounting
        self.dropped = 0


_STATE = _State()
_sid_counter = itertools.count(1)
_tls = threading.local()


def _span_stack() -> list:
    stk = getattr(_tls, "stack", None)
    if stk is None:
        stk = _tls.stack = []
    return stk


def is_enabled() -> bool:
    return _STATE.enabled


def enable(ring_size: Optional[int] = None):
    """Turn recording on (idempotent); optionally resize the ring.

    Also installs the jit cache-miss hook
    (:func:`amgx_tpu.utils.jaxcompat.install_compile_counter`) so
    recompiles show up as ``amgx_jit_compile_total``.
    """
    if ring_size is not None and int(ring_size) > 0 and \
            int(ring_size) != _STATE.ring_size:
        with _STATE.lock:
            old = list(_STATE.ring)
            _STATE.ring_size = int(ring_size)
            _STATE.ring = collections.deque(old, maxlen=_STATE.ring_size)
    _STATE.enabled = True
    from ..utils.jaxcompat import install_compile_counter
    install_compile_counter()


def disable():
    _STATE.enabled = False


def records() -> List[dict]:
    """Snapshot of the ring buffer contents (oldest first)."""
    with _STATE.lock:
        return list(_STATE.ring)


def clear():
    """Drop buffered records.  The sequence number keeps growing so
    incremental flush bookkeeping stays monotonic."""
    with _STATE.lock:
        _STATE.ring.clear()


def dropped_count() -> int:
    """Records evicted by ring overflow since process start (or the last
    :func:`reset_dropped`).  Exporters surface this so a truncated trace
    is detectable — the deque otherwise drops the oldest silently."""
    return _STATE.dropped


def reset_dropped():
    """Zero the overflow counter (test/bench isolation, via
    ``telemetry.reset``)."""
    with _STATE.lock:
        _STATE.dropped = 0


def _jsonable(v: Any):
    """Coerce a value into something ``json.dumps`` accepts: numpy
    scalars → python numbers, sequences element-wise, everything
    unknown → ``str``."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.ndarray):
            return [_jsonable(x) for x in v.tolist()]
    except Exception:
        pass
    return str(v)


def _append(rec: dict):
    with _STATE.lock:
        _STATE.seq += 1
        rec["seq"] = _STATE.seq
        if len(_STATE.ring) == _STATE.ring.maxlen:
            _STATE.dropped += 1       # the deque evicts its oldest record
        _STATE.ring.append(rec)


# ------------------------------------------------------------------- spans
def span_begin(name: str, attrs: Optional[dict] = None) -> Optional[int]:
    """Open a span; returns its id, or None when recording is off (the
    matching :func:`span_end` then no-ops).  Called by
    ``utils.profiler.ProfilerTree.scope`` so every existing
    ``cpu_profiler`` marker doubles as a telemetry span."""
    if not _STATE.enabled:
        return None
    sid = next(_sid_counter)
    stk = _span_stack()
    parent = stk[-1][0] if stk else None
    t = time.perf_counter()
    stk.append((sid, t))
    _append({"kind": "span_begin", "name": str(name), "sid": sid,
             "parent": parent, "t": t, "tid": threading.get_ident(),
             "attrs": _jsonable(attrs or {})})
    return sid


def span_end(sid: Optional[int], name: str):
    if sid is None:
        return
    stk = _span_stack()
    t1 = time.perf_counter()
    t0 = None
    # pop to the matching id — robust against a begin/end imbalance from
    # an instrument raising mid-span
    while stk:
        s, t = stk.pop()
        if s == sid:
            t0 = t
            break
    if not _STATE.enabled:
        return
    parent = stk[-1][0] if stk else None
    _append({"kind": "span_end", "name": str(name), "sid": sid,
             "parent": parent, "t": t1,
             "dur": (t1 - t0) if t0 is not None else 0.0,
             "tid": threading.get_ident()})


_profiler_scope = None


@contextlib.contextmanager
def span(name: str, **attrs):
    """Phase marker: context manager that aggregates into the CPU
    profiler tree (``utils/profiler.py`` — including the optional
    ``jax.profiler.TraceAnnotation`` forwarding) AND, when telemetry is
    enabled, records ``span_begin``/``span_end`` ring records with
    ``attrs``."""
    global _profiler_scope
    if _profiler_scope is None:
        # bound lazily: utils.profiler imports this module at load time
        from ..utils.profiler import profiler_tree
        _profiler_scope = profiler_tree
    with _profiler_scope().scope(str(name), _attrs=attrs or None) as entry:
        yield entry


# ------------------------------------------------------------------ events
def event(name: str, **attrs):
    """Point-in-time record (divergence, per-iteration residual, ...)."""
    if not _STATE.enabled:
        return
    stk = _span_stack()
    _append({"kind": "event", "name": str(name),
             "sid": stk[-1][0] if stk else None,
             "t": time.perf_counter(), "tid": threading.get_ident(),
             "attrs": _jsonable(attrs)})


def metric_sample(kind: str, name: str, value, labels: Dict[str, Any]):
    """Ring record of one metric instrument firing (kept alongside the
    aggregated registry so JSONL traces carry the raw samples)."""
    if not _STATE.enabled:
        return
    _append({"kind": kind, "name": str(name),
             "t": time.perf_counter(), "tid": threading.get_ident(),
             "labels": {str(k): _jsonable(v) for k, v in labels.items()},
             "value": _jsonable(value)})


# ----------------------------------------------------------------- capture
class Capture:
    """Scoped collector handed out by :func:`capture`: the records
    appended while the scope was active, plus small query helpers so
    tests and bench can assert on them."""

    def __init__(self):
        self.records: List[dict] = []
        #: True when the scope produced more records than the ring
        #: holds — the oldest were evicted and aggregates undercount
        self.truncated = False
        #: ring-overflow evictions that happened during the scope
        self.dropped = 0

    def kind(self, kind: str) -> List[dict]:
        return [r for r in self.records if r["kind"] == kind]

    def spans(self, name: Optional[str] = None) -> List[dict]:
        """Completed spans (``span_end`` records carry ``dur``)."""
        return [r for r in self.records if r["kind"] == "span_end"
                and (name is None or r["name"] == name)]

    def events(self, name: Optional[str] = None) -> List[dict]:
        return [r for r in self.records if r["kind"] == "event"
                and (name is None or r["name"] == name)]

    def metric_records(self, name: Optional[str] = None,
                       kind: Optional[str] = None) -> List[dict]:
        return [r for r in self.records
                if r["kind"] in ("counter", "gauge", "hist")
                and (kind is None or r["kind"] == kind)
                and (name is None or r["name"] == name)]

    def counter_totals(self, name: str,
                       label: Optional[str] = None) -> dict:
        """Summed counter increments, keyed by one label's value (or by
        the full sorted label tuple when ``label`` is None)."""
        out: Dict[Any, float] = {}
        for r in self.metric_records(name, kind="counter"):
            key = (r["labels"].get(label) if label is not None
                   else tuple(sorted(r["labels"].items())))
            out[key] = out.get(key, 0) + r["value"]
        return out

    def counter_total(self, name: str, **labels) -> float:
        tot = 0.0
        for r in self.metric_records(name, kind="counter"):
            if all(r["labels"].get(k) == _jsonable(v)
                   for k, v in labels.items()):
                tot += r["value"]
        return tot

    def gauge_last(self, name: str, **labels):
        val = None
        for r in self.metric_records(name, kind="gauge"):
            if all(r["labels"].get(k) == _jsonable(v)
                   for k, v in labels.items()):
                val = r["value"]
        return val

    def summary(self) -> dict:
        """Generic aggregate of the captured records — span totals,
        counter sums and last gauge values — for quick inspection
        (consumers wanting a bespoke shape, like bench's per-case
        packs/phases block, build it from the query helpers above)."""
        spans: Dict[str, dict] = {}
        for r in self.spans():
            s = spans.setdefault(r["name"], {"count": 0, "total_s": 0.0})
            s["count"] += 1
            s["total_s"] = round(s["total_s"] + r["dur"], 6)
        counters: Dict[str, float] = {}
        gauges: Dict[str, Any] = {}
        for r in self.metric_records():
            key = r["name"]
            if r["labels"]:
                key += "{" + ",".join(f"{k}={v}" for k, v in
                                      sorted(r["labels"].items())) + "}"
            if r["kind"] == "counter":
                counters[key] = counters.get(key, 0) + r["value"]
            elif r["kind"] == "gauge":
                gauges[key] = r["value"]
        return {"spans": spans, "counters": counters, "gauges": gauges}


@contextlib.contextmanager
def capture(ring_size: Optional[int] = None):
    """Scoped collection: enables telemetry for the duration (restoring
    the previous state on exit) and yields a :class:`Capture` whose
    ``records`` are those appended inside the scope.  A scope that
    outgrows the ring loses its OLDEST records to eviction — the
    collector then sets ``truncated`` so consumers know the aggregates
    undercount (size the ring via the argument when capturing large
    runs).  A ring resize requested here is scoped: the previous size
    is restored on exit."""
    prev = _STATE.enabled
    prev_size = _STATE.ring_size
    enable(ring_size)
    with _STATE.lock:
        seq0 = _STATE.seq
        dropped0 = _STATE.dropped
    cap = Capture()
    try:
        yield cap
    finally:
        with _STATE.lock:
            cap.records = [r for r in _STATE.ring if r["seq"] > seq0]
            produced = _STATE.seq - seq0
            cap.dropped = _STATE.dropped - dropped0
            if _STATE.ring_size != prev_size:
                _STATE.ring_size = prev_size
                _STATE.ring = collections.deque(_STATE.ring,
                                                maxlen=prev_size)
        cap.truncated = len(cap.records) < produced
        if not prev:
            _STATE.enabled = False
