"""Cross-process cumulative cache-efficacy counters.

The in-process caches that make warm starts cheap — the serve-layer
:class:`~amgx_tpu.serve.cache.SetupCache`, the
:class:`~amgx_tpu.amg.device_setup.DeviceSetupEngine` plan cache, the
persistent XLA compile cache and the AOT executable store — all keep
their hit/miss counters in process memory, so every restart reported a
fresh-looking cache even when the warm-start layer did its job.  This
module folds those counters into a small JSON state file
(``amgx_runstate.json`` next to the warm-start artifacts) and exposes
the CUMULATIVE view, which the telemetry meta header embeds (``cum``)
so ``bench_trend.py`` (and any trace reader) can show cache efficacy
across rounds, not just within one process.

Folding is delta-based: each :func:`fold` adds only the growth since
the previous fold of THIS process, so repeated flushes never
double-count.  The read-modify-write is best-effort across concurrent
processes (the state is observability, not correctness); writes are
atomic (tmp + rename) so readers never see a torn file.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from ..utils import fsio

STATE_BASENAME = "amgx_runstate.json"

_lock = threading.Lock()
_path: Optional[str] = None
#: counter values already folded into the file by this process
_folded: Dict[str, float] = {}


def configure(path: Optional[str]):
    """Point the state file at ``path`` (None disables)."""
    global _path
    with _lock:
        _path = os.path.abspath(path) if path else None


def configure_default(dirpath: str):
    """Adopt ``dirpath/amgx_runstate.json`` unless explicitly
    configured already — the warm-start knobs (``aot_store_dir`` /
    ``compile_cache_dir``) call this so the state rides next to the
    artifacts whose efficacy it records."""
    global _path
    if not dirpath:
        return
    with _lock:
        if _path is None:
            _path = os.path.join(os.path.abspath(dirpath),
                                 STATE_BASENAME)


def state_path() -> Optional[str]:
    return _path


def reset():
    """Forget the configured path and fold history (test isolation;
    the file on disk is untouched)."""
    global _path
    with _lock:
        _path = None
        _folded.clear()


def _live_counters() -> Dict[str, float]:
    """Current process totals of every tracked cache, gathered from the
    live objects (NOT the telemetry registry — these sources count even
    with telemetry off)."""
    out: Dict[str, float] = {}
    try:
        from ..utils import jaxcompat
        cc = jaxcompat.compile_cache_stats()
        out["compile_cache_hits"] = cc["hits"]
        out["compile_cache_misses"] = cc["misses"]
    except Exception:
        pass
    try:
        from ..serve import aot
        st = aot.store_stats()
        if st:
            out["aot_loads"] = st["loads"]
            out["aot_saves"] = st["saves"]
            out["aot_misses"] = st["misses"]
            out["aot_fallbacks"] = st["fallbacks"]
    except Exception:
        pass
    try:
        from ..amg.device_setup import engine_stats
        st = engine_stats()
        if st:
            out["device_plan_hits"] = st["hits"]
            out["device_plan_misses"] = st["misses"]
            out["device_plan_fallbacks"] = st["fallbacks"]
    except Exception:
        pass
    try:
        from ..serve.cache import cache_totals
        st = cache_totals()
        out["serve_cache_hits"] = st["hits"]
        out["serve_cache_misses"] = st["misses"]
        out["serve_cache_evictions"] = st["evictions"]
    except Exception:
        pass
    return out


def fold() -> Optional[dict]:
    """Fold this process's counter growth into the state file and
    return the cumulative state (``{"counters": {...}, "updated": t,
    "folds": n}``), or None when unconfigured.  Never raises."""
    with _lock:
        path = _path
        if path is None:
            return None
        live = _live_counters()
        delta = {k: v - _folded.get(k, 0) for k, v in live.items()
                 if v - _folded.get(k, 0)}
        try:
            state = _read(path)
            if delta:
                c = state.setdefault("counters", {})
                for k, v in delta.items():
                    c[k] = c.get(k, 0) + v
                state["updated"] = time.time()
                state["folds"] = int(state.get("folds", 0)) + 1
                _write(path, state)
            _folded.update(live)
            return state
        except Exception:
            return None


def cumulative() -> Optional[dict]:
    """The state file's current content without folding (readers)."""
    with _lock:
        if _path is None:
            return None
        try:
            return _read(_path)
        except Exception:
            return None


def _read(path: str) -> dict:
    try:
        with open(path) as f:
            state = json.load(f)
        if not isinstance(state, dict):
            state = {}
    except (OSError, ValueError):
        state = {}
    state.setdefault("counters", {})
    return state


def _write(path: str, state: dict):
    """Raises ``OSError`` on failure so :func:`fold` does NOT mark the
    delta as persisted — it retries the same growth next fold."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.atomic_write(path,
                      json.dumps(state, sort_keys=True).encode("utf-8"))
