"""Device-time attribution: the profiler-trace → scope correlator.

Host spans measure dispatch under JAX's async execution; this module
answers where the TPU actually spends a cycle.  It parses a
``jax.profiler`` chrome trace (shared plumbing:
:mod:`amgx_tpu.telemetry.proftrace`), joins the XLA device-op slices
back to the ``jax.named_scope`` taxonomy
(:mod:`amgx_tpu.telemetry.scopes`), and produces a **device-time cycle
anatomy**: per-level pre/post-smooth + restrict/prolong seconds, the
coarse solve, per-pack SpMV device time with *measured* bandwidth
(cost-model bytes ÷ measured device seconds) next to the modelled
roofline, per-smoother and per-Krylov-stage splits, and the
halo-exchange share.

The anatomy is emitted as a schema-validated ``device_anatomy`` event
(``measured`` provenance bool, like PR 16's ``dist_overlap``) and as
``amgx_device_time_seconds_total{scope}`` counters.  Every entry point
degrades to a ``measured=False`` stub when the trace carries no scoped
device ops (CPU runs, profiler plugin absent) — host-side file parsing
only, no profiler dependency.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

from . import proftrace, scopes

_LEVEL_RE = re.compile(r"\Aamgx/cycle/level(\d+)/([a-z0-9_]+)\Z")

#: anatomy sections keyed by scope-name prefix
_AREA_PREFIX = {a: f"amgx/{a}/" for a in scopes.AREAS}


def _round_s(us: float) -> float:
    return round(us * 1e-6, 9)


def measure_anatomy(trace: "str | dict | Iterable[dict]",
                    pack_bytes: Optional[Dict[str, int]] = None,
                    pack_dispatches: Optional[Dict[str, int]] = None,
                    peak_gbs: Optional[float] = None) -> dict:
    """The device-time cycle anatomy of one profiler capture.

    ``trace``: a path (file or profiler logdir), a loaded chrome-trace
    dict, or an iterable of trace events.  ``pack_bytes`` /
    ``pack_dispatches`` (optional, from :func:`pack_stats`) map SpMV
    pack names to modelled bytes-per-apply and traced dispatch counts;
    when both cover a pack the anatomy adds measured GB/s and the
    roofline fraction next to its device seconds.

    ALWAYS returns a dict; ``measured`` is True only when at least one
    device slice carried a contract scope.  Per-scope seconds are the
    per-device **union** of that scope's slice intervals (overlapping
    levels / parallel tids do not double count), summed across devices;
    ``total_device_s`` is the union of every slice the same way, so
    attributed + unattributed ≡ total.
    """
    if peak_gbs is None:
        from .costmodel import HBM_PEAK_GBS
        peak_gbs = HBM_PEAK_GBS
    events = proftrace.trace_events(trace)

    all_iv: Dict[object, List[tuple]] = {}        # pid -> intervals
    scoped_iv: Dict[object, List[tuple]] = {}     # pid -> intervals
    by_scope: Dict[Tuple[object, str], List[tuple]] = {}
    n_slices = 0
    n_scoped = 0
    for ev in proftrace.complete_slices(events):
        n_slices += 1
        pid = ev.get("pid", 0)
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
        all_iv.setdefault(pid, []).append(iv)
        found = scopes.scopes_in_event(ev)
        if not found:
            continue
        n_scoped += 1
        scoped_iv.setdefault(pid, []).append(iv)
        for s in found:
            by_scope.setdefault((pid, s), []).append(iv)

    total_us = sum(proftrace.union_len(iv) for iv in all_iv.values())
    attrib_us = sum(proftrace.union_len(iv) for iv in scoped_iv.values())
    scope_us: Dict[str, float] = {}
    for (_pid, s), iv in by_scope.items():
        scope_us[s] = scope_us.get(s, 0.0) + proftrace.union_len(iv)

    # ---- per-level cycle anatomy (union across a level's components,
    # ---- so a level's total is honest even if components overlap) ----
    levels: Dict[str, dict] = {}
    level_iv: Dict[Tuple[object, str], List[tuple]] = {}
    for (pid, s), iv in by_scope.items():
        m = _LEVEL_RE.match(s)
        if m:
            level_iv.setdefault((pid, m.group(1)), []).extend(iv)
    for s, us in scope_us.items():
        m = _LEVEL_RE.match(s)
        if m:
            levels.setdefault(m.group(1), {})[m.group(2)] = _round_s(us)
    for (_pid, lvl), iv in level_iv.items():
        d = levels.setdefault(lvl, {})
        d["total_s"] = round(d.get("total_s", 0.0)
                             + _round_s(proftrace.union_len(iv)), 9)
    coarse_s = _round_s(scope_us.get("amgx/cycle/coarse_solve", 0.0))

    # ---- per-pack SpMV device time + measured bandwidth -------------
    pb = {scopes.sanitize(k): v for k, v in (pack_bytes or {}).items()
          if v}
    pd = {scopes.sanitize(k): v
          for k, v in (pack_dispatches or {}).items() if v}
    spmv: Dict[str, dict] = {}
    for s, us in scope_us.items():
        if not s.startswith(_AREA_PREFIX["spmv"]):
            continue
        pack = s[len(_AREA_PREFIX["spmv"]):]
        d: dict = {"device_s": _round_s(us)}
        # op_cost events label the base pack kind ("dia", "dia/block"),
        # dispatch counters the refined label ("dia/slices",
        # "dia/block_kernel") — join on the longest base-kind key that
        # prefixes the dispatch label at a segment boundary
        byt = pb.get(pack)
        if not byt:
            for k in sorted(pb, key=len, reverse=True):
                if pack.startswith(k) and (len(pack) == len(k)
                                           or pack[len(k)] in "/_"):
                    byt = pb[k]
                    break
        n = pd.get(pack)
        if byt and n and us > 0:
            d["bytes_per_apply"] = int(byt)
            d["dispatches"] = int(n)
            gbs = (float(byt) * float(n)) / (us * 1e-6) / 1e9
            d["measured_gbs"] = round(gbs, 2)
            d["roofline_fraction"] = round(gbs / peak_gbs, 6)
        spmv[pack] = d

    def _area(area: str) -> Dict[str, float]:
        pre = _AREA_PREFIX[area]
        return {s[len(pre):]: _round_s(us)
                for s, us in scope_us.items() if s.startswith(pre)}

    return {
        "measured": n_scoped > 0,
        "scope_version": scopes.SCOPE_VERSION,
        "total_device_s": _round_s(total_us),
        "attributed_s": _round_s(attrib_us),
        "unattributed_s": _round_s(max(total_us - attrib_us, 0.0)),
        "n_devices": len(all_iv),
        "n_slices": n_slices,
        "n_scoped_slices": n_scoped,
        "scopes": {s: _round_s(us)
                   for s, us in sorted(scope_us.items())},
        "levels": {k: levels[k] for k in sorted(levels, key=int)},
        "coarse_s": coarse_s,
        "spmv": {k: spmv[k] for k in sorted(spmv)},
        "smoothers": _area("smoother"),
        "krylov": _area("krylov"),
        "dist": _area("dist"),
        "hbm_peak_gbs": float(peak_gbs),
    }


def pack_stats(records: Iterable[dict]) -> Tuple[Dict[str, int],
                                                 Dict[str, int]]:
    """(pack → modelled bytes/apply, pack → dispatch count) from
    recorder ring records: the ``op_cost`` events' cost descriptors and
    the ``amgx_spmv_dispatch_total`` counter samples.  The biggest
    descriptor per pack kind wins (the fine operator dominates the
    bandwidth story)."""
    pack_bytes: Dict[str, int] = {}
    pack_disp: Dict[str, int] = {}
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "op_cost":
            a = r.get("attrs") or {}
            pack, byt = a.get("pack"), a.get("bytes_per_apply")
            if pack and isinstance(byt, (int, float)) and byt > 0:
                pack_bytes[str(pack)] = max(
                    pack_bytes.get(str(pack), 0), int(byt))
        elif r.get("kind") == "counter" and \
                r.get("name") == "amgx_spmv_dispatch_total":
            pack = (r.get("labels") or {}).get("pack")
            if pack:
                pack_disp[str(pack)] = pack_disp.get(str(pack), 0) \
                    + int(r.get("value") or 0)
    return pack_bytes, pack_disp


def capture_anatomy(trace, records: Optional[Iterable[dict]] = None
                    ) -> dict:
    """:func:`measure_anatomy` fed with pack bytes/dispatch counts from
    a recorder ring snapshot (default: the live ring)."""
    if records is None:
        from . import recorder
        records = recorder.records()
    pb, pd = pack_stats(records)
    return measure_anatomy(trace, pack_bytes=pb, pack_dispatches=pd)


def emit(anatomy: dict):
    """Record the anatomy: one schema-validated ``device_anatomy``
    event plus ``amgx_device_time_seconds_total{scope}`` counter
    increments (one per attributed scope).  No-op when telemetry is
    off."""
    from . import metrics, recorder
    if not recorder.is_enabled():
        return
    for s, sec in (anatomy.get("scopes") or {}).items():
        if sec:
            metrics.counter_inc("amgx_device_time_seconds_total",
                                float(sec), scope=s)
    recorder.event("device_anatomy", **anatomy)


def top_scopes(anatomy: dict, n: int = 2) -> List[Tuple[str, float]]:
    """The ``n`` largest (scope, seconds) pairs — what bench_trend
    prints per round."""
    sc = anatomy.get("scopes") or {}
    return sorted(((k, float(v)) for k, v in sc.items()),
                  key=lambda kv: -kv[1])[:n]
