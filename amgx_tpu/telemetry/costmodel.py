"""Static per-op cost descriptors: bytes moved and FLOPs per apply.

The ROADMAP north star is "as fast as the hardware allows" — which is a
statement about bytes and FLOPs, not wall seconds.  This module
translates every SpMV pack the dispatcher can choose
(:func:`amgx_tpu.core.matrix.pack_kind`: dia / dia3 Galerkin
composition / tile-DIA shift / windowed one-hot / binned sliced-ELL /
ELL gather / CSR segment-sum / dense / sharded) into a hardware-terms
descriptor:

* ``bytes_per_apply`` — HBM traffic of one ``y = A·x`` (value planes +
  index planes + the x/y vectors), using the same per-layout formulas
  ``bench.py`` uses for its effective-GB/s numbers;
* ``flops_per_apply`` — ``2·nnz`` useful flops (pad slots multiply
  zeros: bandwidth waste, not compute);
* ``padding_waste`` — stored SLOTS ÷ nnz (1.0 = no padding; the
  binned-ELL plan's padding budget is exactly a bound on this);
* for sharded packs additionally ``halo_bytes_per_apply`` — the ICI
  wire bytes of one halo exchange (padded send buffers, every ring).

Pair a descriptor with a recorded span duration to get achieved
bandwidth and roofline fraction (:func:`achieved_gbs`,
:func:`roofline_fraction`) — the numbers every perf PR is judged with.

Everything here is host-side arithmetic on pack SHAPES (no device
compute, no transfers), so it is safe to call at setup time under
telemetry.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

#: public TPU v5e HBM roofline (16 GB @ 819 GB/s) — bench.py's number
HBM_PEAK_GBS = 819.0
#: per-link ICI bandwidth class of a v5e (one direction, GB/s) — used
#: for halo-exchange roofline fractions; override per topology
ICI_PEAK_GBS = 186.0
#: launch+sync latency of one small all-reduce on an ICI ring — the
#: per-collective floor that dominates few-scalar psums (dot products);
#: what the communication-avoiding Krylov variants amortise
PSUM_LATENCY_S = 5e-6

_INDEX_BYTES = 4          # int32 column/row ids


def _vec_bytes(n_rows, n_cols, itemsize):
    """x read once + y written once (gather-free layouts stream them)."""
    return (n_rows + n_cols) * itemsize


def spmv_cost(Ad, nnz: Optional[int] = None) -> dict:
    """Cost descriptor of one ``y = A·x`` on device pack ``Ad``.

    ``nnz``: the true stored-nonzero count when the caller knows it
    (host Matrix levels do); estimated from the padded slot count
    otherwise (``estimated=True`` in the result then flags every
    nnz-derived field as an upper bound).
    """
    from ..core.matrix import pack_kind, padded_entries
    fmt = getattr(Ad, "fmt", "?")
    pack = pack_kind(Ad)
    itemsize = np.dtype(Ad.dtype).itemsize
    slots = padded_entries(Ad)
    estimated = nnz is None
    if nnz is None:
        nnz = slots
    bdim = int(getattr(Ad, "block_dim", 1) or 1)
    out = {"pack": pack, "fmt": fmt, "dtype": str(np.dtype(Ad.dtype)),
           "itemsize": itemsize, "estimated": estimated,
           "block_dim": bdim,
           "nnz": None if nnz is None else int(nnz),
           "padded_entries": None if slots is None else int(slots)}
    if fmt == "op" or slots is None:
        out.update(bytes_per_apply=None, flops_per_apply=None,
                   padding_waste=None)
        return out
    out["flops_per_apply"] = 2 * int(nnz)
    out["padding_waste"] = round(slots / max(int(nnz), 1), 4)

    if fmt == "dia":
        # block-DIA: nd offsets × (n, b, b) value planes + the x/y
        # vectors — zero index bytes either way
        n = Ad.n_rows
        byt = (Ad.ell_width * bdim * bdim + 2 * bdim) * n * itemsize
    elif fmt == "dia3":
        # Galerkin composition R·(A·(P·x)): each factor's diagonal rows
        # stream once, plus the two intermediates and x/y
        nd3 = (len(Ad.P.dia_offsets) + len(Ad.A.dia_offsets)
               + len(Ad.R.dia_offsets) + 6)
        byt = nd3 * Ad.n_rows * itemsize
    elif fmt == "dense":
        byt = (Ad.n_rows * Ad.n_cols) * itemsize \
            + _vec_bytes(Ad.n_rows, Ad.n_cols, itemsize)
    elif fmt == "sharded-ell":
        return _sharded_cost(Ad, out, itemsize, slots)
    elif fmt == "ell" and getattr(Ad, "sh_vals", None) is not None:
        # tile-DIA shift kernel: class-value rows + per-class x windows
        # + y; no per-entry column data at all
        T, n_tiles, Dpad, _pad, _L = Ad.sh_dims
        byt = (n_tiles * Dpad * (T + (T // 128 + 1) * 128)
               + Ad.n_rows) * itemsize
    elif fmt == "ell" and getattr(Ad, "win_codes", None) is not None:
        # windowed one-hot kernel: int16 codes + values + block ids +
        # the VMEM-staged x tiles + y
        K, T = Ad.ell_width, Ad.win_tile
        n_pad = Ad.win_codes.size // K if Ad.win_codes.ndim == 1 \
            else Ad.win_codes.shape[0]
        byt = (n_pad * K * (itemsize + Ad.win_codes.dtype.itemsize)
               + Ad.win_blocks.size * _INDEX_BYTES
               + _vec_bytes(Ad.n_rows, Ad.n_cols, itemsize))
    elif getattr(Ad, "bn_codes", None) is not None:
        # binned sliced-ELL kernel: codes+vals planes stream once, one
        # (Sb, 128) x segment per chunk (× b component sub-lanes), y
        # once.  Block-NATIVE planes carry ONE int32 code per b×b block
        # — index bytes are per BLOCK, not per scalar slot (the
        # satellite fix: the scalar-expansion pack honestly moves b²×
        # the index bytes, and the descriptor must distinguish them)
        from ..ops.pallas_csr import bn_block_dim
        bb = bn_block_dim(Ad.bn_dims)
        L = int(Ad.bn_codes.size)
        C = int(Ad.bn_dims[0])
        Sb = int(Ad.bn_dims[4])
        byt = L * _INDEX_BYTES + L * bb * bb * itemsize \
            + C * Sb * 128 * bb * itemsize \
            + Ad.n_rows * bb * itemsize
    elif fmt == "ell":
        # gather form: values + int32 columns + x/y
        byt = slots * itemsize \
            + Ad.n_rows * Ad.ell_width * _INDEX_BYTES \
            + _vec_bytes(Ad.n, Ad.n_cols * Ad.block_dim, itemsize)
    else:
        # CSR segment-sum: vals + int32 cols/row_ids + x/y
        byt = slots * itemsize \
            + (slots // max(Ad.block_dim ** 2, 1)) * 2 * _INDEX_BYTES \
            + _vec_bytes(Ad.n, Ad.n_cols * Ad.block_dim, itemsize)
    out["bytes_per_apply"] = int(byt)
    return out


def _sharded_cost(A, out: dict, itemsize: int, slots: int) -> dict:
    """Sharded-ELL descriptor: per-shard local streaming + the halo
    exchange's ICI wire bytes (padded send buffers — what actually
    crosses the links, not just the useful entries)."""
    P = A.n_parts
    # local interior/boundary compute: per-shard ELL gather (or the
    # windowed kernel — same value/index planes) over [local | halo]
    byt = slots * itemsize \
        + P * A.n_loc * A.ell_width * _INDEX_BYTES \
        + 2 * P * A.n_loc * A.block_dim * itemsize
    out["bytes_per_apply"] = int(byt)
    out["halo_bytes_per_apply"] = int(halo_wire_bytes(A, ring=1))
    out["halo_entries_per_apply"] = int(halo_entries(A, ring=1))
    out["n_parts"] = P
    return out


# ----------------------------------------------------------- halo costs
def _ring_arrays(A, ring: int):
    if ring == 1:
        return A.send_idx, A.halo_src, A.dists
    return A.send_idx2, A.halo_src2, A.dists2


def halo_wire_bytes(A, ring: int = 1) -> int:
    """ICI bytes one ring-``ring`` exchange moves, mesh-wide: every
    shard sends its full PADDED (B,) buffer once per ppermute distance
    (or P−1 times under the all_gather fallback) — padding crosses the
    wire, which is why this is the counter the MULTICHIP bench series
    watches."""
    send_idx, _, dists = _ring_arrays(A, ring)
    P = A.n_parts
    if P == 1:
        return 0
    from ..distributed.matrix import uses_all_gather
    B = send_idx.shape[1]
    itemsize = np.dtype(A.dtype).itemsize * max(A.block_dim, 1)
    hops = (P - 1) if uses_all_gather(dists, P) else len(dists)
    return P * hops * B * itemsize


def halo_entries(A, ring: int = 1) -> int:
    """USEFUL halo values gathered per exchange (unpadded, mesh-wide):
    the analytic boundary size of the partition when the pack carries
    per-rank counts, else the padded H upper bound."""
    counts = A.halo_counts if ring == 1 else A.halo_counts2
    if counts is not None:
        return int(sum(counts))
    _, halo_src, _ = _ring_arrays(A, ring)
    return A.n_parts * halo_src.shape[1]


# ------------------------------------------------- distributed overlap
def dist_overlap(Ad, nnz: Optional[int] = None,
                 level: Optional[int] = None) -> Optional[dict]:
    """Static interior-vs-halo audit of one sharded level — the
    ``dist_overlap`` cost-model event.

    Models what the interior/boundary split (``multiply.cu:75-196``)
    can actually hide: per-device interior-SpMV seconds (local bytes ÷
    HBM peak, shards stream concurrently) vs per-device halo seconds
    (this shard's wire bytes ÷ ICI peak).  ``overlap_fraction`` is the
    fraction of the halo exchange hideable under the interior compute
    (1.0 = fully hidden); ``halo_bound`` flags levels where the halo
    DOMINATES even with perfect overlap — exactly the levels the
    agglomeration threshold (``dist_agglomerate_min_rows``) exists for.
    Host-side shape arithmetic only; None for non-sharded packs.
    """
    if getattr(Ad, "fmt", "") != "sharded-ell":
        return None
    from ..distributed.agglomerate import active_parts
    c = spmv_cost(Ad, nnz=nnz)
    P = int(Ad.n_parts)
    offs = np.asarray(Ad.offsets) if Ad.offsets is not None else None
    active = active_parts(offs) if offs is not None else P
    active = max(active, 1)
    rows = int(offs[-1]) if offs is not None else P * Ad.n_loc
    local_bytes = int(c.get("bytes_per_apply") or 0)
    wire = int(c.get("halo_bytes_per_apply") or 0)
    # per-device: shards run concurrently, so one device's time is its
    # 1/P share of the mesh-wide byte totals
    est_interior_s = local_bytes / P / (HBM_PEAK_GBS * 1e9)
    est_halo_s = wire / P / (ICI_PEAK_GBS * 1e9)
    if est_halo_s <= 0:
        overlap = 1.0
    else:
        overlap = min(est_interior_s / est_halo_s, 1.0)
    out = {
        "n_parts": P, "active_parts": active,
        "rows": rows, "rows_per_part": rows // active,
        "interior_bytes": local_bytes, "halo_wire_bytes": wire,
        "halo_local_ratio": (round(wire / local_bytes, 4)
                             if local_bytes else None),
        "est_interior_s": round(est_interior_s, 9),
        "est_halo_s": round(est_halo_s, 9),
        "overlap_fraction": round(overlap, 4),
        "halo_bound": bool(est_halo_s > est_interior_s),
        # static model by default; telemetry/overlap.py flips this to
        # True when a profiler trace supplied a measured fraction
        "measured": False,
    }
    if level is not None:
        out["level"] = int(level)
    return out


def krylov_reduction_cost(Ad, coll_per_iter: int) -> Optional[dict]:
    """Modelled per-iteration cost split of a sharded Krylov solve:
    interior-SpMV seconds vs dot-product all-reduce seconds.

    A few-scalar all-reduce on an ICI ring is latency-bound — its cost
    is ~:data:`PSUM_LATENCY_S` per collective regardless of payload —
    so ``est_reduction_s`` scales with the reduction COUNT, which is
    exactly what the communication-avoiding variants shrink.  None for
    non-sharded packs (single-device reductions are register traffic).
    """
    if getattr(Ad, "fmt", "") != "sharded-ell":
        return None
    c = spmv_cost(Ad)
    P = int(Ad.n_parts)
    local_bytes = int(c.get("bytes_per_apply") or 0)
    est_spmv_s = local_bytes / P / (HBM_PEAK_GBS * 1e9)
    est_reduction_s = float(coll_per_iter) * PSUM_LATENCY_S
    return {
        "n_parts": P,
        "est_spmv_s": round(est_spmv_s, 9),
        "est_reduction_s": round(est_reduction_s, 9),
        "reduction_bound": bool(est_reduction_s > est_spmv_s),
    }


# ------------------------------------------------------------- rollups
def hierarchy_cost(levels_costs) -> dict:
    """Roll per-level descriptors (one :func:`spmv_cost` dict per
    level, fine→coarse) into hierarchy totals: one V-cycle visits every
    level's operator, so the totals bound the per-cycle traffic."""
    byt = [c.get("bytes_per_apply") for c in levels_costs]
    flp = [c.get("flops_per_apply") for c in levels_costs]
    nnz = [c.get("nnz") for c in levels_costs]
    slots = [c.get("padded_entries") for c in levels_costs]
    tot_nnz = sum(z for z in nnz if z)
    tot_slots = sum(s for s in slots if s)
    return {
        "levels": list(levels_costs),
        "total_bytes_per_cycle": sum(b for b in byt if b),
        "total_flops_per_cycle": sum(f for f in flp if f),
        "padding_waste": round(tot_slots / max(tot_nnz, 1), 4),
        "halo_bytes_per_cycle": sum(
            c.get("halo_bytes_per_apply", 0) or 0 for c in levels_costs),
    }


# ---------------------------------------------------- achieved vs peak
def achieved_gbs(bytes_moved: float, duration_s: float) -> float:
    """Achieved bandwidth of ``bytes_moved`` in ``duration_s``."""
    if not duration_s or duration_s <= 0:
        return 0.0
    return bytes_moved / duration_s / 1e9


def roofline_fraction(gbs: float, peak_gbs: float = HBM_PEAK_GBS
                      ) -> float:
    """Fraction of a bandwidth roofline actually achieved."""
    return gbs / peak_gbs if peak_gbs > 0 else 0.0
