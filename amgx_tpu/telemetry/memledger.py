"""HBM ledger: device-memory ownership attribution and OOM post-mortems.

The reference runtime is substantially a memory-management layer —
pinned/device pools with explicit ownership
(``global_thread_handle.h:58-197``) — because on an accelerator the
question "who owns these bytes" decides whether the next setup fits.
This module is the bytes-side twin of :mod:`amgx_tpu.telemetry.deviceprof`
(which attributes device *time*): a process-wide ledger joining three
sources:

* an **ownership registry** — allocation sites register their device
  trees under a versioned ``amgx/<owner>/<name>`` taxonomy mirroring
  :mod:`amgx_tpu.telemetry.scopes` (hierarchy level packs, P/R transfer
  packs, smoother state, coarse LU factors, serve ``SetupCache``
  entries, AOT in-memory cache, distributed halo packs, solve-loop
  bindings).  Entries hold **weak references** so the ledger never pins
  memory; :func:`release` drops an entry, and a dead weakref simply
  stops counting;
* a **live-array census** — ``jax.live_arrays()`` joined to owners by
  buffer identity, deduplicated by ``id()`` so shallow views
  (``precision_view`` / ``placement_view`` / lane replicas sharing one
  pack) never double-count;
* the **backend's own truth** — ``device.memory_stats()``
  ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` where the
  platform provides it, honest ``measured=false`` degradation where it
  does not (the deviceprof precedent; CPU backends report no stats).

The honesty invariant ``accounted + unaccounted ≡ bytes_in_use`` holds
per device in both modes (the stub defines ``bytes_in_use`` as the
census total) and is test-asserted.

On RESOURCE_EXHAUSTED — real, or injected through the ``fault_inject``
point ``oom`` — the solver/serve layers call :func:`emit_postmortem`,
producing a schema-validated ``oom_postmortem`` event: ledger snapshot,
top-k owners, recent headroom history, and concrete eviction
suggestions.

Zero-overhead contract: with the ``memledger`` knob off (default),
every entry point returns after one attribute check — no tree walks, no
``live_arrays`` calls, no retraces.
"""
from __future__ import annotations

import collections
import re
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

#: version of the ownership taxonomy carried by every ledger event
LEDGER_VERSION = 1

#: the taxonomy's owner areas.  Order matters: census claims resolve in
#: this order, and the AGGREGATE owners (whole-tree registrations that
#: overlap the specific packs — serve cache entries, solve bindings)
#: only claim buffers no specific owner claimed, so a hierarchy pack
#: inside a cached session is charged to ``hierarchy/…`` once.
OWNERS = ("hierarchy", "transfer", "smoother", "coarse", "matrix",
          "dist", "aot", "serve", "solve")

#: owners whose registrations are whole-tree aggregates of buffers that
#: specific owners may also claim
AGGREGATE_OWNERS = frozenset({"aot", "serve", "solve"})

_SEG = r"[a-z0-9_]+"
#: full-match check of a finished owner name
OWNER_RE = re.compile(rf"amgx(?:/{_SEG})+\Z")

#: samples kept in the headroom history ring (OOM post-mortems replay it)
HISTORY_LEN = 64


def sanitize(name: str) -> str:
    """Map any label into the owner segment alphabet (the scopes.py
    rule): lowercase, everything outside ``[a-z0-9_/]`` becomes ``_``."""
    return re.sub(r"[^a-z0-9_/]", "_", str(name).lower())


def owner_name(owner: str, name: str) -> str:
    """The contract name ``amgx/<owner>/<sanitised name>``; raises
    ``ValueError`` on an unknown owner or an unsanitisable name."""
    if owner not in OWNERS:
        raise ValueError(f"unknown ledger owner {owner!r} "
                         f"(contract v{LEDGER_VERSION} owners: {OWNERS})")
    s = f"amgx/{owner}/{sanitize(name)}"
    if not OWNER_RE.match(s):
        raise ValueError(f"owner name {s!r} violates the "
                         f"amgx/<owner>/<name> contract")
    return s


def validate(name: str) -> bool:
    """True iff ``name`` is a well-formed owner name with a known
    owner area."""
    if not isinstance(name, str) or not OWNER_RE.match(name):
        return False
    parts = name.split("/")
    return len(parts) >= 3 and parts[1] in OWNERS


# --------------------------------------------------------------- state
class _Entry:
    __slots__ = ("name", "refs", "pins", "host_bytes")

    def __init__(self, name: str):
        self.name = name
        #: weakrefs to the registered jax arrays (dead refs stop counting)
        self.refs: List[weakref.ref] = []
        #: strong (id, nbytes) fallbacks for leaves that refuse weakref —
        #: kept as plain ints so nothing is pinned
        self.pins: List[Tuple[int, int]] = []
        #: host-side bytes (AOT serialized cache) — listed in the owners
        #: table, excluded from the device invariant
        self.host_bytes = 0


class _State:
    __slots__ = ("enabled", "lock", "entries", "token_counter",
                 "sample_s", "last_sample", "history")

    def __init__(self):
        self.enabled = False
        self.lock = threading.Lock()
        self.entries: Dict[int, _Entry] = {}
        self.token_counter = 0
        self.sample_s = 0.5
        self.last_sample = 0.0
        #: recent (t, device -> headroom/bytes_in_use) samples
        self.history: collections.deque = collections.deque(
            maxlen=HISTORY_LEN)


_STATE = _State()


def is_enabled() -> bool:
    return _STATE.enabled


def enable(sample_s: Optional[float] = None):
    """Turn the ledger on (idempotent); also enables the recorder so
    ledger events land in the ring (the setup_profile precedent)."""
    if sample_s is not None and float(sample_s) >= 0:
        _STATE.sample_s = float(sample_s)
    _STATE.enabled = True
    from . import recorder
    recorder.enable()


def disable():
    _STATE.enabled = False


def reset():
    """Drop all registrations and history (test isolation, via
    ``telemetry.reset``)."""
    with _STATE.lock:
        _STATE.entries.clear()
        _STATE.history.clear()
        _STATE.last_sample = 0.0
    _STATE.enabled = False


def entry_count() -> int:
    """Registered (un-released) entries — the register/release balance
    tests assert this returns to baseline across setup→teardown."""
    with _STATE.lock:
        return len(_STATE.entries)


# ------------------------------------------------------------ registry
def _array_leaves(tree) -> list:
    import jax
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            out.append(leaf)
    return out


def register(name: str, tree) -> Optional[int]:
    """Register a device pytree under ``name`` (a :func:`owner_name`
    contract string).  Returns an opaque token for :func:`release`, or
    None when the ledger is off (the zero-overhead path — the tree is
    not even traversed).

    The registry holds weakrefs only: registration never extends a
    buffer's lifetime, and a released/garbage-collected pack silently
    stops counting."""
    if not _STATE.enabled:
        return None
    if not validate(name):
        raise ValueError(f"invalid ledger owner name {name!r}")
    e = _Entry(name)
    for leaf in _array_leaves(tree):
        try:
            e.refs.append(weakref.ref(leaf))
        except TypeError:
            # leaf type without weakref support: fall back to an id pin
            # (joined against live_arrays, so a recycled id that is not
            # actually live never counts)
            try:
                e.pins.append((id(leaf), int(leaf.nbytes)))
            except Exception:
                pass
    with _STATE.lock:
        _STATE.token_counter += 1
        tok = _STATE.token_counter
        _STATE.entries[tok] = e
    return tok


def register_bytes(name: str, nbytes: int) -> Optional[int]:
    """Register a host-byte owner (AOT serialized cache): shown in the
    owners table, excluded from the device honesty invariant."""
    if not _STATE.enabled:
        return None
    if not validate(name):
        raise ValueError(f"invalid ledger owner name {name!r}")
    e = _Entry(name)
    e.host_bytes = max(int(nbytes), 0)
    with _STATE.lock:
        _STATE.token_counter += 1
        tok = _STATE.token_counter
        _STATE.entries[tok] = e
    return tok


def release(token: Optional[int]):
    """Drop one registration (None tokens — from a disabled-ledger
    register — are accepted and ignored)."""
    if token is None:
        return
    with _STATE.lock:
        _STATE.entries.pop(token, None)


# -------------------------------------------------------------- census
def _shard_bytes(arr) -> List[Tuple[str, int]]:
    """(device label, bytes) pairs of one array — per-shard for sharded
    arrays, whole-array on its single device otherwise."""
    try:
        shards = arr.addressable_shards
        out = []
        for s in shards:
            d = s.data
            out.append((str(s.device), int(d.nbytes)))
        if out:
            return out
    except Exception:
        pass
    try:
        devs = list(arr.devices())
        dev = str(devs[0]) if devs else "?"
        return [(dev, int(arr.nbytes))]
    except Exception:
        return []


def _claims() -> Dict[int, str]:
    """Buffer-id → owner-name map from the live registry.  Specific
    owners claim first; ``matrix`` (the top-level operator pack, whose
    buffers ARE an AMG hierarchy's level 0) yields to the hierarchy
    owners; aggregate owners (serve/solve/aot trees that wrap the same
    packs) only claim buffers nobody else did."""
    with _STATE.lock:
        entries = list(_STATE.entries.values())

    def rank(e: _Entry) -> int:
        parts = e.name.split("/")
        area = parts[1] if len(parts) > 1 else ""
        if area in AGGREGATE_OWNERS:
            return 2
        return 1 if area == "matrix" else 0

    claims: Dict[int, str] = {}
    for e in sorted(entries, key=rank):
        for ref in e.refs:
            a = ref()
            if a is not None:
                claims.setdefault(id(a), e.name)
        for pid, _nb in e.pins:
            claims.setdefault(pid, e.name)
    return claims


def _backend_stats() -> Dict[str, dict]:
    """Per-device allocator stats where the platform provides them
    (empty on CPU — the honest-stub trigger)."""
    import jax
    out: Dict[str, dict] = {}
    try:
        devices = jax.devices()
    except Exception:
        return out
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms and isinstance(ms, dict) and "bytes_in_use" in ms:
            out[str(d)] = dict(ms)
    return out


def snapshot() -> dict:
    """The ledger snapshot: per-device owner attribution joined over
    the live-array census and the backend allocator stats.

    ALWAYS returns a dict; ``measured`` is True only when at least one
    device exposed ``memory_stats()``.  Per device:
    ``accounted_bytes + unaccounted_bytes == bytes_in_use`` exactly —
    in the stub, ``bytes_in_use`` is defined as the census total so the
    invariant stays arithmetic, not aspirational."""
    import jax
    claims = _claims()
    dev_census: Dict[str, int] = {}
    dev_owner: Dict[str, Dict[str, int]] = {}
    n_live = 0
    n_owned = 0
    seen: set = set()
    try:
        live = jax.live_arrays()
    except Exception:
        live = []
    for a in live:
        aid = id(a)
        if aid in seen:         # shared-buffer dedupe: count once
            continue
        seen.add(aid)
        n_live += 1
        owner = claims.get(aid)
        for dev, nb in _shard_bytes(a):
            dev_census[dev] = dev_census.get(dev, 0) + nb
            if owner is not None:
                dev_owner.setdefault(dev, {})
                dev_owner[dev][owner] = dev_owner[dev].get(owner, 0) + nb
        if owner is not None:
            n_owned += 1

    stats = _backend_stats()
    measured = bool(stats)
    devices: Dict[str, dict] = {}
    for dev in sorted(set(dev_census) | set(stats)):
        owners = dict(sorted((dev_owner.get(dev) or {}).items()))
        accounted = sum(owners.values())
        census = dev_census.get(dev, 0)
        ms = stats.get(dev)
        if ms is not None:
            in_use = int(ms.get("bytes_in_use", 0))
            # allocator padding can put in_use below the census sum on
            # exotic backends; cap so the invariant stays exact
            accounted = min(accounted, in_use)
            d = {
                "bytes_in_use": in_use,
                "accounted_bytes": accounted,
                "unaccounted_bytes": in_use - accounted,
                "census_bytes": census,
                "peak_bytes": int(ms.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(ms.get("bytes_limit", 0)),
            }
            d["headroom_bytes"] = max(d["bytes_limit"] - in_use, 0)
        else:
            d = {
                "bytes_in_use": census,
                "accounted_bytes": accounted,
                "unaccounted_bytes": census - accounted,
                "census_bytes": census,
                "peak_bytes": 0,
                "bytes_limit": 0,
                "headroom_bytes": 0,
            }
        d["owners"] = owners
        devices[dev] = d

    owners_total: Dict[str, int] = {}
    for d in devices.values():
        for o, nb in d["owners"].items():
            owners_total[o] = owners_total.get(o, 0) + nb
    host_owners: Dict[str, int] = {}
    with _STATE.lock:
        entries = list(_STATE.entries.values())
        n_entries = len(entries)
    for e in entries:
        if e.host_bytes:
            host_owners[e.name] = host_owners.get(e.name, 0) \
                + e.host_bytes
    return {
        "measured": measured,
        "ledger_version": LEDGER_VERSION,
        "devices": devices,
        "owners": dict(sorted(owners_total.items())),
        "host_owners": dict(sorted(host_owners.items())),
        "n_live_arrays": n_live,
        "n_owned_arrays": n_owned,
        "registered_entries": n_entries,
    }


def top_owners(snap: dict, n: int = 5) -> List[Tuple[str, int]]:
    """The ``n`` largest (owner, bytes) pairs of a snapshot — what the
    post-mortem, doctor and bench_trend print."""
    ow = snap.get("owners") or {}
    return sorted(((k, int(v)) for k, v in ow.items()),
                  key=lambda kv: -kv[1])[:n]


# ----------------------------------------------------------- surfacing
def _record_history(snap: dict):
    sample = {dev: {"bytes_in_use": d["bytes_in_use"],
                    "headroom_bytes": d["headroom_bytes"]}
              for dev, d in (snap.get("devices") or {}).items()}
    with _STATE.lock:
        _STATE.history.append(
            {"t": time.perf_counter(), "devices": sample})


def headroom_history() -> List[dict]:
    with _STATE.lock:
        return list(_STATE.history)


def emit(snap: dict, phase: str = ""):
    """Record one snapshot: a schema-validated ``hbm_snapshot`` event
    plus the ``amgx_hbm_*`` gauges (owner family cleared first — a
    released owner must not leave a stale series).  No-op when
    telemetry is off."""
    from . import metrics, recorder
    if not recorder.is_enabled():
        return
    reg = metrics.registry()
    reg.gauge_clear("amgx_hbm_bytes")
    for dev, d in (snap.get("devices") or {}).items():
        for o, nb in (d.get("owners") or {}).items():
            metrics.gauge_set("amgx_hbm_bytes", nb, device=dev, owner=o)
        if snap.get("measured"):
            metrics.gauge_set("amgx_hbm_headroom_bytes",
                              d["headroom_bytes"], device=dev)
            metrics.gauge_set("amgx_hbm_peak_bytes",
                              d["peak_bytes"], device=dev)
    recorder.event("hbm_snapshot", phase=str(phase), **snap)


def maybe_sample(phase: str = "", force: bool = False) -> Optional[dict]:
    """Rate-limited snapshot+emit — the hook solver setup phases, solve
    completion and serve dispatch call.  Honours ``memledger_sample_s``
    (0 = sample every call); returns the snapshot when one was taken."""
    if not _STATE.enabled:
        return None
    now = time.perf_counter()
    if not force and _STATE.sample_s > 0 \
            and (now - _STATE.last_sample) < _STATE.sample_s:
        return None
    _STATE.last_sample = now
    snap = snapshot()
    _record_history(snap)
    emit(snap, phase=phase)
    return snap


# ------------------------------------------------------- OOM handling
def is_oom_error(err: BaseException) -> bool:
    """True for device out-of-memory failures: the AMGX ``NO_MEMORY``
    return code (faultinject's injected OOM) and XLA's
    RESOURCE_EXHAUSTED runtime errors."""
    try:
        from ..errors import AMGXError, RC
        if isinstance(err, AMGXError) and err.rc == RC.NO_MEMORY:
            return True
    except Exception:
        pass
    s = str(err).lower()
    return ("resource_exhausted" in s or "resource exhausted" in s
            or "out of memory" in s or "out-of-memory" in s)


def suggestions(snap: dict) -> List[dict]:
    """Doctor-grade eviction suggestions ordered by relevance to what
    is actually resident (each a ``{knob, hint}`` pair)."""
    ow = snap.get("owners") or {}
    out: List[dict] = []
    if any(k.startswith("amgx/serve/") for k in ow):
        out.append({"knob": "serve_cache_bytes",
                    "hint": "shrink the serving setup-cache byte "
                            "budget; cached sessions are evicted LRU"})
    if any(k.startswith("amgx/hierarchy/")
           or k.startswith("amgx/transfer/") for k in ow):
        out.append({"knob": "hierarchy_dtype",
                    "hint": "store coarse hierarchy packs in bfloat16 "
                            "(hierarchy_dtype=bfloat16) — roughly "
                            "halves level+transfer bytes"})
    if any(k.startswith("amgx/dist/") for k in ow):
        out.append({"knob": "dist_agglomerate_min_rows",
                    "hint": "raise the agglomeration threshold so "
                            "coarse levels consolidate onto fewer "
                            "devices earlier"})
    if not out:
        out.append({"knob": "serve_cache_bytes",
                    "hint": "no owned bytes resident — the allocation "
                            "likely predates ledger registration; "
                            "lower cache budgets and retry"})
    return out


def postmortem(err: BaseException, where: str,
               snap: Optional[dict] = None) -> dict:
    """Build the OOM post-mortem bundle (pure — no emission)."""
    if snap is None:
        snap = snapshot()
    msg = str(err)
    return {
        "where": str(where),
        "error": msg[:500],
        "error_type": type(err).__name__,
        "injected": "injected" in msg,
        "ledger_version": LEDGER_VERSION,
        "measured": bool(snap.get("measured")),
        "snapshot": snap,
        "top_owners": [[k, v] for k, v in top_owners(snap)],
        "headroom_history": headroom_history(),
        "suggestions": suggestions(snap),
    }


def emit_postmortem(err: BaseException, where: str,
                    in_recovery: bool = False) -> Optional[dict]:
    """Emit one schema-validated ``oom_postmortem`` event for a device
    OOM (idempotent per exception object: the solver and serve layers
    both wrap the same call stack, and the bundle must be emitted once,
    at the innermost layer that saw it).  Returns the bundle, or None
    when nothing was emitted (ledger off — the zero-overhead contract —
    or recorder off, or already emitted for this exception)."""
    from . import recorder
    if not _STATE.enabled or not recorder.is_enabled():
        return None
    if getattr(err, "_amgx_postmortem_emitted", False):
        return None
    try:
        err._amgx_postmortem_emitted = True
    except Exception:
        pass
    pm = postmortem(err, where)
    pm["in_recovery"] = bool(in_recovery)
    recorder.event("oom_postmortem", **pm)
    return pm
