"""Measured (not modelled) interior-vs-halo overlap from profiler traces.

:func:`costmodel.dist_overlap` predicts how much of a level's halo
exchange the interior SpMV can hide from shape arithmetic alone.  This
module replaces that prediction with TRUTH when a ``jax.profiler``
capture of a real multi-chip run is available: it parses the chrome
trace, classifies device ops into communication (all-reduce /
all-gather / reduce-scatter / collective-permute / all-to-all) vs
compute, and measures the fraction of communication wall time that ran
CONCURRENTLY with compute on the same device — the achieved overlap.

Events refined through :func:`measured_event` carry ``measured=True``
so every downstream consumer (doctor, perf gate, dashboards) can tell
an honest measurement from a model (the ``dist_overlap`` schema's
``measured`` bool).

Host-side file parsing only — safe without any profiler plugin
installed; every entry point degrades to ``None`` when the trace has
no communication ops (single-device or CPU runs keep the modelled
numbers).
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from . import proftrace

# the discovery/parsing mechanics live in the shared proftrace module
# (deviceprof consumes the same plumbing); these aliases keep this
# module's historical private names working
_COMM_RE = proftrace.COMM_RE
_SKIP_PH = proftrace.SKIP_PH
_load_json = proftrace.load_json
find_trace_file = proftrace.find_trace_file
_merge_intervals = proftrace.merge_intervals
_overlap_len = proftrace.overlap_len


def measure(trace: "str | dict | Iterable[dict]") -> Optional[dict]:
    """Measured overlap numbers from a profiler capture.

    ``trace``: a path (file or profiler logdir), a loaded chrome-trace
    dict, or an iterable of trace events.  Returns ``None`` when no
    communication ops appear (nothing to measure — keep the model);
    otherwise a dict with ``overlap_fraction`` (fraction of comm wall
    time concurrent with same-device compute), ``comm_s`` /
    ``compute_s`` totals, ``n_comm_events`` and ``n_devices``.
    """
    events = proftrace.trace_events(trace)

    comm: dict = {}      # pid -> [(start, end)]
    compute: dict = {}   # pid -> [(start, end)]
    for ev in proftrace.complete_slices(events):
        ts, dur = ev["ts"], ev["dur"]
        pid = ev.get("pid", 0)
        name = str(ev.get("name", ""))
        bucket = comm if _COMM_RE.search(name) else compute
        bucket.setdefault(pid, []).append((float(ts), float(ts) + float(dur)))
    if not comm:
        return None

    comm_us = 0.0
    hidden_us = 0.0
    compute_us = 0.0
    for pid, spans in comm.items():
        merged = _merge_intervals(compute.get(pid, []))
        compute_us += sum(e - s for s, e in merged)
        for s, e in spans:
            comm_us += e - s
            hidden_us += _overlap_len(s, e, merged)
    # devices that only computed still count toward the device tally
    n_devices = len(set(comm) | set(compute))
    frac = hidden_us / comm_us if comm_us > 0 else 1.0
    return {
        "overlap_fraction": round(min(frac, 1.0), 4),
        "comm_s": round(comm_us * 1e-6, 9),
        "compute_s": round(compute_us * 1e-6, 9),
        "n_comm_events": sum(len(v) for v in comm.values()),
        "n_devices": n_devices,
    }


def measured_event(base: dict, measured: dict) -> dict:
    """A ``dist_overlap`` event payload with the modelled overlap numbers
    replaced by profiler truth (``measured=True``).

    ``base`` is a modelled event dict (:func:`costmodel.dist_overlap`
    output — its structural fields n_parts/rows/bytes stay authoritative);
    ``measured`` is a :func:`measure` result.
    """
    out = dict(base)
    est_halo_s = measured["comm_s"]
    est_interior_s = measured["compute_s"]
    out.update(
        overlap_fraction=measured["overlap_fraction"],
        est_interior_s=round(est_interior_s, 9),
        est_halo_s=round(est_halo_s, 9),
        halo_bound=bool(est_halo_s * (1.0 - measured["overlap_fraction"])
                        > est_interior_s),
        measured=True,
    )
    return out


def refine_captured(dist_events: List[dict], trace) -> List[dict]:
    """Refine captured modelled ``dist_overlap`` event payloads with one
    trace's measured overlap; returns the refined payloads (empty when the
    trace yields nothing — callers then keep the modelled events)."""
    m = measure(trace)
    if m is None:
        return []
    return [measured_event(ev, m) for ev in dist_events]
