"""Mode system: (memory space, vector precision, matrix precision, index precision).

TPU-native equivalent of the reference's ``TemplateConfig`` / ``AMGX_Mode``
machinery (``base/include/basic_types.h:76-125``,
``base/include/amgx_config.h:102-147``).  The reference explicitly instantiates
every algorithm for each of 10 modes via C++ templates; JAX is dtype-generic,
so a mode here is a small runtime policy object that selects the backend
("host" → CPU, "device" → TPU/default accelerator) and the dtypes used for
vectors, matrix values and indices.

Mode strings follow the reference naming: e.g. ``dDDI`` = device memory,
double vectors, double matrix, int indices.  Complex modes (``dZZI`` …) map to
``complex128``/``complex64``.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .errors import BadModeError

_MEM = {"h": "host", "d": "device"}
_PREC = {
    "D": np.float64,
    "F": np.float32,
    "Z": np.complex128,
    "C": np.complex64,
    "I": np.int32,
}

#: The 12 public modes of the reference (amgx_config.h:125-147).
PUBLIC_MODES = (
    "hDDI", "hDFI", "hFFI",
    "dDDI", "dDFI", "dFFI",
    "hZZI", "hZCI", "hCCI",
    "dZZI", "dZCI", "dCCI",
)

#: Numeric mode ids matching AMGX_Mode enum ordering (amgx_config.h:125-147).
MODE_IDS = {name: i for i, name in enumerate(PUBLIC_MODES)}

_fp64_warned: set = set()
_complex_warned: list = []


def _warn_complex_host():
    """One-time notice: complex data runs on the host backend (this TPU
    runtime has no complex lowering — probed: even c64 add returns
    UNIMPLEMENTED)."""
    if _complex_warned:
        return
    _complex_warned.append(True)
    from .utils.logging import amgx_output

    amgx_output(
        "NOTE: complex-mode data runs on the HOST backend: this TPU "
        "runtime has no complex lowering (c64 ops return "
        "UNIMPLEMENTED), matching the hZZI/hCCI host modes.\n")


def _warn_fp64_downgrade(mode_name: str):
    """One-time visible notice that a device-mode fp64 matrix runs in fp32
    on this accelerator, so tolerance below ~1e-7 cannot converge and the
    user knows why (C-API callers otherwise get no diagnostic)."""
    if mode_name in _fp64_warned:
        return
    _fp64_warned.add(mode_name)
    from .utils.logging import amgx_output

    amgx_output(
        f"NOTE: mode {mode_name}: the device pack runs in fp32 on this "
        "accelerator (TPU fp64 has no hardware path); the host matrix "
        "stays fp64 and mixed-precision refinement recovers "
        "full-precision residuals for tight tolerances.\n")


@dataclasses.dataclass(frozen=True)
class Mode:
    """Runtime policy: where data lives and which dtypes are used."""

    name: str
    mem_space: str        # "host" | "device"
    vec_dtype: np.dtype
    mat_dtype: np.dtype
    ind_dtype: np.dtype

    @property
    def is_complex(self) -> bool:
        return np.issubdtype(self.vec_dtype, np.complexfloating)

    @property
    def is_device(self) -> bool:
        return self.mem_space == "device"

    def jax_platform(self) -> str:
        """The JAX platform this mode runs on."""
        if self.mem_space == "host":
            return "cpu"
        import jax

        return jax.default_backend()

    def placement_device(self):
        """The jax.Device data should live on: CPU for host modes, the
        default accelerator for device modes.  Complex device modes fall
        back to the host backend on TPUs — the runtime has no complex
        lowering (even addition is UNIMPLEMENTED; probed on v5e)."""
        import jax

        if self.mem_space == "host":
            return jax.local_devices(backend="cpu")[0]
        if self.is_complex and jax.default_backend() == "tpu":
            _warn_complex_host()
            return jax.local_devices(backend="cpu")[0]
        return jax.devices()[0]

    def effective_mat_dtype(self):
        """Device-mode fp64 falls back to fp32 on TPU (fp64 is
        emulated/unsupported there; mirrors the honest-precision note of
        SURVEY §7 hard-part 6 — hDDI keeps true fp64 on the host)."""
        import jax

        if (self.mem_space == "device"
                and jax.default_backend() not in ("cpu",)):
            if self.mat_dtype == np.dtype(np.float64):
                _warn_fp64_downgrade(self.name)
                return np.dtype(np.float32)
            if self.mat_dtype == np.dtype(np.complex128) and \
                    jax.default_backend() == "tpu":
                # complex data runs on the HOST backend on this TPU
                # runtime (no complex lowering at all) — c64 pack there
                # keeps the hZZI-style wide-host/narrow-pack split;
                # other accelerators keep native c128
                _warn_complex_host()
                return np.dtype(np.complex64)
        return self.mat_dtype


def parse_mode(mode: "str | int | Mode") -> Mode:
    """Parse a mode string like ``dDDI`` (or AMGX_Mode integer) into a Mode."""
    if isinstance(mode, Mode):
        return mode
    if isinstance(mode, int):
        if not 0 <= mode < len(PUBLIC_MODES):
            raise BadModeError(f"unknown mode id {mode}")
        mode = PUBLIC_MODES[mode]
    if not (isinstance(mode, str) and len(mode) == 4):
        raise BadModeError(f"bad mode {mode!r}")
    mem, vp, mp, ip = mode[0], mode[1], mode[2], mode[3]
    if mem not in _MEM or vp not in _PREC or mp not in _PREC or ip != "I":
        raise BadModeError(f"unknown mode {mode!r}")
    return Mode(
        name=mode,
        mem_space=_MEM[mem],
        vec_dtype=np.dtype(_PREC[vp]),
        mat_dtype=np.dtype(_PREC[mp]),
        ind_dtype=np.dtype(np.int32),
    )


def default_mode() -> Mode:
    return parse_mode("dDDI")
