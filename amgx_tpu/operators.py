"""Implicit linear operators for Krylov-on-operator solves.

Reference: ``base/include/operators/operator.h:37-80`` (the abstract
``Operator::apply`` the solver framework accepts instead of a concrete
matrix) and its concrete flavours in ``core/src/operators/``:
``shifted_operator.cu`` (A − σI), ``deflated_multiply_operator.cu``
(A·v − λ (v·x₀) x₀ for locked eigenpairs), ``pagerank_operator.cu``
(damped column-stochastic web operator), ``solve_operator.cu`` /
``solver_operator.cu`` (a nested solver as an operator).

TPU redesign: an operator is a frozen PYTREE with an ``apply`` the
:func:`amgx_tpu.ops.spmv.spmv` dispatch recognises (``fmt == "op"``) —
it rides through the whole-solve jit as arguments, composes with every
Krylov solver (``Solver.setup`` accepts an operator wherever it accepts
a matrix), and its latency hiding is XLA's problem, as the reference
header's comment wishes it could be.  The eigensolver machinery has
used these formulas inline since round 3 (``eigen/algorithms.py``);
this module makes the capability public API, matching the reference's
operator registry.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["ImplicitOperator", "ShiftedOperator", "DeflatedOperator",
           "PageRankOperator", "SolverOperator", "as_operator"]


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["base", "diag", "aux"],
    meta_fields=["n_rows", "n_cols", "kind"],
)
@dataclasses.dataclass(frozen=True)
class ImplicitOperator:
    """A linear operator defined by composition over a base pack.

    ``base``: the underlying DeviceMatrix (or another operator);
    ``aux``: kind-specific arrays (shift scalar, deflation basis,
    dangling mask...); ``diag``: the operator's diagonal (smoothers and
    Jacobi-family preconditioners read it, reference
    ``Matrix::computeDiagonal`` analog)."""

    base: Any
    diag: jax.Array
    aux: Any
    n_rows: int
    n_cols: int
    kind: str

    fmt = "op"
    block_dim = 1
    ell_width = 0

    @property
    def n(self) -> int:
        return self.n_rows

    @property
    def dtype(self):
        return self.diag.dtype

    def apply(self, x: jax.Array) -> jax.Array:
        from .ops.spmv import spmv
        if self.kind == "shifted":
            # (A − σI)·x  (shifted_operator.cu:'s apply)
            return spmv(self.base, x) - self.aux * x
        if self.kind == "deflated":
            # A·x − Σ_k λ_k (x·v_k) v_k  (deflated_multiply_operator.cu)
            V, lam = self.aux
            coef = lam * (V.T @ x)
            return spmv(self.base, x) - V @ coef
        if self.kind == "pagerank":
            # α·Aᵀ_stoch·x + teleport  (pagerank_operator.cu): base is
            # the pre-normalised column-stochastic pack; aux = (alpha,
            # dangling mask)
            alpha, dangle = self.aux
            y = spmv(self.base, x)
            leaked = jnp.sum(jnp.where(dangle, x, 0.0))
            nr = jnp.asarray(self.n_rows, x.dtype)
            return alpha * (y + leaked / nr) + \
                (1.0 - alpha) * jnp.sum(x) / nr
        raise ValueError(f"unknown operator kind {self.kind!r}")


def _matrix_pack(A):
    """DeviceMatrix of a Matrix/DeviceMatrix/operator argument."""
    return A.device() if hasattr(A, "device") and callable(
        getattr(A, "device")) else A


def ShiftedOperator(A, sigma: float) -> ImplicitOperator:
    """``(A − σI)`` without materialising the shift
    (``shifted_operator.cu``) — the eigensolver spectral transforms
    build on exactly this formula."""
    Ad = _matrix_pack(A)
    sig = jnp.asarray(sigma, Ad.dtype)
    return ImplicitOperator(
        base=Ad, diag=Ad.diag - sig, aux=sig,
        n_rows=Ad.n_rows, n_cols=Ad.n_cols, kind="shifted")


def DeflatedOperator(A, vectors, values) -> ImplicitOperator:
    """``A·v − Σ λ_k (v·x_k) x_k`` for locked eigenpairs
    (``deflated_multiply_operator.cu``)."""
    Ad = _matrix_pack(A)
    V = jnp.asarray(vectors, Ad.dtype)
    if V.ndim == 1:
        V = V[:, None]
    lam = jnp.atleast_1d(jnp.asarray(values, Ad.dtype))
    diag = Ad.diag - jnp.sum(lam[None, :] * V * V, axis=1)
    return ImplicitOperator(
        base=Ad, diag=diag, aux=(V, lam),
        n_rows=Ad.n_rows, n_cols=Ad.n_cols, kind="deflated")


def PageRankOperator(W, alpha: float = 0.85) -> ImplicitOperator:
    """The damped PageRank iteration operator over a link matrix ``W``
    (rows = source pages), matching ``pagerank_operator.cu``'s
    normalise-then-damp apply."""
    import numpy as np
    import scipy.sparse as sp

    from .core.matrix import Matrix, pack_device
    Wc = sp.csr_matrix(W.host if isinstance(W, Matrix) else W)
    out_deg = np.asarray(Wc.sum(axis=1)).ravel()
    dangle = out_deg == 0
    inv = np.where(dangle, 0.0, 1.0 / np.where(dangle, 1.0, out_deg))
    # column-stochastic transpose pack: y = Wᵀ D⁻¹ x
    S = sp.csr_matrix(Wc.T @ sp.diags(inv))
    dtype = np.dtype(getattr(W, "device_dtype", None) or np.float32)
    Sd = pack_device(S, 1, dtype)
    return ImplicitOperator(
        base=Sd, diag=Sd.diag * alpha,
        aux=(jnp.asarray(alpha, dtype), jnp.asarray(dangle)),
        n_rows=Sd.n_rows, n_cols=Sd.n_cols, kind="pagerank")


class SolverOperator:
    """A configured solver as a linear operator v ↦ solve(A, v)
    (``solve_operator.cu`` / ``solver_operator.cu``) — host-driven
    composition; each apply runs the inner solver's whole-solve jit."""

    def __init__(self, solver):
        self.solver = solver

    def apply(self, v):
        return self.solver.solve(v).x


def as_operator(obj) -> Optional[ImplicitOperator]:
    return obj if isinstance(obj, ImplicitOperator) else None
