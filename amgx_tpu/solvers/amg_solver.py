"""AMG as a Solver.

Reference: ``base/src/solvers/algebraic_multigrid_solver.cu`` — wraps the
``AMG`` hierarchy as a ``Solver`` so it can be the main solver, a
preconditioner, or even a smoother; one 'solve iteration' = one multigrid
cycle (``amg.cu:1236-1254``).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..amg.cycles import build_cycle
from ..amg.hierarchy import AMGHierarchy
from ..errors import BadConfigurationError
from .base import Solver, register_solver


@register_solver("AMG")
class AMGSolver(Solver):
    is_smoother = True  # usable as a smoother/preconditioner

    def solver_setup(self):
        if self.A is None:
            raise BadConfigurationError(
                "AMG setup requires the host matrix (upload via Matrix)")
        if not (getattr(self, "_numeric_resetup", False)
                and getattr(self, "hierarchy", None) is not None):
            self.hierarchy = AMGHierarchy(self.cfg, self.scope)
        self.hierarchy.setup(self.A)
        self._cycle = build_cycle(self.hierarchy)

    def solve_iteration(self, b, x, state, iter_idx):
        return self._cycle(b, x), state

    def grid_stats(self):
        return self.hierarchy.grid_stats()

    # resetup(): inherited from Solver — sets _numeric_resetup so
    # solver_setup keeps the hierarchy OBJECT (structure reuse applies)
    # and the base setup preserves compiled executables (same shapes →
    # jit cache hit, no recompile).  A plain setup() rebuilds fresh.
