"""AMG as a Solver.

Reference: ``base/src/solvers/algebraic_multigrid_solver.cu`` — wraps the
``AMG`` hierarchy as a ``Solver`` so it can be the main solver, a
preconditioner, or even a smoother; one 'solve iteration' = one multigrid
cycle (``amg.cu:1236-1254``).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..amg.cycles import build_cycle
from ..amg.hierarchy import AMGHierarchy
from ..errors import BadConfigurationError
from .base import Solver, register_solver


@register_solver("AMG")
class AMGSolver(Solver):
    is_smoother = True  # usable as a smoother/preconditioner

    def solver_setup(self):
        if self.A is None:
            raise BadConfigurationError(
                "AMG setup requires the host matrix (upload via Matrix)")
        if not (getattr(self, "_numeric_resetup", False)
                and getattr(self, "hierarchy", None) is not None):
            self.hierarchy = AMGHierarchy(self.cfg, self.scope)
        self.hierarchy.setup(self.A)
        self._cycle = build_cycle(self.hierarchy)

    def solve_iteration(self, b, x, state, iter_idx):
        return self._cycle(b, x), state

    def set_forensics(self, on: bool = True):
        """Flip cycle-anatomy instrumentation (telemetry/forensics.py)
        on the EXISTING hierarchy without a re-setup: rebuilds the
        traced cycle and drops this solver's compiled executables so
        the next solve traces the (un)instrumented graph.  A caller
        whose OUTER solver inlined this cycle as a preconditioner must
        invalidate that executable itself (and owns its own history
        flag — the asymptotic-rate estimate reads the OUTER solve's
        residual history)."""
        self.forensics = bool(on)
        if on:
            # same coupling as the config knob in Solver.__init__: the
            # asymptotic-rate gauge needs the residual history kept
            # (disabling leaves it on — harmless, maybe user-configured)
            self.store_res_history = True
        self.hierarchy.forensics = 1 if on else 0
        if on:
            # the setup-time quality probes were skipped when the knob
            # was off — run them now so the doctor's probe section (and
            # the hints pointing at it) exist for this enable path too;
            # they emit only if telemetry is currently recording
            from .. import telemetry
            if telemetry.is_enabled():
                try:
                    telemetry.forensics.probe_hierarchy(self.hierarchy)
                except Exception:
                    pass
        self._cycle = build_cycle(self.hierarchy)
        self._solve_fn = None
        self._refined_fn = None
        self._solve_multi = None
        self._solve_multi_refined = None
        self._bindings = None

    def grid_stats(self):
        return self.hierarchy.grid_stats()

    # resetup(): inherited from Solver — sets _numeric_resetup so
    # solver_setup keeps the hierarchy OBJECT (structure reuse applies)
    # and the base setup preserves compiled executables (same shapes →
    # jit cache hit, no recompile).  A plain setup() rebuilds fresh.
