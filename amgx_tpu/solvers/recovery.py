"""The automatic recovery ladder: bounded, telemetry-audited escalation.

When a monitored solve terminates in failure (breakdown, divergence,
stagnation — see :class:`~amgx_tpu.errors.FailureKind`) and the
``recovery_policy`` knob is ``AUTO``, the driver walks a **bounded
ladder** of increasingly expensive repairs instead of handing the
caller a dead result:

0. **krylov_classic** — a communication-avoiding (CA/PIPELINED)
   recurrence that broke down falls back to the CLASSIC reduction
   layout first (PR 16): same operator and hierarchy, only the loop
   body re-traces — cheaper than any rung below and targeted at the
   one thing the reordered recurrences changed;
1. **restart** — re-run the Krylov loop from the last finite iterate
   (a fresh Krylov space sheds the poisoned/collapsed basis; costs one
   more solve, reuses every compiled executable);
2. **promote** — one precision rung up (PR 10's promotion plan, now
   triggered by *breakdown* rather than only tolerance floors: the
   narrow pack re-runs under the defect-correction outer loop bounded
   by the uploaded host matrix);
3. **conservative** — rebuild with a conservative smoother config
   (a Chebyshev smoother with bad spectrum bounds amplifies — swap to
   Jacobi and re-setup a twin solver; the user's solver is untouched);
4. **resetup** — full setup from the original operator (the hierarchy
   itself may be poisoned — e.g. an injected upload corruption).

Each attempt emits a schema-validated ``recovery_attempt`` event and an
``amgx_recovery_total{kind,action,outcome}`` counter sample, so a
production trace says exactly which breakdowns happened, what fixed
them, and what it cost.  The ladder is *bounded* by
``recovery_max_attempts`` and never recurses (attempt solves run with
``_in_recovery`` set).

Off (``recovery_policy=NONE``, the default) this module is never
imported by the solve path.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .. import telemetry
from ..errors import FailureInfo, FailureKind, SolveStatus

#: ladder rungs, cheapest first — the vocabulary of the
#: ``recovery_attempt`` event and the amgx_recovery_total action label.
#: krylov_classic comes before restart: a breakdown in a
#: communication-avoiding recurrence (PR 16) may be an artifact of the
#: reordered scalar recurrences rather than the operator — re-running
#: with the CLASSIC reduction layout reuses every setup product and is
#: cheaper than burning a restart rung on a possibly-poisoned basis
ACTIONS = ("krylov_classic", "restart", "promote", "conservative",
           "resetup")

#: failure kinds the krylov_classic rung can plausibly repair — the
#: recurrence-sensitive breakdowns; a stagnated or diverged solve is
#: not a reduction-layout problem
_KRYLOV_KINDS = (FailureKind.KRYLOV_BREAKDOWN,
                 FailureKind.INDEFINITE_OPERATOR,
                 FailureKind.NAN_POISON)

#: smoother knobs swapped by the conservative rung (any non-Jacobi
#: smoother — Chebyshev with a bad spectrum estimate, an aggressive
#: GS/DILU — falls back to the unconditionally-safe damped Jacobi)
_SMOOTHER_KNOBS = ("smoother", "fine_smoother", "coarse_smoother")
_SAFE_SMOOTHERS = ("BLOCK_JACOBI", "JACOBI_L1", "CF_JACOBI")


class _Skip(Exception):
    """A rung that cannot apply to this solver/config (no wider rung to
    promote to, already-conservative smoother) — audited as outcome
    ``skipped``, burns no attempt budget.  ``audit=False`` marks a rung
    that is *structurally absent* for this solver (a CLASSIC-mode solve
    has no CA fallback rung): it skips silently, so the rung 0
    krylov_classic check does not prepend a noise event to every
    recovery of a default-config solver."""

    def __init__(self, msg: str, audit: bool = True):
        super().__init__(msg)
        self.audit = audit


def _failure_kind(result) -> FailureKind:
    if result.failure is not None:
        return result.failure.kind
    nrm = result.residual_norm
    if nrm is not None and not np.all(np.isfinite(np.asarray(nrm))):
        return FailureKind.DIVERGENCE
    return FailureKind.STAGNATION


def _finite_start(result, x0):
    """The restart iterate: the failed solve's x when every entry is
    finite (a stagnated/indefinite exit keeps its progress), else the
    caller's original guess."""
    try:
        x = np.asarray(result.x)
        if x.size and np.all(np.isfinite(x)):
            return x, False
    except Exception:
        pass
    return x0, True


def _solve_again(solver, b, x0, zero_initial_guess):
    return solver.solve(b, x0=x0, zero_initial_guess=zero_initial_guess)


def _act_krylov_classic(solver, b, x0, zero_initial_guess, last):
    """CA/PIPELINED → CLASSIC fallback: re-run with the two-reduction
    classic recurrence (same operator, same hierarchy — only the jitted
    loop body re-traces).  Sticky on success: a recurrence that broke
    once is not re-trusted; reverted on failure so an unrelated
    breakdown does not permanently slow the solver down."""
    mode = solver._comm_mode() if hasattr(solver, "_comm_mode") \
        else "CLASSIC"
    if mode == "CLASSIC":
        raise _Skip("solver already runs the CLASSIC reduction layout",
                    audit=False)
    if _failure_kind(last) not in _KRYLOV_KINDS:
        raise _Skip("failure kind is not a recurrence breakdown")
    solver._force_krylov_classic = True
    solver._invalidate_solve_fns()
    try:
        res = _solve_again(solver, b, x0, zero_initial_guess)
    except Exception:
        solver._force_krylov_classic = False
        solver._invalidate_solve_fns()
        raise
    if res is None or res.status != SolveStatus.SUCCESS:
        solver._force_krylov_classic = False
        solver._invalidate_solve_fns()
    return res


def _act_restart(solver, b, x0, zero_initial_guess, last):
    x_start, fell_back = _finite_start(last, x0)
    if fell_back:
        return _solve_again(solver, b, x0, zero_initial_guess)
    # under a RELATIVE_* criterion the restarted solve's baseline is
    # the (already reduced) residual at the restart iterate — rescale
    # the tolerance so the restart chases the ORIGINAL target instead
    # of eight more orders from wherever the first leg stopped.  The
    # tolerance rides the jitted body as an argument, so no retrace.
    tol = solver.tolerance
    scaled = None
    if solver.convergence.startswith("RELATIVE") \
            and last.residual_history is not None \
            and len(last.residual_history) \
            and last.residual_norm is not None:
        ini = float(np.max(np.atleast_1d(last.residual_history[0])))
        cur = float(np.max(np.atleast_1d(last.residual_norm)))
        if np.isfinite(ini) and np.isfinite(cur) and 0 < cur and 0 < ini:
            scaled = min(tol * ini / cur, 0.5)
    try:
        if scaled is not None:
            solver.tolerance = scaled
        return solver.solve(b, x0=x_start, zero_initial_guess=False)
    finally:
        solver.tolerance = tol


def _act_promote(solver, b, x0, zero_initial_guess, last):
    base_refine, _w, _s = solver._promotion_plan()
    if base_refine:
        # the failed solve ALREADY ran under the promotion rung (deep
        # tolerance on a narrow pack) — forcing it again would re-run
        # the identical refined solve and burn an attempt for nothing
        raise _Skip("solve already ran at the promoted rung")
    solver._force_promotion = True
    try:
        refine, _wide, _structural = solver._promotion_plan()
        if not refine:
            raise _Skip("no wider promotion rung available "
                        "(host matrix not wider than the device pack, "
                        "or structurally unrefinable)")
        return _solve_again(solver, b, x0, zero_initial_guess)
    finally:
        solver._force_promotion = False


def _setup_source(solver):
    """The operator the rebuild rungs re-setup from: the pre-scaling
    stash when present; the solver's working matrix only when it is
    the caller's original (re-running setup on a scaled/reordered COPY
    would scale twice — skip instead)."""
    A = getattr(solver, "_setup_input", None)
    if A is not None:
        return A
    if solver.scaler is not None \
            or getattr(solver, "_reorder", None) is not None:
        raise _Skip("original operator unavailable (solver holds a "
                    "scaled/reordered copy only)")
    return solver.A if solver.A is not None else solver.Ad


def _act_conservative(solver, b, x0, zero_initial_guess, last):
    cfg = solver.cfg.clone()
    swapped = []
    for (scope, name), (value, new_scope) in list(cfg._params.items()):
        if name in _SMOOTHER_KNOBS and value not in _SAFE_SMOOTHERS:
            # keep the entry's sub-scope binding: the Jacobi twin reads
            # its params from the same scope the old smoother did (and
            # ignores the Chebyshev-specific ones)
            cfg._params[(scope, name)] = ("BLOCK_JACOBI", new_scope)
            swapped.append(f"{scope}:{name}={value}")
    if not swapped:
        raise _Skip("smoother stack is already conservative")
    from .base import SolverFactory
    A = _setup_source(solver)
    twin = SolverFactory.create(solver.config_name, cfg, solver.scope)
    twin._toplevel = getattr(solver, "_toplevel", False)
    twin._in_recovery = True
    twin.setup(A)
    return twin.solve(b, x0=x0, zero_initial_guess=zero_initial_guess)


def _act_resetup(solver, b, x0, zero_initial_guess, last):
    solver.setup(_setup_source(solver))
    return _solve_again(solver, b, x0, zero_initial_guess)


_ACTION_FN = {"krylov_classic": _act_krylov_classic,
              "restart": _act_restart, "promote": _act_promote,
              "conservative": _act_conservative,
              "resetup": _act_resetup}


def _audit(kind: FailureKind, action: str, attempt: int, outcome: str,
           solver, wall_s: float, detail: str = "",
           oom: bool = False):
    telemetry.counter_inc("amgx_recovery_total", kind=kind.value,
                          action=action, outcome=outcome)
    if telemetry.is_enabled():
        extra = {"detail": detail[:200]} if detail else {}
        if oom:
            # HBM-ledger cross-reference: this rung died on a device
            # OOM, whose oom_postmortem event (emitted at the failing
            # setup/solve with in_recovery=true) carries the resident
            # ledger snapshot
            extra["oom"] = True
        telemetry.event("recovery_attempt", kind=kind.value,
                        action=action, attempt=int(attempt),
                        outcome=outcome, solver=solver.config_name,
                        wall_s=round(wall_s, 6), **extra)
        if getattr(solver, "telemetry_path", ""):
            # the audit lands AFTER the attempt solve's own incremental
            # flush — without this, a streaming trace would always be
            # missing its final recovery record
            telemetry.flush_jsonl(solver.telemetry_path)


def maybe_recover(solver, b, x0, zero_initial_guess: bool, result):
    """Walk the ladder for a failed ``result``; returns the recovered
    result (``.recovery`` records the audit) or the best failing one
    (``.recovery["outcome"] == "exhausted"``).  Never raises: a rung
    that errors is audited and the ladder escalates past it.

    Scope: the SINGLE-RHS solve path only.  Batched ``solve_multi``
    lanes report their :class:`FailureInfo` without recovery — in the
    serving layer the retry budget / quarantine are the batched path's
    recovery story, and an in-ladder re-solve there would silently
    multiply a whole batch's deadline by the attempt count."""
    kind = _failure_kind(result)
    budget = max(0, int(solver.recovery_max_attempts))
    if budget == 0:
        return result
    solver._in_recovery = True
    attempt = 0
    last = result
    last_action = None
    try:
        for action in ACTIONS:
            if attempt >= budget:
                break
            t0 = time.perf_counter()
            try:
                cand = _ACTION_FN[action](solver, b, x0,
                                          zero_initial_guess, last)
            except _Skip as sk:
                # an inapplicable rung burns no budget — audit and
                # escalate (unless the rung is structurally absent
                # for this solver, which skips silently)
                if getattr(sk, "audit", True):
                    _audit(kind, action, attempt, "skipped", solver,
                           time.perf_counter() - t0, detail=str(sk))
                continue
            except Exception as e:  # noqa: BLE001 — the ladder must
                # never raise past the solve that invoked it; the
                # failure is audited and the next rung tries
                attempt += 1
                _audit(kind, action, attempt, "error", solver,
                       time.perf_counter() - t0,
                       detail=f"{type(e).__name__}: {e}",
                       oom=telemetry.memledger.is_oom_error(e))
                last_action = action
                continue
            attempt += 1
            last_action = action
            ok = cand is not None and cand.status == SolveStatus.SUCCESS
            _audit(kind, action, attempt,
                   "recovered" if ok else "failed", solver,
                   time.perf_counter() - t0)
            if cand is not None:
                last = cand
            if ok:
                cand.recovery = {"kind": kind.value, "action": action,
                                 "attempts": attempt,
                                 "outcome": "recovered"}
                return cand
        # ladder exhausted: hand back the best failing result with the
        # audit attached (and one terminal counter sample so dashboards
        # can alert on unrecovered breakdowns without event parsing)
        telemetry.counter_inc("amgx_recovery_total", kind=kind.value,
                              action="ladder", outcome="exhausted")
        last.recovery = {"kind": kind.value, "action": last_action,
                         "attempts": attempt, "outcome": "exhausted"}
        if last.failure is None:
            last.failure = result.failure or FailureInfo(kind=kind)
        return last
    finally:
        solver._in_recovery = False
