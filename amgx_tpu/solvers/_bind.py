"""Device-argument binding for jitted solve loops.

The reference streams any-size matrices through its kernels
(``multiply.cu:75-196``, ``solver.cu:589-970`` work at any N).  The TPU
analog of that contract is that the jitted solve function must receive the
matrix / hierarchy / smoother arrays as *arguments* — never as trace-time
closure constants, which XLA bakes into the executable (at 128³ that is
~2 GB of captured constants and a failed compile).

:class:`DeviceBindings` walks the solver object graph — nested solvers,
the AMG hierarchy and its levels, host ``Matrix`` handles with cached
device packs — and records every attribute slot holding device data
(a ``jax.Array``, a ``DeviceMatrix``/``ShardedMatrix`` pytree, or a
list/tuple of those).  ``collect()`` gathers the current values as one
argument pytree; ``bind()`` temporarily swaps tracers into the same slots
while the solve function is traced, so unmodified solver code picks the
tracers up through its normal ``self.X`` attribute reads.

Slots that alias the identical object (e.g. ``solver.Ad`` and
``solver.A._device``) are deduplicated so each buffer appears once in the
argument pytree and both slots receive the same tracer.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax


def _is_device_value(v) -> bool:
    """True when ``v`` is pure device data: a pytree whose leaves are all
    jax Arrays (covers jax.Array, DeviceMatrix, ShardedMatrix, and
    lists/tuples/dicts of them).  Host numpy arrays are deliberately
    excluded — they are setup-phase data and must stay static."""
    leaves = jax.tree_util.tree_leaves(v)
    return bool(leaves) and all(isinstance(l, jax.Array) for l in leaves)


def _is_traversable(v) -> bool:
    """Objects whose attributes may hold device slots: anything defined in
    this package (solvers, hierarchy, levels, matrix handles) that carries
    an instance ``__dict__``.  Config/coloring/scaler objects are harmless
    to visit — they simply contain no device leaves."""
    cls = type(v)
    mod = getattr(cls, "__module__", "")
    return mod.startswith("amgx_tpu") and hasattr(v, "__dict__")


class DeviceBindings:
    def __init__(self, root):
        self._slots: List[Tuple[Any, str]] = []
        #: slot index -> index into the deduplicated value list
        self._value_index: List[int] = []
        self._discover(root)

    # ------------------------------------------------------------ discovery
    def _discover(self, root):
        seen = set()
        stack = [root]
        slots = []
        while stack:
            obj = stack.pop()
            if obj is None or id(obj) in seen:
                continue
            seen.add(id(obj))
            for slot, prop in (("_Ad", "Ad"), ("_Pd", "P"), ("_Rd", "R")):
                if getattr(obj, slot, False) is None:
                    # lazy level pack not yet materialised: force it NOW
                    # so it becomes a bound slot — if it materialised
                    # after discovery, a later retrace would read the
                    # concrete pack through the property and bake it in
                    # as an XLA constant.  A pack failure here is
                    # tolerable only because the matrix handle's own
                    # ``_device`` slot still gets bound; log it rather
                    # than vanish.
                    try:
                        getattr(obj, prop)
                    except Exception as e:      # pragma: no cover
                        import logging
                        logging.getLogger("amgx_tpu").warning(
                            "lazy %s materialisation failed during "
                            "binding discovery: %s", prop, e)
            for k, v in list(vars(obj).items()):
                if k.startswith("_solve_fn") or k == "_bindings":
                    continue
                if _is_device_value(v):
                    slots.append((obj, k))
                elif _is_traversable(v):
                    stack.append(v)
                elif isinstance(v, (list, tuple)):
                    stack.extend(e for e in v if _is_traversable(e))
                elif isinstance(v, dict):
                    stack.extend(e for e in v.values()
                                 if _is_traversable(e))
        # dedup aliased slots by object identity of the current value
        by_id = {}
        self._slots = slots
        self._value_index = []
        for obj, k in slots:
            vid = id(getattr(obj, k))
            if vid not in by_id:
                by_id[vid] = len(by_id)
            self._value_index.append(by_id[vid])
        self._n_values = len(by_id)

    # --------------------------------------------------------- runtime API
    def collect(self) -> list:
        """The deduplicated device-value list (a pytree) to pass to jit."""
        values = [None] * self._n_values
        for (obj, k), vi in zip(self._slots, self._value_index):
            if values[vi] is None:
                values[vi] = getattr(obj, k)
        return values

    def bind(self, values: list) -> list:
        """Swap ``values`` into every slot; returns the previous values
        (in ``collect()`` layout) for restoring after the trace."""
        prev = self.collect()
        for (obj, k), vi in zip(self._slots, self._value_index):
            new = values[vi]
            if _frozen(obj):
                object.__setattr__(obj, k, new)
            else:
                setattr(obj, k, new)
        return prev

    def n_slots(self) -> int:
        return len(self._slots)

    def normalize_placement(self, mesh) -> None:
        """Distributed solves: every bound array must live on the mesh's
        device set (jit rejects mixed device sets).  Arrays on a subset —
        e.g. a consolidated coarse level replicated on one device (the
        reference 'glue' path, distributed/glue.h) — are re-placed as
        mesh-replicated; the result is written back into the slots so the
        transfer happens once, not per solve."""
        import jax.numpy  # noqa: F401  (jax imported at module top)
        from jax.sharding import NamedSharding, PartitionSpec

        mesh_devs = set(mesh.devices.flat)

        def fix_leaf(leaf):
            if not isinstance(leaf, jax.Array):
                return leaf
            if set(leaf.devices()) == mesh_devs:
                return leaf
            repl = NamedSharding(mesh, PartitionSpec())
            return jax.device_put(leaf, repl)

        values = [jax.tree_util.tree_map(fix_leaf, v)
                  for v in self.collect()]
        self.bind(values)


def _frozen(obj) -> bool:
    params = getattr(type(obj), "__dataclass_params__", None)
    return bool(params and params.frozen)


def bind_for_trace(bindings: DeviceBindings, fn):
    """Wrap ``fn(*args)`` as ``wrapped(values, *args)`` where ``values`` is
    the bindings' device pytree: during tracing the slots are temporarily
    rebound to the traced values and restored afterwards."""

    def wrapped(values, *args):
        prev = bindings.bind(values)
        try:
            return fn(*args)
        finally:
            bindings.bind(prev)

    return wrapped
