"""Chebyshev iteration solver and polynomial smoothers.

Reference: ``core/src/solvers/cheb_solver.cu`` (CHEBYSHEV with λ-estimation
modes 0-3: eigensolver / max-abs-row-sum / user-supplied,
``cheb_solver.cu:105-112``), ``chebyshev_poly.cu`` (CHEBYSHEV_POLY
polynomial smoother), ``polynomial_solver.cu`` / ``kpz_polynomial_solver.cu``.

Chebyshev smoothing is the TPU-first smoother of choice: unlike multicolor
GS/ILU it is pure SpMV + axpy (no sequential per-color sweeps), so it maps
onto the VPU with no irregular control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import blas
from ..ops.spmv import spmv
from .base import Solver, register_solver
from .jacobi import _apply_dinv, setup_dinv
from .krylov import _PrecondMixin


def _lanczos_spectrum(matvec, n: int, dtype, m: int = 40, seed: int = 0):
    """(λmin, λmax) Ritz estimates of a (self-adjoint) operator by an
    m-step Lanczos recurrence with full reorthogonalisation — the
    reference's λ-estimate mode 0 runs its eigensolver the same way
    (``cheb_solver.cu:105-112`` → AMGX_eigensolver).

    The whole recurrence runs ON DEVICE inside one jit (the Krylov basis
    is an (m+1, n) carry); only the (m,)-sized tridiagonal coefficients
    are fetched, then ``eigh`` of T on host gives the Ritz values.  For
    λmax this converges far faster than power iteration (which needs
    O(1/gap) iterations and approaches from below — a fixed 30-step run
    was >5% off on clustered spectra); λmin comes from the same T, which
    power iteration cannot give at all."""
    import functools

    m = int(min(m, max(2, n - 1)))
    x0 = np.random.default_rng(seed).standard_normal(n)

    @jax.jit
    def run(v0):
        V = jnp.zeros((m + 1, n), dtype)
        V = V.at[0].set(v0 / jnp.maximum(blas.nrm2(v0), 1e-30))
        alpha = jnp.zeros((m,), dtype)
        beta = jnp.zeros((m,), dtype)

        def body(j, carry):
            V, alpha, beta = carry
            w = matvec(V[j])
            a = jnp.vdot(V[j], w).real.astype(dtype)
            w = w - a * V[j]
            # full reorthogonalisation against the built basis (rows
            # > j are zero, so the masked projection is exact)
            proj = V @ w
            w = w - V.T @ proj
            b = blas.nrm2(w)
            V = V.at[j + 1].set(
                jnp.where(b > 1e-30, w / jnp.maximum(b, 1e-30), 0.0))
            return V, alpha.at[j].set(a), beta.at[j].set(b)

        V, alpha, beta = jax.lax.fori_loop(0, m, body,
                                           (V, alpha, beta))
        return alpha, beta

    alpha, beta = jax.device_get(run(jnp.asarray(x0, dtype)))
    T = np.diag(alpha.astype(np.float64))
    off = beta[:-1].astype(np.float64)
    T += np.diag(off, 1) + np.diag(off, -1)
    ev = np.linalg.eigvalsh(T)
    return float(ev[0]), float(ev[-1])


def _power_iteration_lambda_max(Ad, dinv, n_iters=20, seed=0):
    """Estimate λmax of D⁻¹A by power iteration (device, fixed iterations)."""
    from ..core.precision import compute_dtype
    n = Ad.n_rows * Ad.block_dim
    dt = compute_dtype(np.dtype(Ad.dtype))   # estimate at f32+, always
    x = jnp.asarray(np.random.default_rng(seed).standard_normal(n),
                    dtype=dt)

    def body(i, carry):
        x, lam = carry
        y = _apply_dinv(dinv, spmv(Ad, x))
        nrm = blas.nrm2(y)
        lam = nrm / jnp.maximum(blas.nrm2(x), 1e-30)
        return y / jnp.maximum(nrm, 1e-30), lam

    _, lam = jax.lax.fori_loop(0, n_iters, body,
                               (x, jnp.asarray(1.0, dt)))
    return lam


@register_solver("CHEBYSHEV")
class ChebyshevSolver(_PrecondMixin, Solver):
    """Chebyshev iteration on the preconditioned operator M⁻¹A over
    [λmin, λmax] (reference ``cheb_solver.cu``)."""

    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.lambda_mode = int(cfg.get("chebyshev_lambda_estimate_mode",
                                       scope))
        self.user_max = float(cfg.get("cheby_max_lambda", scope))
        self.user_min = float(cfg.get("cheby_min_lambda", scope))

    def _gershgorin_lmax(self) -> float:
        """Max abs row sum bound (reference compute_eigenmax_estimate)."""
        if self.A is not None and not (self.A.host is None
                                       and self.A.blocks is not None):
            csr = self.A.scalar_csr()
            return float(np.abs(csr).sum(axis=1).max())
        if self.A is not None:
            return max(float(np.abs(b).sum(axis=1).max())
                       for b in self.A.blocks)
        if self.Ad.block_dim == 1 and self.Ad.fmt in ("dia", "ell", "csr"):
            from ..ops.spmv import abs_rowsum
            return float(jnp.max(abs_rowsum(self.Ad)))
        return float(jnp.max(jnp.sum(
            jnp.abs(self.Ad.vals),
            axis=tuple(range(1, self.Ad.vals.ndim)))))

    def solver_setup(self):
        self._setup_preconditioner(True)
        # reference mode semantics (cheb_solver.cu:179-242):
        #   0:   eigensolver estimate of BOTH spectrum ends of M⁻¹A
        #        (Lanczos Ritz values — cheb_solver.cu:105-112)
        #   1:   eigensolver λmax, λmin = λmax/8
        #   2:   Gershgorin λmax when unpreconditioned; with a
        #        preconditioner the reference ASSUMES the spectrum shrank
        #        to ≤ 0.9 — here λmax(M⁻¹A) is measured instead (L1-Jacobi
        #        preconditioned operators sit just under 1.0, where the
        #        0.9 guess makes the smoother amplify the top modes)
        #   3:   Gershgorin when unpreconditioned, else USER λ values
        no_pre = (self.preconditioner is None
                  or self.preconditioner.config_name == "NOSOLVER")
        if self.lambda_mode == 0:
            # spectrum estimation always runs at f32+ — an 8-bit
            # mantissa Lanczos recurrence would hand the smoother a
            # garbage interval (mixed precision: bf16 is storage only)
            from ..core.precision import compute_dtype
            lmin_r, lmax = _lanczos_spectrum(
                lambda v: self._apply_M(spmv(self.Ad, v)),
                self.Ad.n, compute_dtype(np.dtype(self.Ad.dtype)))
            if lmax <= 0:
                # degenerate Lanczos estimate (indefinite/garbage Ritz
                # values): the old fallback set lmin = 0.125·lmax >
                # lmax — an INVERTED Chebyshev interval that turns the
                # smoother into an amplifier.  Re-estimate on the
                # power/Gershgorin path instead, and refuse outright if
                # the spectrum top still comes out non-positive.
                lmax = self._power_lmax() if not no_pre \
                    else self._gershgorin_lmax()
                if lmax <= 0:
                    from ..errors import BadParametersError
                    raise BadParametersError(
                        "CHEBYSHEV: non-positive spectrum-top estimate "
                        "(Lanczos and power/Gershgorin both ≤ 0) — the "
                        "operator is not SPD-like; choose another "
                        "smoother or supply cheby_max/min_lambda")
                lmin = 0.125 * lmax
            else:
                # Ritz λmin approaches from above; keep it positive and
                # below the smoothing band for safety
                lmin = min(max(lmin_r, 1e-12), 0.5 * lmax)
        elif self.lambda_mode == 1 or \
                (self.lambda_mode == 2 and not no_pre):
            lmax = self._power_lmax()
            lmin = 0.125 * lmax
        elif self.lambda_mode == 2:
            lmax = self._gershgorin_lmax()
            lmin = 0.125 * lmax
        elif self.lambda_mode == 3:
            if no_pre:
                lmax = self._gershgorin_lmax()
                lmin = 0.125 * lmax
            else:
                lmax, lmin = self.user_max, self.user_min
        else:
            lmax, lmin = self.user_max, self.user_min
        self.lmax = lmax * 1.05  # safety margin, as usual for Chebyshev
        self.lmin = lmin

    def _power_lmax(self) -> float:
        """λmax(M⁻¹A) by power iteration on the preconditioned operator.

        Power iteration approaches λmax FROM BELOW, and an interval that
        misses the top of the spectrum turns the Chebyshev smoother into
        an amplifier — so the estimate gets extra iterations plus a
        safety factor beyond the usual 1.05 (a slightly generous interval
        only costs a little smoothing efficiency)."""
        from ..core.precision import compute_dtype
        n = self.Ad.n
        dt = compute_dtype(np.dtype(self.Ad.dtype))
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal(n), dtype=dt)
        lam = jnp.asarray(1.0, dt)
        for _ in range(30):
            y = self._apply_M(spmv(self.Ad, x))
            nrm = blas.nrm2(y)
            lam = nrm / jnp.maximum(blas.nrm2(x), 1e-30)
            x = y / jnp.maximum(nrm, 1e-30)
        return 1.1 * float(lam)

    def solve_init(self, b, x):
        r = b - spmv(self.Ad, x)
        d = jnp.zeros_like(b)
        rho = jnp.asarray(0.0, b.dtype)
        return (r, d, rho)

    def solve_iteration(self, b, x, state, iter_idx):
        r, d, rho = state
        theta = 0.5 * (self.lmax + self.lmin)
        delta = max(0.5 * (self.lmax - self.lmin), 1e-30)
        sigma = theta / delta
        z = self._apply_M(r)

        def first(_):
            return z / theta, jnp.asarray(1.0 / sigma, b.dtype)

        def later(_):
            rho_new = 1.0 / (2.0 * sigma - rho)
            d_new = rho_new * rho * d + (2.0 * rho_new / delta) * z
            return d_new, rho_new.astype(b.dtype)

        d_new, rho_new = jax.lax.cond(iter_idx == 0, first, later, None)
        x = x + d_new
        r = r - spmv(self.Ad, d_new)
        return x, (r, d_new, rho_new)

    def residual_norm_estimate(self, b, x, state):
        r = state[0]
        return blas.norm(r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)


@register_solver("CHEBYSHEV_POLY")
class ChebyshevPolySmoother(Solver):
    """Chebyshev polynomial smoother on the Jacobi-preconditioned operator
    D⁻¹A (reference ``chebyshev_poly.cu``): one 'iteration' applies a
    degree-``chebyshev_polynomial_order`` Chebyshev polynomial."""

    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = int(cfg.get("chebyshev_polynomial_order", scope))

    def solver_setup(self):
        self.dinv = setup_dinv(self)
        lmax = float(_power_iteration_lambda_max(self.Ad, self.dinv))
        self.lmax = 1.05 * lmax
        self.lmin = self.lmax / 30.0  # standard smoothing interval upper part

    def solve_iteration(self, b, x, state, iter_idx):
        # classic three-term Chebyshev smoothing (Adams et al.)
        theta = 0.5 * (self.lmax + self.lmin)
        delta = 0.5 * (self.lmax - self.lmin)
        sigma = theta / delta
        rho = 1.0 / sigma
        r = b - spmv(self.Ad, x)
        d = _apply_dinv(self.dinv, r) / theta
        x = x + d
        for _ in range(self.order - 1):
            rho_new = 1.0 / (2.0 * sigma - rho)
            r = b - spmv(self.Ad, x)
            d = rho_new * rho * d + 2.0 * rho_new / delta * _apply_dinv(
                self.dinv, r)
            x = x + d
            rho = rho_new
        return x, state


@register_solver("POLYNOMIAL")
class PolynomialSmoother(Solver):
    """Neumann-series polynomial smoother (reference
    ``polynomial_solver.cu``): x += Σ_k (I − D⁻¹A)^k D⁻¹ r."""

    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.mu = int(cfg.get("kpz_mu", scope))

    def solver_setup(self):
        self.dinv = setup_dinv(self)

    def solve_iteration(self, b, x, state, iter_idx):
        r = b - spmv(self.Ad, x)
        z = _apply_dinv(self.dinv, r)
        acc = z
        for _ in range(self.mu - 1):
            z = z - _apply_dinv(self.dinv, spmv(self.Ad, z))
            acc = acc + z
        return x + acc, state


@register_solver("KPZ_POLYNOMIAL")
class KPZPolynomialSmoother(ChebyshevPolySmoother):
    """KPZ polynomial smoother (reference ``kpz_polynomial_solver.cu``) —
    implemented as a Chebyshev polynomial of order ``kpz_order`` on D⁻¹A."""

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = int(cfg.get("kpz_order", scope))
