"""Krylov solvers: CG, PCG, PCGF, BiCGStab, PBiCGStab, GMRES, FGMRES.

Reference: ``core/src/solvers/{cg,pcg,pcgf,bicgstab,pbicgstab,gmres,
fgmres}_solver.cu``.  Every solver supports an optional nested
preconditioner allocated from its config scope (reference
``fgmres_solver.cu:243-253``), traced inline into the iteration.

TPU design notes:
* (F)GMRES orthogonalisation is two-pass classical Gram-Schmidt (CGS2) —
  two (m+1,n)×(n,) matmuls per iteration instead of the reference's
  sequential Givens-on-Hessenberg MGS loop; numerically as robust as MGS in
  practice and MXU-friendly.  The Givens QR of the Hessenberg column
  (``fgmres_solver.cu:268-273``) is kept, as a sequential scan over the
  (tiny) restart dimension.
* The Krylov basis is a fixed (m+1, n) buffer so the whole solve jits with
  static shapes; restart position is ``iter % m`` computed in-graph.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..errors import BREAKDOWN_INDEFINITE, BREAKDOWN_KRYLOV
from ..ops import blas
from ..ops.spmv import spmv
from ..telemetry import scopes as _tscopes
from .base import Solver, SolverFactory, register_solver


def _cg_breakdown(brk, rz, pq):
    """In-loop CG breakdown guard (reference: the reference detects
    these only post-hoc; the TPU loop flags them ON DEVICE so the
    convergence reduction stops within an iteration of the event):

    * ``rho == 0`` or ``pAp == 0`` — z ⊥ r / A-null search direction,
      the Krylov recursion cannot extend (``BREAKDOWN_KRYLOV``).  This
      code is PROVISIONAL: at true convergence these scalars also
      vanish, so the base monitor block discards it when the monitored
      residual is dead (``Solver.breakdown_code`` contract) — which is
      what lets the guard cost ZERO extra vector work per iteration
      (the old residual-alive dot duplicated the carried norm);
    * ``Re(pAp) < 0`` — the operator (or preconditioner) is not SPD
      (``BREAKDOWN_INDEFINITE``).

    The FIRST code sticks; 0 stays healthy.  NaN comparisons are False,
    so a poisoned state falls through to the monitor's non-finite
    check."""
    kry = (rz == 0) | (pq == 0)
    indef = jnp.real(pq) < 0
    code = jnp.where(indef, BREAKDOWN_INDEFINITE,
                     jnp.where(kry, BREAKDOWN_KRYLOV, 0)) \
        .astype(jnp.int32)
    return jnp.where(brk == 0, code, brk)


class _PrecondMixin:
    """Allocates the nested preconditioner from config scope."""

    def _setup_preconditioner(self, use_precond: bool):
        existing = getattr(self, "preconditioner", None)
        if existing is not None and use_precond \
                and getattr(self, "_numeric_resetup", False):
            # numeric re-setup ONLY: reuse the preconditioner INSTANCE so
            # its hierarchy structure-reuse and compiled executables
            # survive; a plain setup() re-allocates it fresh
            a = self.A if self.A is not None else self.Ad
            existing.resetup(a)
            return
        self.preconditioner: Optional[Solver] = None
        if use_precond and self.cfg.has("preconditioner", self.scope):
            self.preconditioner = SolverFactory.allocate(
                self.cfg, self.scope, "preconditioner")
            a = self.A if self.A is not None else self.Ad
            self.preconditioner.setup(a)

    def _apply_M(self, r):
        if self.preconditioner is None:
            return r
        with _tscopes.scope("krylov", "precond"):
            return self.preconditioner.apply(r)


class _CGState(NamedTuple):
    r: jax.Array
    p: jax.Array
    rz: jax.Array
    brk: jax.Array      # int32 breakdown code (errors.BREAKDOWN_*)


class _CACGState(NamedTuple):
    """Chronopoulos–Gear single-reduction CG state.  Invariants:
    u = M·r, w = A·u, s = A·p; gamma = (r,u), delta = (w,u) for the
    CURRENT vectors (the fused reduction runs at the end of the
    iteration, so the carried scalars are always up to date)."""
    r: jax.Array
    u: jax.Array
    w: jax.Array
    p: jax.Array
    s: jax.Array
    gamma: jax.Array        # (r, u) of current state
    gamma_prev: jax.Array   # previous gamma (for beta)
    delta: jax.Array        # (w, u) of current state
    alpha_prev: jax.Array   # previous step length (for the alpha recurrence)
    rr: jax.Array           # raw norm accumulators of current r, (k,) real
    brk: jax.Array          # int32 breakdown code (errors.BREAKDOWN_*)


class _PipeCGState(NamedTuple):
    """Ghysels–Vanroose pipelined CG state.  Extra auxiliaries keep
    q = M·s and z = A·q so the single fused reduction at the TOP of an
    iteration is independent of the m = M·w / n = A·m applications that
    follow — XLA overlaps the collective with the SpMV + precond.  The
    carried ``rr`` is therefore the norm of the INCOMING residual (lags
    one iteration — the documented price of the overlap)."""
    r: jax.Array
    u: jax.Array
    w: jax.Array
    p: jax.Array
    s: jax.Array
    q: jax.Array
    z: jax.Array
    gamma_prev: jax.Array
    alpha_prev: jax.Array
    rr: jax.Array
    brk: jax.Array


@register_solver("CG")
class CGSolver(Solver):
    """Plain conjugate gradient (reference ``cg_solver.cu``).

    The ``krylov_comm`` knob (or a ``forced_comm`` subclass override)
    selects the communication variant: CLASSIC (two blocking reductions
    per iteration), CA (Chronopoulos–Gear, ONE fused reduction at the end
    of the iteration) or PIPELINED (Ghysels–Vanroose, one fused reduction
    overlapped with the next SpMV + preconditioner apply).  Both CA modes
    recompute the TRUE residual every ``ca_residual_replace`` iterations
    so recurrence drift never fakes convergence."""

    use_preconditioner = False
    forced_comm: Optional[str] = None

    def solver_setup(self):
        if getattr(self, "use_preconditioner", False):
            self._setup_preconditioner(True)

    def _M(self, r):
        return r

    # ---------------------------------------------- communication mode
    def _comm_mode(self) -> str:
        if getattr(self, "_force_krylov_classic", False):
            return "CLASSIC"        # recovery-ladder CA→CLASSIC fallback
        mode = self.forced_comm or self.krylov_comm
        if mode != "CLASSIC" and self.norm_type == blas.NORM_LMAX:
            # LMAX needs a max-reduce and cannot ride the fused psum
            return "CLASSIC"
        return mode

    def _fused_scalars(self, r, u, w):
        """gamma = (r,u), delta = (w,u) and the monitor-norm accumulators
        of r, all from ONE stacked reduction."""
        with _tscopes.scope("krylov", "reduce"):
            terms = [jnp.conj(r) * u, jnp.conj(w) * u]
            terms += blas.norm_terms(r, self.norm_type, self.Ad.block_dim,
                                     self.use_scalar_norm)
            acc = blas.fused_reduce(terms)
            return acc[0], acc[1], jnp.real(acc[2:])

    # ------------------------------------------------------------ init
    def solve_init(self, b, x):
        mode = self._comm_mode()
        if mode == "CLASSIC":
            r = b - spmv(self.Ad, x)
            z = self._M(r)
            rz = blas.dot(r, z)
            return _CGState(r=r, p=z, rz=rz,
                            brk=jnp.zeros((), jnp.int32))
        r = b - spmv(self.Ad, x)
        u = self._M(r)
        w = spmv(self.Ad, u)
        gamma, delta, rr = self._fused_scalars(r, u, w)
        one = jnp.ones((), gamma.dtype)
        zero_v = jnp.zeros_like(r)
        brk = jnp.zeros((), jnp.int32)
        if mode == "CA":
            return _CACGState(r=r, u=u, w=w, p=zero_v, s=zero_v,
                              gamma=gamma, gamma_prev=one, delta=delta,
                              alpha_prev=one, rr=rr, brk=brk)
        return _PipeCGState(r=r, u=u, w=w, p=zero_v, s=zero_v,
                            q=zero_v, z=zero_v, gamma_prev=one,
                            alpha_prev=one, rr=rr, brk=brk)

    # -------------------------------------------------------- iteration
    def solve_iteration(self, b, x, state, iter_idx):
        mode = self._comm_mode()
        if mode == "CA":
            return self._ca_iteration(b, x, state, iter_idx)
        if mode == "PIPELINED":
            return self._pipe_iteration(b, x, state, iter_idx)
        r, p, rz, brk = state
        # breakdown guards: incoming rho collapse / new pAp sign
        # (provisional — the base monitor block validates against the
        # carried residual norm; see _cg_breakdown)
        q = spmv(self.Ad, p)
        pq = blas.dot(p, q)
        brk = _cg_breakdown(brk, rz, pq)
        alpha = jnp.where(pq != 0, rz / jnp.where(pq == 0, 1.0, pq), 0.0)
        x = x + alpha * p
        r = r - alpha * q
        z = self._M(r)
        rz_new = blas.dot(r, z)
        beta = jnp.where(rz != 0, rz_new / jnp.where(rz == 0, 1.0, rz), 0.0)
        p = z + beta * p
        return x, _CGState(r=r, p=p, rz=rz_new, brk=brk)

    def _cg_scalar_step(self, gamma, gamma_prev, delta, alpha_prev, brk,
                        iter_idx):
        """Shared CA/pipelined scalar recurrence:
        beta_i = gamma_i/gamma_{i-1} (0 at i=0),
        pAp    = delta_i − beta_i·gamma_i/alpha_{i-1}  (== (p_i, A p_i)),
        alpha_i = gamma_i/pAp — with the same breakdown guards the
        classic loop applies to (rho, pAp)."""
        first = iter_idx == 0
        beta = jnp.where(
            first, 0.0,
            gamma / jnp.where(gamma_prev == 0, 1.0, gamma_prev))
        pap = delta - beta * gamma \
            / jnp.where(alpha_prev == 0, 1.0, alpha_prev)
        # gamma_{i-1} == 0 is a true Krylov breakdown the recurrence
        # would otherwise divide through (the classic loop sees it as
        # rho == 0 one iteration earlier) — flag it BEFORE the generic
        # guard so the code is deterministic under krylov_zero injection
        brk = jnp.where((brk == 0) & ~first & (gamma_prev == 0),
                        jnp.asarray(BREAKDOWN_KRYLOV, jnp.int32), brk)
        brk = _cg_breakdown(brk, gamma, pap)
        alpha = jnp.where(pap != 0,
                          gamma / jnp.where(pap == 0, 1.0, pap), 0.0)
        return beta, alpha, brk

    def _ca_iteration(self, b, x, state, iter_idx):
        (r, u, w, p, s, gamma, gamma_prev, delta, alpha_prev, rr,
         brk) = state
        rep = self.ca_residual_replace
        if rep > 0:
            do_rep = (iter_idx > 0) & (jnp.mod(iter_idx, rep) == 0)

            def replace(_):
                # true-residual replacement: recompute r, u = M r,
                # w = A u and s = A p from scratch, plus the carried
                # scalars, so accumulated recurrence drift is flushed
                with blas.replacement_scope():
                    r_t = b - spmv(self.Ad, x)
                    u_t = self._M(r_t)
                    w_t = spmv(self.Ad, u_t)
                    s_t = spmv(self.Ad, p)
                    g_t, d_t, rr_t = self._fused_scalars(r_t, u_t, w_t)
                return r_t, u_t, w_t, s_t, g_t, d_t, rr_t

            def keep(_):
                return r, u, w, s, gamma, delta, rr

            r, u, w, s, gamma, delta, rr = \
                jax.lax.cond(do_rep, replace, keep, None)
        beta, alpha, brk = self._cg_scalar_step(
            gamma, gamma_prev, delta, alpha_prev, brk, iter_idx)
        p = u + beta * p
        s = w + beta * s        # s = A p by linearity
        x = x + alpha * p
        r = r - alpha * s
        u = self._M(r)
        w = spmv(self.Ad, u)
        gamma_new, delta_new, rr_new = self._fused_scalars(r, u, w)
        return x, _CACGState(r=r, u=u, w=w, p=p, s=s, gamma=gamma_new,
                             gamma_prev=gamma, delta=delta_new,
                             alpha_prev=alpha, rr=rr_new, brk=brk)

    def _pipe_iteration(self, b, x, state, iter_idx):
        (r, u, w, p, s, q, z, gamma_prev, alpha_prev, rr, brk) = state
        rep = self.ca_residual_replace
        if rep > 0:
            do_rep = (iter_idx > 0) & (jnp.mod(iter_idx, rep) == 0)

            def replace(_):
                with blas.replacement_scope():
                    r_t = b - spmv(self.Ad, x)
                    u_t = self._M(r_t)
                    w_t = spmv(self.Ad, u_t)
                    s_t = spmv(self.Ad, p)
                    q_t = self._M(s_t)
                    z_t = spmv(self.Ad, q_t)
                return r_t, u_t, w_t, s_t, q_t, z_t

            def keep(_):
                return r, u, w, s, q, z

            r, u, w, s, q, z = jax.lax.cond(do_rep, replace, keep, None)
        # ONE fused reduction on the incoming state; m = M·w and
        # n = A·m below do not depend on it, so the collective's latency
        # hides behind the precond apply + SpMV
        gamma, delta, rr_new = self._fused_scalars(r, u, w)
        m_vec = self._M(w)
        n_vec = spmv(self.Ad, m_vec)
        beta, alpha, brk = self._cg_scalar_step(
            gamma, gamma_prev, delta, alpha_prev, brk, iter_idx)
        z = n_vec + beta * z    # z = A q
        q = m_vec + beta * q    # q = M s
        s = w + beta * s        # s = A p
        p = u + beta * p
        x = x + alpha * p
        r = r - alpha * s
        u = u - alpha * q
        w = w - alpha * z
        return x, _PipeCGState(r=r, u=u, w=w, p=p, s=s, q=q, z=z,
                               gamma_prev=gamma, alpha_prev=alpha,
                               rr=rr_new, brk=brk)

    def residual_norm_estimate(self, b, x, state):
        if isinstance(state, (_CACGState, _PipeCGState)):
            # the fused reduction already carried the norm accumulators —
            # finishing them is collective-free
            return blas.finish_norm(state.rr, self.norm_type,
                                    state.r.shape[0], self.Ad.block_dim,
                                    self.use_scalar_norm)
        return blas.norm(state.r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)


@register_solver("PCG")
class PCGSolver(_PrecondMixin, CGSolver):
    """Preconditioned CG (reference ``pcg_solver.cu``)."""

    use_preconditioner = True

    def _M(self, r):
        return self._apply_M(r)


@register_solver("PCG_CA")
class PCGCASolver(PCGSolver):
    """Single-reduction (Chronopoulos–Gear) PCG: ``PCG`` with
    ``krylov_comm=CA`` baked in."""

    forced_comm = "CA"


@register_solver("PCG_PIPE")
class PCGPipeSolver(PCGSolver):
    """Pipelined (Ghysels–Vanroose) PCG: ``PCG`` with
    ``krylov_comm=PIPELINED`` baked in."""

    forced_comm = "PIPELINED"


class _PCGFState(NamedTuple):
    r: jax.Array
    z: jax.Array
    p: jax.Array
    rz: jax.Array
    brk: jax.Array      # int32 breakdown code (errors.BREAKDOWN_*)


@register_solver("PCGF")
class PCGFSolver(_PrecondMixin, Solver):
    """Flexible PCG (reference ``pcgf_solver.cu``): Polak–Ribière β
    ⟨z_new, r_new − r_old⟩/⟨z_old, r_old⟩ tolerates a varying
    preconditioner (e.g. AMG with non-stationary smoothing)."""

    def solver_setup(self):
        self._setup_preconditioner(True)

    def solve_init(self, b, x):
        r = b - spmv(self.Ad, x)
        z = self._apply_M(r)
        rz = blas.dot(r, z)
        return _PCGFState(r=r, z=z, p=z, rz=rz,
                          brk=jnp.zeros((), jnp.int32))

    def solve_iteration(self, b, x, state, iter_idx):
        r, z, p, rz, brk = state
        q = spmv(self.Ad, p)
        pq = blas.dot(p, q)
        brk = _cg_breakdown(brk, rz, pq)
        alpha = jnp.where(pq != 0, rz / jnp.where(pq == 0, 1.0, pq), 0.0)
        x = x + alpha * p
        r_new = r - alpha * q
        z_new = self._apply_M(r_new)
        # flexible beta
        rz_new = blas.dot(r_new, z_new)
        beta_num = rz_new - blas.dot(r, z_new)
        beta = jnp.where(rz != 0, beta_num / jnp.where(rz == 0, 1.0, rz), 0.0)
        p = z_new + beta * p
        return x, _PCGFState(r=r_new, z=z_new, p=p, rz=rz_new, brk=brk)

    def residual_norm_estimate(self, b, x, state):
        return blas.norm(state.r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)


class _BiCGStabState(NamedTuple):
    r: jax.Array
    r_star: jax.Array
    p: jax.Array
    v: jax.Array
    rho: jax.Array
    alpha: jax.Array
    omega: jax.Array
    brk: jax.Array      # int32 breakdown code (errors.BREAKDOWN_*)


class _BiCGStabBase(Solver):
    """BiCGStab skeleton; ``_M`` hooks preconditioning (right)."""

    def _M(self, r):
        return r

    def solve_init(self, b, x):
        r = b - spmv(self.Ad, x)
        one = jnp.asarray(1.0, r.dtype)
        return _BiCGStabState(r=r, r_star=r, p=jnp.zeros_like(r),
                              v=jnp.zeros_like(r), rho=one, alpha=one,
                              omega=one, brk=jnp.zeros((), jnp.int32))

    def solve_iteration(self, b, x, state, iter_idx):
        r, r_star, p, v, rho, alpha, omega, brk = state
        rho_new = blas.dot(r_star, r)
        # the classic BiCGStab serious breakdown: r ⊥ r* — provisional
        # (the base monitor block discards it when the residual is
        # dead, i.e. ordinary convergence)
        brk = jnp.where((brk == 0) & (rho_new == 0),
                        jnp.asarray(BREAKDOWN_KRYLOV, jnp.int32), brk)
        safe = lambda d: jnp.where(d == 0, 1.0, d)
        beta = (rho_new / safe(rho)) * (alpha / safe(omega))
        p = r + beta * (p - omega * v)
        p_hat = self._M(p)
        v = spmv(self.Ad, p_hat)
        alpha = rho_new / safe(blas.dot(r_star, v))
        s = r - alpha * v
        s_hat = self._M(s)
        t = spmv(self.Ad, s_hat)
        tt = blas.dot(t, t)
        omega = jnp.where(tt != 0, blas.dot(t, s) / safe(tt), 0.0)
        x = x + alpha * p_hat + omega * s_hat
        r = s - omega * t
        return x, _BiCGStabState(r=r, r_star=r_star, p=p, v=v, rho=rho_new,
                                 alpha=alpha, omega=omega, brk=brk)

    def residual_norm_estimate(self, b, x, state):
        return blas.norm(state.r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)


@register_solver("BICGSTAB")
class BiCGStabSolver(_BiCGStabBase):
    """Reference ``bicgstab_solver.cu``."""


@register_solver("PBICGSTAB")
class PBiCGStabSolver(_PrecondMixin, _BiCGStabBase):
    """Right-preconditioned BiCGStab (reference ``pbicgstab_solver.cu``)."""

    def solver_setup(self):
        self._setup_preconditioner(True)

    def _M(self, r):
        return self._apply_M(r)


class _GMRESState(NamedTuple):
    V: jax.Array       # (m+1, n) Krylov basis
    Z: jax.Array       # (m, n) preconditioned basis (FGMRES) or (1,1) dummy
    R: jax.Array       # (m+1, m) triangularised Hessenberg
    g: jax.Array       # (m+1,) LS right-hand side
    cs: jax.Array      # (m,) Givens cosines
    sn: jax.Array      # (m,) Givens sines
    x_base: jax.Array  # x at cycle start
    quasi_res: jax.Array
    j: jax.Array       # current cycle position (last completed column)


class _GMRESBase(Solver):
    flexible = False

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.restart = int(cfg.get("gmres_n_restart", scope))
        krylov_dim = int(cfg.get("gmres_krylov_dim", scope))
        if krylov_dim > 0:
            self.restart = min(self.restart, krylov_dim)

    def solver_setup(self):
        self._setup_preconditioner(True)

    def _M(self, r):
        return self._apply_M(r)

    def _comm_mode(self) -> str:
        """CA/PIPELINED both select the fused Arnoldi pass (the second
        CGS2 projection and the normalisation norm share one stacked
        collective); CLASSIC keeps the three reductions per column."""
        if getattr(self, "_force_krylov_classic", False):
            return "CLASSIC"
        return self.krylov_comm

    def solve_init(self, b, x):
        m, n = self.restart, b.shape[0]
        dt = b.dtype
        r = b - spmv(self.Ad, x)
        beta = blas.nrm2(r)
        V = jnp.zeros((m + 1, n), dt)
        V = V.at[0].set(jnp.where(beta > 0, r / jnp.where(beta == 0, 1, beta),
                                  0.0))
        Z = jnp.zeros((m, n), dt) if self.flexible else jnp.zeros((1, 1), dt)
        g = jnp.zeros((m + 1,), dt).at[0].set(beta)
        return _GMRESState(
            V=V, Z=Z, R=jnp.zeros((m + 1, m), dt), g=g,
            cs=jnp.zeros((m,), dt), sn=jnp.zeros((m,), dt),
            x_base=x, quasi_res=jnp.abs(beta),
            j=jnp.asarray(-1, jnp.int32))

    def _solve_ls_and_update(self, state, j):
        """x = x_base + basis · y where R[:j+1,:j+1] y = g[:j+1].

        Unused columns are masked to identity so the fixed-size triangular
        solve is exact for any cycle position j.
        """
        m = self.restart
        with _tscopes.scope("krylov", "update"):
            R = state.R[:m, :m]
            mask = jnp.arange(m) > j
            R = jnp.where(mask[None, :] | mask[:, None], 0.0, R)
            R = R + jnp.diag(jnp.where(mask, 1.0, 0.0))
            g = jnp.where(jnp.arange(m) <= j, state.g[:m], 0.0)
            y = jax.scipy.linalg.solve_triangular(R, g, lower=False)
            if self.flexible:
                dx = state.Z.T @ y
            else:
                y = jnp.where(jnp.arange(m) <= j, y, 0.0)
                dx = self._M(state.V[:m].T @ y)
            return state.x_base + dx

    def solve_iteration(self, b, x, state, iter_idx):
        m = self.restart
        j = jnp.mod(iter_idx, m)
        restart = (j == 0) & (iter_idx > 0)

        # --- restart: recompute the true residual and restart the basis.
        # Only the (n,)-sized pieces ride the branch — rebuilding the whole
        # (m+1, n) state under a cond made XLA materialise a copy of the
        # Krylov basis EVERY iteration (measured ~3× the per-iteration
        # cost at 256³); stale basis rows are instead neutralised by the
        # row masks on the CGS2 coefficients below.
        def fresh_v0(_):
            # ledger: the restart recompute is amortised over the cycle,
            # not part of the steady-state per-iteration profile
            with blas.replacement_scope():
                r = b - spmv(self.Ad, x)
                beta = blas.nrm2(r)
            v0 = jnp.where(beta > 0, r / jnp.where(beta == 0, 1, beta), 0.0)
            # g rides in the basis dtype (complex modes store the real
            # |r| as a complex scalar)
            return v0, jnp.abs(beta).astype(state.g.dtype)

        def keep_v0(_):
            return state.V[0], state.g[0]

        v0, beta = jax.lax.cond(restart, fresh_v0, keep_v0, None)
        V = state.V.at[0].set(v0)
        x_base = jnp.where(restart, x, state.x_base)
        zeros_m = jnp.zeros((m,), V.dtype)
        g = jnp.where(restart, jnp.zeros((m + 1,), V.dtype).at[0].set(beta),
                      state.g)
        cs = jnp.where(restart, zeros_m, state.cs)
        sn = jnp.where(restart, zeros_m, state.sn)
        state = state._replace(V=V, g=g, cs=cs, sn=sn, x_base=x_base)

        # --- Arnoldi step with CGS2 orthogonalisation; rows > j may hold
        # stale directions from the previous cycle — mask their
        # coefficients instead of zeroing the basis storage
        row_ok = (jnp.arange(m + 1) <= j).astype(state.V.real.dtype)
        v_j = state.V[j]
        z_j = self._M(v_j)
        with _tscopes.scope("krylov", "arnoldi"):
            w = spmv(self.Ad, z_j)
            # projections h_i = <v_i, w> are CONJUGATED (complex modes:
            # jnp.conj of a real array is a no-op XLA folds away)
            h1 = blas.gram_dots(state.V, w, row_ok)
            w = w - state.V.T @ h1
            if self._comm_mode() != "CLASSIC":
                # fused Arnoldi: the second CGS2 pass and ‖w‖² ride ONE
                # stacked matmul (3 → 2 collectives per column); after the
                # first pass h2 is O(ε)·‖w‖, so the Pythagorean downdate
                # ‖w − V·h2‖² = ‖w‖² − ‖h2‖² loses no accuracy in practice
                h2, ww = blas.gram_dots_with_norm(state.V, w, row_ok)
                w = w - state.V.T @ h2
                h_next = jnp.sqrt(jnp.maximum(
                    ww - jnp.sum(jnp.abs(h2) ** 2), 0.0))
            else:
                h2 = blas.gram_dots(state.V, w, row_ok)
                w = w - state.V.T @ h2
                h_next = blas.nrm2(w)
            hcol = h1 + h2              # (m+1,)
            V = state.V.at[j + 1].set(
                jnp.where(h_next > 0,
                          w / jnp.where(h_next == 0, 1, h_next), 0.0))
            hcol = hcol.at[j + 1].set(h_next)
        Z = state.Z.at[j].set(z_j) if self.flexible else state.Z

        # --- apply previous Givens rotations to the new column
        # (sequential).  The unitary form G = [[c̄, s̄], [−s, c]] with
        # c = a/r, s = b/r (r = √(|a|²+|b|²)) maps (a, b) → (r, 0) for
        # real AND complex entries alike (conj on reals folds away).
        def rot_body(i, hc):
            ci, si = state.cs[i], state.sn[i]
            hi, hi1 = hc[i], hc[i + 1]
            active = i < j
            new_i = jnp.where(active,
                              jnp.conj(ci) * hi + jnp.conj(si) * hi1, hi)
            new_i1 = jnp.where(active, -si * hi + ci * hi1, hi1)
            return hc.at[i].set(new_i).at[i + 1].set(new_i1)

        with _tscopes.scope("krylov", "givens"):
            hcol = jax.lax.fori_loop(0, m, rot_body, hcol)

            # --- new Givens rotation zeroing h[j+1]
            hj, hj1 = hcol[j], hcol[j + 1]
            denom = jnp.sqrt(jnp.abs(hj) ** 2 + jnp.abs(hj1) ** 2)
            safe = jnp.where(denom == 0, 1.0, denom)
            c = jnp.where(denom == 0, jnp.ones((), hcol.dtype), hj / safe)
            s = jnp.where(denom == 0, jnp.zeros((), hcol.dtype), hj1 / safe)
            hcol = hcol.at[j].set(jnp.conj(c) * hj + jnp.conj(s) * hj1) \
                       .at[j + 1].set(0.0)
            cs = state.cs.at[j].set(c)
            sn = state.sn.at[j].set(s)
            gj = state.g[j]
            g = state.g.at[j].set(jnp.conj(c) * gj).at[j + 1].set(-s * gj)
            R = state.R.at[:, j].set(hcol)
            quasi = jnp.abs(g[j + 1])

        new_state = _GMRESState(V=V, Z=Z, R=R, g=g, cs=cs, sn=sn,
                                x_base=state.x_base, quasi_res=quasi,
                                j=j.astype(jnp.int32))

        # --- end of cycle: fold the LS solution into x
        def finish(st):
            return self._solve_ls_and_update(st, j)

        x = jax.lax.cond(j == m - 1, finish, lambda st: st.x_base, new_state)
        # after a boundary update, x_base:=x and clear g so a later
        # solve_finalize adds nothing on top (y solves R·y = 0)
        at_boundary = j == m - 1
        new_state = new_state._replace(
            x_base=jnp.where(at_boundary, x, new_state.x_base),
            g=jnp.where(at_boundary, jnp.zeros_like(g), g))
        return x, new_state

    def residual_norm_estimate(self, b, x, state):
        if self.norm_type == "L2" and (self.use_scalar_norm or
                                       self.Ad.block_dim == 1):
            return state.quasi_res
        return None  # fall back to explicit residual

    def solve_finalize(self, b, x, state):
        # mid-cycle exit: fold the pending LS solution into x (at cycle
        # boundaries solve_iteration already updated x_base and cleared g,
        # making this a no-op).
        return self._solve_ls_and_update(state, state.j)


@register_solver("GMRES")
class GMRESSolver(_PrecondMixin, _GMRESBase):
    """Restarted right-preconditioned GMRES (reference ``gmres_solver.cu``)."""

    flexible = False


@register_solver("FGMRES")
class FGMRESSolver(_PrecondMixin, _GMRESBase):
    """Flexible GMRES (reference ``fgmres_solver.cu``): stores the
    preconditioned vectors Z so the preconditioner may change every
    iteration (AMG V-cycle)."""

    flexible = True
