"""IDR(s) solvers.

Reference: ``core/src/solvers/idr_solver.cu`` and ``idrmsync_solver.cu``
(induced dimension reduction; ``subspace_dim_s`` param core.cu:416; shipped
configs IDR_DILU.json / IDRMSYNC_DILU.json).

Implementation: IDR(s) with biorthogonalisation (van Gijzen & Sonneveld),
right-preconditioned.  IDRMSYNC (the reference's reduced-synchronisation
variant) shares the algorithm here — on TPU the whole iteration is one
fused XLA computation, so there are no separate synchronisation points to
minimise.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import blas
from ..ops.spmv import spmv
from .base import Solver, register_solver
from .krylov import _PrecondMixin


class _IDRState(NamedTuple):
    r: jax.Array
    G: jax.Array       # (s, n) direction matrix
    U: jax.Array       # (s, n)
    M: jax.Array       # (s, s) P·Gᵀ
    om: jax.Array


@register_solver("IDR")
class IDRSolver(_PrecondMixin, Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.s = int(cfg.get("subspace_dim_s", scope))

    def solver_setup(self):
        self._setup_preconditioner(True)
        s, n = self.s, self.Ad.n
        # fixed shadow space P (random orthonormal rows)
        rng = np.random.default_rng(11)
        P = rng.standard_normal((s, n))
        P, _ = np.linalg.qr(P.T)
        self.P = jnp.asarray(P.T[:s], dtype=self.Ad.dtype)  # (s, n)

    def solve_init(self, b, x):
        s, n = self.s, b.shape[0]
        r = b - spmv(self.Ad, x)
        return _IDRState(
            r=r, G=jnp.zeros((s, n), b.dtype), U=jnp.zeros((s, n), b.dtype),
            M=jnp.eye(s, dtype=b.dtype), om=jnp.asarray(1.0, b.dtype))

    def solve_iteration(self, b, x, state, iter_idx):
        """One IDR(s) cycle: s intermediate steps + the (s+1)-th step.

        The whole cycle is unrolled (s is small, default 8) — the
        reference performs the same s+1 SpMVs per outer iteration.
        """
        s = self.s
        r, G, U, M, om = state
        f = self.P @ r                      # (s,)
        for k in range(s):
            # solve lower-triangular M[k:, k:] c = f[k:] — take first col
            c = jnp.linalg.solve(
                M + jnp.eye(s, dtype=M.dtype) * 1e-30, f)
            v = r - (c[:, None] * G).sum(0)
            v = self._apply_M(v)
            u_new = om * v + (c[:, None] * U).sum(0)
            g_new = spmv(self.Ad, u_new)
            # biorthogonalise g_new against P rows < k
            pg = self.P @ g_new             # (s,)
            for j in range(k):
                alpha = pg[j] / jnp.where(M[j, j] == 0, 1.0, M[j, j])
                g_new = g_new - alpha * G[j]
                u_new = u_new - alpha * U[j]
                pg = self.P @ g_new
            G = G.at[k].set(g_new)
            U = U.at[k].set(u_new)
            M = M.at[:, k].set(self.P @ g_new)
            beta = f[k] / jnp.where(M[k, k] == 0, 1.0, M[k, k])
            r = r - beta * g_new
            x = x + beta * u_new
            f = self.P @ r
        # (s+1)-th step: minimise in the full space
        v = self._apply_M(r)
        t = spmv(self.Ad, v)
        tt = blas.dot(t, t)
        om = jnp.where(tt != 0, blas.dot(t, r) / jnp.where(tt == 0, 1.0, tt),
                       0.0)
        x = x + om * v
        r = r - om * t
        return x, _IDRState(r=r, G=G, U=U, M=M, om=om)

    def residual_norm_estimate(self, b, x, state):
        return blas.norm(state.r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)


@register_solver("IDRMSYNC")
class IDRMSyncSolver(IDRSolver):
    """Minimal-synchronisation IDR(s) (``idrmsync_solver.cu``,
    Collignon & van Gijzen's restructuring).

    The plain IDR(s) inner loop re-projects against the shadow space
    after EVERY Gram-Schmidt elimination (``pg = P @ g_new`` inside the
    j-loop) — O(s²) global reductions per cycle.  The m-sync variant
    performs ONE shadow projection per direction and maintains every
    other quantity algebraically:

    * the elimination coefficients come from one triangular solve
      against the already-known strictly-lower block of M (in exact
      arithmetic identical to the sequential eliminations);
    * the projected residual ``f`` and the projection ``pg`` update by
      the same triangular algebra instead of fresh P·r / P·g products.

    s+2 reductions per cycle instead of O(s²) — on a distributed mesh
    each avoided reduction is an avoided ``psum`` collective (on one
    chip XLA fuses either way; the count matters at scale)."""

    def solve_iteration(self, b, x, state, iter_idx):
        import jax.scipy.linalg as jsl
        s = self.s
        r, G, U, M, om = state
        f = self.P @ r                        # sync 1 of the cycle
        for k in range(s):
            c = jnp.linalg.solve(
                M + jnp.eye(s, dtype=M.dtype) * 1e-30, f)
            v = r - (c[:, None] * G).sum(0)
            v = self._apply_M(v)
            u_new = om * v + (c[:, None] * U).sum(0)
            g_new = spmv(self.Ad, u_new)
            pg = self.P @ g_new               # the ONE projection
            if k:
                Mk = M[:k, :k] + jnp.eye(k, dtype=M.dtype) * 1e-30
                alpha = jsl.solve_triangular(Mk, pg[:k], lower=True)
                g_new = g_new - alpha @ G[:k]
                u_new = u_new - alpha @ U[:k]
                # P·g updates algebraically: P(g − Σ αⱼ Gⱼ) = pg − M·α
                pg = pg - M[:, :k] @ alpha
            G = G.at[k].set(g_new)
            U = U.at[k].set(u_new)
            M = M.at[:, k].set(pg)
            beta = f[k] / jnp.where(pg[k] == 0, 1.0, pg[k])
            r = r - beta * g_new
            x = x + beta * u_new
            f = f - beta * pg                 # algebraic, no sync
        v = self._apply_M(r)
        t = spmv(self.Ad, v)
        tt = blas.dot(t, t)
        om = jnp.where(tt != 0, blas.dot(t, r) /
                       jnp.where(tt == 0, 1.0, tt), 0.0)
        x = x + om * v
        r = r - om * t
        return x, _IDRState(r=r, G=G, U=U, M=M, om=om)
