"""Dense LU coarse solver.

Reference: ``core/src/solvers/dense_lu_solver.cu`` — densifies the (small)
coarsest AMG level and LU-factorises it with cusolverDn.  Here the dense
factorisation happens once at setup with ``jax.scipy.linalg.lu_factor`` and
each application is a pair of triangular solves — small dense work the MXU
handles well.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from .base import Solver, register_solver


@register_solver("DENSE_LU_SOLVER")
class DenseLUSolver(Solver):
    is_smoother = False

    def solver_setup(self):
        # the factorisation dtype FLOORS at f32 (mixed precision: a
        # bf16 LU would make the coarse solve the hierarchy's noise
        # floor; the coarsest grid is tiny, so f32 storage costs
        # nothing) — scipy also cannot densify into sub-f32 buffers
        from ..core.precision import compute_dtype
        fdt = compute_dtype(np.dtype(self.Ad.dtype))
        if self.A is not None:
            # block-distributed coarsest: the coarsest grid is tiny, so
            # assembling it here is the consolidation, not a scalability
            # leak
            host = (self.A.assemble_global() if self.A.host is None
                    and self.A.blocks is not None else self.A.host)
            dense = np.asarray(host.todense()).astype(fdt)
        else:
            dense = _densify_device(self.Ad).astype(fdt)
        if self.Ad.fmt == "sharded-ell":
            # consolidation analog (reference "glue", distributed/glue.h):
            # the tiny coarsest system is replicated on every device and
            # solved redundantly; padded slots get identity rows
            from ..distributed.matrix import pad_map
            pm = pad_map(np.asarray(self.Ad.offsets), self.Ad.n_loc)
            big = np.eye(self.Ad.n, dtype=dense.dtype)
            big[np.ix_(pm, pm)] = dense
            dense = big
        # factorise on the same device the pack lives on (host modes pin
        # to CPU — fp64 LU must not run on the TPU)
        dense_dev = jnp.asarray(dense)
        try:
            # diag always exists (lean windowed packs carry vals=None)
            dense_dev = jax.device_put(dense, list(
                self.Ad.diag.devices())[0])
        except Exception:
            pass
        self._lu, self._piv = jax.scipy.linalg.lu_factor(dense_dev)

    def _lu_apply(self, b):
        # sub-f32 inputs solve at the factor's f32 and round once on
        # the way out (the vectors' dtype is the cycle's contract);
        # wider b (f64 refinement residuals) keeps jax promotion
        from ..core.precision import is_sub_f32
        narrow = is_sub_f32(b.dtype)
        bw = b.astype(self._lu.dtype) if narrow else b
        x = jax.scipy.linalg.lu_solve((self._lu, self._piv), bw)
        return x.astype(b.dtype) if narrow else x

    def solve_iteration(self, b, x, state, iter_idx):
        return self._lu_apply(b), state

    def apply(self, b, x0=None, n_iters=None):
        return self._lu_apply(b)


def _densify_device(Ad) -> np.ndarray:
    """Densify a DeviceMatrix on host (coarse levels are tiny)."""
    b = Ad.block_dim
    n = Ad.n_rows * b
    m = Ad.n_cols * b
    if Ad.fmt == "dia":
        vals = np.asarray(Ad.vals)
        out = np.zeros((n, m), dtype=vals.dtype)
        if b > 1:
            # block-DIA planes: scatter each offset's (nb, b, b) blocks
            nb = Ad.n_rows
            for k, o in enumerate(Ad.dia_offsets):
                rows = np.arange(max(0, -o), min(nb, nb - o))
                for i in rows:
                    out[i * b:(i + 1) * b,
                        (i + o) * b:(i + o + 1) * b] = vals[k, i]
            return out
        for k, o in enumerate(Ad.dia_offsets):
            rows = np.arange(max(0, -o), min(n, n - o))
            out[rows, rows + o] = vals[k, rows]
        return out
    if Ad.fmt == "dense":
        return np.asarray(Ad.vals)
    if Ad.fmt == "ell":
        # view methods reconstruct the gather-form arrays on lean packs
        vals = np.asarray(Ad.ell_vals_view())
        cols = np.asarray(Ad.ell_cols_view())
    elif Ad.fmt == "csr" and Ad.vals is None:
        # lean binned pack: the planes are the only arrays — the view
        # reconstructs the gather-form triplets (padding rides as zeros)
        from ..ops.pallas_csr import binned_entries_view
        rows_v, cols_v, vals_v = binned_entries_view(Ad)
        vals = np.asarray(vals_v)
        cols = np.asarray(cols_v)
        row_ids = np.asarray(rows_v)
    else:
        vals = np.asarray(Ad.vals)
        cols = np.asarray(Ad.cols) if Ad.cols is not None else None
        row_ids = np.asarray(Ad.row_ids) if Ad.row_ids is not None \
            else None
    out = np.zeros((n, m), dtype=vals.dtype)
    if Ad.fmt == "ell":
        for i in range(Ad.n_rows):
            for k in range(cols.shape[1]):
                j = cols[i, k]
                v = vals[i, k]
                if b == 1:
                    out[i, j] += v
                else:
                    out[i * b:(i + 1) * b, j * b:(j + 1) * b] += v
    else:
        rows = row_ids
        for e in range(len(rows)):
            i, j = rows[e], cols[e]
            if b == 1:
                out[i, j] += vals[e]
            else:
                out[i * b:(i + 1) * b, j * b:(j + 1) * b] += vals[e]
    return out


@register_solver("NOSOLVER")
class DummySolver(Solver):
    """Identity solver (reference ``base/src/solvers/dummy_solver.cu``):
    as a preconditioner M = I, so the 'solve' returns the right-hand side."""

    is_smoother = True

    def solve_iteration(self, b, x, state, iter_idx):
        return b, state

    def apply(self, b, x0=None, n_iters=None):
        return b
