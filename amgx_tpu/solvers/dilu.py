"""Multicolor DILU smoother.

Reference: ``core/src/solvers/multicolor_dilu_solver.cu`` (4630 LoC) — the
workhorse smoother of the shipped configs (e.g. FGMRES_AGGREGATION.json).

DILU preconditioner M = (E + L)·E⁻¹·(E + U), where L/U are the strict
lower/upper parts *in color order* and E is the diagonal chosen so that
diag(M) = diag(A):

    E_i = a_ii − Σ_{j ∈ N(i), rank(color_j) < rank(color_i)}
              a_ij · E_j⁻¹ · a_ji

Setup computes E color-by-color on host (each color is vectorised — rows
of one color are independent).  The solve is two color-ordered sweeps, each
color a masked full-width vector op with one masked SpMV:

    forward  (E+L) y = r :  y_c = E_c⁻¹ (r − L·y)_c
    backward (E+U) z = E·y: z_c = y_c − E_c⁻¹ (U·z)_c

Block systems (b×b) use b×b E blocks with batched inverses (the 4×4 path
of ``multicolor_dilu_solver.cu:48-112`` / BASELINE config 4).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..coloring import color_matrix
from ..core.matrix import Matrix, pack_device
from ..errors import BadConfigurationError
from ..ops.spmv import spmv
from ..utils.jaxcompat import shard_map as _shard_map
from .base import Solver, register_solver
from .jacobi import _apply_dinv


def _scalar_dilu_factor(csr: sp.csr_matrix, colors: np.ndarray):
    """Scalar DILU factorisation on one matrix: returns (L, U, 1/E) with
    L/U the strict lower/upper parts in color-rank order."""
    csr = sp.csr_matrix(csr)
    csr.sort_indices()
    n = csr.shape[0]
    rows = np.repeat(np.arange(n), np.diff(csr.indptr))
    cr_i = colors[rows]
    cr_j = colors[csr.indices]
    lower = cr_j < cr_i
    upper = cr_j > cr_i
    a_ji = _transpose_aligned_values(csr)
    diag = csr.diagonal().astype(np.float64)
    E = np.zeros(n, dtype=np.float64)
    Einv = np.zeros(n, dtype=np.float64)
    num_colors = int(colors.max()) + 1 if n else 1
    for c in range(num_colors):
        rc = colors == c
        contrib = np.zeros(n, dtype=np.float64)
        mask = lower & rc[rows]
        np.add.at(contrib, rows[mask],
                  csr.data[mask] * Einv[csr.indices[mask]] * a_ji[mask])
        E[rc] = diag[rc] - contrib[rc]
        bad = rc & (E == 0)
        E[bad] = 1.0
        Einv[rc] = 1.0 / E[rc]
    L = sp.csr_matrix((np.where(lower, csr.data, 0.0),
                       csr.indices.copy(), csr.indptr.copy()),
                      shape=csr.shape)
    L.eliminate_zeros()
    U = sp.csr_matrix((np.where(upper, csr.data, 0.0),
                       csr.indices.copy(), csr.indptr.copy()),
                      shape=csr.shape)
    U.eliminate_zeros()
    return L, U, Einv


def _guarded_batch_inv(E: np.ndarray, bd: int) -> np.ndarray:
    """Batched (nc, b, b) inverse with THE singular-block rule shared
    by the host and device factorisations: normalise each block by its
    max entry (raw |det| underflows for well-conditioned
    small-magnitude blocks), and blocks with zero scale or
    ``|det| < b·eps`` of the COMPUTE dtype take E⁻¹ = I — so the
    preconditioner does not change discontinuously at the
    host↔device size threshold."""
    eps = float(np.finfo(E.dtype).eps)
    scale = np.max(np.abs(E), axis=(-2, -1))
    nz = scale > 0
    En = E / np.where(nz, scale, 1.0)[:, None, None]
    eye = np.eye(bd, dtype=E.dtype)
    En = np.where(nz[:, None, None], En, eye)
    bad = ~nz | (np.abs(np.linalg.det(En)) < bd * eps)
    inv = np.linalg.inv(np.where(bad[:, None, None], eye, En))
    return np.where(bad[:, None, None], eye,
                    inv / np.where(nz, scale, 1.0)[:, None, None])


def _block_dilu_factor(bsr: sp.bsr_matrix, colors: np.ndarray, bd: int):
    """Block DILU factorisation (the b×b path of
    ``multicolor_dilu_solver.cu:48-112``): returns (Lb, Ub, Einv) with
    L/U the strict lower/upper block parts in color-rank order and
    (n, b, b) inverted E blocks (singular blocks guarded by the shared
    :func:`_guarded_batch_inv` rule)."""
    bsr = bsr.copy()
    bsr.sort_indices()
    n = bsr.shape[0] // bd
    rows = np.repeat(np.arange(n), np.diff(bsr.indptr))
    cols_ = bsr.indices
    lower = colors[cols_] < colors[rows]
    upper = colors[cols_] > colors[rows]
    # transpose-aligned blocks: Bt[e] = A_block[j,i]ᵀ-lookup
    keys = rows.astype(np.int64) * n + cols_
    tkeys = cols_.astype(np.int64) * n + rows
    pos = np.searchsorted(keys, tkeys)
    pos_c = np.minimum(pos, len(keys) - 1)
    hit = (pos < len(keys)) & (keys[pos_c] == tkeys)
    Bt = np.zeros_like(bsr.data)
    Bt[hit] = bsr.data[pos_c[hit]]
    diagblocks = np.zeros((n, bd, bd))
    on_diag = cols_ == rows
    diagblocks[rows[on_diag]] = bsr.data[on_diag]
    E = np.zeros((n, bd, bd))
    Einv = np.zeros((n, bd, bd))
    num_colors = int(colors.max()) + 1 if n else 1
    for c in range(num_colors):
        rc = colors == c
        contrib = np.zeros((n, bd, bd))
        mask = lower & rc[rows]
        if mask.any():
            prod = np.einsum("eab,ebc,ecd->ead", bsr.data[mask],
                             Einv[cols_[mask]], Bt[mask])
            np.add.at(contrib, rows[mask], prod)
        E[rc] = diagblocks[rc] - contrib[rc]
        # batched inversion under the shared singular-block rule (one
        # np.linalg.inv per COLOR, not per block row)
        Einv[rc] = _guarded_batch_inv(E[rc], bd)
    Lb = sp.bsr_matrix((np.where(lower[:, None, None], bsr.data, 0.0),
                        cols_.copy(), bsr.indptr.copy()),
                       shape=bsr.shape)
    Ub = sp.bsr_matrix((np.where(upper[:, None, None], bsr.data, 0.0),
                        cols_.copy(), bsr.indptr.copy()),
                       shape=bsr.shape)
    return Lb, Ub, Einv


#: block rows below which the HOST factorisation wins: the device
#: per-color sweep pays one executable compile per (color, shape) pair
#: (~seconds through a remote-TPU tunnel), while the host python loop
#: over b×b inverses finishes small systems in milliseconds — the same
#: small-matrix gate the setup engine applies (device_setup_min_rows)
_DILU_DEVICE_MIN_ROWS = 8192


def _block_dilu_factor_device(bsr: sp.bsr_matrix, colors: np.ndarray,
                              bd: int, compute_dtype=None):
    """Block DILU factorisation with the NUMERIC per-color sweep on
    DEVICE (ISSUE 15 tentpole (d)): the b×b triple products
    ``A_ij·E_j⁻¹·A_jiᵀ`` run as one batched einsum + segment-sum per
    color, and the E-block inversions are ONE batched micro-solve per
    color (``jnp.linalg.inv`` over (nc, b, b), scale-normalised under
    the SAME singular rule as the host path's
    :func:`_guarded_batch_inv`, relative to each path's compute
    dtype) — replacing the host per-color-loop inversions of
    :func:`_block_dilu_factor`.  Index classification (color masks,
    transpose alignment) stays host-side integer work.

    Returns the same ``(Lb, Ub, Einv)`` contract; ``Einv`` is a device
    array at ``compute_dtype`` (f64 off-TPU for host-factorisation
    parity, f32 on TPU)."""
    import jax
    import jax.numpy as jnp
    bsr = bsr.copy()
    bsr.sort_indices()
    n = bsr.shape[0] // bd
    rows = np.repeat(np.arange(n), np.diff(bsr.indptr))
    cols_ = bsr.indices
    lower = colors[cols_] < colors[rows]
    upper = colors[cols_] > colors[rows]
    keys = rows.astype(np.int64) * n + cols_
    tkeys = cols_.astype(np.int64) * n + rows
    pos = np.searchsorted(keys, tkeys)
    pos_c = np.minimum(pos, len(keys) - 1)
    hit = (pos < len(keys)) & (keys[pos_c] == tkeys)
    if compute_dtype is None:
        compute_dtype = np.float32 if jax.default_backend() == "tpu" \
            else np.promote_types(bsr.data.dtype, np.float32)
    cdt = np.dtype(compute_dtype)
    data = jnp.asarray(bsr.data, cdt)
    Bt = jnp.where(jnp.asarray(hit)[:, None, None],
                   data[jnp.asarray(pos_c)], 0)
    on_diag = cols_ == rows
    db = jnp.zeros((n, bd, bd), cdt).at[
        jnp.asarray(rows[on_diag])].set(data[np.flatnonzero(on_diag)])
    Einv = jnp.zeros((n, bd, bd), cdt)
    eye = jnp.eye(bd, dtype=cdt)
    eps = float(np.finfo(cdt).eps)
    num_colors = int(colors.max()) + 1 if n else 1
    for c in range(num_colors):
        rc_idx = np.flatnonzero(colors == c)
        if rc_idx.size == 0:
            continue
        me = np.flatnonzero(lower & (colors[rows] == c))
        Ec = db[jnp.asarray(rc_idx)]
        if me.size:
            prod = jnp.einsum("eab,ebc,ecd->ead", data[me],
                              Einv[jnp.asarray(cols_[me])], Bt[me],
                              preferred_element_type=cdt)
            contrib = jax.ops.segment_sum(prod, jnp.asarray(rows[me]),
                                          num_segments=n)
            Ec = Ec - contrib[jnp.asarray(rc_idx)]
        # scale-invariant singular guard: normalise each block by its
        # max entry before the det test (raw |det| underflows for
        # well-conditioned small-magnitude blocks); singular blocks
        # take E⁻¹ = I, matching the host factorisation's guard
        scale = jnp.max(jnp.abs(Ec), axis=(-2, -1))
        nz = scale > 0
        En = Ec / jnp.where(nz, scale, 1.0)[:, None, None]
        En = jnp.where(nz[:, None, None], En, eye)
        bad = ~nz | (jnp.abs(jnp.linalg.det(En)) < bd * eps)
        inv_n = jnp.linalg.inv(jnp.where(bad[:, None, None], eye, En))
        inv = jnp.where(bad[:, None, None], eye,
                        inv_n / jnp.where(nz, scale, 1.0)[:, None,
                                                          None])
        Einv = Einv.at[jnp.asarray(rc_idx)].set(inv)
    Lb = sp.bsr_matrix((np.where(lower[:, None, None], bsr.data, 0.0),
                        cols_.copy(), bsr.indptr.copy()),
                       shape=bsr.shape)
    Ub = sp.bsr_matrix((np.where(upper[:, None, None], bsr.data, 0.0),
                        cols_.copy(), bsr.indptr.copy()),
                       shape=bsr.shape)
    return Lb, Ub, Einv


def _stack_color_slabs(per_rank, c, n_parts, n_loc, dt, trailing=()):
    """Stack color ``c``'s per-rank slabs into (P, Rc[, ...]) arrays
    padded to a common (rows, width); pad rows point at the trash slot
    ``n_loc``.  ``trailing`` is the value block shape (() scalar,
    (b, b) block)."""
    Rc = max(max(np.asarray(s[c].rows).shape[0] for s in per_rank), 1)
    Kc = max(max(np.asarray(s[c].cols).shape[1] for s in per_rank), 1)
    rows = np.full((n_parts, Rc), n_loc, dtype=np.int32)
    cols = np.zeros((n_parts, Rc, Kc), dtype=np.int32)
    vals = np.zeros((n_parts, Rc, Kc) + trailing, dtype=dt)
    for p, s in enumerate(per_rank):
        sc = s[c]
        r_ = np.asarray(sc.rows)
        c_ = np.asarray(sc.cols)
        v_ = np.asarray(sc.vals)
        rows[p, :r_.shape[0]] = r_
        cols[p, :r_.shape[0], :c_.shape[1]] = c_
        vals[p, :r_.shape[0], :c_.shape[1]] = v_
    return rows, cols, vals


def _put_slab_tree(tree, mesh, axis):
    """Shard stacked slab arrays over the mesh axis (leading dim)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(
            mesh, P(axis, *([None] * (a.ndim - 1))))), tree)


def _transpose_aligned_values(csr: sp.csr_matrix) -> np.ndarray:
    """For each stored entry (i,j) return a_ji (0 when (j,i) not stored)."""
    n = csr.shape[0]
    rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(csr.indptr))
    keys = rows * n + csr.indices
    tkeys = csr.indices.astype(np.int64) * n + rows
    pos = np.searchsorted(keys, tkeys)
    pos_c = np.minimum(pos, len(keys) - 1)
    hit = (pos < len(keys)) & (keys[pos_c] == tkeys)
    out = np.zeros(len(keys), dtype=csr.data.dtype)
    out[hit] = csr.data[pos_c[hit]]
    return out


@register_solver("MULTICOLOR_DILU")
class MulticolorDILUSolver(Solver):
    is_smoother = True

    def solver_setup(self):
        if self.A is None:
            raise BadConfigurationError(
                "MULTICOLOR_DILU setup requires the host matrix")
        coloring = color_matrix(self.A, self.cfg, self.scope)
        colors = coloring.colors
        self.num_colors = coloring.num_colors
        b = self.A.block_dim
        dist = self.Ad.fmt == "sharded-ell"
        if dist and b != 1:
            self._setup_dist_slabs_block(colors)
            self.block = True
            self.block_dim = b
            return

        # entry classification in color-rank order
        if b == 1:
            if dist:
                self._setup_dist_slabs(colors)
                self.block = False
                return
            csr = self.A.scalar_csr()
            csr.sort_indices()
            L, U, Einv = _scalar_dilu_factor(csr, colors)
            # per-color packed slabs (the reference's per-color
            # kernels): each sweep touches only its color's rows —
            # O(nnz) total per DILU application
            from .gs import build_color_slabs
            dt = self.Ad.dtype
            self.L_slabs = build_color_slabs(
                L, colors, self.num_colors, dt)
            self.U_slabs = build_color_slabs(
                U, colors, self.num_colors, dt)
            self.Einv = jnp.asarray(Einv.astype(dt))
            self.Ld = self.Ud = None
            self.color_masks = None
            self.block = False
        else:
            self._setup_block(colors)

    def _setup_dist_slabs(self, colors):
        """Distributed DILU: per-rank LOCAL-block factorisation + stacked
        per-color slabs, swept inside ONE shard_map with ZERO collectives.

        Reference semantics (multicolor_dilu_solver.cu:4167-4209): halo
        values are exchanged once per smoother application and frozen —
        the per-color kernels then touch only local rows, and cross-rank
        couplings relax through the outer residual (which the solve
        iteration computes with the full halo SpMV).  A masked full-width
        formulation cost O(num_colors·nnz) per sweep plus one halo
        exchange per color; the slabs cost O(nnz_shard) total and no
        exchange at all.
        """
        from ..distributed.matrix import shard_vector
        from .gs import build_color_slabs
        mesh, axis, offsets, _ = self.A.dist
        Ad = self.Ad
        offs = np.asarray(Ad.offsets)
        n_parts = Ad.n_parts
        n_loc = Ad.n_loc
        dt = Ad.dtype
        if self.A.host is None and self.A.blocks is not None:
            blocks = self.A.blocks
        else:
            from ..distributed.partition import split_row_blocks
            blocks = split_row_blocks(self.A.scalar_csr(), offs)
        per_rank_L, per_rank_U, Einv_parts = [], [], []
        for p, blk in enumerate(blocks):
            lo, hi = offs[p], offs[p + 1]
            sub = sp.csr_matrix(sp.csr_matrix(blk)[:, lo:hi])
            cp = colors[lo:hi]
            Lp, Up, Einv_p = _scalar_dilu_factor(sub, cp)
            per_rank_L.append(build_color_slabs(
                Lp, cp, self.num_colors, dt, device=False))
            per_rank_U.append(build_color_slabs(
                Up, cp, self.num_colors, dt, device=False))
            Einv_parts.append(Einv_p)
        self.Einv = shard_vector(Ad, np.concatenate(Einv_parts))
        Ls = [_stack_color_slabs(per_rank_L, c, n_parts, n_loc, dt)
              for c in range(self.num_colors)]
        Us = [_stack_color_slabs(per_rank_U, c, n_parts, n_loc, dt)
              for c in range(self.num_colors)]
        self._dist_L = _put_slab_tree(Ls, mesh, axis)
        self._dist_U = _put_slab_tree(Us, mesh, axis)
        self.L_slabs = self.U_slabs = None
        self.Ld = self.Ud = None
        self.color_masks = None

    def _setup_dist_slabs_block(self, colors):
        """Distributed b×b DILU (BASELINE config 4 on the mesh): per-rank
        local-BLOCK factorisation (``multicolor_dilu_solver.cu:48-112``
        b×b path, distributed as in :meth:`_setup_dist_slabs`) + stacked
        per-color block slabs, swept with zero collectives."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .gs import build_color_slabs_block
        mesh, axis, _, _ = self.A.dist
        Ad = self.Ad
        bd = self.A.block_dim
        offs = np.asarray(Ad.offsets)          # BLOCK-row offsets
        n_parts, n_loc = Ad.n_parts, Ad.n_loc
        dt = Ad.dtype
        bsr = self.A.host if isinstance(self.A.host, sp.bsr_matrix) \
            else sp.bsr_matrix(self.A.host, blocksize=(bd, bd))
        csr_full = sp.csr_matrix(bsr)      # one O(nnz) conversion
        per_L, per_U, Einv_pads = [], [], []
        for p in range(n_parts):
            lo, hi = offs[p], offs[p + 1]
            sub = sp.bsr_matrix(
                csr_full[lo * bd:hi * bd, lo * bd:hi * bd],
                blocksize=(bd, bd))
            cp = colors[lo:hi]
            Lp, Up, Einv_p = _block_dilu_factor(sub, cp, bd)
            per_L.append(build_color_slabs_block(
                Lp, cp, self.num_colors, dt, bd, device=False))
            per_U.append(build_color_slabs_block(
                Up, cp, self.num_colors, dt, bd, device=False))
            pad = np.tile(np.eye(bd, dtype=dt), (n_loc, 1, 1))
            pad[:hi - lo] = Einv_p
            Einv_pads.append(pad)

        spec1 = NamedSharding(mesh, P(axis))
        self.Einv = jax.device_put(
            np.concatenate(Einv_pads).astype(dt), spec1)
        self._dist_L = _put_slab_tree(
            [_stack_color_slabs(per_L, c, n_parts, n_loc, dt, (bd, bd))
             for c in range(self.num_colors)], mesh, axis)
        self._dist_U = _put_slab_tree(
            [_stack_color_slabs(per_U, c, n_parts, n_loc, dt, (bd, bd))
             for c in range(self.num_colors)], mesh, axis)
        self.L_slabs = self.U_slabs = None
        self.Ld = self.Ud = None
        self.color_masks = None

    def _apply_dilu_dist_block(self, r):
        """Distributed b×b two-sweep DILU apply: one shard_map, no
        collectives."""
        import jax
        from jax.sharding import PartitionSpec as P
        A = self.Ad
        axis, n_loc, bd = A.axis, A.n_loc, self.block_dim

        def local(Ls, Us, Einv, rl):
            rb = rl.reshape(n_loc, bd)
            y = jnp.zeros((n_loc + 1, bd), rl.dtype)   # +1 trash row
            for c in range(self.num_colors):
                rows, cols, vals = jax.tree_util.tree_map(
                    lambda a: a[0], Ls[c])
                t = jnp.einsum("nkab,nkb->na", vals, y[cols],
                               preferred_element_type=rl.dtype)
                rsafe = jnp.minimum(rows, n_loc - 1)
                rhs = rb[rsafe] - t
                upd = jnp.einsum("nab,nb->na", Einv[rsafe], rhs,
                                 preferred_element_type=rl.dtype)
                y = y.at[rows].set(upd)
            z = y
            for c in range(self.num_colors - 1, -1, -1):
                rows, cols, vals = jax.tree_util.tree_map(
                    lambda a: a[0], Us[c])
                t = jnp.einsum("nkab,nkb->na", vals, z[cols],
                               preferred_element_type=rl.dtype)
                rsafe = jnp.minimum(rows, n_loc - 1)
                upd = y[rsafe] - jnp.einsum(
                    "nab,nb->na", Einv[rsafe], t,
                    preferred_element_type=rl.dtype)
                z = z.at[rows].set(upd)
            return z[:n_loc].reshape(-1)

        spec = lambda a: P(axis, *([None] * (a.ndim - 1)))
        in_specs = (jax.tree_util.tree_map(spec, self._dist_L),
                    jax.tree_util.tree_map(spec, self._dist_U),
                    P(axis), P(axis))
        return _shard_map(
            local, mesh=A.mesh, in_specs=in_specs, out_specs=P(axis),
            check_vma=False,
        )(self._dist_L, self._dist_U, self.Einv, r)

    def _apply_dilu_dist(self, r):
        """Distributed two-sweep DILU apply: one shard_map, no
        collectives (see _setup_dist_slabs)."""
        import jax
        from jax.sharding import PartitionSpec as P
        A = self.Ad
        axis = A.axis
        n_loc = A.n_loc

        def local(Ls, Us, Einv, rl):
            y = jnp.zeros((n_loc + 1,), rl.dtype)     # +1 trash slot
            for c in range(self.num_colors):
                rows, cols, vals = jax.tree_util.tree_map(
                    lambda a: a[0], Ls[c])
                t = jnp.sum(vals * y[cols], axis=1)
                rsafe = jnp.minimum(rows, n_loc - 1)
                upd = Einv[rsafe] * (rl[rsafe] - t)
                y = y.at[rows].set(upd)
            z = y
            for c in range(self.num_colors - 1, -1, -1):
                rows, cols, vals = jax.tree_util.tree_map(
                    lambda a: a[0], Us[c])
                t = jnp.sum(vals * z[cols], axis=1)
                rsafe = jnp.minimum(rows, n_loc - 1)
                upd = y[rsafe] - Einv[rsafe] * t
                z = z.at[rows].set(upd)
            return z[:n_loc]

        spec = lambda a: P(axis, *([None] * (a.ndim - 1)))
        in_specs = (jax.tree_util.tree_map(spec, self._dist_L),
                    jax.tree_util.tree_map(spec, self._dist_U),
                    P(axis), P(axis))
        return _shard_map(
            local, mesh=A.mesh, in_specs=in_specs, out_specs=P(axis),
            check_vma=False,
        )(self._dist_L, self._dist_U, self.Einv, r)

    def _setup_block(self, colors):
        bd = self.A.block_dim
        bsr = self.A.host if isinstance(self.A.host, sp.bsr_matrix) else \
            sp.bsr_matrix(self.A.host, blocksize=(bd, bd))
        n_blk = bsr.shape[0] // bd
        use_device = n_blk >= _DILU_DEVICE_MIN_ROWS
        if use_device:
            try:
                # device factorisation: batched b×b micro-solves per
                # color (the host loop ran one np.linalg.inv per block)
                Lb, Ub, Einv = _block_dilu_factor_device(bsr, colors,
                                                         bd)
                Einv = Einv.astype(self.Ad.dtype)
            except Exception as e:
                # a failed device factorisation must not kill setup —
                # but falling back to the slow host loop SILENTLY would
                # turn a real bug into an unexplained setup regression
                import logging
                logging.getLogger("amgx_tpu").warning(
                    "device block-DILU factorisation failed (%s: %s); "
                    "falling back to the host loop", type(e).__name__,
                    e)
                from ..telemetry import metrics as _tm
                _tm.counter_inc("amgx_dilu_device_factor_fallback_total")
                use_device = False
        if not use_device:
            Lb, Ub, Einv = _block_dilu_factor(bsr, colors, bd)
            Einv = jnp.asarray(Einv.astype(self.Ad.dtype))
        from .gs import build_color_slabs_block
        self.num_colors = int(colors.max()) + 1
        self.L_slabs = build_color_slabs_block(
            Lb, colors, self.num_colors, self.Ad.dtype, bd)
        self.U_slabs = build_color_slabs_block(
            Ub, colors, self.num_colors, self.Ad.dtype, bd)
        self.Einv = Einv
        self.Ld = self.Ud = None
        self.color_masks = None
        self.block = True
        self.block_dim = bd

    def _apply_dilu(self, r):
        """z = M⁻¹ r via the two color-ordered sweeps."""
        if getattr(self, "_dist_L", None) is not None:
            return (self._apply_dilu_dist_block(r) if self.block
                    else self._apply_dilu_dist(r))
        if getattr(self, "L_slabs", None) is not None:
            # per-color slab sweeps: color c reads only its L/U rows
            if not self.block:
                y = jnp.zeros_like(r)
                for c in range(self.num_colors):
                    s = self.L_slabs[c]
                    t = jnp.sum(s.vals * y[s.cols], axis=1)
                    y = y.at[s.rows].set(
                        self.Einv[s.rows] * (r[s.rows] - t))
                z = y
                for c in range(self.num_colors - 1, -1, -1):
                    s = self.U_slabs[c]
                    t = jnp.sum(s.vals * z[s.cols], axis=1)
                    z = z.at[s.rows].set(
                        y[s.rows] - self.Einv[s.rows] * t)
                return z
            bd = self.block_dim
            dt = r.dtype
            y = jnp.zeros_like(r)
            for c in range(self.num_colors):
                s = self.L_slabs[c]
                t = jnp.einsum("nkab,nkb->na", s.vals,
                               y.reshape(-1, bd)[s.cols],
                               preferred_element_type=dt)
                rhs = r.reshape(-1, bd)[s.rows] - t
                upd = jnp.einsum("nab,nb->na", self.Einv[s.rows], rhs,
                                 preferred_element_type=dt)
                y = y.reshape(-1, bd).at[s.rows].set(upd).reshape(-1)
            z = y
            for c in range(self.num_colors - 1, -1, -1):
                s = self.U_slabs[c]
                t = jnp.einsum("nkab,nkb->na", s.vals,
                               z.reshape(-1, bd)[s.cols],
                               preferred_element_type=dt)
                upd = y.reshape(-1, bd)[s.rows] - jnp.einsum(
                    "nab,nb->na", self.Einv[s.rows], t,
                    preferred_element_type=dt)
                z = z.reshape(-1, bd).at[s.rows].set(upd).reshape(-1)
            return z
        y = jnp.zeros_like(r)
        for c in range(self.num_colors):
            t = spmv(self.Ld, y)
            upd = _apply_dinv(self.Einv, r - t)
            y = jnp.where(self.color_masks[c], upd, y)
        z = y
        for c in range(self.num_colors - 1, -1, -1):
            t = spmv(self.Ud, z)
            upd = y - _apply_dinv(self.Einv, t)
            z = jnp.where(self.color_masks[c], upd, z)
        return z

    def solve_iteration(self, b, x, state, iter_idx):
        r = b - spmv(self.Ad, x)
        x = x + self.relaxation_factor * self._apply_dilu(r)
        return x, state
