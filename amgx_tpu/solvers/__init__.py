"""Solver registry — importing this package registers all solvers
(reference: ``registerClasses`` in ``core/src/core.cu:612-641``)."""
from .base import (Solver, SolverFactory, SolveResult, register_solver,
                   check_convergence)
from . import jacobi      # BLOCK_JACOBI, JACOBI_L1, CF_JACOBI
from . import dense_lu    # DENSE_LU_SOLVER, NOSOLVER
from . import krylov      # CG, PCG, PCGF, BICGSTAB, PBICGSTAB, GMRES, FGMRES
from . import chebyshev   # CHEBYSHEV, CHEBYSHEV_POLY, POLYNOMIAL, KPZ_POLYNOMIAL
from . import amg_solver  # AMG
from . import gs          # GS, MULTICOLOR_GS, FIXCOLOR_GS, KACZMARZ
from . import dilu        # MULTICOLOR_DILU
from . import ilu         # MULTICOLOR_ILU
from . import scalers     # BINORMALIZATION, NBINORMALIZATION, DIAGONAL_SYMMETRIC
from . import idr         # IDR, IDRMSYNC

__all__ = ["Solver", "SolverFactory", "SolveResult", "register_solver",
           "check_convergence"]
