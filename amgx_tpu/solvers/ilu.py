"""Multicolor ILU(k) smoother.

Reference: ``core/src/solvers/multicolor_ilu_solver.cu`` (~3k LoC);
``ilu_sparsity_level`` selects ILU(0), ILU(1), … (core.cu:423).

TPU design: the matrix is factorised on host in *color-rank order* (the
reference reorders by color, ``reorderColumnsByColor``); the triangular
solves then parallelise color-by-color.  To keep that true with fill-in,
the coloring is computed on the *filled* sparsity graph L+U — rows of one
color stay mutually independent for any sparsity level k, so each solve
sweep is ``num_colors`` masked SpMV updates, as in DILU.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..coloring import MatrixColoring, create_coloring
from ..core.matrix import pack_device
from ..errors import BadConfigurationError
from ..ops.spmv import spmv
from .base import Solver, register_solver


def _symbolic_fill(A: sp.csr_matrix, level: int) -> sp.csr_matrix:
    """ILU(k) sparsity pattern via k rounds of symbolic products
    (pattern of L·U grows like powers of the adjacency)."""
    pat = sp.csr_matrix(
        (np.ones(len(A.data), dtype=np.int8), A.indices.copy(),
         A.indptr.copy()), shape=A.shape)
    base = pat.copy()
    for _ in range(level):
        pat = ((pat @ base) + pat).tocsr()
        pat.data[:] = 1
    pat.sort_indices()
    return pat


def _ilu_factorize(A: sp.csr_matrix, pattern: sp.csr_matrix,
                   rank: np.ndarray):
    """IKJ ILU on the given pattern with rows processed in ``rank`` order.

    Returns (LU_csr) holding L (strict lower by rank, unit diagonal
    implicit) and U (upper incl. diagonal) in one matrix, plus 1/diag.
    Host-side, O(nnz·avg_row); runs once per setup.
    """
    n = A.shape[0]
    # build working rows on the fill pattern
    pat = pattern.tocsr()
    pat.sort_indices()
    work = sp.csr_matrix((np.zeros(len(pat.data)), pat.indices.copy(),
                          pat.indptr.copy()), shape=A.shape)
    # scatter A into the pattern
    from ..amg.classical.util import entry_mask_in  # noqa
    # positions of A entries inside pattern rows
    arows = np.repeat(np.arange(n), np.diff(A.indptr))
    akeys = arows.astype(np.int64) * n + A.indices
    prows = np.repeat(np.arange(n), np.diff(pat.indptr))
    pkeys = prows.astype(np.int64) * n + pat.indices
    pos = np.searchsorted(pkeys, akeys)
    work.data[pos] = A.data

    indptr, indices, data = work.indptr, work.indices, work.data
    inv_rank = np.empty(n, dtype=np.int64)
    order = np.argsort(rank, kind="stable")
    inv_rank[order] = np.arange(n)
    diag_pos = np.full(n, -1, dtype=np.int64)
    for i in range(n):
        sl = slice(indptr[i], indptr[i + 1])
        dloc = np.flatnonzero(indices[sl] == i)
        if len(dloc):
            diag_pos[i] = indptr[i] + dloc[0]
    # IKJ in rank order
    for i in order:
        sl = slice(indptr[i], indptr[i + 1])
        cols_i = indices[sl]
        row_i = data[sl]
        lower_mask = rank[cols_i] < rank[i]
        for t in np.flatnonzero(lower_mask)[np.argsort(
                rank[cols_i[np.flatnonzero(lower_mask)]])]:
            k = cols_i[t]
            dk = data[diag_pos[k]] if diag_pos[k] >= 0 else 1.0
            if dk == 0:
                dk = 1.0
            lik = row_i[t] / dk
            row_i[t] = lik
            # row_i -= lik * row_k (restricted to row_i's pattern, upper of k)
            slk = slice(indptr[k], indptr[k + 1])
            cols_k = indices[slk]
            upk = rank[cols_k] > rank[k]
            ck = cols_k[upk]
            vk = data[slk][upk]
            posr = np.searchsorted(cols_i, ck)
            posr_c = np.minimum(posr, len(cols_i) - 1)
            hit = (posr < len(cols_i)) & (cols_i[posr_c] == ck)
            row_i[posr_c[hit]] -= lik * vk[hit]
        data[sl] = row_i
    dvals = np.array([data[diag_pos[i]] if diag_pos[i] >= 0 else 1.0
                      for i in range(n)])
    dvals[dvals == 0] = 1.0
    return work, 1.0 / dvals


@register_solver("MULTICOLOR_ILU")
class MulticolorILUSolver(Solver):
    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.sparsity_level = int(cfg.get("ilu_sparsity_level", scope))

    def solver_setup(self):
        if self.A is None:
            raise BadConfigurationError(
                "MULTICOLOR_ILU setup requires the host matrix")
        if self.Ad.fmt == "sharded-ell":
            raise BadConfigurationError(
                "distributed MULTICOLOR_ILU not supported yet — use "
                "MULTICOLOR_DILU (the reference default) instead")
        csr = self.A.scalar_csr().astype(np.float64)
        csr.sort_indices()
        pattern = _symbolic_fill(csr, self.sparsity_level)
        # color the FILLED graph so per-color independence survives fill-in
        algo = create_coloring(
            str(self.cfg.get("matrix_coloring_scheme", self.scope)),
            self.cfg, self.scope)
        coloring = algo.color(pattern)
        colors = coloring.colors
        self.num_colors = coloring.num_colors
        rank = colors.astype(np.int64)
        LU, dinv = _ilu_factorize(csr, pattern, rank)
        n = csr.shape[0]
        rows = np.repeat(np.arange(n), np.diff(LU.indptr))
        lower = rank[LU.indices] < rank[rows]
        upper = rank[LU.indices] > rank[rows]
        L = sp.csr_matrix((np.where(lower, LU.data, 0.0),
                           LU.indices.copy(), LU.indptr.copy()),
                          shape=LU.shape)
        L.eliminate_zeros()
        U = sp.csr_matrix((np.where(upper, LU.data, 0.0),
                           LU.indices.copy(), LU.indptr.copy()),
                          shape=LU.shape)
        U.eliminate_zeros()
        # per-color packed slabs: each triangular-solve sweep reads only
        # its color's L/U rows — O(nnz(LU)) per application, independent
        # of the color count (the reference's per-color kernels)
        from .gs import build_color_slabs
        self.L_slabs = build_color_slabs(L, colors, self.num_colors,
                                         self.Ad.dtype)
        self.U_slabs = build_color_slabs(U, colors, self.num_colors,
                                         self.Ad.dtype)
        self.dinv_f = jnp.asarray(dinv.astype(self.Ad.dtype))

    def _apply_ilu(self, r):
        # L y = r  (unit lower): y_c = r_c − (L·y)_c
        y = jnp.zeros_like(r)
        for c in range(self.num_colors):
            s = self.L_slabs[c]
            t = jnp.sum(s.vals * y[s.cols], axis=1)
            y = y.at[s.rows].set(r[s.rows] - t)
        # U z = y: z_c = dinv_c (y − U·z)_c
        z = jnp.zeros_like(r)
        for c in range(self.num_colors - 1, -1, -1):
            s = self.U_slabs[c]
            t = jnp.sum(s.vals * z[s.cols], axis=1)
            z = z.at[s.rows].set(self.dinv_f[s.rows] * (y[s.rows] - t))
        return z

    def solve_iteration(self, b, x, state, iter_idx):
        r = b - spmv(self.Ad, x)
        x = x + self.relaxation_factor * self._apply_ilu(r)
        return x, state
