"""Jacobi-family smoothers.

Reference: ``core/src/solvers/block_jacobi_solver.cu`` (BLOCK_JACOBI with
1×1/4×4/b×b paths and fused zero-initial-guess kernels),
``jacobi_l1_solver.cu`` (JACOBI_L1), ``cf_jacobi_solver.cu`` (CF_JACOBI).

TPU design: a sweep is ``x + ω·D⁻¹·(b − A·x)`` — one SpMV plus fused
elementwise work, or a batched (n,b,b)×(n,b) block solve for block matrices.
The zero-initial-guess first sweep collapses to ``ω·D⁻¹·b`` exactly as the
reference's fused kernels do (``block_jacobi_solver.cu:1240-1530``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..ops.spmv import spmv
from .base import Solver, register_solver


def _invert_block_diag(diag) -> jax.Array:
    """Invert the (block) diagonal: (n,) → reciprocal, (n,b,b) → batched inv.

    Runs on HOST numpy: this is setup-phase work, and issuing it as a
    device op costs one remote XLA compile per level shape (~0.6 s each
    through the TPU tunnel) — 13 levels of that dominated the whole AMG
    setup.  One host computation + one transfer instead.
    """
    d = np.asarray(diag)
    # sub-f32 storage (bf16 block hierarchies): numpy's LinAlg kernels
    # don't take ml_dtypes — invert at the f32 compute floor and store
    # the RESULT narrow, the same storage-vs-arithmetic split every
    # other smoother-data path applies (core/precision.py)
    from ..core.precision import compute_dtype as _cdt
    store_dt = d.dtype
    work = d.astype(_cdt(d.dtype), copy=False)
    if work.ndim == 1:
        out = np.where(work != 0,
                       1.0 / np.where(work == 0, 1.0, work), 0.0)
    else:
        # scale-invariant singularity test: normalise each block by its
        # max entry first (raw |det| underflows for well-conditioned but
        # small-magnitude blocks, silently replacing D⁻¹ with I)
        bdim = work.shape[-1]
        scale = np.max(np.abs(work), axis=(-2, -1))
        nz = scale > 0
        dn = work / np.where(nz, scale, 1.0)[:, None, None]
        bad = ~nz | (np.abs(np.linalg.det(dn))
                     < bdim * np.finfo(work.dtype).eps)
        safe = np.where(bad[:, None, None],
                        np.eye(bdim, dtype=work.dtype), dn)
        out = np.linalg.inv(safe) / np.where(nz & ~bad, scale,
                                             1.0)[:, None, None]
    return jnp.asarray(out.astype(store_dt))


def _apply_dinv(dinv: jax.Array, v: jax.Array) -> jax.Array:
    if dinv.ndim == 1:
        return dinv * v
    b = dinv.shape[-1]
    return jnp.einsum("nab,nb->na", dinv,
                      v.reshape(-1, b)).reshape(-1)


@functools.lru_cache(maxsize=1)
def _scalar_dinv_fn():
    return jax.jit(lambda d: jnp.where(
        d != 0, 1.0 / jnp.where(d == 0, 1.0, d), 0.0))


def setup_dinv(slv) -> jax.Array:
    """The inverted (block) diagonal for a smoother's setup phase.

    Scalar packs invert the pack's own diagonal ON DEVICE (zero
    transfer — through a remote-TPU tunnel a per-level dinv upload costs
    ~0.1 s latency each); the sharded path keeps the sharding; block
    matrices factor on host (guarded batched inverse); device readback
    is the last resort (device-only block setup)."""
    Ad, A = slv.Ad, slv.A
    if Ad.fmt == "sharded-ell":
        d = Ad.diag
        return jnp.where(d != 0, 1.0 / jnp.where(d == 0, 1.0, d), 0.0)
    if A is not None:
        cached = getattr(A, "_dinv_dev", None)
        if cached is not None and cached[0] == Ad.dtype:
            return cached[1]      # rode the hierarchy's batched upload
    if Ad.block_dim == 1:
        return _scalar_dinv_fn()(Ad.diag)
    if A is not None:
        return _invert_block_diag(host_block_diag(A).astype(Ad.dtype))
    return _invert_block_diag(np.asarray(Ad.diag))


def host_block_diag(A) -> np.ndarray:
    """The (block) diagonal from the HOST matrix — avoids a device
    readback (slow through a remote-TPU tunnel) during setup."""
    b = A.block_dim
    if b == 1:
        return A.host_diag()
    bsr = A.host if isinstance(A.host, sp.bsr_matrix) else sp.bsr_matrix(
        A.host, blocksize=(b, b))
    bsr.sort_indices()
    n = bsr.shape[0] // b
    rows = np.repeat(np.arange(n), np.diff(bsr.indptr))
    out = np.zeros((n, b, b), dtype=bsr.data.dtype)
    on_diag = bsr.indices == rows
    out[rows[on_diag]] = bsr.data[on_diag]
    return out


@register_solver("BLOCK_JACOBI")
class BlockJacobiSolver(Solver):
    """Damped (block) Jacobi: x ← x + ω·D⁻¹·(b − A·x)."""

    is_smoother = True

    def solver_setup(self):
        self.dinv = setup_dinv(self)

    def solve_iteration(self, b, x, state, iter_idx):
        r = b - spmv(self.Ad, x)
        x = x + self.relaxation_factor * _apply_dinv(self.dinv, r)
        return x, state

    def apply(self, b, x0=None, n_iters=None):
        n = self.max_iters if n_iters is None else n_iters
        if x0 is None:
            # fused zero-initial-guess first sweep (reference :1240-1530)
            x = self.relaxation_factor * _apply_dinv(self.dinv, b)
            start = 1
        else:
            x = x0
            start = 0
        for _ in range(start, n):
            x, _ = self.solve_iteration(b, x, (), None)
        return x


@functools.lru_cache(maxsize=1)
def _l1_dinv_fn():
    from ..ops.spmv import abs_rowsum

    def fn(Ad):
        # abs_rowsum accumulates (and returns) f32 for sub-f32 packs;
        # the STORED dinv rides at the pack dtype — smoother data must
        # not silently upcast (mixed-precision bandwidth contract)
        absrow = abs_rowsum(Ad)
        dinv = 1.0 / jnp.where(absrow == 0, 1.0, absrow)
        return dinv.astype(Ad.diag.dtype)

    return jax.jit(fn)

@register_solver("JACOBI_L1")
class JacobiL1Solver(Solver):
    """L1-Jacobi: D_l1[i] = |a_ii| + Σ_{j≠i}|a_ij| per scalar row
    (reference ``jacobi_l1_solver.cu``); unconditionally convergent smoother
    and the TPU-friendly default for aggressive-coarsening configs."""

    is_smoother = True

    def solver_setup(self):
        if self.Ad.block_dim == 1 and self.Ad.fmt in (
                "dia", "dia3", "ell", "csr", "dense", "sharded-ell"):
            # L1 row sums from the pack ON DEVICE (|diag| + Σ|off-diag| =
            # Σ|row|): zero transfer, works with or without a host
            # matrix (blocks-mode distributed levels included), and
            # pad/explicit zeros contribute 0
            self.dinv = _l1_dinv_fn()(self.Ad)
        elif self.A is not None:
            csr = self.A.scalar_csr()
            absrow = np.asarray(np.abs(csr).sum(axis=1)).ravel()
            diag = csr.diagonal()
            d = np.abs(diag) + (absrow - np.abs(diag))
            d[d == 0] = 1.0
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                self.dinv = shard_vector(self.Ad, 1.0 / d)
            else:
                self.dinv = jnp.asarray(1.0 / d, dtype=self.Ad.dtype)
        else:
            # device-only block fallback: |diag|-block row sums
            d = jnp.abs(self.Ad.diag).sum(axis=-1).reshape(-1)
            self.dinv = 1.0 / jnp.where(d == 0, 1.0, d)

    def solve_iteration(self, b, x, state, iter_idx):
        r = b - spmv(self.Ad, x)
        x = x + self.relaxation_factor * self.dinv * r
        return x, state


@register_solver("CF_JACOBI")
class CFJacobiSolver(Solver):
    """C/F-split Jacobi for classical AMG (reference ``cf_jacobi_solver.cu``):
    one sweep updates C points then F points (or the reverse), using the
    C/F splitting attached to the matrix by the classical selector."""

    is_smoother = True

    def solver_setup(self):
        self.dinv = setup_dinv(self)
        self.cf_mode = int(self.cfg.get("cf_smoothing_mode", self.scope))
        cf = getattr(self.A, "cf_map", None) if self.A is not None else None
        if cf is None:
            cf = np.zeros(self.Ad.n_rows, dtype=bool)  # all C
        self.c_mask = jnp.asarray(np.asarray(cf, dtype=bool))

    def _masked_sweep(self, b, x, mask):
        r = b - spmv(self.Ad, x)
        dx = self.relaxation_factor * _apply_dinv(self.dinv, r)
        if self.Ad.block_dim > 1:
            mask = jnp.repeat(mask, self.Ad.block_dim)
        return x + jnp.where(mask, dx, 0.0)

    def solve_iteration(self, b, x, state, iter_idx):
        first_c = self.cf_mode in (0, 2)
        m1 = self.c_mask if first_c else ~self.c_mask
        x = self._masked_sweep(b, x, m1)
        x = self._masked_sweep(b, x, ~m1)
        return x, state
