"""Solver framework.

TPU-native re-design of the reference solver contract
(``base/include/solvers/solver.h:44-325``, ``base/src/solvers/solver.cu``):

* :class:`Solver` — base class owning A, convergence criterion, norms and the
  generic ``setup()`` / ``solve()`` drivers (reference ``solver.cu:380-970``).
* :class:`SolverFactory` — named registry; nested solvers are allocated from
  a config scope (reference ``solver.h:287-325`` + ``core.cu:612-641``).
* Convergence criteria: ABSOLUTE / RELATIVE_INI(_CORE) / RELATIVE_MAX(_CORE) /
  COMBINED_REL_INI_ABS (``core/src/convergence/``).

Execution model (the TPU-first redesign): ``setup()`` runs on host (irregular
graph work → frozen device arrays); the whole ``solve()`` loop is traced once
and executed as a single XLA computation via ``lax.while_loop`` over a state
pytree.  Preconditioner/smoother application is traced inline into the outer
iteration (the reference achieves composition via virtual calls at run time;
here composition happens at trace time, letting XLA fuse across the stack).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AMGConfig
from ..core.matrix import DeviceMatrix, Matrix
from ..errors import BadConfigurationError, SolveStatus
from ..ops import blas
from ..ops.spmv import spmv
from ..utils.logging import amgx_output


# --------------------------------------------------------------------------
# Convergence criteria (core/src/convergence/)
# --------------------------------------------------------------------------
def check_convergence(criterion: str, nrm, nrm_ini, nrm_max, tolerance,
                      alt_rel_tolerance):
    """Return a boolean scalar: has the solve converged?

    All comparisons are per block-component and must hold for every
    component (reference block norms).
    """
    if criterion in ("ABSOLUTE",):
        ok = nrm <= tolerance
    elif criterion in ("RELATIVE_INI", "RELATIVE_INI_CORE"):
        ok = nrm <= tolerance * nrm_ini
    elif criterion in ("RELATIVE_MAX", "RELATIVE_MAX_CORE"):
        ok = nrm <= tolerance * nrm_max
    elif criterion == "COMBINED_REL_INI_ABS":
        ok = (nrm <= tolerance) | (nrm <= alt_rel_tolerance * nrm_ini)
    else:
        raise BadConfigurationError(f"unknown convergence {criterion!r}")
    return jnp.all(ok)


@dataclasses.dataclass
class SolveResult:
    x: jax.Array
    iterations: int
    status: SolveStatus
    residual_norm: Optional[np.ndarray]
    residual_history: Optional[np.ndarray]
    setup_time: float = 0.0
    solve_time: float = 0.0


# --------------------------------------------------------------------------
# Factory registry (reference SolverFactory, solver.h:287-325)
# --------------------------------------------------------------------------
_solver_registry: Dict[str, Type["Solver"]] = {}


def register_solver(name: str):
    def deco(cls):
        _solver_registry[name] = cls
        cls.config_name = name
        return cls
    return deco


class SolverFactory:
    @staticmethod
    def allocate(cfg: AMGConfig, scope: str, param_name: str) -> "Solver":
        """Allocate the solver named by config param ``param_name`` in
        ``scope``; the solver reads its own params from its new scope.

        Reference: ``SolverFactory::allocate(cfg, current_scope,
        solver_name)`` pattern used e.g. at ``fgmres_solver.cu:243-253``.
        """
        value, new_scope = cfg.get_scoped(param_name, scope)
        return SolverFactory.create(str(value), cfg, new_scope)

    @staticmethod
    def create(name: str, cfg: Optional[AMGConfig] = None,
               scope: str = "default") -> "Solver":
        if name not in _solver_registry:
            raise BadConfigurationError(f"unknown solver {name!r}; known: "
                                        f"{sorted(_solver_registry)}")
        return _solver_registry[name](cfg or AMGConfig(), scope)

    @staticmethod
    def registered() -> Dict[str, Type["Solver"]]:
        return dict(_solver_registry)


# --------------------------------------------------------------------------
# Solver base
# --------------------------------------------------------------------------
class Solver:
    """Base solver: common parameter handling + generic solve driver.

    Subclasses implement host-side :meth:`solver_setup` and the traced
    :meth:`solve_init` / :meth:`solve_iteration`.
    """

    config_name = "?"
    #: True for relaxation methods whose one iteration is one sweep
    is_smoother = False

    def __init__(self, cfg: AMGConfig, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda name: cfg.get(name, scope)
        self.max_iters = int(g("max_iters"))
        self.tolerance = float(g("tolerance"))
        self.alt_rel_tolerance = float(g("alt_rel_tolerance"))
        self.convergence = str(g("convergence"))
        self.norm_type = str(g("norm"))
        self.monitor_residual = bool(g("monitor_residual"))
        self.use_scalar_norm = bool(g("use_scalar_norm"))
        self.store_res_history = bool(g("store_res_history"))
        self.print_solve_stats = bool(g("print_solve_stats"))
        self.obtain_timings = bool(g("obtain_timings"))
        self.relaxation_factor = float(g("relaxation_factor"))
        self.A: Optional[Matrix] = None
        self.Ad: Optional[DeviceMatrix] = None
        self.scaler = None
        self._solve_fn = None
        self.setup_time = 0.0

    # ------------------------------------------------------------ lifecycle
    def setup(self, A: "Matrix | DeviceMatrix"):
        """Host-side setup (reference ``Solver::setup``, solver.cu:380-556):
        optional scaling → solver-specific setup."""
        t0 = time.perf_counter()
        self.scaler = None
        scaling = str(self.cfg.get("scaling", self.scope))
        if isinstance(A, Matrix):
            if scaling != "NONE" and A.dist is None and A.block_dim == 1:
                # scale a copy (reference scales in place then "unscales";
                # solver.cu:441-475 documents that workaround — a copy is
                # cleaner and setup-phase only)
                from .scalers import create_scaler
                self.scaler = create_scaler(scaling, self.cfg, self.scope)
                self.scaler.setup(A.scalar_csr())
                A = Matrix(self.scaler.scale_matrix(A.scalar_csr()))
            self.A = A
            self.Ad = A.device()
        else:
            self.A = None
            self.Ad = A
        self.solver_setup()
        self._solve_fn = None
        self.setup_time = time.perf_counter() - t0
        return self

    def solver_setup(self):
        """Override: build device-side data (diag inverse, hierarchy, ...)."""

    # ------------------------------------------------------- traced protocol
    def solve_init(self, b: jax.Array, x: jax.Array) -> Any:
        """Return the solver-specific iteration state pytree."""
        return ()

    def solve_iteration(self, b: jax.Array, x: jax.Array, state: Any,
                        iter_idx: jax.Array):
        """One iteration: return (x_new, state_new).

        ``iter_idx`` is the traced global iteration counter (used e.g. by
        FGMRES for its restart-cycle position).
        """
        raise NotImplementedError

    # ------------------------------------------------ preconditioner protocol
    def apply(self, b: jax.Array, x0: Optional[jax.Array] = None,
              n_iters: Optional[int] = None) -> jax.Array:
        """Traced application as a preconditioner/smoother: run a fixed
        number of iterations with no convergence monitoring (reference
        ``Solver::smooth`` / preconditioner ``solve`` with small max_iters).

        Must be called inside a trace; assumes :meth:`setup` has run.
        """
        n = self.max_iters if n_iters is None else n_iters
        x = jnp.zeros_like(b) if x0 is None else x0
        state = self.solve_init(b, x)
        for i in range(n):
            x, state = self.solve_iteration(b, x, state, jnp.asarray(i))
        return x

    def compute_residual_norm(self, b, x):
        r = b - spmv(self.Ad, x)
        return blas.norm(r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)

    # ------------------------------------------------------------- solve API
    def _tolerance_floor(self, dtype) -> float:
        """Smallest relative residual honestly reachable in ``dtype``."""
        return 25.0 * float(np.finfo(np.dtype(dtype)).eps)

    def solve(self, b, x0=None, zero_initial_guess: bool = False
              ) -> SolveResult:
        """Full solve with convergence monitoring (solver.cu:589-970).

        The entire loop runs as one jitted ``lax.while_loop``; the residual
        history (when requested) is written into a fixed-size device buffer.

        Honesty contract (the reference recomputes true residuals in its
        convergence loop, ``solver.cu:776-805``): the *final* reported norm
        is always a freshly computed true residual — solvers' cheap
        in-loop estimates (FGMRES quasi-residual, CG recursion) only steer
        the loop.  When the requested tolerance is below the device dtype's
        precision floor and a higher-precision host matrix is available,
        the solve runs as mixed-precision iterative refinement: fp32 device
        solves corrected by fp64 host residuals (the TPU realisation of the
        reference's dDFI mixed mode).
        """
        if self.Ad is None:
            raise BadConfigurationError("solve() before setup()")
        dtype = self.Ad.dtype
        b_in = b
        x0_in = None if zero_initial_guess else x0
        if self.scaler is not None:
            b = self.scaler.scale_rhs(np.asarray(b, dtype=dtype))
            if x0 is not None and not zero_initial_guess:
                x0 = self.scaler.scale_initial_guess(
                    np.asarray(x0, dtype=dtype))
        dist = self.Ad.fmt == "sharded-ell"

        floor = self._tolerance_floor(dtype)
        refine = (self.monitor_residual and self.tolerance < floor
                  and not dist and self.scaler is None
                  and self.A is not None
                  and np.dtype(self.A.host.dtype).itemsize >
                  np.dtype(dtype).itemsize)
        if (self.monitor_residual and self.tolerance < floor
                and not refine):
            amgx_output(
                f"WARNING: tolerance {self.tolerance:g} is below the "
                f"{np.dtype(dtype).name} precision floor (~{floor:.1g}); "
                "convergence to it cannot be honestly declared.\n")

        if dist:
            from ..distributed.matrix import shard_vector
            b = shard_vector(self.Ad, b)
            if x0 is not None and not zero_initial_guess:
                x0 = shard_vector(self.Ad, x0)
        else:
            b = jnp.asarray(np.asarray(b), dtype=dtype)
        if x0 is None or zero_initial_guess:
            x0 = jnp.zeros_like(b)
        elif not dist:
            x0 = jnp.asarray(np.asarray(x0), dtype=dtype)

        if self._solve_fn is None:
            # Device data (matrix pack, hierarchy levels, smoother arrays)
            # is passed INTO the jitted function as an argument pytree, not
            # captured as trace-time constants: XLA would bake constants
            # into the executable, which dies at benchmark scale (the
            # reference contract is any-N kernels, multiply.cu:75-196).
            from ._bind import DeviceBindings, bind_for_trace
            self._bindings = DeviceBindings(self)
            if dist:
                self._bindings.normalize_placement(self.Ad.mesh)
            self._solve_fn = jax.jit(
                bind_for_trace(self._bindings, self._build_solve_fn()))

        t0 = time.perf_counter()
        if refine:
            # refinement must see the caller's full-precision rhs/guess —
            # the dtype-cast b/x0 above would fold the fp32 rounding of b
            # itself into the "converged" solution
            x, iters, nrm, nrm_ini, history = self._solve_refined(b_in,
                                                                  x0_in)
        else:
            x, iters, nrm, nrm_ini, history = self._solve_fn(
                self._bindings.collect(), b, x0,
                jnp.asarray(self.tolerance, dtype),
                jnp.asarray(self.max_iters, jnp.int32))
            x.block_until_ready()
        solve_time = time.perf_counter() - t0
        if dist:
            from ..distributed.matrix import unshard_vector
            x = unshard_vector(self.Ad, x)
        if self.scaler is not None:
            x = self.scaler.unscale_solution(np.asarray(x))

        iters = int(iters)
        nrm = np.asarray(nrm)
        nrm_ini_np = np.asarray(nrm_ini)
        if self.monitor_residual:
            conv = bool(np.all(self._host_converged(nrm, nrm_ini_np)))
            diverged = bool(np.any(~np.isfinite(nrm)))
            status = (SolveStatus.SUCCESS if conv else
                      (SolveStatus.DIVERGED if diverged
                       else SolveStatus.NOT_CONVERGED))
        else:
            status = SolveStatus.SUCCESS
        history_np = None
        if self.store_res_history or self.print_solve_stats:
            history_np = np.asarray(history)[:iters + 1]
        if self.print_solve_stats:
            self._print_solve_stats(history_np, iters, status)
        if self.obtain_timings:
            amgx_output(f"Total Time: {self.setup_time + solve_time:10.6f}\n"
                        f"    setup: {self.setup_time:10.6f} s\n"
                        f"    solve: {solve_time:10.6f} s\n"
                        f"    solve(per iteration): "
                        f"{solve_time / max(iters, 1):10.6f} s\n")
        return SolveResult(x=x, iterations=iters, status=status,
                           residual_norm=nrm, residual_history=history_np,
                           setup_time=self.setup_time, solve_time=solve_time)

    def _host_norm(self, v: np.ndarray):
        """Numpy twin of ops.blas.norm — outer refinement norms must match
        the configured norm type/blocking, computed on host (device ops
        here would round-trip the tunnel every outer pass)."""
        nt, bd = self.norm_type, self.Ad.block_dim
        if self.use_scalar_norm or bd == 1:
            if nt in ("L1", "L1_SCALED"):
                r = np.sum(np.abs(v))
                return r / v.shape[0] if nt == "L1_SCALED" else r
            if nt == "LMAX":
                return np.max(np.abs(v))
            return np.linalg.norm(v)
        vb = v.reshape(-1, bd)
        if nt in ("L1", "L1_SCALED"):
            r = np.sum(np.abs(vb), axis=0)
            return r / vb.shape[0] if nt == "L1_SCALED" else r
        if nt == "LMAX":
            return np.max(np.abs(vb), axis=0)
        return np.sqrt(np.sum(np.abs(vb) ** 2, axis=0))

    def _solve_refined(self, b, x0):
        """Mixed-precision iterative refinement: device solves in the pack
        dtype, residuals recomputed on host in the matrix's (wider) dtype.
        Each inner pass only needs to shave ~the device-dtype floor off the
        residual; the outer loop carries the true fp64 residual down to the
        requested tolerance (dDFI analog; reference mixed modes,
        ``amgx_config.h:114-123``).  ``b``/``x0`` arrive in the CALLER's
        precision, never pre-rounded to the device dtype."""
        dtype = self.Ad.dtype
        A64 = self.A.host
        b64 = np.asarray(b, dtype=A64.dtype).ravel()
        inner_tol = jnp.asarray(
            max(self.tolerance, 2.0 * self._tolerance_floor(dtype)), dtype)
        x64 = (np.zeros_like(b64) if x0 is None
               else np.asarray(x0, dtype=A64.dtype).ravel())
        histories = []
        total_iters = 0
        nrm_ini = None
        max_outer = 8
        for _ in range(max_outer):
            r64 = b64 - A64 @ x64
            nrm_true = np.atleast_1d(self._host_norm(r64))
            if nrm_ini is None:
                nrm_ini = nrm_true
                histories.append(nrm_ini[None, :])
            if self._host_converged(nrm_true, nrm_ini).all():
                break
            remaining = self.max_iters - total_iters
            if remaining <= 0:
                break
            scale = float(np.max(np.abs(r64))) or 1.0
            rb = jnp.asarray((r64 / scale).astype(dtype))
            dx, it, nrm, _, hist = self._solve_fn(
                self._bindings.collect(), rb, jnp.zeros_like(rb), inner_tol,
                jnp.asarray(remaining, jnp.int32))
            dx.block_until_ready()
            x64 = x64 + scale * np.asarray(dx, dtype=A64.dtype)
            total_iters += int(it)
            # drop each pass's duplicate initial-residual row so the full
            # history has exactly total_iters + 1 rows
            histories.append(np.atleast_2d(np.asarray(hist))
                             [1:int(it) + 1] * scale)
        r64 = b64 - A64 @ x64
        nrm_final = np.atleast_1d(self._host_norm(r64))
        history = np.concatenate(
            [np.broadcast_to(h, (h.shape[0], nrm_ini.shape[0]))
             for h in histories]) if histories else nrm_ini[None, :]
        # keep the wide-precision solution: rounding x back to the device
        # dtype would throw away exactly the digits refinement bought
        return x64, total_iters, nrm_final, nrm_ini, history

    def _host_converged(self, nrm, nrm_ini):
        crit = self.convergence
        tol = self.tolerance
        if crit == "ABSOLUTE":
            return nrm <= tol
        if crit in ("RELATIVE_INI", "RELATIVE_INI_CORE"):
            return nrm <= tol * nrm_ini
        if crit in ("RELATIVE_MAX", "RELATIVE_MAX_CORE"):
            return nrm <= tol * nrm_ini  # max ≥ ini; conservative host check
        if crit == "COMBINED_REL_INI_ABS":
            return (nrm <= tol) | (nrm <= self.alt_rel_tolerance * nrm_ini)
        return nrm <= tol

    def _print_solve_stats(self, history, iters, status):
        if history is None:
            return
        lines = ["           iter      Mem Usage (GB)       residual      "
                 "rate\n",
                 "         --------------------------------------------------"
                 "------------\n"]
        prev = None
        for i, h in enumerate(history):
            hval = float(np.max(h))
            rate = "" if prev in (None, 0.0) else f"{hval / prev:9.4f}"
            label = "Ini" if i == 0 else f"{i - 1:4d}"
            lines.append(f"        {label}              -         "
                         f"{hval:15.6e}  {rate}\n")
            prev = hval
        lines.append("         ----------------------------------------------"
                     "----------------\n")
        lines.append(f"        Total Iterations: {iters}\n")
        amgx_output("".join(lines))

    # ------------------------------------------------------- the jitted loop
    def _build_solve_fn(self) -> Callable:
        monitor = self.monitor_residual
        keep_history = self.store_res_history or self.print_solve_stats
        max_iters = self.max_iters
        crit = self.convergence
        alt_tol = self.alt_rel_tolerance

        def solve_fn(b, x0, tol, it_limit):
            r0 = b - spmv(self.Ad, x0)
            nrm_ini = blas.norm(r0, self.norm_type, self.Ad.block_dim,
                                self.use_scalar_norm)
            nrm_ini = jnp.atleast_1d(nrm_ini)
            history = jnp.zeros((max_iters + 1,) + nrm_ini.shape,
                                dtype=nrm_ini.dtype)
            history = history.at[0].set(nrm_ini)
            state0 = self.solve_init(b, x0)

            def cond(carry):
                x, state, it, nrm, nmax, done, hist = carry
                return (~done) & (it < jnp.minimum(it_limit, max_iters))

            def body(carry):
                x, state, it, nrm, nmax, done, hist = carry
                x, state = self.solve_iteration(b, x, state, it)
                if monitor:
                    est = self.residual_norm_estimate(b, x, state)
                    if est is None:
                        est = self.compute_residual_norm(b, x)
                    nrm = jnp.atleast_1d(est)
                    nmax = jnp.maximum(nmax, nrm)
                    done = check_convergence(crit, nrm, nrm_ini, nmax,
                                             tol, alt_tol)
                    done = done | ~jnp.all(jnp.isfinite(nrm))
                if keep_history:
                    hist = hist.at[it + 1].set(nrm)
                return x, state, it + 1, nrm, nmax, done, hist

            done0 = jnp.asarray(False)
            if monitor:
                done0 = check_convergence(crit, nrm_ini, nrm_ini, nrm_ini,
                                          tol, alt_tol)
            carry = (x0, state0, jnp.asarray(0, jnp.int32), nrm_ini, nrm_ini,
                     done0, history)
            x, state, it, nrm, nmax, done, history = jax.lax.while_loop(
                cond, body, carry)
            x = self.solve_finalize(b, x, state)
            if monitor:
                # the declared norm is a freshly computed TRUE residual —
                # in-loop estimates (quasi-residual, CG recursion) only
                # steer the loop (reference solver.cu:776-805)
                nrm = jnp.atleast_1d(self.compute_residual_norm(b, x))
            return x, it, nrm, nrm_ini, history

        return solve_fn

    def residual_norm_estimate(self, b, x, state):
        """Solvers with an implicit residual estimate (FGMRES quasi-residual)
        override this to avoid an extra SpMV per iteration."""
        return None

    def solve_finalize(self, b, x, state):
        return x

    # ------------------------------------------------------------- utilities
    def grid_stats(self) -> str:
        return ""
