"""Solver framework.

TPU-native re-design of the reference solver contract
(``base/include/solvers/solver.h:44-325``, ``base/src/solvers/solver.cu``):

* :class:`Solver` — base class owning A, convergence criterion, norms and the
  generic ``setup()`` / ``solve()`` drivers (reference ``solver.cu:380-970``).
* :class:`SolverFactory` — named registry; nested solvers are allocated from
  a config scope (reference ``solver.h:287-325`` + ``core.cu:612-641``).
* Convergence criteria: ABSOLUTE / RELATIVE_INI(_CORE) / RELATIVE_MAX(_CORE) /
  COMBINED_REL_INI_ABS (``core/src/convergence/``).

Execution model (the TPU-first redesign): ``setup()`` runs on host (irregular
graph work → frozen device arrays); the whole ``solve()`` loop is traced once
and executed as a single XLA computation via ``lax.while_loop`` over a state
pytree.  Preconditioner/smoother application is traced inline into the outer
iteration (the reference achieves composition via virtual calls at run time;
here composition happens at trace time, letting XLA fuse across the stack).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..config import AMGConfig
from ..core.matrix import DeviceMatrix, Matrix
from ..errors import (AMGXError, BadConfigurationError,
                      BadParametersError, FailureInfo, FailureKind,
                      RC, SolveStatus, breakdown_kind,
                      BREAKDOWN_KRYLOV, BREAKDOWN_NAN,
                      BREAKDOWN_DIVERGENCE)
from ..utils import faultinject
from ..ops import blas
from ..ops.spmv import spmv
from ..utils.logging import amgx_output
from ..utils.profiler import cpu_profiler


# --------------------------------------------------------------------------
# Convergence criteria (core/src/convergence/)
# --------------------------------------------------------------------------
def check_convergence(criterion: str, nrm, nrm_ini, nrm_max, tolerance,
                      alt_rel_tolerance):
    """Return a boolean scalar: has the solve converged?

    All comparisons are per block-component and must hold for every
    component (reference block norms).
    """
    if criterion in ("ABSOLUTE",):
        ok = nrm <= tolerance
    elif criterion in ("RELATIVE_INI", "RELATIVE_INI_CORE"):
        ok = nrm <= tolerance * nrm_ini
    elif criterion in ("RELATIVE_MAX", "RELATIVE_MAX_CORE"):
        ok = nrm <= tolerance * nrm_max
    elif criterion == "COMBINED_REL_INI_ABS":
        ok = (nrm <= tolerance) | (nrm <= alt_rel_tolerance * nrm_ini)
    else:
        raise BadConfigurationError(f"unknown convergence {criterion!r}")
    return jnp.all(ok)


def _inject_fault(fault, it, x, state):
    """Apply a traced fault-injection point to the iteration state at
    its target iteration (``fault = (mode, iteration)`` — see
    ``utils.faultinject.TRACED_POINTS``).  Only ever traced when a
    point is armed; the clean path never calls this."""
    mode, f_it = fault
    tgt = jnp.asarray(int(f_it), jnp.int32)
    if mode == "values_nan":
        def poison(v):
            if not jnp.issubdtype(v.dtype, jnp.inexact):
                return v
            bad = jnp.asarray(float("nan"), v.dtype)
            return jnp.where(it == tgt, v * bad, v)
        return poison(x), jax.tree_util.tree_map(poison, state)
    # krylov_zero: collapse the 0-dim Krylov scalars (CG's rho) while
    # the residual vectors stay healthy — the classic rho-breakdown
    def zero_scalar(v):
        if not jnp.issubdtype(v.dtype, jnp.inexact) or v.ndim != 0:
            return v
        return jnp.where(it == tgt, jnp.zeros_like(v), v)
    return x, jax.tree_util.tree_map(zero_scalar, state)


@dataclasses.dataclass
class SolveResult:
    x: jax.Array
    iterations: int
    status: SolveStatus
    residual_norm: Optional[np.ndarray]
    residual_history: Optional[np.ndarray]
    setup_time: float = 0.0
    solve_time: float = 0.0
    #: what went wrong (errors.FailureInfo: taxonomy kind + the first
    #: iteration the in-loop guards observed it at); None on SUCCESS
    failure: Optional[FailureInfo] = None
    #: recovery-ladder audit (solvers/recovery.py) when the solve was
    #: retried: {"kind", "action", "attempts", "outcome"}; None when no
    #: recovery ran
    recovery: Optional[dict] = None


# --------------------------------------------------------------------------
# Factory registry (reference SolverFactory, solver.h:287-325)
# --------------------------------------------------------------------------
_solver_registry: Dict[str, Type["Solver"]] = {}


def register_solver(name: str):
    def deco(cls):
        _solver_registry[name] = cls
        cls.config_name = name
        return cls
    return deco


class SolverFactory:
    @staticmethod
    def allocate(cfg: AMGConfig, scope: str, param_name: str) -> "Solver":
        """Allocate the solver named by config param ``param_name`` in
        ``scope``; the solver reads its own params from its new scope.

        Reference: ``SolverFactory::allocate(cfg, current_scope,
        solver_name)`` pattern used e.g. at ``fgmres_solver.cu:243-253``.
        """
        value, new_scope = cfg.get_scoped(param_name, scope)
        return SolverFactory.create(str(value), cfg, new_scope)

    @staticmethod
    def create(name: str, cfg: Optional[AMGConfig] = None,
               scope: str = "default") -> "Solver":
        if name not in _solver_registry:
            raise BadConfigurationError(f"unknown solver {name!r}; known: "
                                        f"{sorted(_solver_registry)}")
        return _solver_registry[name](cfg or AMGConfig(), scope)

    @staticmethod
    def registered() -> Dict[str, Type["Solver"]]:
        return dict(_solver_registry)


# --------------------------------------------------------------------------
# Solver base
# --------------------------------------------------------------------------
def _window_fits(csr) -> "Optional[bool]":
    """Does a CSR matrix fit the windowed-kernel budget?  True/False, or
    None when it is outside the kernel's row-width envelope entirely
    (K > 160 — no reordering can rescue that)."""
    from ..core.matrix import ell_layout
    from ..ops.pallas_ell import ell_window_pack
    for_rows, pos, k = ell_layout(csr.indptr, csr.indices)
    if k > 160:
        return None
    cols = np.zeros((csr.shape[0], k), dtype=np.int32)
    cols[for_rows, pos] = csr.indices
    return ell_window_pack(cols) is not None


def _binned_fits(csr) -> bool:
    """Does the binned sliced-ELL plan (ops/pallas_csr.py) carry this
    matrix efficiently?  True when the plan's padded-lane factor is
    small enough that the binned kernel's throughput class matches or
    beats the windowed kernel an RCM permute could rescue — AUTO
    reordering then skips the O(nnz log n) RCM pass (and the permuted
    solve boundary) entirely.  The probe re-runs the pack's layout plan
    (one extra O(nnz log nnz) host pass at setup); that is the price of
    the decision, still well under the RCM+repack it avoids."""
    from ..ops.pallas_csr import binned_pad_factor
    pf = binned_pad_factor(csr.indptr, csr.indices, csr.shape[1])
    return pf is not None and pf <= 2.0


class Solver:
    """Base solver: common parameter handling + generic solve driver.

    Subclasses implement host-side :meth:`solver_setup` and the traced
    :meth:`solve_init` / :meth:`solve_iteration`.
    """

    config_name = "?"
    #: True for relaxation methods whose one iteration is one sweep
    is_smoother = False

    def __init__(self, cfg: AMGConfig, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda name: cfg.get(name, scope)
        self.max_iters = int(g("max_iters"))
        self.tolerance = float(g("tolerance"))
        self.alt_rel_tolerance = float(g("alt_rel_tolerance"))
        self.convergence = str(g("convergence"))
        self.norm_type = str(g("norm"))
        self.monitor_residual = bool(g("monitor_residual"))
        self.use_scalar_norm = bool(g("use_scalar_norm"))
        self.store_res_history = bool(g("store_res_history"))
        self.print_solve_stats = bool(g("print_solve_stats"))
        self.obtain_timings = bool(g("obtain_timings"))
        self.relaxation_factor = float(g("relaxation_factor"))
        # communication-avoiding Krylov (ops/blas.py): the knob picks
        # the reduction layout (CLASSIC / CA / PIPELINED); the ledger
        # counts, at trace time, the reduction ops one iteration body
        # performs — the truth behind amgx_krylov_collectives_total
        self.krylov_comm = str(g("krylov_comm"))
        self.ca_residual_replace = int(g("ca_residual_replace"))
        self._collective_ledger = blas.CollectiveLedger()
        # structured telemetry (amgx_tpu/telemetry/): the knob enables
        # the process-global recorder; keeping the residual history is
        # what makes per-iteration residual records available post-solve
        self.telemetry_path = str(g("telemetry_path"))
        if int(g("telemetry")):
            telemetry.enable(int(g("telemetry_ring_size")))
            self.store_res_history = True
        # convergence forensics (telemetry/forensics.py): cycle-anatomy
        # instrumentation rides the hierarchy (amg/cycles.py reads the
        # same knob); at this layer the knob keeps the residual history
        # so the asymptotic convergence-factor estimate can be computed
        # per solve
        self.forensics = bool(int(g("forensics")))
        if self.forensics:
            self.store_res_history = True
        # setup profiler (telemetry/setup_profile.py): per-phase setup
        # attribution with compile/transfer/memory splits; the knob
        # enables the process-global profiler (which enables the
        # telemetry recorder — phase records live in the same ring)
        if int(g("setup_profile")):
            telemetry.setup_profile.enable()
        # HBM ledger (telemetry/memledger.py): device-memory ownership
        # attribution + OOM post-mortems.  Off by default — with the
        # knob off every registration site is one attribute check and
        # solve traces are byte-identical
        if int(g("memledger")):
            telemetry.memledger.enable(
                sample_s=float(g("memledger_sample_s")))
        # zero cold-start (utils/jaxcompat.py + serve/aot.py): an
        # explicit compile_cache_dir disk-backs every jit in the stack;
        # aot_store_dir additionally serializes the hot executables so
        # a fresh process skips tracing too.  Idempotent and knob-gated
        # — solvers built without the knobs keep the import-time env
        # defaults untouched
        cache_dir = str(g("compile_cache_dir"))
        if cache_dir:
            from ..utils.jaxcompat import enable_compilation_cache
            enable_compilation_cache(cache_dir)
        aot_dir = str(g("aot_store_dir"))
        if aot_dir:
            from ..serve import aot as _aot
            _aot.configure(aot_dir)
        if cache_dir or aot_dir:
            # cumulative cache-efficacy counters survive restarts in a
            # state file next to the warm-start artifacts
            telemetry.runstate.configure_default(aot_dir or cache_dir)
        # breakdown-aware solving (solvers/recovery.py +
        # utils/faultinject.py): the recovery ladder is opt-in
        # (recovery_policy=AUTO); a non-empty fault_inject spec arms
        # the process-global injection plan — configuring from the
        # solver keeps C-shaped drivers on the one-config-string model
        self.recovery_policy = str(g("recovery_policy"))
        self.recovery_max_attempts = int(g("recovery_max_attempts"))
        fi_spec = str(g("fault_inject"))
        if fi_spec:
            # idempotent per spec: nested/session/twin solvers built
            # from the same config must not re-arm consumed triggers
            faultinject.configure_knob(fi_spec)
        # an EXPLICIT verbosity_level drives the level-gated output
        # stream; the registry default must not clobber a verbosity the
        # host application set programmatically
        if cfg.has("verbosity_level", scope):
            from ..utils.logging import set_verbosity
            set_verbosity(int(g("verbosity_level")))
        self.A: Optional[Matrix] = None
        self.Ad: Optional[DeviceMatrix] = None
        self.scaler = None
        self._solve_fn = None
        self._refined_fn = None
        self._solve_multi = None
        self._solve_multi_refined = None
        self._bindings = None
        self.setup_time = 0.0

    # ------------------------------------------------------------ lifecycle
    def setup(self, A: "Matrix | DeviceMatrix"):
        """Host-side setup (reference ``Solver::setup``, solver.cu:380-556):
        optional scaling → solver-specific setup."""
        phase = "resetup" if getattr(self, "_numeric_resetup", False) \
            else "setup"
        # nested solvers (smoothers, coarse solver, preconditioner)
        # re-enter setup(): their spans nest in the trace (that is how
        # "where did setup time go" reads), but the phase METRICS are
        # top-level only — 8 overlapping amgx_setup_seconds samples per
        # user-facing setup would inflate every aggregate
        toplevel = bool(getattr(self, "_toplevel", False))
        t0 = time.perf_counter()
        # setup attribution (telemetry/setup_profile.py): only the
        # TOP-LEVEL setup opens a profile scope — nested smoother/
        # coarse-solver setups contribute phases into it
        _sp = telemetry.setup_profile
        prof = _sp.profile_setup(self.config_name) if toplevel \
            else _sp.null()
        try:
            with telemetry.span(phase, solver=self.config_name,
                                scope=self.scope, toplevel=toplevel), \
                    prof:
                self._setup_impl(A)
        except Exception as e:
            # device OOM (real RESOURCE_EXHAUSTED or the injected
            # fault_inject `oom` point): emit the ledger post-mortem
            # before the failure propagates — what was resident is
            # exactly the forensic record an OOM destroys
            if telemetry.memledger.is_oom_error(e):
                telemetry.memledger.emit_postmortem(
                    e, "setup", in_recovery=bool(
                        getattr(self, "_in_recovery", False)))
            raise
        self.setup_time = time.perf_counter() - t0
        if toplevel:
            telemetry.memledger.maybe_sample(phase=phase)
        if toplevel and telemetry.is_enabled():
            telemetry.hist_observe(f"amgx_{phase}_seconds",
                                   self.setup_time)
            telemetry.gauge_set("amgx_last_setup_seconds",
                                self.setup_time)
            if self.Ad is not None:
                # the fine operator's static cost descriptor
                # (telemetry/costmodel.py): bytes/FLOPs per apply,
                # padding waste, halo wire bytes when sharded — the
                # doctor pairs it with span durations for
                # achieved-vs-peak fractions
                try:
                    from ..telemetry import costmodel
                    telemetry.event(
                        "operator_cost", solver=self.config_name,
                        **costmodel.spmv_cost(
                            self.Ad,
                            nnz=self.A.nnz if self.A is not None
                            else None))
                except Exception:
                    pass    # a cost-model gap must never break setup
            # device setup engine (amg/device_setup/): plan-cache
            # hit/miss/fallback counts of the pattern-keyed Galerkin
            # executables — None (and zero cost) when nothing ever
            # instantiated the engine
            try:
                from ..amg.device_setup import engine_stats
                st = engine_stats()
                if st is not None:
                    telemetry.event("device_setup_cache", **st)
            except Exception:
                pass        # observability must never break setup
            if self.telemetry_path:
                telemetry.flush_jsonl(self.telemetry_path)
        return self

    def _apply_precision_knobs(self, A: Matrix) -> Matrix:
        """``krylov_dtype`` / ``tpu_matrix_dtype``: the TOP-LEVEL
        solver's device pack dtype — which IS the Krylov loop's
        vector/dot/monitoring precision.  Only the outermost solver
        applies it: nested smoothers get their storage precision from
        the hierarchy policy (``hierarchy_dtype``), and a default-scope
        knob leaking into every nested setup would override it.

        Returns a shallow VIEW when the knob applies — the caller's
        matrix is never mutated (a second solver sharing the same
        Matrix must see its own dtype choice, not this one's).  Packs
        that would lose an f32-only kernel layout keep their dtype
        (``precision.precision_view`` returns ``A`` unchanged)."""
        from ..core import precision
        kd = precision.resolve_dtype(
            str(self.cfg.get("krylov_dtype", self.scope)))
        if kd is None:
            kd = precision.resolve_dtype(
                str(self.cfg.get("tpu_matrix_dtype", self.scope)))
        if kd is None:
            return A
        cur = np.dtype(A.device_dtype or A.dtype)
        kd = np.dtype(kd)
        if cur == kd:
            return A
        dev = A._device
        if dev is not None and kd.itemsize > \
                np.dtype(dev.dtype).itemsize:
            # widening an existing pack must rebuild from the wide host
            # values (an on-device upcast would keep the narrow digits)
            import copy
            m = copy.copy(A)
            m.device_dtype = kd
            m._device = None
            m._device_dtype = None
            m._dinv_dev = None
            return m
        # narrowing (or no pack yet): on-device cast when a pack
        # exists (zero wire bytes), cast-on-upload otherwise
        return precision.precision_view(A, kd)

    def _setup_impl(self, A: "Matrix | DeviceMatrix"):
        # the matrix AS THE CALLER PASSED IT (pre-scaling/reorder): the
        # recovery ladder's conservative/resetup rungs rebuild from it —
        # re-running setup on the scaled copy would scale twice.  Only
        # retained when the ladder can use it: with recovery off,
        # pinning the original next to a scaled/reordered copy would
        # double host matrix retention for nothing
        if self.recovery_policy not in ("", "NONE"):
            self._setup_input = A
        faultinject.maybe_raise(
            "setup_error", AMGXError("injected setup failure", RC.CORE))
        self.scaler = None
        self._reorder = None
        scaling = str(self.cfg.get("scaling", self.scope))
        if isinstance(A, Matrix):
            if getattr(self, "_toplevel", False) and A.dist is None:
                A = self._apply_precision_knobs(A)
            if scaling != "NONE" and A.dist is None and A.block_dim == 1:
                # scale a copy (reference scales in place then "unscales";
                # solver.cu:441-475 documents that workaround — a copy is
                # cleaner and setup-phase only)
                from .scalers import create_scaler
                with cpu_profiler("setup_scaling"), \
                        telemetry.setup_profile.phase("scaling"):
                    self.scaler = create_scaler(scaling, self.cfg,
                                                self.scope)
                    self.scaler.setup(A.scalar_csr())
                    dd = A.device_dtype
                    A = Matrix(self.scaler.scale_matrix(A.scalar_csr()))
                    # the scaled copy must keep the precision knobs'
                    # pack dtype (the reorder copy does the same)
                    A.device_dtype = dd
            if getattr(self, "_toplevel", False):
                # reordering is OWNED by the outermost solver: only its
                # solve() has the permute boundary — a nested smoother/
                # preconditioner permuting its operator would be fed
                # residuals in the un-permuted level ordering
                with telemetry.setup_profile.phase("reorder"):
                    A2 = self._maybe_reorder(A)
                if A2 is not None:
                    A = A2
            self.A = A
            faultinject.maybe_raise(
                "upload_error",
                AMGXError("injected transfer/upload failure",
                          RC.CUDA_FAILURE))
            faultinject.maybe_raise(
                "oom", AMGXError("injected device out-of-memory",
                                 RC.NO_MEMORY))
            with cpu_profiler("matrix_pack_device"), \
                    telemetry.setup_profile.phase("pack", kind="device"):
                self.Ad = A.device()
        else:
            self.A = None
            self.Ad = A
        ml = telemetry.memledger
        if ml.is_enabled() and getattr(self, "_toplevel", False) \
                and self.Ad is not None:
            # the top-level operator pack; hierarchy/transfer/smoother
            # packs register themselves (amg/hierarchy.py) and claim
            # their buffers ahead of this aggregate-adjacent owner
            ml.release(getattr(self, "_ml_matrix_tok", None))
            self._ml_matrix_tok = ml.register(
                ml.owner_name("matrix", self.config_name), self.Ad)
        with cpu_profiler(f"setup:{self.config_name}"):
            self.solver_setup()
        if getattr(self, "_numeric_resetup", False) \
                and (self._solve_fn is not None
                     or self._solve_multi is not None
                     or self._solve_multi_refined is not None) \
                and self._bindings is not None:
            # numeric re-setup (resetup() only — a plain setup() keeps
            # its full-rebuild contract): keep the jitted executables and
            # refresh the binding slots in place — with unchanged array
            # shapes jax.jit's cache hits and the ~20 s remote recompile
            # is skipped (AMGX_solver_resetup contract: same structure,
            # new values).  A structural change alters the argument
            # pytree and retraces automatically.
            if hasattr(self, "_refine_lo"):
                del self._refine_lo       # stale rounding residue
                self._ensure_refine_data()
            self._bindings._discover(self)
            if self.Ad is not None and self.Ad.fmt == "sharded-ell":
                # rebuilt consolidated coarse levels may sit on a device
                # subset again — re-replicate them onto the mesh
                self._bindings.normalize_placement(self.Ad.mesh)
        else:
            self._solve_fn = None
            self._refined_fn = None
            self._solve_multi = None
            self._solve_multi_refined = None
            # a full rebuild replaces hierarchy/level objects: bindings
            # slots referencing the OLD objects would keep serving stale
            # device data to a later solve_multi
            self._bindings = None
            # new matrix values ⇒ stale rounding residue; next refined
            # solve rebuilds it (and the bindings that carry it)
            if hasattr(self, "_refine_lo"):
                del self._refine_lo

    def resetup(self, A: "Matrix | DeviceMatrix"):
        """Numeric refresh after ``replace_coefficients``: same structure,
        new values (``AMGX_solver_resetup``).  Compiled executables,
        nested preconditioner instances, and hierarchy structure survive;
        a plain ``setup()`` remains a full rebuild."""
        self._numeric_resetup = True
        try:
            return self.setup(A)
        finally:
            self._numeric_resetup = False

    def _maybe_reorder(self, A: Matrix) -> Optional[Matrix]:
        """Setup-time RCM bandwidth reduction — the gather-cliff rescue.

        A matrix that is neither DIA-eligible nor within the windowed
        kernel's per-tile column-block budget would fall onto XLA's TPU
        gather lowering (~0.2 GFLOPS, three orders below the window
        kernel).  AUTO mode permutes such matrices with reverse
        Cuthill–McKee ONCE at setup when that makes the window fit; the
        whole solve then runs in permuted space and rhs/solution are
        converted at the solve boundaries (reference analog: setup-time
        renumbering, ``matrix.cu:760-813``).  Returns the permuted
        Matrix, or None to keep ``A``."""
        mode = str(self.cfg.get("matrix_reorder", self.scope))
        # probe private fields, not the .host property: that would lazily
        # assemble CSR for DIA-backed matrices (a device-generated 256³
        # operator never needs a host CSR; AUTO bails on DIA below anyway)
        if mode == "NONE" or not isinstance(A, Matrix) or \
                A.dist is not None or A.block_dim != 1 or \
                (A._host is None and A._dia is None and
                 getattr(A, "_dia_thunk", None) is None) or \
                A.shape[0] != A.shape[1]:
            return None
        if mode == "AUTO":
            if getattr(A, "_dia_offsets_hint", None) is not None:
                # device-generated stencil: DIA-backed by construction,
                # AUTO never reorders those — skip without materialising
                # the host arrays
                return None
            from ..ops.pallas_ell import _INTERPRET
            if not (jax.default_backend() == "tpu" or _INTERPRET):
                return None
            dtype = np.dtype(A.device_dtype or A.dtype)
            if dtype != np.float32 or A.dia_cache(48) is not None:
                return None
            csr0 = A.scalar_csr()
            if _window_fits(csr0) is not False:
                return None     # already window-eligible (or too wide)
            if _binned_fits(csr0):
                # the binned sliced-ELL kernel already carries this
                # matrix at windowed-kernel class or better — no RCM
                return None
        from scipy.sparse.csgraph import reverse_cuthill_mckee
        csr = A.scalar_csr()
        perm = np.asarray(reverse_cuthill_mckee(csr,
                                                symmetric_mode=False),
                          dtype=np.int64)
        csr_p = csr[perm][:, perm].tocsr()
        if mode == "AUTO" and _window_fits(csr_p) is not True:
            return None          # RCM didn't make the window fit
        Ap = Matrix(csr_p)
        Ap.device_dtype = A.device_dtype
        Ap.placement = A.placement
        self._reorder = (perm, np.argsort(perm))
        return Ap

    def solver_setup(self):
        """Override: build device-side data (diag inverse, hierarchy, ...)."""

    # ------------------------------------------------------- traced protocol
    def solve_init(self, b: jax.Array, x: jax.Array) -> Any:
        """Return the solver-specific iteration state pytree."""
        return ()

    def solve_iteration(self, b: jax.Array, x: jax.Array, state: Any,
                        iter_idx: jax.Array):
        """One iteration: return (x_new, state_new).

        ``iter_idx`` is the traced global iteration counter (used e.g. by
        FGMRES for its restart-cycle position).
        """
        raise NotImplementedError

    # ------------------------------------------------ preconditioner protocol
    def apply(self, b: jax.Array, x0: Optional[jax.Array] = None,
              n_iters: Optional[int] = None) -> jax.Array:
        """Traced application as a preconditioner/smoother: run a fixed
        number of iterations with no convergence monitoring (reference
        ``Solver::smooth`` / preconditioner ``solve`` with small max_iters).

        Must be called inside a trace; assumes :meth:`setup` has run.

        Smoother applications carry the ``amgx/smoother/<config_name>``
        named scope (telemetry/scopes.py contract) so the profiler-trace
        correlator can attribute their device time.
        """
        n = self.max_iters if n_iters is None else n_iters
        x = jnp.zeros_like(b) if x0 is None else x0
        if self.is_smoother:
            with telemetry.scopes.scope("smoother", self.config_name):
                state = self.solve_init(b, x)
                for i in range(n):
                    x, state = self.solve_iteration(b, x, state,
                                                    jnp.asarray(i))
            return x
        state = self.solve_init(b, x)
        for i in range(n):
            x, state = self.solve_iteration(b, x, state, jnp.asarray(i))
        return x

    def compute_residual_norm(self, b, x):
        r = b - spmv(self.Ad, x)
        return blas.norm(r, self.norm_type, self.Ad.block_dim,
                         self.use_scalar_norm)

    # ------------------------------------------------------------- solve API
    def _sync_fault_trace(self):
        """Fault injection (utils/faultinject.py): an armed traced
        point (values_nan / krylov_zero) is compiled INTO the loop;
        arming-state changes must invalidate EVERY jitted solve body —
        one list, shared by both drivers, so a future cached variant
        cannot be forgotten on one path and serve a poisoned executable
        on the clean one.  Returns the active ``(mode, iteration)`` or
        None; costs one getattr when disarmed."""
        fault = faultinject.trace_mode()
        if fault != getattr(self, "_fault_trace", None):
            self._fault_trace = fault
            self._invalidate_solve_fns()
        return fault

    def _invalidate_solve_fns(self):
        """Drop every cached jitted solve body — anything compiled INTO
        the loop (fault points, the krylov_comm reduction layout) must
        call this when it changes."""
        self._solve_fn = None
        self._refined_fn = None
        self._solve_multi = None
        self._solve_multi_refined = None

    def _tolerance_floor(self, dtype) -> float:
        """Smallest relative residual honestly reachable in ``dtype``
        (core/precision.py owns the floor formula and the ladder)."""
        from ..core.precision import tolerance_floor
        return tolerance_floor(dtype)

    def _promotion_plan(self):
        """(refine_active, wide_dtype, structural_block) for the
        current tolerance.

        ``refine_active`` says whether the mixed-precision
        defect-correction outer loop (``_solve_refined``) runs;
        ``wide_dtype`` is the ladder rung recomputing true residuals
        (``core.precision.promotion_target``: bf16 → f32, f32 → f64 —
        one rounding-residue plane per promotion).  The rung needs the
        wide HOST matrix: ``lo = vals_w − w(pack(vals_w))``
        reconstructs the exact wide operator only against genuinely
        wider uploaded values.  ``structural_block`` is True when
        refinement is unavailable for reasons no precision choice can
        fix (distribution, scaling, device-only operator, complex
        modes) — the single predicate ``_check_tolerance_floor`` keys
        its warn-vs-raise split on."""
        dtype = self.Ad.dtype
        # breakdown-triggered promotion (solvers/recovery.py "promote"
        # rung): the ladder may force a promotion even when the
        # tolerance sits above the dtype floor — a stagnating/poisoned
        # narrow solve is re-run one rung wider
        forced = bool(getattr(self, "_force_promotion", False))
        if not (self.monitor_residual
                and (forced
                     or self.tolerance < self._tolerance_floor(dtype))):
            return False, None, False
        from ..core import precision
        if self.tolerance <= 0 \
                or self.Ad.fmt == "sharded-ell" \
                or self.scaler is not None or self.A is None \
                or not precision.is_floating(np.dtype(dtype)):
            # tolerance<=0 is the run-to-max_iters convention (the
            # reference's "never converge, fixed sweeps") — no
            # convergence claim is ever made, so no honesty error; it
            # keeps the historical warn-and-run like the other
            # structurally-unrefinable cases
            return False, None, True
        # Matrix.dtype, not .host.dtype: the property would lazily
        # assemble CSR for DIA-backed operators
        host_dt = np.dtype(self.A.dtype)
        if host_dt.itemsize <= np.dtype(dtype).itemsize:
            return False, None, False
        wide = precision.promotion_target(dtype, host_dt,
                                          self.tolerance)
        if wide is None and forced:
            # the tolerance alone asked for no rung — take the next one
            # up anyway (bounded by the host dtype and the hi+lo
            # reconstruction limit, same gates as promotion_target)
            ddt = np.dtype(dtype)
            for rung in precision.LADDER:
                if ddt.itemsize < rung.itemsize <= host_dt.itemsize \
                        and rung.itemsize <= 2 * ddt.itemsize:
                    wide = rung
                    break
        if wide is None:
            return False, None, False
        return True, np.dtype(wide), False

    def _check_tolerance_floor(self, refine: bool, structural: bool):
        """Below-floor tolerances without a promotion rung are a
        configuration error, not a silent stall: the solve would burn
        its full iteration budget and report NOT_CONVERGED at best —
        or, in a narrow dtype, declare a convergence no true residual
        supports.  Structurally-unrefinable solves (``structural`` from
        ``_promotion_plan``: complex modes, distribution, scaling,
        device-only operators) keep the historical warn-and-run — an
        error whose guidance could not help them would break existing
        deep-tolerance workflows."""
        dtype = self.Ad.dtype
        floor = self._tolerance_floor(dtype)
        if refine or not self.monitor_residual \
                or self.tolerance >= floor:
            return
        if structural:
            amgx_output(
                f"WARNING: tolerance {self.tolerance:g} is below the "
                f"{np.dtype(dtype).name} precision floor (~{floor:.1g});"
                " convergence to it cannot be honestly declared.\n")
            return
        raise BadParametersError(
            f"tolerance {self.tolerance:g} is below the "
            f"{np.dtype(dtype).name} precision floor (~{floor:.1g}) "
            "and no promotion rung is available: upload the matrix at "
            "a wider dtype (f64 host + narrow device pack enables the "
            "defect-correction ladder), raise the tolerance, or run "
            "the Krylov loop wider (krylov_dtype=float32 with "
            "hierarchy_dtype=bfloat16 keeps the bandwidth win)")

    def solve(self, b, x0=None, zero_initial_guess: bool = False
              ) -> SolveResult:
        """Full solve with convergence monitoring (solver.cu:589-970).

        The entire loop runs as one jitted ``lax.while_loop``; the residual
        history (when requested) is written into a fixed-size device buffer.

        Honesty contract (the reference recomputes true residuals in its
        convergence loop, ``solver.cu:776-805``): the *final* reported norm
        is always a freshly computed true residual — solvers' cheap
        in-loop estimates (FGMRES quasi-residual, CG recursion) only steer
        the loop.  When the requested tolerance is below the device dtype's
        precision floor and a higher-precision host matrix is available,
        the solve runs as mixed-precision iterative refinement: fp32 device
        solves corrected by fp64 host residuals (the TPU realisation of the
        reference's dDFI mixed mode).
        """
        if self.Ad is None:
            raise BadConfigurationError("solve() before setup()")
        dtype = self.Ad.dtype
        # the caller's untouched rhs/guess: the recovery ladder
        # (solvers/recovery.py) re-enters solve() with these — the
        # scaled/permuted/sharded forms below are per-attempt state
        b_caller, x0_caller = b, x0
        fault = self._sync_fault_trace()
        if self.scaler is not None:
            b = self.scaler.scale_rhs(np.asarray(b, dtype=dtype))
            if x0 is not None and not zero_initial_guess:
                x0 = self.scaler.scale_initial_guess(
                    np.asarray(x0, dtype=dtype))
        if self._reorder is not None:
            # the pack lives in RCM space (see _maybe_reorder): permute
            # the rhs/guess in AFTER scaling (setup scaled first, then
            # permuted — the pack is P·S·A·S·Pᵀ) and un-permute the
            # solution BEFORE unscaling on the way out; norms are
            # permutation-invariant, so monitoring is unchanged
            perm, _ = self._reorder
            b = np.asarray(b)[perm]
            if x0 is not None and not zero_initial_guess:
                x0 = np.asarray(x0)[perm]
        b_in = b
        x0_in = None if zero_initial_guess else x0
        dist = self.Ad.fmt == "sharded-ell"

        # the promotion ladder (core/precision.py): inner solves at the
        # pack dtype, true residuals recomputed one rung wider
        # (bf16 → f32, f32 → f64), bounded by the uploaded host matrix
        refine, wide, structural = self._promotion_plan()
        self._check_tolerance_floor(refine, structural)

        if dist:
            from ..distributed.matrix import shard_vector
            b = shard_vector(self.Ad, b)
            if x0 is not None and not zero_initial_guess:
                x0 = shard_vector(self.Ad, x0)
        pin = None
        if not dist:
            # pinned packs (host modes; complex modes on a TPU runtime
            # without complex support) pull the solve vectors onto THEIR
            # device — jit rejects mixed device sets
            try:
                devs = list(self.Ad.diag.devices())
                if len(devs) == 1 and devs[0] != jax.devices()[0]:
                    pin = devs[0]
            except Exception:
                pin = None
        if not dist and not refine:
            # device-resident b stays put; anything else uploads — and a
            # wrong-dtype device array is cast so the loop never silently
            # retraces in (TPU-emulated) f64.  Pinned solves go STRAIGHT
            # to the pin: staging through the default device would ship
            # (and, for complex dtypes, hang) on a backend that cannot
            # hold the data.
            if pin is not None:
                if not (isinstance(b, jax.Array) and b.dtype == dtype
                        and set(b.devices()) == {pin}):
                    b = jax.device_put(np.asarray(b, dtype=dtype), pin)
            else:
                b = jnp.asarray(b, dtype) if isinstance(b, jax.Array) \
                    else jnp.asarray(np.asarray(b), dtype=dtype)
        if not refine:
            if x0 is None or zero_initial_guess:
                if pin is not None:
                    x0 = jax.device_put(
                        np.zeros(np.shape(b), dtype=dtype), pin)
                else:
                    x0 = jnp.zeros_like(b)
            elif not dist:
                if pin is not None:
                    if not (isinstance(x0, jax.Array)
                            and x0.dtype == dtype
                            and set(x0.devices()) == {pin}):
                        x0 = jax.device_put(np.asarray(x0, dtype=dtype),
                                            pin)
                else:
                    x0 = jnp.asarray(x0, dtype) \
                        if isinstance(x0, jax.Array) \
                        else jnp.asarray(np.asarray(x0), dtype=dtype)

        if refine and not hasattr(self, "_refine_lo"):
            # refine became active after a non-refined solve (e.g. the user
            # tightened .tolerance): the bindings must be rebuilt so the
            # refine pack rides as a jit argument, not a trace constant
            self._solve_fn = None
        if self._solve_fn is None:
            # Device data (matrix pack, hierarchy levels, smoother arrays)
            # is passed INTO the jitted function as an argument pytree, not
            # captured as trace-time constants: XLA would bake constants
            # into the executable, which dies at benchmark scale (the
            # reference contract is any-N kernels, multiply.cu:75-196).
            from ._bind import DeviceBindings, bind_for_trace
            if refine:
                self._ensure_refine_data()
            self._bindings = DeviceBindings(self)
            # the batched executables close over the bindings object —
            # a rebuilt bindings set means they must re-bind too
            self._solve_multi = None
            self._solve_multi_refined = None
            if dist:
                self._bindings.normalize_placement(self.Ad.mesh)
            self._solve_fn = jax.jit(
                bind_for_trace(self._bindings, self._packed_solve_fn()))
            self._refined_fn = None
            self._ml_register_bindings()

        t0 = time.perf_counter()
        try:
            with telemetry.span("solve", solver=self.config_name,
                                scope=self.scope, refined=bool(refine)), \
                    cpu_profiler(f"solve:{self.config_name}"):
                if refine:
                    # refinement must see the caller's full-precision
                    # rhs/guess — the dtype-cast b/x0 above would fold
                    # the fp32 rounding of b itself into the
                    # "converged" solution
                    x, iters, brk_code, first_bad, nrm, nrm_ini, \
                        history = self._solve_refined(b_in, x0_in, wide)
                else:
                    import contextlib
                    ctx = jax.default_device(pin) if pin is not None \
                        else contextlib.nullcontext()
                    # tolerances compare against REAL norms (complex
                    # modes)
                    rdt = np.zeros((), dtype).real.dtype
                    with ctx:
                        # the scalar operands are created INSIDE the pin
                        # context — built outside they would land on the
                        # default device and ship per solve
                        call_args = (self._bindings.collect(), b, x0,
                                     jnp.asarray(self.tolerance, rdt),
                                     jnp.asarray(self.max_iters,
                                                 jnp.int32))
                        fn = self._solve_fn
                        if not dist:
                            # warm-start layer: load/compile-and-save
                            # the AOT executable for these shapes (no-op
                            # without a configured store); sharded packs
                            # keep jit.  Pinned packs (multi-lane
                            # serving: one executor lane per device)
                            # participate with a device-qualified key —
                            # a serialized executable bakes in its
                            # device assignment, so lane 3's entry must
                            # never load on lane 0
                            fn = self._maybe_aot("solve", fn, call_args,
                                                 device=pin)
                        x, stats, history = fn(*call_args)
                    # ONE small host fetch for (iters, breakdown,
                    # norms) — per-transfer cost dominates on
                    # remote-attached TPUs
                    iters, brk_code, first_bad, nrm, nrm_ini = \
                        self._decode_stats(np.asarray(stats))
        except Exception as e:
            # device OOM mid-solve: the ledger post-mortem is the only
            # record of what was resident when the allocator gave up
            if telemetry.memledger.is_oom_error(e):
                telemetry.memledger.emit_postmortem(
                    e, "solve", in_recovery=bool(
                        getattr(self, "_in_recovery", False)))
            raise
        solve_time = time.perf_counter() - t0
        telemetry.memledger.maybe_sample(phase="solve")
        # record the injection only when it actually PROVOKED something
        # (a solve converging before the target iteration — or a
        # solver whose recursion recomputes the zeroed scalar, like
        # BiCGStab under krylov_zero — must not claim a fault that
        # never bit): on monitored solves the breakdown flag is the
        # evidence; unmonitored solves can only witness the iteration
        # count
        if fault is not None and \
                (bool(brk_code) if self.monitor_residual
                 else int(iters) > int(fault[1])):
            faultinject.fired(fault[0], iteration=fault[1])
        if dist:
            from ..distributed.matrix import unshard_vector
            x = unshard_vector(self.Ad, x)
        if self._reorder is not None:
            x = np.asarray(x)[self._reorder[1]]
        if self.scaler is not None:
            x = self.scaler.unscale_solution(np.asarray(x))

        iters = int(iters)
        nrm = np.atleast_1d(np.asarray(nrm))
        nrm_ini_np = np.atleast_1d(np.asarray(nrm_ini))
        failure = None
        if self.monitor_residual:
            nrm_max_np = nrm_ini_np
            if self.convergence in ("RELATIVE_MAX", "RELATIVE_MAX_CORE") \
                    and history is not None:
                # the true running max of the monitored norms — treating
                # max as ini under-reported legitimately converged solves
                # against a growing nrm_max (solver.cu:776-805 tracks it)
                h = np.atleast_2d(np.asarray(history))[:iters + 1]
                h = self._finite_history(h, context="nrm_max")
                if h.size:
                    nrm_max_np = np.maximum(nrm_ini_np, h.max(axis=0))
            conv = bool(np.all(self._host_converged(nrm, nrm_ini_np,
                                                    nrm_max_np)))
            diverged = bool(np.any(~np.isfinite(nrm)))
            # breakdown codes with a finite terminal residual (krylov
            # rho-collapse, indefinite pAp) report FAILED — the loop was
            # cut short by the guard, not by the iteration budget
            status = (SolveStatus.SUCCESS if conv else
                      (SolveStatus.DIVERGED if diverged else
                       (SolveStatus.FAILED if brk_code
                        else SolveStatus.NOT_CONVERGED)))
            failure = self._classify_failure(conv, diverged, brk_code,
                                             first_bad, nrm, iters)
        else:
            status = SolveStatus.SUCCESS
        history_np = None
        if self.store_res_history or self.print_solve_stats:
            history_np = np.asarray(history)[:iters + 1]
        if self.print_solve_stats:
            self._print_solve_stats(history_np, iters, status)
        if self.obtain_timings:
            amgx_output(f"Total Time: {self.setup_time + solve_time:10.6f}\n"
                        f"    setup: {self.setup_time:10.6f} s\n"
                        f"    solve: {solve_time:10.6f} s\n"
                        f"    solve(per iteration): "
                        f"{solve_time / max(iters, 1):10.6f} s\n")
        if telemetry.is_enabled():
            self._emit_solve_telemetry(iters, nrm, nrm_ini_np, status,
                                       history_np, solve_time,
                                       failure=failure)
        res = SolveResult(x=x, iterations=iters, status=status,
                          residual_norm=nrm, residual_history=history_np,
                          setup_time=self.setup_time,
                          solve_time=solve_time, failure=failure)
        if status != SolveStatus.SUCCESS \
                and self.recovery_policy not in ("", "NONE") \
                and self.monitor_residual \
                and not getattr(self, "_in_recovery", False) \
                and not getattr(self, "_suppress_recovery", False):
            # bounded, telemetry-audited escalation (restart → promote
            # → conservative smoother → full re-setup); the ladder
            # re-enters solve() with _in_recovery set, so it can never
            # recurse into itself
            from .recovery import maybe_recover
            res = maybe_recover(self, b_caller, x0_caller,
                                zero_initial_guess, res)
        return res

    def _ml_register_bindings(self):
        """HBM-ledger registration of the solve-loop binding pytree
        (owner ``amgx/solve/bindings`` — an AGGREGATE owner: buffers the
        hierarchy/smoother/matrix owners already claimed stay theirs,
        so this names only the otherwise-unowned solve transients).
        One attribute check when the ledger is off."""
        ml = telemetry.memledger
        if not ml.is_enabled() or self._bindings is None:
            return
        # binding discovery just FORCED the lazy device packs (P/R
        # transfer operators materialize on first touch) — re-register
        # the hierarchies so those buffers claim under amgx/transfer/…
        # instead of falling through to this aggregate.  The hierarchy
        # hangs off self for a standalone AMG solve and off the
        # preconditioner chain for a Krylov-wrapped one
        obj, seen = self, set()
        while obj is not None and id(obj) not in seen:
            seen.add(id(obj))
            h = getattr(obj, "hierarchy", None)
            if h is not None and hasattr(h, "_register_memledger"):
                h._register_memledger()
            obj = getattr(obj, "preconditioner", None)
        ml.release(getattr(self, "_ml_bind_tok", None))
        self._ml_bind_tok = ml.register(
            ml.owner_name("solve", "bindings"), self._bindings.collect())

    def release_memledger(self):
        """Drop this solver's HBM-ledger registrations (teardown): the
        operator pack, the solve bindings, and — for AMG solvers — the
        hierarchy/transfer/smoother/coarse entries.  Weakref-backed
        entries stop counting when the arrays die anyway; explicit
        release keeps the register/release balance exact."""
        ml = telemetry.memledger
        ml.release(getattr(self, "_ml_matrix_tok", None))
        ml.release(getattr(self, "_ml_bind_tok", None))
        self._ml_matrix_tok = self._ml_bind_tok = None
        obj, seen = self, set()
        while obj is not None and id(obj) not in seen:
            seen.add(id(obj))
            h = getattr(obj, "hierarchy", None)
            if h is not None and hasattr(h, "release_memledger"):
                h.release_memledger()
            obj = getattr(obj, "preconditioner", None)

    def _maybe_aot(self, tag: str, jit_fn: Callable, args: tuple,
                   device=None) -> Callable:
        """The AOT-store executable for ``jit_fn(*args)`` when the
        warm-start layer is configured and this solve path serializes
        cleanly; else ``jit_fn`` unchanged.  Serialization gates:
        forensics inserts ``jax.debug.callback``s (host callbacks do
        not survive serialization across processes), so instrumented
        solves keep the plain jit path — the persistent compilation
        cache still covers their XLA compile.  ``device``: the pin of a
        device-pinned solve (host modes; multi-lane serving's per-chip
        executor lanes) — qualifies the store key, because a serialized
        executable carries its device assignment and must only ever be
        reloaded for that same device."""
        if self.forensics:
            return jit_fn
        if getattr(self, "_fault_trace", None) is not None:
            # a traced fault injection is compiled INTO this body — it
            # must never be serialized under the clean executable's key
            return jit_fn
        try:
            from ..serve import aot
            if aot.get_store() is None:
                return jit_fn
            if device is not None:
                tag = f"{tag}@{device.platform}{device.id}"
            # per-solve memo, living ON the bindings object: the full
            # key digests the whole bindings pytree (kilobytes for a
            # deep hierarchy) — too costly per warmed millisecond-class
            # solve.  Binding avals are fixed for a bindings object's
            # lifetime (a structural rebuild replaces it), so (tag, RHS
            # shape/dtype) identifies the executable within it.
            memo = getattr(self._bindings, "_aot_memo", None)
            if memo is None:
                memo = self._bindings._aot_memo = {}
            rhs = args[1]
            mk = (tag, getattr(rhs, "shape", None),
                  str(getattr(rhs, "dtype", "")))
            hit = memo.get(mk)
            if hit is not None:
                return hit
            if not hasattr(self, "_aot_cfg_hash"):
                self._aot_cfg_hash = self.cfg.stable_hash()
            from ..core.matrix import pack_kind
            meta = {"solver": self.config_name, "scope": self.scope,
                    "pack": pack_kind(self.Ad) if self.Ad is not None
                    else None,
                    "n_rows": int(self.Ad.n_rows)
                    if self.Ad is not None else None,
                    "dtype": str(self.Ad.dtype)
                    if self.Ad is not None else None}
            fn = aot.aot_compile(
                f"{tag}:{self.config_name}:{self.scope}", jit_fn, args,
                cfg_hash=self._aot_cfg_hash, meta=meta)
            memo[mk] = fn
            return fn
        except Exception:   # the warm-start layer must never break solve
            return jit_fn

    def _packed_solve_fn(self) -> Callable:
        """The solve body with (iters, breakdown, nrm, nrm_ini) packed
        into one f64 stats vector — ONE small host fetch per solve.
        Shared by the single-RHS driver and the vmapped multi-RHS
        driver so both stay on the same wire layout (decoded by
        :meth:`_decode_stats`: ``[it, brk_code, first_bad, nrm*m,
        nrm_ini*m]``)."""
        body = self._build_solve_fn()

        def packed(b, x0, tol, it_limit):
            x, it, nrm, nrm_ini, history, fail = body(b, x0, tol,
                                                      it_limit)
            stats = jnp.concatenate([
                it[None].astype(jnp.float64),
                fail.astype(jnp.float64),
                jnp.ravel(nrm).astype(jnp.float64),
                jnp.ravel(nrm_ini).astype(jnp.float64)])
            return x, stats, history

        return packed

    @staticmethod
    def _decode_stats(stats: np.ndarray):
        """Inverse of :meth:`_packed_solve_fn`'s wire layout:
        ``(iters, brk_code, first_bad, nrm, nrm_ini)``."""
        iters = int(stats[0])
        brk_code = int(stats[1])
        first_bad = int(stats[2])
        m = (len(stats) - 3) // 2
        return iters, brk_code, first_bad, stats[3:3 + m], stats[3 + m:]

    def _classify_failure(self, conv: bool, diverged: bool,
                          brk_code: int, first_bad: int, nrm,
                          iters: int) -> Optional[FailureInfo]:
        """The terminal :class:`~amgx_tpu.errors.FailureInfo` of a
        monitored solve (None on convergence): the in-loop guard's code
        wins (it carries the first-bad iteration); a non-finite final
        norm without one classifies by NaN-vs-inf; anything else that
        burned the budget is stagnation."""
        if conv:
            return None
        if brk_code:
            kind = breakdown_kind(brk_code)
            if kind is not None:
                return FailureInfo(
                    kind=kind,
                    iteration=first_bad if first_bad >= 0 else None)
        if diverged:
            nan = bool(np.any(np.isnan(np.asarray(nrm))))
            return FailureInfo(
                kind=(FailureKind.NAN_POISON if nan
                      else FailureKind.DIVERGENCE),
                iteration=iters)
        return FailureInfo(kind=FailureKind.STAGNATION, iteration=iters)

    def _finite_history(self, h: np.ndarray,
                        context: str = "") -> np.ndarray:
        """Filter non-finite rows out of a residual-history slab — and
        SAY SO: the old silent ``np.isfinite(...).all(axis=1)`` filters
        dropped the very rows a breakdown forensics needs, with no
        trace that the iteration record was truncated."""
        if h.size == 0:
            return h
        mask = np.isfinite(h).all(axis=1)
        if mask.all():
            return h
        first_bad = int(np.argmin(mask))
        dropped = int((~mask).sum())
        if telemetry.is_enabled():
            telemetry.counter_inc("amgx_history_truncated_total")
            telemetry.event("history_truncated",
                            first_bad_iteration=first_bad,
                            dropped=dropped, context=context,
                            solver=self.config_name)
        return h[mask]

    # ------------------------------------------------------ multi-RHS solve
    def solve_multi(self, B, X0=None, zero_initial_guess: bool = False,
                    pad_to_bucket: bool = False) -> "list[SolveResult]":
        """Batched solve of k right-hand sides against ONE operator in a
        single executable — the serving layer's micro-batch path
        (serve/batch.py).

        ``B`` is (k, n) (or a sequence of k vectors); returns one
        :class:`SolveResult` per RHS.  The batched loop is the
        single-RHS solve body vmapped over the RHS axis: per-request
        convergence monitoring is preserved (the batched ``while_loop``
        runs until every lane is done, a converged lane's state frozen
        by the standard select-masking), so one RHS can converge in 3
        iterations while its batchmate runs to the iteration limit, each
        reporting its own count, status and true final residual.
        Configurations whose executable shape is not RHS-batchable —
        distributed operators, mixed-precision refinement below the
        dtype floor, device-pinned host-mode packs — fall back to
        sequential :meth:`solve` calls with identical per-request
        results.

        ``pad_to_bucket`` (the serving micro-batcher's mode): pad the
        batch axis to the next power of two with zero RHS so a stream
        of ragged batch sizes compiles at most log2(max) executables —
        pad lanes converge at iteration 0, are excluded from telemetry,
        and only the k live results are returned."""
        if self.Ad is None:
            raise BadConfigurationError("solve_multi() before setup()")
        B = [B[i] for i in range(B.shape[0])] \
            if isinstance(B, (np.ndarray, jax.Array)) and np.ndim(B) == 2 \
            else list(B)
        k = len(B)
        if k == 0:
            return []
        dtype = self.Ad.dtype
        dist = self.Ad.fmt == "sharded-ell"
        fault = self._sync_fault_trace()
        refine, wide, structural = self._promotion_plan()
        self._check_tolerance_floor(refine, structural)
        # the bf16 → f32 promotion rung is BATCHABLE: the refined outer
        # loop vmaps like the plain solve body (f32 is TPU-native); the
        # f32 → f64 rung keeps the sequential fallback — emulated-f64
        # SpMVs under vmap blow past sane executable sizes
        refined_batch = (refine and wide == np.dtype(np.float32)
                         and not dist)
        pin = None
        if not dist:
            try:
                devs = list(self.Ad.diag.devices())
                if len(devs) == 1 and devs[0] != jax.devices()[0]:
                    pin = devs[0]
            except Exception:
                pin = None
        # device-pinned packs ride the batched path too (the multi-lane
        # serving layer pins one executor lane per device — losing
        # micro-batching there would cap every non-default lane at
        # single-RHS throughput); only the refinement ladder keeps its
        # sequential fallback under a pin
        if k == 1 or dist or (refine and not refined_batch) \
                or (refine and pin is not None):
            # sequential fallback: recovery stays OFF here so
            # solve_multi behaves uniformly across batch sizes — the
            # serving layer executes everything through this API, and
            # a ladder engaging only when a request happened to batch
            # alone would multiply that batch's deadline by the
            # attempt count (recovery.maybe_recover's scope contract)
            suppress = not getattr(self, "_suppress_recovery", False)
            if suppress:
                self._suppress_recovery = True
            try:
                out = []
                for j, bj in enumerate(B):
                    xj = None if X0 is None else X0[j]
                    out.append(self.solve(bj, x0=xj,
                                          zero_initial_guess=
                                          zero_initial_guess))
                return out
            finally:
                if suppress:
                    self._suppress_recovery = False

        Bm = np.stack([np.asarray(bj).ravel() for bj in B])
        if self.scaler is not None:
            Bm = np.stack([self.scaler.scale_rhs(r.astype(dtype))
                           for r in Bm])
        X0m = None
        if X0 is not None and not zero_initial_guess:
            X0m = np.stack([np.asarray(x).ravel() for x in X0])
            if self.scaler is not None:
                X0m = np.stack([self.scaler.scale_initial_guess(
                    r.astype(dtype)) for r in X0m])
        if self._reorder is not None:
            perm, _ = self._reorder
            Bm = Bm[:, perm]
            if X0m is not None:
                X0m = X0m[:, perm]
        if pad_to_bucket:
            bucket = 1
            while bucket < k:
                bucket <<= 1
            if bucket > k:
                Bm = np.concatenate(
                    [Bm, np.zeros((bucket - k, Bm.shape[1]), Bm.dtype)])
                if X0m is not None:
                    X0m = np.concatenate(
                        [X0m, np.zeros((bucket - k, X0m.shape[1]),
                                       X0m.dtype)])
        t0 = time.perf_counter()
        with telemetry.span("solve_multi", solver=self.config_name,
                            scope=self.scope, batch=k,
                            refined=bool(refined_batch)), \
                cpu_profiler(f"solve_multi:{self.config_name}"):
            if refined_batch:
                X, stats, history = self._solve_multi_refined_call(
                    Bm, X0m, wide)
            else:
                import contextlib
                # pinned packs: the batch arrays and scalar operands
                # are created INSIDE the pin context so the jitted
                # call never sees a mixed device set (same contract as
                # the single-RHS pin path above)
                ctx = jax.default_device(pin) if pin is not None \
                    else contextlib.nullcontext()
                with ctx:
                    Bd = jnp.asarray(Bm, dtype)
                    X0d = jnp.zeros_like(Bd) if X0m is None \
                        else jnp.asarray(X0m, dtype)
                    if self._solve_multi is None:
                        from ._bind import DeviceBindings, bind_for_trace
                        if self._bindings is None:
                            self._bindings = DeviceBindings(self)
                            self._ml_register_bindings()
                        bindings = self._bindings
                        vm = jax.vmap(self._packed_solve_fn(),
                                      in_axes=(0, 0, None, None))
                        self._solve_multi = (
                            bindings,
                            jax.jit(bind_for_trace(bindings, vm)))
                    bindings, fn = self._solve_multi
                    rdt = np.zeros((), dtype).real.dtype
                    call_args = (bindings.collect(), Bd, X0d,
                                 jnp.asarray(self.tolerance, rdt),
                                 jnp.asarray(self.max_iters, jnp.int32))
                    # warm-start layer: each batch bucket (Bd's leading
                    # dim) is its own AOT executable — the serving
                    # micro-batcher's power-of-two padding keeps that
                    # set log2(max_batch)-sized; pinned lanes key by
                    # device (see _maybe_aot)
                    X, stats, history = self._maybe_aot(
                        "solve_multi", fn, call_args,
                        device=pin)(*call_args)
            stats = np.asarray(stats)      # ONE host fetch: (k, 3+2m)
        solve_time = time.perf_counter() - t0
        if fault is not None and \
                (bool((stats[:, 1] != 0).any()) if self.monitor_residual
                 else int(stats[:, 0].max()) > int(fault[1])):
            # provoked iff ANY lane flagged the breakdown (monitored) /
            # reached the target iteration (unmonitored)
            faultinject.fired(fault[0], iteration=fault[1], batch=k)
        Xh = None
        if self._reorder is not None or self.scaler is not None:
            Xh = np.asarray(X)
        hist_all = None
        if self.store_res_history or self.print_solve_stats \
                or self.convergence in ("RELATIVE_MAX",
                                        "RELATIVE_MAX_CORE"):
            # RELATIVE_MAX needs the monitored trajectory for the true
            # running max even when the caller didn't ask to keep it —
            # same as solve()'s nrm_max recovery
            hist_all = np.asarray(history)

        results = []
        m = (stats.shape[1] - 3) // 2
        for j in range(k):
            iters, brk_code, first_bad, nrm, nrm_ini = \
                self._decode_stats(stats[j])
            nrm = np.atleast_1d(nrm)
            nrm_ini = np.atleast_1d(nrm_ini)
            failure = None
            if Xh is not None:
                xj = Xh[j]
                if self._reorder is not None:
                    xj = xj[self._reorder[1]]
                if self.scaler is not None:
                    xj = self.scaler.unscale_solution(np.asarray(xj))
            else:
                xj = X[j]
            history_np = None
            if hist_all is not None:
                history_np = np.atleast_2d(hist_all[j])[:iters + 1]
            if self.monitor_residual:
                nrm_max = nrm_ini
                if self.convergence in ("RELATIVE_MAX",
                                        "RELATIVE_MAX_CORE") \
                        and history_np is not None:
                    h = self._finite_history(history_np,
                                             context=f"nrm_max[{j}]") \
                        if history_np.size else history_np
                    if h.size:
                        nrm_max = np.maximum(nrm_ini, h.max(axis=0))
                conv = bool(np.all(self._host_converged(nrm, nrm_ini,
                                                        nrm_max)))
                diverged = bool(np.any(~np.isfinite(nrm)))
                status = (SolveStatus.SUCCESS if conv else
                          (SolveStatus.DIVERGED if diverged else
                           (SolveStatus.FAILED if brk_code
                            else SolveStatus.NOT_CONVERGED)))
                failure = self._classify_failure(conv, diverged,
                                                 brk_code, first_bad,
                                                 nrm, iters)
            else:
                status = SolveStatus.SUCCESS
            if telemetry.is_enabled():
                label = ("SUCCESS" if status == SolveStatus.SUCCESS
                         else ("DIVERGED"
                               if bool(np.any(~np.isfinite(nrm)))
                               else "NOT_CONVERGED"))
                telemetry.counter_inc("amgx_solves_total", status=label)
                if failure is not None:
                    # the serving layer executes everything through this
                    # path — production breakdowns must land in the same
                    # taxonomy counter/event the single-RHS path emits
                    telemetry.counter_inc("amgx_solve_failures_total",
                                          kind=failure.kind.value)
                    telemetry.event("breakdown",
                                    solver=self.config_name,
                                    kind=failure.kind.value,
                                    iteration=failure.iteration,
                                    batch_lane=j)
            results.append(SolveResult(
                x=xj, iterations=iters, status=status,
                residual_norm=nrm,
                # history is RETURNED only on request (solve() parity);
                # a RELATIVE_MAX fetch above serves the status math only
                residual_history=(history_np
                                  if self.store_res_history
                                  or self.print_solve_stats else None),
                setup_time=self.setup_time, solve_time=solve_time,
                failure=failure))
        if telemetry.is_enabled():
            if self.forensics:
                # drain in-flight forensics callbacks (see
                # _emit_solve_telemetry) before the flush below
                try:
                    jax.effects_barrier()
                except Exception:
                    pass
            telemetry.hist_observe("amgx_solve_seconds", solve_time)
            telemetry.gauge_set("amgx_last_solve_seconds", solve_time)
            if self.telemetry_path:
                telemetry.flush_jsonl(self.telemetry_path)
        return results

    def _solve_multi_refined_call(self, Bm, X0m, wide):
        """The batched bf16 → f32 promotion rung: the refined outer
        loop (``_build_refined_fn``) vmapped over the RHS axis — each
        lane runs its own defect-correction ladder with per-lane
        convergence, so a narrow-pack multi-RHS batch stays one
        executable instead of falling back to sequential solves (the
        f64 rung keeps that fallback; see ``solve_multi``)."""
        dtype = self.Ad.dtype
        wide = np.dtype(wide)
        had_refine = hasattr(self, "_refine_lo")
        self._ensure_refine_data()
        if self._solve_multi_refined is None \
                or self._solve_multi_refined[0] != wide:
            from ._bind import DeviceBindings, bind_for_trace
            if self._bindings is None or not had_refine:
                # fresh bindings so the refine residue (when present)
                # rides as a bound argument, never a trace constant —
                # executables closing over the OLD bindings object must
                # re-bind.  Bindings that already cover the refine data
                # are REUSED: replacing them here would invalidate
                # _solve_fn, whose next call would invalidate this
                # executable right back — a retrace ping-pong for
                # workloads alternating single- and multi-RHS solves
                self._bindings = DeviceBindings(self)
                self._ml_register_bindings()
                self._solve_fn = None
                self._refined_fn = None
                self._solve_multi = None
            vm = jax.vmap(self._build_refined_fn(wide),
                          in_axes=(0, 0, 0, 0, None, None))
            self._solve_multi_refined = (
                wide, self._bindings,
                jax.jit(bind_for_trace(self._bindings, vm)))
        _, bindings, fn = self._solve_multi_refined
        lo_dt = np.float32
        B64 = Bm.astype(np.float64, copy=False)
        Bhi = B64.astype(dtype)
        Blo = (B64 - Bhi.astype(np.float64)).astype(lo_dt)
        if X0m is None:
            Xhi = np.zeros_like(Bhi)
            Xlo = np.zeros(Bhi.shape, dtype=lo_dt)
        else:
            X64 = X0m.astype(np.float64, copy=False)
            Xhi = X64.astype(dtype)
            Xlo = (X64 - Xhi.astype(np.float64)).astype(lo_dt)
        wdt = jnp.dtype(wide.name)
        call_args = (bindings.collect(), jnp.asarray(Bhi),
                     jnp.asarray(Blo), jnp.asarray(Xhi),
                     jnp.asarray(Xlo),
                     jnp.asarray(self.tolerance, wdt),
                     jnp.asarray(self.max_iters, jnp.int32))
        # the warm-start layer covers the refined batches too: without
        # it a restarted mixed-precision serving process would pay the
        # full trace+compile on the first batch of every bucket size
        return self._maybe_aot("solve_multi_refined", fn,
                               call_args)(*call_args)

    def _emit_solve_telemetry(self, iters, nrm, nrm_ini, status,
                              history, solve_time, failure=None):
        """Per-solve telemetry: phase duration, iteration count, final
        relative residual, convergence-rate estimate, divergence event
        and the per-iteration residual trajectory (iteration 0 = the
        initial residual, matching ``AMGX_solver_get_iteration_residual``
        indexing)."""
        if self.forensics:
            # cycle-anatomy events arrive through unordered
            # jax.debug.callback: on an async backend they may still be
            # in flight when the solve returns — drain them before the
            # trace is scanned/flushed (else the doctor undercounts
            # cycles and a capture scope closing would drop them)
            try:
                jax.effects_barrier()
            except Exception:
                pass
        telemetry.hist_observe("amgx_solve_seconds", solve_time)
        telemetry.gauge_set("amgx_last_solve_seconds", solve_time)
        telemetry.gauge_set("amgx_solve_iterations", iters)
        # NOT_CONVERGED aliases DIVERGED in the reference enum (both 2);
        # distinguish by the non-finite check the status was derived from
        diverged = bool(np.any(~np.isfinite(np.asarray(nrm))))
        label = ("SUCCESS" if status == SolveStatus.SUCCESS else
                 ("DIVERGED" if diverged else "NOT_CONVERGED"))
        telemetry.counter_inc("amgx_solves_total", status=label)
        if self.monitor_residual:
            nrm_m = float(np.max(nrm))
            ini_m = float(np.max(nrm_ini))
            relres = nrm_m / ini_m if ini_m > 0 else nrm_m
            telemetry.gauge_set("amgx_solve_final_relres", relres)
            if iters > 0 and np.isfinite(relres) and relres > 0:
                telemetry.gauge_set("amgx_solve_convergence_rate",
                                    relres ** (1.0 / iters))
            if self.forensics and history is not None:
                # asymptotic convergence factor: trailing-half estimate
                # (telemetry/forensics.py) — the number that predicts
                # iteration growth, vs the whole-solve geometric mean
                # above which the fast early iterations flatter
                from ..telemetry import forensics
                rate = forensics.asymptotic_rate(
                    [float(np.max(row))
                     for row in np.atleast_2d(history)])
                if rate is not None:
                    telemetry.gauge_set(
                        "amgx_forensics_asymptotic_rate", rate)
                    telemetry.event("solve_forensics",
                                    solver=self.config_name,
                                    iterations=iters,
                                    asymptotic_rate=rate)
            if diverged:
                telemetry.counter_inc("amgx_solve_diverged_total")
                telemetry.event("divergence", solver=self.config_name,
                                iteration=iters, norm=nrm_m)
            if failure is not None:
                # the taxonomy-kinded failure record (errors.FailureKind)
                # — what the doctor's "failures & recovery" section and
                # the recovery ladder's audit key on
                telemetry.counter_inc("amgx_solve_failures_total",
                                      kind=failure.kind.value)
                telemetry.event("breakdown", solver=self.config_name,
                                kind=failure.kind.value,
                                iteration=failure.iteration)
            if history is not None:
                for i, row in enumerate(np.atleast_2d(history)):
                    telemetry.event("residual", iteration=i,
                                    norm=float(np.max(row)))
        self._emit_krylov_comm_telemetry(iters)
        if self.telemetry_path:
            telemetry.flush_jsonl(self.telemetry_path)

    def _emit_krylov_comm_telemetry(self, iters: int):
        """Per-solve communication accounting: the trace-time reduction
        profile (ops/blas.py ledger) scaled by executed iterations.  The
        counters are the measured truth ISSUE 16 gates on; the event
        additionally carries the modelled SpMV-vs-reduction split the
        doctor's latency-bound hint keys on.  Silent when the loop body
        was never traced this session (pure AOT-load path)."""
        led = getattr(self, "_collective_ledger", None)
        if led is None or not led.counts:
            return
        prof = {op: int(c) for op, c in led.counts.items()}
        iters = max(int(iters), 0)
        for op, c in prof.items():
            if iters > 0:
                telemetry.counter_inc("amgx_krylov_collectives_total",
                                      float(c * iters), op=op)
        rep = int(getattr(self, "ca_residual_replace", 0) or 0)
        n_rep = (iters - 1) // rep if (rep > 0 and iters > 1
                                       and led.replace) else 0
        if n_rep > 0:
            telemetry.counter_inc(
                "amgx_krylov_collectives_total",
                float(sum(led.replace.values()) * n_rep), op="replace")
        mode = (self._comm_mode() if hasattr(self, "_comm_mode")
                else "CLASSIC")
        ev = {
            "solver": self.config_name,
            "mode": mode,
            "iterations": iters,
            "per_iter": prof,
            "collectives_per_iter": int(sum(prof.values())),
            "fused": bool("fused" in prof),
        }
        model = telemetry.costmodel.krylov_reduction_cost(
            self.Ad, ev["collectives_per_iter"]) \
            if self.Ad is not None else None
        if model is not None:
            ev.update(model)
        else:
            ev["n_parts"] = int(getattr(self.Ad, "n_parts", 1) or 1) \
                if self.Ad is not None else 1
        telemetry.event("krylov_comm", **ev)

    def _host_norm(self, v: np.ndarray):
        """Numpy twin of ops.blas.norm — outer refinement norms must match
        the configured norm type/blocking, computed on host (device ops
        here would round-trip the tunnel every outer pass)."""
        nt, bd = self.norm_type, self.Ad.block_dim
        if self.use_scalar_norm or bd == 1:
            if nt in ("L1", "L1_SCALED"):
                r = np.sum(np.abs(v))
                return r / v.shape[0] if nt == "L1_SCALED" else r
            if nt == "LMAX":
                return np.max(np.abs(v))
            return np.linalg.norm(v)
        vb = v.reshape(-1, bd)
        if nt in ("L1", "L1_SCALED"):
            r = np.sum(np.abs(vb), axis=0)
            return r / vb.shape[0] if nt == "L1_SCALED" else r
        if nt == "LMAX":
            return np.max(np.abs(vb), axis=0)
        return np.sqrt(np.sum(np.abs(vb) ** 2, axis=0))

    def _ensure_refine_data(self):
        """Device data for on-device refinement: the rounding residue
        ``lo = vals_w − w(pack(vals_w))`` of the device pack vs the wide
        host matrix, so the traced wide SpMV can reconstruct the exact
        wide operator as ``vals.astype(w) + lo``.  ``lo`` is stored in
        f32 whatever the pack dtype (it exactly carries an f32 pack's
        f64 residue AND a bf16 pack's f32 residue), and is None —
        no extra upload — for integer-valued stencils (Poisson), which
        are exactly representable in the pack dtype."""
        if hasattr(self, "_refine_lo"):
            return
        pdt = np.dtype(self.Ad.dtype)
        if pdt == np.float32 and getattr(self.A, "_vals_f32_exact",
                                         False):
            # device-generated integer-valued stencils declare f32
            # exactness analytically — no host values to scan (a bf16
            # pack still scans: the hint promises f32, not bf16)
            self._refine_lo = None
            return
        # a pack produced by an ON-DEVICE cast holds pdt(via(v)), not
        # pdt(v) — one extra rounding (precision_view records the
        # chain); the residue must model the pack's ACTUAL values or
        # hi+lo reconstructs a subtly wrong wide operator and the
        # refined loop's "true" residual stops being true
        via = getattr(self.A, "_pack_cast_via", None) \
            if self.A is not None else None

        def to_pack(c):
            return (c.astype(via) if via is not None else c).astype(pdt)

        vals64 = self._host_pack_vals64()
        # chunked exactness scan with early exit: integer-valued stencils
        # (the common benchmark operators) are exactly representable in
        # the narrow dtype, and detecting that must not cost four full
        # passes over a ~1 GB fine-level array
        flat = vals64.reshape(-1)
        exact = True
        step = 1 << 22
        for s in range(0, flat.size, step):
            c = flat[s:s + step]
            if not np.array_equal(to_pack(c).astype(np.float64), c):
                exact = False
                break
        if exact:
            self._refine_lo = None
            return
        lo = (vals64 - to_pack(vals64).astype(np.float64)) \
            .astype(np.float32)
        self._refine_lo = jnp.asarray(lo)

    def _host_pack_vals64(self) -> np.ndarray:
        """The device pack's ``vals`` layout rebuilt on host in f64
        (must mirror ``core.matrix.pack_device`` exactly)."""
        Ad = self.Ad
        import scipy.sparse as sp
        from ..core.matrix import dia_arrays, ell_layout
        if Ad.fmt == "dia":
            if Ad.block_dim > 1:
                # block-DIA planes: rebuild (nd, n, b, b) from the BSR
                from ..core.matrix import dia_arrays_block
                b = Ad.block_dim
                bsr = self.A.host if isinstance(self.A.host,
                                                sp.bsr_matrix) else \
                    sp.bsr_matrix(self.A.host, blocksize=(b, b))
                bsr.sort_indices()
                offs, bvals = dia_arrays_block(bsr)
                assert tuple(offs) == tuple(Ad.dia_offsets)
                return bvals.astype(np.float64, copy=False)
            # dia_cache first: for DIA-backed matrices (device-generated
            # operators included) this never assembles the host CSR
            arrs = self.A.dia_cache() if isinstance(self.A, Matrix) \
                else None
            offs, vals = arrs if arrs is not None else \
                dia_arrays(sp.csr_matrix(self.A.host))
            assert tuple(offs) == tuple(Ad.dia_offsets)
            return vals.astype(np.float64, copy=False)
        host = self.A.host
        if Ad.fmt == "dense":
            return np.asarray(sp.csr_matrix(host).todense(),
                              dtype=np.float64)
        b = Ad.block_dim
        if b == 1:
            csr = sp.csr_matrix(host)
            csr.sort_indices()
            indptr, indices, data = csr.indptr, csr.indices, csr.data
            block_shape = ()
        else:
            bsr = host if isinstance(host, sp.bsr_matrix) else \
                sp.bsr_matrix(host, blocksize=(b, b))
            bsr.sort_indices()
            indptr, indices, data = bsr.indptr, bsr.indices, bsr.data
            block_shape = (b, b)
        if Ad.fmt == "csr":
            return data.astype(np.float64)
        for_rows, pos, k = ell_layout(indptr, indices)
        assert k == Ad.ell_width
        out = np.zeros((Ad.n_rows, k) + block_shape, dtype=np.float64)
        out[for_rows, pos] = data
        return out

    def _wide_pack(self, wide=np.float64):
        """The traced wide device pack of the exact host operator
        (``wide`` is the promotion rung: f64 for an f32 pack, f32 for a
        bf16 pack)."""
        Ad64 = self.Ad
        if Ad64.fmt == "ell" and Ad64.vals is None:
            # lean windowed pack: the f64 path needs the gather-form
            # arrays — rebuild them as traced views (f64 never takes the
            # f32-only window kernel)
            Ad64 = dataclasses.replace(
                Ad64, vals=Ad64.ell_vals_view(), cols=Ad64.ell_cols_view(),
                win_blocks=None, win_codes=None, win_vals=None)
        if Ad64.bn_codes is not None and Ad64.vals is not None:
            # the wide pack must dispatch on the CORRECTED gather-form
            # vals: under the interpreter the binned kernel serves f64
            # too and would read the UN-corrected bn_vals planes,
            # silently dropping the _refine_lo residue the refinement
            # residual exists for
            Ad64 = dataclasses.replace(
                Ad64, bn_codes=None, bn_vals=None, bn_meta=None,
                bn_pos=None, bn_dims=())
        wdt = jnp.dtype(np.dtype(wide).name)
        Ad64 = Ad64.astype(wdt)
        if self._refine_lo is not None:
            Ad64 = dataclasses.replace(
                Ad64, vals=Ad64.vals + self._refine_lo.astype(wdt))
        return Ad64

    def _spmv_wide(self, x64, Ad64=None, wide=np.float64):
        """Traced wide SpMV of the exact host operator (XLA emulates f64
        on TPU — slower than f32 but bit-honest, which is all the
        refinement residual needs; the bf16 → f32 rung runs native).
        Pass a precomputed ``Ad64`` when calling inside a loop: XLA does
        not reliably hoist the ~2×vals widening out of ``while`` bodies,
        and at 256³ that is ~1 GB of rematerialisation per refinement
        pass."""
        return spmv(self._wide_pack(wide) if Ad64 is None else Ad64, x64)

    def _solve_refined(self, b, x0, wide=np.float64):
        """Mixed-precision iterative refinement, entirely on device:
        inner solves run in the pack dtype, true residuals are
        recomputed at the ``wide`` promotion rung (f64 is XLA-emulated
        on TPU; the bf16 → f32 rung runs native) inside the same
        executable, and the outer correction loop is a
        ``lax.while_loop`` — ONE host round trip per solve, which is
        what the remote-attached TPU tunnel demands (the old host-side
        outer loop paid ~2 s of vector transfers per pass).  The dDFI
        analog of the reference's mixed modes
        (``amgx_config.h:114-123``).  ``b``/``x0`` arrive in the
        CALLER's precision, never pre-rounded to the device dtype."""
        from ._bind import bind_for_trace
        dtype = self.Ad.dtype
        wide = np.dtype(wide)
        wdt = jnp.dtype(wide.name)
        # the residue plane always rides f32: it must carry digits the
        # pack dtype cannot (a bf16 lo would forfeit the promotion)
        lo_dt = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype

        def split(v):
            """Caller-precision vector → device-dtype (hi, lo residue)."""
            if isinstance(v, jax.Array) and v.dtype == dtype:
                return v, None          # device-resident input: exact
            v64 = np.asarray(v, dtype=np.float64).ravel()
            hi = v64.astype(dtype)
            lo = (v64 - hi.astype(np.float64)).astype(lo_dt)
            return jnp.asarray(hi), \
                (jnp.asarray(lo) if np.any(lo) else None)

        b_hi, b_lo = split(b)
        x_hi = x_lo = None
        if x0 is not None:
            x_hi, x_lo = split(x0)
        if self._refined_fn is None or self._refined_fn[0] != wide:
            self._refined_fn = (wide, jax.jit(bind_for_trace(
                self._bindings, self._build_refined_fn(wide))))
        x64, stats, history = self._refined_fn[1](
            self._bindings.collect(), b_hi, b_lo, x_hi, x_lo,
            jnp.asarray(self.tolerance, wdt),
            jnp.asarray(self.max_iters, jnp.int32))
        # ONE small host fetch; same wire layout as _packed_solve_fn
        iters, brk_code, first_bad, nrm, nrm_ini = \
            self._decode_stats(np.asarray(stats))
        # keep the wide-precision device solution: rounding x back to the
        # device dtype would throw away the digits refinement bought
        return x64, iters, brk_code, first_bad, nrm, nrm_ini, history

    def _build_refined_fn(self, wide=np.float64) -> Callable:
        body = self._build_solve_fn()
        dtype = self.Ad.dtype
        crit, alt_tol = self.convergence, self.alt_rel_tolerance
        inner_tol = max(self.tolerance, 2.0 * self._tolerance_floor(dtype))
        max_iters = self.max_iters
        # each outer pass reduces the wide residual by roughly the
        # inner tolerance; a bf16 inner floor (~0.4 per pass) needs far
        # more rungs to reach its f32 target than the f32 → f64 case's
        # historical 8 — size the budget from the reduction per pass
        import math
        if 0.0 < inner_tol < 1.0:
            need = math.log(max(self.tolerance, 1e-300)) \
                / math.log(inner_tol)
            max_outer = int(min(64, max(8, math.ceil(need) + 4)))
        else:
            max_outer = 8
        keep_history = self.store_res_history or self.print_solve_stats
        f64 = jnp.dtype(np.dtype(wide).name)    # the promotion rung
        tiny = float(np.finfo(np.dtype(wide)).tiny)
        # the history buffer floors at f32: a bf16 pack's residual
        # trajectory spans magnitudes bf16 cannot represent
        hist_dt = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype

        def norm64(r):
            return jnp.atleast_1d(blas.norm(r, self.norm_type,
                                            self.Ad.block_dim,
                                            self.use_scalar_norm))

        def widen(hi, lo):
            w = hi.astype(f64)
            return w if lo is None else w + lo.astype(f64)

        def refined_fn(b_hi, b_lo, x_hi, x_lo, tol, it_limit):
            # widen the operator ONCE, outside the while body (see
            # _spmv_wide: XLA won't hoist the ~2×vals materialisation)
            Ad64 = self._wide_pack(wide)
            b64 = widen(b_hi, b_lo)
            x64 = jnp.zeros_like(b64) if x_hi is None else widen(x_hi, x_lo)
            r64 = b64 - self._spmv_wide(x64, Ad64, wide)
            nrm_ini = norm64(r64)
            m = nrm_ini.shape[0]
            hist = jnp.zeros((max_iters + 1, m), hist_dt)
            hist = hist.at[0].set(nrm_ini.astype(hist_dt))
            done0 = check_convergence(crit, nrm_ini, nrm_ini, nrm_ini,
                                      tol, alt_tol)

            def cond(c):
                _x, _r, it_tot, _n, done, _h, k, _f = c
                return (~done) & (it_tot < it_limit) & (k < max_outer)

            def outer(c):
                x64, r64, it_tot, _nrm, _done, hist, k, fail = c
                scale = jnp.maximum(jnp.max(jnp.abs(r64)),
                                    jnp.asarray(tiny, f64))
                rb = (r64 / scale).astype(dtype)
                dx, it, _, _, h_in, f_in = body(
                    rb, jnp.zeros_like(rb),
                    jnp.asarray(inner_tol, dtype), it_limit - it_tot)
                # the FIRST inner breakdown wins; its first-bad
                # iteration re-bases onto the global iteration count
                new = (fail[0] == 0) & (f_in[0] != 0)
                fail = jnp.where(
                    new, jnp.stack([f_in[0], it_tot + f_in[1]]), fail)
                x64n = x64 + scale * dx.astype(f64)
                r64n = b64 - self._spmv_wide(x64n, Ad64, wide)
                nrm_n = norm64(r64n)
                if keep_history:
                    # place h_in rows 1..it (scaled) at hist rows
                    # it_tot+1 .. it_tot+it
                    rows = jnp.arange(max_iters + 1)[:, None]
                    src = rows - it_tot
                    take = jnp.broadcast_to(
                        jnp.clip(src, 0, max_iters), (max_iters + 1, m))
                    cand = jnp.take_along_axis(h_in, take, axis=0) \
                        .astype(hist_dt)
                    mask = (src >= 1) & (src <= it)
                    hist = jnp.where(mask, cand * scale.astype(hist_dt),
                                     hist)
                done_n = check_convergence(crit, nrm_n, nrm_ini, nrm_ini,
                                           tol, alt_tol) \
                    | ~jnp.all(jnp.isfinite(nrm_n)) \
                    | (fail[0] != 0)
                return (x64n, r64n, it_tot + it, nrm_n, done_n, hist,
                        k + jnp.asarray(1, jnp.int32), fail)

            fail0 = jnp.stack([jnp.asarray(0, jnp.int32),
                               jnp.asarray(-1, jnp.int32)])
            carry = (x64, r64, jnp.asarray(0, jnp.int32), nrm_ini, done0,
                     hist, jnp.asarray(0, jnp.int32), fail0)
            x64, r64, it_tot, nrm, done, hist, k, fail = \
                jax.lax.while_loop(cond, outer, carry)
            stats = jnp.concatenate([it_tot[None].astype(f64),
                                     fail.astype(f64), nrm, nrm_ini])
            return x64, stats, hist

        return refined_fn

    def _host_converged(self, nrm, nrm_ini, nrm_max=None):
        crit = self.convergence
        tol = self.tolerance
        if crit == "ABSOLUTE":
            return nrm <= tol
        if crit in ("RELATIVE_INI", "RELATIVE_INI_CORE"):
            return nrm <= tol * nrm_ini
        if crit in ("RELATIVE_MAX", "RELATIVE_MAX_CORE"):
            return nrm <= tol * (nrm_ini if nrm_max is None else nrm_max)
        if crit == "COMBINED_REL_INI_ABS":
            return (nrm <= tol) | (nrm <= self.alt_rel_tolerance * nrm_ini)
        return nrm <= tol

    def _print_solve_stats(self, history, iters, status):
        if history is None:
            return
        lines = ["           iter      Mem Usage (GB)       residual      "
                 "rate\n",
                 "         --------------------------------------------------"
                 "------------\n"]
        prev = None
        for i, h in enumerate(history):
            hval = float(np.max(h))
            rate = "" if prev in (None, 0.0) else f"{hval / prev:9.4f}"
            label = "Ini" if i == 0 else f"{i - 1:4d}"
            lines.append(f"        {label}              -         "
                         f"{hval:15.6e}  {rate}\n")
            prev = hval
        lines.append("         ----------------------------------------------"
                     "----------------\n")
        lines.append(f"        Total Iterations: {iters}\n")
        amgx_output("".join(lines))

    # ------------------------------------------------------- the jitted loop
    def _build_solve_fn(self) -> Callable:
        monitor = self.monitor_residual
        keep_history = self.store_res_history or self.print_solve_stats
        max_iters = self.max_iters
        crit = self.convergence
        alt_tol = self.alt_rel_tolerance
        # traced fault injection (utils/faultinject.py): None — the
        # default — adds NOTHING to the jaxpr; an armed values_nan /
        # krylov_zero point mutates the iteration state at one target
        # iteration (solve() invalidates this body on arming changes)
        fault = getattr(self, "_fault_trace", None)
        ledger = self._collective_ledger

        def solve_fn(b, x0, tol, it_limit):
            r0 = b - spmv(self.Ad, x0)
            nrm_ini = blas.norm(r0, self.norm_type, self.Ad.block_dim,
                                self.use_scalar_norm)
            nrm_ini = jnp.atleast_1d(nrm_ini)
            history = jnp.zeros((max_iters + 1,) + nrm_ini.shape,
                                dtype=nrm_ini.dtype)
            history = history.at[0].set(nrm_ini)
            state0 = self.solve_init(b, x0)

            def cond(carry):
                x, state, it, nrm, nmax, done, brk, bad_it, hist = carry
                return (~done) & (it < jnp.minimum(it_limit, max_iters))

            def body(carry):
                x, state, it, nrm, nmax, done, brk, bad_it, hist = carry
                # collective ledger: this body traces ONCE per compile,
                # so resetting here and counting through the iteration +
                # monitor estimate yields the steady-state per-iteration
                # reduction profile (host-side; adds nothing to the jaxpr)
                ledger.reset()
                with blas.count_collectives(ledger):
                    x, state = self.solve_iteration(b, x, state, it)
                    if fault is not None:
                        x, state = _inject_fault(fault, it, x, state)
                    est = None
                    if monitor:
                        est = self.residual_norm_estimate(b, x, state)
                        if est is None:
                            est = self.compute_residual_norm(b, x)
                if monitor:
                    nrm = jnp.atleast_1d(est)
                    # device-side breakdown flag: the solver's in-loop
                    # guards (CG pAp/rho) carry a code in their state;
                    # a flagged loop stops at THIS iteration instead of
                    # burning the remaining budget, and the first-bad
                    # iteration rides out in the packed stats.  The
                    # KRYLOV code is provisional (collapsed scalars
                    # also mean ordinary convergence) — it only sticks
                    # while the monitored residual is alive, which the
                    # carried norm already knows for free
                    code = self.breakdown_code(state)
                    if code is not None:
                        alive = jnp.any(nrm > 0)
                        code = jnp.where(
                            (code == BREAKDOWN_KRYLOV) & ~alive,
                            0, code)
                        hit = (brk == 0) & (code != 0)
                        brk = jnp.where(hit, code, brk)
                        bad_it = jnp.where(hit, it + 1, bad_it)
                    nmax = jnp.maximum(nmax, nrm)
                    done = check_convergence(crit, nrm, nrm_ini, nmax,
                                             tol, alt_tol)
                    bad = ~jnp.all(jnp.isfinite(nrm))
                    hit = (brk == 0) & bad
                    brk = jnp.where(
                        hit, jnp.where(jnp.any(jnp.isnan(nrm)),
                                       BREAKDOWN_NAN,
                                       BREAKDOWN_DIVERGENCE), brk)
                    bad_it = jnp.where(hit, it + 1, bad_it)
                    done = done | bad | (brk != 0)
                if keep_history:
                    hist = hist.at[it + 1].set(nrm)
                return (x, state, it + 1, nrm, nmax, done, brk, bad_it,
                        hist)

            done0 = jnp.asarray(False)
            if monitor:
                done0 = check_convergence(crit, nrm_ini, nrm_ini, nrm_ini,
                                          tol, alt_tol)
            carry = (x0, state0, jnp.asarray(0, jnp.int32), nrm_ini,
                     nrm_ini, done0, jnp.asarray(0, jnp.int32),
                     jnp.asarray(-1, jnp.int32), history)
            (x, state, it, nrm, nmax, done, brk, bad_it, history) = \
                jax.lax.while_loop(cond, body, carry)
            x = self.solve_finalize(b, x, state)
            if monitor:
                # the declared norm is a freshly computed TRUE residual —
                # in-loop estimates (quasi-residual, CG recursion) only
                # steer the loop (reference solver.cu:776-805)
                nrm = jnp.atleast_1d(self.compute_residual_norm(b, x))
            fail = jnp.stack([brk, bad_it])
            return x, it, nrm, nrm_ini, history, fail

        return solve_fn

    def breakdown_code(self, state) -> Optional[jax.Array]:
        """Traced int32 breakdown code the solver's iteration state
        carries (``errors.BREAKDOWN_*``; 0 = healthy).  Solvers with
        in-loop guards (CG family: ``pAp < 0``, ``rho == 0``) keep a
        ``brk`` field in their state; everything else returns None and
        relies on the non-finite residual check."""
        return getattr(state, "brk", None)

    def residual_norm_estimate(self, b, x, state):
        """Solvers with an implicit residual estimate (FGMRES quasi-residual)
        override this to avoid an extra SpMV per iteration."""
        return None

    def solve_finalize(self, b, x, state):
        return x

    # ------------------------------------------------------------- utilities
    def grid_stats(self) -> str:
        return ""
