"""Gauss-Seidel family + Kaczmarz smoothers (color-parallel).

Reference: ``core/src/solvers/multicolor_gauss_seidel_solver.cu``,
``fixcolor_gauss_seidel_solver.cu``, ``gauss_seidel_solver.cu``,
``kaczmarz_solver.cu``; params ``symmetric_GS``, ``GS_L1_variant``
(core.cu:425-427), ``kaczmarz_coloring_needed``.

TPU design: rows of one color are independent.  Each color's rows are
gathered at setup into a compact ELL slab (rows, padded cols, values), so
one sweep costs O(nnz) total — the per-color update reads only that
color's slab and scatters only that color's rows, exactly like the
reference's per-color kernels (``multicolor_dilu_solver.cu``) and unlike
a masked full-width relaxation, which would pay O(num_colors · nnz).
The serial "GS" solver maps onto the same color-ordered sweep.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..coloring import color_matrix
from ..ops.spmv import spmv
from ..utils.jaxcompat import shard_map as _shard_map
from .base import Solver, register_solver
from .jacobi import _apply_dinv, setup_dinv


class ColorSlab:
    """One color's compact row slab: ELL rows with GLOBAL column ids."""

    def __init__(self, rows, cols, vals):
        self.rows = rows        # (nc,) int32 — this color's (block) rows
        self.cols = cols        # (nc, K) int32
        self.vals = vals        # (nc, K[, b, b])


def build_color_slabs(csr, colors, num_colors, dtype, device=True):
    """Per-color packed ELL slabs from a scalar CSR matrix
    (multicolor_dilu_solver.cu per-color kernel data, TPU-packed);
    ``device=False`` keeps host arrays (the distributed packer stacks
    and re-shards them itself)."""
    from ..core.matrix import ell_layout
    wrap = jnp.asarray if device else (lambda x: x)
    slabs = []
    for c in range(num_colors):
        rows = np.where(colors == c)[0]
        sub = csr[rows]
        sub.sort_indices()
        for_rows, pos, k = ell_layout(sub.indptr, sub.indices)
        cols = np.zeros((len(rows), k), dtype=np.int32)
        vals = np.zeros((len(rows), k), dtype=dtype)
        cols[for_rows, pos] = sub.indices
        vals[for_rows, pos] = sub.data
        slabs.append(ColorSlab(wrap(rows.astype(np.int32)),
                               wrap(cols), wrap(vals)))
    return slabs


def build_color_slabs_block(bsr, colors, num_colors, dtype, bd,
                            device=True):
    """Per-color packed block-ELL slabs from a BSR matrix: cols are BLOCK
    columns, vals (nc, K, b, b); ``device=False`` keeps host arrays (the
    distributed packer stacks and re-shards them itself)."""
    import scipy.sparse as sp
    from ..core.matrix import ell_layout
    wrap = jnp.asarray if device else (lambda x: x)
    bsr.sort_indices()
    ind = sp.csr_matrix(
        (np.arange(len(bsr.indices)), bsr.indices, bsr.indptr),
        shape=(bsr.shape[0] // bd, bsr.shape[1] // bd))
    slabs = []
    for c in range(num_colors):
        rows = np.where(colors == c)[0]
        sub = ind[rows]
        for_rows, pos, k = ell_layout(sub.indptr, sub.indices)
        cols = np.zeros((len(rows), k), dtype=np.int32)
        vals = np.zeros((len(rows), k, bd, bd), dtype=dtype)
        cols[for_rows, pos] = sub.indices
        vals[for_rows, pos] = bsr.data[sub.data]
        slabs.append(ColorSlab(wrap(rows.astype(np.int32)),
                               wrap(cols), wrap(vals)))
    return slabs


class _ColoredSmootherBase(Solver):
    """Shared setup: coloring + per-color packed slabs (or masks for the
    sharded fallback) + block-diag inverse."""

    def _setup_colors(self, build_slabs: bool = True,
                      dist_slabs: bool = True):
        if self.A is not None:
            coloring = color_matrix(self.A, self.cfg, self.scope)
            colors = coloring.colors
            self.num_colors = coloring.num_colors
        else:
            # device-only fallback: single color (degenerates to Jacobi)
            colors = np.zeros(self.Ad.n_rows, dtype=np.int32)
            self.num_colors = 1
        b = self.Ad.block_dim
        self.color_slabs = None
        self.color_masks = None
        self.dist_slab_rows = None
        if build_slabs and self.Ad.fmt != "sharded-ell" \
                and self.A is not None:
            if b == 1:
                self.color_slabs = build_color_slabs(
                    self.A.scalar_csr(), colors, self.num_colors,
                    self.Ad.dtype)
            else:
                import scipy.sparse as sp
                bsr = self.A.host if isinstance(self.A.host,
                                                sp.bsr_matrix) else \
                    sp.bsr_matrix(self.A.host, blocksize=(b, b))
                self.color_slabs = build_color_slabs_block(
                    bsr, colors, self.num_colors, self.Ad.dtype, b)
        elif build_slabs and dist_slabs \
                and self.Ad.fmt == "sharded-ell" and b == 1 \
                and self.A is not None:
            # distributed per-color slabs: the shard pack's columns are
            # already in [local | halo] coordinates, so each color's
            # slab is a row-selection of the shard ELL; the sweep pays
            # ONE halo exchange and O(nnz_shard) per pass (reference
            # per-color kernels, multicolor_dilu_solver.cu) instead of
            # the masked O(num_colors·nnz) with per-color exchanges
            self.dist_slab_rows = self._stack_dist_color_rows(colors)
        else:
            # device-only (or block-sharded) fallback: masked full-width
            masks = []
            for c in range(self.num_colors):
                m = colors == c
                if b > 1:
                    m = np.repeat(m, b)
                if self.Ad.fmt == "sharded-ell":
                    from ..distributed.matrix import shard_vector
                    masks.append(shard_vector(
                        self.Ad, m.astype(self.Ad.dtype)) > 0.5)
                else:
                    masks.append(jnp.asarray(m))
            self.color_masks = masks
        self.dinv = setup_dinv(self)

    def _stack_dist_color_rows(self, colors):
        """(P, Rc) local row ids per color, padded with the trash id
        ``n_loc`` (the sweep clamps for gathering and scatters pads into
        a trash slot)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        Ad = self.Ad
        offs = np.asarray(Ad.offsets)
        n_parts, n_loc = Ad.n_parts, Ad.n_loc
        out = []
        for c in range(self.num_colors):
            per_rank = [np.flatnonzero(colors[offs[p]:offs[p + 1]] == c)
                        for p in range(n_parts)]
            Rc = max(max(len(r) for r in per_rank), 1)
            rows = np.full((n_parts, Rc), n_loc, dtype=np.int32)
            for p, r in enumerate(per_rank):
                rows[p, :len(r)] = r
            out.append(jax.device_put(
                rows, NamedSharding(Ad.mesh, P(Ad.axis, None))))
        return out


def _shard_transpose(A, Ad):
    """Sharded pack of Aᵀ from per-rank row blocks of A: each rank's
    entries route to their COLUMN owners (send-side, neighbour-wise —
    the Pᵀ collection pattern of the classical distributed path).
    Row partition of Aᵀ = column partition of A = the same offsets."""
    import scipy.sparse as sp

    from ..distributed.matrix import shard_matrix_from_blocks
    offs = np.asarray(Ad.offsets)
    n_parts = Ad.n_parts
    n = int(offs[-1])
    if A.host is None and A.blocks is not None:
        blocks = A.blocks
    else:
        from ..distributed.partition import split_row_blocks
        blocks = split_row_blocks(A.scalar_csr(), offs)
    tri = [([], [], []) for _ in range(n_parts)]
    for p, blk in enumerate(blocks):
        coo = sp.coo_matrix(blk)
        gl_rows = coo.row.astype(np.int64) + offs[p]
        owner = np.searchsorted(offs, coo.col, side="right") - 1
        for q in np.unique(owner) if len(coo.col) else []:
            m = owner == q
            tri[q][0].append(coo.col[m] - offs[q])   # Aᵀ local rows
            tri[q][1].append(gl_rows[m])             # Aᵀ global cols
            tri[q][2].append(coo.data[m])
    t_blocks = []
    for q in range(n_parts):
        rr, cc, vv = tri[q]
        t_blocks.append(sp.csr_matrix(
            (np.concatenate(vv) if vv else [],
             (np.concatenate(rr) if rr else [],
              np.concatenate(cc) if cc else [])),
            shape=(int(offs[q + 1] - offs[q]), n)))
    return shard_matrix_from_blocks(t_blocks, offs, Ad.mesh,
                                    axis=Ad.axis, dtype=Ad.dtype,
                                    n_loc=Ad.n_loc)


def _structurally_symmetric(A) -> bool:
    """Pattern symmetry of a host Matrix (global or per-rank blocks);
    True when unknown (no host data) — the caller only warns."""
    import scipy.sparse as sp
    if A is None or (A.host is None and A.blocks is None):
        return True          # no host data: unknown — don't warn
    if A.blocks is None:
        csr = sp.csr_matrix(A.host)
        pat = sp.csr_matrix(
            (np.ones(csr.nnz, np.int8), csr.indices, csr.indptr),
            shape=csr.shape)
        return (pat != pat.T).nnz == 0
    # blocks mode: compare the sorted (i, j) and (j, i) key sets from
    # per-rank COO indices (index arrays only — no global matrix)
    n = int(A.block_offsets[-1])
    keys, rkeys = [], []
    for p, b in enumerate(A.blocks):
        coo = b.tocoo()
        rows = coo.row.astype(np.int64) + int(A.block_offsets[p])
        cols = coo.col.astype(np.int64)
        keys.append(rows * n + cols)
        rkeys.append(cols * n + rows)
    return bool(np.array_equal(np.sort(np.concatenate(keys)),
                               np.sort(np.concatenate(rkeys))))


def _abs_row_sums_and_diag(A):
    """(Σ_j |a_ij|, |a_ii|) per scalar row — per-rank in block mode."""
    if A.host is None and A.blocks is not None:
        offs = A.block_offsets
        absrow = np.concatenate([
            np.asarray(np.abs(b).sum(axis=1)).ravel() for b in A.blocks])
        d = np.concatenate([
            np.abs(np.asarray(b[:, offs[p]:offs[p + 1]].diagonal()))
            for p, b in enumerate(A.blocks)])
        return absrow, d
    csr = A.scalar_csr()
    return (np.asarray(np.abs(csr).sum(axis=1)).ravel(),
            np.abs(csr.diagonal()))


@register_solver("MULTICOLOR_GS")
class MulticolorGSSolver(_ColoredSmootherBase):
    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.symmetric = bool(cfg.get("symmetric_GS", scope))
        self.l1_variant = bool(cfg.get("GS_L1_variant", scope))

    def solver_setup(self):
        self._setup_colors()
        if self.l1_variant and self.A is not None:
            # L1 damping: d_i ← d_i + Σ_{j∉color(i)}|a_ij| (jacobi_l1-style)
            absrow, d = _abs_row_sums_and_diag(self.A)
            dl1 = d + 0.5 * (absrow - d)
            dl1[dl1 == 0] = 1.0
            vec = (1.0 / dl1).astype(self.Ad.dtype)
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                self.dinv = shard_vector(self.Ad, vec)
            else:
                self.dinv = jnp.asarray(vec)

    def _color_sweep(self, b, x, order):
        if getattr(self, "dist_slab_rows", None) is not None:
            return self._dist_color_sweep(b, x, order)
        if self.color_slabs is None:
            # masked fallback (device-only packs)
            for c in order:
                r = b - spmv(self.Ad, x)
                dx = self.relaxation_factor * _apply_dinv(self.dinv, r)
                x = jnp.where(self.color_masks[c], x + dx, x)
            return x
        bd = self.Ad.block_dim
        relax = self.relaxation_factor
        if bd == 1:
            for c in order:
                s = self.color_slabs[c]
                r_c = b[s.rows] - jnp.sum(s.vals * x[s.cols], axis=1)
                x = x.at[s.rows].add(relax * self.dinv[s.rows] * r_c)
            return x
        for c in order:
            s = self.color_slabs[c]
            xg = x.reshape(-1, bd)[s.cols]                 # (nc, K, b)
            # sub-f32 slab values (bf16 hierarchy) accumulate in f32 —
            # the same floor every SpMV path applies (core/precision.py)
            from ..core.precision import compute_dtype as _cdt
            pet = jnp.promote_types(_cdt(s.vals.dtype), xg.dtype)
            Ax = jnp.einsum("nkab,nkb->na", s.vals, xg,
                            preferred_element_type=pet)
            r_c = b.reshape(-1, bd)[s.rows] - Ax
            if self.dinv.ndim == 1:    # L1 variant: scalar damped diag
                dx = relax * self.dinv.reshape(-1, bd)[s.rows] * r_c
            else:
                dx = relax * jnp.einsum("nab,nb->na", self.dinv[s.rows],
                                        r_c)
            x = x.reshape(-1, bd).at[s.rows].add(dx).reshape(-1)
        return x

    def _dist_color_sweep(self, b, x, order):
        """Distributed color-ordered sweep: ONE halo exchange at sweep
        start (halo values frozen, local updates visible — the
        reference's exchange-once-then-per-color-kernels pattern,
        multicolor_dilu_solver.cu:4167-4209), O(nnz_shard) total."""
        import jax
        from jax.sharding import PartitionSpec as P

        from ..distributed.matrix import _exchange
        A = self.Ad
        axis, n_parts, n_loc = A.axis, A.n_parts, A.n_loc
        relax = self.relaxation_factor
        order = list(order)

        def local(cols, vals, send_idx, halo_src, slab_rows, dinv, bl,
                  xl):
            cols, vals = cols[0], vals[0]
            send_idx, halo_src = send_idx[0], halo_src[0]
            H = halo_src.shape[0]
            buf = xl[send_idx]
            got = _exchange(buf, A.dists, axis, n_parts)
            hvals = got[halo_src]
            # [local | frozen halo | trash]
            xe = jnp.concatenate([xl, hvals,
                                  jnp.zeros((1,), xl.dtype)])
            for c in order:
                rows = slab_rows[c][0]
                rsafe = jnp.minimum(rows, n_loc - 1)
                cc = cols[rsafe]                  # (Rc, K)
                vv = vals[rsafe]
                r_c = bl[rsafe] - jnp.sum(vv * xe[cc], axis=1)
                upd = relax * dinv[rsafe] * r_c
                wr = jnp.where(rows >= n_loc, n_loc + H, rows)
                xe = xe.at[wr].add(upd)
            return xe[:n_loc]

        spec2 = P(axis, None)
        return _shard_map(
            local, mesh=A.mesh,
            in_specs=(P(axis, None, None), P(axis, None, None),
                      spec2, spec2, [spec2] * len(self.dist_slab_rows),
                      P(axis), P(axis), P(axis)),
            out_specs=P(axis), check_vma=False,
        )(A.cols, A.vals, A.send_idx, A.halo_src, self.dist_slab_rows,
          self.dinv, b, x)

    def solve_iteration(self, b, x, state, iter_idx):
        x = self._color_sweep(b, x, range(self.num_colors))
        if self.symmetric:
            x = self._color_sweep(b, x, range(self.num_colors - 1, -1, -1))
        return x, state


@register_solver("GS")
class GSSolver(MulticolorGSSolver):
    """Serial GS (reference ``gauss_seidel_solver.cu``) — realised as the
    color-ordered sweep, which performs the identical relaxation for
    properly colored matrices."""


@register_solver("FIXCOLOR_GS")
class FixcolorGSSolver(MulticolorGSSolver):
    """GS with a fixed color count (``fixcolor_gauss_seidel_solver.cu``):
    forces the ROUND_ROBIN coloring with ``num_colors`` stripes."""

    def solver_setup(self):
        if self.A is not None and getattr(self.A, "coloring", None) is None:
            cfg2 = self.cfg.clone()
            cfg2.set("matrix_coloring_scheme", "ROUND_ROBIN", "default")
            from ..coloring import color_matrix as cm
            self.A.coloring = cm(self.A, cfg2, self.scope)
        super().solver_setup()


@register_solver("KACZMARZ")
class KaczmarzSolver(_ColoredSmootherBase):
    """Multicolor Kaczmarz (reference ``kaczmarz_solver.cu``): row
    projections x += a_i (b_i − a_i·x)/‖a_i‖², one color at a time."""

    is_smoother = True

    def solver_setup(self):
        # Kaczmarz colors the A·Aᵀ graph: same-color rows must not share
        # ANY column, so simultaneous projections are orthogonal
        # (reference ``kaczmarz_coloring_needed``, core.cu:437)
        if self.A is not None and self.Ad.block_dim == 1 and \
                (self.Ad.fmt != "sharded-ell" or self.A.blocks is None):
            # the scalar A·Aᵀ coloring (kaczmarz_coloring_needed) also
            # serves the sharded path whenever a host view exists (or is
            # dia-derivable), so the distributed sweep order matches the
            # single-device one; blocks-mode keeps the default
            # distance-1 coloring, and BLOCK matrices use the default
            # block-row coloring (the scalar-row A·Aᵀ colors would not
            # align with the b×b mask layout)
            import scipy.sparse as sp
            from ..coloring import MatrixColoring, create_coloring
            csr = self.A.scalar_csr()
            pat = sp.csr_matrix(
                (np.ones(len(csr.data), dtype=np.int8),
                 csr.indices.copy(), csr.indptr.copy()), shape=csr.shape)
            G = sp.csr_matrix(pat @ pat.T)
            algo = create_coloring("MIN_MAX", self.cfg, self.scope)
            coloring = algo.color(G)
            self.A.coloring = coloring
        # slab projections are scalar-row based; block packs use masks
        # Kaczmarz's scatter projection keeps the masked sharded path
        self._setup_colors(build_slabs=(self.Ad.block_dim == 1),
                           dist_slabs=False)
        # row squared norms + explicit transpose pack for the projections
        if self.A is not None:
            if self.A.host is None and self.A.blocks is not None:
                csr = None
                rn = np.concatenate([
                    np.asarray(b.multiply(b).sum(axis=1)).ravel()
                    for b in self.A.blocks])
            else:
                csr = self.A.scalar_csr()
                rn = np.asarray(csr.multiply(csr).sum(axis=1)).ravel()
            rn[rn == 0] = 1.0
            vec = (1.0 / rn).astype(self.Ad.dtype)
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                self.rowinv = shard_vector(self.Ad, vec)
                if self.Ad.block_dim == 1:
                    # TRUE distributed transpose pack (kaczmarz_solver.cu
                    # builds Aᵀ): per-rank Aᵀ row blocks are collected
                    # send-side — each rank routes its entries to their
                    # column owners (the same neighbour-wise collection
                    # as classical R), then pack as a ShardedMatrix
                    self.AdT = _shard_transpose(self.A, self.Ad)
                else:
                    # block transpose pack not built yet: reuse A, exact
                    # only under structural symmetry — warn when false
                    self.AdT = self.Ad
                    if not _structurally_symmetric(self.A):
                        import logging
                        logging.getLogger("amgx_tpu").warning(
                            "distributed block KACZMARZ substitutes A "
                            "for A^T but this matrix is NOT structurally"
                            " symmetric — projections use wrong "
                            "couplings and convergence will degrade")
            else:
                self.rowinv = jnp.asarray(vec)
                from ..core.matrix import Matrix as _M
                self.AdT = _M(csr.T.tocsr().astype(
                    self.Ad.dtype)).device()
        else:
            self.rowinv = jnp.ones((self.Ad.n,), self.Ad.dtype)
            self.AdT = self.Ad

    def solve_iteration(self, b, x, state, iter_idx):
        # colorwise projection: for rows i of color c,
        # x += a_i (b_i − a_i·x)/‖a_i‖² — per-color slab form reads and
        # scatters only that color's rows/columns (O(nnz) per sweep)
        if self.color_slabs is not None and self.Ad.block_dim == 1:
            for c in range(self.num_colors):
                s = self.color_slabs[c]
                r_c = b[s.rows] - jnp.sum(s.vals * x[s.cols], axis=1)
                w = self.relaxation_factor * r_c * self.rowinv[s.rows]
                # same-color rows share no column (AᵀA coloring), and
                # padded slots carry zero values — scatter-add is exact
                x = x.at[s.cols.ravel()].add((s.vals * w[:, None]).ravel())
            return x, state
        for c in range(self.num_colors):
            r = b - spmv(self.Ad, x)
            w = jnp.where(self.color_masks[c], r * self.rowinv, 0.0)
            x = x + self.relaxation_factor * spmv(self.AdT, w)
        return x, state
