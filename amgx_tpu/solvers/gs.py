"""Gauss-Seidel family + Kaczmarz smoothers (color-parallel).

Reference: ``core/src/solvers/multicolor_gauss_seidel_solver.cu``,
``fixcolor_gauss_seidel_solver.cu``, ``gauss_seidel_solver.cu``,
``kaczmarz_solver.cu``; params ``symmetric_GS``, ``GS_L1_variant``
(core.cu:425-427), ``kaczmarz_coloring_needed``.

TPU design: rows of one color are independent, so a GS sweep is
``num_colors`` masked Jacobi-style vector updates — each a full-width VPU
op.  The serial "GS" solver maps onto the same color-ordered sweep (the
reference's serial GS exists only because a GPU warp could not do better;
on TPU the colored sweep is the native expression of the same relaxation).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..coloring import color_matrix
from ..ops.spmv import spmv
from .base import Solver, register_solver
from .jacobi import _apply_dinv, setup_dinv


class _ColoredSmootherBase(Solver):
    """Shared setup: coloring + per-color masks + block-diag inverse."""

    def _setup_colors(self):
        if self.A is not None:
            coloring = color_matrix(self.A, self.cfg, self.scope)
            colors = coloring.colors
            self.num_colors = coloring.num_colors
        else:
            # device-only fallback: single color (degenerates to Jacobi)
            colors = np.zeros(self.Ad.n_rows, dtype=np.int32)
            self.num_colors = 1
        b = self.Ad.block_dim
        masks = []
        for c in range(self.num_colors):
            m = colors == c
            if b > 1:
                m = np.repeat(m, b)
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                masks.append(shard_vector(self.Ad, m.astype(self.Ad.dtype))
                             > 0.5)
            else:
                masks.append(jnp.asarray(m))
        self.color_masks = masks
        self.dinv = setup_dinv(self)


@register_solver("MULTICOLOR_GS")
class MulticolorGSSolver(_ColoredSmootherBase):
    is_smoother = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.symmetric = bool(cfg.get("symmetric_GS", scope))
        self.l1_variant = bool(cfg.get("GS_L1_variant", scope))

    def solver_setup(self):
        self._setup_colors()
        if self.l1_variant and self.A is not None:
            # L1 damping: d_i ← d_i + Σ_{j∉color(i)}|a_ij| (jacobi_l1-style)
            csr = self.A.scalar_csr()
            absrow = np.asarray(np.abs(csr).sum(axis=1)).ravel()
            d = np.abs(csr.diagonal())
            dl1 = d + 0.5 * (absrow - d)
            dl1[dl1 == 0] = 1.0
            vec = (1.0 / dl1).astype(self.Ad.dtype)
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                self.dinv = shard_vector(self.Ad, vec)
            else:
                self.dinv = jnp.asarray(vec)

    def _color_sweep(self, b, x, order):
        for c in order:
            r = b - spmv(self.Ad, x)
            dx = self.relaxation_factor * _apply_dinv(self.dinv, r)
            x = jnp.where(self.color_masks[c], x + dx, x)
        return x

    def solve_iteration(self, b, x, state, iter_idx):
        x = self._color_sweep(b, x, range(self.num_colors))
        if self.symmetric:
            x = self._color_sweep(b, x, range(self.num_colors - 1, -1, -1))
        return x, state


@register_solver("GS")
class GSSolver(MulticolorGSSolver):
    """Serial GS (reference ``gauss_seidel_solver.cu``) — realised as the
    color-ordered sweep, which performs the identical relaxation for
    properly colored matrices."""


@register_solver("FIXCOLOR_GS")
class FixcolorGSSolver(MulticolorGSSolver):
    """GS with a fixed color count (``fixcolor_gauss_seidel_solver.cu``):
    forces the ROUND_ROBIN coloring with ``num_colors`` stripes."""

    def solver_setup(self):
        if self.A is not None and getattr(self.A, "coloring", None) is None:
            cfg2 = self.cfg.clone()
            cfg2.set("matrix_coloring_scheme", "ROUND_ROBIN", "default")
            from ..coloring import color_matrix as cm
            self.A.coloring = cm(self.A, cfg2, self.scope)
        super().solver_setup()


@register_solver("KACZMARZ")
class KaczmarzSolver(_ColoredSmootherBase):
    """Multicolor Kaczmarz (reference ``kaczmarz_solver.cu``): row
    projections x += a_i (b_i − a_i·x)/‖a_i‖², one color at a time."""

    is_smoother = True

    def solver_setup(self):
        # Kaczmarz colors the A·Aᵀ graph: same-color rows must not share
        # ANY column, so simultaneous projections are orthogonal
        # (reference ``kaczmarz_coloring_needed``, core.cu:437)
        if self.A is not None and self.Ad.fmt != "sharded-ell":
            import scipy.sparse as sp
            from ..coloring import MatrixColoring, create_coloring
            csr = self.A.scalar_csr()
            pat = sp.csr_matrix(
                (np.ones(len(csr.data), dtype=np.int8),
                 csr.indices.copy(), csr.indptr.copy()), shape=csr.shape)
            G = sp.csr_matrix(pat @ pat.T)
            algo = create_coloring("MIN_MAX", self.cfg, self.scope)
            coloring = algo.color(G)
            self.A.coloring = coloring
        self._setup_colors()
        # row squared norms + explicit transpose pack for the projections
        if self.A is not None:
            csr = self.A.scalar_csr()
            rn = np.asarray(csr.multiply(csr).sum(axis=1)).ravel()
            rn[rn == 0] = 1.0
            vec = (1.0 / rn).astype(self.Ad.dtype)
            if self.Ad.fmt == "sharded-ell":
                from ..distributed.matrix import shard_vector
                self.rowinv = shard_vector(self.Ad, vec)
                self.AdT = self.Ad  # structurally symmetric assumption
            else:
                self.rowinv = jnp.asarray(vec)
                from ..core.matrix import Matrix as _M
                self.AdT = _M(csr.T.tocsr().astype(
                    self.Ad.dtype)).device()
        else:
            self.rowinv = jnp.ones((self.Ad.n,), self.Ad.dtype)
            self.AdT = self.Ad

    def solve_iteration(self, b, x, state, iter_idx):
        # colorwise projection: for rows i of color c,
        # x += Aᵀ·(w ⊙ r) with w_i = 1/‖a_i‖² masked to the color
        for c in range(self.num_colors):
            r = b - spmv(self.Ad, x)
            w = jnp.where(self.color_masks[c], r * self.rowinv, 0.0)
            x = x + self.relaxation_factor * spmv(self.AdT, w)
        return x, state
