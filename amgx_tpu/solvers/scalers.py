"""Matrix scalers: equation scaling pre/post solve.

Reference: ``core/src/scalers/`` (1538 LoC; registered core.cu:703-705;
workaround flow documented ``solver.cu:441-455``): BINORMALIZATION
(Sinkhorn-style row/column 2-norm equilibration), NBINORMALIZATION,
DIAGONAL_SYMMETRIC (D^{-1/2}·A·D^{-1/2}).
"""
from __future__ import annotations

from typing import Dict

import numpy as np
import scipy.sparse as sp

from ..errors import BadConfigurationError

_scaler_registry: Dict[str, type] = {}


def register_scaler(name):
    def deco(cls):
        _scaler_registry[name] = cls
        cls.config_name = name
        return cls
    return deco


def create_scaler(name, cfg, scope):
    if name not in _scaler_registry:
        raise BadConfigurationError(f"unknown scaler {name!r}")
    return _scaler_registry[name](cfg, scope)


class Scaler:
    """left/right diagonal scaling: A' = Dl·A·Dr, b' = Dl·b, x = Dr·x'."""

    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope
        self.dl = None
        self.dr = None

    def setup(self, A: sp.csr_matrix):
        raise NotImplementedError

    def scale_matrix(self, A: sp.csr_matrix) -> sp.csr_matrix:
        return sp.csr_matrix(
            sp.diags(self.dl) @ A @ sp.diags(self.dr))

    def scale_rhs(self, b):
        return self.dl * b

    def unscale_solution(self, x):
        return self.dr * x

    def scale_initial_guess(self, x0):
        return x0 / np.where(self.dr == 0, 1.0, self.dr)


@register_scaler("DIAGONAL_SYMMETRIC")
class DiagonalSymmetricScaler(Scaler):
    def setup(self, A):
        d = np.abs(A.diagonal())
        d[d == 0] = 1.0
        s = 1.0 / np.sqrt(d)
        self.dl = s
        self.dr = s
        return self


@register_scaler("BINORMALIZATION")
class BinormalizationScaler(Scaler):
    """Iterative row/col 2-norm equilibration (``binormalization.cu``)."""

    n_iters = 10

    def setup(self, A):
        A2 = sp.csr_matrix(A).copy()
        A2.data = A2.data ** 2
        n, m = A.shape
        dl = np.ones(n)
        dr = np.ones(m)
        for _ in range(self.n_iters):
            rs = A2 @ (dr ** 2)          # row 2-norms² of Dl·A·Dr
            rs[rs == 0] = 1.0
            dl = 1.0 / np.sqrt(rs)
            cs = A2.T @ (dl ** 2)
            cs[cs == 0] = 1.0
            dr = 1.0 / np.sqrt(cs)
        # symmetric matrices keep a symmetric scaling (PCG requires the
        # scaled operator to stay SPD) — use the geometric mean of the two
        # one-sided equilibrations
        diffnorm = sp.linalg.norm(A - A.T) if n == m else np.inf
        if diffnorm <= 1e-12 * sp.linalg.norm(A):
            d = np.sqrt(np.abs(dl * dr))
            dl = dr = d
        self.dl, self.dr = dl, dr
        return self


@register_scaler("NBINORMALIZATION")
class NBinormalizationScaler(Scaler):
    """NORMALISED binormalization (``nbinormalization.cu:440-540``) —
    algorithmically distinct from BINORMALIZATION (round-4 advisor):
    Sinkhorn on B = A∘A with row-sum target ``cols`` and col-sum target
    ``rows`` via EXACT alternating updates x = cols/(B·y),
    y = rows/(Bᵀ·x), a measured std-deviation stopping test
    (tol 1e-10, ≤50 sweeps), and the final scaling
    F = √|x|, G = √|y| — so ‖F·A·G‖²_F ≈ rows·cols with every row and
    column of the squared matrix equilibrated to its target."""

    max_iters = 50
    tolerance = 1e-10

    def setup(self, A):
        B = sp.csr_matrix(A).copy()
        B.data = B.data ** 2
        n, m = B.shape
        x = np.ones(n)
        y = np.ones(m)
        sum1, sum2 = float(m), float(n)
        beta = B @ y
        gamma = B.T @ x

        def dev(v, s, target):
            return np.sqrt(np.mean((v * s - target) ** 2)) / target

        std = np.hypot(dev(x, beta, sum1), dev(y, gamma, sum2))
        for _ in range(self.max_iters):
            if std < self.tolerance:
                break
            x = np.where(np.abs(beta) > 1e-300, sum1 /
                         np.where(beta == 0, 1.0, beta), 1.0)
            gamma = B.T @ x
            y = np.where(np.abs(gamma) > 1e-300, sum2 /
                         np.where(gamma == 0, 1.0, gamma), 1.0)
            beta = B @ y
            std = dev(x, beta, sum1)
        dl = np.sqrt(np.abs(x))
        dr = np.sqrt(np.abs(y))
        # keep SPD operators SPD for PCG (the same symmetrisation the
        # BINORMALIZATION port applies; x ≈ y for symmetric A anyway)
        diffnorm = sp.linalg.norm(A - A.T) if n == m else np.inf
        if diffnorm <= 1e-12 * sp.linalg.norm(A):
            d = np.sqrt(np.abs(dl * dr))
            dl = dr = d
        self.dl, self.dr = dl, dr
        return self
