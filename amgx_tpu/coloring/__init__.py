"""Graph coloring for color-parallel smoothers.

Reference: ``core/src/matrix_coloring/`` (~6.7k LoC, 11 algorithms,
registered ``core.cu:685-694``).  Colors expose row-parallelism inside
GS/ILU/DILU sweeps: rows of one color have no mutual edges, so a whole
color updates as one vector op — on TPU each color is a masked VPU sweep.

Host-side numpy implementations (setup phase).  ``coloring_level=2`` colors
the distance-2 graph (``core.cu:512``).  The ``determinism_flag`` seeds the
hashes (SURVEY §5.2).
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np
import scipy.sparse as sp

from ..errors import BadConfigurationError
from ..utils.determinism import SESSION_SEED

_coloring_registry: Dict[str, type] = {}


def register_coloring(name):
    def deco(cls):
        _coloring_registry[name] = cls
        cls.config_name = name
        return cls
    return deco


def create_coloring(name, cfg, scope):
    if name not in _coloring_registry:
        raise BadConfigurationError(
            f"unknown coloring scheme {name!r}; known: "
            f"{sorted(_coloring_registry)}")
    return _coloring_registry[name](cfg, scope)


@dataclasses.dataclass
class MatrixColoring:
    """Attached to a matrix after coloring (reference ``MatrixColoring``,
    matrix.h:108)."""

    colors: np.ndarray      # (n,) int32 color per row
    num_colors: int

    def rows_of(self, c):
        return np.flatnonzero(self.colors == c)


def _adjacency(A: sp.csr_matrix, level: int) -> sp.csr_matrix:
    """Symmetric adjacency of the (distance-``level``) graph."""
    G = sp.csr_matrix(A)
    G = (abs(G) + abs(G).T).tocsr()
    if level >= 2:
        G2 = G
        for _ in range(level - 1):
            G2 = sp.csr_matrix(G2 @ G)
        G = G2.tocsr()
    G.setdiag(0)
    G.eliminate_zeros()
    return G


def check_coloring(A: sp.csr_matrix, coloring: MatrixColoring,
                   level: int = 1) -> float:
    """Fraction of edges whose endpoints share a color (0.0 = perfect);
    the reference tolerates ``max_uncolored_percentage`` imperfection."""
    G = _adjacency(A, level)
    rows = np.repeat(np.arange(G.shape[0]), np.diff(G.indptr))
    bad = coloring.colors[rows] == coloring.colors[G.indices]
    return float(bad.sum()) / max(G.nnz, 1)


class _ColoringBase:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope
        self.level = int(cfg.get("coloring_level", scope))
        self.deterministic = bool(cfg.get("determinism_flag"))
        self.max_uncolored = float(cfg.get("max_uncolored_percentage", scope))

    def color(self, A: sp.csr_matrix) -> MatrixColoring:
        raise NotImplementedError


def _exact_color_leftovers(indptr, indices, colors: np.ndarray) -> None:
    """Sequential exact first-fit for the nodes the vectorized 63-bit
    used-color masks could not place: on graphs needing more than 63
    colors the mask saturates (``free == 0``), and lumping the leftovers
    into one shared color would be an IMPROPER coloring.  Per node the
    smallest color absent from its neighbourhood is exact for any color
    count; the leftover set is tiny (the saturated tail), so the python
    loop is negligible."""
    for v in np.flatnonzero(colors < 0):
        nb = colors[indices[indptr[v]:indptr[v + 1]]]
        used = set(int(c) for c in nb[nb >= 0])
        c = 0
        while c in used:
            c += 1
        colors[v] = c


def _jones_plassmann(G: sp.csr_matrix, seed: int, max_hash_rounds: int = 64
                     ) -> MatrixColoring:
    """Jones-Plassmann with hashed weights: a node takes the smallest color
    not used by any neighbour that beat it; local maxima color themselves
    each round.  This is the MIN_MAX family's strategy
    (``min_max.cu``/``min_max_2ring.cu``)."""
    n = G.shape[0]
    indptr, indices = G.indptr, G.indices
    rows = np.repeat(np.arange(n), np.diff(indptr))
    h = ((np.arange(n, dtype=np.uint64) * np.uint64(2654435761) +
          np.uint64(seed)) % np.uint64(1 << 30)).astype(np.int64)
    colors = np.full(n, -1, dtype=np.int64)
    deferred = np.zeros(n, dtype=bool)
    for _ in range(max_hash_rounds):
        un = (colors < 0) & ~deferred
        if not un.any():
            break
        both = un[rows] & un[indices]
        # local max among uncolored neighbours → gets colored this round
        nb_max = np.full(n, -1, dtype=np.int64)
        np.maximum.at(nb_max, rows[both], h[indices[both]])
        winners = un & (h > nb_max)
        if not winners.any():
            # tie pathologies: bump hashes and retry
            h = (h * 31 + 7) % (1 << 30)
            continue
        # smallest color unused by already-colored neighbours, vectorised
        # via a 63-bit used-color mask per row
        nb_colored = colors[indices] >= 0
        bits = np.zeros(n, dtype=np.int64)
        e = nb_colored & winners[rows]
        np.bitwise_or.at(bits, rows[e],
                         np.int64(1) << np.minimum(colors[indices[e]], 62))
        free = (~bits) & ~(~np.int64(0) << 63)
        # index of lowest set bit of `free`; a SATURATED mask (free==0,
        # >63 neighbour colors) must not color via log2(0) — DEFER the
        # node to the exact pass and drop it from the competition, or a
        # saturated hub that keeps the max hash would stall its whole
        # uncolored neighbourhood until the round cap (guard analog of
        # _recolor_compact's lowbit>0 check)
        lowbit = free & -free
        ok = winners & (lowbit > 0)
        colors[ok] = np.round(np.log2(lowbit[ok].astype(
            np.float64))).astype(np.int64)
        deferred |= winners & (lowbit == 0)
    _exact_color_leftovers(indptr, indices, colors)
    return MatrixColoring(colors=colors.astype(np.int32),
                          num_colors=int(colors.max()) + 1)


@register_coloring("MIN_MAX")
class MinMaxColoring(_ColoringBase):
    """Hash-based parallel coloring (reference ``min_max.cu``)."""

    def color(self, A):
        G = _adjacency(A, self.level)
        return _jones_plassmann(G, 7 if self.deterministic else SESSION_SEED)


@register_coloring("MIN_MAX_2RING")
class MinMax2RingColoring(_ColoringBase):
    """Distance-2 min-max coloring (``min_max_2ring.cu``)."""

    def color(self, A):
        G = _adjacency(A, max(self.level, 2))
        # determinism is free on this backend: the non-deterministic mode
        # still uses a fixed seed so results never depend on global RNG
        # state (utils.determinism.SESSION_SEED)
        return _jones_plassmann(G, 7 if self.deterministic else SESSION_SEED)


def _priority_greedy_color(G: sp.csr_matrix, prio: np.ndarray,
                           seed: int, max_rounds: int = 64
                           ) -> MatrixColoring:
    """First-fit greedy coloring as VECTORIZED fixed-point rounds: each
    round the uncolored nodes that beat every uncolored neighbour's
    priority take the smallest color unused by their colored neighbours
    (63-bit used-color masks — no python per-node loop).

    With a strictly-distinct priority this reproduces the sequential
    first-fit greedy in descending-priority order exactly; past
    ``max_rounds`` (adversarial orders: a path walked end-to-end) the
    remaining nodes finish with hash priorities — still a proper
    coloring, same color-count class.  This is the same round structure
    as the reference's parallel greedy kernels
    (``parallel_greedy.cu``)."""
    n = G.shape[0]
    indptr, indices = G.indptr, G.indices
    rows = np.repeat(np.arange(n), np.diff(indptr))
    # strictly-distinct composite priority; ties break by a BIJECTIVE
    # pseudorandom permutation, not by index — an index tiebreak builds
    # monotone chains (one winner per mesh line per round: measured
    # 15 s at 10⁶ rows) while a scrambled tiebreak converges in O(log n)
    # rounds like Jones-Plassmann
    from ..amg.classical.device_fine import pmis_multiplier
    a = np.uint64(pmis_multiplier(max(n, 1)))
    perm = ((np.arange(n, dtype=np.uint64) * a + np.uint64(seed)) %
            np.uint64(max(n, 1))).astype(np.int64)
    p = prio.astype(np.int64) * np.int64(n) + perm
    colors = np.full(n, -1, dtype=np.int64)
    deferred = np.zeros(n, dtype=bool)
    h = ((np.arange(n, dtype=np.uint64) * np.uint64(2654435761) +
          np.uint64(seed)) % np.uint64(1 << 30)).astype(np.int64)
    for rnd in range(2 * max_rounds):
        un = (colors < 0) & ~deferred
        if not un.any():
            break
        if rnd == max_rounds:
            # order-faithful rounds stalled (long monotone chains):
            # finish with hash priorities, which converge in O(log n)
            p = h * np.int64(n) + np.arange(n, dtype=np.int64)
        both = un[rows] & un[indices]
        nb_max = np.full(n, np.iinfo(np.int64).min, dtype=np.int64)
        np.maximum.at(nb_max, rows[both], p[indices[both]])
        winners = un & (p > nb_max)
        nb_colored = colors[indices] >= 0
        bits = np.zeros(n, dtype=np.int64)
        e = nb_colored & winners[rows]
        np.bitwise_or.at(bits, rows[e],
                         np.int64(1) << np.minimum(colors[indices[e]],
                                                   62))
        free = (~bits) & ~(~np.int64(0) << 63)
        # saturated 63-bit masks (>63-color graphs, e.g. large cliques)
        # yield free==0: log2(0) would leave those nodes "uncolorable"
        # and the old leftover-lumping gave them ONE shared color — a
        # silently improper coloring.  Guard like _recolor_compact,
        # DEFER the saturated winners out of the competition (a
        # saturated high-priority hub must not stall its neighbourhood
        # until the round cap), and place them in the exact pass.
        lowbit = free & -free
        ok = winners & (lowbit > 0)
        colors[ok] = np.round(np.log2(lowbit[ok].astype(
            np.float64))).astype(np.int64)
        deferred |= winners & (lowbit == 0)
    _exact_color_leftovers(indptr, indices, colors)
    return MatrixColoring(colors=colors.astype(np.int32),
                          num_colors=int(colors.max()) + 1)


def _recolor_compact(G: sp.csr_matrix, col: MatrixColoring,
                     max_passes: int = 8) -> MatrixColoring:
    """Greedy RECOLOR pass (``greedy_recolor.cu``): nodes of the
    top (largest-index) color class move to the smallest free smaller
    color.  A color class is an independent set, so every move in one
    pass is simultaneously safe — fully vectorized.  When the whole top
    class empties, the color count drops; passes repeat until a class
    resists."""
    n = G.shape[0]
    indptr, indices = G.indptr, G.indices
    rows = np.repeat(np.arange(n), np.diff(indptr))
    colors = col.colors.astype(np.int64).copy()
    k = col.num_colors
    for _ in range(max_passes):
        if k <= 1:
            break
        top = k - 1
        movers = colors == top
        if not movers.any():
            k -= 1
            continue
        bits = np.zeros(n, dtype=np.int64)
        e = movers[rows] & (colors[indices] >= 0) & \
            (colors[indices] < top)
        np.bitwise_or.at(bits, rows[e],
                         np.int64(1) << np.minimum(colors[indices[e]],
                                                   62))
        free = (~bits) & ~(~np.int64(0) << 63) & \
            ((np.int64(1) << np.int64(min(top, 62))) - 1)
        lowbit = free & -free
        can = movers & (lowbit > 0)
        colors[can] = np.round(np.log2(lowbit[can].astype(
            np.float64))).astype(np.int64)
        if not (movers & ~can).any():
            k -= 1               # class emptied: fewer colors
        else:
            break                # a stuck node keeps the class alive
    return MatrixColoring(colors=colors.astype(np.int32),
                          num_colors=int(colors.max()) + 1)


@register_coloring("GREEDY_MIN_MAX_2RING")
class GreedyMinMax2RingColoring(MinMax2RingColoring):
    """``greedy_min_max_2ring.cu``: min-max (Jones-Plassmann) coloring
    of the DISTANCE-2 graph followed by the greedy recolor refinement on
    the same 2-ring — typically one or two fewer colors than plain
    MIN_MAX_2RING (= fewer masked sweeps per DILU/GS application)."""

    def color(self, A):
        G = _adjacency(A, max(self.level, 2))
        base = _jones_plassmann(G, 7 if self.deterministic
                                else SESSION_SEED)
        return _recolor_compact(G, base)


@register_coloring("PARALLEL_GREEDY")
class ParallelGreedyColoring(_ColoringBase):
    """``parallel_greedy.cu``: first-fit greedy with highest-degree
    priority, run as vectorized conflict-free rounds
    (:func:`_priority_greedy_color`)."""

    def color(self, A):
        G = _adjacency(A, self.level)
        deg = np.diff(G.indptr).astype(np.int64)
        return _priority_greedy_color(
            G, deg, 7 if self.deterministic else SESSION_SEED)


@register_coloring("SERIAL_GREEDY_BFS")
class SerialGreedyBFSColoring(ParallelGreedyColoring):
    """``serial_greedy_bfs.cu`` parity — first-fit greedy in BFS order,
    vectorized: BFS ranks (scipy csgraph, C speed) become the round
    priority, so mesh-like graphs reproduce the serial result in a few
    fronts' worth of rounds."""

    def color(self, A):
        G = _adjacency(A, self.level)
        n = G.shape[0]
        order = sp.csgraph.breadth_first_order(
            G, 0, return_predecessors=False) if n else np.arange(0)
        seen = np.zeros(n, dtype=bool)
        seen[order] = True
        order = np.concatenate([order, np.flatnonzero(~seen)])
        rank = np.empty(n, dtype=np.int64)
        rank[order] = np.arange(n, dtype=np.int64)
        # rank order is inherently chain-like (each BFS front is an
        # ordered line): cap the order-faithful rounds early and let the
        # hash rounds finish — same color-count class, bounded time
        return _priority_greedy_color(
            G, -rank, 7 if self.deterministic else SESSION_SEED,
            max_rounds=16)


@register_coloring("ROUND_ROBIN")
class RoundRobinColoring(_ColoringBase):
    """``round_robin.cu``: color = row mod num_colors — cheap, imperfect
    (allowed by ``max_uncolored_percentage``)."""

    def color(self, A):
        k = int(self.cfg.get("num_colors", self.scope))
        n = A.shape[0]
        colors = (np.arange(n) % max(k, 1)).astype(np.int32)
        return MatrixColoring(colors=colors, num_colors=max(k, 1))


@register_coloring("UNIFORM")
class UniformColoring(_ColoringBase):
    """``uniform.cu``: geometric striping — valid for banded/stencil
    matrices when the stripe period exceeds the bandwidth."""

    def color(self, A):
        G = _adjacency(A, self.level)
        # period = max |i-j| over edges + 1 capped to a sane stripe count
        rows = np.repeat(np.arange(G.shape[0]), np.diff(G.indptr))
        bw = int(np.abs(rows - G.indices).max()) + 1 if G.nnz else 1
        k = min(bw, 32)
        colors = (np.arange(A.shape[0]) % k).astype(np.int32)
        return MatrixColoring(colors=colors, num_colors=k)


@register_coloring("MULTI_HASH")
class MultiHashColoring(_ColoringBase):
    """``multi_hash.cu``: several INDEPENDENT hashed colorings, keep the
    one with the fewest colors (the reference tries multiple hashes per
    node per round toward the same goal — fewer colors = fewer masked
    sweeps per DILU/GS application)."""

    #: independent hash attempts (reference default num_hash ~ 7-8)
    attempts = 8

    def color(self, A):
        G = _adjacency(A, self.level)
        base = 7 if self.deterministic else SESSION_SEED
        best = None
        for k in range(self.attempts):
            c = _jones_plassmann(G, base + 1009 * k)
            if best is None or c.num_colors < best.num_colors:
                best = c
            if best.num_colors <= 2:
                break                      # bipartite: can't do better
        return best


@register_coloring("GREEDY_RECOLOR")
class GreedyRecolorColoring(ParallelGreedyColoring):
    """``greedy_recolor.cu``: greedy coloring, then RECOLOR passes that
    empty the largest-index color classes into smaller free colors
    (every class is an independent set, so one pass's moves are
    simultaneously safe) — measurably fewer colors than the plain
    greedy on irregular graphs."""

    def color(self, A):
        G = _adjacency(A, self.level)
        deg = np.diff(G.indptr).astype(np.int64)
        seed = 7 if self.deterministic else SESSION_SEED
        base = _priority_greedy_color(G, deg, seed)
        # recolor pass 1: a SECOND first-fit greedy in descending-color
        # order (high-color nodes go first, so the classes that forced
        # the extra colors get first pick) — the classic
        # interchange-free recolor heuristic of greedy_recolor.cu
        rec = _priority_greedy_color(
            G, base.colors.astype(np.int64), seed + 1)
        if rec.num_colors > base.num_colors:
            rec = base
        # recolor pass 2: empty the top classes where safely possible
        return _recolor_compact(G, rec)


@register_coloring("LOCALLY_DOWNWIND")
class LocallyDownwindColoring(_ColoringBase):
    """``locally_downwind.cu``: color ORDER follows the advective flow.

    For convection-dominated operators a forward multicolor DILU/GS
    sweep is most effective when upstream rows update before the rows
    they feed (in the limit of pure advection the matrix is triangular
    in flow order and one sweep solves it).  Direction is read off the
    matrix asymmetry — ``|A[v,u]| > |A[u,v]|`` marks ``u`` upstream of
    ``v`` (upwind discretisations put the flow coupling on the upstream
    side) — then:

    * downwind LEVELS via a monotone fixed point
      ``lvl[v] = max(lvl[u]+1)`` over upstream edges (cycles saturate at
      the round cap),
    * each level is properly colored by Jones-Plassmann on its own
      subgraph, and global colors concatenate level by level — a PROPER
    coloring whose class order is the downwind order.
    """

    #: level-propagation cap (cycles in the flow graph saturate here)
    max_depth = 64

    def color(self, A):
        A = sp.csr_matrix(A)
        n = A.shape[0]
        coo = A.tocoo()
        off = coo.row != coo.col
        r, c, v = coo.row[off], coo.col[off], coo.data[off]
        Aabs = sp.csr_matrix((np.abs(v), (r, c)), shape=A.shape)
        diff = (Aabs - sp.csr_matrix(Aabs.T)).tocoo()
        m = diff.data > 0          # entry (v, u): u strictly upstream
        up_u, dn_v = diff.col[m], diff.row[m]
        lvl = np.zeros(n, dtype=np.int64)
        for _ in range(self.max_depth):
            new = np.zeros(n, dtype=np.int64)
            if len(dn_v):
                np.maximum.at(new, dn_v, lvl[up_u] + 1)
            new = np.maximum(new, lvl)
            if np.array_equal(new, lvl):
                break
            lvl = new
        G = _adjacency(A, self.level)
        colors = np.full(n, -1, dtype=np.int64)
        seed = 7 if self.deterministic else SESSION_SEED
        next_color = 0
        for L in np.unique(lvl):
            idx = np.flatnonzero(lvl == L)
            sub = sp.csr_matrix(G[idx][:, idx])
            cp = _jones_plassmann(sub, seed)
            colors[idx] = next_color + cp.colors
            next_color += cp.num_colors
        return MatrixColoring(colors=colors.astype(np.int32),
                              num_colors=int(next_color))


def color_matrix(matrix, cfg, scope) -> MatrixColoring:
    """Color a Matrix and cache the result on it (reference
    ``Matrix::colorMatrix`` / setupMatrix, matrix.cu:760-813)."""
    cached = getattr(matrix, "coloring", None)
    if cached is not None:
        return cached
    scheme = str(cfg.get("matrix_coloring_scheme", scope))
    algo = create_coloring(scheme, cfg, scope)
    if getattr(matrix, "blocks", None) is not None \
            and getattr(matrix, "host", 1) is None:
        # block-distributed matrix: color each rank's diagonal block
        # independently (the reference also colors per-rank; cross-rank
        # edges are relaxed Jacobi-style by the masked sharded sweep)
        offs = matrix.block_offsets
        parts = []
        num = 1
        for p, blk in enumerate(matrix.blocks):
            lo, hi = offs[p], offs[p + 1]
            sub = sp.csr_matrix(blk[:, lo:hi])
            cp = algo.color(sub)
            parts.append(cp.colors)
            num = max(num, cp.num_colors)
        coloring = MatrixColoring(np.concatenate(parts)
                                  if parts else np.zeros(0, np.int64), num)
        matrix.coloring = coloring
        return coloring
    if hasattr(matrix, "block_dim") and matrix.block_dim > 1:
        # color the block graph: one color per block row (matrix.h:108)
        bd = matrix.block_dim
        bsr = matrix.host if isinstance(matrix.host, sp.bsr_matrix) else \
            sp.bsr_matrix(matrix.host, blocksize=(bd, bd))
        nb = bsr.shape[0] // bd
        G = sp.csr_matrix(
            (np.ones(len(bsr.indices)), bsr.indices.copy(),
             bsr.indptr.copy()), shape=(nb, nb))
        coloring = algo.color(G)
    elif hasattr(matrix, "scalar_csr"):
        coloring = algo.color(matrix.scalar_csr())
    else:
        coloring = algo.color(sp.csr_matrix(matrix))
    if hasattr(matrix, "__dict__"):
        matrix.coloring = coloring
    return coloring
