"""Device-side AMG setup engine.

Reference: AmgX performs the entire Galerkin product on the accelerator
(``CSR_Multiply::csr_galerkin_product`` / ``csr_RAP_sparse_add``,
PAPER.md layers L5/L9) with a symbolic phase that sizes the output once
and a numeric phase that re-runs on new values.  This subsystem is the
TPU equivalent for the HOST classical/aggregation setup paths: a
pattern-keyed cache of reusable "setup executables" (the
:class:`~amgx_tpu.ops.spgemm.GalerkinPlan` schedules) whose numeric
pass runs entirely under ``jit`` with the hierarchy passed as jit
ARGUMENTS — so the executable for a given (pattern fingerprint, level
shape bucket) compiles once, and a ``resetup`` on new coefficients is a
pure device numeric pass with zero recompiles.

Fallback contract: every gate failure (tiny level, schedule budget,
f64-on-TPU, unexpected error) returns None to the caller — the host
scipy path stays the correctness net — and emits a
``device_setup_fallback`` telemetry event carrying the reason, which
the doctor surfaces per level.
"""
from .engine import (DeviceSetupEngine, engine, engine_stats,
                     reset_engine)

__all__ = ["DeviceSetupEngine", "engine", "engine_stats",
           "reset_engine"]
